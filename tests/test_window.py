"""Window functions: device kernel (ops/window.py) vs row-at-a-time oracle
(exec/executor.py _ref_window) parity, plus SQL-level semantics
(ref: pkg/executor/window.go; aggfuncs/func_*.go)."""

import random

import pytest

from tidb_tpu.sql.session import Session, SQLError


@pytest.fixture(scope="module")
def sess():
    s = Session()
    s.execute("CREATE TABLE emp (id INT PRIMARY KEY, dept INT, sal INT, note VARCHAR(8))")
    rng = random.Random(7)
    rows = []
    for i in range(1, 101):
        dept = rng.choice([10, 20, 30, 40])
        sal = rng.choice([100, 150, 200, 200, 300, None])
        note = rng.choice(["'a'", "'b'", "NULL"])
        rows.append(f"({i},{dept},{'NULL' if sal is None else sal},{note})")
    s.execute("INSERT INTO emp VALUES " + ",".join(rows))
    return s


QUERIES = [
    "SELECT id, row_number() OVER (PARTITION BY dept ORDER BY sal, id) FROM emp",
    "SELECT id, rank() OVER (PARTITION BY dept ORDER BY sal) FROM emp",
    "SELECT id, dense_rank() OVER (PARTITION BY dept ORDER BY sal DESC) FROM emp",
    "SELECT id, sum(sal) OVER (PARTITION BY dept) FROM emp",
    "SELECT id, sum(sal) OVER (PARTITION BY dept ORDER BY sal) FROM emp",
    "SELECT id, count(sal) OVER (PARTITION BY dept ORDER BY sal) FROM emp",
    "SELECT id, count(*) OVER (PARTITION BY dept) FROM emp",
    "SELECT id, min(sal) OVER (PARTITION BY dept ORDER BY id) FROM emp",
    "SELECT id, max(sal) OVER (PARTITION BY dept ORDER BY sal, id) FROM emp",
    "SELECT id, avg(sal) OVER (PARTITION BY dept) FROM emp",
    "SELECT id, lead(sal) OVER (PARTITION BY dept ORDER BY id) FROM emp",
    "SELECT id, lag(sal, 2, -5) OVER (PARTITION BY dept ORDER BY id) FROM emp",
    "SELECT id, first_value(sal) OVER (PARTITION BY dept ORDER BY sal, id) FROM emp",
    "SELECT id, last_value(sal) OVER (PARTITION BY dept ORDER BY sal, id) FROM emp",
    "SELECT id, nth_value(sal, 3) OVER (PARTITION BY dept ORDER BY id) FROM emp",
    "SELECT id, ntile(3) OVER (ORDER BY sal, id) FROM emp",
    "SELECT id, row_number() OVER () FROM emp",
    # strings route to the oracle on both paths (gathers work on device)
    "SELECT id, first_value(note) OVER (PARTITION BY dept ORDER BY id) FROM emp",
    "SELECT id, lead(note) OVER (PARTITION BY dept ORDER BY id) FROM emp",
]


def _canon(rows):
    out = []
    for r in rows:
        row = []
        for v in r:
            if isinstance(v, float):
                v = round(v, 9)
            row.append(str(v))
        out.append(tuple(row))
    return sorted(out)


@pytest.mark.parametrize("sql", QUERIES)
def test_device_vs_oracle(sess, sql):
    sess.execute("SET tidb_enable_tpu_coprocessor = ON")
    dev = sess.execute(sql).values()
    sess.execute("SET tidb_enable_tpu_coprocessor = OFF")
    ora = sess.execute(sql).values()
    sess.execute("SET tidb_enable_tpu_coprocessor = ON")
    assert _canon(dev) == _canon(ora), sql


def test_float_rank_funcs(sess):
    sess.execute("SET tidb_enable_tpu_coprocessor = ON")
    dev = sess.execute("SELECT id, percent_rank() OVER (ORDER BY sal), cume_dist() OVER (ORDER BY sal) FROM emp").values()
    sess.execute("SET tidb_enable_tpu_coprocessor = OFF")
    ora = sess.execute("SELECT id, percent_rank() OVER (ORDER BY sal), cume_dist() OVER (ORDER BY sal) FROM emp").values()
    sess.execute("SET tidb_enable_tpu_coprocessor = ON")
    for d, o in zip(sorted(dev), sorted(ora)):
        assert d[0] == o[0]
        assert abs(d[1] - o[1]) < 1e-9 and abs(d[2] - o[2]) < 1e-9


def test_window_exact_values():
    s = Session()
    s.execute("CREATE TABLE w (id INT PRIMARY KEY, g INT, x INT)")
    s.execute("INSERT INTO w VALUES (1,1,10),(2,1,20),(3,1,20),(4,2,5)")
    got = s.execute("SELECT id, rank() OVER (PARTITION BY g ORDER BY x), sum(x) OVER (PARTITION BY g ORDER BY x) FROM w ORDER BY id").values()
    assert [[r[0], r[1], int(str(r[2]))] for r in got] == [
        [1, 1, 10], [2, 2, 50], [3, 2, 50], [4, 1, 5]]


def test_window_over_expression(sess):
    # window result inside an expression
    got = sess.execute("SELECT id, row_number() OVER (ORDER BY id) * 10 FROM emp ORDER BY id LIMIT 3").values()
    assert got == [[1, 10], [2, 20], [3, 30]]


def test_window_in_order_by():
    s = Session()
    s.execute("CREATE TABLE w2 (id INT PRIMARY KEY, x INT)")
    s.execute("INSERT INTO w2 VALUES (1,30),(2,10),(3,20)")
    got = s.execute("SELECT id FROM w2 ORDER BY row_number() OVER (ORDER BY x) DESC").values()
    assert got == [[1], [3], [2]]


def test_window_errors(sess):
    from tidb_tpu.sql import PlanError

    with pytest.raises((SQLError, PlanError)):
        sess.execute("SELECT dept, sum(sal), row_number() OVER () FROM emp GROUP BY dept")
    with pytest.raises(Exception):
        sess.execute("SELECT sum(sal) OVER (ORDER BY id ROWS BETWEEN 1 PRECEDING AND CURRENT ROW) FROM emp")


def test_window_with_where_and_limit(sess):
    got = sess.execute(
        "SELECT id, row_number() OVER (ORDER BY id) FROM emp WHERE id <= 5 ORDER BY id LIMIT 3"
    ).values()
    assert got == [[1, 1], [2, 2], [3, 3]]
