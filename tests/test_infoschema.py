"""information_schema memtables (ref: pkg/infoschema +
pkg/executor/infoschema_reader.go — schema introspection served from the
engine itself)."""

import pytest

from tidb_tpu.sql.session import Session, SQLError


@pytest.fixture()
def sess():
    s = Session()
    s.execute("CREATE TABLE t (id INT PRIMARY KEY, v INT, s VARCHAR(8))")
    s.execute("CREATE TABLE u (id INT PRIMARY KEY)")
    s.execute("CREATE UNIQUE INDEX uv ON t (v)")
    s.execute("INSERT INTO t VALUES (1,1,'a'),(2,2,'b')")
    return s


def test_tables(sess):
    got = sess.execute(
        "SELECT table_name, table_rows FROM information_schema.tables "
        "WHERE table_schema = 'test' ORDER BY table_name"
    ).values()
    assert got == [["t", 2], ["u", 0]]
    # the mysql bootstrap schema is listed too (ref: bootstrap.go tables)
    sys_got = sess.execute(
        "SELECT count(*) FROM information_schema.tables WHERE table_schema = 'mysql'"
    ).values()
    assert sys_got[0][0] >= 5


def test_columns(sess):
    got = sess.execute(
        "SELECT column_name, column_type, column_key FROM information_schema.columns "
        "WHERE table_name = 't' ORDER BY ordinal_position"
    ).values()
    # declared spellings are preserved (INT stays "int")
    assert got == [["id", "int", "PRI"], ["v", "int", ""], ["s", "varchar(8)", ""]]


def test_statistics(sess):
    got = sess.execute(
        "SELECT index_name, non_unique, column_name FROM information_schema.statistics"
    ).values()
    assert got == [["uv", 0, "v"]]


def test_join_memtables(sess):
    got = sess.execute(
        "SELECT count(*) FROM information_schema.columns c "
        "JOIN information_schema.tables tt ON c.table_name = tt.table_name "
        "WHERE tt.table_schema = 'test'"
    ).values()
    assert got == [[4]]


def test_unknown_memtable(sess):
    with pytest.raises(SQLError, match="not supported"):
        sess.execute("SELECT * FROM information_schema.engines")


def test_memtable_does_not_shadow_user_table(sess):
    sess.execute("CREATE TABLE tables (id INT PRIMARY KEY)")
    sess.execute("INSERT INTO tables VALUES (7)")
    assert sess.execute("SELECT id FROM tables").values() == [[7]]
    got = sess.execute(
        "SELECT count(*) FROM information_schema.tables WHERE table_schema = 'test'"
    ).values()
    assert got == [[3]]
