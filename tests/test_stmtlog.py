"""Slow-query log + statement summary (VERDICT r3 missing #9; ref:
pkg/executor/adapter.go LogSlowQuery, pkg/util/stmtsummary)."""

from tidb_tpu.sql import Session
from tidb_tpu.util.stmtlog import normalize_sql


class TestStmtSummary:
    def test_digest_groups_literal_variants(self):
        n1, d1 = normalize_sql("select * from t where a = 5")
        n2, d2 = normalize_sql("SELECT * FROM t WHERE a = 99")
        n3, d3 = normalize_sql("select * from t where b = 5")
        assert d1 == d2 and n1 == n2 == "select * from t where a = ?"
        assert d3 != d1

    def test_summary_via_information_schema(self):
        s = Session()
        s.execute("create table t (a bigint primary key)")
        s.execute("insert into t values (1),(2),(3)")
        for v in (1, 2, 3):
            s.execute(f"select * from t where a = {v}")
        r = s.execute(
            "select exec_count, sum_rows from information_schema.statements_summary "
            "where digest_text = 'select * from t where a = ?'"
        )
        assert len(r.rows) == 1
        assert int(r.rows[0][0].val) == 3 and int(r.rows[0][1].val) == 3

    def test_errors_counted(self):
        s = Session()
        try:
            s.execute("select * from missing_table")
        except Exception:
            pass
        r = s.execute(
            "select errors from information_schema.statements_summary "
            "where digest_text = 'select * from missing_table'"
        )
        assert int(r.rows[0][0].val) == 1

    def test_summary_toggle(self):
        s = Session()
        s.execute("set tidb_enable_stmt_summary = OFF")
        s.execute("select 1")
        r = s.execute("select count(*) from information_schema.statements_summary")
        # only the OFF-window statements are absent; the SET itself ran
        # before the toggle applied... simplest: nothing recorded while OFF
        n_off = int(r.rows[0][0].val)
        s.execute("set tidb_enable_stmt_summary = ON")
        s.execute("select 1")
        r = s.execute("select count(*) from information_schema.statements_summary")
        assert int(r.rows[0][0].val) > n_off


class TestSlowLog:
    def test_slow_statement_lands_in_slow_query(self):
        s = Session()
        s.execute("create table t (a bigint primary key)")
        s.execute("set tidb_slow_log_threshold = 0")  # everything is slow now
        s.execute("insert into t values (42)")
        s.execute("set tidb_slow_log_threshold = 300")
        r = s.execute(
            "select query, success from information_schema.slow_query "
            "where digest = %r" % normalize_sql("insert into t values (42)")[1]
        )
        assert len(r.rows) >= 1
        assert "insert into t values (42)" in str(r.rows[0][0].val)
        assert int(r.rows[0][1].val) == 1

    def test_disabled_slow_log_records_nothing(self):
        s = Session()
        s.execute("set tidb_enable_slow_log = OFF")
        s.execute("set tidb_slow_log_threshold = 0")
        s.execute("select 1")
        s.execute("set tidb_slow_log_threshold = 300")
        s.execute("set tidb_enable_slow_log = ON")
        assert s.catalog.stmtlog.slow_entries() == []


def test_top_sql_cpu_attribution():
    """Top SQL (ISSUE 17; ref: pkg/util/topsql): per-digest CPU time
    accumulates into the windowed reporter and
    information_schema.tidb_top_sql surfaces it ranked by cpu+device."""
    from tidb_tpu import topsql
    from tidb_tpu.sql import Session

    topsql.COLLECTOR.reset()
    s = Session()
    s.execute("create table t (a bigint primary key, b bigint)")
    s.execute("insert into t values " + ",".join(f"({i},{i})" for i in range(300)))
    for i in range(5):
        s.execute(f"select sum(b) from t where a > {i}")
    s.execute("select 1")
    digest = normalize_sql("select sum(b) from t where a > 0")[1]
    rows = s.execute(
        "select exec_count, cpu_ns, cost_class from information_schema.tidb_top_sql "
        f"where digest = '{digest}'"
    ).values()
    assert rows and rows[0][0] == 5 and rows[0][1] > 0
    assert rows[0][2] in ("point", "small", "scan", "heavy")
    # rows come out ranked by cumulative cpu+device within each window:
    # the repeated aggregation outranks `select 1`
    top = s.execute(
        "select digest from information_schema.tidb_top_sql limit 3"
    ).values()
    assert any(r[0] == digest for r in top)
