"""Partitioned tables end-to-end (VERDICT r3 missing #4): RANGE/HASH
partitions with their own physical key spaces, partition pruning visible
in EXPLAIN, row movement on partition-column updates
(ref: pkg/planner/core/rule_partition_processor.go, meta/model
PartitionInfo, tablecodec per-partition IDs)."""

import pytest

from tidb_tpu.sql import Session


def _range_session():
    s = Session()
    s.execute(
        "create table r (amt bigint primary key, note varchar(16)) "
        "partition by range (amt) ("
        " partition p0 values less than (100),"
        " partition p1 values less than (200),"
        " partition p2 values less than maxvalue)"
    )
    s.execute("insert into r values " + ",".join(f"({v}, 'n{v}')" for v in (5, 50, 150, 199, 250, 1000)))
    return s


class TestRangePartition:
    def test_rows_land_in_partition_keyspaces(self):
        from tidb_tpu.codec import tablecodec

        s = _range_session()
        meta = s.catalog.table("r")
        pids = meta.physical_ids()
        assert len(pids) == 3 and meta.table_id not in pids
        # physical placement: amt=5 under p0, amt=150 under p1, amt=250 under p2
        ts = s.store.next_ts()
        assert s.store.kv.get(tablecodec.encode_row_key(pids[0], 5), ts) is not None
        assert s.store.kv.get(tablecodec.encode_row_key(pids[1], 150), ts) is not None
        assert s.store.kv.get(tablecodec.encode_row_key(pids[2], 250), ts) is not None
        assert s.store.kv.get(tablecodec.encode_row_key(pids[0], 150), ts) is None

    def test_select_scans_all_partitions(self):
        s = _range_session()
        r = s.execute("select amt from r order by amt")
        assert [int(x[0].val) for x in r.rows] == [5, 50, 150, 199, 250, 1000]
        assert int(s.execute("select count(*) from r").rows[0][0].val) == 6

    def test_pruning_visible_in_explain(self):
        s = _range_session()
        txt = "\n".join(str(d.val) for row in s.execute(
            "explain select * from r where amt >= 150 and amt < 210").rows for d in row)
        assert "partitions(p1,p2)" in txt, txt
        txt = "\n".join(str(d.val) for row in s.execute(
            "explain select * from r where amt = 50").rows for d in row)
        assert "partitions(p0)" in txt, txt
        # unconstrained: all partitions
        txt = "\n".join(str(d.val) for row in s.execute(
            "explain select * from r").rows for d in row)
        assert "partitions(p0,p1,p2)" in txt, txt

    def test_pruned_select_results(self):
        s = _range_session()
        r = s.execute("select amt from r where amt >= 150 and amt < 260 order by amt")
        assert [int(x[0].val) for x in r.rows] == [150, 199, 250]
        r = s.execute("select sum(amt) from r where amt < 100")
        assert int(str(r.rows[0][0].val)) == 55

    def test_update_moves_row_across_partitions(self):
        from tidb_tpu.codec import tablecodec

        s = _range_session()
        meta = s.catalog.table("r")
        pids = meta.physical_ids()
        s.execute("update r set amt = 120 where amt = 5")
        ts = s.store.next_ts()
        assert s.store.kv.get(tablecodec.encode_row_key(pids[0], 5), ts) is None
        assert s.store.kv.get(tablecodec.encode_row_key(pids[1], 120), ts) is not None
        r = s.execute("select amt from r where amt >= 100 and amt < 200 order by amt")
        assert [int(x[0].val) for x in r.rows] == [120, 150, 199]

    def test_delete_and_out_of_range_insert(self):
        s = _range_session()
        s.execute("delete from r where amt >= 200")
        assert int(s.execute("select count(*) from r").rows[0][0].val) == 4
        s2 = Session()
        s2.execute(
            "create table b (v bigint) partition by range (v) "
            "(partition p0 values less than (10))"
        )
        with pytest.raises(Exception, match="no partition"):
            s2.execute("insert into b values (99)")

    def test_partition_survives_restart(self):
        s = _range_session()
        s2 = Session(store=s.store)
        meta = s2.catalog.table("r")
        assert meta.partition is not None and len(meta.partition.parts) == 3
        r = s2.execute("select count(*) from r where amt < 100")
        assert int(r.rows[0][0].val) == 2
        s2.execute("insert into r values (60, 'new')")
        assert int(s2.execute("select count(*) from r where amt < 100").rows[0][0].val) == 3


class TestHashPartition:
    def test_hash_routing_and_point_prune(self):
        from tidb_tpu.codec import tablecodec

        s = Session()
        s.execute("create table h (k bigint primary key, v bigint) partition by hash (k) partitions 4")
        s.execute("insert into h values " + ",".join(f"({i}, {i * 10})" for i in range(20)))
        meta = s.catalog.table("h")
        pids = meta.physical_ids()
        assert len(pids) == 4
        ts = s.store.next_ts()
        assert s.store.kv.get(tablecodec.encode_row_key(pids[7 % 4], 7), ts) is not None
        r = s.execute("select v from h where k = 7")
        assert int(r.rows[0][0].val) == 70
        txt = "\n".join(str(d.val) for row in s.execute(
            "explain select * from h where k = 7").rows for d in row)
        assert "partitions(p3)" in txt, txt
        assert int(s.execute("select count(*) from h").rows[0][0].val) == 20


class TestPartitionRestrictions:
    def test_pk_must_cover_partition_column(self):
        s = Session()
        with pytest.raises(Exception, match="PRIMARY KEY must include"):
            s.execute(
                "create table bad (id bigint primary key, amt bigint) "
                "partition by range (amt) (partition p0 values less than (10))"
            )

    def test_no_secondary_indexes(self):
        s = Session()
        s.execute(
            "create table p (amt bigint primary key, v bigint) "
            "partition by range (amt) (partition p0 values less than maxvalue)"
        )
        with pytest.raises(Exception, match="partitioned"):
            s.execute("create index iv on p (v)")

    def test_txn_rollback_and_partitioned_dml(self):
        s = Session()
        s.execute(
            "create table p (amt bigint primary key) "
            "partition by range (amt) (partition p0 values less than (100),"
            " partition p1 values less than maxvalue)"
        )
        s.execute("insert into p values (1), (150)")
        s.execute("begin")
        s.execute("insert into p values (2), (160)")
        s.execute("update p set amt = 120 where amt = 1")
        r = s.execute("select amt from p order by amt")
        assert [int(x[0].val) for x in r.rows] == [2, 120, 150, 160]
        s.execute("rollback")
        r = s.execute("select amt from p order by amt")
        assert [int(x[0].val) for x in r.rows] == [1, 150]


class TestPartitionReviewRegressions:
    def test_inline_key_rejected(self):
        """code-review r4: an inline KEY must not bypass the no-secondary-
        index rule for partitioned tables."""
        import pytest

        s = Session()
        with pytest.raises(Exception, match="partitioned"):
            s.execute(
                "create table bad (a bigint primary key, b bigint, key ib (b)) "
                "partition by hash (a) partitions 2"
            )

    def test_set_snapshot_in_txn_rejected(self):
        import pytest

        s = Session()
        s.execute("create table st (a bigint primary key)")
        s.execute("begin")
        with pytest.raises(Exception, match="tidb_snapshot"):
            s.execute("set tidb_snapshot = 123")
        s.execute("rollback")

    def test_load_data_routes_partitions(self):
        """code-review r4: LOAD DATA must write rows under partition pids."""
        import os
        import tempfile

        s = Session()
        s.execute(
            "create table lp (amt bigint primary key) partition by range (amt) "
            "(partition p0 values less than (100), partition p1 values less than maxvalue)"
        )
        with tempfile.NamedTemporaryFile("w", suffix=".csv", delete=False) as f:
            f.write("5\n150\n250\n")
            path = f.name
        try:
            s.execute(f"load data infile '{path}' into table lp fields terminated by ','")
            r = s.execute("select amt from lp order by amt")
            assert [int(x[0].val) for x in r.rows] == [5, 150, 250]
            assert int(s.execute("select count(*) from lp where amt >= 100").rows[0][0].val) == 2
        finally:
            os.unlink(path)

    def test_backup_restore_partitioned(self):
        """code-review r4: BR must round-trip PartitionInfo."""
        import tempfile

        from tidb_tpu.tools.br import backup, restore
        from tidb_tpu.sql.catalog import Catalog
        from tidb_tpu.store import TPUStore

        s = Session()
        s.execute(
            "create table bp (amt bigint primary key) partition by hash (amt) partitions 3"
        )
        s.execute("insert into bp values (1),(2),(3),(4),(5)")
        with tempfile.TemporaryDirectory() as d:
            backup(s.store, s.catalog, d)
            store2, cat2 = TPUStore(), Catalog()
            restore(store2, cat2, d)
            s2 = Session(store=store2, catalog=cat2)
            assert int(s2.execute("select count(*) from bp").rows[0][0].val) == 5
            meta = cat2.table("bp")
            assert meta.partition is not None and len(meta.partition.parts) == 3
            # id allocator rebased above partition pids
            assert cat2._next_id > max(p.pid for p in meta.partition.parts)

    def test_point_get_beyond_last_range_partition(self):
        """code-review r4: out-of-range PK point read = empty set, not error."""
        s = Session()
        s.execute(
            "create table pr (a bigint primary key) partition by range (a) "
            "(partition p0 values less than (10))"
        )
        s.execute("insert into pr values (5)")
        assert s.execute("select * from pr where a = 50").rows == []
        assert [int(x[0].val) for x in s.execute("select * from pr where a = 5").rows] == [5]


def test_partition_column_protected_from_alter():
    sess = Session()
    sess.execute(
        "CREATE TABLE pguard (a INT, b INT) PARTITION BY HASH(a) PARTITIONS 3"
    )
    sess.execute("INSERT INTO pguard VALUES (1, 2)")
    with pytest.raises(Exception, match="partition"):
        sess.execute("ALTER TABLE pguard DROP COLUMN a")
    # renaming the partition column is allowed and keeps routing intact
    sess.execute("ALTER TABLE pguard CHANGE COLUMN a a2 INT")
    sess.execute("INSERT INTO pguard VALUES (5, 6)")
    assert sess.execute("SELECT count(*) FROM pguard").values() == [[2]]
    assert sess.execute("SELECT a2 FROM pguard WHERE a2 = 5").values() == [[5]]
