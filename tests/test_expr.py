"""Expression compiler parity: device (JAX) vs reference (row-at-a-time).

Mirrors the reference's vectorized-vs-row cross-check pattern
(ref: pkg/expression/builtin_*_vec_test.go).
"""

import numpy as np
import pytest

from tidb_tpu.types import (
    Datum,
    FieldType,
    MyDecimal,
    MyTime,
    TypeCode,
    new_datetime,
    new_decimal,
    new_double,
    new_longlong,
    new_varchar,
)
from tidb_tpu.chunk import Chunk, to_device_batch
from tidb_tpu.expr import col, const, func, lit, compile_exprs
from tidb_tpu.expr.eval_ref import RefEvaluator
from tidb_tpu.expr.ir import ScalarFunc

BOOL_FT = new_longlong(notnull=True)


def random_chunk(rng, n=64):
    """int a, uint b, double c, decimal(12,2) d, varchar e, datetime f, int g(small)."""
    fts = [
        new_longlong(),
        new_longlong(unsigned=True),
        new_double(),
        new_decimal(12, 2),
        new_varchar(12),
        new_datetime(),
        new_longlong(),
    ]
    words = ["apple", "pear", "fig", "kiwi", "banana", "plum", ""]
    rows = []
    for i in range(n):
        def maybe(d, p=0.15):
            return Datum.NULL if rng.random() < p else d

        y, m, dd = 1992 + int(rng.integers(8)), 1 + int(rng.integers(12)), 1 + int(rng.integers(28))
        rows.append(
            [
                maybe(Datum.i64(int(rng.integers(-1000, 1000)))),
                maybe(Datum.u64(int(rng.integers(0, 2**62)) * 3)),
                maybe(Datum.f64(float(np.round(rng.normal() * 100, 3)))),
                maybe(Datum.dec(MyDecimal(f"{rng.integers(-99999, 99999) / 100:.2f}"))),
                maybe(Datum.string(words[int(rng.integers(len(words)))])),
                maybe(Datum.time(MyTime.from_ymd(y, m, dd, int(rng.integers(24)), int(rng.integers(60)), int(rng.integers(60))))),
                maybe(Datum.i64(int(rng.integers(-5, 5)))),
            ]
        )
    return Chunk.from_rows(fts, rows), fts


def check_parity(chunk, fts, exprs, atol=1e-9, dec_ulp=0):
    db = to_device_batch(chunk, capacity=chunk.num_rows())
    compiled = compile_exprs(fts, exprs)
    outs = compiled.fn(db.cols)
    ref = RefEvaluator()
    rows = chunk.rows()
    for ei, (e, (val, null)) in enumerate(zip(exprs, outs)):
        val, null = np.asarray(val), np.asarray(null)
        for i, row in enumerate(rows):
            want = ref.eval(e, row)
            if want.is_null():
                assert null[i], f"expr#{ei} row{i}: device={val[i]} want NULL ({e})"
                continue
            assert not null[i], f"expr#{ei} row{i}: device NULL, want {want} ({e})"
            et = e.ft.eval_type()
            if et == "real":
                assert val[i] == pytest.approx(float(want.val), abs=atol, rel=1e-12), f"expr#{ei} row{i} ({e})"
            elif et == "decimal":
                got = MyDecimal.from_scaled_int(int(val[i]), max(e.ft.decimal, 0))
                if dec_ulp:
                    diff = abs(got.to_scaled_int() - want.val.to_scaled_int(got.scale))
                    assert diff <= dec_ulp, f"expr#{ei} row{i}: {got} != {want.val} ({e})"
                else:
                    assert got == want.val, f"expr#{ei} row{i}: {got} != {want.val} ({e})"
            elif et in ("int", "time"):
                w = want.val.packed if isinstance(want.val, MyTime) else int(want.val)
                got = int(val[i])
                if e.ft.is_unsigned():
                    got &= (1 << 64) - 1
                assert got == w, f"expr#{ei} row{i}: {got} != {w} ({e})"


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(7)
    return random_chunk(rng, 96)


FTS = None  # populated by data fixture in each test via tuple unpack


def C(i, fts):
    return col(i, fts[i])


def test_arithmetic_int_real_decimal(data):
    ch, fts = data
    a, c, d, g = C(0, fts), C(2, fts), C(3, fts), C(6, fts)
    exprs = [
        func("plus", new_longlong(), a, g),
        func("minus", new_longlong(), a, lit(7, new_longlong())),
        func("mul", new_longlong(), a, g),
        func("plus", new_double(), c, c),
        func("mul", new_double(), c, a),
        func("plus", new_decimal(14, 2), d, d),
        func("minus", new_decimal(14, 2), d, lit("1.25", new_decimal(4, 2))),
        func("mul", new_decimal(24, 4), d, d),
        func("plus", new_decimal(14, 2), d, a),
        func("unaryminus", new_longlong(), a),
        func("abs", new_longlong(), a),
    ]
    check_parity(ch, fts, exprs)


def test_division(data):
    ch, fts = data
    a, c, d, g = C(0, fts), C(2, fts), C(3, fts), C(6, fts)
    exprs = [
        func("div", new_double(), c, c),
        func("div", new_decimal(20, 6), d, lit(3, new_longlong())),
        func("div", new_decimal(20, 4), a, g),
        func("intdiv", new_longlong(), a, g),
        func("mod", new_longlong(), a, g),
        func("mod", new_decimal(12, 2), d, lit("7.5", new_decimal(3, 1))),
    ]
    check_parity(ch, fts, exprs)


def test_comparisons(data):
    ch, fts = data
    a, b, c, d, s, t, g = (C(i, fts) for i in range(7))
    exprs = [
        func("gt", BOOL_FT, a, g),
        func("le", BOOL_FT, a, lit(0, new_longlong())),
        func("eq", BOOL_FT, g, lit(2, new_longlong())),
        func("lt", BOOL_FT, c, lit(0.0, new_double())),
        func("ge", BOOL_FT, d, lit("10.00", new_decimal(12, 2))),
        func("gt", BOOL_FT, b, a),  # unsigned vs signed
        func("lt", BOOL_FT, a, d),  # int vs decimal
        func("gt", BOOL_FT, c, d),  # real vs decimal
        func("eq", BOOL_FT, s, lit("fig", new_varchar(8))),
        func("lt", BOOL_FT, s, lit("kiwi", new_varchar(8))),
        func("gt", BOOL_FT, t, lit("1995-06-15", new_datetime())),
        func("nulleq", BOOL_FT, a, g),
        func("between", BOOL_FT, a, lit(-100, new_longlong()), lit(100, new_longlong())),
        func("in", BOOL_FT, g, lit(1, new_longlong()), lit(-2, new_longlong()), lit(4, new_longlong())),
    ]
    check_parity(ch, fts, exprs)


def test_logic_null_control(data):
    ch, fts = data
    a, g = C(0, fts), C(6, fts)
    p = func("gt", BOOL_FT, a, lit(0, new_longlong()))
    q = func("lt", BOOL_FT, g, lit(0, new_longlong()))
    exprs = [
        func("and", BOOL_FT, p, q),
        func("or", BOOL_FT, p, q),
        func("not", BOOL_FT, p),
        func("xor", BOOL_FT, p, q),
        func("isnull", BOOL_FT, a),
        func("ifnull", new_longlong(), a, lit(-999, new_longlong())),
        func("if", new_longlong(), p, a, g),
        func("case", new_longlong(), p, lit(1, new_longlong()), q, lit(2, new_longlong()), lit(3, new_longlong())),
        func("coalesce", new_longlong(), a, g, lit(0, new_longlong())),
    ]
    check_parity(ch, fts, exprs)


def test_casts_and_math(data):
    ch, fts = data
    a, c, d = C(0, fts), C(2, fts), C(3, fts)
    exprs = [
        func("cast", new_double(), a),
        func("cast", new_decimal(20, 3), a),
        func("cast", new_double(), d),
        func("cast", new_longlong(), d),
        func("cast", new_decimal(20, 2), c),
        func("ceil", new_longlong(), d),
        func("floor", new_longlong(), d),
        func("round", new_decimal(12, 0), d),
        func("round", new_double(), c, lit(1, new_longlong())),
        func("sign", new_longlong(), a),
    ]
    # dec_ulp=1: double->decimal cast rounds the binary value on device vs
    # the shortest-repr on the oracle — repr midpoints differ by 1 ulp
    # (documented deviation, see ExprCompiler._to_class)
    check_parity(ch, fts, exprs, dec_ulp=1)


def test_strings_and_time(data):
    ch, fts = data
    s, t = C(4, fts), C(5, fts)
    exprs = [
        func("length", new_longlong(), s),
        func("strcmp", new_longlong(), s, lit("pear", new_varchar(8))),
        func("like", BOOL_FT, s, lit("p%", new_varchar(4))),
        func("like", BOOL_FT, s, lit("fig", new_varchar(4))),
        func("year", new_longlong(), t),
        func("month", new_longlong(), t),
        func("day", new_longlong(), t),
        func("hour", new_longlong(), t),
        func("minute", new_longlong(), t),
        func("second", new_longlong(), t),
        func("to_days", new_longlong(), t),
        func("weekday", new_longlong(), t),
    ]
    check_parity(ch, fts, exprs)


def test_bitops(data):
    ch, fts = data
    a, g = C(0, fts), C(6, fts)
    ub = new_longlong(unsigned=True)
    exprs = [
        func("bitand", ub, a, g),
        func("bitor", ub, a, g),
        func("bitxor", ub, a, g),
        func("bitneg", ub, a),
    ]
    check_parity(ch, fts, exprs)
