"""Fused join+stream-agg kernel (ops/joinagg.py): differential parity vs
the row-at-a-time oracle AND vs the general hash_join+group_aggregate path,
plus the overflow contracts (duplicate build keys -> join overflow -> the
driver's unique-hint drop lands on the general kernel; group capacity ->
grow) — the shapes the bench q3 config rides (ref:
pkg/executor/join/hash_join_v2.go, agg_stream_executor.go)."""

import numpy as np
import pytest

import jax


@pytest.fixture(autouse=True, scope="module")
def _fresh_jax_caches():
    """jax 0.4.x: jitted subfunctions cached by earlier tests under a
    different x64 weak-type state poison the Pallas kernels' lowering
    (i32/i64 verifier mismatch). A clean cache per kernel module keeps
    these hermetic; newer jax keys the cache correctly."""
    jax.clear_caches()

from tidb_tpu.chunk import Chunk
from tidb_tpu.exec import (
    Aggregation,
    ColumnInfo,
    DAGRequest,
    Join,
    Selection,
    TableScan,
    run_dag_on_chunks,
    run_dag_reference,
)
from tidb_tpu.exec.executor import datum_group_key
from tidb_tpu.expr import AggDesc, col, func, lit
from tidb_tpu.types import Datum, new_longlong

LL = new_longlong()
BOOL = new_longlong(notnull=True)


def canon(rows):
    return sorted(tuple(datum_group_key(d) for d in r) for r in rows)


def _mk(fts, cols_np):
    rows = []
    n = len(cols_np[0])
    for i in range(n):
        rows.append([Datum.NULL if c[i] is None else Datum.i64(int(c[i])) for c in cols_np])
    return Chunk.from_rows(fts, rows)


def _dag(aggs, build_unique=True, probe_sel=None, group_key=0):
    pfts = [LL, LL]  # okey, v
    bfts = [LL, LL]  # okey, w
    ps = TableScan(1, (ColumnInfo(1, pfts[0]), ColumnInfo(2, pfts[1])))
    bs = TableScan(2, (ColumnInfo(1, bfts[0]), ColumnInfo(2, bfts[1])))
    j = Join(build=(bs,), probe_keys=(col(group_key, pfts[0]),),
             build_keys=(col(0, bfts[0]),), join_type="inner",
             build_unique=build_unique)
    agg = Aggregation(group_by=(col(group_key, pfts[0]),), aggs=tuple(aggs))
    execs = [ps]
    if probe_sel is not None:
        execs.append(probe_sel)
    execs += [j, agg]
    n_out = len(aggs) + 1
    return DAGRequest(tuple(execs), output_offsets=tuple(range(n_out)))


def _fused_calls(monkeypatch):
    """Spy on BOTH fused kernels (packed int fast path + general
    stream-agg path); either counts as the fused route."""
    import tidb_tpu.ops.joinagg as ja

    calls = []
    og, op = ja.join_stream_agg, ja.packed_join_groupsum

    def spy_g(*a, **k):
        calls.append("general")
        return og(*a, **k)

    def spy_p(*a, **k):
        calls.append("packed")
        return op(*a, **k)

    monkeypatch.setattr(ja, "join_stream_agg", spy_g)
    monkeypatch.setattr(ja, "packed_join_groupsum", spy_p)
    return calls


def test_fused_parity_and_trigger(monkeypatch):
    calls = _fused_calls(monkeypatch)
    rng = np.random.default_rng(0)
    n, nb = 600, 40
    probe = _mk([LL, LL], [rng.integers(0, 64, n), rng.integers(0, 100, n)])
    build = _mk([LL, LL], [np.arange(nb), rng.integers(0, 9, nb)])
    dag = _dag([AggDesc("sum", (col(1, LL),)), AggDesc("count", ()),
                AggDesc("min", (col(1, LL),)), AggDesc("first_row", (col(1, LL),))])
    got = run_dag_on_chunks(dag, [probe, build], group_capacity=256)
    want = run_dag_reference(dag, [probe, build])
    assert canon(got.rows()) == canon(want)
    assert calls, "fused join+agg path did not trigger"


def test_fused_null_keys_excluded(monkeypatch):
    calls = _fused_calls(monkeypatch)
    probe = _mk([LL, LL], [[1, None, 2, None, 1], [10, 20, 30, 40, 50]])
    build = _mk([LL, LL], [[1, 2, 3], [7, 8, 9]])
    dag = _dag([AggDesc("sum", (col(1, LL),)), AggDesc("count", ())])
    got = run_dag_on_chunks(dag, [probe, build], group_capacity=64)
    want = run_dag_reference(dag, [probe, build])
    assert canon(got.rows()) == canon(want)
    assert calls


def test_fused_with_probe_selection(monkeypatch):
    calls = _fused_calls(monkeypatch)
    rng = np.random.default_rng(1)
    n = 500
    probe = _mk([LL, LL], [rng.integers(0, 32, n), rng.integers(0, 100, n)])
    build = _mk([LL, LL], [np.arange(24), rng.integers(0, 9, 24)])
    sel = Selection((func("gt", BOOL, col(1, LL), lit(40, LL)),))
    dag = _dag([AggDesc("avg", (col(1, LL),)), AggDesc("max", (col(1, LL),))],
               probe_sel=sel)
    got = run_dag_on_chunks(dag, [probe, build], group_capacity=128)
    want = run_dag_reference(dag, [probe, build])
    assert canon(got.rows()) == canon(want)
    assert calls


def test_duplicate_build_keys_fall_back_correctly(monkeypatch):
    """A false unique-build promise: the fused kernel raises the join
    overflow, the driver drops the hint and the general kernel (fan-out
    expansion) still returns the right multiset."""
    calls = _fused_calls(monkeypatch)
    probe = _mk([LL, LL], [[5, 5, 6, 7], [1, 2, 3, 4]])
    build = _mk([LL, LL], [[5, 5, 7, 8], [100, 200, 300, 400]])
    dag = _dag([AggDesc("count", ()), AggDesc("sum", (col(1, LL),))])
    got = run_dag_on_chunks(dag, [probe, build], group_capacity=64)
    want = run_dag_reference(dag, [probe, build])
    assert canon(got.rows()) == canon(want)
    assert calls, "fused path must run first (and overflow)"
    # key 5 matches two build rows -> count doubles through expansion
    assert any(int(r[0].val) == 4 for r in got.rows())


def test_mostly_unmatched_probes(monkeypatch):
    calls = _fused_calls(monkeypatch)
    rng = np.random.default_rng(2)
    n = 400
    probe = _mk([LL, LL], [rng.integers(0, 1000, n), rng.integers(0, 50, n)])
    build = _mk([LL, LL], [np.arange(5), np.arange(5)])
    dag = _dag([AggDesc("sum", (col(1, LL),)), AggDesc("count", ())])
    got = run_dag_on_chunks(dag, [probe, build], group_capacity=2048)
    want = run_dag_reference(dag, [probe, build])
    assert canon(got.rows()) == canon(want)
    assert calls


def test_group_capacity_overflow_grows(monkeypatch):
    """More distinct matched keys than capacity: the group flag drives the
    retry ladder, and the resolved run matches the oracle."""
    calls = _fused_calls(monkeypatch)
    rng = np.random.default_rng(3)
    n = 800
    probe = _mk([LL, LL], [rng.integers(0, 300, n), rng.integers(0, 10, n)])
    build = _mk([LL, LL], [np.arange(300), np.zeros(300)])
    dag = _dag([AggDesc("sum", (col(1, LL),))])
    got = run_dag_on_chunks(dag, [probe, build], group_capacity=16)
    want = run_dag_reference(dag, [probe, build])
    assert canon(got.rows()) == canon(want)
    # the packed path has no group capacity at all (boundary-layout
    # outputs); the general fused path would retry through the ladder
    assert calls, "fused path did not trigger"


def test_filtered_runs_do_not_trip_capacity():
    """Build∪probe key runs that contribute nothing must not raise the
    group overflow (the precise surviving-row condition): 4 output groups
    through a capacity of 8 despite ~100 distinct unmatched probe keys."""
    from tidb_tpu.exec.builder import build_program
    from tidb_tpu.chunk import to_device_batch

    rng = np.random.default_rng(4)
    probe = _mk([LL, LL], [
        np.concatenate([rng.integers(0, 4, 64), rng.integers(1000, 1100, 100)]),
        rng.integers(0, 10, 164),
    ])
    build = _mk([LL, LL], [np.arange(4), np.arange(4)])
    dag = _dag([AggDesc("sum", (col(1, LL),))])
    batches = [to_device_batch(c, capacity=256) for c in (probe, build)]
    prog = build_program(dag, tuple(b.capacity for b in batches), group_capacity=8)
    packed, valid, n_out, (g_ovf, j_ovf, t_ovf, *_needs), _ = prog.fn(*batches)
    assert not bool(g_ovf) and not bool(j_ovf)
    assert int(n_out) == 4


def test_packed_negative_values_and_nulls(monkeypatch):
    """Negative agg values exercise the non-negativity shift unwind; NULL
    args exercise the per-combo non-null count lanes."""
    calls = _fused_calls(monkeypatch)
    rng = np.random.default_rng(5)
    n = 500
    vals = [int(v) if v % 3 else None for v in rng.integers(-10**6, 10**6, n)]
    probe = _mk([LL, LL], [rng.integers(0, 40, n), vals])
    build = _mk([LL, LL], [np.arange(30), np.zeros(30)])
    dag = _dag([AggDesc("sum", (col(1, LL),)), AggDesc("avg", (col(1, LL),)),
                AggDesc("count", (col(1, LL),)), AggDesc("count", ())])
    got = run_dag_on_chunks(dag, [probe, build], group_capacity=128)
    want = run_dag_reference(dag, [probe, build])
    assert canon(got.rows()) == canon(want)
    assert "packed" in calls


def test_packed_chain_three_tables(monkeypatch):
    """The q3 shape: lineitem joins orders joins customer, GROUP BY okey —
    the membership chain plus packed groupsum, diffed against the oracle."""
    calls = _fused_calls(monkeypatch)
    rng = np.random.default_rng(6)
    nl, no, nc = 800, 100, 20
    lfts = [LL, LL]
    ofts = [LL, LL]
    cfts = [LL, LL]
    ls = TableScan(1, (ColumnInfo(1, lfts[0]), ColumnInfo(2, lfts[1])))
    os_ = TableScan(2, (ColumnInfo(1, ofts[0]), ColumnInfo(2, ofts[1])))
    cs = TableScan(3, (ColumnInfo(1, cfts[0]), ColumnInfo(2, cfts[1])))
    cust_sel = Selection((func("eq", BOOL, col(1, cfts[1]), lit(1, LL)),))
    inner = Join(build=(cs, cust_sel), probe_keys=(col(1, ofts[1]),),
                 build_keys=(col(0, cfts[0]),), join_type="inner", build_unique=True)
    outer = Join(build=(os_, inner), probe_keys=(col(0, lfts[0]),),
                 build_keys=(col(0, ofts[0]),), join_type="inner", build_unique=True)
    lsel = Selection((func("gt", BOOL, col(1, lfts[1]), lit(5, LL)),))
    agg = Aggregation(group_by=(col(0, lfts[0]),),
                      aggs=(AggDesc("sum", (col(1, lfts[1]),)), AggDesc("count", ())))
    dag = DAGRequest((ls, lsel, outer, agg), output_offsets=(0, 1, 2))
    lchunk = _mk(lfts, [rng.integers(0, no, nl), rng.integers(0, 100, nl)])
    ochunk = _mk(ofts, [np.arange(no), rng.integers(0, nc, no)])
    cchunk = _mk(cfts, [np.arange(nc), rng.integers(0, 3, nc)])
    got = run_dag_on_chunks(dag, [lchunk, ochunk, cchunk], group_capacity=256)
    want = run_dag_reference(dag, [lchunk, ochunk, cchunk])
    assert canon(got.rows()) == canon(want)
    assert "packed" in calls


def test_packed_int32_min_key_no_phantom_join(monkeypatch):
    """ADVICE r5 high, pinned end-to-end: jnp.abs(INT32_MIN) wraps to
    INT32_MIN (negative) and used to PASS the packed range gate, so key
    -2^31 shifted left wrapped to packed key 0 and silently joined as a
    phantom key-0 group with no overflow flag. The int64-domain range
    check must flag it instead, and the driver's retry must land on a
    correct general-kernel run (same contract as any out-of-range key)."""
    calls = _fused_calls(monkeypatch)
    INT32_MIN = -(1 << 31)
    # build key INT32_MIN + probe key 0: the ADVICE repro — before the fix
    # probe rows with key 0 joined the INT32_MIN build row as key 0
    probe = _mk([LL, LL], [[0, 0, 5, INT32_MIN], [10, 20, 30, 40]])
    build = _mk([LL, LL], [[INT32_MIN, 5], [7, 8]])
    dag = _dag([AggDesc("sum", (col(1, LL),)), AggDesc("count", ())])
    got = run_dag_on_chunks(dag, [probe, build], group_capacity=64)
    want = run_dag_reference(dag, [probe, build])
    assert canon(got.rows()) == canon(want)
    assert "packed" in calls, "packed path must run (and overflow-retry)"
    # sanity on the oracle itself: key 0 must NOT appear (no build row 0),
    # and the INT32_MIN probe row joins its real build row
    keys = {r[-1][1] for r in canon(want)}
    assert 0 not in keys and INT32_MIN in keys and 5 in keys


def test_membership_chain_int32_min_payload_key(monkeypatch):
    """The same wrap through membership_chain (the 3-table packed chain):
    an INT32_MIN key on the chain's inner join must not alias key 0."""
    import jax.numpy as jnp

    from tidb_tpu.ops.joinagg import membership_chain

    INT32_MIN = -(1 << 31)
    outer = jnp.asarray([0, INT32_MIN, 7], jnp.int64)
    inner = jnp.asarray([INT32_MIN, 7], jnp.int64)
    ok = jnp.ones(3, bool)
    iok = jnp.ones(2, bool)
    payload = jnp.asarray([1, 2, 3], jnp.int64)
    _pay, _ok_out, overflow = membership_chain(outer, ok, inner, iok, payload)
    # out-of-range key must raise the overflow flag -> general-kernel retry
    assert bool(overflow)


def test_packed_wide_key_range_falls_back(monkeypatch):
    """Keys spanning more than 2^30 trip the packed range check; the
    driver's retry lands on a correct general-path run."""
    calls = _fused_calls(monkeypatch)
    probe = _mk([LL, LL], [[0, 1 << 40, 5], [10, 20, 30]])
    build = _mk([LL, LL], [[0, 1 << 40], [0, 0]])
    dag = _dag([AggDesc("sum", (col(1, LL),))])
    got = run_dag_on_chunks(dag, [probe, build], group_capacity=64)
    want = run_dag_reference(dag, [probe, build])
    assert canon(got.rows()) == canon(want)
    assert "packed" in calls, "packed path must run (and overflow)"
