"""Production front door (ISSUE 15): digest-keyed plan cache across its
three tiers (pointget / dag / ast), typed decline reasons, invalidation
on schema + sysvar + binding drift, PREPARE/EXECUTE digest sharing,
admission control with typed ServerIsBusy shedding on the Backoffer
server_busy budget, per-session memory quotas, and the shared-cache
lockwatch storm (ref: pkg/planner/core/plan_cache.go +
pkg/parser/digester.go; TiDB VLDB'20 §"SQL engine")."""

import os
import sys
import threading
import time

import pytest

from tidb_tpu.sql.session import Session, SQLError
from tidb_tpu.util import failpoint, metrics

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))


def make_session(rows=8):
    s = Session()
    s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT, "
              "k VARCHAR(20), KEY iv (v))")
    if rows:
        s.execute("INSERT INTO t VALUES " + ",".join(
            f"({i},{i * 10},'x{i}')" for i in range(rows)))
    return s


def hits():
    return metrics.PLAN_CACHE_HITS.value


def misses():
    return metrics.PLAN_CACHE_MISSES.value


def declines(reason):
    return metrics.PLAN_CACHE_DECLINES.labels(reason).value


def cold_rows(s, sql):
    """The statement's rows with the plan cache OFF — the byte-equality
    oracle for re-bound hits."""
    s.execute("SET tidb_enable_plan_cache = OFF")
    try:
        return s.execute(sql).rows
    finally:
        s.execute("SET tidb_enable_plan_cache = ON")


def same_rows(a, b):
    """Byte-level row equality: datum kinds AND values."""
    assert len(a) == len(b)
    for ra, rb in zip(a, b):
        assert len(ra) == len(rb)
        for da, db in zip(ra, rb):
            assert da.kind == db.kind and da.val == db.val, (da, db)


# ------------------------------------------------------------ cache matrix

class TestPlanCacheMatrix:
    def test_pointget_tier_hit_and_value_rebind(self):
        s = make_session()
        h0, m0 = hits(), misses()
        assert s.execute("select v from t where id = 3").values() == [[30]]
        assert (hits(), misses()) == (h0, m0 + 1)  # cold: install
        assert s.execute("select v from t where id = 3").values() == [[30]]
        assert (hits(), misses()) == (h0 + 1, m0 + 1)  # identical shape: hit
        # a DIFFERENT literal re-binds into the same template
        assert s.execute("select v from t where id = 5").values() == [[50]]
        assert hits() == h0 + 2
        assert s.catalog.plan_cache.stats()["tiers"]["pointget"] == 1

    def test_dag_tier_selection_rebind_byte_equal(self):
        s = make_session()
        sql = "select v from t where k = 'x4'"
        oracle = cold_rows(s, sql)
        s.execute("select v from t where k = 'x2'")  # install
        assert s.catalog.plan_cache.stats()["tiers"]["dag"] >= 1
        h0 = hits()
        got = s.execute(sql).rows
        assert hits() == h0 + 1
        same_rows(got, oracle)

    def test_handle_range_rebind_byte_equal(self):
        s = make_session()
        sql = "select v, k from t where id >= 2 and id < 6 order by id"
        oracle = cold_rows(s, sql)
        s.execute("select v, k from t where id >= 1 and id < 3 order by id")
        h0 = hits()
        got = s.execute(sql).rows
        assert hits() == h0 + 1
        same_rows(got, oracle)

    def test_ast_tier_index_range_hit(self):
        s = make_session()
        sql = "select k from t where v >= 20 and v < 51 order by v"
        oracle = cold_rows(s, sql)
        s.execute("select k from t where v >= 10 and v < 31 order by v")
        h0 = hits()
        got = s.execute(sql).rows
        assert hits() == h0 + 1
        same_rows(got, oracle)

    def test_miss_on_alter_table_schema_fingerprint(self):
        s = make_session()
        s.execute("select v from t where id = 2")
        h0 = hits()
        assert s.execute("select v from t where id = 2").values() == [[20]]
        assert hits() == h0 + 1
        s.execute("alter table t add column w bigint")
        h1, m1 = hits(), misses()
        assert s.execute("select v from t where id = 2").values() == [[20]]
        # schema drift dropped the entry: miss + reinstall, then hits again
        assert (hits(), misses()) == (h1, m1 + 1)
        assert s.execute("select v from t where id = 2").values() == [[20]]
        assert hits() == h1 + 1

    def test_miss_on_plan_sysvar_change(self):
        s = make_session()
        s.execute("select v from t where id = 2")
        for set_sql in ("set tidb_isolation_read_engines = 'tpu'",
                        "set sql_mode = ''"):
            s.execute(set_sql)
            h0, m0 = hits(), misses()
            assert s.execute("select v from t where id = 2").values() == [[20]]
            # the sysvar fingerprint is part of the KEY: other entries
            assert (hits(), misses()) == (h0, m0 + 1)

    def test_prepare_execute_shares_entry_and_summary_digest(self):
        s = make_session()
        s.execute("prepare st from 'select v from t where id = ?'")
        s.execute("set @a = 2")
        m0 = misses()
        assert s.execute("execute st using @a").values() == [[20]]
        assert misses() == m0 + 1  # EXECUTE installed the entry
        h0 = hits()
        # the DIRECT textual form digests identically: instant hit
        assert s.execute("select v from t where id = 6").values() == [[60]]
        assert hits() == h0 + 1
        assert s.execute("execute st using @a").values() == [[20]]
        assert hits() == h0 + 2
        # satellite: EXECUTE records under the UNDERLYING statement's
        # digest — one summary row for the prepared + direct forms
        r = s.execute(
            "select exec_count from information_schema.statements_summary "
            "where digest_text = 'select v from t where id = ?'")
        assert len(r.rows) == 1 and int(r.rows[0][0].val) == 3

    def test_execute_param_rebind_byte_equal_cold(self):
        s = make_session()
        oracle = cold_rows(s, "select v, k from t where id = 5")
        s.execute("prepare st from 'select v, k from t where id = ?'")
        s.execute("set @p = 1")
        s.execute("execute st using @p")  # install
        s.execute("set @p = 5")
        h0 = hits()
        got = s.execute("execute st using @p").rows
        assert hits() == h0 + 1
        same_rows(got, oracle)

    def test_decline_reasons_typed_and_counted(self):
        s = make_session()
        cases = [
            ("select v from t where id = (select max(id) from t)", "subquery"),
            ("select * from (select v from t) d", "derived_table"),
            ("select @x", "user_var"),
            ("select 1", "no_table"),
        ]
        for sql, reason in cases:
            d0 = declines(reason)
            s.execute(sql)
            assert declines(reason) == d0 + 1, reason
        # session-state reasons: open txn + stale read
        s.execute("begin")
        d0 = declines("in_txn")
        s.execute("select v from t where id = 1")
        assert declines("in_txn") == d0 + 1
        s.execute("commit")
        ts = s.store.kv.max_committed()
        s.execute(f"set tidb_snapshot = '{ts}'")
        d0 = declines("stale_read")
        s.execute("select v from t where id = 1")
        assert declines("stale_read") == d0 + 1
        s.execute("set tidb_snapshot = ''")
        # non-SELECT kinds decline typed too
        d0 = declines("not_select")
        s.execute("insert into t values (100, 1000, 'y')")
        assert declines("not_select") == d0 + 1

    def test_explain_surfaces_cacheability(self):
        s = make_session()
        r = s.execute("explain select v from t where id = 1").values()
        assert ["plan_cache: cacheable"] in r
        r = s.execute(
            "explain select v from t where id = (select max(id) from t)"
        ).values()
        assert ["plan_cache: decline(subquery)"] in r

    def test_explain_analyze_plan_cache_row_and_trace_span(self):
        s = make_session()
        # EXPLAIN ANALYZE probes with the INNER statement's digest, so
        # the first run misses and the second hits — attributably
        r = s.execute("explain analyze select v from t where id = 1")
        rows = {str(x[0].val): str(x[5].val) for x in r.rows}
        assert rows.get("plan_cache") == "miss"
        r = s.execute("explain analyze select v from t where id = 1")
        rows = {str(x[0].val): str(x[5].val) for x in r.rows}
        assert rows.get("plan_cache") == "hit(pointget)"
        tr = s.execute("TRACE select v from t where id = 1").values()
        assert any("session.plan_cache" in str(row[0]) for row in tr)

    def test_lru_eviction_bounded_and_counted(self):
        s = make_session()
        s.execute("set tidb_plan_cache_size = 2")
        e0 = metrics.PLAN_CACHE_EVICTIONS.value
        s.execute("select v from t where id = 1")
        s.execute("select k from t where id = 1")
        s.execute("select id from t where v = 10")
        assert len(s.catalog.plan_cache) <= 2
        assert metrics.PLAN_CACHE_EVICTIONS.value > e0

    def test_binding_change_invalidates(self):
        s = make_session()
        s.execute("select v from t where id = 2")
        h0 = hits()
        s.execute("select v from t where id = 2")
        assert hits() == h0 + 1
        s.execute("create global binding for select v from t where id = 1 "
                  "using select /*+ use_index(t, iv) */ v from t where id = 1")
        h1, m1 = hits(), misses()
        s.execute("select v from t where id = 2")
        assert misses() == m1 + 1  # bindings_rev moved: revalidate cold

    def test_disabled_consults_nothing(self):
        s = make_session()
        s.execute("set tidb_enable_plan_cache = OFF")
        h0, m0 = hits(), misses()
        s.execute("select v from t where id = 1")
        s.execute("select v from t where id = 1")
        assert (hits(), misses()) == (h0, m0)


class TestProbeNeverLeaksIntoNestedSelects:
    """The probe names the WHOLE statement's text. A non-SELECT statement
    must drop it before any nested _run_select could install the inner
    select under the outer digest — a later digest-equal statement would
    then serve rows instead of running the DML."""

    def test_insert_select_never_installs_under_insert_digest(self):
        s = make_session(rows=4)
        s.execute("create table t2 (id bigint primary key, v bigint)")
        n0 = len(s.catalog.plan_cache)
        s.execute("insert into t2 select id, v from t where v = 20")
        assert len(s.catalog.plan_cache) == n0  # nothing installed
        s.execute("delete from t2")
        # digest-equal re-run must INSERT, not serve cached select rows
        r = s.execute("insert into t2 select id, v from t where v = 20")
        assert r.affected == 1 and not r.rows
        assert s.execute("select count(*) from t2").values() == [[1]]

    def test_prepared_dml_execute_never_arms_the_plan_cache(self):
        s = make_session(rows=4)
        s.execute("create table t3 (id bigint primary key, v bigint)")
        s.execute("prepare pi from 'insert into t3 select id, v from t where v = ?'")
        s.execute("set @w = 20")
        n0 = len(s.catalog.plan_cache)
        s.execute("execute pi using @w")
        assert len(s.catalog.plan_cache) == n0
        s.execute("delete from t3")
        r = s.execute("execute pi using @w")
        assert r.affected == 1 and not r.rows
        # the summary still joins the underlying digest (the logging ride
        # is independent of the plan-cache arm)
        r = s.execute(
            "select exec_count from information_schema.statements_summary "
            "where digest_text = 'insert into t3 select id , v from t where v = ?'")
        assert len(r.rows) == 1 and int(r.rows[0][0].val) == 2

    def test_create_view_never_installs_under_ddl_digest(self):
        s = make_session(rows=4)
        n0 = len(s.catalog.plan_cache)
        s.execute("create view vv as select id from t where v = 20")
        assert len(s.catalog.plan_cache) == n0
        s.execute("drop view vv")
        s.execute("create view vv as select id from t where v = 20")
        assert s.catalog.view_of("vv") is not None


# ------------------------------------------------------------- admission

class TestAdmission:
    def test_failpoint_shed_is_typed_9003_with_backoff_hint(self):
        s = make_session(rows=2)
        a0 = metrics.ADMISSION_SHED.labels("gate").value
        failpoint.enable("server/admission-full", True)
        try:
            with pytest.raises(SQLError) as ei:
                s.execute("select v from t where id = 1")
        finally:
            failpoint.disable("server/admission-full")
        assert ei.value.code == 9003
        assert ei.value.backoff_ms > 0
        assert "server_is_busy" in str(ei.value)
        assert metrics.ADMISSION_SHED.labels("gate").value == a0 + 1
        # gate cleared: the statement runs
        assert s.execute("select v from t where id = 1").values() == [[10]]

    def test_saturation_sheds_and_backoffer_retry_succeeds(self):
        from tidb_tpu.util.backoff import Backoffer

        s = make_session(rows=2)
        gate = s.store.admission
        gate.configure(max_inflight=1, session_queue=1, queue_wait_ms=2.0,
                       shed_backoff_ms=5)
        entered = threading.Event()
        release = threading.Event()

        def holder():
            with gate.admit("holder"):
                entered.set()
                release.wait(timeout=30)

        th = threading.Thread(target=holder, daemon=True)
        th.start()
        entered.wait(timeout=30)
        s2 = Session(store=s.store, catalog=s.catalog)
        try:
            with pytest.raises(SQLError) as ei:
                s2.execute("select v from t where id = 1")
            assert ei.value.code == 9003
            # the client contract: classify as server_busy, back off on
            # the existing budget, retry — and succeed once load drains
            bo = Backoffer(budget_ms=4000)
            release.set()
            th.join(timeout=30)
            for _ in range(50):
                try:
                    got = s2.execute("select v from t where id = 1").values()
                    break
                except SQLError as exc:
                    assert exc.code == 9003
                    bo.backoff("server_busy",
                               suggested_ms=getattr(exc, "backoff_ms", 0))
            else:
                raise AssertionError("backoffer retries never admitted")
            assert got == [[10]]
        finally:
            gate.configure(max_inflight=0)

    def test_dispatch_gate_sheds_before_tasks(self):
        s = make_session()
        gate = s.store.admission
        gate.configure(max_dispatch=1)
        tok = gate.before_dispatch()  # occupy the only dispatch slot
        try:
            with tok:
                with pytest.raises(SQLError) as ei:
                    # a scan must go through distsql dispatch (not pointget)
                    s.execute("select sum(v) from t")  # noqa: B017
                assert ei.value.code == 9003
        finally:
            gate.configure(max_dispatch=0)
        assert str(s.execute("select sum(v) from t").values()[0][0]) == "280"

    def test_queue_admits_when_slot_frees_in_time(self):
        s = make_session(rows=2)
        gate = s.store.admission
        gate.configure(max_inflight=1, session_queue=2, queue_wait_ms=2000.0)
        entered = threading.Event()

        def holder():
            with gate.admit("holder"):
                entered.set()
                time.sleep(0.15)

        th = threading.Thread(target=holder, daemon=True)
        th.start()
        entered.wait(timeout=30)
        q0 = metrics.ADMISSION_QUEUE_WAITS.value
        try:
            s2 = Session(store=s.store, catalog=s.catalog)
            # waits in the per-session queue, admitted when the holder exits
            assert s2.execute("select v from t where id = 1").values() == [[10]]
            assert metrics.ADMISSION_QUEUE_WAITS.value == q0 + 1
        finally:
            th.join(timeout=30)
            gate.configure(max_inflight=0)

    def test_metric_families_pass_scrape_check(self):
        s = make_session(rows=2)
        failpoint.enable("server/admission-full", True)
        try:
            with pytest.raises(SQLError):
                s.execute("select v from t where id = 1")
        finally:
            failpoint.disable("server/admission-full")
        s.execute("select v from t where id = 1")
        s.execute("select v from t where id = 1")
        text = metrics.REGISTRY.dump()
        for family in (
            "tidb_tpu_plan_cache_hits_total",
            "tidb_tpu_plan_cache_misses_total",
            "tidb_tpu_plan_cache_evictions_total",
            "tidb_tpu_plan_cache_declines_total",
            "tidb_tpu_plan_cache_entries",
            "tidb_tpu_admission_admitted_total",
            "tidb_tpu_admission_shed_total",
            "tidb_tpu_admission_queue_waits_total",
            "tidb_tpu_admission_inflight",
        ):
            assert f"# TYPE {family}" in text, family
        from scrape_check import validate

        assert validate(text) == []


# ------------------------------------------------- session memory quota

class TestSessionMemQuota:
    def test_over_quota_spills_then_types_the_error(self):
        s = make_session(rows=64)
        e0 = metrics.MEM_EVICTIONS.value
        s.execute("set tidb_mem_quota_session = 1")
        try:
            with pytest.raises(SQLError, match="memory quota exceeded"):
                s.execute("select v, count(*) from t group by v")
        finally:
            s.execute("set tidb_mem_quota_session = 0")
        # the breach ran the spill hook (host eviction) before cancelling
        assert metrics.MEM_EVICTIONS.value > e0
        # the session survives: quota released, statements run again
        assert s.execute("select count(*) from t").values() == [[64]]

    def test_generous_quota_unaffected(self):
        s = make_session(rows=32)
        s.execute("set tidb_mem_quota_session = 1073741824")
        try:
            r = s.execute("select v, count(*) from t group by v order by v")
            assert len(r.rows) == 32
        finally:
            s.execute("set tidb_mem_quota_session = 0")


# ------------------------------------------------------- lockwatch storm

def test_shared_plan_cache_lockwatch_storm():
    """Concurrent sessions of ONE catalog hammering one shared plan
    cache (hits, installs, invalidating DDL, sysvar flips) under the
    runtime lockset detector: zero lock-order cycles, zero unguarded
    annotated accesses, and the cache actually serves hits."""
    from tidb_tpu.analysis import lockwatch

    with lockwatch.watching() as w:
        src = make_session(rows=32)
        stop = threading.Event()
        errors: list = []
        h0 = hits()

        def reader(seed):
            sess = Session(store=src.store, catalog=src.catalog)
            i = seed
            while not stop.is_set():
                try:
                    sess.execute(f"select v from t where id = {i % 32}")
                    sess.execute(f"select k from t where v = {(i % 32) * 10}")
                    i += 1
                except SQLError:
                    pass
                except Exception as exc:  # noqa: BLE001
                    errors.append(exc)
                    return

        def ddler():
            sess = Session(store=src.store, catalog=src.catalog)
            n = 0
            while not stop.is_set():
                try:
                    sess.execute(f"alter table t add column w{n} bigint")
                    n += 1
                    time.sleep(0.02)
                except SQLError:
                    pass
                except Exception as exc:  # noqa: BLE001
                    errors.append(exc)
                    return

        def sysvar_flipper():
            sess = Session(store=src.store, catalog=src.catalog)
            while not stop.is_set():
                try:
                    sess.execute("set global tidb_plan_cache_size = 64")
                    sess.execute("set global tidb_plan_cache_size = 512")
                    time.sleep(0.01)
                except SQLError:
                    pass
                except Exception as exc:  # noqa: BLE001
                    errors.append(exc)
                    return

        threads = [threading.Thread(target=reader, args=(i * 7,), daemon=True)
                   for i in range(3)]
        threads.append(threading.Thread(target=ddler, daemon=True))
        threads.append(threading.Thread(target=sysvar_flipper, daemon=True))
        for t in threads:
            t.start()
        time.sleep(1.5)
        stop.set()
        for t in threads:
            t.join(timeout=30)
    rep = w.report()
    assert rep["cycles"] == [], rep["cycles"]
    assert rep["violations"] == [], "\n".join(rep["violations"])
    assert not errors, errors
    assert hits() > h0, "storm never hit the shared cache"
