"""Radix-partitioned hash join (ISSUE 13): partition-count sweep vs the
oracle, the skewed-key escape hatch, Pallas-vs-general byte-equality over
the full key-type matrix (signed/unsigned incl. INT32_MIN boundary keys,
NULLs), capacity-ladder rung reuse (retries hit cached rungs — zero
recompiles, asserted via ProgramCache stats), the never-starve
overflow-degrade contract, and a mesh-tier join run matching the pool
tier, plus EXPLAIN ANALYZE / TRACE `join_radix` attribution."""

import numpy as np
import pytest

import jax.numpy as jnp

from tidb_tpu.chunk import Chunk, to_device_batch
from tidb_tpu.exec import (
    Aggregation,
    ColumnInfo,
    DAGRequest,
    Join,
    TableScan,
    run_dag_reference,
)
from tidb_tpu.exec.builder import ProgramCache, build_program
from tidb_tpu.exec.executor import datum_group_key, decode_outputs, drive_program_info
from tidb_tpu.exec.ladder import RUNG_BASE, next_rung, rung_for, rungs_up_to
from tidb_tpu.expr import AggDesc, col
from tidb_tpu.expr.compile import CompVal
from tidb_tpu.ops.radix_join import radix_hash_join, radix_plan
from tidb_tpu.types import Datum, new_longlong

LL = new_longlong()
NN = new_longlong(notnull=True)


def _cv(vals, nulls, ft=LL):
    vals = np.asarray(vals, np.int64)
    nulls = np.zeros(len(vals), bool) if nulls is None else np.asarray(nulls, bool)
    return CompVal(jnp.asarray(vals), jnp.asarray(nulls), ft)


def _ref_unique_join(bk, b_ok, pk, p_ok):
    """(build_idx, matched) oracle: first build row per key; None on dup."""
    table = {}
    dup = False
    for i, (k, ok) in enumerate(zip(bk, b_ok)):
        if ok:
            if k in table:
                dup = True
            else:
                table[k] = i
    idx = np.full(len(pk), -1, np.int64)
    for j, (k, ok) in enumerate(zip(pk, p_ok)):
        if ok and k in table:
            idx[j] = table[k]
    return idx, dup


def _run_kernel(bk, bnull, pk, pnull, plan, strategy, ft=LL, jc=4096):
    bkv, pkv = _cv(bk, bnull, ft), _cv(pk, pnull, ft)
    nb, np_ = len(bk), len(pk)
    res, esc = radix_hash_join(
        [bkv], [pkv], jnp.ones(nb, bool), jnp.ones(np_, bool),
        "inner", jc, plan, strategy=strategy,
    )
    return (np.asarray(res.build_idx), np.asarray(res.out_valid),
            bool(res.overflow), int(res.need), int(esc))


class TestLadder:
    def test_rungs(self):
        assert rung_for(0) == RUNG_BASE
        assert rung_for(64) == 64
        assert rung_for(65) == 128
        assert rung_for(4096) == 4096
        assert next_rung(64) == 256
        assert rungs_up_to(512) == [64, 128, 256, 512]

    def test_overflow_step_policy(self):
        from tidb_tpu.exec.ladder import RUNG_MAX, overflow_step

        # pure capacity miss: direct jump, hints kept
        gc, jc, drop = overflow_step(64, 64, True, True, 700, 4096)
        assert (gc, jc, drop) == (1024, 4096, False)
        # hintless join overflow: step + drop (the re-salt dual action)
        _gc, jc, drop = overflow_step(64, 64, False, True, 0, 0)
        assert jc == 256 and drop
        # RUNG_MAX ceiling: the jump saturates — the retry must still
        # change the program, so the hints drop instead of a stall
        _gc, jc, drop = overflow_step(64, RUNG_MAX, False, True, 0, RUNG_MAX * 4)
        assert drop


class TestRadixKernel:
    @pytest.mark.parametrize("n_parts", [2, 8, 32])
    @pytest.mark.parametrize("strategy", ["dense", "search"])
    def test_partition_sweep_parity(self, n_parts, strategy):
        rng = np.random.default_rng(n_parts)
        nb, np_ = 64, 1024
        bk = rng.permutation(np.arange(-32, nb - 32)).astype(np.int64)
        pk = rng.integers(-40, 48, np_).astype(np.int64)
        bnull = rng.random(nb) < 0.1
        pnull = rng.random(np_) < 0.1
        plan = (n_parts, 128, max(8, 2 * np_ // n_parts), 1024)
        bidx, ov, overflow, _need, _esc = _run_kernel(bk, bnull, pk, pnull, plan, strategy)
        assert not overflow
        want, _dup = _ref_unique_join(bk, ~bnull, pk, ~pnull)
        assert (bidx == want).all()
        assert (ov == (want >= 0)).all()

    def test_skewed_key_escape_hatch(self):
        """A heavy-hitter probe key overflows its partition's probe table;
        the escape hatch routes the whole partition through the general
        merge kernel and the result stays exact."""
        rng = np.random.default_rng(3)
        nb, np_ = 32, 512
        bk = np.arange(nb, dtype=np.int64)
        pk = np.where(rng.random(np_) < 0.5, np.int64(7),
                      rng.integers(0, 40, np_)).astype(np.int64)
        plan = (8, 16, 64, 1024)
        bidx, ov, overflow, _need, esc = _run_kernel(bk, None, pk, None, plan, "dense")
        assert not overflow
        assert esc > 0  # the hot partition escaped
        want, _ = _ref_unique_join(bk, np.ones(nb, bool), pk, np.ones(np_, bool))
        assert (bidx == want).all()

    def test_escape_overflow_reports_need(self):
        """Escape rows past esc_cap raise join-overflow WITH the rung
        that clears it (the ladder retry's direct-jump hint)."""
        rng = np.random.default_rng(4)
        nb, np_ = 32, 512
        bk = np.arange(nb, dtype=np.int64)
        pk = np.full(np_, 7, np.int64)
        plan = (8, 16, 16, 64)  # esc_cap 64 << the ~512 escaping rows
        _bidx, _ov, overflow, need, _esc = _run_kernel(bk, None, pk, None, plan, "dense")
        assert overflow and need > 0
        # the hinted rung sizes the escape buffer past the skew
        from tidb_tpu.ops.radix_join import ESC_DIV

        assert need >= 512 * ESC_DIV // 2

    def test_unique_violation_flags_zero_need(self):
        bk = np.array([1, 2, 2, 3], np.int64)
        pk = np.array([2, 1, 9], np.int64)
        for strategy in ("dense", "search"):
            _bidx, _ov, overflow, need, _esc = _run_kernel(
                bk, None, pk, None, (2, 8, 8, 64), strategy)
            assert overflow and need == 0  # growth cannot help: drop hints

    @pytest.mark.parametrize("case", ["signed", "int32_min", "unsigned", "nulls"])
    def test_pallas_vs_general_key_matrix(self, case, monkeypatch):
        """Byte-equality of the Pallas probe (interpret mode), the dense
        XLA probe and the search probe over the key-type matrix — incl.
        INT32_MIN/INT64 boundary keys and unsigned keys living in the
        bit-flipped top half of the word domain."""
        monkeypatch.setenv("TIDB_TPU_PALLAS", "interpret")
        rng = np.random.default_rng(5)
        nb, np_ = 64, 1024
        ft = LL
        bnull = pnull = None
        if case == "signed":
            bk = (rng.permutation(nb).astype(np.int64) - 32) * (1 << 37)
            bk[0], bk[1] = np.iinfo(np.int64).min, np.iinfo(np.int64).max
        elif case == "int32_min":
            bk = np.arange(nb, dtype=np.int64) - 31
            bk[0] = -(1 << 31)  # INT32_MIN: the packed-kernel wrap class
            bk[1] = (1 << 31) - 1
        elif case == "unsigned":
            ft = new_longlong(unsigned=True)
            bk = rng.permutation(nb).astype(np.int64) * (1 << 40)
            bk[0] = -1  # u64 max bit pattern
        else:
            bk = np.arange(nb, dtype=np.int64)
            bnull = rng.random(nb) < 0.2
            pnull = rng.random(np_) < 0.2
        pk = bk[rng.integers(0, nb, np_)]
        pk[::5] = 999_999_999_999  # unmatched lane
        plan = (2, 128, 1024, 1024)  # pallas-eligible shape
        outs = {}
        for strategy in (None, "dense", "search"):
            outs[strategy] = _run_kernel(bk, bnull, pk, pnull, plan, strategy, ft=ft)
        from tidb_tpu.ops.radix_join import probe_strategy

        assert probe_strategy(*plan[:3]) == "pallas-interpret"
        base_idx, base_ov = outs[None][0], outs[None][1]
        for strategy in ("dense", "search"):
            assert (outs[strategy][0] == base_idx).all()
            assert (outs[strategy][1] == base_ov).all()
        want, _ = _ref_unique_join(
            bk, np.ones(nb, bool) if bnull is None else ~bnull,
            pk, np.ones(np_, bool) if pnull is None else ~pnull)
        assert (base_idx == want).all()

    def test_plan_gates(self):
        assert radix_plan(64, 64, 4096) is None  # build-heavy: monolithic
        plan = radix_plan(512, 1 << 16, 4096)
        assert plan is not None
        n_parts, part_cap, probe_cap, esc_cap = plan
        assert n_parts * part_cap >= 2 * 512  # slack holds the build side
        assert probe_cap * n_parts >= 2 * (1 << 16)


def _join_dag(join_type="inner", build_unique=True, agg=None, offsets=None):
    ls = TableScan(1, (ColumnInfo(1, NN), ColumnInfo(2, NN)))
    os_ = TableScan(2, (ColumnInfo(1, NN), ColumnInfo(2, NN)))
    join = Join(build=(os_,), probe_keys=(col(0, NN),), build_keys=(col(0, NN),),
                join_type=join_type, build_unique=build_unique)
    execs = (ls, join) if agg is None else (ls, join, agg)
    if offsets is None:
        offsets = (0, 1, 2, 3) if join_type in ("inner", "left_outer") else (0, 1)
    return DAGRequest(execs, output_offsets=offsets)


def _chunks(np_=512, nb=32, seed=0, dup_build=False):
    rng = np.random.default_rng(seed)
    pk = rng.integers(0, nb + 8, np_)
    prows = [[Datum.i64(int(k)), Datum.i64(i)] for i, k in enumerate(pk)]
    brows = [[Datum.i64(k % nb if dup_build else k), Datum.i64(k * 3)]
             for k in range(nb if not dup_build else nb * 4)]
    return Chunk.from_rows([NN, NN], prows), Chunk.from_rows([NN, NN], brows)


def _canon(rows):
    return sorted(tuple(datum_group_key(d) for d in r) for r in rows)


class TestRadixThroughDAG:
    @pytest.mark.parametrize("jt", ["inner", "left_outer", "semi", "anti"])
    def test_join_type_parity(self, jt):
        probe, build = _chunks()
        dag = _join_dag(jt)
        batches = [to_device_batch(c, capacity=_pow2(c.num_rows())) for c in (probe, build)]
        prog = build_program(dag, tuple(b.capacity for b in batches), group_capacity=64)
        packed, valid, _n, ovfs, _ex = prog.fn(*batches)
        assert prog.radix_info, "eligible join must ride the radix kernel"
        assert not any(bool(x) for x in ovfs[:3])
        got = _canon(decode_outputs(packed, valid, prog.out_fts).rows())
        want = _canon(run_dag_reference(dag, [probe, build]))
        assert got == want

    def test_build_heavy_stays_monolithic(self):
        probe, build = _chunks(np_=64, nb=64)
        dag = _join_dag()
        batches = [to_device_batch(c, capacity=64) for c in (probe, build)]
        prog = build_program(dag, (64, 64), group_capacity=64)
        packed, valid, _n, ovfs, _ex = prog.fn(*batches)
        assert not prog.radix_info  # ratio gate: monolithic kernel
        assert not any(bool(x) for x in ovfs[:3])

    def test_rung_reuse_zero_recompiles(self):
        """The pinned acceptance test: with the ladder warm, an overflow
        on rung 1 re-dispatches a CACHED rung — ProgramCache stats show
        zero new compiles across the retry (the recompile-per-retry class
        that gave q3 its 131s first call)."""
        rng = np.random.default_rng(9)
        probe, build = _chunks(np_=512, nb=32, seed=9)
        # group by the probe payload: ~512 groups >> rung 1 (64)
        agg = Aggregation(group_by=(col(1, NN),),
                          aggs=(AggDesc("count", ()),))
        dag = _join_dag(agg=agg, offsets=(0, 1))
        batches = [to_device_batch(c, capacity=_pow2(c.num_rows())) for c in (probe, build)]
        caps = tuple(b.capacity for b in batches)
        cache = ProgramCache()
        jc = rung_for(max(caps))
        for rung in rungs_up_to(1024):  # precompile the ladder
            prog = cache.get(dag, caps, group_capacity=rung, join_capacity=jc)
            prog.fn(*batches)
        s0 = cache.stats()
        chunk, _counts, _info = drive_program_info(cache, dag, batches, group_capacity=64)
        s1 = cache.stats()
        assert s1["compiles"] == s0["compiles"], "retry must hit a cached rung"
        assert s1["hits"] >= s0["hits"] + 2  # first rung + the retry rung
        want = _canon(run_dag_reference(dag, [probe, build]))
        assert _canon(chunk.rows()) == want

    def test_overflow_on_rung_one_degrades_and_reports(self):
        """Never-starve: a cold cache and a rung-1 overflow still return
        a correct result — the need hint jumps the retry straight to the
        covering rung (ONE extra compile, not a blind 4x walk)."""
        probe, build = _chunks(np_=512, nb=32, seed=11)
        agg = Aggregation(group_by=(col(1, NN),), aggs=(AggDesc("count", ()),))
        dag = _join_dag(agg=agg, offsets=(0, 1))
        batches = [to_device_batch(c, capacity=_pow2(c.num_rows())) for c in (probe, build)]
        cache = ProgramCache()
        chunk, _counts, _info = drive_program_info(cache, dag, batches, group_capacity=64)
        stats = cache.stats()
        assert stats["compiles"] == 2  # rung 1 + the hinted rung, nothing between
        assert _canon(chunk.rows()) == _canon(run_dag_reference(dag, [probe, build]))

    def test_join_need_hint_jumps_to_exact_rung(self):
        """General (non-unique) expansion join: out-capacity overflow
        carries the exact fan-out, so the retry lands in one step."""
        probe, build = _chunks(np_=512, nb=32, dup_build=True, seed=13)
        dag = _join_dag(build_unique=False)
        batches = [to_device_batch(c, capacity=_pow2(c.num_rows())) for c in (probe, build)]
        cache = ProgramCache()
        chunk, _counts, _info = drive_program_info(
            cache, dag, batches, group_capacity=64, join_capacity=64)
        assert cache.stats()["compiles"] == 2  # 64 -> rung_for(true fan-out)
        assert _canon(chunk.rows()) == _canon(run_dag_reference(dag, [probe, build]))


def _pow2(n: int) -> int:
    c = 1
    while c < max(n, 1):
        c *= 2
    return c


class TestMeshAndSurfaces:
    def test_mesh_tier_join_matches_pool(self):
        """A radix-eligible join + Partial1 agg dispatched through the
        MESH tier (on-device psum of the per-region partials) returns the
        same merged state as the pool/batch tier."""
        from tidb_tpu.codec import tablecodec
        from tidb_tpu.distsql import KVRequest, full_table_ranges, select
        from tidb_tpu.store import TPUStore

        rng = np.random.default_rng(17)
        store = TPUStore()
        nb, np_ = 8, 400
        for h in range(np_):
            store.put_row(1, h, [1, 2], [Datum.i64(int(rng.integers(0, nb + 2))), Datum.i64(h)], ts=10)
        for i in range(1, 4):
            store.cluster.split(tablecodec.encode_row_key(1, i * 100))
        build = Chunk.from_rows([NN, NN], [[Datum.i64(k), Datum.i64(k * 7)] for k in range(nb)])
        agg = Aggregation(group_by=(), aggs=(
            AggDesc("sum", (col(1, NN),)), AggDesc("count", ())), partial=True)
        dag = _join_dag(agg=agg, offsets=(0, 1))
        res_pool = select(store, KVRequest(dag, full_table_ranges(1), start_ts=100,
                                           aux_chunks=[build], mesh=False))
        res_mesh = select(store, KVRequest(dag, full_table_ranges(1), start_ts=100,
                                           aux_chunks=[build], mesh=True))
        pool = Chunk.concat([c for c in res_pool.chunks if c is not None])
        mesh = Chunk.concat([c for c in res_mesh.chunks if c is not None])
        # pool answers one partial per region, mesh ONE merged state: the
        # folded totals must agree
        def fold(ch):
            s = c_ = 0
            for r in ch.rows():
                s += int(str(r[0].val))  # sum state decodes as decimal
                c_ += int(r[1].val)
            return s, c_

        assert fold(pool) == fold(mesh)

    def test_explain_analyze_and_trace_attribution(self):
        """EXPLAIN ANALYZE grows a `join_radix` row (partitions, rung,
        escapes) and TRACE carries the exec.join_radix span."""
        from tidb_tpu.sql.session import Session

        s = Session()
        s.execute("CREATE TABLE o (id BIGINT PRIMARY KEY, w BIGINT)")
        s.execute("CREATE TABLE l (id BIGINT PRIMARY KEY, ok BIGINT NOT NULL, v BIGINT NOT NULL)")
        s.execute("INSERT INTO o VALUES " + ",".join(f"({k},{k * 3})" for k in range(32)))
        s.execute("INSERT INTO l VALUES " + ",".join(
            f"({i},{i % 40},{i % 97})" for i in range(512)))
        sql = "SELECT sum(l.v), count(*) FROM l JOIN o ON l.ok = o.id"
        assert s.execute(sql).rows  # warm + correctness smoke
        rows = s.execute("EXPLAIN ANALYZE " + sql).rows
        radix_rows = [r for r in rows if str(r[0].val) == "join_radix"]
        assert radix_rows, [str(r[0].val) for r in rows]
        # partitions reports what EXECUTED: 1 on CPU-class backends (the
        # search strategy probes one un-partitioned sorted build table)
        assert int(radix_rows[0][1].val) >= 1
        assert "rung=" in str(radix_rows[0][5].val)
        trace = s.execute("TRACE FORMAT='row' " + sql).rows
        names = "\n".join(str(r[0].val) for r in trace)
        assert "exec.join_radix" in names
