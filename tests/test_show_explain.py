"""SHOW CREATE TABLE/COLUMNS/INDEX/STATUS + EXPLAIN ANALYZE
(ref: pkg/executor/show.go, explain.go with exec summaries)."""

import pytest

from tidb_tpu.sql.session import Session


@pytest.fixture()
def sess():
    s = Session()
    s.execute("CREATE TABLE t (id INT PRIMARY KEY, v INT, s VARCHAR(8))")
    s.execute("INSERT INTO t VALUES " + ",".join(f"({i},{i % 7},'x{i % 3}')" for i in range(1, 101)))
    return s


def test_show_create_table_reimports(sess):
    ddl = sess.execute("SHOW CREATE TABLE t").values()[0][1]
    s2 = Session()
    s2.execute(ddl.rstrip().rstrip(";"))
    assert [c.name for c in s2.catalog.table("t").columns] == ["id", "v", "s"]


def test_show_columns(sess):
    rows = sess.execute("SHOW COLUMNS FROM t").values()
    # declared type spelling is preserved (TiDB prints int, not bigint)
    assert rows[0][:4] == ["id", "int", "NO", "PRI"]
    assert rows[2][0] == "s" and rows[2][1] == "varchar(8)"


def test_show_index(sess):
    sess.execute("CREATE UNIQUE INDEX uv ON t (id, v)")
    rows = sess.execute("SHOW INDEX FROM t").values()
    assert rows == [["t", 0, "uv", 1, "id"], ["t", 0, "uv", 2, "v"]]


def test_show_status_metrics(sess):
    rows = sess.execute("SHOW STATUS").values()
    names = [r[0] for r in rows]
    assert any("cop_requests" in n for n in names)


def test_explain_analyze_row_counts(sess):
    rows = sess.execute("EXPLAIN ANALYZE SELECT count(*) FROM t WHERE v < 3").values()
    by_exec = {r[0]: r for r in rows}
    assert by_exec["push[Selection]"][1] == 44  # rows surviving the filter
    assert by_exec["result"][1] == 1
    scan_row = rows[0]
    assert scan_row[0].startswith("push[") and scan_row[2] >= 1  # tasks


def test_explain_analyze_multi_region(sess):
    from tidb_tpu.codec import tablecodec

    tid = sess.catalog.table("t").table_id
    for h in (30, 60):
        sess.store.cluster.split(tablecodec.encode_row_key(tid, h))
    rows = sess.execute("EXPLAIN ANALYZE SELECT count(*) FROM t").values()
    by_exec = {r[0]: r for r in rows}
    assert by_exec["push[TableScan]"][1] == 100
    assert by_exec["push[TableScan]"][2] == 3  # one summary per region task


def test_explain_analyze_attribution_columns(sess):
    """The device-time attribution columns (ref: EXPLAIN ANALYZE execution
    info: cop task compile time + coprocessor-cache hit ratio + bytes)."""
    res = sess.execute("EXPLAIN ANALYZE SELECT count(*) FROM t WHERE v < 3")
    assert res.columns == ["executor", "rows", "tasks", "time", "compile", "cache", "bytes"]
    by_exec = {r[0]: r for r in res.values()}
    scan = by_exec["push[TableScan]"]
    n_tasks = scan[2]
    hits, total = scan[5].split("/")
    assert int(total) == n_tasks and 0 <= int(hits) <= n_tasks
    assert scan[4].endswith("ms")  # compile time, shared per fused program
    assert scan[6] > 0  # decoded region bytes ride the scan row
    # the SAME query again: every per-task program now comes from the cache
    res2 = sess.execute("EXPLAIN ANALYZE SELECT count(*) FROM t WHERE v < 3")
    scan2 = {r[0]: r for r in res2.values()}["push[TableScan]"]
    hits2, total2 = scan2[5].split("/")
    assert hits2 == total2  # all cache hits, no recompiles
    assert scan2[4] == "0.00ms"
