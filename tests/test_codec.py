import struct

import pytest

from tidb_tpu.types import Datum, DatumKind, MyDecimal, MyTime, new_decimal, new_double, new_longlong, new_varchar, new_datetime
from tidb_tpu.codec import number, datum_codec, tablecodec
from tidb_tpu.codec.decimal_bin import decode_decimal, encode_decimal
from tidb_tpu.codec.rowcodec import RowEncoder, decode_row_to_datum_map


class TestNumber:
    def test_int_cmp_order(self):
        vals = [-(2**63), -5, -1, 0, 1, 7, 2**63 - 1]
        encs = [number.encode_int_cmp(v) for v in vals]
        assert encs == sorted(encs)
        for v, e in zip(vals, encs):
            assert number.decode_int_cmp(e)[0] == v

    def test_float_cmp_order(self):
        vals = [float("-inf"), -1e300, -1.5, -0.0, 0.0, 2.25, 1e300, float("inf")]
        encs = [number.encode_float_cmp(v) for v in vals]
        assert encs == sorted(encs)
        assert number.decode_float_cmp(number.encode_float_cmp(-1.5))[0] == -1.5

    def test_bytes_cmp(self):
        vals = [b"", b"a", b"a\x00", b"ab", b"abcdefgh", b"abcdefghi", b"b"]
        encs = [number.encode_bytes_cmp(v) for v in vals]
        assert encs == sorted(encs)
        for v, e in zip(vals, encs):
            assert number.decode_bytes_cmp(e)[0] == v

    def test_varint_roundtrip(self):
        for v in [0, 1, -1, 127, -128, 300, -300, 2**62, -(2**62)]:
            got, _ = number.decode_varint(number.encode_varint(v))
            assert got == v, v

    def test_int_value_widths(self):
        assert len(number.encode_int_value(1)) == 1
        assert len(number.encode_int_value(300)) == 2
        assert len(number.encode_int_value(70000)) == 4
        assert len(number.encode_int_value(2**40)) == 8
        for v in [0, -1, 127, -129, 2**20, -(2**35)]:
            assert number.decode_int_value(number.encode_int_value(v)) == v


class TestDecimalBin:
    @pytest.mark.parametrize("s,prec,frac", [
        ("0", 1, 0),
        ("1234567890.1234", 14, 4),
        ("-1234567890.1234", 14, 4),
        ("0.00012345000098765", 22, 20),
        ("12345", 5, 0),
        ("-99.99", 4, 2),
        ("1234567891234567890.12", 21, 2),
    ])
    def test_roundtrip(self, s, prec, frac):
        d = MyDecimal(s)
        enc = encode_decimal(d, prec, frac)
        got, pos = decode_decimal(enc)
        assert pos == len(enc)
        assert got == MyDecimal(s), f"{got} != {s}"

    def test_order_same_precision(self):
        vals = ["-100.5", "-2.25", "0", "0.01", "3.5", "99.99"]
        encs = [encode_decimal(MyDecimal(v), 6, 2)[2:] for v in vals]
        assert encs == sorted(encs)


class TestDatumCodec:
    def test_roundtrip_kinds(self):
        ds = [
            Datum.i64(-42),
            Datum.u64(2**63 + 5),
            Datum.f64(2.5),
            Datum.string("hello"),
            Datum.NULL,
            Datum.dec("12.345"),
            Datum.time(MyTime.parse("1996-04-01 12:00:01")),
        ]
        fts = [new_longlong(), new_longlong(True), new_double(), new_varchar(8), new_longlong(), new_decimal(7, 3), new_datetime()]
        enc = datum_codec.encode_datums(ds)
        got = datum_codec.decode_datums(enc, fts)
        assert got[0].val == -42
        assert got[1].val == 2**63 + 5
        assert got[2].val == 2.5
        assert got[3].val == "hello"
        assert got[4].is_null()
        assert got[5].val == MyDecimal("12.345")
        assert isinstance(got[6].val, MyTime) and str(got[6].val) == "1996-04-01 12:00:01"

    def test_key_order_mixed(self):
        rows = [[Datum.i64(1), Datum.string("a")], [Datum.i64(1), Datum.string("b")], [Datum.i64(2), Datum.string("a")]]
        encs = [datum_codec.encode_datums(r) for r in rows]
        assert encs == sorted(encs)


class TestRowCodec:
    def test_roundtrip_small(self):
        fts = {1: new_longlong(), 2: new_double(), 3: new_varchar(10), 4: new_decimal(10, 2), 5: new_longlong(True)}
        enc = RowEncoder().encode(
            [1, 2, 3, 4, 5],
            [Datum.i64(-7), Datum.NULL, Datum.string("xyz"), Datum.dec("55.66"), Datum.u64(2**40)],
        )
        got = decode_row_to_datum_map(enc, fts)
        assert got[1].val == -7
        assert got[2].is_null()
        assert got[3].val == "xyz"
        assert got[4].val == MyDecimal("55.66")
        assert got[5].val == 2**40

    def test_large_row(self):
        fts = {1000: new_longlong(), 2: new_varchar(5)}
        enc = RowEncoder().encode([1000, 2], [Datum.i64(9), Datum.string("ab")])
        assert enc[1] & 1  # large flag
        got = decode_row_to_datum_map(enc, fts)
        assert got[1000].val == 9 and got[2].val == "ab"

    def test_absent_column_is_null(self):
        fts = {1: new_longlong(), 9: new_longlong()}
        enc = RowEncoder().encode([1], [Datum.i64(5)])
        got = decode_row_to_datum_map(enc, fts)
        assert got[9].is_null()


class TestTableCodec:
    def test_row_key_roundtrip_and_order(self):
        k1 = tablecodec.encode_row_key(45, -10)
        k2 = tablecodec.encode_row_key(45, 3)
        k3 = tablecodec.encode_row_key(46, -99)
        assert k1 < k2 < k3
        assert tablecodec.decode_row_key(k2) == (45, 3)

    def test_index_key(self):
        k = tablecodec.encode_index_key(7, 1, [Datum.i64(5), Datum.string("x")])
        assert k.startswith(b"t")
        assert b"_i" in k
