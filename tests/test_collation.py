"""Full-Unicode collations (VERDICT r4 next #3; ref:
pkg/util/collate/collate.go:335-348 general_ci/unicode_ci registration):
weight-based compare/group/sort on the oracle path, é-class regression
tests, and the device guard that routes non-ASCII CI data to the oracle
instead of comparing wrongly."""

from tidb_tpu.sql import Session


def _s(collate):
    s = Session()
    s.execute(f"create table t (id bigint primary key, v varchar(20) collate {collate})")
    return s


def test_general_ci_case_insensitive_unicode():
    s = _s("utf8mb4_general_ci")
    s.execute("insert into t values (1, 'Müller'), (2, 'MÜLLER'), (3, 'muller')")
    # ü and Ü equal under general_ci; u differs (no accent folding)
    assert s.execute("select count(*) from t where v = 'müller'").values() == [[2]]
    got = s.execute("select count(*), min(id) from t group by v order by 2").values()
    assert got == [[2, 1], [1, 3]]


def test_unicode_ci_accent_insensitive():
    s = _s("utf8mb4_unicode_ci")
    s.execute("insert into t values (1, 'café'), (2, 'CAFE'), (3, 'cafe'), (4, 'caffè')")
    # unicode_ci folds accents AND case: café == CAFE == cafe
    assert s.execute("select count(*) from t where v = 'cafe'").values() == [[3]]
    got = s.execute("select count(*) from t group by v order by 1 desc").values()
    assert got == [[3], [1]]


def test_general_ci_ascii_still_on_device():
    """Pure-ASCII CI data keeps the device path (no behavior change)."""
    s = _s("utf8mb4_general_ci")
    s.execute("insert into t values " + ",".join(
        f"({i}, '{'AbCd'[i % 4]}x')" for i in range(64)))
    assert s.execute("select count(*) from t where v = 'AX'").values() == [[16]]
    assert s.execute("select count(distinct v) from t").values() == [[4]]


def test_bin_collation_unaffected():
    s = _s("utf8mb4_bin")
    s.execute("insert into t values (1, 'a'), (2, 'A'), (3, 'é')")
    assert s.execute("select count(*) from t where v = 'a'").values() == [[1]]
    assert s.execute("select count(*) from t where v = 'é'").values() == [[1]]


def test_german_sharp_s_unicode_ci():
    s = _s("utf8mb4_unicode_ci")
    s.execute("insert into t values (1, 'straße'), (2, 'STRASSE')")
    # casefold expands ß -> ss (the UCA expansion unicode_ci implements)
    assert s.execute("select count(*) from t where v = 'strasse'").values() == [[2]]


def test_order_by_ci_groups_equal_keys():
    s = _s("utf8mb4_unicode_ci")
    s.execute("insert into t values (1, 'b'), (2, 'É'), (3, 'a'), (4, 'e')")
    got = [r[0] for r in s.execute("select v from t order by v, id").values()]
    # weight order: a < b < (e == É, tie broken by id: É id=2 before e id=4)
    assert got == ["a", "b", "É", "e"]
