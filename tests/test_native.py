"""Native C++ rowcodec decoder (tidb_tpu/native) vs the Python decoders —
bit-parity on random rows across all supported type classes, plus the
store-integration fallback contract (ref: the reference's native store-side
decode, rowcodec ChunkDecoder at cophandler/cop_handler.go:424-467)."""

import random

import pytest

from tidb_tpu import native
from tidb_tpu.chunk import Chunk
from tidb_tpu.codec.rowcodec import RowEncoder
from tidb_tpu.exec.dag import ColumnInfo
from tidb_tpu.types import (
    Datum, MyDecimal, MyTime, new_datetime, new_decimal, new_double,
    new_longlong, new_varchar,
)

pytestmark = pytest.mark.skipif(not native.available(), reason="g++ toolchain unavailable")


def _random_rows(n, seed=7):
    rng = random.Random(seed)
    fts = [new_longlong(), new_longlong(unsigned=True), new_double(),
           new_decimal(20, 4), new_varchar(16), new_datetime()]
    enc = RowEncoder()
    values, handles, expect = [], [], []
    for h in range(n):
        row = [
            Datum.NULL if rng.random() < 0.15 else Datum.i64(rng.randint(-2**62, 2**62)),
            Datum.NULL if rng.random() < 0.15 else Datum.u64(rng.randint(0, 2**63 + 5)),
            Datum.NULL if rng.random() < 0.15 else Datum.f64(rng.uniform(-1e10, 1e10)),
            Datum.NULL if rng.random() < 0.15 else Datum.dec(MyDecimal(f"{rng.uniform(-1e6, 1e6):.4f}")),
            Datum.NULL if rng.random() < 0.15 else Datum.string(
                "".join(rng.choice("abcdef") for _ in range(rng.randint(0, 12)))),
            Datum.NULL if rng.random() < 0.15 else Datum.time(
                MyTime.from_ymd(2024, rng.randint(1, 12), rng.randint(1, 28))),
        ]
        values.append(enc.encode([1, 2, 3, 4, 5, 6], row))
        handles.append(h)
        expect.append(row)
    return fts, values, handles, expect


def test_native_parity_random_rows():
    fts, values, handles, expect = _random_rows(400)
    cols_meta = [ColumnInfo(i + 1, ft) for i, ft in enumerate(fts)] + [
        ColumnInfo(-1, new_longlong(notnull=True))
    ]
    cols = native.decode_rows_columnar(values, handles, cols_meta)
    assert cols is not None
    ch = Chunk(cols)
    for r in range(len(values)):
        got = ch.row(r)
        assert int(got[-1].val) == r  # handle column
        for i, ft in enumerate(fts):
            e, g = expect[r][i], got[i]
            assert e.is_null() == g.is_null(), (r, i)
            if e.is_null():
                continue
            if ft.is_decimal():
                assert str(e.val.round(4)) == str(g.val), (r, i)
            elif ft.is_time():
                assert e.val.packed == g.val.packed
            elif isinstance(e.val, float):
                assert abs(e.val - g.val) <= 1e-9 * max(1.0, abs(e.val))
            else:
                assert e.val == g.val, (r, i)


def test_native_subset_of_columns():
    fts, values, handles, _ = _random_rows(50)
    # request only columns 2 and 5 (out of order id lookup)
    cols_meta = [ColumnInfo(5, fts[4]), ColumnInfo(2, fts[1])]
    cols = native.decode_rows_columnar(values, handles, cols_meta)
    assert cols is not None and len(cols) == 2
    assert Chunk(cols).num_rows() == 50


def test_native_malformed_falls_back():
    fts, values, handles, _ = _random_rows(10)
    values[3] = b"\x00garbage"  # wrong version byte
    cols_meta = [ColumnInfo(1, fts[0])]
    assert native.decode_rows_columnar(values, handles, cols_meta) is None


def test_native_unsupported_type_declines():
    from tidb_tpu.types import FieldType, TypeCode

    f32 = FieldType(TypeCode.Float)
    assert native._col_class(f32) is None


def test_store_uses_native_path():
    from tidb_tpu.sql.session import Session
    from tidb_tpu.util import metrics

    before = metrics.NATIVE_DECODES.value
    s = Session()
    s.execute("CREATE TABLE nt (id INT PRIMARY KEY, a INT, s VARCHAR(8))")
    s.execute("INSERT INTO nt VALUES (1, 10, 'x'), (2, NULL, NULL), (3, 30, 'zzz')")
    got = s.execute("SELECT id, a, s FROM nt ORDER BY id").values()
    assert got == [[1, 10, "x"], [2, None, None], [3, 30, "zzz"]]
    assert metrics.NATIVE_DECODES.value > before


def test_native_empty_batch():
    cols_meta = [ColumnInfo(1, new_longlong())]
    cols = native.decode_rows_columnar([], [], cols_meta)
    assert cols is not None
    assert Chunk(cols).num_rows() == 0
