"""Expression breadth pass (VERDICT next #10): string functions, date
arithmetic, general_ci collation, stddev/var and group_concat — device vs
oracle parity plus end-to-end SQL."""

import math

import numpy as np
import pytest

from tidb_tpu.chunk import Chunk
from tidb_tpu.exec import Aggregation, ColumnInfo, DAGRequest, Projection, Selection, TableScan, run_dag_on_chunk, run_dag_reference
from tidb_tpu.exec.executor import datum_group_key
from tidb_tpu.expr import AggDesc, col, func, lit
from tidb_tpu.sql import Session
from tidb_tpu.types import (
    Collation,
    Datum,
    FieldType,
    MyDecimal,
    MyTime,
    TypeCode,
    new_datetime,
    new_decimal,
    new_longlong,
    new_varchar,
)

BOOL = new_longlong(notnull=True)
VC = new_varchar(16)


def canon(rows, fts=None):
    return sorted(tuple(datum_group_key(d) for d in r) for r in rows)


def str_chunk(vals):
    fts = [new_longlong(), VC]
    rows = [[Datum.i64(i), Datum.NULL if v is None else Datum.string(v)] for i, v in enumerate(vals)]
    return Chunk.from_rows(fts, rows), fts


def parity(dag, ch, sort=True):
    dev = run_dag_on_chunk(dag, ch)
    ref = run_dag_reference(dag, ch)
    if sort:
        assert canon(dev.rows()) == canon(ref)
    else:
        assert [tuple(datum_group_key(d) for d in r) for r in dev.rows()] == [
            tuple(datum_group_key(d) for d in r) for r in ref
        ]
    return dev


class TestStringFuncs:
    def test_upper_lower_trim(self):
        ch, fts = str_chunk(["Hello", "  padded  ", "MIXed cASE", "", None, "  x"])
        s = TableScan(1, (ColumnInfo(1, fts[0]), ColumnInfo(2, fts[1])))
        C1 = col(1, fts[1])
        proj = Projection((
            func("upper", VC, C1),
            func("lower", VC, C1),
            func("trim", VC, C1),
            func("ltrim", VC, C1),
            func("rtrim", VC, C1),
        ))
        parity(DAGRequest((s, proj), output_offsets=(0, 1, 2, 3, 4)), ch, sort=False)

    def test_concat_substr(self):
        ch, fts = str_chunk(["ab", "xyz", "", None, "long-ish value"])
        s = TableScan(1, (ColumnInfo(1, fts[0]), ColumnInfo(2, fts[1])))
        C0, C1 = col(0, fts[0]), col(1, fts[1])
        proj = Projection((
            func("concat", new_varchar(40), C1, lit("-", new_varchar(1)), C1),
            func("substr", VC, C1, lit(2, new_longlong())),
            func("substr", VC, C1, lit(2, new_longlong()), lit(3, new_longlong())),
            func("substr", VC, C1, lit(-3, new_longlong())),
        ))
        parity(DAGRequest((s, proj), output_offsets=(0, 1, 2, 3)), ch, sort=False)

    def test_replace_falls_back_to_oracle(self):
        """replace() is host-only: the root path must degrade via the
        oracle fallback, not crash."""
        from tidb_tpu.exec import run_dag_on_chunks

        ch, fts = str_chunk(["aXbXc", "nope", None])
        s = TableScan(1, (ColumnInfo(1, fts[0]), ColumnInfo(2, fts[1])))
        proj = Projection((func("replace", VC, col(1, fts[1]), lit("X", VC), lit("-", VC)),))
        dag = DAGRequest((s, proj), output_offsets=(0,))
        out = run_dag_on_chunks(dag, [ch])
        assert [r[0].val for r in out.rows()] == ["a-b-c", "nope", None]


class TestCollationCI:
    def ci_ft(self):
        return FieldType(TypeCode.Varchar, flen=16, collate=Collation.Utf8MB4GeneralCI)

    def test_ci_compare_and_group(self):
        ci = self.ci_ft()
        fts = [new_longlong(), ci]
        rows = [[Datum.i64(i), Datum.string(v)] for i, v in enumerate(["Apple", "APPLE", "apple", "Banana", "banana", "cherry"])]
        ch = Chunk.from_rows(fts, rows)
        s = TableScan(1, (ColumnInfo(1, fts[0]), ColumnInfo(2, fts[1])))
        # eq compare is case-insensitive
        sel = Selection((func("eq", BOOL, col(1, ci), lit("apple", new_varchar(8))),))
        dev = parity(DAGRequest((s, sel), output_offsets=(0,)), ch)
        assert dev.num_rows() == 3
        # GROUP BY folds case into one group
        agg = Aggregation(group_by=(col(1, ci),), aggs=(AggDesc("count", ()),))
        dev = run_dag_on_chunk(DAGRequest((s, agg), output_offsets=(0,)), ch)
        ref = run_dag_reference(DAGRequest((s, agg), output_offsets=(0,)), ch)
        assert sorted(r[0].val for r in dev.rows()) == sorted(r[0].val for r in ref) == [1, 2, 3]

    def test_binary_collation_stays_sensitive(self):
        fts = [new_longlong(), new_varchar(8)]  # default binary collate
        rows = [[Datum.i64(i), Datum.string(v)] for i, v in enumerate(["a", "A"])]
        ch = Chunk.from_rows(fts, rows)
        s = TableScan(1, (ColumnInfo(1, fts[0]), ColumnInfo(2, fts[1])))
        sel = Selection((func("eq", BOOL, col(1, fts[1]), lit("a", new_varchar(1))),))
        dev = parity(DAGRequest((s, sel), output_offsets=(0,)), ch)
        assert dev.num_rows() == 1


class TestDateArith:
    def date_chunk(self):
        fts = [new_datetime()]
        dates = [(2020, 1, 31), (2019, 12, 31), (2020, 2, 29), (1999, 6, 15), (2024, 3, 1)]
        rows = [[Datum.time(MyTime.from_ymd(y, m, d))] for y, m, d in dates]
        return Chunk.from_rows(fts, rows), fts

    @pytest.mark.parametrize("unit,n", [("day", 40), ("day", -60), ("month", 1), ("month", -13), ("year", 1), ("week", 3), ("hour", 30), ("quarter", 5)])
    def test_date_add_units(self, unit, n):
        ch, fts = self.date_chunk()
        s = TableScan(1, (ColumnInfo(1, fts[0]),))
        proj = Projection((func("date_add", new_datetime(), col(0, fts[0]), lit(n, new_longlong()), lit(unit, new_varchar(8))),))
        parity(DAGRequest((s, proj), output_offsets=(0,)), ch, sort=False)

    def test_month_end_clamp(self):
        """'2020-01-31' + 1 month = '2020-02-29' (leap clamp)."""
        ch, fts = self.date_chunk()
        s = TableScan(1, (ColumnInfo(1, fts[0]),))
        proj = Projection((func("date_add", new_datetime(), col(0, fts[0]), lit(1, new_longlong()), lit("month", new_varchar(8))),))
        dev = run_dag_on_chunk(DAGRequest((s, proj), output_offsets=(0,)), ch)
        assert str(dev.row(0)[0].val).startswith("2020-02-29")

    def test_datediff(self):
        ch, fts = self.date_chunk()
        s = TableScan(1, (ColumnInfo(1, fts[0]),))
        proj = Projection((func("datediff", new_longlong(), col(0, fts[0]), lit("2020-01-01", new_datetime())),))
        dev = parity(DAGRequest((s, proj), output_offsets=(0,)), ch, sort=False)
        assert dev.row(0)[0].val == 30  # 2020-01-31 vs 2020-01-01


class TestMomentAggs:
    def test_stddev_var_parity(self):
        fts = [new_longlong(), new_decimal(8, 2)]
        rng = np.random.default_rng(4)
        rows = [[Datum.i64(int(rng.integers(0, 4))), Datum.dec(MyDecimal(f"{int(rng.integers(-999, 999))/100:.2f}"))] for _ in range(120)]
        rows.append([Datum.i64(9), Datum.dec(MyDecimal("5.00"))])  # n=1 group
        ch = Chunk.from_rows(fts, rows)
        s = TableScan(1, (ColumnInfo(1, fts[0]), ColumnInfo(2, fts[1])))
        agg = Aggregation(
            group_by=(col(0, fts[0]),),
            aggs=(
                AggDesc("var_pop", (col(1, fts[1]),)),
                AggDesc("stddev_pop", (col(1, fts[1]),)),
                AggDesc("var_samp", (col(1, fts[1]),)),
                AggDesc("stddev_samp", (col(1, fts[1]),)),
            ),
        )
        dag = DAGRequest((s, agg), output_offsets=(0, 1, 2, 3, 4))
        dev = run_dag_on_chunk(dag, ch)
        ref = run_dag_reference(dag, ch)

        def fl(rows_):
            out = []
            for r in rows_:
                out.append(tuple(None if d.is_null() else round(float(d.val), 9) if isinstance(d.val, float) else d.val for d in r))
            return sorted(out, key=str)

        assert fl(dev.rows()) == fl(ref)

    def test_sql_stddev_group_concat(self):
        s = Session()
        s.execute("CREATE TABLE m (id BIGINT PRIMARY KEY, g INT, v DOUBLE, w VARCHAR(8))")
        s.execute("INSERT INTO m VALUES (1,1,2.0,'a'), (2,1,4.0,'b'), (3,1,6.0,'c'), (4,2,5.0,'z')")
        r = s.execute("SELECT g, stddev(v), var_pop(v), group_concat(w SEPARATOR '|') FROM m GROUP BY g ORDER BY g")
        row1 = r.rows[0]
        assert row1[0].val == 1
        assert abs(row1[1].val - math.sqrt(8.0 / 3)) < 1e-9
        assert abs(row1[2].val - 8.0 / 3) < 1e-9
        assert row1[3].val == "a|b|c"
        assert r.rows[1][3].val == "z"
        # var_samp of a single row is NULL
        assert s.execute("SELECT var_samp(v) FROM m WHERE g = 2").scalar() is None

    def test_moment_aggs_split_over_regions(self):
        """stddev states are additive: Partial1 per region + Final merge."""
        from tidb_tpu.codec import tablecodec

        s = Session()
        s.execute("CREATE TABLE mm (id BIGINT PRIMARY KEY, v DOUBLE)")
        vals = ", ".join(f"({i}, {i * 0.5})" for i in range(200))
        s.execute(f"INSERT INTO mm (id, v) VALUES {vals}")
        tid = s.catalog.table("mm").table_id
        s.store.cluster.split(tablecodec.encode_row_key(tid, 100))
        got = s.execute("SELECT var_pop(v), stddev_samp(v) FROM mm").rows[0]
        data = [i * 0.5 for i in range(200)]
        mean = sum(data) / len(data)
        var_pop = sum((x - mean) ** 2 for x in data) / len(data)
        var_samp = sum((x - mean) ** 2 for x in data) / (len(data) - 1)
        assert abs(got[0].val - var_pop) < 1e-6
        assert abs(got[1].val - math.sqrt(var_samp)) < 1e-6


class TestSQLBreadth:
    def test_sql_string_and_date(self):
        s = Session()
        s.execute("CREATE TABLE e (id BIGINT PRIMARY KEY, name VARCHAR(20), hired DATETIME)")
        s.execute("INSERT INTO e VALUES (1, '  Ada  ', '2020-01-31 00:00:00'), (2, 'bob', '2019-06-15 00:00:00')")
        r = s.execute("SELECT upper(trim(name)), concat(name, '!') FROM e ORDER BY id")
        assert r.values()[0][0] == "ADA"
        assert r.values()[1][1] == "bob!"
        r = s.execute("SELECT id FROM e WHERE hired + INTERVAL 1 MONTH > '2020-02-28' ORDER BY id")
        assert [x for x, in r.values()] == [1]
        r = s.execute("SELECT datediff('2020-03-01', hired) FROM e WHERE id = 1")
        assert r.scalar() == 30
        r = s.execute("SELECT replace(name, 'o', '0') FROM e WHERE id = 2")
        assert r.scalar() == "b0b"


class TestReviewRegressions2:
    def test_update_unique_failure_keeps_index(self):
        """A failed UPDATE must not tombstone index entries (no corruption)."""
        from tidb_tpu.sql import SQLError

        s = Session()
        s.execute("CREATE TABLE u (id BIGINT PRIMARY KEY, a INT)")
        s.execute("INSERT INTO u VALUES (1, 5), (2, 6)")
        s.execute("CREATE UNIQUE INDEX ua ON u (a)")
        with pytest.raises(SQLError):
            s.execute("UPDATE u SET a = 6 WHERE id = 1")
        assert s.execute("SELECT count(*) FROM u WHERE a = 5").scalar() == 1

    def test_in_duplicates_no_double_scan(self):
        s = Session()
        s.execute("CREATE TABLE t2 (id BIGINT PRIMARY KEY)")
        s.execute("INSERT INTO t2 VALUES (4), (5), (6)")
        assert s.execute("SELECT count(*) FROM t2 WHERE id IN (5, 5)").scalar() == 1
        r = s.execute("SELECT id FROM t2 WHERE id IN (5, 5, 4) OR id = 4 ORDER BY id") if False else None
        assert s.execute("SELECT count(*) FROM t2 WHERE id IN (4, 5, 5, 6)").scalar() == 3
        assert s.execute("SELECT count(*) FROM t2 WHERE id >= 4 AND id IN (4, 5)").scalar() == 2

    def test_distinct_new_aggs(self):
        s = Session()
        s.execute("CREATE TABLE d (id BIGINT PRIMARY KEY, g INT)")
        s.execute("INSERT INTO d VALUES (1,1),(2,1),(3,1),(4,2)")
        assert s.execute("SELECT group_concat(DISTINCT g) FROM d").scalar() == "1,2"
        assert abs(s.execute("SELECT var_pop(DISTINCT g) FROM d").scalar() - 0.25) < 1e-12

    def test_device_like_ci(self):
        from tidb_tpu.types import Collation, FieldType, TypeCode
        from tidb_tpu.expr.ir import func as F, col as C, lit as L

        ci = FieldType(TypeCode.Varchar, flen=16, collate=Collation.Utf8MB4GeneralCI)
        fts = [new_longlong(), ci]
        rows = [[Datum.i64(i), Datum.string(v)] for i, v in enumerate(["Apple", "apple", "grape"])]
        ch = Chunk.from_rows(fts, rows)
        s = TableScan(1, (ColumnInfo(1, fts[0]), ColumnInfo(2, fts[1])))
        sel = Selection((F("like", BOOL, C(1, ci), L("app%", new_varchar(4))),))
        dev = parity(DAGRequest((s, sel), output_offsets=(0,)), ch)
        assert dev.num_rows() == 2

    def test_substr_null_pos(self):
        fts = [new_varchar(8), new_longlong()]
        rows = [[Datum.string("hello"), Datum.NULL], [Datum.string("hello"), Datum.i64(2)]]
        ch = Chunk.from_rows(fts, rows)
        s = TableScan(1, (ColumnInfo(1, fts[0]), ColumnInfo(2, fts[1])))
        proj = Projection((func("substr", VC, col(0, fts[0]), col(1, fts[1])),))
        dev = parity(DAGRequest((s, proj), output_offsets=(0,)), ch, sort=False)
        assert dev.row(0)[0].is_null()
