"""Region replication (ISSUE 8): peer sets + the shared placement
helper, quorum-acked writes, leader transfer (PD operator, breaker
failover, balance scheduler), NotLeader leader hints, replica reads
gated on per-peer safe_ts, and the stale-read contract under lagging
apply (ref: TiKV raftstore peers + resolved-ts follower reads,
client-go's replica selector and DataIsNotReady fallback)."""

import os
import sys
import threading

import pytest

from tidb_tpu.codec import tablecodec
from tidb_tpu.distsql.dispatch import KVRequest, full_table_ranges, select
from tidb_tpu.exec.dag import ColumnInfo, DAGRequest, TableScan
from tidb_tpu.replication import QUORUM_SAFE_TS_MAX
from tidb_tpu.sql.session import Session, SQLError
from tidb_tpu.store import (
    CopRequest,
    DataIsNotReady,
    KeyRange,
    NotLeader,
    TPUStore,
    parse_region_error,
)
from tidb_tpu.types import Datum, new_longlong
from tidb_tpu.util import failpoint, metrics

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

TID = 21


def fill_store(rows=120, regions=4, stores=4):
    store = TPUStore()
    for h in range(rows):
        store.put_row(TID, h, [1], [Datum.i64(h)], ts=10)
    for i in range(1, regions):
        store.cluster.split(tablecodec.encode_row_key(TID, i * rows // regions))
    store.cluster.set_stores(stores)
    store.cluster.scatter()
    return store


def scan_req(start_ts=100, **kw):
    dag = DAGRequest((TableScan(TID, (ColumnInfo(1, new_longlong()),)),), output_offsets=(0,))
    return KVRequest(dag, full_table_ranges(TID), start_ts=start_ts, **kw)


def replica_reads() -> dict:
    out = {"leader": 0, "follower": 0}
    for series, value in metrics.REGISTRY.sample_lines():
        if series.startswith("tidb_tpu_replica_read_total{"):
            out[series.split('"')[1]] = int(value)
    return out


# ------------------------------------------------------ peer-set topology

class TestPeerSets:
    def test_scatter_builds_peer_sets_with_leaders(self):
        store = fill_store()
        for r in store.cluster.regions():
            peers = store.cluster.peers_of(r.region_id)
            leader = store.cluster.leader_of(r.region_id)
            assert len(peers) == min(store.cluster.max_replicas, 4)
            assert len(set(peers)) == len(peers)
            assert leader in peers
            assert store.cluster.store_of(r.region_id) == leader  # back-compat

    def test_max_replicas_capped_at_n_stores(self):
        store = TPUStore()
        store.cluster.set_stores(2)
        for r in store.cluster.regions():
            assert len(store.cluster.peers_of(r.region_id)) == 2

    def test_split_child_inherits_peer_set(self):
        store = fill_store(rows=40, regions=1, stores=4)
        parent = store.cluster.regions()[0]
        ppeers = store.cluster.peers_of(parent.region_id)
        child = store.cluster.split(tablecodec.encode_row_key(TID, 20))
        assert store.cluster.peers_of(child.region_id) == ppeers
        assert store.cluster.leader_of(child.region_id) == \
            store.cluster.leader_of(parent.region_id)

    def test_merge_drops_absorbed_peer_set(self):
        store = fill_store(rows=40, regions=2, stores=4)
        left, right = store.cluster.regions()
        store.cluster.merge(left.region_id, right.region_id)
        assert store.cluster.region_by_id(right.region_id) is None
        with store.cluster._mu:
            assert right.region_id not in store.cluster._peers

    def test_placement_miss_assigns_peers_via_shared_helper(self):
        """A store_of miss places leader AND peers in one decision —
        the three historical hard-coding sites route through one
        helper now (satellite: reset/split drift)."""
        store = fill_store()
        child = store.cluster.split(tablecodec.encode_row_key(TID, 7))
        with store.cluster._mu:
            store.cluster._store_of.pop(child.region_id)
            store.cluster._peers.pop(child.region_id)
        leader = store.cluster.store_of(child.region_id)  # drives the miss
        peers = store.cluster.peers_of(child.region_id)
        assert leader in peers and len(peers) == 3

    def test_peer_counts_per_store(self):
        store = fill_store(rows=40, regions=4, stores=4)
        counts = store.cluster.peer_counts_per_store()
        assert sum(counts.values()) == 4 * 3  # 4 regions x 3 replicas


# -------------------------------------------------------- leader transfer

class TestLeaderTransfer:
    def test_transfer_within_peer_set_only_no_epoch_bump(self):
        store = fill_store()
        region = store.cluster.regions()[0]
        rid = region.region_id
        epoch0 = region.epoch
        leader = store.cluster.leader_of(rid)
        follower = store.cluster.followers_of(rid)[0]
        outsider = next(s for s in range(4) if s not in store.cluster.peers_of(rid))
        assert not store.cluster.transfer_leader(rid, outsider)
        assert not store.cluster.transfer_leader(rid, leader)  # already leads
        assert store.cluster.transfer_leader(rid, follower)
        assert store.cluster.leader_of(rid) == follower
        assert store.cluster.region_by_id(rid).epoch == epoch0  # no bump
        # the old leader becomes a fully-applied follower: it can serve
        # any snapshot immediately
        assert store.replication.safe_ts(rid, leader) == QUORUM_SAFE_TS_MAX

    def test_pd_transfer_leader_operator(self):
        store = fill_store()
        rid = store.cluster.regions()[0].region_id
        follower = store.cluster.followers_of(rid)[0]
        t0 = metrics.PD_TRANSFER_LEADER.value
        op = store.pd.new_operator("transfer-leader", rid, target=follower)
        store.pd._apply(op)
        assert op.state == "finished"
        assert store.cluster.leader_of(rid) == follower
        assert metrics.PD_TRANSFER_LEADER.value == t0 + 1

    def test_transfer_leader_timeout_failpoint(self):
        store = fill_store()
        rid = store.cluster.regions()[0].region_id
        follower = store.cluster.followers_of(rid)[0]
        leader0 = store.cluster.leader_of(rid)
        with failpoint.enabled("store/transfer-leader-timeout", 1):
            op = store.pd.new_operator("transfer-leader", rid, target=follower)
            store.pd._apply(op)
        assert op.state == "timeout"
        assert store.cluster.leader_of(rid) == leader0  # nothing moved

    def test_breaker_failover_is_a_leader_transfer(self):
        """ISSUE 8 acceptance: a down leader store fails over by
        TRANSFERRING leadership to a live peer — no placement move, the
        peer sets stay put."""
        store = fill_store()
        peer_counts0 = store.cluster.peer_counts_per_store()
        store.set_down(1)
        t0 = metrics.PD_TRANSFER_LEADER.value
        res = select(store, scan_req())
        assert sum(c.num_rows() for c in res.chunks) == 120
        assert metrics.PD_TRANSFER_LEADER.value > t0
        assert store.cluster.counts_per_store().get(1, 0) == 0  # no leaders
        # peer sets unchanged: store 1 still HOLDS its follower replicas
        assert store.cluster.peer_counts_per_store() == peer_counts0
        store.set_up(1)

    def test_quorum_loss_falls_back_to_placement_move(self):
        """With a majority of a region's peers dead no transfer can win:
        the PD re-places the whole group on healthy stores (the ONLY
        failover shape that moves placement)."""
        store = fill_store()
        region = store.cluster.regions()[0]
        peers = store.cluster.peers_of(region.region_id)
        for p in peers:
            store.set_down(p)
        survivor = next(s for s in range(4) if s not in peers)
        t0 = metrics.PD_TRANSFER_LEADER.value
        res = select(store, scan_req())
        assert sum(c.num_rows() for c in res.chunks) == 120
        assert store.cluster.leader_of(region.region_id) == survivor
        assert survivor in store.cluster.peers_of(region.region_id)
        assert metrics.PD_TRANSFER_LEADER.value == t0  # no transfer could win
        ops = [o for o in store.pd.queue.history_view() if o.kind == "failover"]
        assert ops and "quorum lost" in ops[-1].note
        for p in peers:
            store.set_up(p)

    def test_leader_balance_scheduler_evens_leader_counts(self):
        store = fill_store(rows=120, regions=8, stores=4)
        for r in store.cluster.regions():
            store.cluster.set_store(r.region_id, 0)  # pathological pin
        t0 = metrics.PD_TRANSFER_LEADER.value
        for _ in range(8):
            store.pd.tick()
            counts = store.cluster.counts_per_store()
            if max(counts.values()) - min(counts.values()) <= store.pd.conf.balance_tolerance:
                break
        counts = store.cluster.counts_per_store()
        assert max(counts.values()) - min(counts.values()) <= store.pd.conf.balance_tolerance
        assert metrics.PD_TRANSFER_LEADER.value > t0  # moved BY TRANSFER


# ------------------------------------------------- NotLeader leader hints

class TestNotLeaderHint:
    def test_hint_round_trips_the_wire_string(self):
        err = NotLeader.make(5, 2, leader_store=3)
        back = parse_region_error(str(err))
        assert isinstance(back, NotLeader)
        assert back.store_id == 2 and back.leader_store == 3
        # hint-less legacy strings still classify, hint unknown
        old = parse_region_error("not_leader: region 5 store 2")
        assert isinstance(old, NotLeader)
        assert old.store_id == 2 and old.leader_store == -1

    def test_non_leader_peer_answers_hint(self):
        store = fill_store()
        region = store.cluster.regions()[0]
        leader = store.cluster.leader_of(region.region_id)
        follower = store.cluster.followers_of(region.region_id)[0]
        dag = DAGRequest((TableScan(TID, (ColumnInfo(1, new_longlong()),)),), output_offsets=(0,))
        resp = store.coprocessor(CopRequest(
            dag, [KeyRange(region.start_key, region.end_key)], 100,
            region.region_id, region.epoch, peer_store=follower))
        err = parse_region_error(resp.region_error)
        assert isinstance(err, NotLeader)
        assert err.store_id == follower and err.leader_store == leader

    def test_dispatch_uses_hint_for_immediate_retry_without_backoff(self):
        """Satellite: a usable leader hint switches peers in ONE shot —
        the not_leader backoff budget is never touched. A follower-read
        against a store whose not-leader failpoint is armed produces
        exactly that shape: the error carries the REAL leader as hint."""
        store = fill_store()
        region = store.cluster.regions()[0]
        follower = store.cluster.followers_of(region.region_id)[0]
        b0 = metrics.BACKOFF_SECONDS.labels("not_leader").value
        e0 = metrics.REGISTRY.counter_vec(
            "tidb_tpu_region_errors_total", labelnames=("kind",)
        ).labels("not_leader").value
        with failpoint.enabled("store/not-leader", {follower}):
            res = select(store, scan_req(replica_read="follower", concurrency=1))
        assert sum(c.num_rows() for c in res.chunks) == 120
        assert metrics.REGISTRY.counter_vec(
            "tidb_tpu_region_errors_total", labelnames=("kind",)
        ).labels("not_leader").value > e0  # the flap really fired
        assert metrics.BACKOFF_SECONDS.labels("not_leader").value == b0  # no budget burned


# ------------------------------------------- replica reads + safe_ts gate

class TestReplicaReads:
    def test_follower_mode_serves_from_followers(self):
        store = fill_store()
        r0 = replica_reads()
        res = select(store, scan_req(replica_read="follower"))
        assert sum(c.num_rows() for c in res.chunks) == 120
        r1 = replica_reads()
        assert r1["follower"] - r0["follower"] >= 4  # every region task
        assert r1["leader"] == r0["leader"]

    def test_closest_replica_spreads_read_load(self):
        store = fill_store()
        for _ in range(6):
            res = select(store, scan_req(replica_read="closest-replica"))
            assert sum(c.num_rows() for c in res.chunks) == 120
        loads = store.replication.read_counts()
        assert len([s for s, n in loads.items() if n > 0]) >= 3

    def test_lagging_follower_gates_new_snapshots_to_leader(self):
        """A wedged apply loop must NEVER serve a snapshot past its
        safe_ts: reads at the new ts fall back to the leader (typed
        DataIsNotReady wait), reads at or below the watermark still ride
        the follower — and both return exactly the leader-oracle rows."""
        store = fill_store()
        res = select(store, scan_req(replica_read="follower"))  # join peers
        region = store.cluster.locate(tablecodec.encode_row_key(TID, 500))
        rid = region.region_id
        followers = store.cluster.followers_of(rid)
        with failpoint.enabled("replica/apply-lag", True):
            store.put_row(TID, 500, [1], [Datum.i64(500)], ts=150)
            for f in followers:
                assert store.replication.safe_ts(rid, f) < 150
            d0 = metrics.REGISTRY.counter_vec(
                "tidb_tpu_region_errors_total", labelnames=("kind",)
            ).labels("data_not_ready").value
            res = select(store, scan_req(start_ts=200, replica_read="follower"))
            assert sum(c.num_rows() for c in res.chunks) == 121  # leader truth
            assert metrics.REGISTRY.counter_vec(
                "tidb_tpu_region_errors_total", labelnames=("kind",)
            ).labels("data_not_ready").value > d0
            # stale snapshot UNDER the watermark: the follower serves it
            r0 = replica_reads()
            res = select(store, scan_req(start_ts=100, replica_read="follower"))
            assert sum(c.num_rows() for c in res.chunks) == 120
            assert replica_reads()["follower"] > r0["follower"]
        store.pd.tick()  # catch-up: the wedge is gone
        for f in store.cluster.followers_of(rid):
            assert store.replication.safe_ts(rid, f) == QUORUM_SAFE_TS_MAX

    def test_batch_cop_groups_by_routed_follower(self):
        store = fill_store(rows=120, regions=6, stores=3)
        r0 = replica_reads()
        res = select(store, scan_req(replica_read="follower", batch_cop=True))
        assert sum(c.num_rows() for c in res.chunks) == 120
        assert replica_reads()["follower"] - r0["follower"] >= 6

    def test_cop_request_peer_fields_survive_the_wire(self):
        from tidb_tpu.codec.wire import decode_cop_request, encode_cop_request

        dag = DAGRequest((TableScan(TID, (ColumnInfo(1, new_longlong()),)),), output_offsets=(0,))
        req = CopRequest(dag, [KeyRange(b"a", b"z")], 100, 7, 3,
                         peer_store=2, replica_read=True)
        back = decode_cop_request(encode_cop_request(req))
        assert back.peer_store == 2 and back.replica_read is True
        req = CopRequest(dag, [KeyRange(b"a", b"z")], 100, 7, 3)
        back = decode_cop_request(encode_cop_request(req))
        assert back.peer_store == -1 and back.replica_read is False

    def test_data_is_not_ready_round_trips(self):
        err = DataIsNotReady.make(7, 2, safe_ts=42)
        back = parse_region_error(str(err))
        assert isinstance(back, DataIsNotReady)
        assert back.store_id == 2 and back.safe_ts == 42
        assert back.kind == "data_not_ready"


class TestWatermarkEdges:
    def test_first_proposal_under_wedge_still_gates(self):
        """A region's FIRST tracked write while apply-lag is armed must
        not credit the wedged followers with the write itself (the lazy
        group bootstrap reads kv.max_committed() AFTER the put landed —
        review finding: the gate could never fire for first writes)."""
        store = TPUStore()
        store.cluster.set_stores(3)
        with failpoint.enabled("replica/apply-lag", True):
            store.put_row(TID, 1, [1], [Datum.i64(1)], ts=50)
            rid = store.cluster.locate(tablecodec.encode_row_key(TID, 1)).region_id
            for f in store.cluster.followers_of(rid):
                assert store.replication.safe_ts(rid, f) < 50

    def test_leader_move_within_peers_leaves_no_phantom_lag(self):
        """set_store() to an existing peer changes leadership; the new
        leader's stale follower watermark must not linger as ever-growing
        safe_ts lag in the PD views (review finding)."""
        store = fill_store()
        rid = store.cluster.locate(tablecodec.encode_row_key(TID, 1)).region_id
        follower = store.cluster.followers_of(rid)[0]
        store.cluster.set_store(rid, follower)  # move onto a peer
        assert store.cluster.leader_of(rid) == follower
        store.put_row(TID, 1, [1], [Datum.i64(2)], ts=300)
        store.pd.tick()  # catch-up + lag gauges
        assert all(v == 0 for v in store.replication.lag_view().values())

    def test_failover_prefers_caught_up_peer(self):
        """Raft: only an up-to-date peer may win — with one follower
        wedged, breaker failover transfers to the caught-up one."""
        store = fill_store()
        rid = store.cluster.locate(tablecodec.encode_row_key(TID, 1)).region_id
        leader = store.cluster.leader_of(rid)
        lagging, healthy = store.cluster.followers_of(rid)
        with failpoint.enabled("replica/apply-lag", {lagging}):
            store.put_row(TID, 1, [1], [Datum.i64(3)], ts=400)
            store.set_down(leader)
            target = store.pd.failover_region(rid, leader)
        assert target == healthy
        store.set_up(leader)


# --------------------------------------------------------- quorum writes

class TestQuorumWrites:
    def test_one_dropped_ack_still_commits(self):
        store = fill_store()
        rid = store.cluster.regions()[0].region_id
        follower = store.cluster.followers_of(rid)[0]
        q0 = metrics.REPLICA_QUORUM_FAILS.value
        with failpoint.enabled("replica/drop-ack", {follower}):
            assert store.replication.propose(rid, 200)  # 2/3 acks: quorum
        assert metrics.REPLICA_QUORUM_FAILS.value == q0
        assert store.replication.quorum_ok(rid)

    def test_majority_dropped_acks_lose_quorum(self):
        store = fill_store()
        rid = store.cluster.regions()[0].region_id
        followers = store.cluster.followers_of(rid)
        q0 = metrics.REPLICA_QUORUM_FAILS.value
        with failpoint.enabled("replica/drop-ack", set(followers)):
            assert not store.replication.propose(rid, 200)  # 1/3 acks
        assert metrics.REPLICA_QUORUM_FAILS.value > q0
        assert not store.replication.quorum_ok(rid)
        # the PD tick's roll call restores quorum WITHOUT a new proposal
        # (review finding: read-only workloads latched quorum_ok False
        # forever, degrading later failovers to placement moves)
        store.pd.tick()
        assert store.replication.quorum_ok(rid)
        # ...and a healthy proposal agrees
        assert store.replication.propose(rid, 201)
        assert store.replication.quorum_ok(rid)

    def test_write_refused_on_quorum_loss_then_succeeds(self):
        """ISSUE 10 satellite (ROADMAP PR-8 follow-on): a write against a
        quorum-lost region is REFUSED with MySQL 9005 — it no longer
        stays silently durable on the shared KV — and succeeds as soon
        as acks resume. The refusal still counts quorum-fail."""
        s = Session()
        s.execute("CREATE TABLE qw (id BIGINT PRIMARY KEY, v BIGINT)")
        s.execute("INSERT INTO qw VALUES (1, 1)")
        s.store.cluster.set_stores(4)
        s.store.cluster.scatter()
        tid = s.catalog.table("qw").table_id
        rid = s.store.cluster.locate(tablecodec.encode_row_key(tid, 2)).region_id
        followers = s.store.cluster.followers_of(rid)
        q0 = metrics.REPLICA_QUORUM_FAILS.value
        with failpoint.enabled("replica/drop-ack", set(followers)):
            with pytest.raises(SQLError) as ei:
                s.execute("INSERT INTO qw VALUES (2, 2)")
            assert ei.value.code == 9005
            assert "quorum_lost" in str(ei.value)
            # nothing turned durable: the refused row is invisible
            assert s.execute("SELECT count(*) FROM qw").values() == [[1]]
        assert metrics.REPLICA_QUORUM_FAILS.value > q0
        s.execute("INSERT INTO qw VALUES (2, 2)")  # acks resumed
        assert s.execute("SELECT count(*) FROM qw").values() == [[2]]

    def test_direct_put_refused_on_quorum_loss(self):
        from tidb_tpu.store import QuorumLostError

        store = fill_store()
        # arm the drop against the REGION THE WRITE LANDS IN — peer sets
        # differ per region after scatter
        rid = store.cluster.locate(tablecodec.encode_row_key(TID, 999)).region_id
        followers = store.cluster.followers_of(rid)
        with failpoint.enabled("replica/drop-ack", set(followers)):
            with pytest.raises(QuorumLostError):
                store.put_row(TID, 999, [1], [Datum.i64(999)], ts=300)
        store.put_row(TID, 999, [1], [Datum.i64(999)], ts=301)


# ------------------------------------------------------- session surfaces

class TestSessionSurfaces:
    def make_session(self, rows=120, regions=6, stores=3):
        s = Session()
        s.execute("CREATE TABLE rep (id BIGINT PRIMARY KEY, v BIGINT)")
        s.execute("INSERT INTO rep VALUES " + ",".join(f"({i},{i % 7})" for i in range(rows)))
        tid = s.catalog.table("rep").table_id
        for i in range(1, regions):
            s.store.cluster.split(tablecodec.encode_row_key(tid, i * rows // regions))
        s.store.cluster.set_stores(stores)
        s.store.cluster.scatter()
        return s

    def test_replica_read_sysvar_validates_and_routes(self):
        s = self.make_session()
        with pytest.raises(SQLError):
            s.execute("SET tidb_replica_read = 'sideways'")
        s.execute("SET tidb_replica_read = 'follower'")
        assert s.execute("SELECT @@tidb_replica_read").scalar() == "follower"
        r0 = replica_reads()
        assert s.execute("SELECT count(*) FROM rep").scalar() == 120
        assert replica_reads()["follower"] > r0["follower"]

    def test_stale_snapshot_session_rides_followers_only_when_covered(self):
        """Satellite: a tidb_snapshot-rewound session is served by a
        follower only when `safe_ts >= snapshot_ts`; a lagging follower
        never changes the answer at EITHER snapshot."""
        s = self.make_session(rows=60, regions=3, stores=3)
        snap_ts = s.store.next_ts()
        s.execute("SET tidb_replica_read = 'follower'")
        assert s.execute("SELECT count(*) FROM rep").scalar() == 60  # peers join
        with failpoint.enabled("replica/apply-lag", True):
            s.execute("INSERT INTO rep VALUES (1000, 1)")
            # current reads: the gate forces the leader; count is correct
            assert s.execute("SELECT count(*) FROM rep").scalar() == 61
            # rewound session: under every follower's watermark -> follower
            r0 = replica_reads()
            s.execute(f"SET tidb_snapshot = '{snap_ts}'")
            assert s.execute("SELECT count(*) FROM rep").scalar() == 60
            assert replica_reads()["follower"] > r0["follower"]
            s.execute("SET tidb_snapshot = ''")
            assert s.execute("SELECT count(*) FROM rep").scalar() == 61

    def test_show_placement_lists_peers_and_leaders(self):
        s = self.make_session(rows=40, regions=2, stores=3)
        rows = s.execute("SHOW PLACEMENT").values()
        store_rows = [r for r in rows if r[0].startswith("STORE")]
        region_rows = [r for r in rows if r[0].startswith("REGION")]
        assert all("leaders=" in r[1] and "peers=" in r[1] for r in store_rows)
        assert all("leader=" in r[1] and "peers=[" in r[1] for r in region_rows)

    def test_stores_view_surfaces_replica_counts(self):
        s = self.make_session(rows=40, regions=2, stores=3)
        for st in s.store.pd.stores_view():
            assert "leader_count" in st and "peer_count" in st and "safe_ts_lag" in st
        total_peers = sum(st["peer_count"] for st in s.store.pd.stores_view())
        assert total_peers == sum(
            len(s.store.cluster.peers_of(r.region_id))
            for r in s.store.cluster.regions())


# --------------------------------- lockwatch storm: transfers vs dispatch

def test_leader_transfer_storm_under_lockwatch():
    """ISSUE 8 satellite: leader transfers racing follower-read dispatch
    AND the PD tick under the runtime lockset detector — zero lock-order
    cycles, zero unguarded annotated accesses, and every scan returns
    the full row count (a transfer mid-scan costs at most a NotLeader
    hint retry, never rows)."""
    from tidb_tpu.analysis import lockwatch

    rows, regions = 160, 8
    with lockwatch.watching() as w:
        store = TPUStore()
        for h in range(rows):
            store.put_row(TID, h, [1], [Datum.i64(h)], ts=10)
        for i in range(1, regions):
            store.cluster.split(tablecodec.encode_row_key(TID, i * rows // regions))
        store.cluster.set_stores(4)
        store.cluster.scatter()
        dag = DAGRequest((TableScan(TID, (ColumnInfo(1, new_longlong()),)),),
                         output_offsets=(0,))
        stop = threading.Event()
        errors: list = []
        counts: list = []

        def scanner(mode):
            while not stop.is_set():
                try:
                    res = select(store, KVRequest(
                        dag, full_table_ranges(TID), 100, replica_read=mode))
                    counts.append(sum(c.num_rows() for c in res.chunks))
                except Exception as exc:  # noqa: BLE001
                    errors.append(exc)
                    return

        def transferrer():
            k = 0
            while not stop.is_set():
                for r in store.cluster.regions():
                    folls = store.cluster.followers_of(r.region_id)
                    if folls:
                        store.cluster.transfer_leader(
                            r.region_id, folls[k % len(folls)])
                k += 1
                store.pd.tick()

        threads = [threading.Thread(target=scanner, args=(m,), daemon=True)
                   for m in ("follower", "closest-replica", "leader")]
        threads.append(threading.Thread(target=transferrer, daemon=True))
        for t in threads:
            t.start()
        import time

        time.sleep(1.5)
        stop.set()
        for t in threads:
            t.join(timeout=30)
    rep = w.report()
    assert rep["cycles"] == [], rep["cycles"]
    assert rep["violations"] == [], "\n".join(rep["violations"])
    assert not errors, errors
    assert counts and all(c == rows for c in counts)
    assert rep["edges"], "lockwatch saw no lock nesting at all"
