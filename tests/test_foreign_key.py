"""Foreign-key enforcement at DML time (VERDICT r4 next #9; ref:
pkg/executor/foreign_key.go FKCheckExec/FKCascadeExec): insert/update
referential checks, ON DELETE RESTRICT/CASCADE/SET NULL, ON UPDATE
CASCADE, foreign_key_checks gate."""

import pytest

from tidb_tpu.sql import Session, SQLError


def _schema(on_delete="", on_update=""):
    s = Session()
    s.execute("create table parent (id bigint primary key, v bigint)")
    s.execute("insert into parent values (1, 10), (2, 20)")
    clause = ""
    if on_delete:
        clause += f" on delete {on_delete}"
    if on_update:
        clause += f" on update {on_update}"
    s.execute(
        "create table child (cid bigint primary key, pid bigint, "
        f"foreign key fk_p (pid) references parent (id){clause})"
    )
    return s


def test_insert_child_checks_parent():
    s = _schema()
    s.execute("insert into child values (1, 1)")
    s.execute("insert into child values (2, NULL)")  # NULL never violates
    with pytest.raises(SQLError, match="foreign key constraint fails"):
        s.execute("insert into child values (3, 99)")
    s.execute("set foreign_key_checks = OFF")
    s.execute("insert into child values (3, 99)")  # gate off


def test_update_child_checks_parent():
    s = _schema()
    s.execute("insert into child values (1, 1)")
    with pytest.raises(SQLError, match="foreign key constraint fails"):
        s.execute("update child set pid = 42 where cid = 1")
    s.execute("update child set pid = 2 where cid = 1")


def test_delete_parent_restrict():
    s = _schema()
    s.execute("insert into child values (1, 1)")
    with pytest.raises(SQLError, match="foreign key constraint fails"):
        s.execute("delete from parent where id = 1")
    s.execute("delete from parent where id = 2")  # unreferenced is fine


def test_delete_parent_cascade():
    s = _schema(on_delete="cascade")
    s.execute("insert into child values (1, 1), (2, 1), (3, 2)")
    s.execute("delete from parent where id = 1")
    assert s.execute("select cid from child order by cid").values() == [[3]]


def test_delete_parent_set_null():
    s = _schema(on_delete="set null")
    s.execute("insert into child values (1, 1)")
    s.execute("delete from parent where id = 1")
    assert s.execute("select pid from child where cid = 1").values() == [[None]]


def test_update_parent_cascade():
    s = _schema(on_update="cascade")
    s.execute("insert into child values (1, 1)")
    s.execute("update parent set id = 7 where id = 1")
    assert s.execute("select pid from child where cid = 1").values() == [[7]]


def test_update_parent_restrict():
    s = _schema()
    s.execute("insert into child values (1, 1)")
    with pytest.raises(SQLError, match="foreign key constraint fails"):
        s.execute("update parent set id = 7 where id = 1")


def test_cascade_chain():
    s = Session()
    s.execute("create table a (id bigint primary key)")
    s.execute("insert into a values (1)")
    s.execute("create table b (id bigint primary key, aid bigint, "
              "foreign key (aid) references a (id) on delete cascade)")
    s.execute("insert into b values (10, 1)")
    s.execute("create table c (id bigint primary key, bid bigint, "
              "foreign key (bid) references b (id) on delete cascade)")
    s.execute("insert into c values (100, 10)")
    s.execute("delete from a where id = 1")
    assert s.execute("select count(*) from b").values() == [[0]]
    assert s.execute("select count(*) from c").values() == [[0]]
