"""Top SQL (ISSUE 17; ref: pkg/util/topsql + ng-monitoring): windowed
per-digest CPU+device attribution threaded through every execution
layer, and the admission gate's measured-cost mode it feeds.

Covers: the one-digest join across slow log / statements_summary /
tidb_top_sql / plan cache (normalize_sql is fallback-only), exact
attribution conservation across the single / vmapped-batch / mesh cop
tiers (per-lane row-weighted splits sum exactly; cop-cache hits lose
nothing), window top-K + "(others)" fold conservation, EWMA cost-class
re-learning, cost-classed shedding (heavy sheds typed 9003 while
point-gets keep flowing), byte-consistency of the four surfaces
(collector view == information_schema == HTTP API == Prometheus
counters), the PD tick's topsql.report span, scrape_check on the new
metric families, and a lockwatch storm over rotation vs sessions vs
the PD tick."""

import json
import os
import sys
import threading
import time
import urllib.request

import pytest

from tidb_tpu import topsql
from tidb_tpu.codec import tablecodec
from tidb_tpu.distsql import KVRequest, full_table_ranges, select
from tidb_tpu.exec import Aggregation, ColumnInfo, DAGRequest, Selection, TableScan
from tidb_tpu.expr import AggDesc, col, func, lit
from tidb_tpu.sql.session import Session, SQLError
from tidb_tpu.store import TPUStore
from tidb_tpu.topsql import (
    CLASS_WEIGHTS,
    COLLECTOR,
    OTHERS_DIGEST,
    ResourceTag,
    TopSQLCollector,
    split_by_rows,
)
from tidb_tpu.types import Datum, new_longlong
from tidb_tpu.util import metrics
from tidb_tpu.util.stmtlog import normalize_sql

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

BOOL = new_longlong(notnull=True)
TID = 97
FT = new_longlong()


def fill_store(n=200, regions=8):
    store = TPUStore()
    for h in range(n):
        store.put_row(TID, h, [1], [Datum.i64(h * 3)], ts=10)
    for i in range(1, regions):
        store.cluster.split(tablecodec.encode_row_key(TID, i * n // regions))
    return store


def scan_dag():
    scan = TableScan(TID, (ColumnInfo(1, FT),))
    return DAGRequest((scan,), output_offsets=(0,))


def agg_dag():
    scan = TableScan(TID, (ColumnInfo(1, FT),))
    sel = Selection((func("lt", BOOL, col(0, FT), lit(300, new_longlong())),))
    agg = Aggregation(group_by=(), aggs=(AggDesc("count", ()),), partial=True)
    return DAGRequest((scan, sel, agg), output_offsets=(0,))


def kvreq(dag, ts, **kw):
    return KVRequest(dag, full_table_ranges(TID), start_ts=ts, **kw)


def snap(digest, cpu=0, dev=0, compile_ns=0, backoff=0.0, queue=0.0,
         byt=0, cop_hits=0, plan_digest="", sample=""):
    """A finished-tag snapshot, shaped like ResourceTag.snapshot()."""
    return {
        "sql_digest": digest, "plan_digest": plan_digest, "sample_sql": sample,
        "cpu_ns": cpu, "device_ns": dev, "compile_ns": compile_ns,
        "backoff_ms": backoff, "queue_ms": queue, "bytes_to_device": byt,
        "cop_cache_hits": cop_hits,
    }


# ------------------------------------------------------- exact lane split


def test_split_by_rows_exact():
    assert split_by_rows(0, []) == []
    assert split_by_rows(100, [1]) == [100]
    # always sums exactly, proportional, deterministic
    s = split_by_rows(1000, [1, 2, 7])
    assert sum(s) == 1000 and s[2] > s[1] > s[0]
    s = split_by_rows(7, [3, 3, 3])
    assert sum(s) == 7
    # all-zero rows degrade to equal split, still exact
    s = split_by_rows(10, [0, 0, 0])
    assert sum(s) == 10 and max(s) - min(s) <= 1
    # negative guard + skew
    s = split_by_rows(12345, [-1, 0, 1, 10**6])
    assert sum(s) == 12345 and s[3] >= 12343


# ------------------------------------------------------ digest unification


class TestDigestUnification:
    def test_four_surfaces_share_one_digest(self):
        """Slow log, statements_summary, tidb_top_sql and the plan cache
        all key the SAME statement by ONE digest — the plan-cache probe's
        literal-masked digest from its single lexer pass."""
        COLLECTOR.reset()
        s = Session()
        s.execute("create table t (a bigint primary key, b bigint)")
        s.execute("insert into t values (1, 10), (2, 20)")
        s.execute("set tidb_slow_log_threshold = 0")
        s.execute("select b from t where a = 1")
        s.execute("select b from t where a = 2")  # plan-cache hit
        s.execute("set tidb_slow_log_threshold = 300")
        digest = normalize_sql("select b from t where a = 1")[1]

        slow = s.execute(
            f"select digest from information_schema.slow_query where digest = '{digest}'"
        ).values()
        assert slow, "slow log missed the digest"
        summ = s.execute(
            "select digest, exec_count from information_schema.statements_summary "
            f"where digest = '{digest}'"
        ).values()
        assert summ and summ[0][1] == 2
        top = s.execute(
            "select digest, exec_count, plan_cache_hits from "
            f"information_schema.tidb_top_sql where digest = '{digest}'"
        ).values()
        assert top and top[0][1] == 2
        # the plan cache joined on the same digest: the second execution
        # was a hit, and Top SQL saw it as one
        assert top[0][2] >= 1

    def test_normalize_sql_is_fallback_only(self, monkeypatch):
        """A probed statement never re-lexes: the probe's digest rides
        from the plan cache through the stmt log and Top SQL, so
        normalize_sql is not called on the hot path."""
        from tidb_tpu.util import stmtlog as sl

        s = Session()
        s.execute("create table t (a bigint primary key)")
        s.execute("insert into t values (1)")
        s.execute("select a from t where a = 1")  # warm every cache

        calls = []
        real = sl.normalize_sql

        def counting(sql):
            calls.append(sql)
            return real(sql)

        monkeypatch.setattr(sl, "normalize_sql", counting)
        s.execute("select a from t where a = 1")
        assert calls == [], f"hot path re-lexed: {calls}"


# ------------------------------------------------ attribution conservation


class TestConservation:
    def test_tiers_conserve_device_time(self):
        """sum(per-digest device_ns) == sum(launch totals), exactly,
        across the per-region, vmapped-batch and mesh tiers; per-lane
        ExecSummary shares sum exactly to each launch's elapsed."""
        COLLECTOR.reset()
        store = fill_store(n=200, regions=8)
        tag = ResourceTag("tier-test")
        with topsql.adopt(tag):
            select(store, kvreq(scan_dag(), 100, concurrency=2, mesh=False))
            store.evict_caches()
            res_b = select(store, kvreq(scan_dag(), 101, batch_cop=True, mesh=False))
            store.evict_caches()
            select(store, kvreq(agg_dag(), 102))  # planner default: mesh tier
        assert tag.device_ns > 0
        assert tag.device_ns == COLLECTOR.launch_device_ns
        assert tag.compile_ns > 0 and tag.bytes_to_device > 0
        # batched per-lane shares: every lane of every launch carries its
        # row-weighted share; the shares of one launch sum to that
        # launch's elapsed, so lanes total the tier's device time
        lane_total = sum(task[0].time_processed_ns for task in res_b.exec_summaries)
        batch_elapsed = tag.device_ns  # after all three tiers; recompute:
        del batch_elapsed
        # re-run the batched tier alone under a fresh tag for the exact sum
        store.evict_caches()
        tag2 = ResourceTag("lane-sum")
        with topsql.adopt(tag2):
            res2 = select(store, kvreq(scan_dag(), 103, batch_cop=True, mesh=False))
        lane_total = sum(task[0].time_processed_ns for task in res2.exec_summaries)
        assert lane_total == tag2.device_ns, (lane_total, tag2.device_ns)

    def test_cop_cache_hits_lose_nothing(self):
        """A fully cached re-read does zero device work: the tag shows
        the hit count instead of silently attributing nothing, and the
        conservation ledger is untouched."""
        COLLECTOR.reset()
        store = fill_store(n=120, regions=6)
        select(store, kvreq(scan_dag(), 100, concurrency=2, mesh=False))  # untagged populate
        assert COLLECTOR.launch_device_ns == 0  # no ambient tag, no ledger
        tag = ResourceTag("cached")
        l0 = metrics.PROGRAM_LAUNCHES.value
        with topsql.adopt(tag):
            select(store, kvreq(scan_dag(), 101, concurrency=2, mesh=False))
        assert metrics.PROGRAM_LAUNCHES.value == l0  # served from cop cache
        assert tag.device_ns == 0 and tag.cop_cache_hits == 6
        assert COLLECTOR.launch_device_ns == 0

    def test_untagged_sinks_are_free_noops(self):
        topsql.record_device(123, compile_ns=1)
        topsql.record_backoff(1.0)
        topsql.record_queue_wait(1.0)
        topsql.record_cop_cache_hit()  # no ambient tag: all no-ops


# ----------------------------------------------------- windows + the fold


class TestReporterWindows:
    def test_topk_union_and_others_fold_conserve(self):
        """A sealed window keeps the union of top-K digests BY EACH
        metric and folds the rest into (others) — window totals stay
        conservation-exact."""
        c = TopSQLCollector(window_s=1000.0, top_k=1)
        c.record_statement(snap("cpu-hog", cpu=1000))
        c.record_statement(snap("backoff-hog", cpu=1, backoff=500.0))
        c.record_statement(snap("dev-hog", dev=900))
        c.record_statement(snap("nobody-1", cpu=2))
        c.record_statement(snap("nobody-2", cpu=3))
        assert c.rotate(force=True) == 1
        (w,) = c.windows_view()
        kept = {d["digest"] for d in w["digests"]}
        # top-1 by cpu, by device and by backoff all survive independently
        assert {"cpu-hog", "backoff-hog", "dev-hog"} <= kept
        assert "nobody-1" not in kept and "nobody-2" not in kept
        assert w["others"]["digest"] == OTHERS_DIGEST
        assert w["others"]["exec_count"] == 2
        total_cpu = sum(d["cpu_ns"] for d in w["digests"]) + w["others"]["cpu_ns"]
        assert total_cpu == c.totals["cpu_ns"] == 1006

    def test_ring_is_bounded_and_ordered(self):
        clock = [0.0]
        c = TopSQLCollector(window_s=1.0, ring=3, now_fn=lambda: clock[0])
        for i in range(6):
            if i:
                clock[0] += 1.5  # every statement lands in its own window
            c.record_statement(snap(f"d{i}", cpu=10))
        views = c.windows_view()
        sealed = [w for w in views if not w["live"]]
        assert len(sealed) == 3  # ring bound ate the oldest
        assert [w["start"] for w in sealed] == sorted(w["start"] for w in sealed)
        live = [w for w in views if w["live"]]
        assert len(live) == 1 and live[0]["digests"][0]["digest"] == "d5"

    def test_sysvar_bridges(self):
        s = Session()
        try:
            s.execute("set tidb_top_sql_max_statement_count = 7")
            assert COLLECTOR.top_k == 7
            s.execute("set tidb_enable_top_sql = OFF")
            assert not COLLECTOR.enabled
            COLLECTOR.reset()
            s.execute("select 1")
            assert COLLECTOR.windows_view() == []  # nothing recorded while off
        finally:
            s.execute("set tidb_enable_top_sql = ON")
            s.execute("set tidb_top_sql_max_statement_count = 30")
        assert COLLECTOR.enabled and COLLECTOR.top_k == 30

    def test_pd_tick_runs_the_reporter(self):
        """The PD tick owns the rotation clock: a topsql.report child
        span under pd.tick, and a due live window actually seals."""
        c = COLLECTOR
        c.reset()
        c.configure(window_s=0.001)
        try:
            c.record_statement(snap("tick-digest", cpu=5))
            time.sleep(0.005)
            store = fill_store(n=20, regions=2)
            store.pd.tick()
            root = store.pd.last_tick_root
            assert root is not None
            names = {ch.name for ch in root.children}
            assert "topsql.report" in names
            sealed = [w for w in c.windows_view() if not w["live"]]
            assert sealed and sealed[0]["digests"][0]["digest"] == "tick-digest"
        finally:
            c.configure(window_s=1.0)


# ------------------------------------------------------------ cost classes


class TestCostClasses:
    def test_ewma_classifies_and_relearns(self):
        """Classes are measured, never guessed — and re-learned: a digest
        whose plan changes migrates as soon as the EWMA crosses."""
        c = TopSQLCollector()
        assert c.cost_class("never-seen") == "small"  # DEFAULT_CLASS
        for _ in range(3):
            c.record_statement(snap("d", cpu=80_000_000, dev=80_000_000))
        assert c.cost_class("d") == "heavy"
        # the plan improved: cheap executions walk the EWMA back down
        for _ in range(12):
            c.record_statement(snap("d", cpu=100_000))
        assert c.cost_class("d") == "point"
        assert c.weight("d") == CLASS_WEIGHTS["point"] == 1

    def test_heavy_sheds_while_point_flows(self):
        from tidb_tpu.server.admission import AdmissionGate, AdmissionShed

        g = AdmissionGate(max_inflight=4, session_queue=0, queue_wait_ms=5.0,
                          cost_classed=True,
                          classifier=lambda d: "heavy" if d == "H" else "point")
        held = g.admit("h1", digest="H")  # heavy lane: 4 // 4 = 1 slot
        try:
            with pytest.raises(AdmissionShed) as ei:
                g.admit("h2", digest="H")
            assert ei.value.where in ("queue_full", "queue_timeout")
            # the full point-get budget still flows beside the wedged lane
            pts = [g.admit(f"p{i}", digest="P") for i in range(4)]
            v = g.view()
            assert v["by_class"] == {"heavy": 1, "point": 4}
            assert v["weighted_inflight"] == 8
            for t in pts:
                t.__exit__(None, None, None)
        finally:
            held.__exit__(None, None, None)
        assert g.view()["by_class"] == {}

    def test_session_shed_is_typed_9003(self):
        """End to end: measured-heavy digest sheds at a saturated gate as
        SQLError 9003 while a measured-point statement still runs."""
        COLLECTOR.reset()
        s = Session()
        s.execute("create table t (a bigint primary key, b bigint)")
        s.execute("insert into t values (1, 10), (2, 20)")
        heavy_sql = "select sum(b) from t where b > 0"
        point_sql = "select b from t where a = 1"
        heavy_d = normalize_sql(heavy_sql)[1]
        point_d = normalize_sql(point_sql)[1]
        for _ in range(3):  # train the EWMAs: measured, not guessed
            COLLECTOR.record_statement(snap(heavy_d, cpu=200_000_000))
            COLLECTOR.record_statement(snap(point_d, cpu=50_000))
        assert COLLECTOR.cost_class(heavy_d) == "heavy"
        assert COLLECTOR.cost_class(point_d) == "point"

        gate = s.store.admission
        gate.configure(max_inflight=4, session_queue=0, queue_wait_ms=2.0,
                       cost_classed=True)
        held = gate.admit("wedge", digest=heavy_d)  # heavy lane full (cap 1)
        try:
            with pytest.raises(SQLError) as ei:
                s.execute(heavy_sql)
            assert ei.value.code == 9003
            assert s.execute(point_sql).values() == [[10]]
        finally:
            held.__exit__(None, None, None)
            gate.configure(max_inflight=0, session_queue=4,
                           queue_wait_ms=50.0, cost_classed=False)

    def test_queue_wait_attributed_to_the_waiter(self):
        from tidb_tpu.server.admission import AdmissionGate

        g = AdmissionGate(max_inflight=1, session_queue=2, queue_wait_ms=200.0)
        held = g.admit("holder")
        tag = ResourceTag("waiter")
        got = []

        def waiter():
            with topsql.adopt(tag):
                with g.admit("w"):
                    got.append(True)

        th = threading.Thread(target=waiter, daemon=True)
        th.start()
        time.sleep(0.03)
        held.__exit__(None, None, None)
        th.join(timeout=30)
        assert got and tag.queue_ms > 0


# ------------------------------------------------- surfaces stay in sync


def test_surfaces_byte_consistent():
    """One serializer, four surfaces: the collector's windows_view, the
    information_schema memtable, the HTTP API and the Prometheus counters
    all show THE SAME numbers."""
    COLLECTOR.reset()
    cpu0 = metrics.TOPSQL_CPU_NS.value
    dev0 = metrics.TOPSQL_DEVICE_NS.value
    n0 = metrics.TOPSQL_RECORDS.value
    s = Session()
    s.execute("create table t (a bigint primary key, b bigint)")
    s.execute("insert into t values " + ",".join(f"({i},{i})" for i in range(64)))
    for i in range(4):
        s.execute(f"select sum(b) from t where a > {i}")
    s.execute("set tidb_enable_top_sql = OFF")  # freeze: reads don't self-record
    COLLECTOR.rotate(force=True)
    try:
        view = COLLECTOR.windows_view()
        assert view and all(not w["live"] for w in view)

        def total(win_list, key):
            return sum(
                sum(d[key] for d in w["digests"])
                + (w["others"][key] if w["others"] else 0)
                for w in win_list
            )

        # collector totals == window sums == prometheus counter deltas
        assert total(view, "cpu_ns") == COLLECTOR.totals["cpu_ns"] == \
            metrics.TOPSQL_CPU_NS.value - cpu0
        assert total(view, "device_ns") == COLLECTOR.totals["device_ns"] == \
            metrics.TOPSQL_DEVICE_NS.value - dev0
        assert COLLECTOR.totals["exec_count"] == metrics.TOPSQL_RECORDS.value - n0
        # ... == the conservation ledger (every launch was tagged)
        assert COLLECTOR.totals["device_ns"] == COLLECTOR.launch_device_ns

        # information_schema renders the same rows
        rows = s.execute(
            "select digest, exec_count, cpu_ns, device_ns "
            "from information_schema.tidb_top_sql"
        ).values()
        by_digest = {}
        for dg, ec, cpu, dev in rows:
            acc = by_digest.setdefault(dg, [0, 0, 0])
            acc[0] += ec
            acc[1] += cpu
            acc[2] += dev
        want = {}
        for w in view:
            for d in w["digests"] + ([w["others"]] if w["others"] else []):
                acc = want.setdefault(d["digest"], [0, 0, 0])
                acc[0] += d["exec_count"]
                acc[1] += d["cpu_ns"]
                acc[2] += d["device_ns"]
        assert by_digest == want

        # the HTTP API serves the very same serializer output
        from tidb_tpu.server.http_api import StatusServer

        srv = StatusServer(s).start_background()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            api = json.loads(urllib.request.urlopen(base + "/topsql/api/v1/windows").read())
            assert api == json.loads(json.dumps(view, default=str))
            dg = view[-1]["digests"][0]["digest"]
            one = json.loads(urllib.request.urlopen(
                base + f"/topsql/api/v1/digests/{dg}").read())
            assert one["digest"] == dg and one["windows"]
            assert one["cost_class"] in ("point", "small", "scan", "heavy")
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(base + "/topsql/api/v1/digests/absent")
        finally:
            srv.close()
    finally:
        s.execute("set tidb_enable_top_sql = ON")


def test_statements_summary_enriched_columns():
    COLLECTOR.reset()
    s = Session()
    s.execute("create table t (a bigint primary key, b bigint)")
    s.execute("insert into t values " + ",".join(f"({i},{i})" for i in range(300)))
    s.execute("select sum(b) from t where a >= 0")
    digest = normalize_sql("select sum(b) from t where a >= 0")[1]
    rows = s.execute(
        "select avg_device_ns, max_device_ns, avg_compile_ns, cost_class "
        f"from information_schema.statements_summary where digest = '{digest}'"
    ).values()
    assert rows
    avg_dev, max_dev, avg_comp, cls = rows[0]
    assert avg_dev > 0 and max_dev >= avg_dev and avg_comp > 0
    assert cls in ("point", "small", "scan", "heavy")


def test_metric_families_pass_scrape_check():
    COLLECTOR.reset()
    s = Session()
    s.execute("create table t (a bigint primary key)")
    s.execute("insert into t values (1)")
    s.execute("select a from t where a = 1")
    COLLECTOR.rotate(force=True)
    text = metrics.REGISTRY.dump()
    for family in (
        "tidb_tpu_topsql_records_total",
        "tidb_tpu_topsql_cpu_ns_total",
        "tidb_tpu_topsql_device_ns_total",
        "tidb_tpu_topsql_compile_ns_total",
        "tidb_tpu_topsql_backoff_ms_total",
        "tidb_tpu_topsql_queue_ms_total",
        "tidb_tpu_topsql_launch_device_ns_total",
        "tidb_tpu_topsql_windows_sealed_total",
        "tidb_tpu_topsql_live_digests",
        "tidb_tpu_topsql_class_admissions_total",
    ):
        assert f"# TYPE {family}" in text, family
    from scrape_check import validate

    assert validate(text) == []


# ------------------------------------------------------- lockwatch storm


def test_topsql_lockwatch_storm():
    """Window rotation + 8 recording sessions + the PD tick's reporter
    phase, all racing under the runtime lockset detector: zero lock-order
    cycles, zero unguarded annotated accesses — the collector and tag
    locks really are leaves."""
    from tidb_tpu.analysis import lockwatch

    COLLECTOR.reset()
    with lockwatch.watching() as w:
        src = Session()
        src.execute("create table t (a bigint primary key, b bigint)")
        src.execute("insert into t values " + ",".join(
            f"({i},{i * 10})" for i in range(32)))
        gate = src.store.admission
        gate.configure(max_inflight=6, cost_classed=True)
        stop = threading.Event()
        errors: list = []

        def runner(seed):
            sess = Session(store=src.store, catalog=src.catalog)
            i = seed
            while not stop.is_set():
                try:
                    sess.execute(f"select b from t where a = {i % 32}")
                    sess.execute(f"select sum(b) from t where a > {i % 8}")
                    i += 1
                except SQLError:
                    pass
                except Exception as exc:  # noqa: BLE001
                    errors.append(exc)
                    return

        def rotator():
            while not stop.is_set():
                try:
                    COLLECTOR.rotate(force=True)
                    COLLECTOR.windows_view()
                    time.sleep(0.005)
                except Exception as exc:  # noqa: BLE001
                    errors.append(exc)
                    return

        def ticker():
            pd = getattr(src.store, "pd", None)
            while not stop.is_set():
                try:
                    if pd is not None:
                        pd.tick()
                    time.sleep(0.01)
                except Exception as exc:  # noqa: BLE001
                    errors.append(exc)
                    return

        threads = [threading.Thread(target=runner, args=(i * 5,), daemon=True)
                   for i in range(8)]
        threads.append(threading.Thread(target=rotator, daemon=True))
        threads.append(threading.Thread(target=ticker, daemon=True))
        for t in threads:
            t.start()
        time.sleep(1.5)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        gate.configure(max_inflight=0, cost_classed=False)
    rep = w.report()
    assert rep["cycles"] == [], rep["cycles"]
    assert rep["violations"] == [], "\n".join(rep["violations"])
    assert not errors, errors
    assert metrics.TOPSQL_WINDOWS_SEALED.value > 0


def test_chaos_oracle_clean_with_cost_classed_gate():
    """ISSUE 17 acceptance: the answer-correctness chaos storm stays
    clean with Top SQL attribution on and the admission gate in
    measured-cost mode — classes learned live under faults, every shed
    typed 9003 (already in the storm's retryable set), zero wrong
    results, zero untyped errors."""
    from chaos import run_chaos

    topsql.COLLECTOR.reset()
    report = run_chaos(seed=13, statements=40, admission_flicker=0.1,
                       cost_classed=True)
    assert report["wrong_results"] == []
    assert report["untyped_errors"] == []
    assert report["breakers_all_closed"], report["breakers"]
    # the flicker-forced sheds surfaced typed, and the storm's statements
    # actually flowed through the collector (classes were live, not idle)
    assert report["errors_by_code"].get(9003, 0) >= 1
    assert topsql.COLLECTOR.totals["exec_count"] > 0
