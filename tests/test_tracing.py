"""Statement tracing: the span tree behind TRACE (ref: pkg/util/tracing +
executor/trace.go), the tracing primitives' threading contract, the
device-time attribution riding the exec summaries, and the Prometheus
exposition contract enforced by tools/scrape_check."""

import json
import os
import sys
import threading

import pytest

from tidb_tpu.codec import tablecodec
from tidb_tpu.sql.session import Session
from tidb_tpu.util import tracing

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
from scrape_check import validate  # noqa: E402


@pytest.fixture()
def sess():
    s = Session()
    s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
    s.execute("INSERT INTO t VALUES " + ",".join(f"({i},{i % 5})" for i in range(1, 61)))
    tid = s.catalog.table("t").table_id
    for h in (20, 40):  # 3 regions
        s.store.cluster.split(tablecodec.encode_row_key(tid, h))
    return s


# ---------------------------------------------------------------- primitives
class TestSpanPrimitives:
    def test_span_is_noop_without_trace(self):
        assert tracing.current_span() is None
        with tracing.span("anything") as sp:
            assert sp is None  # zero bookkeeping when tracing is off
        assert tracing.current_span() is None

    def test_nesting_and_attrs(self):
        with tracing.trace("root") as root:
            with tracing.span("child", k=1) as c:
                c.set("rows", 7)
                with tracing.span("grand"):
                    pass
        assert [c.name for c in root.children] == ["child"]
        assert root.children[0].attrs == {"k": 1, "rows": 7}
        assert [g.name for g in root.children[0].children] == ["grand"]
        # every span finished, children contained in the parent window
        assert root.end_ns is not None
        assert root.children[0].duration_ns <= root.duration_ns

    def test_exception_recorded_and_reraised(self):
        with tracing.trace("root") as root:
            with pytest.raises(ValueError):
                with tracing.span("boom"):
                    raise ValueError("no")
        assert "ValueError: no" in root.children[0].attrs["error"]
        assert root.children[0].end_ns is not None

    def test_cross_thread_parent_handoff(self):
        """Pool workers don't inherit contextvars; the explicit parent=
        handoff is how dispatch parents its cop-task spans."""
        with tracing.trace("root") as root:
            parent = tracing.current_span()

            def worker():
                assert tracing.current_span() is None  # not inherited
                with tracing.span("task", parent=parent, region_id=9):
                    pass

            t = threading.Thread(target=worker)
            t.start()
            t.join()
        assert [c.name for c in root.children] == ["task"]
        assert root.children[0].attrs["region_id"] == 9

    def test_find_and_rows_render(self):
        with tracing.trace("root") as root:
            with tracing.span("a"):
                with tracing.span("b"):
                    pass
            with tracing.span("b"):
                pass
        assert len(root.find("b")) == 2
        ops = [r[0] for r in root.rows()]
        assert ops == ["root", "  a", "    b", "  b"]


# ---------------------------------------------------------------- TRACE stmt
class TestTraceStatement:
    def _tree(self, sess, sql):
        res = sess.execute(f"TRACE FORMAT='json' {sql}")
        assert res.columns == ["trace"]
        return json.loads(res.values()[0][0])

    @staticmethod
    def _find(node, name):
        out = [node] if node["name"] == name else []
        for c in node.get("children", []):
            out.extend(TestTraceStatement._find(c, name))
        return out

    def test_multi_region_aggregate_span_shape(self, sess):
        tree = self._tree(sess, "SELECT v, count(*) FROM t GROUP BY v")
        assert tree["name"] == "session"
        assert self._find(tree, "session.execute")
        assert self._find(tree, "planner.plan")
        # dispatch level: the thread-pool path, the device-mesh path, or
        # the mpp fragment path — whichever the gate picked on this host
        dispatch = (self._find(tree, "distsql.execute_root")
                    + self._find(tree, "parallel.mesh_select")
                    + self._find(tree, "mpp.dispatch"))
        assert dispatch
        cop = self._find(tree, "distsql.cop_task")
        assert len(cop) == 3  # one child span per region
        assert sorted(c["attrs"]["region_id"] for c in cop) == [1, 2, 3]
        assert all(c["attrs"]["rows"] >= 1 for c in cop)
        # program compile/cache level spans exist, and the program compiled
        # at most once across the per-region tasks (cache hits after)
        progs = self._find(tree, "exec.program")
        assert progs and any("cache_hit" in p["attrs"] for p in progs)
        assert sum(1 for p in progs if not p["attrs"]["cache_hit"]) <= 2  # push + root merge

    def test_durations_sum_consistently(self, sess):
        tree = self._tree(sess, "SELECT v, count(*) FROM t GROUP BY v")

        def check(node):
            for c in node.get("children", []):
                assert c["duration_ns"] <= node["duration_ns"]
                check(c)

        check(tree)
        dispatch = (self._find(tree, "distsql.execute_root")
                    + self._find(tree, "parallel.mesh_select")
                    + self._find(tree, "mpp.dispatch"))[0]
        cop = self._find(tree, "distsql.cop_task")
        assert cop and all(c["duration_ns"] <= dispatch["duration_ns"] for c in cop)

    def test_row_format(self, sess):
        res = sess.execute("TRACE SELECT count(*) FROM t")
        assert res.columns == ["operation", "start_us", "duration_us", "attrs"]
        ops = [r[0] for r in res.values()]
        assert ops[0] == "session"
        assert any(op.lstrip().startswith("distsql.cop_task") for op in ops)
        # indentation encodes the tree depth
        assert any(op.startswith("  ") for op in ops)

    def test_trace_of_failing_statement_returns_partial_tree(self, sess):
        res = sess.execute("TRACE FORMAT='json' SELECT * FROM no_such_table")
        tree = json.loads(res.values()[0][0])
        assert "error" in tree["attrs"]
        assert self._find(tree, "session.execute")  # the partial tree survived

    def test_trace_dml(self, sess):
        tree = self._tree(sess, "INSERT INTO t VALUES (1000, 1)")
        assert tree["attrs"].get("rows") == 1
        assert sess.execute("SELECT v FROM t WHERE id = 1000").values() == [[1]]


# ------------------------------------------------------- summary attribution
class TestExecSummaryAttribution:
    def test_summaries_carry_compile_and_bytes(self, sess):
        from tidb_tpu.distsql import full_table_ranges
        from tidb_tpu.exec.dag import DAGRequest, TableScan

        meta = sess.catalog.table("t")
        scan = TableScan(meta.table_id, meta.scan_columns())
        dag = DAGRequest((scan,), output_offsets=(0, 1))
        from tidb_tpu.distsql.dispatch import KVRequest, select

        res = select(sess.store, KVRequest(dag, full_table_ranges(meta.table_id), sess.store.next_ts()))
        assert len(res.exec_summaries) == 3  # one per region task
        for task_sums in res.exec_summaries:
            assert task_sums[0].num_bytes > 0  # decoded region bytes
        # a second identical dispatch: every program comes from the cache
        res2 = select(sess.store, KVRequest(dag, full_table_ranges(meta.table_id), sess.store.next_ts()))
        assert all(s[0].cache_hit for s in res2.exec_summaries)
        assert all(s[0].time_compile_ns == 0 for s in res2.exec_summaries)

    def test_wire_roundtrip_preserves_attribution(self):
        from tidb_tpu.codec.wire import decode_cop_response, encode_cop_response
        from tidb_tpu.store.store import CopResponse, ExecSummary

        resp = CopResponse(
            chunk=None,
            exec_summaries=[ExecSummary(10, 5, 1, time_compile_ns=77, cache_hit=True, num_bytes=123)],
        )
        out = decode_cop_response(encode_cop_response(resp))
        s = out.exec_summaries[0]
        assert (s.time_compile_ns, s.cache_hit, s.num_bytes) == (77, True, 123)


# ------------------------------------------------------------ slow-log links
class TestSlowLogArtifacts:
    def test_fast_failure_leaves_slow_log_entry(self, sess):
        from tidb_tpu.util import failpoint

        sess.execute("SET tidb_slow_log_threshold = 100000")  # nothing is slow
        failpoint.enable("cop-other-error", 1)
        try:
            with pytest.raises(Exception, match="injected"):
                sess.execute("SELECT sum(v) FROM t")
        finally:
            failpoint.disable("cop-other-error")
        rows = sess.execute(
            "SELECT query, success, error FROM information_schema.slow_query"
        ).values()
        failed = [r for r in rows if r[1] == 0]
        assert failed and any("injected" in (r[2] or "") for r in failed)

    def test_plan_digest_joins_slow_log(self, sess):
        sess.execute("SET tidb_slow_log_threshold = 0")  # everything is slow
        sess.execute("SELECT sum(v) FROM t")
        rows = sess.execute(
            "SELECT plan_digest, query FROM information_schema.slow_query"
        ).values()
        digests = [r[0] for r in rows if "sum(v)" in r[1].lower()]
        assert digests and all(len(d) == 32 for d in digests)


# ------------------------------------------------------------- metrics/text
class TestMetricsExposition:
    def test_dump_passes_scrape_check(self, sess):
        sess.execute("SELECT sum(v) FROM t")  # move some instruments
        from tidb_tpu.util import metrics

        text = metrics.REGISTRY.dump()
        assert validate(text) == []
        assert "# HELP tidb_tpu_cop_requests_total" in text
        assert "# TYPE tidb_tpu_cop_duration_seconds histogram" in text
        assert 'tidb_tpu_cop_duration_seconds_bucket{le="+Inf"}' in text

    def test_labeled_vec_exposition(self):
        from tidb_tpu.util import metrics

        metrics.STATEMENTS.labels("select", "ok").inc(3)
        metrics.DISTSQL_TASK_DURATION.labels("table").observe(0.02)
        text = metrics.REGISTRY.dump()
        assert validate(text) == []
        assert 'tidb_tpu_statements_total{type="select",status="ok"}' in text
        assert 'tidb_tpu_distsql_task_duration_seconds_bucket{scan="table",le="0.05"}' in text

    def test_gauge_moves_both_ways(self, sess):
        from tidb_tpu.util import metrics

        base = metrics.OPEN_TXNS.value
        sess.execute("BEGIN")
        assert metrics.OPEN_TXNS.value == base + 1
        sess.execute("ROLLBACK")
        assert metrics.OPEN_TXNS.value == base

    def test_scrape_check_rejects_bad_expositions(self):
        assert validate('# TYPE h histogram\nh_bucket{le="1"} 5\nh_bucket{le="+Inf"} 3\nh_sum 1.0\nh_count 3\n')
        assert validate("# TYPE c counter\nc -4\n")
        assert validate("# TYPE c counter\nc 1\nc 1\n")  # duplicate series
        assert validate('# TYPE h histogram\nh_bucket{le="+Inf"} 1\nh_count 1\n')  # no _sum
