"""Full-DAG parity: fused device program vs reference interpreter.

The bit-parity harness of SURVEY.md §4/§7: same DAG, two engines, diff rows.
"""

import numpy as np
import pytest

from tidb_tpu.types import (
    Datum,
    MyDecimal,
    MyTime,
    new_datetime,
    new_decimal,
    new_double,
    new_longlong,
    new_varchar,
)
from tidb_tpu.chunk import Chunk
from tidb_tpu.expr import AggDesc, col, func, lit
from tidb_tpu.exec import (
    Aggregation,
    ColumnInfo,
    DAGRequest,
    Limit,
    ProgramCache,
    Selection,
    TableScan,
    TopN,
    run_dag_on_chunk,
    run_dag_reference,
)
from tidb_tpu.exec.executor import datum_group_key

BOOL = new_longlong(notnull=True)

# lineitem-ish schema: shipdate, qty dec(15,2), price dec(15,2), disc dec(15,2),
# returnflag varchar(1), linestatus varchar(1), tax double
FTS = [new_datetime(), new_decimal(15, 2), new_decimal(15, 2), new_decimal(15, 2), new_varchar(1), new_varchar(1), new_double()]


def lineitem_chunk(n=400, seed=9, null_p=0.03):
    rng = np.random.default_rng(seed)
    rows = []
    for _ in range(n):
        def maybe(d):
            return Datum.NULL if rng.random() < null_p else d

        y = 1992 + int(rng.integers(7))
        rows.append([
            maybe(Datum.time(MyTime.from_ymd(y, 1 + int(rng.integers(12)), 1 + int(rng.integers(28))))),
            maybe(Datum.dec(MyDecimal(f"{int(rng.integers(1, 51))}.00"))),
            maybe(Datum.dec(MyDecimal(f"{int(rng.integers(90000, 9000000))/100:.2f}"))),
            maybe(Datum.dec(MyDecimal(f"0.0{int(rng.integers(10))}"))),
            maybe(Datum.string("ANR"[int(rng.integers(3))])),
            maybe(Datum.string("OF"[int(rng.integers(2))])),
            maybe(Datum.f64(float(np.round(rng.random() * 0.08, 2)))),
        ])
    return Chunk.from_rows(FTS, rows)


def scan():
    return TableScan(1, tuple(ColumnInfo(i + 1, ft) for i, ft in enumerate(FTS)))


def canon(d):
    k = datum_group_key(d)
    # float aggregates sum in different orders on device vs oracle; IEEE
    # non-associativity makes last-bit drift expected — 12 sig digits is the
    # parity contract for DOUBLE (decimals stay bit-exact)
    if isinstance(k[1], float):
        return (k[0], float(f"{k[1]:.12g}"))
    return k


def rows_canon(rows):
    return [tuple(canon(d) for d in r) for r in rows]


def assert_same(dev_chunk, ref_rows, sort=True):
    got = rows_canon(dev_chunk.rows())
    want = rows_canon(ref_rows)
    if sort:
        got, want = sorted(got), sorted(want)
    assert got == want, f"\ndevice={got[:5]}\nref   ={want[:5]} (len {len(got)} vs {len(want)})"


C = lambda i: col(i, FTS[i])


class TestDAGParity:
    def test_q6_shape(self):
        """Selection + scalar agg: sum(price*disc), count(*)."""
        ch = lineitem_chunk()
        pred = func(
            "and",
            BOOL,
            func("ge", BOOL, C(0), lit("1994-01-01", new_datetime())),
            func(
                "and",
                BOOL,
                func("lt", BOOL, C(0), lit("1995-01-01", new_datetime())),
                func(
                    "and",
                    BOOL,
                    func("between", BOOL, C(3), lit("0.05", new_decimal(3, 2)), lit("0.07", new_decimal(3, 2))),
                    func("lt", BOOL, C(1), lit(24, new_longlong())),
                ),
            ),
        )
        revenue = func("mul", new_decimal(31, 4), C(2), C(3))
        agg = Aggregation(
            group_by=(),
            aggs=(AggDesc("sum", (revenue,)), AggDesc("count", ())),
        )
        dag = DAGRequest((scan(), Selection((pred,)), agg), output_offsets=(0, 1))
        dev = run_dag_on_chunk(dag, ch)
        ref = run_dag_reference(dag, ch)
        assert_same(dev, ref)

    def test_q1_shape(self):
        """GROUP BY returnflag, linestatus with 8 aggregates."""
        ch = lineitem_chunk(600)
        disc_price = func("mul", new_decimal(31, 4), C(2), func("minus", new_decimal(16, 2), lit(1, new_longlong()), C(3)))
        charge = func("mul", new_double(), func("cast", new_double(), disc_price), func("plus", new_double(), lit(1.0, new_double()), C(6)))
        agg = Aggregation(
            group_by=(C(4), C(5)),
            aggs=(
                AggDesc("sum", (C(1),)),
                AggDesc("sum", (C(2),)),
                AggDesc("sum", (disc_price,)),
                AggDesc("avg", (C(1),)),
                AggDesc("avg", (C(2),)),
                AggDesc("avg", (C(3),)),
                AggDesc("count", ()),
                AggDesc("sum", (charge,)),
            ),
        )
        sel = Selection((func("le", BOOL, C(0), lit("1998-09-02", new_datetime())),))
        dag = DAGRequest((scan(), sel, agg), output_offsets=tuple(range(10)))
        dev = run_dag_on_chunk(dag, ch)
        ref = run_dag_reference(dag, ch)
        assert dev.num_rows() == len(ref)
        assert_same(dev, ref)

    def test_topn_limit(self):
        ch = lineitem_chunk(300)
        t = TopN(order_by=((C(2), True), (C(0), False)), limit=17)
        dag = DAGRequest((scan(), t), output_offsets=(2, 0, 4))
        dev = run_dag_on_chunk(dag, ch)
        ref = run_dag_reference(dag, ch)
        assert_same(dev, ref, sort=False)  # TopN is ordered

    def test_limit(self):
        ch = lineitem_chunk(100)
        dag = DAGRequest((scan(), Limit(9)), output_offsets=(0, 1, 2, 3, 4, 5, 6))
        dev = run_dag_on_chunk(dag, ch)
        assert dev.num_rows() == 9
        # device keeps first 9 valid rows in input order
        ref = run_dag_reference(dag, ch)
        assert_same(dev, ref, sort=False)

    def test_group_overflow_retry(self):
        """More groups than initial capacity: driver retries with bigger."""
        ch = lineitem_chunk(500)
        agg = Aggregation(group_by=(C(2),), aggs=(AggDesc("count", ()),))
        dag = DAGRequest((scan(), agg), output_offsets=(0, 1))
        dev = run_dag_on_chunk(dag, ch, group_capacity=32)  # ~500 distinct prices
        ref = run_dag_reference(dag, ch)
        assert_same(dev, ref)

    def test_empty_result(self):
        ch = lineitem_chunk(50)
        sel = Selection((func("gt", BOOL, C(1), lit(1000, new_longlong())),))
        agg = Aggregation(group_by=(), aggs=(AggDesc("count", ()), AggDesc("sum", (C(2),))))
        dag = DAGRequest((scan(), sel, agg), output_offsets=(0, 1))
        dev = run_dag_on_chunk(dag, ch)
        assert dev.num_rows() == 1
        r = dev.row(0)
        assert r[0].val == 0 and r[1].is_null()


def test_first_row_string_group():
    """first_row over a varchar column via rep-row gather."""
    from tidb_tpu.expr import AggDesc

    ch = lineitem_chunk(120)
    agg = Aggregation(group_by=(C(4),), aggs=(AggDesc("first_row", (C(5),)), AggDesc("count", ())))
    dag = DAGRequest((scan(), agg), output_offsets=(0, 1, 2))
    dev = run_dag_on_chunk(dag, ch)
    # first_row is 'any row of the group' — verify each value is drawn from
    # the group's actual rows and counts match the oracle
    groups = {}
    for r in ch.rows():
        k = canon(r[4])
        groups.setdefault(k, []).append(r)
    assert dev.num_rows() == len(groups)
    for r in dev.rows():
        k = canon(r[2])
        members = groups[k]
        assert r[1].val == len(members)
        vals = {canon(m[5]) for m in members}
        assert canon(r[0]) in vals


class TestFullSort:
    """The Sort executor: ORDER BY without LIMIT returns EVERY row in order
    (the r2 SORT_NO_LIMIT 2^20 truncation trap is gone)."""

    def test_sort_beyond_old_topn_bound(self):
        """> 2^21 rows through the device Sort — every row comes back,
        globally ordered (the old bound silently dropped rows past 2^20)."""
        import numpy as np

        from tidb_tpu.chunk import Chunk, Column, to_device_batch
        from tidb_tpu.exec import DAGRequest, Sort, TableScan, ColumnInfo
        from tidb_tpu.exec.executor import drive_program
        from tidb_tpu.exec.builder import ProgramCache
        from tidb_tpu.expr import col
        from tidb_tpu.types import new_longlong

        n = (1 << 21) + 17
        rng = np.random.default_rng(0)
        vals = rng.integers(-(10**12), 10**12, n).astype(np.int64)
        ft = new_longlong(notnull=True)
        chunk = Chunk([Column(ft, vals.copy(), np.zeros(n, bool))])
        scan = TableScan(1, (ColumnInfo(1, ft),))
        dag = DAGRequest((scan, Sort(order_by=((col(0, ft), False),))), output_offsets=(0,))
        batch = to_device_batch(chunk, capacity=1 << 22)
        out, _ = drive_program(ProgramCache(), dag, [batch], group_capacity=64)
        got = np.asarray(out.columns[0].data, dtype=np.int64)
        assert got.shape[0] == n, f"rows dropped: {got.shape[0]} != {n}"
        assert np.array_equal(got, np.sort(vals))

    def test_sql_order_by_without_limit_matches_oracle(self):
        from tidb_tpu.sql import Session

        s = Session()
        s.execute("create table srt (a bigint, b varchar(4))")
        rows = ",".join(f"({(i * 7919) % 1000}, '{'wxyz'[i % 4]}')" for i in range(500))
        s.execute("insert into srt values " + rows)
        r = s.execute("select a, b from srt order by a desc, b")
        assert len(r.rows) == 500  # every row, no bound
        got = [(int(x[0].val), str(x[1].val)) for x in r.rows]
        assert got == sorted(got, key=lambda t: (-t[0], t[1]))

    def test_sql_sort_across_regions(self):
        from tidb_tpu.codec import tablecodec
        from tidb_tpu.sql import Session

        s = Session()
        s.execute("create table srt2 (a bigint)")
        s.execute("insert into srt2 values " + ",".join(f"({999 - i})" for i in range(300)))
        meta = s.catalog.table("srt2")
        for h in (80, 160, 240):
            s.store.cluster.split(tablecodec.encode_row_key(meta.table_id, h))
        got = [int(x[0].val) for x in s.execute("select a from srt2 order by a").rows]
        assert got == sorted(got) and len(got) == 300


class TestProgramCacheSingleFlight:
    """A cold key hit by N pool threads at once must compile exactly once —
    the launch-count regression guard (compiles/hits, the TRACE cache_hit
    attr) is meaningless if it's timing-dependent."""

    def test_concurrent_cold_miss_compiles_once(self, monkeypatch):
        import threading
        import time
        from concurrent.futures import ThreadPoolExecutor

        from tidb_tpu.exec import DAGRequest, Selection, TableScan, ColumnInfo
        from tidb_tpu.exec import builder as builder_mod
        from tidb_tpu.exec.builder import ProgramCache
        from tidb_tpu.expr import col, func, lit
        from tidb_tpu.types import new_longlong

        real_build = builder_mod.build_program
        started = threading.Barrier(4, timeout=10)

        def slow_build(*a, **kw):
            time.sleep(0.05)  # hold the miss window open for every racer
            return real_build(*a, **kw)

        monkeypatch.setattr(builder_mod, "build_program", slow_build)
        ft = new_longlong(notnull=True)
        pred = func("gt", BOOL, col(0, ft), lit(0, ft))
        dag = DAGRequest(
            (TableScan(1, (ColumnInfo(1, ft),)), Selection((pred,))),
            output_offsets=(0,),
        )
        cache = ProgramCache()

        def fetch():
            started.wait()
            return cache.get_info(dag, (64,))

        with ThreadPoolExecutor(max_workers=4) as pool:
            results = [f.result() for f in [pool.submit(fetch) for _ in range(4)]]
        assert cache.stats()["compiles"] == 1
        assert cache.stats()["hits"] == 3
        assert sorted(hit for _, hit, _ in results) == [False, True, True, True]
        progs = {id(p) for p, _, _ in results}
        assert len(progs) == 1  # every thread got the one compiled program
        assert not cache._inflight  # claim released
