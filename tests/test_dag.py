"""Full-DAG parity: fused device program vs reference interpreter.

The bit-parity harness of SURVEY.md §4/§7: same DAG, two engines, diff rows.
"""

import numpy as np
import pytest

from tidb_tpu.types import (
    Datum,
    MyDecimal,
    MyTime,
    new_datetime,
    new_decimal,
    new_double,
    new_longlong,
    new_varchar,
)
from tidb_tpu.chunk import Chunk
from tidb_tpu.expr import AggDesc, col, func, lit
from tidb_tpu.exec import (
    Aggregation,
    ColumnInfo,
    DAGRequest,
    Limit,
    ProgramCache,
    Selection,
    TableScan,
    TopN,
    run_dag_on_chunk,
    run_dag_reference,
)
from tidb_tpu.exec.executor import datum_group_key

BOOL = new_longlong(notnull=True)

# lineitem-ish schema: shipdate, qty dec(15,2), price dec(15,2), disc dec(15,2),
# returnflag varchar(1), linestatus varchar(1), tax double
FTS = [new_datetime(), new_decimal(15, 2), new_decimal(15, 2), new_decimal(15, 2), new_varchar(1), new_varchar(1), new_double()]


def lineitem_chunk(n=400, seed=9, null_p=0.03):
    rng = np.random.default_rng(seed)
    rows = []
    for _ in range(n):
        def maybe(d):
            return Datum.NULL if rng.random() < null_p else d

        y = 1992 + int(rng.integers(7))
        rows.append([
            maybe(Datum.time(MyTime.from_ymd(y, 1 + int(rng.integers(12)), 1 + int(rng.integers(28))))),
            maybe(Datum.dec(MyDecimal(f"{int(rng.integers(1, 51))}.00"))),
            maybe(Datum.dec(MyDecimal(f"{int(rng.integers(90000, 9000000))/100:.2f}"))),
            maybe(Datum.dec(MyDecimal(f"0.0{int(rng.integers(10))}"))),
            maybe(Datum.string("ANR"[int(rng.integers(3))])),
            maybe(Datum.string("OF"[int(rng.integers(2))])),
            maybe(Datum.f64(float(np.round(rng.random() * 0.08, 2)))),
        ])
    return Chunk.from_rows(FTS, rows)


def scan():
    return TableScan(1, tuple(ColumnInfo(i + 1, ft) for i, ft in enumerate(FTS)))


def canon(d):
    k = datum_group_key(d)
    # float aggregates sum in different orders on device vs oracle; IEEE
    # non-associativity makes last-bit drift expected — 12 sig digits is the
    # parity contract for DOUBLE (decimals stay bit-exact)
    if isinstance(k[1], float):
        return (k[0], float(f"{k[1]:.12g}"))
    return k


def rows_canon(rows):
    return [tuple(canon(d) for d in r) for r in rows]


def assert_same(dev_chunk, ref_rows, sort=True):
    got = rows_canon(dev_chunk.rows())
    want = rows_canon(ref_rows)
    if sort:
        got, want = sorted(got), sorted(want)
    assert got == want, f"\ndevice={got[:5]}\nref   ={want[:5]} (len {len(got)} vs {len(want)})"


C = lambda i: col(i, FTS[i])


class TestDAGParity:
    def test_q6_shape(self):
        """Selection + scalar agg: sum(price*disc), count(*)."""
        ch = lineitem_chunk()
        pred = func(
            "and",
            BOOL,
            func("ge", BOOL, C(0), lit("1994-01-01", new_datetime())),
            func(
                "and",
                BOOL,
                func("lt", BOOL, C(0), lit("1995-01-01", new_datetime())),
                func(
                    "and",
                    BOOL,
                    func("between", BOOL, C(3), lit("0.05", new_decimal(3, 2)), lit("0.07", new_decimal(3, 2))),
                    func("lt", BOOL, C(1), lit(24, new_longlong())),
                ),
            ),
        )
        revenue = func("mul", new_decimal(31, 4), C(2), C(3))
        agg = Aggregation(
            group_by=(),
            aggs=(AggDesc("sum", (revenue,)), AggDesc("count", ())),
        )
        dag = DAGRequest((scan(), Selection((pred,)), agg), output_offsets=(0, 1))
        dev = run_dag_on_chunk(dag, ch)
        ref = run_dag_reference(dag, ch)
        assert_same(dev, ref)

    def test_q1_shape(self):
        """GROUP BY returnflag, linestatus with 8 aggregates."""
        ch = lineitem_chunk(600)
        disc_price = func("mul", new_decimal(31, 4), C(2), func("minus", new_decimal(16, 2), lit(1, new_longlong()), C(3)))
        charge = func("mul", new_double(), func("cast", new_double(), disc_price), func("plus", new_double(), lit(1.0, new_double()), C(6)))
        agg = Aggregation(
            group_by=(C(4), C(5)),
            aggs=(
                AggDesc("sum", (C(1),)),
                AggDesc("sum", (C(2),)),
                AggDesc("sum", (disc_price,)),
                AggDesc("avg", (C(1),)),
                AggDesc("avg", (C(2),)),
                AggDesc("avg", (C(3),)),
                AggDesc("count", ()),
                AggDesc("sum", (charge,)),
            ),
        )
        sel = Selection((func("le", BOOL, C(0), lit("1998-09-02", new_datetime())),))
        dag = DAGRequest((scan(), sel, agg), output_offsets=tuple(range(10)))
        dev = run_dag_on_chunk(dag, ch)
        ref = run_dag_reference(dag, ch)
        assert dev.num_rows() == len(ref)
        assert_same(dev, ref)

    def test_topn_limit(self):
        ch = lineitem_chunk(300)
        t = TopN(order_by=((C(2), True), (C(0), False)), limit=17)
        dag = DAGRequest((scan(), t), output_offsets=(2, 0, 4))
        dev = run_dag_on_chunk(dag, ch)
        ref = run_dag_reference(dag, ch)
        assert_same(dev, ref, sort=False)  # TopN is ordered

    def test_limit(self):
        ch = lineitem_chunk(100)
        dag = DAGRequest((scan(), Limit(9)), output_offsets=(0, 1, 2, 3, 4, 5, 6))
        dev = run_dag_on_chunk(dag, ch)
        assert dev.num_rows() == 9
        # device keeps first 9 valid rows in input order
        ref = run_dag_reference(dag, ch)
        assert_same(dev, ref, sort=False)

    def test_group_overflow_retry(self):
        """More groups than initial capacity: driver retries with bigger."""
        ch = lineitem_chunk(500)
        agg = Aggregation(group_by=(C(2),), aggs=(AggDesc("count", ()),))
        dag = DAGRequest((scan(), agg), output_offsets=(0, 1))
        dev = run_dag_on_chunk(dag, ch, group_capacity=32)  # ~500 distinct prices
        ref = run_dag_reference(dag, ch)
        assert_same(dev, ref)

    def test_empty_result(self):
        ch = lineitem_chunk(50)
        sel = Selection((func("gt", BOOL, C(1), lit(1000, new_longlong())),))
        agg = Aggregation(group_by=(), aggs=(AggDesc("count", ()), AggDesc("sum", (C(2),))))
        dag = DAGRequest((scan(), sel, agg), output_offsets=(0, 1))
        dev = run_dag_on_chunk(dag, ch)
        assert dev.num_rows() == 1
        r = dev.row(0)
        assert r[0].val == 0 and r[1].is_null()


def test_first_row_string_group():
    """first_row over a varchar column via rep-row gather."""
    from tidb_tpu.expr import AggDesc

    ch = lineitem_chunk(120)
    agg = Aggregation(group_by=(C(4),), aggs=(AggDesc("first_row", (C(5),)), AggDesc("count", ())))
    dag = DAGRequest((scan(), agg), output_offsets=(0, 1, 2))
    dev = run_dag_on_chunk(dag, ch)
    # first_row is 'any row of the group' — verify each value is drawn from
    # the group's actual rows and counts match the oracle
    groups = {}
    for r in ch.rows():
        k = canon(r[4])
        groups.setdefault(k, []).append(r)
    assert dev.num_rows() == len(groups)
    for r in dev.rows():
        k = canon(r[2])
        members = groups[k]
        assert r[1].val == len(members)
        vals = {canon(m[5]) for m in members}
        assert canon(r[0]) in vals
