import numpy as np
import pytest

from tidb_tpu.types import Datum, MyDecimal, new_decimal, new_double, new_longlong, new_varchar
from tidb_tpu.chunk import Chunk, Column, to_device_batch
from tidb_tpu.chunk.device import pack_string_words

import jax.numpy as jnp


def make_chunk():
    fts = [new_longlong(), new_double(), new_decimal(15, 2), new_varchar(16)]
    rows = [
        [Datum.i64(1), Datum.f64(1.5), Datum.dec("10.25"), Datum.string("apple")],
        [Datum.i64(-7), Datum.NULL, Datum.dec("-3.10"), Datum.string("banana")],
        [Datum.NULL, Datum.f64(2.25), Datum.NULL, Datum.NULL],
    ]
    return Chunk.from_rows(fts, rows), rows


def test_chunk_roundtrip():
    ch, rows = make_chunk()
    assert ch.num_rows() == 3
    got = ch.rows()
    assert got[0][0].val == 1
    assert got[1][2].val == MyDecimal("-3.10")
    assert got[2][3].is_null()
    assert got[0][3].val == "apple"


def test_chunk_take_concat():
    ch, _ = make_chunk()
    sub = ch.take(np.array([2, 0]))
    assert sub.num_rows() == 2
    assert sub.row(1)[3].val == "apple"
    cc = Chunk.concat([ch, sub])
    assert cc.num_rows() == 5
    assert cc.row(4)[3].val == "apple"


def test_device_batch_padding():
    ch, _ = make_chunk()
    db = to_device_batch(ch, capacity=8)
    assert db.capacity == 8
    assert int(db.n_rows) == 3
    assert bool(db.row_valid[2]) and not bool(db.row_valid[3])
    # decimal stored as scaled int64
    assert int(db.cols[2].data[0]) == 1025
    # null mask set for padding too
    assert bool(db.cols[0].null[5])


def test_pack_string_words_order():
    ch, _ = make_chunk()
    db = to_device_batch(ch, capacity=4)
    col = db.cols[3]
    words = pack_string_words(col.data, col.length)
    # "apple" < "banana" lexicographically
    a, b = words[0], words[1]
    lt = (a[0] < b[0]) | ((a[0] == b[0]) & (a[1] < b[1]))
    assert bool(lt)
