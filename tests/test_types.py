import numpy as np
import pytest

from tidb_tpu.types import (
    Datum,
    FieldType,
    MyDecimal,
    MyTime,
    TypeCode,
    new_decimal,
    new_longlong,
    new_varchar,
    pack_datetime,
    unpack_datetime,
)


class TestMyDecimal:
    def test_scale_propagation_add(self):
        a = MyDecimal("1.25")
        b = MyDecimal("2.5")
        c = a + b
        assert c.scale == 2
        assert str(c) == "3.75"

    def test_mul_scale(self):
        c = MyDecimal("1.50") * MyDecimal("0.06")
        assert c.scale == 4
        assert str(c) == "0.0900"

    def test_div_frac_incr(self):
        # MySQL: scale(a/b) = scale(a) + 4 (ref div_frac_incr)
        c = MyDecimal("1.00").div(MyDecimal("3"))
        assert c.scale == 6
        assert str(c) == "0.333333"

    def test_div_by_zero_is_null(self):
        assert MyDecimal("1").div(MyDecimal("0")) is None

    def test_round_half_away_from_zero(self):
        assert str(MyDecimal("2.5", 2).round(0)) == "3"
        assert str(MyDecimal("-2.5", 2).round(0)) == "-3"

    def test_scaled_int_roundtrip(self):
        d = MyDecimal("12345.67")
        assert d.to_scaled_int() == 1234567
        assert MyDecimal.from_scaled_int(1234567, 2) == d


class TestMyTime:
    def test_pack_order_preserving(self):
        a = MyTime.parse("1997-12-31 23:59:59")
        b = MyTime.parse("1998-01-01")
        assert a.packed < b.packed

    def test_roundtrip(self):
        p = pack_datetime(1995, 3, 15, 10, 30, 45, 123456)
        assert unpack_datetime(p) == (1995, 3, 15, 10, 30, 45, 123456)

    def test_str(self):
        assert str(MyTime.parse("1995-03-15")) == "1995-03-15"
        assert str(MyTime.parse("1995-03-15 01:02:03")) == "1995-03-15 01:02:03"


class TestFieldType:
    def test_eval_types(self):
        assert new_longlong().eval_type() == "int"
        assert new_decimal(15, 2).eval_type() == "decimal"
        assert new_varchar(10).eval_type() == "string"
        assert FieldType(TypeCode.Double).eval_type() == "real"
