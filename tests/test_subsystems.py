"""Auxiliary subsystems (SURVEY §5 / VERDICT inventory rows 17, 46, 49-51):
sysvars + the TPU feature gate, failpoints, metrics, memory tracking,
config."""

import pytest

from tidb_tpu.config import Config
from tidb_tpu.sql import Session, SQLError
from tidb_tpu.sql.sysvar import SysVarError, SysVarStore
from tidb_tpu.util import MemTracker, QuotaExceeded, REGISTRY, failpoint
from tidb_tpu.util import metrics as M


@pytest.fixture()
def sess():
    s = Session()
    s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, g INT, v DECIMAL(8,2))")
    vals = ", ".join(f"({i}, {i % 5}, {i}.25)" for i in range(100))
    s.execute(f"INSERT INTO t (id, g, v) VALUES {vals}")
    return s


class TestSysVars:
    def test_validation(self):
        sv = SysVarStore()
        sv.set("tidb_distsql_scan_concurrency", "8")
        assert sv.get_int("tidb_distsql_scan_concurrency") == 8
        with pytest.raises(SysVarError):
            sv.set("tidb_distsql_scan_concurrency", "0")
        with pytest.raises(SysVarError):
            sv.set("tidb_enable_tpu_coprocessor", "maybe")
        with pytest.raises(SysVarError):
            sv.set("no_such_variable", "1")

    def test_set_through_sql(self, sess):
        sess.execute("SET tidb_distsql_scan_concurrency = 2")
        assert sess.sysvars.get_int("tidb_distsql_scan_concurrency") == 2
        with pytest.raises(SQLError):
            sess.execute("SET tidb_distsql_scan_concurrency = 'lots'")
        r = sess.execute("SHOW VARIABLES")
        names = [row[0].val for row in r.rows]
        assert "tidb_enable_tpu_coprocessor" in names

    def test_tpu_gate_off_same_results(self, sess):
        want = sess.execute("SELECT g, count(*), sum(v) FROM t GROUP BY g ORDER BY g").values()
        sess.execute("SET tidb_enable_tpu_coprocessor = OFF")
        got = sess.execute("SELECT g, count(*), sum(v) FROM t GROUP BY g ORDER BY g").values()
        assert [[a, b, str(c)] for a, b, c in got] == [[a, b, str(c)] for a, b, c in want]
        sess.execute("SET tidb_enable_tpu_coprocessor = ON")

    def test_paging_sysvar(self, sess):
        sess.execute("SET tidb_enable_paging = ON")
        sess.execute("SET tidb_max_chunk_size = 32")
        # row-local query pages; aggregation query silently doesn't
        r = sess.execute("SELECT id FROM t WHERE g = 1 ORDER BY id")
        assert [x for x, in r.values()] == [i for i in range(100) if i % 5 == 1]
        assert sess.execute("SELECT count(*) FROM t").scalar() == 100

    def test_mem_quota(self, sess):
        sess.execute("SET tidb_mem_quota_query = 1")
        with pytest.raises(SQLError, match="memory quota"):
            sess.execute("SELECT * FROM t")
        sess.execute(f"SET tidb_mem_quota_query = {1 << 30}")
        assert sess.execute("SELECT count(*) FROM t").scalar() == 100


class TestFailpoints:
    def test_injected_region_error_retried(self, sess):
        """A failpoint-injected region error exercises the transparent
        retry path (ref: testfailpoint-driven rpc error tests)."""
        before = M.DISTSQL_RETRIES.value
        with failpoint.enabled("cop-region-error", 1):  # fire once
            assert sess.execute("SELECT count(*) FROM t").scalar() == 100
        assert M.DISTSQL_RETRIES.value == before + 1

    def test_injected_other_error_surfaces(self, sess):
        with failpoint.enabled("cop-other-error"):
            with pytest.raises(SQLError, match="injected") as ei:
                sess.execute("SELECT count(*) FROM t")
        assert ei.value.code == 1105  # ER_UNKNOWN_ERROR: non-retryable cop failure

    def test_counted_failpoint_expires(self):
        failpoint.enable("fp-x", 2)
        assert failpoint.eval("fp-x") and failpoint.eval("fp-x")
        assert failpoint.eval("fp-x") is None


class TestMetrics:
    def test_cop_counters_move(self, sess):
        c0, d0 = M.COP_REQUESTS.value, M.COP_DURATION.count
        sess.execute("SELECT sum(v) FROM t")
        assert M.COP_REQUESTS.value > c0
        assert M.COP_DURATION.count > d0
        dump = REGISTRY.dump()
        assert "tidb_tpu_cop_requests_total" in dump
        assert "tidb_tpu_cop_duration_seconds_count" in dump


class TestMemTracker:
    def test_quota_and_action(self):
        freed = []

        def action(tr, n):
            freed.append(n)
            tr.consume(-tr.consumed)  # free everything (spill analog)

        parent = MemTracker("root", quota=None)
        t = MemTracker("q", quota=100, parent=parent, action=action)
        t.consume(80)
        t.consume(50)  # over quota -> action frees -> passes
        assert freed and t.consumed <= 100
        hard = MemTracker("hard", quota=10)
        with pytest.raises(QuotaExceeded):
            hard.consume(11)

    def test_peak_and_release(self):
        p = MemTracker("p")
        c = MemTracker("c", parent=p)
        c.consume(40)
        c.consume(-10)
        assert c.peak == 40 and p.consumed == 30
        c.release_all()
        assert c.consumed == 0 and p.consumed == 0


class TestConfig:
    def test_from_toml(self, tmp_path):
        f = tmp_path / "cfg.toml"
        f.write_text("group_capacity = 128\n[performance]\ndistsql_scan_concurrency = 9\n")
        cfg = Config.from_toml(str(f))
        assert cfg.group_capacity == 128
        assert cfg.distsql_scan_concurrency == 9
        assert cfg.mem_quota_query == 1 << 30  # default survives


class TestVarsAndConfig2:
    def test_user_vars_readable(self, sess):
        sess.execute("SET @thresh = 50")
        r = sess.execute("SELECT count(*) FROM t WHERE id >= @thresh")
        assert r.scalar() == 50
        assert sess.execute("SELECT @thresh + 1").scalar() == 51
        assert sess.execute("SELECT @undefined").scalar() is None

    def test_sysvar_reference(self, sess):
        assert sess.execute("SELECT @@tidb_distsql_scan_concurrency").scalar() == 4

    def test_session_from_config(self):
        s = Session(config=Config(distsql_scan_concurrency=2, mem_quota_query=1 << 20, paging_size=64))
        assert s.sysvars.get_int("tidb_distsql_scan_concurrency") == 2
        assert s.sysvars.get_bool("tidb_enable_paging")

    def test_update_pk_same_unique_value_ok(self, sess):
        sess.execute("CREATE TABLE pu (id BIGINT PRIMARY KEY, u INT)")
        sess.execute("INSERT INTO pu VALUES (1, 5), (3, 7)")
        sess.execute("CREATE UNIQUE INDEX uu ON pu (u)")
        sess.execute("UPDATE pu SET id = 2 WHERE id = 1")  # u unchanged
        assert sorted(x for x, in sess.execute("SELECT id FROM pu").values()) == [2, 3]
        with pytest.raises(SQLError, match="duplicate"):
            sess.execute("UPDATE pu SET u = 7 WHERE id = 2")


class TestSpillDegrade:
    """Quota-bounded aggregation completes via the degraded low-memory
    fold instead of erroring (VERDICT r2 next #10; ref: util/memory
    action chain + the bounded-memory intent of agg_spill.go)."""

    def _big_agg_session(self):
        from tidb_tpu.codec import tablecodec
        from tidb_tpu.sql import Session

        s = Session()
        s.execute("create table sp (id bigint primary key, g bigint, v bigint)")
        rows = ",".join(f"({i}, {i % 500}, {i})" for i in range(3000))
        s.execute("insert into sp values " + rows)
        meta = s.catalog.table("sp")
        for h in range(500, 3000, 500):
            s.store.cluster.split(tablecodec.encode_row_key(meta.table_id, h))
        return s

    def test_degraded_path_completes(self):
        from tidb_tpu.util import metrics

        s = self._big_agg_session()
        want = {}
        for i in range(3000):
            want[i % 500] = want.get(i % 500, 0) + i
        # mesh path doesn't exercise the tracker — force the per-region
        # thread-pool path, with a quota small enough that holding every
        # region's partial table breaches it but one region + the fold
        # accumulator fits
        s.execute("set tidb_enable_tpu_mesh = OFF")
        s.execute("set tidb_mem_quota_query = 30000")
        before = metrics.MEM_DEGRADED_QUERIES.value
        r = s.execute("select g, sum(v) from sp group by g")
        assert metrics.MEM_DEGRADED_QUERIES.value == before + 1, "did not degrade"
        got = {int(x[0].val): int(str(x[1].val).split(".")[0]) for x in r.rows}
        assert got == want

    def test_eviction_action_runs_first(self):
        from tidb_tpu.util import metrics

        s = self._big_agg_session()
        s.execute("select g, sum(v) from sp group by g")  # warm the caches
        s.execute("set tidb_enable_tpu_mesh = OFF")
        s.execute("set tidb_mem_quota_query = 30000")
        before = metrics.MEM_EVICTIONS.value
        s.execute("select g, sum(v) from sp group by g")
        assert metrics.MEM_EVICTIONS.value == before + 1
