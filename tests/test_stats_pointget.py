"""Statistics (ANALYZE -> histograms/TopN/NDV), planner cardinality
estimates, and the PointGet/BatchPointGet fast path
(ref: pkg/statistics, pkg/executor/point_get.go, planner TryFastPlan)."""

import pytest

from tidb_tpu.sql.ranger import Interval
from tidb_tpu.sql.session import Session
from tidb_tpu.sql.stats import build_column_stats, est_selectivity
from tidb_tpu.types import Datum


@pytest.fixture()
def sess():
    s = Session()
    s.execute("CREATE TABLE t (id INT PRIMARY KEY, v INT, s VARCHAR(10))")
    s.execute("INSERT INTO t VALUES " + ",".join(
        f"({i},{i % 10},'{chr(97 + i % 3)}')" for i in range(1, 101)))
    return s


def test_build_column_stats_basic():
    vals = [Datum.i64(i % 5) for i in range(100)] + [Datum.NULL] * 10
    cs = build_column_stats(vals)
    assert cs.null_count == 10
    assert cs.ndv == 5
    assert cs.total == 100
    # every value repeats 20x -> all in TopN
    assert sum(c for _, c in cs.topn) == 100


def test_histogram_buckets_uniform():
    vals = [Datum.i64(i) for i in range(1000)]
    cs = build_column_stats(vals, n_buckets=16)
    assert cs.ndv == 1000 and not cs.topn
    assert sum(b.count for b in cs.buckets) == 1000
    # range selectivity of the lower half ~ 0.5
    sel = est_selectivity(cs, [Interval(None, Datum.i64(500), True, False)])
    assert 0.4 < sel < 0.6


def test_point_selectivity_via_topn():
    vals = [Datum.i64(1)] * 90 + [Datum.i64(i + 10) for i in range(10)]
    cs = build_column_stats(vals)
    sel = est_selectivity(cs, [Interval(Datum.i64(1), Datum.i64(1), True, True)])
    assert 0.85 < sel <= 0.95


def test_analyze_registers_stats(sess):
    sess.execute("ANALYZE TABLE t")
    meta = sess.catalog.table("t")
    st = sess.catalog.stats[meta.table_id]
    assert st.row_count == 100
    assert st.columns["v"].ndv == 10
    assert st.columns["id"].ndv == 100


def test_analyze_specific_columns(sess):
    sess.execute("ANALYZE TABLE t COLUMNS v")
    st = sess.catalog.stats[sess.catalog.table("t").table_id]
    assert "v" in st.columns and "id" not in st.columns


# ---------------------------------------------------------------- pointget


def test_point_get_eq(sess):
    assert sess.execute("SELECT id, v FROM t WHERE id = 42").values() == [[42, 2]]


def test_point_get_missing(sess):
    assert sess.execute("SELECT id FROM t WHERE id = 4242").values() == []


def test_batch_point_get_in(sess):
    got = sess.execute("SELECT id FROM t WHERE id IN (5, 3, 999) ORDER BY id").values()
    assert got == [[3], [5]]


def test_point_get_extra_filter(sess):
    assert sess.execute("SELECT id FROM t WHERE id = 42 AND v > 5").values() == []
    assert sess.execute("SELECT id FROM t WHERE id = 47 AND v > 5").values() == [[47]]


def test_point_get_projection_alias(sess):
    got = sess.execute("SELECT v * 10 AS x FROM t WHERE id = 7")
    assert got.columns == ["x"] and got.values() == [[70]]


def test_point_get_star(sess):
    assert sess.execute("SELECT * FROM t WHERE id = 7").values() == [[7, 7, "b"]]


def test_point_get_in_txn_sees_buffer(sess):
    sess.execute("BEGIN")
    sess.execute("UPDATE t SET v = 777 WHERE id = 7")
    assert sess.execute("SELECT v FROM t WHERE id = 7").values() == [[777]]
    sess.execute("DELETE FROM t WHERE id = 8")
    assert sess.execute("SELECT v FROM t WHERE id = 8").values() == []
    sess.execute("ROLLBACK")
    assert sess.execute("SELECT v FROM t WHERE id = 7").values() == [[7]]


def test_point_get_not_used_for_aggregates(sess):
    # agg forces the full path and still answers correctly
    assert sess.execute("SELECT count(*) FROM t WHERE id = 7").values() == [[1]]


def test_estimate_drives_probe_choice():
    s = Session()
    s.execute("CREATE TABLE big (id INT PRIMARY KEY, k INT)")
    s.execute("CREATE TABLE small (id INT PRIMARY KEY, k INT)")
    s.execute("INSERT INTO big VALUES " + ",".join(f"({i},{i % 7})" for i in range(1, 201)))
    s.execute("INSERT INTO small VALUES (1,1),(2,2),(3,3)")
    s.execute("ANALYZE TABLE big")
    s.execute("ANALYZE TABLE small")
    # with a selective filter on big, either probe choice must still answer right
    got = s.execute(
        "SELECT count(*) FROM big JOIN small ON big.k = small.k WHERE big.id < 8"
    ).values()
    assert got == [[3]]  # ids 1..7, k in {1..6,0}: k=1,2,3 match
