"""Statistics (ANALYZE -> histograms/TopN/NDV), planner cardinality
estimates, and the PointGet/BatchPointGet fast path
(ref: pkg/statistics, pkg/executor/point_get.go, planner TryFastPlan)."""

import pytest

from tidb_tpu.sql.ranger import Interval
from tidb_tpu.sql.session import Session
from tidb_tpu.sql.stats import build_column_stats, est_selectivity
from tidb_tpu.types import Datum


@pytest.fixture()
def sess():
    s = Session()
    s.execute("CREATE TABLE t (id INT PRIMARY KEY, v INT, s VARCHAR(10))")
    s.execute("INSERT INTO t VALUES " + ",".join(
        f"({i},{i % 10},'{chr(97 + i % 3)}')" for i in range(1, 101)))
    return s


def test_build_column_stats_basic():
    vals = [Datum.i64(i % 5) for i in range(100)] + [Datum.NULL] * 10
    cs = build_column_stats(vals)
    assert cs.null_count == 10
    assert cs.ndv == 5
    assert cs.total == 100
    # every value repeats 20x -> all in TopN
    assert sum(c for _, c in cs.topn) == 100


def test_histogram_buckets_uniform():
    vals = [Datum.i64(i) for i in range(1000)]
    cs = build_column_stats(vals, n_buckets=16)
    assert cs.ndv == 1000 and not cs.topn
    assert sum(b.count for b in cs.buckets) == 1000
    # range selectivity of the lower half ~ 0.5
    sel = est_selectivity(cs, [Interval(None, Datum.i64(500), True, False)])
    assert 0.4 < sel < 0.6


def test_point_selectivity_via_topn():
    vals = [Datum.i64(1)] * 90 + [Datum.i64(i + 10) for i in range(10)]
    cs = build_column_stats(vals)
    sel = est_selectivity(cs, [Interval(Datum.i64(1), Datum.i64(1), True, True)])
    assert 0.85 < sel <= 0.95


def test_analyze_registers_stats(sess):
    sess.execute("ANALYZE TABLE t")
    meta = sess.catalog.table("t")
    st = sess.catalog.stats[meta.table_id]
    assert st.row_count == 100
    assert st.columns["v"].ndv == 10
    assert st.columns["id"].ndv == 100


def test_analyze_specific_columns(sess):
    sess.execute("ANALYZE TABLE t COLUMNS v")
    st = sess.catalog.stats[sess.catalog.table("t").table_id]
    assert "v" in st.columns and "id" not in st.columns


# ---------------------------------------------------------------- pointget


def test_point_get_eq(sess):
    assert sess.execute("SELECT id, v FROM t WHERE id = 42").values() == [[42, 2]]


def test_point_get_missing(sess):
    assert sess.execute("SELECT id FROM t WHERE id = 4242").values() == []


def test_batch_point_get_in(sess):
    got = sess.execute("SELECT id FROM t WHERE id IN (5, 3, 999) ORDER BY id").values()
    assert got == [[3], [5]]


def test_point_get_extra_filter(sess):
    assert sess.execute("SELECT id FROM t WHERE id = 42 AND v > 5").values() == []
    assert sess.execute("SELECT id FROM t WHERE id = 47 AND v > 5").values() == [[47]]


def test_point_get_projection_alias(sess):
    got = sess.execute("SELECT v * 10 AS x FROM t WHERE id = 7")
    assert got.columns == ["x"] and got.values() == [[70]]


def test_point_get_star(sess):
    assert sess.execute("SELECT * FROM t WHERE id = 7").values() == [[7, 7, "b"]]


def test_point_get_in_txn_sees_buffer(sess):
    sess.execute("BEGIN")
    sess.execute("UPDATE t SET v = 777 WHERE id = 7")
    assert sess.execute("SELECT v FROM t WHERE id = 7").values() == [[777]]
    sess.execute("DELETE FROM t WHERE id = 8")
    assert sess.execute("SELECT v FROM t WHERE id = 8").values() == []
    sess.execute("ROLLBACK")
    assert sess.execute("SELECT v FROM t WHERE id = 7").values() == [[7]]


def test_point_get_not_used_for_aggregates(sess):
    # agg forces the full path and still answers correctly
    assert sess.execute("SELECT count(*) FROM t WHERE id = 7").values() == [[1]]


def test_estimate_drives_probe_choice():
    s = Session()
    s.execute("CREATE TABLE big (id INT PRIMARY KEY, k INT)")
    s.execute("CREATE TABLE small (id INT PRIMARY KEY, k INT)")
    s.execute("INSERT INTO big VALUES " + ",".join(f"({i},{i % 7})" for i in range(1, 201)))
    s.execute("INSERT INTO small VALUES (1,1),(2,2),(3,3)")
    s.execute("ANALYZE TABLE big")
    s.execute("ANALYZE TABLE small")
    # with a selective filter on big, either probe choice must still answer right
    got = s.execute(
        "SELECT count(*) FROM big JOIN small ON big.k = small.k WHERE big.id < 8"
    ).values()
    assert got == [[3]]  # ids 1..7, k in {1..6,0}: k=1,2,3 match


class TestStatsDepth:
    """CMSketch + NDV consumers (VERDICT r3 missing #7 / next #10)."""

    def test_cmsketch_point_frequency(self):
        from tidb_tpu.sql.stats import CMSketch
        from tidb_tpu.types import Datum

        cm = CMSketch()
        for v, c in ((5, 40), (9, 7), (123456, 1)):
            cm.insert(Datum.i64(v), c)
        # count-min never underestimates
        assert cm.query(Datum.i64(5)) >= 40
        assert cm.query(Datum.i64(9)) >= 7
        # sketch answers a non-TopN point much closer than a uniform guess
        assert cm.query(Datum.i64(123456)) < 40

    def test_analyze_builds_sketch_and_est_uses_it(self):
        import numpy as np

        from tidb_tpu.sql import Session
        from tidb_tpu.sql.ranger import Interval
        from tidb_tpu.sql.stats import est_interval_rows
        from tidb_tpu.types import Datum

        s = Session()
        s.execute("create table cs (v bigint)")
        rng = np.random.default_rng(1)
        # 200 distinct singletons + no repeats -> all non-TopN, sketch-backed
        vals = rng.permutation(5000)[:200]
        s.execute("insert into cs values " + ",".join(f"({int(v)})" for v in vals))
        s.execute("analyze table cs")
        cst = s.catalog.stats[s.catalog.table("cs").table_id].columns["v"]
        assert cst.cmsketch is not None and cst.ndv == 200
        d = Datum.i64(int(vals[0]))
        est = est_interval_rows(cst, Interval(low=d, high=d))
        assert 1 <= est <= 4  # sketch-exact-ish, not bucket-smeared

    def test_ndv_hint_reaches_plan_and_wrong_hint_stays_correct(self):
        """ANALYZE-derived NDV produces the few-groups hint; STALE stats
        (NDV exploded after ANALYZE) must still give exact results via the
        overflow fallback — the mis-estimation regression."""
        from tidb_tpu.parser import parse_one
        from tidb_tpu.sql import Session
        from tidb_tpu.sql.planner import plan_select

        s = Session()
        s.execute("create table g (k bigint, v bigint)")
        s.execute("insert into g values " + ",".join(f"({i % 4}, {i})" for i in range(64)))
        s.execute("analyze table g")
        plan = plan_select(parse_one("select k, count(*) from g group by k"), s.catalog)
        assert plan.small_groups == 16  # NDV 4 (+pow2 floor 16)
        # no stats on expression keys
        plan2 = plan_select(parse_one("select k + 1, count(*) from g group by k + 1"), s.catalog)
        assert plan2.small_groups is None
        # stats go stale: 3000 distinct keys appear AFTER the ANALYZE
        s.execute("insert into g values " + ",".join(f"({i}, {i})" for i in range(100, 3100)))
        r = s.execute("select count(*) from (select k, count(*) as c from g group by k) d")
        assert int(r.rows[0][0].val) == 3004
        r = s.execute("select k, count(*) from g where k < 4 group by k order by k")
        assert [(int(x[0].val), int(x[1].val)) for x in r.rows] == [
            (0, 16), (1, 16), (2, 16), (3, 16)]
