"""Online ADD INDEX with REAL concurrent DML (VERDICT r3 weak #6): the
IndexMeta.state walk drives per-state visibility — not a recorded list.
Failpoints pause the builder between states while writer threads run DML;
ADMIN CHECK TABLE verifies the index afterwards
(ref: pkg/ddl/index.go F1 states; testkit/testfailpoint activation)."""

import threading
import time

import pytest

from tidb_tpu.sql import Session
from tidb_tpu.util import failpoint


def _mk(n=60):
    s = Session()
    s.execute("create table t (id bigint primary key, v bigint)")
    s.execute("insert into t values " + ",".join(f"({i}, {i * 3})" for i in range(n)))
    return s


class TestOnlineAddIndex:
    def test_states_recorded_and_index_consistent(self):
        s = _mk()
        s.execute("create index iv on t (v)")
        job = s.catalog.ddl_jobs.jobs[-1]
        assert job.states_seen == ["delete_only", "write_only", "write_reorg", "public"]
        assert s.catalog.table("t").indices[0].state == "public"
        s.execute("admin check table t")

    def test_dml_during_each_state_keeps_index_consistent(self):
        """Writer threads INSERT/UPDATE/DELETE while the builder is paused
        inside delete_only, write_only, and write_reorg. The final index
        must agree with the final rows (ADMIN CHECK TABLE)."""
        s = _mk()
        store, catalog = s.store, s.catalog
        errors: list = []

        def writer(sql):
            w = Session(store=store, catalog=catalog)
            for _ in range(40):
                try:
                    w.execute(sql)
                    return
                except Exception as exc:  # schema-version retry (real TiDB
                    # behavior: "Information schema is changed")
                    if "schema" in str(exc).lower() or "conflict" in str(exc).lower():
                        time.sleep(0.005)
                        continue
                    errors.append(exc)
                    return
            errors.append(RuntimeError(f"retries exhausted: {sql}"))

        def run_writers(sqls):
            ts = [threading.Thread(target=writer, args=(q,)) for q in sqls]
            for t_ in ts:
                t_.start()
            for t_ in ts:
                t_.join()

        state_dml = {
            # delete_only: inserts must NOT add entries; deletes must drop them
            "ddl_index_delete_only": [
                "insert into t values (1001, 999)",
                "delete from t where id = 5",
            ],
            # write_only: DML double-writes entries the backfill won't see
            "ddl_index_write_only": [
                "insert into t values (1002, 998)",
                "update t set v = 777 where id = 10",
            ],
            # write_reorg (before the backfill scan): more concurrent churn
            "ddl_index_write_reorg": [
                "insert into t values (1003, 997)",
                "delete from t where id = 20",
                "update t set v = 555 where id = 30",
            ],
        }
        for name, sqls in state_dml.items():
            failpoint.enable(name, lambda sqls=sqls: run_writers(sqls))
        try:
            s.execute("create index iv on t (v)")
        finally:
            for name in state_dml:
                failpoint.disable(name)
        assert not errors, errors
        # the index agrees with the table after all that churn
        s.execute("admin check table t")
        # and the reader path actually uses it for the right answers
        meta = s.catalog.table("t")
        assert meta.indices[0].state == "public"
        r = s.execute("select id from t where v = 777")
        assert [int(x[0].val) for x in r.rows] == [10]
        r = s.execute("select count(*) from t where v = 999")
        assert int(r.rows[0][0].val) == 1
        assert int(s.execute("select count(*) from t").rows[0][0].val) == 60 + 3 - 2

    def test_delete_only_index_invisible_to_dml_writes(self):
        """While an index is in delete_only, INSERTs add no entries (they
        would be dangling after a failed build rolls the metadata back)."""
        from tidb_tpu.codec import tablecodec

        s = _mk(8)
        meta = s.catalog.table("t")
        seen_entries = []

        def probe():
            im = meta.indices[-1]
            w = Session(store=s.store, catalog=s.catalog)
            w.execute("insert into t values (500, 12345)")
            prefix = tablecodec.encode_index_key(meta.table_id, im.index_id, [])
            ts = s.store.next_ts()
            seen_entries.append(
                sum(1 for _ in s.store.kv.scan(prefix, prefix + b"\xff", ts))
            )

        failpoint.enable("ddl_index_delete_only", probe)
        try:
            s.execute("create index iv on t (v)")
        finally:
            failpoint.disable("ddl_index_delete_only")
        assert seen_entries == [0], seen_entries  # no entry written in delete_only
        s.execute("admin check table t")  # backfill picked the row up later
        r = s.execute("select id from t where v = 12345")
        assert [int(x[0].val) for x in r.rows] == [500]

    def test_failed_build_rolls_back_metadata(self):
        s = _mk(8)
        s.execute("insert into t values (100, 3)")  # duplicate v with id=1
        with pytest.raises(Exception, match="duplicate"):
            s.execute("create unique index uv on t (v)")
        assert s.catalog.table("t").indices == []
        job = s.catalog.ddl_jobs.jobs[-1]
        assert job.state == "cancelled" and "duplicate" in job.error
