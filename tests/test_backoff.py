"""Backoffer: per-kind exponential budgets with equal jitter, the
tidb_backoff_weight scaling, deadline clamping, and KILL-mid-backoff
(ISSUE 6; ref: tikv/client-go retry/backoff.go + TiDB BackOffWeight)."""

import random

import pytest

from tidb_tpu.distsql.runaway import QueryKilledError, RunawayChecker
from tidb_tpu.util import metrics
from tidb_tpu.util.backoff import CONFIGS, Backoffer, BackoffExhausted


class FakeClock:
    """Deterministic time: sleep() advances now() — no wall-clock in the
    schedule assertions."""

    def __init__(self):
        self.t = 0.0
        self.sleeps: list[float] = []

    def now(self) -> float:
        return self.t

    def sleep(self, s: float) -> None:
        self.sleeps.append(s)
        self.t += s


def make(budget_ms=10_000, weight=1, checker=None, seed=1):
    clk = FakeClock()
    b = Backoffer(budget_ms=budget_ms, weight=weight, checker=checker,
                  rng=random.Random(seed), sleep_fn=clk.sleep, now_fn=clk.now)
    return b, clk


def test_exponential_growth_capped_with_equal_jitter():
    b, _ = make()
    cfg = CONFIGS["region_miss"]
    for attempt in range(12):
        slept = b.backoff("region_miss")
        raw = min(cfg.base_ms * 2 ** attempt, cfg.cap_ms)
        # equal jitter: uniform[raw/2, raw]
        assert raw / 2 <= slept <= raw + 1e-9
    assert b.attempts["region_miss"] == 12


def test_budget_scales_with_backoff_weight_and_exhausts_per_task():
    b, _ = make(budget_ms=20, weight=2)  # 40ms effective
    total = 0.0
    with pytest.raises(BackoffExhausted) as ei:
        for _ in range(50):
            total += b.backoff("server_busy")
    assert ei.value.kind == "server_busy"
    assert total <= 40.0
    # weight 0: no sleep budget at all — first backoff raises
    b0, _ = make(budget_ms=200, weight=0)
    with pytest.raises(BackoffExhausted):
        b0.backoff("region_miss")


def test_per_kind_budgets_are_independent_but_share_the_total():
    b, _ = make()
    b.backoff("region_miss")
    b.backoff("server_busy")
    # each kind restarts its own exponent: second region_miss is attempt 1
    assert b.attempts == {"region_miss": 1, "server_busy": 1}
    assert b.total_ms > 0


def test_server_suggested_backoff_is_a_floor():
    b, _ = make(seed=3)
    slept = b.backoff("server_busy", suggested_ms=77)
    assert slept >= 77


def test_sleep_never_passes_the_checker_deadline():
    clk = FakeClock()
    checker = RunawayChecker(max_execution_ms=50, now_fn=clk.now)
    b = Backoffer(budget_ms=10_000, weight=1, checker=checker,
                  rng=random.Random(1), sleep_fn=clk.sleep, now_fn=clk.now)
    slept = b.sleep(500, "store_unavailable")
    assert slept <= 50.0 + 1e-9  # clamped to the deadline, not the ask
    assert clk.t <= 0.0501


def test_kill_query_interrupts_mid_backoff():
    clk = FakeClock()
    checker = RunawayChecker(max_execution_ms=0, now_fn=clk.now)
    kills_after = [3]

    def killing_sleep(s):
        clk.sleep(s)
        kills_after[0] -= 1
        if kills_after[0] == 0:
            checker.kill()

    b = Backoffer(budget_ms=10_000, weight=1, checker=checker,
                  rng=random.Random(1), sleep_fn=killing_sleep, now_fn=clk.now)
    with pytest.raises(QueryKilledError):
        b.sleep(500, "server_busy")
    # died mid-sleep: only the slices before the kill actually ran
    assert sum(clk.sleeps) < 500 / 1000.0
    assert len(clk.sleeps) == 3


def test_backoff_metric_and_span_attribution():
    from tidb_tpu.util import tracing

    before = metrics.BACKOFF_SECONDS.labels("not_leader").value
    b, _ = make()
    with tracing.trace("t") as root:
        with tracing.span("distsql.cop_task") as sp:
            slept = b.backoff("not_leader")
        assert sp.attrs["backoff_ms"] == pytest.approx(slept, abs=0.02)
    assert root is not None
    after = metrics.BACKOFF_SECONDS.labels("not_leader").value
    assert after - before == pytest.approx(slept / 1000.0, abs=1e-6)


def test_unknown_kind_gets_a_default_schedule():
    b, _ = make()
    assert b.backoff("mystery_kind") > 0  # total, no KeyError
