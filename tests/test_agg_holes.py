"""Device-kernel paths closed in round 2 (VERDICT weak #6 / next #8):
string min/max + first_row (GatherState arg-extreme), DISTINCT aggregates,
string filter truthiness, honest per-executor exec summaries, and the
partial->merge roundtrip for the gather-served aggregates."""

import numpy as np
import pytest

from tidb_tpu.chunk import Chunk
from tidb_tpu.exec import (
    Aggregation,
    ColumnInfo,
    DAGRequest,
    Selection,
    TableScan,
    run_dag_on_chunk,
    run_dag_reference,
)
from tidb_tpu.exec.executor import datum_group_key
from tidb_tpu.expr import AggDesc, AggMode, col, func, lit
from tidb_tpu.types import Datum, MyDecimal, new_decimal, new_longlong, new_varchar

BOOL = new_longlong(notnull=True)
FTS = [new_longlong(), new_varchar(12), new_decimal(10, 2), new_longlong(unsigned=True)]


def make_chunk(n=200, seed=3, null_p=0.06):
    rng = np.random.default_rng(seed)
    words = ["alpha", "beta", "Gamma", "delta", "", "zz", "omega9", "a", "ab"]
    rows = []
    for h in range(n):
        def maybe(d):
            return Datum.NULL if rng.random() < null_p else d

        rows.append([
            maybe(Datum.i64(int(rng.integers(0, 6)))),
            maybe(Datum.string(words[int(rng.integers(len(words)))])),
            maybe(Datum.dec(MyDecimal(f"{int(rng.integers(-5000, 5000))/100:.2f}"))),
            maybe(Datum.u64(int(rng.integers(0, 2**63 - 1, dtype=np.int64)) + int(rng.integers(0, 3)))),
        ])
    return Chunk.from_rows(FTS, rows)


def scan():
    return TableScan(7, tuple(ColumnInfo(i + 1, ft) for i, ft in enumerate(FTS)))


C = lambda i: col(i, FTS[i])


def canon_rows(rows):
    return sorted(tuple(datum_group_key(d) for d in r) for r in rows)


def assert_parity(dag, ch, **kw):
    dev = run_dag_on_chunk(dag, ch, **kw)
    ref = run_dag_reference(dag, ch)
    assert canon_rows(dev.rows()) == canon_rows(ref), (
        f"\ndevice={canon_rows(dev.rows())[:4]}\nref   ={canon_rows(ref)[:4]}"
    )
    return dev


class TestStringMinMax:
    def test_grouped(self):
        ch = make_chunk()
        agg = Aggregation(
            group_by=(C(0),),
            aggs=(AggDesc("min", (C(1),)), AggDesc("max", (C(1),)), AggDesc("count", ())),
        )
        assert_parity(DAGRequest((scan(), agg), output_offsets=(0, 1, 2, 3)), ch)

    def test_scalar(self):
        ch = make_chunk(90)
        agg = Aggregation(group_by=(), aggs=(AggDesc("min", (C(1),)), AggDesc("max", (C(1),))))
        assert_parity(DAGRequest((scan(), agg), output_offsets=(0, 1)), ch)

    def test_all_null_group(self):
        rows = [[Datum.i64(1), Datum.NULL], [Datum.i64(1), Datum.NULL], [Datum.i64(2), Datum.string("x")]]
        fts = [FTS[0], FTS[1]]
        ch = Chunk.from_rows(fts, rows)
        s = TableScan(7, (ColumnInfo(1, fts[0]), ColumnInfo(2, fts[1])))
        agg = Aggregation(group_by=(col(0, fts[0]),), aggs=(AggDesc("min", (col(1, fts[1]),)),))
        assert_parity(DAGRequest((s, agg), output_offsets=(0, 1)), ch)


class TestFirstRow:
    def test_first_row_is_earliest_row(self):
        """Deterministic parity: device first_row == oracle's first in row
        order (not merely 'any group member')."""
        ch = make_chunk(150)
        agg = Aggregation(
            group_by=(C(0),),
            aggs=(AggDesc("first_row", (C(1),)), AggDesc("first_row", (C(2),)), AggDesc("first_row", (C(3),))),
        )
        assert_parity(DAGRequest((scan(), agg), output_offsets=(0, 1, 2, 3)), ch)

    def test_scalar_first_row_string(self):
        ch = make_chunk(40)
        agg = Aggregation(group_by=(), aggs=(AggDesc("first_row", (C(1),)),))
        assert_parity(DAGRequest((scan(), agg), output_offsets=(0,)), ch)

    def test_partial_then_merge_roundtrip(self):
        """Partial1 on two halves, concat states, Final merge == Complete.
        Covers the merge-mode first_row [has,value] routing (ADVICE medium)
        and string min/max state merge."""
        ch = make_chunk(160)
        rows = ch.rows()
        halves = [Chunk.from_rows(FTS, rows[:80]), Chunk.from_rows(FTS, rows[80:])]
        partial = Aggregation(
            group_by=(C(0),),
            aggs=(AggDesc("first_row", (C(1),)), AggDesc("min", (C(1),)), AggDesc("first_row", (C(2),))),
            partial=True,
        )
        # partial schema: [fr.has, fr.val(str), min.val(str), fr2.has, fr2.val(dec), g]
        pdag = DAGRequest((scan(), partial), output_offsets=tuple(range(6)))
        parts = [run_dag_on_chunk(pdag, h) for h in halves]
        stacked = Chunk.concat(parts)
        pfts = stacked.field_types()
        merge_agg = Aggregation(
            group_by=(col(5, pfts[5]),),
            aggs=(
                AggDesc("first_row", (col(0, pfts[0]), col(1, pfts[1])), mode=AggMode.Final),
                AggDesc("min", (col(2, pfts[2]),), mode=AggMode.Final),
                AggDesc("first_row", (col(3, pfts[3]), col(4, pfts[4])), mode=AggMode.Final),
            ),
            merge=True,
        )
        root = DAGRequest(
            (TableScan(0, tuple(ColumnInfo(i, ft) for i, ft in enumerate(pfts))), merge_agg),
            output_offsets=(0, 1, 2, 3),
        )
        final = run_dag_on_chunk(root, stacked)
        complete = Aggregation(group_by=(C(0),), aggs=(AggDesc("first_row", (C(1),)), AggDesc("min", (C(1),)), AggDesc("first_row", (C(2),))))
        oracle = run_dag_reference(DAGRequest((scan(), complete), output_offsets=(0, 1, 2, 3)), ch)
        assert canon_rows(final.rows()) == canon_rows(oracle)


class TestDistinct:
    def test_grouped_count_sum_avg_distinct(self):
        ch = make_chunk(250)
        agg = Aggregation(
            group_by=(C(0),),
            aggs=(
                AggDesc("count", (C(2),), distinct=True),
                AggDesc("sum", (C(2),), distinct=True),
                AggDesc("avg", (C(2),), distinct=True),
                AggDesc("count", (C(2),)),  # non-distinct alongside
            ),
        )
        assert_parity(DAGRequest((scan(), agg), output_offsets=(0, 1, 2, 3, 4)), ch)

    def test_count_distinct_multi_arg(self):
        ch = make_chunk(180)
        agg = Aggregation(group_by=(C(0),), aggs=(AggDesc("count", (C(1), C(2)), distinct=True),))
        assert_parity(DAGRequest((scan(), agg), output_offsets=(0, 1)), ch)

    def test_scalar_distinct(self):
        ch = make_chunk(120)
        agg = Aggregation(group_by=(), aggs=(AggDesc("count", (C(1),), distinct=True), AggDesc("sum", (C(2),), distinct=True)))
        assert_parity(DAGRequest((scan(), agg), output_offsets=(0, 1)), ch)

    def test_distinct_string_count(self):
        ch = make_chunk(140)
        agg = Aggregation(group_by=(C(0),), aggs=(AggDesc("count", (C(1),), distinct=True),))
        assert_parity(DAGRequest((scan(), agg), output_offsets=(0, 1)), ch)

    def test_distinct_merge_raises(self):
        ch = make_chunk(30)
        agg = Aggregation(
            group_by=(C(0),),
            aggs=(AggDesc("sum", (C(2),), distinct=True, mode=AggMode.Final),),
            merge=True,
        )
        dag = DAGRequest((scan(), agg), output_offsets=(0, 1))
        with pytest.raises(NotImplementedError):
            run_dag_on_chunk(dag, ch)


class TestStringTruthiness:
    def test_string_filter(self):
        """WHERE <varchar col>: numeric-prefix truthiness (MySQL)."""
        fts = [new_longlong(), new_varchar(10)]
        vals = ["1", "0", "0.5x", "abc", "", " 12ab", "-0.0", "1e2", ".0", "2e-1", None, "+3"]
        rows = [[Datum.i64(i), Datum.NULL if v is None else Datum.string(v)] for i, v in enumerate(vals)]
        ch = Chunk.from_rows(fts, rows)
        s = TableScan(7, (ColumnInfo(1, fts[0]), ColumnInfo(2, fts[1])))
        dag = DAGRequest((s, Selection((col(1, fts[1]),))), output_offsets=(0,))
        dev = run_dag_on_chunk(dag, ch)
        ref = run_dag_reference(dag, ch)
        got = sorted(r[0].val for r in dev.rows())
        want = sorted(r[0].val for r in ref)
        assert got == want == [0, 2, 5, 7, 9, 11]


def test_exec_summary_rows_are_real():
    """Per-executor produced-row counts come from the fused program."""
    from tidb_tpu.store import TPUStore, CopRequest
    from tidb_tpu.codec import tablecodec
    from tidb_tpu.distsql import full_table_ranges

    store = TPUStore()
    tid = 9
    fts = [new_longlong()]
    n = 50
    for h in range(n):
        store.put_row(tid, h, [1], [Datum.i64(h)], ts=5)
    s = TableScan(tid, (ColumnInfo(1, fts[0]),))
    pred = func("lt", BOOL, col(0, fts[0]), lit(10, new_longlong()))
    agg = Aggregation(group_by=(), aggs=(AggDesc("count", ()),))
    dag = DAGRequest((s, Selection((pred,)), agg), output_offsets=(0,))
    region = store.cluster.regions_in_range(b"", b"\xff" * 20)[0]
    resp = store.coprocessor(CopRequest(dag, full_table_ranges(tid), start_ts=100, region_id=region.region_id, region_epoch=region.epoch))
    assert resp.other_error is None and resp.region_error is None
    rows_per_exec = [sm.num_produced_rows for sm in resp.exec_summaries]
    assert rows_per_exec == [50, 10, 1]
