"""Out-of-capacity (spill-analog) execution (VERDICT r3 missing #2): when
the overflow retry range exhausts, the input host-partitions and the SAME
device program runs per partition — kernels only, no row-at-a-time oracle
(ref: pkg/executor/aggregate/agg_spill.go, join/hash_join_spill.go)."""

import numpy as np
import pytest

from tidb_tpu.chunk import Chunk
from tidb_tpu.exec import Aggregation, ColumnInfo, DAGRequest, Join, Selection, TableScan
from tidb_tpu.exec.executor import run_dag_on_chunks, run_dag_reference
from tidb_tpu.expr import AggDesc, col, func, lit
from tidb_tpu.types import Datum, new_longlong
from tidb_tpu.util import metrics


def _chunk(vals, fts):
    rows = [[Datum.i64(int(v)) for v in r] for r in vals]
    return Chunk.from_rows(fts, rows)


class TestSpillPartitioned:
    def test_group_overflow_partitions_by_key_hash(self):
        """500 groups through a capacity range that tops out at 256: the
        key-hash partition must produce exact results without the oracle."""
        LL = new_longlong()
        fts = [LL, LL]
        n = 2000
        rng = np.random.default_rng(5)
        g = rng.integers(0, 500, n)
        v = rng.integers(0, 1000, n)
        ch = _chunk(list(zip(g, v)), fts)
        scan = TableScan(1, (ColumnInfo(1, LL), ColumnInfo(2, LL)))
        agg = Aggregation(group_by=(col(0, LL),), aggs=(AggDesc("count", ()), AggDesc("sum", (col(1, LL),))))
        dag = DAGRequest((scan, agg), output_offsets=(0, 1, 2))
        before = metrics.SPILL_PARTITIONS.value
        # max_retries=0 pins the SPILL machinery: with retries allowed the
        # ladder's need hint (the sort kernel's true group count) would
        # resolve 500 groups on the second dispatch without ever spilling
        out = run_dag_on_chunks(dag, [ch], group_capacity=4, max_retries=0,
                                oracle_fallback=False)
        assert metrics.SPILL_PARTITIONS.value > before, "spill path did not run"
        ref = run_dag_reference(dag, [ch])
        got = sorted((int(r[0].val), int(str(r[1].val)), int(r[2].val)) for r in out.rows())
        want = sorted((int(r[0].val), int(str(r[1].val)), int(r[2].val)) for r in ref)
        assert got == want

    def test_partial_agg_row_split(self):
        """Partial-mode aggregation spills by plain row halving (the Final
        merge combines duplicate groups downstream)."""
        LL = new_longlong()
        fts = [LL, LL]
        n = 1500
        rng = np.random.default_rng(6)
        ch = _chunk(list(zip(rng.integers(0, 400, n), rng.integers(0, 9, n))), fts)
        scan = TableScan(1, (ColumnInfo(1, LL), ColumnInfo(2, LL)))
        agg = Aggregation(group_by=(col(0, LL),), aggs=(AggDesc("count", ()),), partial=True)
        dag = DAGRequest((scan, agg), output_offsets=(0, 1))
        out = run_dag_on_chunks(dag, [ch], group_capacity=4, oracle_fallback=False)
        # partial outputs may repeat a group (once per part); totals must match
        totals: dict = {}
        for r in out.rows():
            totals[int(r[1].val)] = totals.get(int(r[1].val), 0) + int(r[0].val)
        ref: dict = {}
        for r in run_dag_reference(dag, [ch]):
            ref[int(r[1].val)] = ref.get(int(r[1].val), 0) + int(r[0].val)
        assert totals == ref

    def test_join_fanout_overflow_halves_probe(self):
        """Join fan-out beyond the retry range: the probe side halves and
        output slices concatenate in probe order."""
        LL = new_longlong()
        build_vals = [[k] for k in range(64) for _ in range(16)]  # 16x fan-out
        probe_vals = [[k % 64] for k in range(256)]
        bch = _chunk(build_vals, [LL])
        pch = _chunk(probe_vals, [LL])
        ps = TableScan(1, (ColumnInfo(1, LL),))
        bs = TableScan(2, (ColumnInfo(1, LL),))
        join = Join(build=(bs,), probe_keys=(col(0, LL),), build_keys=(col(0, LL),))
        dag = DAGRequest((ps, join), output_offsets=(0, 1))
        # out = 256*16 = 4096; jc pinned at 1024 (max_retries=0) -> two
        # probe halvings bring per-part output to 1024
        before = metrics.SPILL_PARTITIONS.value
        out = run_dag_on_chunks(dag, [pch, bch], group_capacity=16, max_retries=0, oracle_fallback=False)
        assert metrics.SPILL_PARTITIONS.value > before
        assert out.num_rows() == 256 * 16

    def test_depth_exhaustion_raises_without_oracle(self):
        """A shape with no safe decomposition raises instead of silently
        falling back when oracle_fallback=False."""
        from tidb_tpu.exec.executor import OverflowRetryError
        from tidb_tpu.exec.dag import TopN

        LL = new_longlong()
        ch = _chunk([[i] for i in range(8)], [LL])
        scan = TableScan(1, (ColumnInfo(1, LL),))
        # group_concat is host-only -> NotImplementedError path, not spill
        agg = Aggregation(group_by=(col(0, LL),), aggs=(AggDesc("group_concat", (col(0, LL),)),))
        dag = DAGRequest((scan, agg), output_offsets=(0,))
        with pytest.raises(Exception):
            run_dag_on_chunks(dag, [ch], group_capacity=4, oracle_fallback=False)
