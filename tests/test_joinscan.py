"""Parity: the Pallas post-sort segscan path of packed_join_groupsum vs
the XLA scan path, in interpret mode on CPU (ref coverage mirrors
tests/test_joinagg.py; the compiled path runs on TPU via bench.py)."""

import numpy as np
import pytest

import jax


@pytest.fixture(autouse=True, scope="module")
def _fresh_jax_caches():
    """jax 0.4.x: jitted subfunctions cached by earlier tests under a
    different x64 weak-type state poison the Pallas kernels' lowering
    (i32/i64 verifier mismatch). A clean cache per kernel module keeps
    these hermetic; newer jax keys the cache correctly."""
    jax.clear_caches()
import jax.numpy as jnp

from tidb_tpu.chunk import Chunk
from tidb_tpu.exec import run_dag_on_chunks, run_dag_reference
from tidb_tpu.types import Datum, new_longlong

from test_joinagg import _dag, _mk, canon, LL
from tidb_tpu.expr import AggDesc, col


@pytest.fixture(autouse=True)
def _interpret(monkeypatch):
    monkeypatch.setenv("TIDB_TPU_PALLAS", "interpret")
    # retrace every program: a cache hit from a sibling module would skip
    # the traced-function spy below
    from tidb_tpu.exec.executor import DEFAULT_PROGRAM_CACHE

    DEFAULT_PROGRAM_CACHE._cache.clear()


def _spy_segscan(monkeypatch):
    import tidb_tpu.ops.joinscan as js

    calls = []
    orig = js.postsort_segscan

    def spy(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    monkeypatch.setattr(js, "postsort_segscan", spy)
    return calls


def test_segscan_parity_basic(monkeypatch):
    calls = _spy_segscan(monkeypatch)
    rng = np.random.default_rng(0)
    n, nb = 700, 50
    probe = _mk([LL, LL], [rng.integers(0, 64, n), rng.integers(-1000, 1000, n)])
    build = _mk([LL, LL], [np.arange(nb), rng.integers(0, 9, nb)])
    dag = _dag([AggDesc("sum", (col(1, LL),)), AggDesc("count", ()),
                AggDesc("avg", (col(1, LL),))])
    got = run_dag_on_chunks(dag, [probe, build], group_capacity=256)
    want = run_dag_reference(dag, [probe, build])
    assert canon(got.rows()) == canon(want)
    assert calls, "segscan path did not engage"


def test_segscan_null_probe_keys(monkeypatch):
    calls = _spy_segscan(monkeypatch)
    probe = _mk([LL, LL], [[1, None, 2, None, 1, 3], [10, 20, 30, 40, 50, 60]])
    build = _mk([LL, LL], [[1, 2, 3], [7, 8, 9]])
    dag = _dag([AggDesc("sum", (col(1, LL),)), AggDesc("count", ())])
    got = run_dag_on_chunks(dag, [probe, build], group_capacity=64)
    want = run_dag_reference(dag, [probe, build])
    assert canon(got.rows()) == canon(want)
    assert calls


def test_segscan_unmatched_and_negative(monkeypatch):
    calls = _spy_segscan(monkeypatch)
    rng = np.random.default_rng(2)
    n = 900
    probe = _mk([LL, LL], [rng.integers(-40, 40, n), rng.integers(-10**6, 10**6, n)])
    build = _mk([LL, LL], [np.arange(0, 20), rng.integers(0, 9, 20)])
    dag = _dag([AggDesc("sum", (col(1, LL),)), AggDesc("count", ())])
    got = run_dag_on_chunks(dag, [probe, build], group_capacity=256)
    want = run_dag_reference(dag, [probe, build])
    assert canon(got.rows()) == canon(want)
    assert calls


def test_segscan_dup_build_falls_back(monkeypatch):
    calls = _spy_segscan(monkeypatch)
    rng = np.random.default_rng(3)
    probe = _mk([LL, LL], [rng.integers(0, 8, 200), rng.integers(0, 50, 200)])
    build = _mk([LL, LL], [[1, 1, 2, 3], [7, 8, 9, 10]])  # dup build keys
    dag = _dag([AggDesc("sum", (col(1, LL),)), AggDesc("count", ())])
    got = run_dag_on_chunks(dag, [probe, build], group_capacity=256)
    want = run_dag_reference(dag, [probe, build])
    assert canon(got.rows()) == canon(want)


def test_segscan_min_key_and_no_pins(monkeypatch):
    """Review regressions: (a) key -1 must not match the prev-key sentinel;
    (b) the max-key group must survive when every row is usable (the final
    boundary emission lands on the pad element)."""
    calls = _spy_segscan(monkeypatch)
    probe = _mk([LL, LL], [[-1, 0, 1, 7, 7], [100, 10, 20, 30, 40]])
    build = _mk([LL, LL], [[0, 1, 7], [5, 6, 8]])  # no -1: unmatched probe
    dag = _dag([AggDesc("sum", (col(1, LL),)), AggDesc("count", ())])
    got = run_dag_on_chunks(dag, [probe, build], group_capacity=64)
    want = run_dag_reference(dag, [probe, build])
    assert canon(got.rows()) == canon(want)
    assert calls
