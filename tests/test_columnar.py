"""HTAP columnar replica (ISSUE 12): delta+stable layers fed by the
changefeed, background compaction on the pd.columnar tick, engine
routing via tidb_isolation_read_engines with typed-staleness fallback,
the mid-feed DDL guard, the columnar/* failpoints, and the HTAP chaos
acceptance (ref: TiDB VLDB'20's TiFlash + DeltaTree design)."""

import os
import sys
import threading
import time

import pytest

from tidb_tpu.sql.session import Session, SQLError
from tidb_tpu.util import failpoint, metrics

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))


def norm(v):
    return None if v is None else str(v)


def make_replicated(rows=40):
    s = Session()
    s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT, g BIGINT)")
    if rows:
        s.execute("INSERT INTO t VALUES " + ",".join(
            f"({i},{(i * 7) % 13},{i % 3})" for i in range(rows)))
    s.execute("ALTER TABLE t SET COLUMNAR REPLICA 1")
    s.store.pd.tick()  # birth incremental scan + first compaction
    return s


def both_engines(s, sql):
    """(routed result, row-store result) back to back — single-threaded,
    so both see the same snapshot."""
    s.execute("SET tidb_isolation_read_engines = 'tpu,columnar'")
    got = s.execute(sql).values()
    s.execute("SET tidb_isolation_read_engines = 'tpu'")
    want = s.execute(sql).values()
    s.execute("SET tidb_isolation_read_engines = 'tpu,columnar'")
    return got, want


# ------------------------------------------------------------ engine routing

class TestEngineRouting:
    def test_aggregate_scan_rides_the_replica_and_matches_row_store(self):
        s = make_replicated()
        sc0 = metrics.COLUMNAR_SCANS.value
        got, want = both_engines(
            s, "SELECT g, count(*), sum(v) FROM t GROUP BY g ORDER BY g")
        assert got == want
        assert metrics.COLUMNAR_SCANS.value == sc0 + 1

    def test_topn_rides_the_replica(self):
        s = make_replicated()
        sc0 = metrics.COLUMNAR_SCANS.value
        got, want = both_engines(
            s, "SELECT id, v FROM t ORDER BY v DESC, id LIMIT 7")
        assert got == want
        assert metrics.COLUMNAR_SCANS.value == sc0 + 1

    def test_range_scan_with_agg_routes_and_agrees(self):
        s = make_replicated()
        sc0 = metrics.COLUMNAR_SCANS.value
        got, want = both_engines(
            s, "SELECT count(*), max(v) FROM t WHERE id BETWEEN 5 AND 25")
        assert got == want
        assert metrics.COLUMNAR_SCANS.value > sc0

    def test_point_get_and_row_local_scans_never_route(self):
        s = make_replicated()
        sc0 = metrics.COLUMNAR_SCANS.value
        s.execute("SELECT * FROM t WHERE id = 3")
        s.execute("SELECT id, v FROM t WHERE v > 4 ORDER BY id")
        assert metrics.COLUMNAR_SCANS.value == sc0

    def test_in_txn_reads_stay_on_the_row_store(self):
        s = make_replicated()
        sc0 = metrics.COLUMNAR_SCANS.value
        s.execute("BEGIN")
        r = s.execute("SELECT count(*) FROM t").values()
        s.execute("COMMIT")
        assert r == [[40]]
        assert metrics.COLUMNAR_SCANS.value == sc0

    def test_partitioned_table_routes_across_pids(self):
        s = Session()
        s.execute("CREATE TABLE pt (a BIGINT PRIMARY KEY, v BIGINT) "
                  "PARTITION BY HASH(a) PARTITIONS 3")
        s.execute("INSERT INTO pt VALUES " + ",".join(
            f"({i},{i % 11})" for i in range(30)))
        s.execute("ALTER TABLE pt SET COLUMNAR REPLICA 1")
        s.store.pd.tick()
        sc0 = metrics.COLUMNAR_SCANS.value
        got, want = both_engines(s, "SELECT count(*), sum(v) FROM pt")
        assert got == want
        assert metrics.COLUMNAR_SCANS.value == sc0 + 1

    def test_join_probe_on_replica_matches(self):
        s = make_replicated()
        s.execute("CREATE TABLE d (g BIGINT PRIMARY KEY, name VARCHAR(8))")
        s.execute("INSERT INTO d VALUES (0,'a'),(1,'b'),(2,'c')")
        s.store.pd.tick()
        got, want = both_engines(
            s, "SELECT t.g, d.name, count(*) FROM t JOIN d ON t.g = d.g "
               "GROUP BY t.g, d.name ORDER BY t.g")
        assert got == want

    def test_explain_analyze_keeps_the_cop_path(self):
        s = make_replicated()
        sc0 = metrics.COLUMNAR_SCANS.value
        r = s.execute("EXPLAIN ANALYZE SELECT g, count(*) FROM t GROUP BY g")
        assert metrics.COLUMNAR_SCANS.value == sc0  # attribution needs cop
        assert any("push" in str(row[0]) for row in r.values())

    def test_trace_has_columnar_scan_span(self):
        s = make_replicated()
        r = s.execute("TRACE SELECT g, count(*) FROM t GROUP BY g").values()
        assert any("columnar.scan" in str(row[0]) for row in r)


# ------------------------------------- sysvar validation (ISSUE 12 satellite)

class TestIsolationReadEnginesSysvar:
    def test_unknown_engine_rejected_at_set_time(self):
        s = Session()
        with pytest.raises(SQLError, match="unknown isolation read engine"):
            s.execute("SET tidb_isolation_read_engines = 'bogus'")
        with pytest.raises(SQLError, match="unknown isolation read engine"):
            s.execute("SET GLOBAL tidb_isolation_read_engines = 'tpu,nope'")

    def test_reference_names_normalize_to_this_builds_engines(self):
        s = Session()
        s.execute("SET tidb_isolation_read_engines = 'tikv,tiflash,tidb'")
        assert s.execute("SELECT @@tidb_isolation_read_engines").values() == [["tpu,columnar"]]
        s.execute("SET SESSION tidb_isolation_read_engines = 'TiFlash'")
        assert s.execute("SELECT @@tidb_isolation_read_engines").values() == [["columnar"]]

    def test_empty_engine_list_rejected(self):
        s = Session()
        with pytest.raises(SQLError, match="at least one engine"):
            s.execute("SET tidb_isolation_read_engines = ''")

    def test_default_is_normalized(self):
        s = Session()
        assert s.execute("SELECT @@tidb_isolation_read_engines").values() == [["tpu,columnar"]]


# --------------------------------------------- mounter -> scan parity matrix

class TestTypeMatrixParity:
    def test_every_column_type_survives_delta_compaction_and_scan(self):
        """mounter -> delta -> compaction -> stable scan reproduces the
        row store byte for byte over the full type matrix, NULLs
        included (ISSUE 12 satellite; the cdc mounter-parity test's
        columnar sibling)."""
        s = Session()
        s.execute("""CREATE TABLE m (
            id BIGINT PRIMARY KEY, i INT, u BIGINT UNSIGNED, f FLOAT,
            d DOUBLE, dec DECIMAL(10,2), dt DATETIME, da DATE,
            j JSON, e ENUM('a','b','c'), cs VARCHAR(16) COLLATE utf8mb4_general_ci,
            vb VARBINARY(16))""")
        s.execute("INSERT INTO m VALUES "
                  "(1, -5, 18446744073709551610, 1.5, 2.25, '12345.67', "
                  "'2024-02-29 12:34:56', '2024-02-29', '{\"k\": [1, 2]}', 'b', 'Ab', x'00ff10'),"
                  "(2, NULL, NULL, NULL, NULL, NULL, NULL, NULL, NULL, NULL, NULL, NULL),"
                  "(3, 7, 0, -0.5, 1e10, '-0.01', '1999-12-31 23:59:59', '1970-01-01', "
                  "'[true, null]', 'c', 'zz', x'')")
        s.execute("ALTER TABLE m SET COLUMNAR REPLICA 1")
        s.store.pd.tick()
        meta = s.catalog.table("m")
        t = s.store.columnar.table_for(meta.table_id)
        assert t.view()["stable_rows"] == 3 and t.view()["delta_rows"] == 0
        chunk, _batch = t.scan(t.frontier()[0], None)
        got = [[norm(None if d.is_null() else d.val) for d in chunk.row(i)]
               for i in range(chunk.num_rows())]
        want = [[norm(v) for v in row]
                for row in s.execute("SELECT * FROM m ORDER BY id").values()]
        assert got == want

    def test_delete_and_overwrite_fold_in_compaction(self):
        s = make_replicated(rows=10)
        s.execute("UPDATE t SET v = 100 WHERE id = 3")
        s.execute("UPDATE t SET v = 200 WHERE id = 3")
        s.execute("DELETE FROM t WHERE id = 4")
        s.store.pd.tick()
        meta = s.catalog.table("t")
        t = s.store.columnar.table_for(meta.table_id)
        v = t.view()
        assert v["delta_rows"] == 0  # everything folded
        assert v["stable_rows"] == 9  # 10 - 1 delete
        chunk, _ = t.scan(t.frontier()[0], None)
        by_id = {chunk.row(i)[0].val: chunk.row(i)[1].val
                 for i in range(chunk.num_rows())}
        assert by_id[3] == 200  # overwrite folded to the LATEST version
        assert 4 not in by_id  # delete folded away
        got, want = both_engines(s, "SELECT count(*), sum(v) FROM t")
        assert got == want

    def test_delta_overlay_serves_before_compaction(self):
        """Applied-but-not-folded changes (compact-stall) serve through
        the delta overlay, still byte-identical to the row store."""
        s = make_replicated(rows=10)
        with failpoint.enabled("columnar/compact-stall"):
            s.execute("UPDATE t SET v = 999 WHERE id = 2")
            s.execute("DELETE FROM t WHERE id = 5")
            s.execute("INSERT INTO t VALUES (77, 7, 1)")
            s.store.pd.tick()  # advances the frontier, skips the fold
            meta = s.catalog.table("t")
            t = s.store.columnar.table_for(meta.table_id)
            assert t.view()["delta_rows"] > 0
            got, want = both_engines(
                s, "SELECT count(*), sum(v), max(v) FROM t")
            assert got == want
        s.store.pd.tick()
        assert t.view()["delta_rows"] == 0  # disarmed: the fold catches up


# ----------------------------------------------------------------- staleness

class TestStaleness:
    def test_scan_beyond_frontier_falls_back_not_torn(self):
        """A write the frontier has not resolved yet: the routed query
        answers from the ROW STORE (counted fallback) — correct data,
        never a torn columnar prefix (ISSUE 12 satellite)."""
        s = make_replicated(rows=10)
        fb0 = metrics.COLUMNAR_FALLBACKS.value
        sc0 = metrics.COLUMNAR_SCANS.value
        s.execute("INSERT INTO t VALUES (50, 9, 0)")  # no tick: frontier lags
        got, want = both_engines(s, "SELECT count(*), sum(v) FROM t")
        assert got == want
        assert got[0][0] == 11
        assert str(got[0][1]) == str(sum((i * 7) % 13 for i in range(10)) + 9)
        assert metrics.COLUMNAR_FALLBACKS.value > fb0
        assert metrics.COLUMNAR_SCANS.value == sc0
        s.store.pd.tick()  # frontier catches up: the replica serves again
        got2, _ = both_engines(s, "SELECT count(*), sum(v) FROM t")
        assert got2 == got
        assert metrics.COLUMNAR_SCANS.value > sc0

    def test_in_flight_write_blocks_the_frontier_shortcut(self):
        """The applied>=max_committed equivalence shortcut must be
        proven under a quiescent WriteGuard double-sample: a writer
        inside its [commit-ts draw .. apply] window has a ts drawn but
        nothing in kv yet, so serving at the frontier could miss its
        commit (review finding) — the routed read must fall back."""
        s = make_replicated(rows=8)
        fb0 = metrics.COLUMNAR_FALLBACKS.value
        sc0 = metrics.COLUMNAR_SCANS.value
        with s.store.cdc.guard.writing():  # an in-flight write bracket
            got, want = both_engines(s, "SELECT count(*), sum(v) FROM t")
        assert got == want
        assert metrics.COLUMNAR_SCANS.value == sc0
        assert metrics.COLUMNAR_FALLBACKS.value > fb0
        # quiescent again: the shortcut serves
        got2, _ = both_engines(s, "SELECT count(*), sum(v) FROM t")
        assert got2 == got
        assert metrics.COLUMNAR_SCANS.value > sc0

    def test_rename_table_keeps_replica_attached_and_disposable(self):
        """RENAME TABLE mutates meta.name in place: the replica registry
        is keyed by table id, so routing follows the new name and
        REPLICA 0 under the new name really drops the feed (no orphaned
        GC safepoint; review finding)."""
        s = make_replicated(rows=12)
        s.execute("ALTER TABLE t RENAME TO u")
        s.store.pd.tick()
        assert s.store.columnar.views()[0]["table"] == "u"
        sc0 = metrics.COLUMNAR_SCANS.value
        got, want = both_engines(s, "SELECT count(*), sum(v) FROM u")
        assert got == want
        assert metrics.COLUMNAR_SCANS.value > sc0
        s.execute("ALTER TABLE u SET COLUMNAR REPLICA 0")
        assert s.execute("SHOW COLUMNAR TABLES").values() == []
        assert s.execute("SHOW CHANGEFEEDS").values() == []  # feed dropped,
        # its GC-safepoint pin released with it
        s.execute("ALTER TABLE u SET COLUMNAR REPLICA 1")  # re-enable works
        s.store.pd.tick()
        assert len(s.execute("SHOW CHANGEFEEDS").values()) == 1

    def test_stale_read_below_compaction_floor_falls_back(self):
        """tidb_snapshot older than the stable floor: the overwritten
        versions were folded away, so the replica declines and the row
        store's MVCC serves the historical read."""
        s = make_replicated(rows=6)
        old = s.store.kv.max_committed()
        s.execute("UPDATE t SET v = 500 WHERE id = 1")
        s.store.pd.tick()  # folds the overwrite; floor moves past `old`
        fb0 = metrics.COLUMNAR_FALLBACKS.value
        s.execute(f"SET tidb_snapshot = '{old}'")
        r = s.execute("SELECT max(v), count(*) FROM t").values()
        s.execute("SET tidb_snapshot = ''")
        assert r[0][1] == 6 and r[0][0] < 500  # pre-update snapshot
        assert metrics.COLUMNAR_FALLBACKS.value > fb0


# ---------------------------------- mid-feed DDL through the feed (ISSUE 20)

class TestSchemaChangeThroughFeed:
    """The pre-ISSUE-20 guard PARKED any feed whose table shape moved.
    DDL now replicates THROUGH the feed as an ordered SchemaEvent (the
    mounter tracks a per-feed snapshot advanced only by the schema
    stream), so a mid-feed ALTER is an event, never a park — and the
    legacy SchemaDriftError survives only as a counted fallback."""

    def test_alter_mid_feed_replicates_as_ordered_event(self):
        from tidb_tpu.cdc import MemorySink, SchemaEvent

        s = Session()
        s.execute("CREATE TABLE g (id BIGINT PRIMARY KEY, v BIGINT)")
        meta = s.catalog.table("g")
        feed = s.store.cdc.create("gf", MemorySink(), s.catalog,
                                  table_ids={meta.table_id}, start_ts=0)
        s.execute("INSERT INTO g VALUES (1, 10)")
        s.store.cdc.tick()
        assert len(feed.sink.rows()) == 1
        ckpt_before = feed.view(s.store)["checkpoint_ts"]
        s.execute("ALTER TABLE g ADD COLUMN w BIGINT DEFAULT 7")
        s.execute("INSERT INTO g VALUES (2, 20, 21)")
        s.store.cdc.tick()
        v = feed.view(s.store)
        assert v["state"] == "normal" and v["error"] == ""
        assert v["checkpoint_ts"] > ckpt_before  # never held by the DDL
        events = feed.sink.rows()
        assert [type(e).__name__ for e in events[1:]] == ["SchemaEvent", "RowEvent"]
        ddl = events[1]
        assert isinstance(ddl, SchemaEvent) and ddl.op == "add column"
        assert "alter table g" in ddl.query.lower() and ddl.schema_version == 1
        assert ddl.commit_ts < events[2].commit_ts  # ordered, not out-of-band
        assert dict(events[2].columns)["w"].val == 21  # mounted on NEW shape

    def test_paused_feed_across_alter_resumes_without_parking(self):
        """A feed paused BEFORE the ALTER drains its old-shape backlog
        and the schema event in commit order on resume — the case that
        used to need a double RESUME to acknowledge the drift."""
        from tidb_tpu.cdc import MemorySink, SchemaEvent

        s = Session()
        s.execute("CREATE TABLE g (id BIGINT PRIMARY KEY, v BIGINT)")
        meta = s.catalog.table("g")
        feed = s.store.cdc.create("gf", MemorySink(), s.catalog,
                                  table_ids={meta.table_id}, start_ts=0)
        s.execute("INSERT INTO g VALUES (1, 10)")
        s.store.cdc.pause("gf")
        s.execute("ALTER TABLE g ADD COLUMN w BIGINT DEFAULT 7")
        s.execute("INSERT INTO g VALUES (2, 20, 21)")
        s.store.cdc.resume("gf")
        s.store.cdc.tick()
        assert feed.view(s.store)["state"] == "normal"
        events = feed.sink.rows()
        rows = [e for e in events if not isinstance(e, SchemaEvent)]
        assert [r.handle for r in rows] == [1, 2]
        assert "w" not in dict(rows[0].columns)  # old row, old shape
        assert dict(rows[1].columns)["w"].val == 21
        assert sum(isinstance(e, SchemaEvent) for e in events) == 1

    def test_unexplained_drift_counts_legacy_fallback_not_park(self):
        """Bytes the tracked snapshot cannot decode AND the schema
        stream never explained: the mounter re-decodes against the live
        catalog as a counted CDC_SCHEMA_DRIFT_LEGACY fallback — the
        typed park is gone."""
        from tidb_tpu.cdc import MemorySink
        from tidb_tpu.cdc.schema import ColumnSnap, SchemaSnapshot

        s = Session()
        s.execute("CREATE TABLE g (id BIGINT PRIMARY KEY, v BIGINT)")
        meta = s.catalog.table("g")
        feed = s.store.cdc.create("gf", MemorySink(), s.catalog,
                                  table_ids={meta.table_id}, start_ts=0)
        s.execute("INSERT INTO g VALUES (1, 10)")
        s.store.cdc.tick()
        # wedge the tracked snapshot with a shape the row bytes cannot
        # satisfy — a schema move the journal never carried (ft=None on a
        # STORED column makes decode_row_value raise)
        vid = next(c.col_id for c in meta.columns if c.name == "v")
        with feed.mounter._mu:
            feed.mounter._tracked[meta.table_id] = SchemaSnapshot(
                0, (ColumnSnap("v", vid, None, None),))
        d0 = metrics.CDC_SCHEMA_DRIFT_LEGACY.value
        s.execute("INSERT INTO g VALUES (2, 20)")
        s.store.cdc.tick()
        assert metrics.CDC_SCHEMA_DRIFT_LEGACY.value > d0
        assert feed.view(s.store)["state"] == "normal"  # counted, not parked
        assert [r.handle for r in feed.sink.rows()] == [1, 2]
        # the fallback re-tracked the live shape: the next row is clean
        d1 = metrics.CDC_SCHEMA_DRIFT_LEGACY.value
        s.execute("INSERT INTO g VALUES (3, 30)")
        s.store.cdc.tick()
        assert metrics.CDC_SCHEMA_DRIFT_LEGACY.value == d1
        assert [r.handle for r in feed.sink.rows()] == [1, 2, 3]

    def test_columnar_replica_reshapes_and_keeps_serving(self):
        """The ColumnarSink applies the replicated ALTER as a reshape of
        the attached replica (old rows backfill the origin default) and
        keeps consuming — scans stay on the replica, no park, no rebuild
        toggle."""
        s = make_replicated(rows=8)
        s.execute("ALTER TABLE t ADD COLUMN extra BIGINT DEFAULT 0")
        s.execute("INSERT INTO t VALUES (90, 1, 1, 5)")
        r0 = metrics.COLUMNAR_RESHAPES.value
        s.store.pd.tick()
        assert metrics.COLUMNAR_RESHAPES.value > r0
        assert s.store.columnar.views()[0]["state"] == "normal"
        sc0 = metrics.COLUMNAR_SCANS.value
        got, want = both_engines(s, "SELECT count(*), sum(extra) FROM t")
        assert got == want
        assert got[0][0] == 9 and str(got[0][1]) == "5"
        assert metrics.COLUMNAR_SCANS.value > sc0  # served, not fallen back

    def test_change_column_rename_reshapes_in_place(self):
        s = make_replicated(rows=6)
        s.execute("ALTER TABLE t CHANGE COLUMN v vol BIGINT")
        s.execute("INSERT INTO t VALUES (90, 4, 1)")
        s.store.pd.tick()
        assert s.store.columnar.views()[0]["state"] == "normal"
        got, want = both_engines(s, "SELECT count(*), sum(vol) FROM t")
        assert got == want and got[0][0] == 7

    def test_partition_moving_update_keeps_the_row(self):
        """An UPDATE that moves a row across partitions emits delete(old
        pid) + put(new pid) at the SAME commit ts, and the value-less
        delete fans to every pid — the fold's put-wins-ties rule must
        keep the new partition's live row (review finding)."""
        s = Session()
        s.execute("CREATE TABLE pm (id BIGINT, p BIGINT, v BIGINT) "
                  "PARTITION BY HASH(p) PARTITIONS 4")
        s.execute("INSERT INTO pm VALUES (1, 3, 10), (2, 1, 20), (3, 2, 30)")
        s.execute("ALTER TABLE pm SET COLUMNAR REPLICA 1")
        s.store.pd.tick()
        # move DOWN in pid order: the new pid's put sorts before the old
        # pid's delete in the (ts, key) batch, so without put-wins-ties
        # the fanned tombstone erases the freshly moved row
        s.execute("UPDATE pm SET p = 0 WHERE id = 1")
        s.store.pd.tick()
        got, want = both_engines(
            s, "SELECT count(*), sum(p), sum(v) FROM pm")
        assert got == want
        assert got[0][0] == 3  # the moved row survived the tombstone fan

    def test_reshape_remaps_uncompacted_delta_rows(self):
        """An ALTER landing while old-shape rows still sit in the delta
        layer (compaction stalled) must remap delta AND stable under the
        new shape — the misaligned-rows bug the old rebuild park
        guarded against."""
        s = make_replicated(rows=4)
        failpoint.enable("columnar/compact-stall", True)
        try:
            s.execute("INSERT INTO t VALUES (50, 2, 1)")  # old shape, delta
            s.store.pd.tick()  # applied but NOT compacted
            s.execute("ALTER TABLE t ADD COLUMN extra BIGINT DEFAULT 3")
            s.execute("INSERT INTO t VALUES (90, 1, 1, 5)")
            s.store.pd.tick()  # reshape + new-shape apply, still stalled
            assert s.store.columnar.views()[0]["state"] == "normal"
            got, want = both_engines(s, "SELECT count(*), sum(extra) FROM t")
            assert got == want
            assert got[0][0] == 6 and str(got[0][1]) == str(3 * 5 + 5)
        finally:
            failpoint.disable("columnar/compact-stall")
        s.store.pd.tick()  # drain: compaction folds the remapped delta
        got, want = both_engines(s, "SELECT count(*), sum(extra) FROM t")
        assert got == want and got[0][0] == 6

    def test_index_ddl_does_not_park(self):
        s = make_replicated(rows=8)
        s.execute("CREATE INDEX iv ON t (v)")
        s.execute("INSERT INTO t VALUES (90, 1, 1)")
        s.store.pd.tick()
        assert s.store.columnar.views()[0]["state"] == "normal"


# ------------------------------------------------------------------ surfaces

class TestSurfaces:
    def test_show_columnar_tables_and_disable(self):
        s = make_replicated()
        rows = s.execute("SHOW COLUMNAR TABLES").values()
        assert len(rows) == 1
        tbl, state, pids, delta, stable = rows[0][:5]
        assert (tbl, state, pids, delta, stable) == ("t", "normal", 1, 0, 40)
        s.execute("ALTER TABLE t SET COLUMNAR REPLICA 1")  # idempotent
        assert len(s.execute("SHOW COLUMNAR TABLES").values()) == 1
        s.execute("ALTER TABLE t SET COLUMNAR REPLICA 0")
        assert s.execute("SHOW COLUMNAR TABLES").values() == []
        assert s.execute("SHOW CHANGEFEEDS").values() == []  # feed dropped

    def test_tiflash_spelling_accepted(self):
        s = Session()
        s.execute("CREATE TABLE ft (id BIGINT PRIMARY KEY, v BIGINT)")
        s.execute("ALTER TABLE ft SET TIFLASH REPLICA 1")
        assert s.execute("SHOW COLUMNAR TABLES").values()[0][0] == "ft"

    def test_http_columnar_routes(self):
        import json
        import urllib.request

        from tidb_tpu.server.http_api import StatusServer

        s = make_replicated()
        srv = StatusServer(s).start_background()
        try:
            def get(path):
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{srv.port}{path}") as r:
                    return r.status, json.loads(r.read())

            code, body = get("/columnar/api/v1/tables")
            assert code == 200 and body[0]["table"] == "t"
            assert body[0]["stable_rows"] == 40
            code, body = get("/columnar/api/v1/tables/t")
            assert code == 200 and body["state"] == "normal"
            try:
                code, _ = get("/columnar/api/v1/tables/nope")
            except urllib.error.HTTPError as exc:
                code = exc.code
            assert code == 404
        finally:
            srv.close()

    def test_columnar_metric_families_pass_scrape_check(self):
        """scrape_check tier-1 coverage of the tidb_tpu_columnar_*
        families (ISSUE 12 satellite)."""
        s = make_replicated()
        both_engines(s, "SELECT count(*) , sum(v) FROM t")
        text = metrics.REGISTRY.dump()
        for family in (
            "tidb_tpu_columnar_applied_events_total",
            "tidb_tpu_columnar_compactions_total",
            "tidb_tpu_columnar_scans_total",
            "tidb_tpu_columnar_fallbacks_total",
            "tidb_tpu_columnar_resolved_ts_lag",
        ):
            assert f"# TYPE {family}" in text, family
        assert 'tidb_tpu_columnar_resolved_ts_lag{table="t"}' in text
        from scrape_check import validate

        assert validate(text) == []

    def test_trace_has_pd_columnar_phase(self):
        s = make_replicated()
        s.store.pd.tick()
        root = s.store.pd.last_tick_root
        assert any(c.name == "pd.columnar" for c in root.children)


# ---------------------------------------------------------------- failpoints

class TestFailpoints:
    def test_apply_stall_parks_feed_and_resume_replays(self):
        s = make_replicated(rows=6)
        with failpoint.enabled("columnar/apply-stall"):
            s.execute("INSERT INTO t VALUES (60, 3, 0)")
            s.store.pd.tick()
            v = s.store.columnar.views()[0]
            assert v["state"] == "error"
        s.store.columnar.resume_all()
        s.store.pd.tick()
        v = s.store.columnar.views()[0]
        assert v["state"] == "normal"
        assert v["stable_rows"] == 7  # the stalled write replayed
        got, want = both_engines(s, "SELECT count(*), sum(v) FROM t")
        assert got == want

    def test_compact_stall_grows_delta_then_drains(self):
        s = make_replicated(rows=6)
        with failpoint.enabled("columnar/compact-stall"):
            s.execute("INSERT INTO t VALUES (61, 4, 1)")
            s.store.pd.tick()
            assert s.store.columnar.views()[0]["delta_rows"] > 0
        s.store.pd.tick()
        v = s.store.columnar.views()[0]
        assert v["delta_rows"] == 0 and v["stable_rows"] == 7


# ------------------------------------------------------------ lockwatch storm

def test_columnar_lockwatch_storm():
    """Compaction (pd tick) vs the apply path (writers) vs engine-routed
    scanners vs region splits under the runtime lockset detector: zero
    lock-order cycles, zero unguarded annotated accesses (ISSUE 12
    satellite)."""
    from tidb_tpu.analysis import lockwatch
    from tidb_tpu.codec import tablecodec

    with lockwatch.watching() as w:
        src = Session()
        src.execute("CREATE TABLE lw (id BIGINT PRIMARY KEY, v BIGINT, g BIGINT)")
        src.execute("INSERT INTO lw VALUES " + ",".join(
            f"({i},{i},{i % 4})" for i in range(64)))
        src.store.cluster.set_stores(4)
        src.store.cluster.scatter()
        src.execute("ALTER TABLE lw SET COLUMNAR REPLICA 1")
        src.store.pd.tick()
        tid = src.catalog.table("lw").table_id
        stop = threading.Event()
        errors: list = []

        def writer():
            w_sess = Session(store=src.store, catalog=src.catalog)
            k = 1000
            while not stop.is_set():
                try:
                    w_sess.execute(f"INSERT INTO lw VALUES ({k}, {k}, {k % 4})")
                    w_sess.execute(f"UPDATE lw SET v = v + 1 WHERE id = {k - 1000}")
                    k += 1
                except SQLError:
                    pass
                except Exception as exc:  # noqa: BLE001
                    errors.append(exc)
                    return

        def ticker():
            while not stop.is_set():
                try:
                    src.store.pd.tick()  # pd.cdc + pd.columnar phases
                except Exception as exc:  # noqa: BLE001
                    errors.append(exc)
                    return

        def scanner():
            r_sess = Session(store=src.store, catalog=src.catalog)
            while not stop.is_set():
                try:
                    r_sess.execute("SELECT g, count(*), sum(v) FROM lw GROUP BY g")
                except SQLError:
                    pass
                except Exception as exc:  # noqa: BLE001
                    errors.append(exc)
                    return

        def splitter():
            i = 0
            while not stop.is_set():
                try:
                    src.store.cluster.split(
                        tablecodec.encode_row_key(tid, (i * 7) % 64))
                    regions = src.store.cluster.regions()
                    if len(regions) > 6:
                        src.store.cluster.merge(regions[0].region_id)
                    i += 1
                except Exception as exc:  # noqa: BLE001
                    errors.append(exc)
                    return

        threads = [threading.Thread(target=f, daemon=True)
                   for f in (writer, ticker, scanner, scanner, splitter)]
        for t in threads:
            t.start()
        time.sleep(1.5)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        for _ in range(4):
            src.store.pd.tick()  # drain
    rep = w.report()
    assert rep["cycles"] == [], rep["cycles"]
    assert rep["violations"] == [], "\n".join(rep["violations"])
    assert not errors, errors
    assert rep["edges"], "lockwatch saw no lock nesting at all"


# -------------------------------------------------- HTAP chaos acceptance

def test_htap_chaos_storm_acceptance():
    """ISSUE 12 acceptance: a live changefeed feeds the columnar replica
    under splits/merges/leader transfers/a store outage and the
    columnar/* + cdc/* failpoints; every engine-routed analytical query
    is byte-identical to the row-store oracle at the same snapshot, the
    replica's resolved-ts lag drains to 0 after the storm, and zero
    untyped errors escape."""
    from chaos import run_htap_storm

    report = run_htap_storm(seed=13, statements=100)
    assert report["wrong_results"] == [], report["wrong_results"]
    assert report["untyped_errors"] == [], report["untyped_errors"]
    assert report["columnar_scans"] > 0, report
    assert report["lag_drained"], report["tables"]
    assert report["feeds_normal"], report["tables"]
    assert report["delta_drained"], report["tables"]
    assert report["applied_events"] > 0
