"""dataflow-snapshot true positives: MVCC reads on a request path that
bypass the statement snapshot — a latest-version oracle read, a constant
ts, and a ts that never flowed from the request's start_ts."""


class MemKV:
    def get(self, key, ts):
        return None

    def scan(self, start, end, ts):
        return iter(())

    def max_ts(self):
        return 1 << 62


class Store:
    def __init__(self):
        self.kv = MemKV()
        self.wall_clock = 77

    def coprocessor(self, req):  # vet: request-path-root
        # BAD: reads whatever committed last, not the snapshot
        latest = self.kv.get(b"k", self.kv.max_ts())
        # BAD: constant ts — sees a frozen arbitrary cut
        pinned = list(self.kv.scan(b"a", b"z", 12345))
        # BAD: ts from unrelated state, no REQ/TS fact reaches it
        drifted = self.kv.get(b"k", self.wall_clock)
        # GOOD: flows the request's start_ts
        seen = self.kv.get(b"k", req.start_ts)
        return latest, pinned, drifted, seen

    def helper_scan(self, start_ts):
        # GOOD: start_ts arrives from the root through the call below
        return list(self.kv.scan(b"a", b"z", start_ts))

    def coprocessor_paged(self, req):  # vet: request-path-root
        return self.helper_scan(req.start_ts)
