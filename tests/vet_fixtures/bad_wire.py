"""True-positive fixture for the `wire-parity` pass (filename ends in
`wire.py` so the pass picks it up): an encoder with no decoder, and an
encode/decode pair whose fields don't line up. NEVER imported — scanned
as text by tests/test_vet.py."""


def encode_orphan(w, req):  # VIOLATION: no decode_orphan anywhere
    w.i64(req.id)


def encode_lossy(w, resp):
    w.i64(resp.rows)
    w.f64(resp.elapsed)  # VIOLATION: the decoder never reads an f64 back


def decode_lossy(r):
    return r.i64()


def encode_nested(w, x):
    w.blob(encode_orphan_bytes(x))  # helper with no decode_ mirror
    w.i32(1)


def encode_orphan_bytes(x) -> bytes:
    return b""


def decode_nested(r):
    return r.i32()  # VIOLATION: blob written but never read
