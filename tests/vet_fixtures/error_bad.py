"""True-positive fixture for the `error-taxonomy` pass: bare
RuntimeError/Exception raised in what would be a request path. NEVER
imported — scanned as text by tests/test_vet.py."""


def handle_request(region_id: int):
    if region_id < 0:
        raise RuntimeError(f"region {region_id} bad")  # VIOLATION: untyped
    raise Exception("boom")  # VIOLATION: untyped
