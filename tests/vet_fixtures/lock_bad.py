"""True-positive fixture for the `lock-discipline` pass: a
`# guarded_by:`-annotated attribute read and written off-lock. NEVER
imported — scanned as text by tests/test_vet.py."""

import threading


class LeakyCounter:
    def __init__(self):
        self._mu = threading.Lock()
        self.hits = 0  # guarded_by: _mu

    def bump(self):
        with self._mu:
            self.hits += 1

    def bump_racy(self):
        self.hits += 1  # VIOLATION: write outside the lock

    def peek_racy(self) -> int:
        return self.hits  # VIOLATION: read outside the lock

    def helper(self):  # requires: _mu
        self.hits = 0  # ok: declared to run with _mu held
