"""dataflow-error-escape true positives: a bare RuntimeError escaping
the request path, and a typed region error crossing the session boundary
with no SQLError mapping."""


class RegionTimeoutError(RuntimeError):
    """Typed region error nobody maps to a MySQL code."""


def select(store, req):  # vet: request-path-root
    if store.busy:
        raise RuntimeError("store busy")  # bare: dispatch cannot classify it
    raise RegionTimeoutError("region 7 timed out")


class Session:
    def execute(self, sql):  # vet: session-boundary
        return select(self.store, sql)
