"""jax-audit true positives: an integer program that leaks float64, and
a builder whose closure captures a mutating Python scalar (every build
traces a different jaxpr — the ProgramCache multiplies silently)."""

import itertools

import numpy as np

_counter = itertools.count(1)


def _args():
    return [np.arange(8, dtype=np.int64)]


def _f64_leak():
    import jax.numpy as jnp

    def fn(x):
        # BAD: int64 input promoted to float64 inside the program
        return (x.astype(jnp.float64) * 1.5).sum()

    return fn, _args()


def _closure_scalar():
    salt = next(_counter)  # BAD: baked into the trace, changes per build

    def fn(x):
        return x + salt

    return fn, _args()


JAX_AUDIT_CATALOG = [
    {"name": "f64-leak", "make": _f64_leak, "line": 17},
    {"name": "closure-scalar", "make": _closure_scalar, "line": 27},
]
