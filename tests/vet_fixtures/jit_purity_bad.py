"""True-positive fixture for the `jit-purity` pass: a module-level jax
array constant (the PR-2 tracer-leak class) and an import-time global
config toggle. NEVER imported — scanned as text by tests/test_vet.py."""

import jax
import jax.numpy as jnp

BAD_CONST = jnp.zeros(4)  # created whenever this module first imports
BAD_DERIVED = BAD_CONST + jnp.int64(1)

jax.config.update("jax_enable_x64", False)  # import-order becomes semantics
