"""True-positive fixture for the `failpoints` pass: arms a name no
eval/is_armed/peek site under tidb_tpu/ defines — it could never fire.
NEVER imported — scanned as text by tests/test_vet.py (which feeds it to
the pass's scanner directly; the live-tree run must not see it, which is
why fixtures live outside the pass's tests//tools/ scan roots... this one
is exercised through failpoints._scan on the explicit path)."""

from tidb_tpu.util import failpoint

failpoint.enable("vetfix/undefined-name")
