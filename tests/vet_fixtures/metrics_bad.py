"""True-positive fixture for the `metrics` pass: duplicate registration,
naming-convention breaks, label-arity mismatch, vec addressed without
.labels(), plain counter addressed with .labels(). NEVER imported —
scanned as text by tests/test_vet.py."""

from tidb_tpu.util import metrics
from tidb_tpu.util.metrics import Registry

REG = Registry()

FIX_A = REG.counter("vetfix_requests_total")
FIX_DUP = REG.counter("vetfix_requests_total")  # VIOLATION: registered twice
FIX_NO_SUFFIX = REG.counter("vetfix_requests")  # VIOLATION: counter sans _total
FIX_BAD_NAME = REG.gauge("vetfix-bad-name")  # VIOLATION: invalid charset
FIX_GAUGE_TOTAL = REG.gauge("vetfix_open_total")  # VIOLATION: gauge claims _total
FIX_VEC = REG.counter_vec("vetfix_tasks_total", "per-store tasks",
                          labelnames=("store",))


def use_sites():
    metrics.FIX_VEC.labels("0", "extra").inc()  # VIOLATION: arity mismatch
    metrics.FIX_VEC.inc()  # VIOLATION: vec without .labels
    metrics.FIX_A.labels("x").inc()  # VIOLATION: plain counter has no labels
    metrics.FIX_TYPO_TOTAL.inc()  # VIOLATION: never registered
