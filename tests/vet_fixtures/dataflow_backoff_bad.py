"""dataflow-backoff true positives: an unbounded retry loop that never
consults a Backoffer budget, and a raw time.sleep on the request path
(unsliced: KILL QUERY waits out the whole nap; unclamped: it can outlive
the statement deadline)."""

import time


def select(store, req):  # vet: request-path-root
    while True:
        resp = store.coprocessor(req)
        if resp.region_error is not None:
            time.sleep(0.05)
            continue
        return resp
