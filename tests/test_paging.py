"""Coprocessor paging/resume contract (VERDICT next #6): paging_size in,
last_range resume cursor out (ref: copr/coprocessor.go:1393,
cophandler/cop_handler.go:210-224 lastRange)."""

import numpy as np
import pytest

from tidb_tpu.chunk import Chunk
from tidb_tpu.codec import tablecodec
from tidb_tpu.distsql import KVRequest, full_table_ranges, handle_ranges, select
from tidb_tpu.exec import Aggregation, ColumnInfo, DAGRequest, Selection, TableScan
from tidb_tpu.expr import AggDesc, col, func, lit
from tidb_tpu.store import CopRequest, TPUStore
from tidb_tpu.types import Datum, new_longlong

BOOL = new_longlong(notnull=True)
TID = 21
FT = new_longlong()


def fill(n=90, regions=1):
    store = TPUStore()
    for h in range(n):
        store.put_row(TID, h, [1], [Datum.i64(h)], ts=5)
    for i in range(1, regions):
        store.cluster.split(tablecodec.encode_row_key(TID, i * n // regions))
    return store


def scan():
    return TableScan(TID, (ColumnInfo(1, FT),))


def region0(store):
    return store.cluster.regions_in_range(b"", b"\xff" * 20)[0]


def test_region_drains_in_three_pages():
    store = fill(90)
    dag = DAGRequest((scan(),), output_offsets=(0,))
    region = region0(store)
    ranges = full_table_ranges(TID)
    pages = []
    for _ in range(10):
        resp = store.coprocessor(
            CopRequest(dag, ranges, 100, region.region_id, region.epoch, paging_size=30)
        )
        assert resp.other_error is None and resp.region_error is None
        pages.append(resp.chunk)
        if resp.last_range is None:
            break
        ranges = resp.last_range
    assert len(pages) == 3
    assert [p.num_rows() for p in pages] == [30, 30, 30]
    one_shot = store.coprocessor(
        CopRequest(dag, full_table_ranges(TID), 100, region.region_id, region.epoch)
    ).chunk
    got = [r[0].val for p in pages for r in p.rows()]
    want = [r[0].val for r in one_shot.rows()]
    assert got == want  # resume cursor preserves scan order, no dup/loss


def test_paging_with_selection():
    store = fill(80)
    pred = func("eq", BOOL, func("mod", new_longlong(), col(0, FT), lit(3, new_longlong())), lit(0, new_longlong()))
    dag = DAGRequest((scan(), Selection((pred,))), output_offsets=(0,))
    region = region0(store)
    ranges = full_table_ranges(TID)
    got = []
    while True:
        resp = store.coprocessor(CopRequest(dag, ranges, 100, region.region_id, region.epoch, paging_size=25))
        assert resp.other_error is None
        got += [r[0].val for r in resp.chunk.rows()]
        if resp.last_range is None:
            break
        ranges = resp.last_range
    assert got == [v for v in range(80) if v % 3 == 0]


def test_paging_rejects_aggregation():
    store = fill(10)
    agg = Aggregation(group_by=(), aggs=(AggDesc("count", ()),))
    dag = DAGRequest((scan(), agg), output_offsets=(0,))
    region = region0(store)
    resp = store.coprocessor(CopRequest(dag, full_table_ranges(TID), 100, region.region_id, region.epoch, paging_size=4))
    assert resp.other_error is not None and "paging" in resp.other_error


def test_dispatch_paging_loop_multi_region():
    store = fill(120, regions=3)
    dag = DAGRequest((scan(),), output_offsets=(0,))
    paged = select(store, KVRequest(dag, full_table_ranges(TID), start_ts=100, paging_size=17))
    plain = select(store, KVRequest(dag, full_table_ranges(TID), start_ts=100))
    assert len(paged.chunks) > len(plain.chunks)
    got = sorted(r[0].val for c in paged.chunks for r in c.rows())
    want = sorted(r[0].val for c in plain.chunks for r in c.rows())
    assert got == want == list(range(120))


def test_paging_multi_range():
    store = fill(60)
    dag = DAGRequest((scan(),), output_offsets=(0,))
    region = region0(store)
    ranges = handle_ranges(TID, [(5, 14), (30, 44)])
    got = []
    while True:
        resp = store.coprocessor(CopRequest(dag, ranges, 100, region.region_id, region.epoch, paging_size=7))
        assert resp.other_error is None
        got += [r[0].val for r in resp.chunk.rows()]
        if resp.last_range is None:
            break
        ranges = resp.last_range
    assert got == list(range(5, 15)) + list(range(30, 45))


def test_paging_rejects_topn_limit_and_zero():
    from tidb_tpu.exec import Limit, TopN

    store = fill(20)
    region = region0(store)
    for ex in (Limit(5), TopN(order_by=((col(0, FT), False),), limit=5)):
        dag = DAGRequest((scan(), ex), output_offsets=(0,))
        resp = store.coprocessor(CopRequest(dag, full_table_ranges(TID), 100, region.region_id, region.epoch, paging_size=4))
        assert resp.other_error and "row-local" in resp.other_error
    dag = DAGRequest((scan(),), output_offsets=(0,))
    resp = store.coprocessor(CopRequest(dag, full_table_ranges(TID), 100, region.region_id, region.epoch, paging_size=0))
    assert resp.other_error and "paging_size" in resp.other_error
