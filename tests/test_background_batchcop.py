"""Batch coprocessor + PD scatter + exchange modes + background frameworks
(ref: copr/batch_coprocessor.go, PD scatter, mpp_exec.go:669-719 partition
modes, pkg/timer, pkg/ttl, pkg/disttask, statistics auto-analyze)."""

import numpy as np
import pytest

from tidb_tpu.sql.session import Session, SQLError


# ---------------------------------------------------------------- batch cop


def test_batch_cop_matches_plain():
    s = Session()
    s.execute("CREATE TABLE t (id INT PRIMARY KEY, v INT)")
    s.execute("INSERT INTO t VALUES " + ",".join(f"({i},{i % 13})" for i in range(1, 501)))
    # split into several regions, scattered over 4 stores
    from tidb_tpu.codec import tablecodec

    for h in (100, 200, 300, 400):
        s.store.cluster.split(tablecodec.encode_row_key(s.catalog.table("t").table_id, h))
    s.store.cluster.set_stores(4)
    plain = s.execute("SELECT count(*), sum(v) FROM t WHERE v < 7").values()
    s.execute("SET tidb_allow_batch_cop = ON")
    batched = s.execute("SELECT count(*), sum(v) FROM t WHERE v < 7").values()
    assert plain == batched


def test_scatter_assignment():
    from tidb_tpu.store.region import Cluster

    c = Cluster()
    for k in (b"b", b"d", b"f", b"h"):
        c.split(k)
    c.set_stores(3)
    stores = {c.store_of(r.region_id) for r in c.regions()}
    assert stores == {0, 1, 2}  # every store got regions


# ---------------------------------------------------------------- exchanges


def _mesh8():
    import jax

    devs = np.array(jax.devices()[:8])
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    from jax.sharding import Mesh

    return Mesh(devs, ("x",))


def test_broadcast_exchange():
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from tidb_tpu.parallel.exchange import broadcast_exchange

    mesh = _mesh8()
    n = 4
    vals = jnp.arange(8 * n, dtype=jnp.int64)
    valid = jnp.ones(8 * n, bool)

    def body(v, m):
        (out,), gv = broadcast_exchange("x", [v], m)
        # every device must hold every row
        return jnp.sum(jnp.where(gv, out, 0))[None]

    f = shard_map(body, mesh=mesh, in_specs=(P("x"), P("x")), out_specs=P("x"))
    got = f(vals, valid)
    assert np.all(np.asarray(got) == int(vals.sum()))


def test_passthrough_exchange():
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from tidb_tpu.parallel.exchange import passthrough_exchange

    mesh = _mesh8()
    n = 4
    vals = jnp.arange(8 * n, dtype=jnp.int64)
    valid = jnp.ones(8 * n, bool)

    def body(v, m):
        (out,), gv = passthrough_exchange("x", [v], m, target=0)
        return jnp.sum(jnp.where(gv, out, 0))[None]

    got = np.asarray(shard_map(body, mesh=mesh, in_specs=(P("x"), P("x")), out_specs=P("x"))(vals, valid))
    # only device 0 owns rows; everyone else sums to zero
    assert got[0] == int(vals.sum()) and np.all(got[1:] == 0)


# ---------------------------------------------------------------- background


def test_timer_fires_and_survives_errors():
    from tidb_tpu.background import Timer

    calls = []

    def fn():
        calls.append(1)
        if len(calls) == 1:
            raise RuntimeError("boom")

    t = Timer("t", 0.01, fn).start()
    import time

    time.sleep(0.15)
    t.stop()
    assert len(calls) >= 3
    assert t.error_count >= 1 and t.fire_count >= 1


def test_ttl_worker_deletes_expired():
    from tidb_tpu.background import TTLWorker

    s = Session()
    s.execute("CREATE TABLE ev (id INT PRIMARY KEY, created DATETIME)")
    s.execute("INSERT INTO ev VALUES (1,'2024-01-01 00:00:00'),(2,'2024-06-01 00:00:00'),(3,'2024-12-01 00:00:00')")
    w = TTLWorker(s, now_fn=lambda: "2024-12-02 00:00:00")
    w.attach("ev", "created", expire_after_days=30.0)
    deleted = w.run_once()
    assert deleted == 2
    assert s.execute("SELECT id FROM ev").values() == [[3]]
    assert w.run_once() == 0  # idempotent


def test_ttl_rejects_unknown_column():
    from tidb_tpu.background import TTLWorker

    s = Session()
    s.execute("CREATE TABLE ev (id INT PRIMARY KEY)")
    with pytest.raises(Exception):
        TTLWorker(s).attach("ev", "nope", 1.0)


def test_disttask_scheduler():
    from tidb_tpu.background import DistTaskScheduler

    sched = DistTaskScheduler(n_workers=4)
    task = sched.run("square", list(range(20)), lambda p: p * p)
    assert task.state == "succeed"
    assert sorted(st.result for st in task.subtasks) == sorted(i * i for i in range(20))


def test_disttask_retry_then_revert():
    from tidb_tpu.background import DistTaskScheduler

    sched = DistTaskScheduler(n_workers=2, max_retries=1)

    def flaky(p):
        if p == 13:
            raise RuntimeError("always fails")
        return p

    task = sched.run("flaky", [1, 13, 2], flaky)
    assert task.state == "reverted"
    failed = [st for st in task.subtasks if st.state == "failed"]
    assert failed and failed[0].payload == 13 and failed[0].attempts == 2


def test_auto_analyze_triggers_on_drift():
    from tidb_tpu.background import AutoAnalyzer

    s = Session()
    s.execute("CREATE TABLE t (id INT PRIMARY KEY, v INT)")
    s.execute("INSERT INTO t VALUES " + ",".join(f"({i},{i})" for i in range(1, 11)))
    a = AutoAnalyzer(s)
    assert a.run_once() == ["t"]  # no stats yet
    assert a.run_once() == []  # fresh stats, no drift
    s.execute("INSERT INTO t VALUES " + ",".join(f"({i},{i})" for i in range(11, 31)))
    assert a.run_once() == ["t"]  # 200% growth > 50% ratio
