"""Placement Driver: region heartbeats, hot-region detection, and
load-based split/merge/rebalance scheduling (ISSUE 3; ref: tikv/pd
coordinator + statistics/hot_peer_cache.go + checker/{split,merge}_checker
+ schedulers/{balance_region,hot_region}.go)."""

import json
import urllib.request

import pytest

from tidb_tpu.codec import tablecodec
from tidb_tpu.sql.session import Session
from tidb_tpu.store import TPUStore
from tidb_tpu.types import Datum
from tidb_tpu.util import failpoint, metrics

TID = 9


def fill_store(rows=200, regions=4, stores=4, pin_store=None):
    """Store with `rows` int rows split into `regions` regions over
    `stores` stores; `pin_store` forces every region onto one store (the
    skew pathology PD must fix)."""
    store = TPUStore()
    for h in range(rows):
        store.put_row(TID, h, [1], [Datum.i64(h)], ts=10)
    for i in range(1, regions):
        store.cluster.split(tablecodec.encode_row_key(TID, i * rows // regions))
    store.cluster.set_stores(stores)
    if pin_store is not None:
        for r in store.cluster.regions():
            store.cluster.set_store(r.region_id, pin_store)
    return store


def scan_region(store, region):
    """One cop task over a region (drives the read-flow path)."""
    from tidb_tpu.exec.dag import ColumnInfo, DAGRequest, TableScan
    from tidb_tpu.store import CopRequest, KeyRange
    from tidb_tpu.types import new_longlong

    dag = DAGRequest((TableScan(TID, (ColumnInfo(1, new_longlong()),)),), output_offsets=(0,))
    resp = store.coprocessor(CopRequest(
        dag, [KeyRange(region.start_key, region.end_key)], 100,
        region.region_id, region.epoch,
    ))
    assert resp.other_error is None and resp.region_error is None, (
        resp.other_error or resp.region_error)
    return resp


# ---------------------------------------------------------------- flow

def test_flow_records_reads_and_writes_into_heartbeats():
    store = fill_store(rows=100, regions=2, stores=1)
    r1 = store.cluster.regions()[0]
    scan_region(store, r1)
    beats = {b.region_id: b for b in store.pd.flow.heartbeat()}
    assert set(beats) == {r.region_id for r in store.cluster.regions()}
    b = beats[r1.region_id]
    assert b.read_bytes > 0 and b.read_keys > 0  # the scan
    assert b.write_bytes > 0 and b.write_keys > 0  # the puts
    assert b.approx_keys > 0 and b.approx_size > 0
    # deltas drain, approximate totals persist
    b2 = {x.region_id: x for x in store.pd.flow.heartbeat()}[r1.region_id]
    assert b2.read_bytes == 0 and b2.write_keys == 0
    assert b2.approx_keys == b.approx_keys


def test_flow_write_path_through_txn_commit():
    s = Session()
    s.execute("CREATE TABLE w (id INT PRIMARY KEY, v INT)")
    s.execute("BEGIN")
    s.execute("INSERT INTO w VALUES (1, 10), (2, 20)")
    s.execute("COMMIT")
    beats = s.store.pd.flow.heartbeat()
    assert sum(b.write_keys for b in beats) >= 2  # 2PC apply recorded


def test_flow_split_and_merge_redistribute_approximates():
    store = fill_store(rows=100, regions=1, stores=1)
    before = store.pd.flow.stats()
    (rid,) = before
    size, keys = before[rid]
    child = store.cluster.split(tablecodec.encode_row_key(TID, 50))
    stats = store.pd.flow.stats()
    assert stats[rid][1] + stats[child.region_id][1] == keys
    assert abs(stats[rid][1] - stats[child.region_id][1]) <= 1
    store.cluster.merge(rid, child.region_id)
    stats = store.pd.flow.stats()
    assert child.region_id not in stats
    assert stats[rid] == (size, keys)


def test_flow_overwrites_and_deletes_track_logical_size():
    """UPDATE churn must not grow approximate size into split-checker
    churn; deleting everything must shrink it back toward zero."""
    store = fill_store(rows=20, regions=1, stores=1)
    (rid,) = store.pd.flow.stats()
    size0, keys0 = store.pd.flow.stats()[rid]
    assert keys0 == 20
    for _ in range(50):  # overwrite one row repeatedly
        store.put_row(TID, 0, [1], [Datum.i64(999)], ts=store.next_ts())
    size1, keys1 = store.pd.flow.stats()[rid]
    assert keys1 == 20  # overwrites are traffic, not growth
    assert size1 == size0
    for h in range(20):
        store.delete_row(TID, h, ts=store.next_ts())
    size2, keys2 = store.pd.flow.stats()[rid]
    assert keys2 == 0
    assert size2 <= size0 // 10  # shrunk toward zero (mean-size estimate)


def test_load_data_records_region_flow(tmp_path):
    """LOAD DATA's raw-kv bulk path must feed the PD flow, or the
    merge-checker folds freshly loaded regions as 'empty'."""
    s = Session()
    s.execute("CREATE TABLE ld (id INT PRIMARY KEY, v INT)")
    p = tmp_path / "ld.csv"
    p.write_text("".join(f"{i},{i}\n" for i in range(40)))
    s.execute(f"LOAD DATA INFILE '{p}' INTO TABLE ld FIELDS TERMINATED BY ','")
    assert s.execute("SELECT count(*) FROM ld").values() == [[40]]
    stats = s.store.pd.flow.stats()
    assert sum(k for _, k in stats.values()) >= 40


# ---------------------------------------------------------------- hot peers

def test_hot_peer_cache_hysteresis_and_decay():
    from tidb_tpu.pd.core import HotPeerCache, PDConfig

    conf = PDConfig(hot_byte_rate=100.0, hot_min_degree=2, hot_decay=0.5)
    c = HotPeerCache("read", conf)
    c.update(1, 1000, 10)
    assert not c.hot_peers()  # one hot interval is not "hot" yet
    c.update(1, 1000, 10)
    assert [p.region_id for p in c.hot_peers()] == [1]
    # quiet intervals decay the rate and shrink the degree
    for _ in range(8):
        c.update(1, 0, 0)
    assert not c.hot_peers()


# ---------------------------------------------------------------- operators

def test_operator_queue_bounded_and_one_per_region():
    from tidb_tpu.pd.core import Operator, OperatorQueue

    q = OperatorQueue(limit=2)
    assert q.add(Operator(1, "split", 10))
    assert not q.add(Operator(2, "move-region", 10))  # region busy
    assert not q.add(Operator(3, "merge", 11, peer_region=10))  # peer busy
    assert q.add(Operator(4, "move-region", 12))
    assert not q.add(Operator(5, "split", 13))  # full
    assert len(q.pending()) == 2


def test_operator_timeout_failpoint_expires_pending():
    store = fill_store(rows=100, regions=2, stores=4, pin_store=0)
    base = metrics.PD_OPERATOR_TIMEOUTS.value
    with failpoint.enabled("pd/operator-timeout"):
        dispatched = store.pd.tick()
    # everything proposed this tick expired instead of dispatching
    assert dispatched == []
    assert metrics.PD_OPERATOR_TIMEOUTS.value > base
    assert any(o.state == "timeout" for o in store.pd.queue.history)
    # placement unchanged: the skew persists while operators time out
    counts = store.cluster.counts_per_store()
    assert counts[0] == len(store.cluster.regions())


def test_heartbeat_lost_failpoint_drops_interval():
    store = fill_store(rows=100, regions=2, stores=1)
    scan_region(store, store.cluster.regions()[0])
    base = store.pd.heartbeats_seen
    with failpoint.enabled("pd/heartbeat-lost"):
        store.pd.tick()
    assert store.pd.heartbeats_seen == base  # interval dropped on the floor
    store.pd.tick()
    assert store.pd.heartbeats_seen > base  # stream recovers next tick


# ---------------------------------------------------------------- checkers

def test_split_checker_splits_oversized_region_and_bumps_epoch():
    store = fill_store(rows=120, regions=1, stores=1)
    region = store.cluster.regions()[0]
    epoch0 = region.epoch
    store.pd.conf.max_region_keys = 50
    store.pd.conf.merge_region_keys = -1  # isolate the split checker
    store.pd.conf.merge_region_size = -1
    base = metrics.PD_OPERATORS.labels("split").value
    for _ in range(4):
        store.pd.tick()
    regions = store.cluster.regions()
    assert len(regions) >= 2
    assert metrics.PD_OPERATORS.labels("split").value > base
    assert store.cluster.region_by_id(region.region_id).epoch > epoch0
    # every split decision came from recorded stats, and stats followed
    stats = store.pd.flow.stats()
    assert sum(stats[r.region_id][1] for r in regions) == 120


def test_merge_checker_folds_adjacent_empty_regions():
    store = fill_store(rows=60, regions=1, stores=1)
    # manufacture empty tail regions beyond the data
    store.cluster.split(tablecodec.encode_row_key(TID, 1000))
    store.cluster.split(tablecodec.encode_row_key(TID, 2000))
    assert len(store.cluster.regions()) == 3
    base = metrics.PD_OPERATORS.labels("merge").value
    for _ in range(4):
        store.pd.tick()
    assert len(store.cluster.regions()) < 3
    assert metrics.PD_OPERATORS.labels("merge").value > base
    # the data is still fully readable after the fold
    total = 0
    for r in store.cluster.regions():
        total += scan_region(store, r).chunk.num_rows()
    assert total == 60


# ---------------------------------------------------------------- placement

def test_store_of_miss_routes_through_pd_and_is_recorded():
    store = fill_store(rows=40, regions=4, stores=4)
    base = metrics.PD_PLACEMENT_DECISIONS.value
    # forget one region's placement — the seed would silently answer
    # region_id % n_stores; now the PD decides and records
    r = store.cluster.regions()[2]
    with store.cluster._mu:
        store.cluster._store_of.pop(r.region_id)
    first = store.cluster.store_of(r.region_id)
    assert metrics.PD_PLACEMENT_DECISIONS.value == base + 1
    # recorded: the second lookup answers from the map, no new decision
    assert store.cluster.store_of(r.region_id) == first
    assert metrics.PD_PLACEMENT_DECISIONS.value == base + 1


def test_split_child_inherits_parent_store():
    store = fill_store(rows=100, regions=2, stores=4)
    parent = store.cluster.regions()[1]
    parent_store = store.cluster.store_of(parent.region_id)
    child = store.cluster.split(tablecodec.encode_row_key(TID, 75))
    assert store.cluster.store_of(child.region_id) == parent_store


def test_standalone_cluster_without_pd_places_least_loaded():
    from tidb_tpu.store.region import Cluster

    c = Cluster(n_stores=3)
    for k in (b"b", b"d", b"f"):
        c.split(k)
    c.scatter()
    # a miss on a live region lands on the emptiest store and sticks
    with c._mu:
        rid = c._regions[1].region_id
        c._store_of.pop(rid)
    sid = c.store_of(rid)
    assert 0 <= sid < 3
    assert c.store_of(rid) == sid


# ---------------------------------------------------------------- schedulers

def test_balance_converges_skewed_placement():
    """The ISSUE acceptance bar: skewed placement over >= 4 stores ends
    with max/min region-count ratio <= 2 and no store holding more than
    half the regions."""
    store = fill_store(rows=400, regions=8, stores=4, pin_store=0)
    store.pd.conf.merge_region_keys = -1  # keep the 8 regions stable
    store.pd.conf.merge_region_size = -1
    for _ in range(8):
        store.pd.tick()
    counts = store.cluster.counts_per_store()
    total = len(store.cluster.regions())
    assert max(counts.values()) <= total / 2
    assert max(counts.values()) / max(min(counts.values()), 1) <= 2
    assert min(counts.values()) >= 1


def test_hot_region_scheduler_moves_hot_peer_off_overloaded_store():
    store = fill_store(rows=200, regions=4, stores=2)
    store.pd.conf.hot_byte_rate = 64.0
    store.pd.conf.merge_region_keys = -1
    store.pd.conf.merge_region_size = -1
    store.pd.conf.balance_tolerance = 100  # isolate the hot scheduler
    regions = store.cluster.regions()
    hot1, hot2 = regions[0], regions[1]
    store.cluster.set_store(hot1.region_id, 0)
    store.cluster.set_store(hot2.region_id, 0)
    base = metrics.PD_OPERATORS.labels("move-hot-region").value
    for _ in range(6):
        for _ in range(4):
            scan_region(store, store.cluster.region_by_id(hot1.region_id))
            scan_region(store, store.cluster.region_by_id(hot2.region_id))
        store.pd.tick()
    assert metrics.PD_OPERATORS.labels("move-hot-region").value > base
    # the two hot peers no longer share a store
    s1 = store.cluster.store_of(hot1.region_id)
    s2 = store.cluster.store_of(hot2.region_id)
    assert s1 != s2
    hot = store.pd.hotspot_view()
    assert {p["region_id"] for p in hot["read"]} >= {hot1.region_id, hot2.region_id}


# ---------------------------------------------------------------- retry path

def test_concurrent_pd_split_retries_through_epoch_not_match():
    """A PD split landing while a scan's tasks are in flight surfaces
    EpochNotMatch and the dispatch retry path re-splits cleanly."""
    s = Session()
    s.execute("CREATE TABLE c (id INT PRIMARY KEY, v INT)")
    s.execute("INSERT INTO c VALUES " + ",".join(f"({i},{i % 11})" for i in range(200)))
    pd = s.store.pd
    pd.conf.max_region_keys = 40  # every region is oversized
    pd.conf.merge_region_keys = -1
    pd.conf.merge_region_size = -1
    retries0 = metrics.DISTSQL_RETRIES.value
    fired = []

    def mid_dispatch_tick():
        if not fired:  # once: split the region under the running scan
            fired.append(1)
            pd.tick()

    with failpoint.enabled("distsql.before_task", mid_dispatch_tick):
        got = s.execute("SELECT count(*), sum(v) FROM c").values()
    assert fired and len(s.store.cluster.regions()) >= 2
    assert got[0][0] == 200 and int(str(got[0][1])) == sum(i % 11 for i in range(200))
    assert metrics.DISTSQL_RETRIES.value > retries0


# ---------------------------------------------------------------- surfaces

def test_show_placement_statement():
    s = Session()
    s.execute("CREATE TABLE p (id INT PRIMARY KEY, v INT)")
    s.execute("INSERT INTO p VALUES (1, 1), (2, 2)")
    s.store.cluster.set_stores(2)
    r = s.execute("SHOW PLACEMENT")
    assert r.columns == ["Target", "Placement", "Scheduling_State"]
    targets = [row[0] for row in r.values()]
    assert any(t.startswith("STORE") for t in targets)
    assert any(t.startswith("REGION") for t in targets)
    assert any("store=" in row[1] for row in r.values())


def test_pd_http_api_endpoints():
    s = Session()
    s.execute("CREATE TABLE h (id INT PRIMARY KEY, v INT)")
    s.execute("INSERT INTO h VALUES " + ",".join(f"({i},{i})" for i in range(50)))
    s.store.cluster.set_stores(2)
    s.execute("SELECT sum(v) FROM h")
    s.store.pd.tick()
    from tidb_tpu.server.http_api import StatusServer

    srv = StatusServer(s).start_background()
    try:
        def get(path):
            with urllib.request.urlopen(f"http://{srv.host}:{srv.port}{path}") as resp:
                assert resp.status == 200
                return json.loads(resp.read())

        regions = get("/pd/api/v1/regions")
        assert regions and {"region_id", "store", "epoch", "approximate_size"} <= set(regions[0])
        stores = get("/pd/api/v1/stores")
        assert [st["store_id"] for st in stores] == [0, 1]
        assert sum(st["region_count"] for st in stores) == len(regions)
        hot = get("/pd/api/v1/hotspot")
        assert "read" in hot and "write" in hot
        ops = get("/pd/api/v1/operators")
        assert "pending" in ops and "history" in ops
    finally:
        srv.close()


def test_pd_tick_emits_trace_span():
    store = fill_store(rows=50, regions=2, stores=2)
    store.pd.tick()
    root = store.pd.last_tick_root
    assert root is not None and root.name == "pd.tick"
    names = {c.name for c in root.children}
    assert {"pd.heartbeat", "pd.schedule", "pd.dispatch"} <= names


def test_pd_timer_tick_loop():
    store = fill_store(rows=50, regions=2, stores=2)
    t = store.pd.timer(0.01)
    assert t.name == "pd"
    t.fire_once()
    assert store.pd.ticks >= 1


def test_config_server_boots_and_stops_pd_loop():
    from tidb_tpu.config import Config
    from tidb_tpu.server import MySQLServer

    srv = MySQLServer(port=0, config=Config(pd_tick_interval=0.01))
    try:
        assert srv.store.pd._timer is not None
        import time

        deadline = time.monotonic() + 2.0
        while srv.store.pd.ticks == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert srv.store.pd.ticks >= 1
    finally:
        srv.close()
    assert srv.store.pd._timer is None  # close() stopped the loop


def test_pd_metric_families_pass_scrape_check():
    """The tier-1 exposition gate extended to the pd_* families."""
    import os
    import sys

    store = fill_store(rows=400, regions=8, stores=4, pin_store=0)
    for _ in range(4):
        store.pd.tick()
    text = metrics.REGISTRY.dump()
    for family in ("pd_operator_total", "pd_hot_region", "pd_region_heartbeat_total",
                   "pd_regions", "pd_store_regions", "pd_tick_seconds"):
        assert f"# TYPE {family} " in text, family
    assert 'pd_operator_total{type="move-region"}' in text
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    from scrape_check import validate

    assert validate(text) == []


def test_hot_key_workload_end_to_end_acceptance():
    """ISSUE 3 acceptance: a hot-key workload over >= 4 stores converges
    (no store holds more than half the regions), the hotspot view
    reports the hot regions, and the operators show in
    pd_operator_total."""
    s = Session()
    s.execute("CREATE TABLE acc (id BIGINT PRIMARY KEY, v BIGINT)")
    s.execute("INSERT INTO acc VALUES " + ",".join(f"({i},{i % 13})" for i in range(400)))
    tid = s.catalog.table("acc").table_id
    for i in range(1, 8):
        s.store.cluster.split(tablecodec.encode_row_key(tid, i * 50))
    s.store.cluster.set_stores(4)
    for r in s.store.cluster.regions():
        s.store.cluster.set_store(r.region_id, 0)  # worst-case skew
    pd = s.store.pd
    pd.conf.hot_byte_rate = 64.0
    pd.conf.merge_region_keys = -1
    pd.conf.merge_region_size = -1
    op_base = {
        kind: metrics.PD_OPERATORS.labels(kind).value
        for kind in ("move-region", "move-hot-region")
    }
    # the hot-key workload: every query hammers the low-handle range
    for _ in range(6):
        for _ in range(3):
            s.execute("SELECT sum(v) FROM acc WHERE id < 50")
        pd.tick()
    counts = s.store.cluster.counts_per_store()
    total = len(s.store.cluster.regions())
    assert max(counts.values()) <= total / 2, counts
    hot = pd.hotspot_view()
    assert hot["read"], "hot regions must be reported"
    moved = sum(
        metrics.PD_OPERATORS.labels(kind).value - op_base[kind]
        for kind in ("move-region", "move-hot-region")
    )
    assert moved > 0
    # and the balanced data plane still answers correctly
    assert s.execute("SELECT count(*) FROM acc").values() == [[400]]


# ------------------------------------------- PD failpoints under dispatch

def test_pd_failpoints_under_concurrent_dispatch():
    """ISSUE 6 satellite: `pd/heartbeat-lost` + `pd/operator-timeout`
    armed WHILE multi-region scans run from a thread pool must neither
    wedge the tick loop nor leak operators — every proposed operator is
    force-expired, the pending queue drains to zero each tick, and once
    the failpoints disarm the schedulers converge as usual."""
    import threading

    from tidb_tpu.distsql.dispatch import KVRequest, full_table_ranges, select
    from tidb_tpu.exec.dag import ColumnInfo, DAGRequest, TableScan
    from tidb_tpu.types import new_longlong

    rows = 400
    store = fill_store(rows=rows, regions=8, stores=4, pin_store=0)
    dag = DAGRequest((TableScan(TID, (ColumnInfo(1, new_longlong()),)),), output_offsets=(0,))
    stop = threading.Event()
    errors: list = []
    scan_counts: list = []

    def scanner():
        while not stop.is_set():
            try:
                res = select(store, KVRequest(dag, full_table_ranges(TID), 100))
                scan_counts.append(sum(c.num_rows() for c in res.chunks))
            except Exception as exc:  # noqa: BLE001 — any error fails the test
                errors.append(exc)
                return

    threads = [threading.Thread(target=scanner, daemon=True) for _ in range(3)]
    for t in threads:
        t.start()
    try:
        with failpoint.enabled("pd/heartbeat-lost"), \
             failpoint.enabled("pd/operator-timeout"):
            for _ in range(6):
                store.pd.tick()
                # force-expiry ran inside the tick: nothing may linger
                assert store.pd.queue.pending() == []
        # storm over: the loop keeps scheduling normally and converges
        for _ in range(16):
            store.pd.tick()
            counts = store.cluster.counts_per_store()
            if max(counts.values()) - min(counts.values()) <= store.pd.conf.balance_tolerance:
                break
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
    assert not any(t.is_alive() for t in threads), "dispatch wedged under PD failpoints"
    assert errors == []
    assert scan_counts and all(c == rows for c in scan_counts)
    # operators retired during the armed window are all timeouts, none lost
    timed_out = [o for o in store.pd.queue.history if o.state == "timeout"]
    assert timed_out, "operator-timeout failpoint never expired anything"
    assert metrics.REGISTRY.counter("pd_operator_timeout_total").value >= len(timed_out)
    assert store.pd.queue.pending() == []


def test_stores_view_exposes_health_and_breaker_state():
    store = fill_store(rows=100, regions=4, stores=4)
    store.set_down(2)
    store.pd.tick()  # the health probe phase sees the down store
    view = {d["store_id"]: d for d in store.pd.stores_view()}
    assert view[2]["state"] == "down"
    assert view[0]["state"] == "up"
    assert all("breaker" in d for d in view.values())
    store.set_up(2)
    store.pd.tick()
    assert {d["store_id"]: d["state"] for d in store.pd.stores_view()}[2] == "up"
