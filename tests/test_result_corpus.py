"""Result-corpus ratchet (VERDICT r3 missing #3): a pinned set of the
reference's integration files EXECUTES through the session and the
recorded-result match rate may only go UP. Skips cleanly when the
reference tree is absent. The full sweep (all files) runs via
`python tools/result_corpus.py`; this test pins a fast, stable subset so
the suite stays quick and the signal deterministic."""

import os
import sys

import pytest

CORPUS = "/root/reference/tests/integrationtest/t"
# small, fast files with solid current rates (full-run numbers 2026-07-30:
# overall match_rate 0.54, data_match_rate 0.64 over 2191 stmts/37 files)
PINNED = ["select", "agg_predicate_pushdown", "access_path_selection", "cte"]
# measured 2026-07-30 on the pinned set; raise when it improves, never lower
RATCHET_DATA = 0.70


@pytest.mark.skipif(not os.path.isdir(CORPUS), reason="reference corpus not present")
def test_result_corpus_ratchet():
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    from result_corpus import run_corpus

    r = run_corpus(PINNED)
    assert r["executed"] > 250, f"corpus execution collapsed: {r}"
    assert r["data_match_rate"] >= RATCHET_DATA, (
        f"result-corpus data match rate regressed: {r}"
    )
