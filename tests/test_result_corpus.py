"""Result-corpus ratchet (VERDICT r3 missing #3, r4 next #2): the FULL
37-file reference integration corpus EXECUTES through the session and the
recorded-result match rate may only go UP. The sweep runs hermetic-CPU in
~25s (tools/result_corpus.py pops the axon TPU factory — it used to
round-trip the tunnel), so the whole corpus ratchets, not a pinned subset.
Skips cleanly when the reference tree is absent."""

import os
import sys

import pytest

CORPUS = "/root/reference/tests/integrationtest/t"
# measured 2026-07-31 (round 5): data_match_rate 0.8269 over 2235
# statements / 37 files with ZERO desync (wrapped-echo matching fixed
# the tpch file, so 44 previously unalignable statements now execute
# and count — the denominator grew). Charset/binary package,
# expression-index degradation, FROM DUAL, mysql.* bootstrap, row
# expressions, EXTRACT incl. composite units, SUBSTRING FROM/FOR.
# Raise when it improves, never lower.
RATCHET_DATA = 0.82
RATCHET_EXEC = 2200  # executed statements (desync guard)

# per-file floors for the former pinned set (these carried the round-4
# ratchet; keep them from silently regressing inside a passing aggregate)
PER_FILE = {"select": 0.80, "agg_predicate_pushdown": 0.70,
            "access_path_selection": 0.50, "cte": 0.75}


@pytest.mark.skipif(not os.path.isdir(CORPUS), reason="reference corpus not present")
def test_result_corpus_ratchet():
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    from result_corpus import run_corpus

    r = run_corpus(per_file=True)
    details = r.pop("details")
    assert r["executed"] >= RATCHET_EXEC, f"corpus execution collapsed: {r}"
    assert r["data_match_rate"] >= RATCHET_DATA, (
        f"result-corpus data match rate regressed: {r}"
    )
    for name, floor in PER_FILE.items():
        c = details[name]["counts"]
        ex = sum(c.values()) - c["desync"] - c["explain_diff"]
        rate = (c["match"] + c["error_ok"]) / ex if ex else 0.0
        assert rate >= floor, f"{name} data-match regressed to {rate:.3f}: {c}"
