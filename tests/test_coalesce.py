"""Cross-session fused execution (ISSUE 19): the per-store session
coalescer — concurrent plan-cache-hit point gets batched into one device
launch, autocommit writes folded into group commits — plus the DML
point-write plan-cache tier and the shared cross-catalog tier. Every
coalesced result must be byte-equal to its uncoalesced oracle; every
fault falls out to the single path as a typed, counted fallback."""

import os
import sys
import threading
import time

import pytest

from tidb_tpu.sql.session import Session, SQLError
from tidb_tpu.store.txn import TxnError
from tidb_tpu.util import failpoint, metrics

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))


def make_store(rows=16):
    s = Session()
    s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT, k VARCHAR(20))")
    if rows:
        s.execute("INSERT INTO t VALUES " + ",".join(
            f"({i},{i * 10},'x{i}')" for i in range(rows)))
    return s


def clone(s, wait_us=20000):
    """A concurrent session over the same store/catalog, coalescing ON
    with a window wide enough that barrier-released lanes reliably meet."""
    x = Session(store=s.store, catalog=s.catalog)
    x.execute("SET tidb_tpu_enable_coalesce = ON")
    x.execute(f"SET tidb_tpu_coalesce_wait_us = {wait_us}")
    return x


def same_rows(a, b):
    assert len(a) == len(b), (a, b)
    for ra, rb in zip(a, b):
        assert len(ra) == len(rb)
        for da, db in zip(ra, rb):
            assert da.kind == db.kind and da.val == db.val, (da, db)


def fallbacks(reason):
    return metrics.COALESCE_FALLBACKS.labels(reason).value


# ------------------------------------------------- coalesced reads

def test_coalesced_reads_match_uncoalesced_oracle():
    """N sessions × mixed point statements, concurrent with coalescing
    ON, byte-equal to a cold parse+plan oracle session."""
    s = make_store(rows=32)
    s.execute("CREATE TABLE u (id BIGINT PRIMARY KEY, w BIGINT)")
    s.execute("INSERT INTO u VALUES " + ",".join(
        f"({i},{i * 7})" for i in range(32)))
    oracle = Session(store=s.store, catalog=s.catalog)
    oracle.execute("SET tidb_enable_plan_cache = OFF")

    def stmts(i):
        return [
            f"SELECT v FROM t WHERE id = {i}",
            f"SELECT id, v FROM t WHERE id IN ({i}, {i + 8}, {i + 16})",
            f"SELECT k FROM t WHERE id = {i} AND v > 1",
            f"SELECT w FROM u WHERE id = {i}",
            f"SELECT v FROM t WHERE id = {1000 + i}",  # no such row
        ]

    # warm the digests so the concurrent wave rides the pointget tier
    for sql in stmts(1):
        s.execute(sql)

    n, rounds = 6, 3
    sessions = [clone(s) for _ in range(n)]
    barrier = threading.Barrier(n)
    got = [[] for _ in range(n)]
    errors = []

    def run(i):
        try:
            for _r in range(rounds):
                barrier.wait()
                for sql in stmts(i):
                    got[i].append((sql, sessions[i].execute(sql).rows))
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    b0 = metrics.COALESCE_BATCHES.value
    l0 = metrics.COALESCE_LANES.labels("read").value
    threads = [threading.Thread(target=run, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    for i in range(n):
        assert len(got[i]) == rounds * 5
        for sql, rows in got[i]:
            same_rows(rows, oracle.execute(sql).rows)
    assert metrics.COALESCE_BATCHES.value > b0
    # nearly every statement parked in some window (a handful may ride
    # the single path if its session's window raced shut)
    assert metrics.COALESCE_LANES.labels("read").value - l0 >= n * rounds


def test_coalesced_reads_save_launches():
    """Same-table lanes in one window share a DAG fingerprint, so the
    batch stacks them into one vmapped launch — launches-saved counts."""
    s = make_store(rows=32)
    s.execute("SELECT v FROM t WHERE id = 1")  # install pointget entry
    n = 8
    sessions = [clone(s) for _ in range(n)]
    barrier = threading.Barrier(n)
    sv0 = metrics.COALESCE_LAUNCHES_SAVED.value

    def run(i):
        barrier.wait()
        assert sessions[i].execute(
            f"SELECT v FROM t WHERE id = {i}").rows[0][0].val == i * 10

    threads = [threading.Thread(target=run, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert metrics.COALESCE_LAUNCHES_SAVED.value > sv0


def test_fault_lane_falls_out_mid_batch():
    """A region fault on one lane's cop request mid-batch: that lane
    falls out (typed, counted) and its session answers through the
    single path — rows still byte-correct, other lanes unaffected."""
    s = make_store(rows=16)
    s.execute("SELECT v FROM t WHERE id = 1")
    n = 4
    sessions = [clone(s) for _ in range(n)]
    barrier = threading.Barrier(n)
    out = [None] * n
    f0 = fallbacks("fault_lane")

    def run(i):
        barrier.wait()
        out[i] = sessions[i].execute(f"SELECT v FROM t WHERE id = {i}").rows

    with failpoint.enabled("cop-region-error", 1):
        threads = [threading.Thread(target=run, args=(i,)) for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
    for i in range(n):
        assert out[i][0][0].val == i * 10
    assert fallbacks("fault_lane") > f0


def test_window_stall_follower_withdraws():
    """coalesce/window-stall wedges the leader past the follower's
    patience: the follower withdraws its unclaimed lane (typed
    window_stall fall-out → single path), the leader still answers its
    own lane after the hold."""
    s = make_store()
    meta = s.catalog.table("t")
    co = s.store.coalescer
    results = {}
    f0 = fallbacks("window_stall")

    def call(name, delay):
        if delay:
            time.sleep(delay)
        results[name] = co.point_get(meta, [1], wait_us=100_000, max_lanes=8)

    with failpoint.enabled("coalesce/window-stall", 0.8):
        t1 = threading.Thread(target=call, args=("leader", 0))
        t2 = threading.Thread(target=call, args=("follower", 0.02))
        t1.start()
        t2.start()
        t1.join(timeout=30)
        t2.join(timeout=30)
    vals = list(results.values())
    assert sum(v is None for v in vals) == 1  # the stalled-out lane
    served = next(v for v in vals if v is not None)
    assert served[1][1].val == 10  # row for handle 1: [id, v, k]
    assert fallbacks("window_stall") > f0


def test_flush_lost_read_lanes_fall_back():
    """coalesce/flush-lost loses a window's flush before any lane is
    answered: every lane falls out (counted) and re-runs its single
    path — no statement lost, rows byte-correct."""
    s = make_store(rows=16)
    s.execute("SELECT v FROM t WHERE id = 1")
    n = 4
    sessions = [clone(s) for _ in range(n)]
    barrier = threading.Barrier(n)
    out = [None] * n
    f0 = fallbacks("flush_lost")

    def run(i):
        barrier.wait()
        out[i] = sessions[i].execute(f"SELECT v FROM t WHERE id = {i}").rows

    with failpoint.enabled("coalesce/flush-lost", 1):
        threads = [threading.Thread(target=run, args=(i,)) for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
    for i in range(n):
        assert out[i][0][0].val == i * 10
    assert fallbacks("flush_lost") > f0


# ------------------------------------------------- group commit

def test_group_commit_concurrent_writes_apply():
    """Concurrent autocommit single-row writes coalesce into group
    commits: every write lands, distinct sessions' lanes share windows
    (group commits counted), final state equals the serial outcome."""
    s = make_store(rows=8)
    n, rounds = 6, 4
    sessions = [clone(s) for _ in range(n)]
    barrier = threading.Barrier(n)
    errors = []

    def run(i):
        try:
            for _r in range(rounds):
                barrier.wait()
                sessions[i].execute(f"UPDATE t SET v = v + 1 WHERE id = {i}")
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    g0 = metrics.COALESCE_GROUP_COMMITS.value
    threads = [threading.Thread(target=run, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    for i in range(n):
        got = s.execute(f"SELECT v FROM t WHERE id = {i}").rows[0][0].val
        assert got == i * 10 + rounds
    assert metrics.COALESCE_GROUP_COMMITS.value > g0


def test_group_commit_saves_proposals():
    """A multi-lane write window folds into one quorum proposal per
    (region, window): proposals-saved counts the fold."""
    s = make_store(rows=8)
    n = 6
    sessions = [clone(s) for _ in range(n)]
    barrier = threading.Barrier(n)

    def run(i):
        barrier.wait()
        sessions[i].execute(f"UPDATE t SET v = {i + 100} WHERE id = {i}")

    p0 = metrics.COALESCE_GROUP_PROPOSALS_SAVED.value
    for _attempt in range(5):  # barrier makes a shared window near-certain
        threads = [threading.Thread(target=run, args=(i,)) for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        if metrics.COALESCE_GROUP_PROPOSALS_SAVED.value > p0:
            break
    assert metrics.COALESCE_GROUP_PROPOSALS_SAVED.value > p0


def test_commit_group_engine_semantics():
    """TxnEngine.commit_group: one result per lane — ascending commit ts
    for committed lanes, a TxnError instance for an intra-window key
    conflict (its locks released, the window standing), None for an
    empty lane."""
    from tidb_tpu.codec import tablecodec

    s = make_store(rows=4)
    st = s.store
    tid = s.catalog.table("t").table_id
    k1 = tablecodec.encode_row_key(tid, 101)
    k2 = tablecodec.encode_row_key(tid, 102)
    ts1, ts2, ts3 = st.next_ts(), st.next_ts(), st.next_ts()
    res = st.txn.commit_group(
        [({k1: b"a"}, ts1), ({k1: b"b"}, ts2), ({k2: b"c"}, ts3)],
        st.next_ts,
    )
    assert isinstance(res[0], int) and isinstance(res[2], int)
    assert res[2] > res[0]
    assert isinstance(res[1], TxnError)
    now = st.next_ts()
    assert st.kv.get(k1, now) == b"a"
    assert st.kv.get(k2, now) == b"c"
    # the refused lane released its locks: a follow-up commit succeeds
    res2 = st.txn.commit_group([({k1: b"b2"}, st.next_ts())], st.next_ts)
    assert isinstance(res2[0], int)
    assert st.kv.get(k1, st.next_ts()) == b"b2"
    # empty lane: nothing staged, nothing reported
    assert st.txn.commit_group([({}, st.next_ts())], st.next_ts) == [None]


def test_group_commit_lane_error_raises_typed():
    """A lane the engine refuses with a typed non-conflict error (quorum
    lost) raises in that lane's session — falling back would fail
    identically, so the coalescer must not retry it."""
    from tidb_tpu.codec import tablecodec
    from tidb_tpu.store import QuorumLostError

    s = make_store(rows=4)
    st = s.store
    tid = s.catalog.table("t").table_id
    k = tablecodec.encode_row_key(tid, 300)
    orig = st.txn._pre_apply

    def refuse(keys):
        raise QuorumLostError(1, 1, 2)

    st.txn._pre_apply = refuse
    try:
        with pytest.raises(QuorumLostError):
            st.coalescer.group_commit({k: b"z"}, st.next_ts(),
                                      wait_us=1000, max_lanes=4)
    finally:
        st.txn._pre_apply = orig


def test_flush_lost_write_lanes_fall_back():
    """coalesce/flush-lost on a write window: lanes fall out and commit
    through the single path — the write still lands exactly once."""
    s = make_store(rows=8)
    n = 4
    sessions = [clone(s) for _ in range(n)]
    barrier = threading.Barrier(n)
    errors = []
    f0 = fallbacks("flush_lost")

    def run(i):
        try:
            barrier.wait()
            sessions[i].execute(f"UPDATE t SET v = {i + 500} WHERE id = {i}")
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    with failpoint.enabled("coalesce/flush-lost", 1):
        threads = [threading.Thread(target=run, args=(i,)) for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
    assert not errors, errors
    for i in range(n):
        assert s.execute(
            f"SELECT v FROM t WHERE id = {i}").rows[0][0].val == i + 500
    assert fallbacks("flush_lost") > f0


def test_group_commit_cdc_per_key_order():
    """Group-committed windows must replicate in commit-ts order: the
    changefeed's ordering oracle (per-key strictly increasing commit ts,
    monotone resolved marks) stays clean under concurrent coalesced
    writers."""
    from chaos import CheckingSink

    from tidb_tpu.cdc import MemorySink

    s = Session()
    s.execute("CREATE TABLE gc (id BIGINT PRIMARY KEY, v BIGINT)")
    s.execute("INSERT INTO gc VALUES " + ",".join(
        f"({i},{i * 10})" for i in range(8)))
    sink = CheckingSink(MemorySink())
    s.store.cdc.create("gc", sink, s.catalog, start_ts=0)
    n, rounds = 6, 6
    sessions = [clone(s) for _ in range(n)]
    barrier = threading.Barrier(n)
    errors = []

    def run(i):
        try:
            for _r in range(rounds):
                barrier.wait()
                # distinct key per session per window; the same key
                # round after round exercises per-key commit order
                sessions[i].execute(f"UPDATE gc SET v = v + 1 WHERE id = {i}")
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    threads = [threading.Thread(target=run, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    for _ in range(4):
        s.store.cdc.tick()
    assert sink.violations == [], sink.violations
    for i in range(n):
        assert s.execute(
            f"SELECT v FROM gc WHERE id = {i}").rows[0][0].val == i * 10 + rounds


def test_coalesce_lockwatch_storm():
    """Coalesced readers + group-committing writers + the PD tick under
    the runtime lockset detector: the coalescer mutex is a leaf, so zero
    lock-order cycles and zero guarded-access violations."""
    from tidb_tpu.analysis import lockwatch

    with lockwatch.watching() as w:
        s = make_store(rows=32)
        s.execute("SELECT v FROM t WHERE id = 1")  # pointget entry
        stop = threading.Event()
        errors = []

        def reader(i):
            sess = clone(s, wait_us=2000)
            j = 0
            while not stop.is_set():
                try:
                    sess.execute(f"SELECT v FROM t WHERE id = {(i + j) % 32}")
                    j += 1
                except SQLError:
                    pass
                except Exception as exc:  # noqa: BLE001
                    errors.append(exc)
                    return

        def writer(i):
            sess = clone(s, wait_us=2000)
            j = 0
            while not stop.is_set():
                try:
                    sess.execute(
                        f"UPDATE t SET v = v + 1 WHERE id = {(i + j) % 32}")
                    j += 1
                except SQLError:
                    pass  # cross-window write conflicts are the race's
                except Exception as exc:  # noqa: BLE001 — typed surface
                    errors.append(exc)
                    return

        def ticker():
            while not stop.is_set():
                try:
                    s.store.pd.tick()
                except Exception as exc:  # noqa: BLE001
                    errors.append(exc)
                    return

        threads = [threading.Thread(target=reader, args=(i,), daemon=True)
                   for i in range(3)]
        threads += [threading.Thread(target=writer, args=(i,), daemon=True)
                    for i in range(2)]
        threads.append(threading.Thread(target=ticker, daemon=True))
        for t in threads:
            t.start()
        time.sleep(1.2)
        stop.set()
        for t in threads:
            t.join(timeout=30)
    rep = w.report()
    assert rep["cycles"] == [], rep["cycles"]
    assert rep["violations"] == [], "\n".join(rep["violations"])
    assert not errors, errors


# ------------------------------------------------- DML point-write tier

def test_pointwrite_tier_update_hits():
    s = make_store()
    s.execute("UPDATE t SET v = 777 WHERE id = 3")
    assert s._last_plan_cache[0] == "miss"
    h0 = metrics.PLAN_CACHE_HITS.value
    res = s.execute("UPDATE t SET v = 888 WHERE id = 4")  # same digest
    assert res.affected == 1
    assert s._last_plan_cache == ("hit", "", "pointwrite")
    assert metrics.PLAN_CACHE_HITS.value == h0 + 1
    assert s.execute("SELECT v FROM t WHERE id = 3").rows[0][0].val == 777
    assert s.execute("SELECT v FROM t WHERE id = 4").rows[0][0].val == 888
    assert s.catalog.plan_cache.stats()["tiers"]["pointwrite"] >= 1


def test_pointwrite_tier_delete_and_in_list():
    s = make_store()
    s.execute("DELETE FROM t WHERE id = 1")
    res = s.execute("DELETE FROM t WHERE id = 2")  # hit
    assert res.affected == 1
    assert s._last_plan_cache == ("hit", "", "pointwrite")
    assert s.execute("SELECT v FROM t WHERE id IN (1, 2)").rows == []
    s.execute("UPDATE t SET v = 0 WHERE id IN (5, 6)")
    res = s.execute("UPDATE t SET v = 1 WHERE id IN (7, 8)")  # hit
    assert res.affected == 2
    assert s._last_plan_cache == ("hit", "", "pointwrite")
    assert [r[0].val for r in s.execute(
        "SELECT v FROM t WHERE id IN (5, 6, 7, 8) ORDER BY id").rows] == [0, 0, 1, 1]


def test_pointwrite_tier_declines_typed():
    s = make_store()
    d0 = metrics.PLAN_CACHE_DECLINES.labels("dml_shape").value
    s.execute("UPDATE t SET v = 1 WHERE v = 10")  # not a pk point write
    assert metrics.PLAN_CACHE_DECLINES.labels("dml_shape").value == d0 + 1
    assert s._last_plan_cache == ("decline", "dml_shape", "")
    i0 = metrics.PLAN_CACHE_DECLINES.labels("in_txn").value
    s.execute("BEGIN")
    s.execute("UPDATE t SET v = 2 WHERE id = 5")
    s.execute("COMMIT")
    assert metrics.PLAN_CACHE_DECLINES.labels("in_txn").value == i0 + 1
    assert s.execute("SELECT v FROM t WHERE id = 5").rows[0][0].val == 2


def test_pointwrite_hit_serves_through_coalescer():
    """A pointwrite-tier hit reaches the group-commit window: the serve
    path is parse-free AND its write coalesces."""
    s = make_store(rows=8)
    s.execute("SET tidb_tpu_enable_coalesce = ON")
    s.execute("UPDATE t SET v = 1 WHERE id = 1")  # install
    g0 = metrics.COALESCE_LANES.labels("write").value
    s.execute("UPDATE t SET v = 2 WHERE id = 2")  # pointwrite hit
    assert s._last_plan_cache == ("hit", "", "pointwrite")
    # single-lane window still flushes through the coalescer
    assert metrics.COALESCE_LANES.labels("write").value > g0
    assert s.execute("SELECT v FROM t WHERE id = 2").rows[0][0].val == 2


# ------------------------------------------------- shared cross-catalog tier

def test_shared_tier_adopts_across_catalogs():
    from tidb_tpu.sql import plancache as pc

    pc.SHARED_CACHE.clear()
    a = Session()
    a.execute("SET tidb_tpu_plan_cache_shared = ON")
    a.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
    a.execute("INSERT INTO t VALUES (1, 10), (2, 20)")
    a.execute("SELECT v FROM t WHERE id = 1")  # install + publish
    b = Session()  # fresh store + catalog: identical bootstrap → same ids
    b.execute("SET tidb_tpu_plan_cache_shared = ON")
    b.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
    b.execute("INSERT INTO t VALUES (1, 11), (2, 22)")
    h0 = metrics.PLAN_CACHE_SHARED_HITS.value
    r = b.execute("SELECT v FROM t WHERE id = 2")
    assert r.rows[0][0].val == 22  # bound against B's data, not A's
    assert metrics.PLAN_CACHE_SHARED_HITS.value == h0 + 1
    assert b._last_plan_cache == ("hit", "", "pointget")
    # promoted: the next statement hits B's local cache, not the shared tier
    b.execute("SELECT v FROM t WHERE id = 1")
    assert metrics.PLAN_CACHE_SHARED_HITS.value == h0 + 1


def test_shared_tier_rejects_schema_drift():
    from tidb_tpu.sql import plancache as pc

    pc.SHARED_CACHE.clear()
    a = Session()
    a.execute("SET tidb_tpu_plan_cache_shared = ON")
    a.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
    a.execute("INSERT INTO t VALUES (1, 10)")
    a.execute("SELECT v FROM t WHERE id = 1")
    c = Session()
    c.execute("SET tidb_tpu_plan_cache_shared = ON")
    c.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v VARCHAR(8))")
    c.execute("INSERT INTO t VALUES (1, 'a')")
    h0 = metrics.PLAN_CACHE_SHARED_HITS.value
    r = c.execute("SELECT v FROM t WHERE id = 1")  # fingerprint mismatch
    assert r.rows[0][0].val == "a"
    assert metrics.PLAN_CACHE_SHARED_HITS.value == h0
    # the home catalog's entry survives the rejected adoption
    a2 = Session(store=a.store, catalog=a.catalog)
    a2.execute("SET tidb_tpu_plan_cache_shared = ON")
    assert a2.execute("SELECT v FROM t WHERE id = 1").rows[0][0].val == 10
    assert a2._last_plan_cache[0] == "hit"


def test_shared_tier_off_by_default():
    from tidb_tpu.sql import plancache as pc

    pc.SHARED_CACHE.clear()
    a = Session()
    a.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
    a.execute("INSERT INTO t VALUES (1, 10)")
    a.execute("SELECT v FROM t WHERE id = 1")
    assert len(pc.SHARED_CACHE) == 0  # no publish without the sysvar
