"""SQL front-to-back (VERDICT next #2): parse -> plan -> execute_root over
the embedded store. No hand-built DAGs anywhere — the parser is no longer an
island. Expected values are computed in plain Python over the same data."""

import pytest

from tidb_tpu.sql import CatalogError, PlanError, Session, SQLError


@pytest.fixture()
def sess():
    s = Session()
    s.execute(
        "CREATE TABLE emp (id BIGINT PRIMARY KEY, dept VARCHAR(10), salary DECIMAL(10,2),"
        " age INT, hired DATETIME, bonus DOUBLE)"
    )
    rows = [
        (1, "'eng'", "1000.00", 30, "'2020-01-15 00:00:00'", 0.1),
        (2, "'eng'", "2000.00", 35, "'2019-06-01 00:00:00'", 0.2),
        (3, "'sales'", "1500.00", 28, "'2021-03-10 00:00:00'", "NULL"),
        (4, "'sales'", "500.00", 45, "'2018-11-20 00:00:00'", 0.05),
        (5, "'hr'", "800.00", 30, "'2022-07-04 00:00:00'", 0.0),
        (6, "NULL", "1200.00", "NULL", "NULL", 0.15),
    ]
    vals = ", ".join(f"({', '.join(str(v) for v in r)})" for r in rows)
    s.execute(f"INSERT INTO emp (id, dept, salary, age, hired, bonus) VALUES {vals}")
    return s


class TestBasics:
    def test_count_scan(self, sess):
        assert sess.execute("SELECT count(*) FROM emp").scalar() == 6

    def test_where_filter(self, sess):
        r = sess.execute("SELECT id FROM emp WHERE salary > 1000 ORDER BY id")
        assert [x for x, in r.values()] == [2, 3, 6]

    def test_projection_expr(self, sess):
        r = sess.execute("SELECT id, salary * 2 FROM emp WHERE id = 1")
        assert str(r.rows[0][1].val) == "2000.00"

    def test_select_star(self, sess):
        r = sess.execute("SELECT * FROM emp WHERE id = 5")
        assert r.columns == ["id", "dept", "salary", "age", "hired", "bonus"]
        assert r.values()[0][:4] == [5, "hr", r.rows[0][2].val, 30]

    def test_order_desc_limit_offset(self, sess):
        r = sess.execute("SELECT id FROM emp ORDER BY salary DESC LIMIT 2 OFFSET 1")
        assert [x for x, in r.values()] == [3, 6]

    def test_limit_no_order(self, sess):
        assert len(sess.execute("SELECT id FROM emp LIMIT 3").rows) == 3

    def test_order_without_limit_sorts_all(self, sess):
        r = sess.execute("SELECT id FROM emp ORDER BY age, id")
        # NULL age sorts first (MySQL), ties by id
        assert [x for x, in r.values()] == [6, 3, 1, 5, 2, 4]

    def test_in_between_like_case(self, sess):
        assert len(sess.execute("SELECT id FROM emp WHERE dept IN ('eng', 'hr')").rows) == 3
        assert len(sess.execute("SELECT id FROM emp WHERE age BETWEEN 28 AND 35").rows) == 4
        assert len(sess.execute("SELECT id FROM emp WHERE dept LIKE 'e%'").rows) == 2
        r = sess.execute(
            "SELECT id, CASE WHEN salary >= 1500 THEN 'high' WHEN salary >= 800 THEN 'mid' ELSE 'low' END FROM emp ORDER BY id"
        )
        assert [v for _, v in r.values()] == ["mid", "high", "high", "low", "mid", "mid"]

    def test_null_semantics(self, sess):
        assert sess.execute("SELECT count(*) FROM emp WHERE dept IS NULL").scalar() == 1
        assert sess.execute("SELECT count(*) FROM emp WHERE dept IS NOT NULL").scalar() == 5
        # NULL never satisfies a comparison
        assert sess.execute("SELECT count(*) FROM emp WHERE age <> 30").scalar() == 3

    def test_datetime_compare(self, sess):
        r = sess.execute("SELECT id FROM emp WHERE hired >= '2021-01-01' ORDER BY id")
        assert [x for x, in r.values()] == [3, 5]

    def test_select_no_from(self, sess):
        assert sess.execute("SELECT 2 + 3 * 4").scalar() == 14


class TestAggregation:
    def test_scalar_aggs(self, sess):
        r = sess.execute("SELECT count(*), count(age), sum(salary), min(age), max(age), avg(salary) FROM emp")
        v = r.rows[0]
        assert v[0].val == 6 and v[1].val == 5
        assert str(v[2].val) == "7000.00"
        assert v[3].val == 28 and v[4].val == 45
        assert str(v[5].val) == "1166.666667"

    def test_group_by_having_order(self, sess):
        r = sess.execute(
            "SELECT dept, count(*) c, sum(salary) FROM emp GROUP BY dept HAVING c >= 2 ORDER BY dept"
        )
        assert r.values() == [["eng", 2, r.rows[0][2].val], ["sales", 2, r.rows[1][2].val]]
        assert str(r.rows[0][2].val) == "3000.00"

    def test_implicit_first_row(self, sess):
        # bare column outside GROUP BY -> implicit first_row (loose mode)
        r = sess.execute("SELECT dept, age FROM emp GROUP BY dept ORDER BY dept")
        assert len(r.rows) == 4  # NULL dept forms a group

    def test_distinct(self, sess):
        r = sess.execute("SELECT DISTINCT age FROM emp ORDER BY age")
        assert [x for x, in r.values()] == [None, 28, 30, 35, 45]

    def test_count_distinct(self, sess):
        assert sess.execute("SELECT count(DISTINCT age) FROM emp").scalar() == 4

    def test_group_expr_key(self, sess):
        r = sess.execute("SELECT age > 30, count(*) FROM emp GROUP BY age > 30 ORDER BY count(*)")
        got = sorted(r.values(), key=lambda x: (x[0] is not None, x[0] or 0))
        assert got == [[None, 1], [0, 3], [1, 2]]

    def test_min_max_string(self, sess):
        r = sess.execute("SELECT min(dept), max(dept) FROM emp")
        assert r.values()[0] == ["eng", "sales"]


class TestJoins:
    @pytest.fixture()
    def jsess(self, sess):
        sess.execute("CREATE TABLE dept (dname VARCHAR(10), head VARCHAR(20), budget BIGINT)")
        sess.execute("INSERT INTO dept VALUES ('eng','ada',100), ('sales','tina',50), ('ops','zed',10)")
        return sess

    def test_inner_join_where(self, jsess):
        r = jsess.execute(
            "SELECT e.id, d.head FROM emp e, dept d WHERE e.dept = d.dname ORDER BY e.id"
        )
        assert r.values() == [[1, "ada"], [2, "ada"], [3, "tina"], [4, "tina"]]

    def test_join_on_syntax(self, jsess):
        r = jsess.execute(
            "SELECT d.head, sum(e.salary) FROM emp e JOIN dept d ON e.dept = d.dname GROUP BY d.head ORDER BY d.head"
        )
        assert [h for h, _ in r.values()] == ["ada", "tina"]
        assert str(r.rows[0][1].val) == "3000.00"

    def test_left_join(self, jsess):
        r = jsess.execute(
            "SELECT d.dname, e.id FROM dept d LEFT JOIN emp e ON d.dname = e.dept ORDER BY d.dname, e.id"
        )
        vals = r.values()
        assert ["ops", None] in vals  # null-extended
        assert len(vals) == 5

    def test_cartesian(self, jsess):
        assert jsess.execute("SELECT count(*) FROM emp, dept").scalar() == 18

    def test_three_way_join(self, jsess):
        jsess.execute("CREATE TABLE region (head2 VARCHAR(20), zone VARCHAR(8))")
        jsess.execute("INSERT INTO region VALUES ('ada','west'), ('tina','east')")
        r = jsess.execute(
            "SELECT e.id, r.zone FROM emp e, dept d, region r"
            " WHERE e.dept = d.dname AND d.head = r.head2 AND e.salary >= 1500 ORDER BY e.id"
        )
        assert r.values() == [[2, "west"], [3, "east"]]


class TestDML:
    def test_update_delete_truncate(self, sess):
        sess.execute("UPDATE emp SET salary = salary + 100 WHERE dept = 'eng'")
        assert str(sess.execute("SELECT sum(salary) FROM emp WHERE dept = 'eng'").scalar()) == "3200.00"
        n = sess.execute("DELETE FROM emp WHERE age > 40").affected
        assert n == 1 and sess.execute("SELECT count(*) FROM emp").scalar() == 5
        sess.execute("TRUNCATE TABLE emp")
        assert sess.execute("SELECT count(*) FROM emp").scalar() == 0

    def test_insert_select(self, sess):
        sess.execute("CREATE TABLE emp2 (id BIGINT PRIMARY KEY, salary DECIMAL(10,2))")
        sess.execute("INSERT INTO emp2 (id, salary) SELECT id, salary FROM emp WHERE salary >= 1000")
        assert sess.execute("SELECT count(*) FROM emp2").scalar() == 4

    def test_autoid(self, sess):
        sess.execute("CREATE TABLE noid (v INT)")
        sess.execute("INSERT INTO noid VALUES (7), (8)")
        assert sess.execute("SELECT count(*) FROM noid").scalar() == 2


class TestReviewRegressions:
    """Fixes from the round-2 review: MySQL-semantics edge cases."""

    def test_left_join_where_applies_post_join(self, sess):
        sess.execute("CREATE TABLE dept2 (dname VARCHAR(10))")
        sess.execute("INSERT INTO dept2 VALUES ('eng'), ('sales'), ('ops')")
        r = sess.execute(
            "SELECT d.dname, e.id FROM dept2 d LEFT JOIN emp e ON d.dname = e.dept WHERE e.salary > 1500"
        )
        assert r.values() == [["eng", 2]]  # null-extended rows filtered by WHERE

    def test_delete_order_limit(self, sess):
        n = sess.execute("DELETE FROM emp ORDER BY salary LIMIT 2").affected
        assert n == 2
        # lowest two salaries (500, 800) gone
        assert sess.execute("SELECT min(salary) FROM emp").scalar() is not None
        assert str(sess.execute("SELECT min(salary) FROM emp").scalar()) == "1000.00"

    def test_join_using(self, sess):
        sess.execute("CREATE TABLE u1 (g INT, x INT)")
        sess.execute("CREATE TABLE u2 (g INT, y INT)")
        sess.execute("INSERT INTO u1 VALUES (1,10),(1,11),(2,20)")
        sess.execute("INSERT INTO u2 VALUES (1,100),(2,200),(3,300)")
        assert sess.execute("SELECT count(*) FROM u1 JOIN u2 USING (g)").scalar() == 3

    def test_alias_shadowing(self, sess):
        # WHERE resolves against the real column, not the select alias
        r = sess.execute("SELECT salary * 2 AS salary, id FROM emp WHERE salary > 1800 ORDER BY id")
        assert [i for _, i in r.values()] == [2]
        # self-alias must not recurse
        assert len(sess.execute("SELECT salary AS salary FROM emp").rows) == 6

    def test_duplicate_pk(self, sess):
        with pytest.raises(SQLError, match="duplicate entry"):
            sess.execute("INSERT INTO emp (id, salary) VALUES (1, 1.00)")
        sess.execute("INSERT IGNORE INTO emp (id, salary) VALUES (1, 1.00)")  # skipped
        assert str(sess.execute("SELECT salary FROM emp WHERE id = 1").scalar()) == "1000.00"
        sess.execute("REPLACE INTO emp (id, dept, salary, age, hired, bonus) VALUES (1, 'ops', 9.00, 1, NULL, 0)")
        assert str(sess.execute("SELECT salary FROM emp WHERE id = 1").scalar()) == "9.00"
        assert sess.execute("SELECT count(*) FROM emp").scalar() == 6

    def test_update_sequential_assignment(self, sess):
        sess.execute("CREATE TABLE seqt (id BIGINT PRIMARY KEY, a INT, b INT)")
        sess.execute("INSERT INTO seqt VALUES (1, 1, 100)")
        sess.execute("UPDATE seqt SET a = 5, b = a WHERE id = 1")
        assert sess.execute("SELECT b FROM seqt").scalar() == 5

    def test_order_by_position(self, sess):
        r = sess.execute("SELECT id FROM emp ORDER BY 1 DESC LIMIT 3")
        assert [x for x, in r.values()] == [6, 5, 4]

    def test_insert_select_width_mismatch(self, sess):
        sess.execute("CREATE TABLE w (a INT)")
        with pytest.raises(SQLError, match="column count"):
            sess.execute("INSERT INTO w (a) SELECT id, age FROM emp")

    def test_update_pk_moves_row(self, sess):
        sess.execute("CREATE TABLE pk (id BIGINT PRIMARY KEY, v INT)")
        sess.execute("INSERT INTO pk VALUES (1, 10)")
        sess.execute("UPDATE pk SET id = 5 WHERE id = 1")
        assert sess.execute("SELECT count(*) FROM pk").scalar() == 1
        with pytest.raises(SQLError, match="duplicate entry"):
            sess.execute("INSERT INTO pk VALUES (5, 99)")
        sess.execute("INSERT INTO pk VALUES (1, 99)")  # old key is free again
        with pytest.raises(SQLError, match="duplicate entry"):
            sess.execute("UPDATE pk SET id = 5 WHERE id = 1")

    def test_non_int_pk_nonclustered(self, sess):
        """Non-int / composite PRIMARY KEY now lands as the reference's
        NONCLUSTERED layout: implicit rowid handle + unique PRIMARY index
        with enforced uniqueness and NOT NULL."""
        sess.execute("CREATE TABLE sp (a VARCHAR(10) PRIMARY KEY)")
        sess.execute("INSERT INTO sp VALUES ('x')")
        with pytest.raises(Exception, match="duplicate"):
            sess.execute("INSERT INTO sp VALUES ('x')")
        with pytest.raises(Exception, match="null"):
            sess.execute("INSERT INTO sp VALUES (NULL)")
        sess.execute("CREATE TABLE cp (a INT, b INT, PRIMARY KEY (a, b))")
        sess.execute("INSERT INTO cp VALUES (1, 2)")
        with pytest.raises(Exception, match="duplicate"):
            sess.execute("INSERT INTO cp VALUES (1, 2)")
        assert sess.execute("SELECT a FROM sp").values() == [["x"]]

    def test_star_textual_order_after_reorder(self, sess):
        sess.execute("CREATE TABLE small (k BIGINT PRIMARY KEY, s VARCHAR(4))")
        sess.execute("INSERT INTO small VALUES (30, 'x')")
        # emp has more rows -> becomes probe; * must still list small first
        r = sess.execute("SELECT * FROM small, emp WHERE small.k = emp.age AND emp.id = 1")
        assert r.columns[:2] == ["k", "s"] and r.values()[0][:2] == [30, "x"]

    def test_ambiguous_column(self, sess):
        sess.execute("CREATE TABLE amb1 (x INT, a INT)")
        sess.execute("CREATE TABLE amb2 (x INT, b INT)")
        sess.execute("INSERT INTO amb1 VALUES (1, 1)")
        sess.execute("INSERT INTO amb2 VALUES (1, 2)")
        with pytest.raises(PlanError, match="ambiguous"):
            sess.execute("SELECT a FROM amb1, amb2 WHERE x > 0 AND amb1.a = amb2.b")


class TestMeta:
    def test_show_tables(self, sess):
        r = sess.execute("SHOW TABLES")
        assert ["emp"] in r.values()

    def test_explain_shows_split(self, sess):
        r = sess.execute("EXPLAIN SELECT dept, count(*) FROM emp GROUP BY dept")
        plans = [x for x, in r.values()]
        assert "push[Aggregation]" in plans and "root[Aggregation]" in plans

    def test_drop_and_errors(self, sess):
        sess.execute("DROP TABLE emp")
        with pytest.raises(CatalogError):
            sess.execute("SELECT * FROM emp")
        with pytest.raises(CatalogError):
            sess.execute("DROP TABLE emp")
        sess.execute("DROP TABLE IF EXISTS emp")  # no raise

    def test_unknown_column(self, sess):
        with pytest.raises(PlanError, match="unknown column"):
            sess.execute("SELECT nope FROM emp")

    def test_multi_region_sql(self):
        """SQL over a region-split store: same answers."""
        from tidb_tpu.codec import tablecodec

        s = Session()
        s.execute("CREATE TABLE big (id BIGINT PRIMARY KEY, g INT, v DECIMAL(8,2))")
        vals = ", ".join(f"({i}, {i % 5}, {i}.25)" for i in range(200))
        s.execute(f"INSERT INTO big (id, g, v) VALUES {vals}")
        tid = s.catalog.table("big").table_id
        for split in (50, 100, 150):
            s.store.cluster.split(tablecodec.encode_row_key(tid, split))
        r = s.execute("SELECT g, count(*), sum(v) FROM big GROUP BY g ORDER BY g")
        assert [row[:2] for row in r.values()] == [[g, 40] for g in range(5)]
        want_sum = {g: sum(i + 0.25 for i in range(200) if i % 5 == g) for g in range(5)}
        for g, _, sv in r.values():
            assert float(str(sv)) == pytest.approx(want_sum[g])


class TestStaleReadAndSelectLimit:
    def test_sql_select_limit_top_level_only(self):
        """code-review r4: sql_select_limit must not leak into subqueries"""
        from tidb_tpu.sql import Session

        s = Session()
        s.execute("create table sl (a bigint primary key)")
        s.execute("insert into sl values (1),(2),(3),(4),(5)")
        s.execute("set sql_select_limit = 2")
        assert len(s.execute("select * from sl").rows) == 2
        r = s.execute("select count(*) from (select * from sl) d")
        assert int(r.rows[0][0].val) == 5
        r = s.execute("select a from sl where a in (select a from sl) order by a")
        assert len(r.rows) == 2  # top-level cap only; subquery saw all 5
        r = s.execute("select a from sl union select a from sl")
        assert len(r.rows) == 2
        s.execute("set sql_select_limit = 18446744073709551615")
        assert len(s.execute("select * from sl").rows) == 5

    def test_tidb_snapshot_stale_read(self):
        """tidb_snapshot: reads rewind to the TSO; writes rejected
        (ref: pkg/sessiontxn/staleread)."""
        import pytest

        from tidb_tpu.sql import Session

        s = Session()
        s.execute("create table sr (id bigint primary key, v bigint)")
        s.execute("insert into sr values (1, 10)")
        ts = s.store.next_ts()
        s.execute("update sr set v = 20 where id = 1")
        s.execute(f"set tidb_snapshot = {ts}")
        assert int(s.execute("select v from sr").rows[0][0].val) == 10
        with pytest.raises(Exception, match="tidb_snapshot"):
            s.execute("update sr set v = 30 where id = 1")
        s.execute("set tidb_snapshot = ''")
        assert int(s.execute("select v from sr").rows[0][0].val) == 20

    def test_tidb_snapshot_rejects_begin_ddl_and_pre_gc_ts(self):
        """code-review r4: stale-read mode must reject BEGIN and DDL, and a
        snapshot at/below the GC safepoint must error, not return holes."""
        import pytest

        from tidb_tpu.sql import Session

        s = Session()
        s.execute("create table sg (id bigint primary key, v bigint)")
        s.execute("insert into sg values (1, 10)")
        old = s.store.next_ts()
        s.execute("update sg set v = 20 where id = 1")
        s.store.run_gc()  # collects v=10; safepoint recorded
        s.execute(f"set tidb_snapshot = {old}")
        with pytest.raises(Exception, match="GC safe point"):
            s.execute("select v from sg")
        fresh = s.store.next_ts()
        s.execute(f"set tidb_snapshot = {fresh}")
        with pytest.raises(Exception, match="tidb_snapshot"):
            s.execute("begin")
        with pytest.raises(Exception, match="tidb_snapshot"):
            s.execute("create table nope (a bigint)")
        s.execute("set tidb_snapshot = ''")
        s.execute("begin")
        s.execute("commit")
