"""Subqueries, derived tables, CTEs, UNION — the session/planner rewrite
pass (ref: pkg/planner/core/expression_rewriter.go uncorrelated evaluation,
rule_decorrelate.go semi/anti/outer-join decorrelation, executor/cte.go).

Every result is cross-checked against hand-computed MySQL semantics,
including three-valued NOT IN edge cases.
"""

import pytest

from tidb_tpu.sql.session import Session, SQLError


@pytest.fixture()
def sess():
    s = Session()
    s.execute("CREATE TABLE t (id INT PRIMARY KEY, k INT, v INT)")
    s.execute("CREATE TABLE u (id INT PRIMARY KEY, tk INT, w INT)")
    s.execute("INSERT INTO t VALUES (1,1,10),(2,1,20),(3,2,30),(4,3,40),(5,NULL,50)")
    s.execute("INSERT INTO u VALUES (1,1,100),(2,2,200),(3,2,250),(4,9,300)")
    return s


def q(sess, sql):
    return sess.execute(sql).values()


# ---------------------------------------------------------------- scalar


def test_scalar_uncorrelated(sess):
    assert q(sess, "SELECT max(v) FROM t WHERE v < (SELECT avg(w) FROM u)") == [[50]]


def test_scalar_empty_is_null(sess):
    assert q(sess, "SELECT (SELECT w FROM u WHERE tk = 777)") == [[None]]


def test_scalar_multirow_errors(sess):
    with pytest.raises(SQLError, match="more than 1 row"):
        q(sess, "SELECT (SELECT w FROM u)")


def test_scalar_no_from(sess):
    assert q(sess, "SELECT 1 + (SELECT count(*) FROM u)") == [[5]]


def test_scalar_correlated_count_empty_group_is_zero(sess):
    got = q(sess, "SELECT id, (SELECT count(*) FROM u WHERE u.tk = t.k) FROM t ORDER BY id")
    assert got == [[1, 1], [2, 1], [3, 2], [4, 0], [5, 0]]


def test_scalar_correlated_sum_empty_group_is_null(sess):
    got = q(sess, "SELECT id, (SELECT sum(w) FROM u WHERE u.tk = t.k) FROM t ORDER BY id")
    assert [[r[0], None if r[1] is None else int(str(r[1]))] for r in got] == [
        [1, 100], [2, 100], [3, 450], [4, None], [5, None]]


def test_scalar_correlated_nonagg_dup_errors(sess):
    # tk=2 has two rows — a non-aggregated correlated scalar must error
    with pytest.raises(SQLError, match="more than 1 row"):
        q(sess, "SELECT id, (SELECT w FROM u WHERE u.tk = t.k) FROM t")


# ---------------------------------------------------------------- IN / EXISTS


def test_in_uncorrelated(sess):
    assert q(sess, "SELECT id FROM t WHERE k IN (SELECT tk FROM u) ORDER BY id") == [[1], [2], [3]]


def test_not_in_uncorrelated(sess):
    # k=NULL row never passes NOT IN; k=3 not in {1,2,9}
    assert q(sess, "SELECT id FROM t WHERE k NOT IN (SELECT tk FROM u) ORDER BY id") == [[4]]


def test_not_in_with_null_in_set_is_empty(sess):
    sess.execute("INSERT INTO u VALUES (5, NULL, 0)")
    assert q(sess, "SELECT id FROM t WHERE k NOT IN (SELECT tk FROM u)") == []


def test_exists_correlated(sess):
    assert q(sess, "SELECT id FROM t WHERE EXISTS (SELECT 1 FROM u WHERE u.tk = t.k) ORDER BY id") == [[1], [2], [3]]


def test_not_exists_correlated(sess):
    assert q(sess, "SELECT id FROM t WHERE NOT EXISTS (SELECT 1 FROM u WHERE u.tk = t.k) ORDER BY id") == [[4], [5]]


def test_exists_uncorrelated(sess):
    assert q(sess, "SELECT count(*) FROM t WHERE EXISTS (SELECT 1 FROM u WHERE w > 250)") == [[5]]
    assert q(sess, "SELECT count(*) FROM t WHERE EXISTS (SELECT 1 FROM u WHERE w > 999)") == [[0]]


def test_in_correlated(sess):
    assert q(sess, "SELECT id FROM t WHERE v IN (SELECT w/10 FROM u WHERE u.tk = t.k) ORDER BY id") == [[1]]


def test_in_large_set_semi_join(sess):
    s = Session()
    s.execute("CREATE TABLE big (id INT PRIMARY KEY, x INT)")
    s.execute("CREATE TABLE probe (id INT PRIMARY KEY, x INT)")
    vals = ",".join(f"({i},{i * 3})" for i in range(1, 201))
    s.execute(f"INSERT INTO big VALUES {vals}")
    s.execute("INSERT INTO probe VALUES (1,3),(2,4),(3,300),(4,601),(5,NULL)")
    assert q(s, "SELECT id FROM probe WHERE x IN (SELECT x FROM big) ORDER BY id") == [[1], [3]]
    assert q(s, "SELECT id FROM probe WHERE x NOT IN (SELECT x FROM big) ORDER BY id") == [[2], [4]]


def test_any_all(sess):
    assert q(sess, "SELECT id FROM t WHERE v >= ALL (SELECT w/10 FROM u) ORDER BY id") == [[3], [4], [5]]
    assert q(sess, "SELECT id FROM t WHERE v < ANY (SELECT w/10 FROM u) ORDER BY id") == [[1], [2]]
    # empty set: ALL true, ANY false
    assert q(sess, "SELECT count(*) FROM t WHERE v > ALL (SELECT w FROM u WHERE tk = 777)") == [[5]]
    assert q(sess, "SELECT count(*) FROM t WHERE v > ANY (SELECT w FROM u WHERE tk = 777)") == [[0]]


# ---------------------------------------------------------------- derived / CTE


def test_derived_table(sess):
    got = q(sess, "SELECT a.k, a.s FROM (SELECT k, sum(v) AS s FROM t GROUP BY k) a ORDER BY a.k")
    assert [[r[0], int(str(r[1]))] for r in got] == [[None, 50], [1, 30], [2, 30], [3, 40]]


def test_derived_join_real_table(sess):
    got = q(sess, """
        SELECT t.id, a.cnt FROM t
        JOIN (SELECT tk, count(*) AS cnt FROM u GROUP BY tk) a ON a.tk = t.k
        ORDER BY t.id""")
    assert got == [[1, 1], [2, 1], [3, 2]]


def test_cte_basic(sess):
    assert q(sess, "WITH big AS (SELECT * FROM t WHERE v >= 30) SELECT count(*) FROM big") == [[3]]


def test_cte_chained(sess):
    got = q(sess, """
        WITH a AS (SELECT k, v FROM t WHERE v > 10),
             b AS (SELECT k, sum(v) AS s FROM a GROUP BY k)
        SELECT count(*), max(s) FROM b""")
    assert [[got[0][0], int(str(got[0][1]))]] == [[4, 50]]


def test_cte_column_aliases(sess):
    assert q(sess, "WITH c (x) AS (SELECT v FROM t) SELECT max(x) FROM c") == [[50]]


def test_recursive_cte(sess):
    assert q(sess, """
        WITH RECURSIVE seq AS (SELECT 1 AS n UNION ALL SELECT n+1 FROM seq WHERE n < 10)
        SELECT count(*), sum(n) FROM seq""") == [[10, 55]] or q(sess, """
        WITH RECURSIVE seq AS (SELECT 1 AS n UNION ALL SELECT n+1 FROM seq WHERE n < 10)
        SELECT count(*), sum(n) FROM seq""")[0][0] == 10


def test_recursive_cte_distinct_terminates(sess):
    # UNION (distinct) recursion reaches a fixpoint instead of the cap
    got = q(sess, """
        WITH RECURSIVE r AS (SELECT 1 AS n UNION SELECT 3 - n FROM r)
        SELECT count(*) FROM r""")
    assert got == [[2]]  # {1, 2}


def test_recursive_cte_depth_cap(sess):
    sess.execute("SET cte_max_recursion_depth = 10")
    with pytest.raises(SQLError, match="recursion"):
        q(sess, "WITH RECURSIVE s AS (SELECT 1 AS n UNION ALL SELECT n+1 FROM s) SELECT count(*) FROM s")


# ---------------------------------------------------------------- UNION


def test_union_distinct(sess):
    assert q(sess, "SELECT k FROM t UNION SELECT tk FROM u ORDER BY k") == [[None], [1], [2], [3], [9]]


def test_union_all(sess):
    assert len(q(sess, "SELECT k FROM t UNION ALL SELECT tk FROM u")) == 9


def test_union_order_limit(sess):
    assert q(sess, "SELECT v FROM t UNION SELECT w FROM u ORDER BY v DESC LIMIT 3") == [[300], [250], [200]]


def test_union_column_count_mismatch(sess):
    with pytest.raises(SQLError, match="different number"):
        q(sess, "SELECT id, k FROM t UNION SELECT id FROM u")


def test_union_in_subquery(sess):
    assert q(sess, "SELECT count(*) FROM t WHERE k IN (SELECT tk FROM u WHERE w < 150 UNION SELECT 3)") == [[3]]
