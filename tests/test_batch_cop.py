"""Batched multi-region coprocessor: one vmapped XLA launch per store batch
(ref: copr/batch_coprocessor.go — all regions of a TiFlash store travel in
one request) + the coprocessor result cache (ref: copr/coprocessor_cache.go).

Covers the batch interaction contract: launch-count regression guard (one
compile + one launch for a >=16-region scan, then cache hits per repeated
batch shape), epoch-mismatch of ONE region mid-batch retrying only that
region, paging exclusion, the batched wire frames, per-region overflow
fall-out, cop-cache hit/invalidation, aux-cache token identity, and
deterministic exec-summary ordering (keep_order)."""

import numpy as np
import pytest

from tidb_tpu.codec import tablecodec
from tidb_tpu.distsql import KVRequest, full_table_ranges, select
from tidb_tpu.exec import Aggregation, ColumnInfo, DAGRequest, Selection, TableScan
from tidb_tpu.expr import AggDesc, col, func, lit
from tidb_tpu.store import CopRequest, KeyRange, TPUStore
from tidb_tpu.types import Datum, new_longlong
from tidb_tpu.util import metrics

BOOL = new_longlong(notnull=True)
TID = 91
FT = new_longlong()


def fill_store(n=340, regions=17):
    """n rows of (v = 3*handle) split into `regions` PD regions, 1 store."""
    store = TPUStore()
    for h in range(n):
        store.put_row(TID, h, [1], [Datum.i64(h * 3)], ts=10)
    for i in range(1, regions):
        store.cluster.split(tablecodec.encode_row_key(TID, i * n // regions))
    assert len(store.cluster.regions()) == regions
    return store


def scan_dag():
    scan = TableScan(TID, (ColumnInfo(1, FT),))
    return DAGRequest((scan,), output_offsets=(0,))


def agg_dag():
    scan = TableScan(TID, (ColumnInfo(1, FT),))
    sel = Selection((func("lt", BOOL, col(0, FT), lit(300, new_longlong())),))
    agg = Aggregation(group_by=(), aggs=(AggDesc("count", ()),), partial=True)
    return DAGRequest((scan, sel, agg), output_offsets=(0,))


def kvreq(dag, ts, **kw):
    return KVRequest(dag, full_table_ranges(TID), start_ts=ts, **kw)


def all_vals(res):
    return sorted(r[0].val for r in res.merged().rows())


# ------------------------------------------------- launch-count regression


def test_one_launch_per_store_for_16_regions():
    """The acceptance bar: >=16 regions, batch_cop=True -> ONE XLA program
    execution (and one compile) on the store for the whole scan."""
    store = fill_store(n=340, regions=17)
    l0 = metrics.PROGRAM_LAUNCHES.value
    s0 = store.programs.stats()
    res = select(store, kvreq(scan_dag(), 100, batch_cop=True))
    launches = metrics.PROGRAM_LAUNCHES.value - l0
    s1 = store.programs.stats()
    assert launches == 1  # one vmapped launch, not 17
    assert s1["compiles"] - s0["compiles"] == 1
    assert res.batch_stats == {"batches": 1, "regions": 17, "launches_saved": 16,
                               "mesh_batches": 0, "mesh_lanes": 0}
    assert all_vals(res) == [h * 3 for h in range(340)]
    assert len(res.exec_summaries) == 17  # still one summary list per region


def test_one_compile_then_hits_per_batch_shape():
    """Same batch shape again (after a write invalidates the cop result
    cache): the vmapped program comes from the ProgramCache — one compile
    per shape, cache hits and exactly one launch per repeat."""
    store = fill_store(n=340, regions=17)
    select(store, kvreq(scan_dag(), 100, batch_cop=True))  # compile + warm
    for ts in (200, 300, 400):
        # a write bumps the store write version: cop cache misses, the
        # decode reruns, but the program (same shape) must NOT recompile
        store.put_row(TID, 0, [1], [Datum.i64(0)], ts=ts - 10)
        s0 = store.programs.stats()
        l0 = metrics.PROGRAM_LAUNCHES.value
        res = select(store, kvreq(scan_dag(), ts, batch_cop=True))
        s1 = store.programs.stats()
        assert s1["compiles"] - s0["compiles"] == 0
        assert s1["hits"] - s0["hits"] == 1
        assert metrics.PROGRAM_LAUNCHES.value - l0 == 1
        assert all_vals(res) == [h * 3 for h in range(340)]


def test_batched_matches_per_region_partial_agg():
    store = fill_store(n=200, regions=8)
    dag = agg_dag()
    # mesh=False pins the per-region pool path (the mesh tier would
    # otherwise claim this partial-agg shape — tested separately below)
    plain = select(store, kvreq(dag, 100, concurrency=4, mesh=False))
    store.evict_caches()  # defeat the cop cache: exercise the real launch
    batched = select(store, kvreq(dag, 101, batch_cop=True, mesh=False))
    assert sum(all_vals(plain)) == sum(all_vals(batched)) == 100
    assert plain.batch_stats is None
    assert batched.batch_stats["regions"] == 8
    assert batched.batch_stats["mesh_lanes"] == 0
    store.evict_caches()
    meshed = select(store, kvreq(dag, 102))  # planner default: mesh tier
    assert sum(all_vals(meshed)) == 100
    assert meshed.batch_stats["mesh_lanes"] == 8
    # ONE merged partial state came back (no per-region host merge)
    assert sum(1 for c in meshed.chunks if c is not None and c.num_rows()) == 1


# ------------------------------------------------- batch interaction edges


def test_capacity_buckets_split_skewed_regions():
    """Regions bucket by their own pow2 capacity before stacking: a skewed
    region must not inflate every lane to its padded size. 4x20-row and
    3x40-row regions -> two vmapped launches (32- and 64-capacity), never
    one 64-capacity launch over all seven."""
    store = TPUStore()
    n = 200
    for h in range(n):
        store.put_row(TID, h, [1], [Datum.i64(h * 3)], ts=10)
    for b in (20, 40, 60, 80, 120, 160):
        store.cluster.split(tablecodec.encode_row_key(TID, b))
    l0 = metrics.PROGRAM_LAUNCHES.value
    res = select(store, kvreq(scan_dag(), 100, batch_cop=True))
    assert res.batch_stats == {"batches": 2, "regions": 7, "launches_saved": 5,
                               "mesh_batches": 0, "mesh_lanes": 0}
    assert metrics.PROGRAM_LAUNCHES.value - l0 == 2
    assert all_vals(res) == [h * 3 for h in range(n)]


def test_epoch_mismatch_one_region_retries_only_that_region():
    """A concurrent split lands between task build and dispatch: the stale
    region falls out of the batch into the single-task retry path; every
    other region's batched result stands."""
    store = fill_store(n=200, regions=8)
    orig = store.batch_coprocessor
    fired = []

    def hijack(reqs, **kw):
        if not fired:
            fired.append(1)
            store.cluster.split(tablecodec.encode_row_key(TID, 10))
        return orig(reqs, **kw)

    store.batch_coprocessor = hijack
    r0 = metrics.DISTSQL_RETRIES.value
    res = select(store, kvreq(scan_dag(), 100, batch_cop=True))
    assert metrics.DISTSQL_RETRIES.value - r0 == 1  # only the split region
    assert res.batch_stats["regions"] == 7  # the other 7 stayed batched
    assert all_vals(res) == [h * 3 for h in range(200)]


def test_paging_requests_are_excluded_from_batching():
    store = fill_store(n=200, regions=8)
    called = []
    orig = store.batch_coprocessor
    store.batch_coprocessor = lambda *a, **k: called.append(1) or orig(*a, **k)
    res = select(store, kvreq(scan_dag(), 100, batch_cop=True, paging_size=16))
    assert not called  # paging bypasses the batch path entirely
    assert res.batch_stats is None
    assert all_vals(res) == [h * 3 for h in range(200)]


def test_store_batch_endpoint_stale_epoch_inline():
    """batch_coprocessor itself: a stale-epoch request answers with a
    region_error without poisoning the rest of the batch."""
    store = fill_store(n=200, regions=4)
    dag = scan_dag()
    regions = store.cluster.regions()
    reqs = [CopRequest(dag, full_table_ranges(TID), 100, r.region_id, r.epoch)
            for r in regions]
    reqs[1] = CopRequest(dag, full_table_ranges(TID), 100,
                         regions[1].region_id, regions[1].epoch + 7)
    resps = store.batch_coprocessor(reqs)
    assert "epoch_not_match" in resps[1].region_error
    ok = [r for i, r in enumerate(resps) if i != 1]
    assert all(r.region_error is None and r.chunk is not None for r in ok)


def test_batched_overflow_lane_falls_out_alone():
    """A tiny group capacity overflows the vmapped lanes; each lane then
    rides the single-region capacity ladder and the results still match."""
    store = fill_store(n=120, regions=4)
    scan = TableScan(TID, (ColumnInfo(1, FT),))
    agg = Aggregation(group_by=(col(0, FT),), aggs=(AggDesc("count", ()),), partial=True)
    dag = DAGRequest((scan, agg), output_offsets=(0, 1))
    regions = store.cluster.regions()
    reqs = [CopRequest(dag, full_table_ranges(TID), 100, r.region_id, r.epoch)
            for r in regions]
    resps = store.batch_coprocessor(reqs, group_capacity=2)  # forces overflow
    assert all(r.region_error is None and r.other_error is None for r in resps)
    total = sum(row[0].val for r in resps for row in r.chunk.rows())
    assert total == 120  # every row counted exactly once


# ------------------------------------------------- wire frames


def test_batch_wire_codec_roundtrip():
    from tidb_tpu.codec.wire import (
        decode_batch_cop_request,
        decode_batch_cop_response,
        encode_batch_cop_request,
        encode_batch_cop_response,
    )

    dag = scan_dag()
    reqs = [CopRequest(dag, [KeyRange(b"a", b"z")], 5, region_id=i, region_epoch=i + 1)
            for i in range(3)]
    back = decode_batch_cop_request(encode_batch_cop_request(reqs))
    assert [(r.region_id, r.region_epoch, r.start_ts) for r in back] == \
        [(0, 1, 5), (1, 2, 5), (2, 3, 5)]

    store = fill_store(n=80, regions=4)
    creqs = [CopRequest(dag, full_table_ranges(TID), 100, r.region_id, r.epoch)
             for r in store.cluster.regions()]
    resps = store.batch_coprocessor(creqs)
    rt = decode_batch_cop_response(encode_batch_cop_response(resps))
    assert len(rt) == len(resps)
    for a, b in zip(resps, rt):
        assert a.chunk.num_rows() == b.chunk.num_rows()
        assert [s.num_produced_rows for s in a.exec_summaries] == \
            [s.num_produced_rows for s in b.exec_summaries]


def test_batched_dispatch_over_wire_matches():
    store = fill_store(n=200, regions=8)
    res = select(store, kvreq(scan_dag(), 100, batch_cop=True, use_wire=True))
    assert all_vals(res) == [h * 3 for h in range(200)]


def test_batch_wire_shares_decoded_aux_identity():
    """Every region task of a broadcast join carries the same build side:
    a batch frame must decode it ONCE so the store's identity-keyed group
    and aux-upload caches still work across the wire seam."""
    from tidb_tpu.chunk import Chunk
    from tidb_tpu.codec.wire import decode_batch_cop_request, encode_batch_cop_request

    aux = Chunk.from_rows([FT], [[Datum.i64(9)], [Datum.i64(10)]])
    dag = scan_dag()
    reqs = [CopRequest(dag, [KeyRange(b"a", b"z")], 5, region_id=i,
                       region_epoch=1, aux_chunks=[aux]) for i in range(3)]
    back = decode_batch_cop_request(encode_batch_cop_request(reqs))
    assert back[0].aux_chunks[0] is back[1].aux_chunks[0] is back[2].aux_chunks[0]


# ------------------------------------------------- coprocessor result cache


def test_cop_cache_hits_and_write_invalidation():
    store = fill_store(n=200, regions=8)
    dag = scan_dag()
    select(store, kvreq(dag, 100, concurrency=2))  # populate
    h0 = metrics.COP_CACHE_HITS.value
    l0 = metrics.PROGRAM_LAUNCHES.value
    res = select(store, kvreq(dag, 101, concurrency=2))
    assert metrics.COP_CACHE_HITS.value - h0 == 8  # every region served cached
    assert metrics.PROGRAM_LAUNCHES.value - l0 == 0  # zero device work
    assert all(s.cache_hit and s.time_compile_ns == 0
               for task in res.exec_summaries for s in task)
    assert all_vals(res) == [h * 3 for h in range(200)]
    # a write invalidates: the next read must NOT serve stale data
    store.put_row(TID, 0, [1], [Datum.i64(-5)], ts=150)
    h1 = metrics.COP_CACHE_HITS.value
    res2 = select(store, kvreq(dag, 200, concurrency=2))
    assert metrics.COP_CACHE_HITS.value - h1 == 0
    assert all_vals(res2)[0] == -5


def test_cop_cache_rejects_older_snapshot():
    """An entry built at ts=100 must not serve a request at ts=90 — the
    older snapshot could predate a commit the entry already includes."""
    store = fill_store(n=40, regions=2)
    dag = scan_dag()
    r = store.cluster.regions()[0]
    req_new = CopRequest(dag, full_table_ranges(TID), 100, r.region_id, r.epoch)
    store.coprocessor(req_new)
    h0 = metrics.COP_CACHE_HITS.value
    req_old = CopRequest(dag, full_table_ranges(TID), 90, r.region_id, r.epoch)
    store.coprocessor(req_old)
    assert metrics.COP_CACHE_HITS.value - h0 == 0
    store.coprocessor(CopRequest(dag, full_table_ranges(TID), 110, r.region_id, r.epoch))
    assert metrics.COP_CACHE_HITS.value - h0 == 1


def test_cop_cache_drained_by_evict():
    store = fill_store(n=80, regions=4)
    select(store, kvreq(scan_dag(), 100))
    assert len(store._cop_cache) > 0
    freed = store.evict_caches()
    assert freed > 0 and len(store._cop_cache) == 0
    h0 = metrics.COP_CACHE_HITS.value
    select(store, kvreq(scan_dag(), 101))
    assert metrics.COP_CACHE_HITS.value - h0 == 0  # cold after evict


def test_cop_cache_metric_exposed():
    names = [series for series, _ in metrics.REGISTRY.sample_lines()]
    assert any("tidb_tpu_cop_cache_hits_total" in n for n in names)
    assert any("tidb_tpu_batch_cop_batches_total" in n for n in names)
    assert any("tidb_tpu_program_launches_total" in n for n in names)


# ------------------------------------------------- aux cache token identity


def test_aux_cache_keys_by_token_not_id():
    from tidb_tpu.chunk import Chunk

    store = TPUStore()
    a = Chunk.from_rows([FT], [[Datum.i64(1)]])
    b = Chunk.from_rows([FT], [[Datum.i64(1)]])
    ba = store._aux_batch(a)
    bb = store._aux_batch(b)
    assert ba is not bb  # equal content, distinct identity -> distinct entries
    assert store._aux_batch(a) is ba  # stable per object
    ta, tb = a._device_token, b._device_token
    assert ta != tb
    # tokens are monotonic and never reused, even if id() were recycled
    c = Chunk.from_rows([FT], [[Datum.i64(2)]])
    store._aux_batch(c)
    assert c._device_token > max(ta, tb)


# ------------------------------------------------- summary determinism


def test_exec_summaries_follow_task_order():
    """Regions of DIFFERENT sizes dispatched over a pool: the scan summary
    row counts must come back in region (task) order, not completion order
    — EXPLAIN ANALYZE attribution is deterministic (keep_order)."""
    store = TPUStore()
    n = 100
    for h in range(n):
        store.put_row(TID, h, [1], [Datum.i64(h)], ts=10)
    for boundary in (10, 30, 60):  # region sizes 10, 20, 30, 40
        store.cluster.split(tablecodec.encode_row_key(TID, boundary))
    for _ in range(3):
        store.evict_caches()  # defeat the cop cache: run the real path
        res = select(store, kvreq(scan_dag(), 100, concurrency=4, keep_order=True))
        assert [task[0].num_produced_rows for task in res.exec_summaries] == \
            [10, 20, 30, 40]


# ------------------------------------------------- SQL-level integration


def test_sql_batch_cop_matches_and_explains():
    from tidb_tpu.sql.session import Session

    s = Session()
    s.execute("CREATE TABLE bt (id INT PRIMARY KEY, v INT)")
    s.execute("INSERT INTO bt VALUES " + ",".join(f"({i},{i % 13})" for i in range(1, 401)))
    tid = s.catalog.table("bt").table_id
    for i in range(1, 17):
        s.store.cluster.split(tablecodec.encode_row_key(tid, i * 400 // 17))
    # this test pins the VMAPPED batch tier + cop-cache attribution;
    # the mesh tier (which sits above it and skips the cop cache) has its
    # own SQL-level coverage in tests/test_mesh_dispatch.py
    s.execute("SET tidb_enable_tpu_mesh = OFF")
    plain = s.execute("SELECT count(*), sum(v) FROM bt WHERE v < 7").values()
    s.execute("SET tidb_allow_batch_cop = ON")
    l0 = metrics.PROGRAM_LAUNCHES.value
    batched = s.execute("SELECT count(*), sum(v) FROM bt WHERE v < 7").values()
    assert plain == batched
    # one batched push launch + the root merge's launch, never 17
    assert metrics.PROGRAM_LAUNCHES.value - l0 <= 3
    s.store.evict_caches()  # drain the cop cache: attribute a REAL launch
    rows = s.execute("EXPLAIN ANALYZE SELECT count(*), sum(v) FROM bt WHERE v < 7").values()
    by_exec = {r[0]: r for r in rows}
    bc = by_exec["batch_cop"]
    assert bc[1] >= 16 and bc[2] >= 1  # regions batched, launches
    assert bc[5].startswith("saved=") and int(bc[5].split("=")[1]) >= 15
    # same statement again: every region now comes from the cop result
    # cache, which did NOT ride a launch — attribution must say so
    rows2 = s.execute("EXPLAIN ANALYZE SELECT count(*), sum(v) FROM bt WHERE v < 7").values()
    bc2 = {r[0]: r for r in rows2}["batch_cop"]
    assert bc2[1] == 0 and bc2[5] == "saved=0"


def test_trace_batch_cop_attribution():
    from tidb_tpu.util import tracing

    store = fill_store(n=200, regions=8)
    with tracing.trace("test") as root:
        select(store, kvreq(scan_dag(), 100, batch_cop=True))
    batch_spans = root.find("distsql.batch_cop")
    assert len(batch_spans) == 1
    assert root.sum_attr("distsql.batch_cop", "batch_size") == 8
    assert root.sum_attr("distsql.batch_cop", "launches_saved") == 7
    # per-region cop_task spans still exist under the batch span
    assert len(root.find("distsql.cop_task")) == 8
