"""Test config: force an 8-device virtual CPU platform before jax initializes.

Mirrors how the reference tests distributed behavior fully in-process
(ref: pkg/testkit/mockstore.go CreateMockStore + unistore region splitting):
we get an 8-device mesh on CPU so shard_map/psum/all_to_all paths run
without TPU hardware.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: shell may have axon/tpu set
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax  # noqa: E402

# The environment's sitecustomize registers the axon TPU plugin in every
# interpreter; any backend init then touches the single-client TPU tunnel.
# Tests must be hermetic CPU — drop the factory before any backend inits.
try:  # noqa: SIM105
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
except Exception:
    pass

jax.config.update("jax_platforms", "cpu")  # axon register() overrides the env var
jax.config.update("jax_enable_x64", True)
# persistent compile cache: repeated test runs skip XLA compiles
jax.config.update("jax_compilation_cache_dir", "/tmp/tidb_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running acceptance tests, excluded from tier-1 (-m 'not slow')"
    )


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)
