"""Mesh-unified dispatch (ISSUE 11): the ONE execution planner routes the
standard `distsql.select` path onto the device mesh — partial aggregate
states psum-reduced over the region axis under shard_map, one merged state
per store instead of R per-region partials for the host to fold (SURVEY
§3.1/§5; ref: TiDB's MPP partial/final split lowered onto SPMD collectives).
"""

import os
import sys

import pytest

from tidb_tpu.codec import tablecodec
from tidb_tpu.codec.wire import (
    decode_cop_request,
    decode_cop_response,
    encode_cop_request,
    encode_cop_response,
)
from tidb_tpu.distsql.dispatch import KVRequest, full_table_ranges, select, select_stream
from tidb_tpu.distsql.planner import TierDecision, choose_tier, mesh_merge_kind
from tidb_tpu.distsql.root import execute_root, split_dag
from tidb_tpu.exec.dag import Aggregation, ColumnInfo, DAGRequest, Selection, TableScan, TopN
from tidb_tpu.exec.executor import run_dag_reference
from tidb_tpu.expr import AggDesc, col, func, lit
from tidb_tpu.store import CopRequest, TPUStore
from tidb_tpu.store.store import CopResponse
from tidb_tpu.types import Datum, new_longlong
from tidb_tpu.util import metrics

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

TID = 21
I = new_longlong()
BOOL = new_longlong(notnull=True)


def fill_store(rows=180, regions=6, stores=2):
    store = TPUStore()
    for h in range(rows):
        store.put_row(TID, h, [1, 2], [Datum.i64(h % 7), Datum.i64(h)], ts=10)
    for i in range(1, regions):
        store.cluster.split(tablecodec.encode_row_key(TID, i * rows // regions))
    if stores > 1:
        store.cluster.set_stores(stores)
        store.cluster.scatter()
    return store


def scan():
    return TableScan(TID, (ColumnInfo(1, I), ColumnInfo(2, I)))


def scalar_partial_dag():
    agg = Aggregation(group_by=(), aggs=(
        AggDesc("count", ()), AggDesc("sum", (col(1, I),)),
        AggDesc("min", (col(1, I),)), AggDesc("max", (col(1, I),)),
    ), partial=True)
    pred = func("gt", BOOL, col(0, I), lit(1, I))
    return DAGRequest((scan(), Selection((pred,)), agg), output_offsets=tuple(range(4)))


def logical_dag(aggs, group_by=()):
    agg = Aggregation(group_by=group_by, aggs=aggs)
    return DAGRequest((scan(), agg),
                      output_offsets=tuple(range(len(aggs) + len(group_by))))


def oracle_rows(store, dag, rows=180):
    chunk_rows = [[Datum.i64(h % 7), Datum.i64(h)] for h in range(rows)]
    from tidb_tpu.chunk import Chunk

    return run_dag_reference(dag, Chunk.from_rows([I, I], chunk_rows))


# ------------------------------------------------------------ the planner

def test_planner_tier_rules():
    store = fill_store()
    tasks = list(range(6))  # only len() is consulted
    pdag = scalar_partial_dag()
    sdag = DAGRequest((scan(),), output_offsets=(0, 1))
    assert choose_tier(store, KVRequest(pdag, [], 100), tasks) == \
        TierDecision("mesh", "scalar")
    # plain scans never mesh; batch_cop claims them
    assert choose_tier(store, KVRequest(sdag, [], 100, batch_cop=True), tasks).tier == "batch"
    assert choose_tier(store, KVRequest(sdag, [], 100), tasks).tier == "pool"
    # paging pins the per-task path (resume cursors are sequential state)
    assert choose_tier(store, KVRequest(pdag, [], 100, paging_size=16), tasks).tier == "pool"
    # single task: nothing to merge
    assert choose_tier(store, KVRequest(pdag, [], 100), tasks[:1]).tier == "single"
    # the kill switch pins the pre-mesh tiers
    assert choose_tier(store, KVRequest(pdag, [], 100, mesh=False), tasks).tier == "pool"
    assert choose_tier(store, KVRequest(pdag, [], 100, mesh=False, batch_cop=True), tasks).tier == "batch"
    # data-size floor: an absurd min-rows hint pushes it off the mesh
    assert choose_tier(store, KVRequest(pdag, [], 100, mesh_min_rows=1 << 30), tasks).tier == "pool"


def test_mesh_merge_kind_gate():
    pdag = scalar_partial_dag()
    assert mesh_merge_kind(pdag) == "scalar"
    # grouped partial -> "group"
    gagg = Aggregation(group_by=(col(0, I),),
                       aggs=(AggDesc("sum", (col(1, I),)),), partial=True)
    assert mesh_merge_kind(DAGRequest((scan(), gagg), output_offsets=(0, 1))) == "group"
    # TopN -> "topn"
    tdag = DAGRequest((scan(), TopN(order_by=((col(1, I), True),), limit=5)),
                      output_offsets=(0, 1))
    assert mesh_merge_kind(tdag) == "topn"
    # Complete-mode aggregation: the root owns the finalize — no mesh
    cagg = Aggregation(group_by=(), aggs=(AggDesc("count", ()),))
    assert mesh_merge_kind(DAGRequest((scan(), cagg), output_offsets=(0,))) is None
    # DISTINCT states are not mergeable
    dagg = Aggregation(group_by=(), aggs=(
        AggDesc("count", (col(1, I),), distinct=True),), partial=True)
    assert mesh_merge_kind(DAGRequest((scan(), dagg), output_offsets=(0,))) is None
    # reordered output offsets: the positional merge plan would misalign
    from dataclasses import replace

    assert mesh_merge_kind(replace(pdag, output_offsets=(1, 0, 2, 3))) is None


# -------------------------------------------- the acceptance: psum on device

def test_scalar_psum_one_merged_state_per_store():
    """THE acceptance bar: a standard select() over a multi-device mesh
    executes via shard_map, partial states psum-reduce on device, and each
    store answers ONE merged state — byte-identical to the per-region
    host-merge result."""
    store = fill_store(rows=180, regions=6, stores=2)
    dag = scalar_partial_dag()
    l0 = metrics.MESH_COP_LANES.value
    b0 = metrics.MESH_COP_BATCHES.value
    res = select(store, KVRequest(dag, full_table_ranges(TID), start_ts=100))
    assert metrics.MESH_COP_LANES.value - l0 == 6
    assert metrics.MESH_COP_BATCHES.value - b0 == 2  # one launch per store
    assert res.batch_stats["mesh_lanes"] == 6
    assert res.batch_stats["mesh_batches"] == 2
    # one merged state per STORE at root — no per-region host merge
    live = [c for c in res.chunks if c is not None and c.num_rows()]
    assert len(live) == 2
    # the merged partials equal the per-region path's root-merge input
    ref = select(store, KVRequest(dag, full_table_ranges(TID), start_ts=100,
                                  mesh=False))
    from tidb_tpu.chunk import Chunk

    def folded(chunks):
        merge = split_dag(logical_dag((
            AggDesc("count", ()), AggDesc("sum", (col(1, I),)),
            AggDesc("min", (col(1, I),)), AggDesc("max", (col(1, I),)),
        ))).root_dag  # Final merge over the partial schema
        rows = run_dag_reference(merge, Chunk.concat(chunks))
        return [[str(d) for d in r] for r in rows]

    assert folded([c for c in res.chunks if c is not None]) == \
        folded([c for c in ref.chunks if c is not None])


def test_execute_root_scalar_matches_oracle():
    store = fill_store()
    dag = logical_dag((
        AggDesc("count", ()), AggDesc("sum", (col(1, I),)),
        AggDesc("avg", (col(1, I),)), AggDesc("min", (col(0, I),)),
        AggDesc("max", (col(1, I),)), AggDesc("first_row", (col(0, I),)),
    ))
    l0 = metrics.MESH_COP_LANES.value
    out = execute_root(store, dag, full_table_ranges(TID), start_ts=100)
    assert metrics.MESH_COP_LANES.value - l0 > 0  # the mesh tier ran
    want = oracle_rows(store, dag)
    assert [[str(d) for d in r] for r in out.rows()] == \
        [[str(d) for d in r] for r in want]


def test_execute_root_grouped_matches_oracle():
    """GROUP BY partials merge on device too (all_gather + merge-mode
    re-group): one merged group table per store."""
    store = fill_store()
    dag = logical_dag((
        AggDesc("count", ()), AggDesc("sum", (col(1, I),)),
        AggDesc("max", (col(1, I),)),
    ), group_by=(col(0, I),))
    l0 = metrics.MESH_COP_LANES.value
    out = execute_root(store, dag, full_table_ranges(TID), start_ts=100)
    assert metrics.MESH_COP_LANES.value - l0 > 0
    want = oracle_rows(store, dag)
    assert sorted(map(str, out.rows())) == sorted(map(str, want))


def test_execute_root_topn_matches_oracle():
    store = fill_store()
    dag = DAGRequest((scan(), TopN(order_by=((col(1, I), True),), limit=9)),
                     output_offsets=(0, 1))
    l0 = metrics.MESH_COP_LANES.value
    out = execute_root(store, dag, full_table_ranges(TID), start_ts=100)
    assert metrics.MESH_COP_LANES.value - l0 > 0
    want = oracle_rows(store, dag)
    assert [[str(d) for d in r] for r in out.rows()] == \
        [[str(d) for d in r] for r in want]


def test_select_stream_mesh_yields_merged_states():
    store = fill_store(rows=180, regions=6, stores=2)
    dag = scalar_partial_dag()
    got = list(select_stream(store, KVRequest(dag, full_table_ranges(TID), start_ts=100)))
    live = [c for c, _sums in got if c.num_rows()]
    assert len(live) == 2  # one merged state per store
    ref = select(store, KVRequest(dag, full_table_ranges(TID), start_ts=100, mesh=False))
    from tidb_tpu.chunk import Chunk

    merge = split_dag(logical_dag((
        AggDesc("count", ()), AggDesc("sum", (col(1, I),)),
        AggDesc("min", (col(1, I),)), AggDesc("max", (col(1, I),)),
    ))).root_dag
    a = run_dag_reference(merge, Chunk.concat(live))
    b = run_dag_reference(merge, Chunk.concat([c for c in ref.chunks if c is not None]))
    assert [[str(d) for d in r] for r in a] == [[str(d) for d in r] for r in b]


# ---------------------------------------------------- robustness contracts

def test_epoch_mismatch_falls_out_of_mesh_batch():
    """A concurrent split between task build and dispatch: the stale lane
    falls out of the mesh batch into the single-task retry path; the other
    lanes' states still merge on device and the total stays correct."""
    store = fill_store(rows=180, regions=6, stores=1)
    dag = scalar_partial_dag()
    orig = store.batch_coprocessor
    fired = []

    def hijack(reqs, **kw):
        if not fired:
            fired.append(1)
            store.cluster.split(tablecodec.encode_row_key(TID, 5))
        return orig(reqs, **kw)

    store.batch_coprocessor = hijack
    r0 = metrics.DISTSQL_RETRIES.value
    res = select(store, KVRequest(dag, full_table_ranges(TID), start_ts=100))
    assert metrics.DISTSQL_RETRIES.value - r0 >= 1  # the split lane retried
    assert res.batch_stats["mesh_lanes"] >= 4  # the rest still merged
    store.batch_coprocessor = orig
    ref = select(store, KVRequest(dag, full_table_ranges(TID), start_ts=100,
                                  mesh=False))
    merge = split_dag(logical_dag((
        AggDesc("count", ()), AggDesc("sum", (col(1, I),)),
        AggDesc("min", (col(1, I),)), AggDesc("max", (col(1, I),)),
    ))).root_dag
    from tidb_tpu.chunk import Chunk

    def folded(chunks):
        rows = run_dag_reference(merge, Chunk.concat([c for c in chunks if c is not None]))
        return [[str(d) for d in r] for r in rows]

    assert folded(res.chunks) == folded(ref.chunks)


def test_min_group_rows_floor_degrades_to_vmap():
    store = fill_store()
    store.MESH_MIN_GROUP_ROWS = 10_000  # instance override of the env knob
    dag = scalar_partial_dag()
    l0 = metrics.MESH_COP_LANES.value
    res = select(store, KVRequest(dag, full_table_ranges(TID), start_ts=100))
    assert metrics.MESH_COP_LANES.value == l0  # mesh declined on data size
    assert res.batch_stats["mesh_lanes"] == 0
    assert res.batch_stats["regions"] > 0  # the vmapped tier served instead


def test_mesh_min_rows_hint_enforced_on_actual_rows():
    """The tidb_tpu_mesh_min_rows hint rides the cop requests and the
    STORE enforces it against the group's actually-decoded rows — a floor
    above the table's real size keeps the query off the mesh even though
    the client-side estimate (whole-store keys) passed."""
    store = fill_store(rows=180, stores=1)  # one group of 180 decoded rows
    dag = scalar_partial_dag()
    l0 = metrics.MESH_COP_LANES.value
    res = select(store, KVRequest(dag, full_table_ranges(TID), start_ts=100,
                                  mesh_min_rows=120))
    assert metrics.MESH_COP_LANES.value > l0  # 180 rows >= 120: mesh ran
    # another table's keys inflate the CLIENT estimate (whole-store keys)
    # past the floor — exactly the case the store-side check exists for
    for h in range(100):
        store.put_row(TID + 1, h, [1, 2], [Datum.i64(h), Datum.i64(h)], ts=11)
    l0 = metrics.MESH_COP_LANES.value
    res = select(store, KVRequest(dag, full_table_ranges(TID), start_ts=101,
                                  mesh_min_rows=200))
    assert metrics.MESH_COP_LANES.value == l0  # 180 decoded rows < 200
    assert res.batch_stats["mesh_lanes"] == 0
    assert res.batch_stats["regions"] > 0


def test_skewed_capacities_degrade_to_vmap_buckets():
    """One post-split giant among tiny regions: padding every mesh lane
    to the max pow2 capacity would blow the stacked footprint toward
    lanes*max (#review), so the skew guard degrades the group to the
    vmapped tier, whose capacity BUCKETING right-sizes the launches."""
    store = TPUStore()
    for h in range(220):
        store.put_row(TID, h, [1, 2], [Datum.i64(h % 7), Datum.i64(h)], ts=10)
    # region 0 keeps ~200 rows; five tiny regions of 4 rows each
    for i in range(5):
        store.cluster.split(tablecodec.encode_row_key(TID, 200 + i * 4))
    dag = scalar_partial_dag()
    l0 = metrics.MESH_COP_LANES.value
    f0 = metrics.MESH_COP_FALLBACKS.value
    res = select(store, KVRequest(dag, full_table_ranges(TID), start_ts=100))
    assert metrics.MESH_COP_LANES.value == l0  # mesh declined on skew
    assert metrics.MESH_COP_FALLBACKS.value - f0 == 1
    assert res.batch_stats["regions"] > 0  # vmapped buckets served
    merge = split_dag(logical_dag((
        AggDesc("count", ()), AggDesc("sum", (col(1, I),)),
        AggDesc("min", (col(1, I),)), AggDesc("max", (col(1, I),)),
    ))).root_dag
    from tidb_tpu.chunk import Chunk

    rows = run_dag_reference(merge, Chunk.concat([c for c in res.chunks if c is not None]))
    assert int(rows[0][0].val) == sum(1 for h in range(220) if h % 7 > 1)


def test_mesh_off_pins_old_paths():
    store = fill_store()
    dag = scalar_partial_dag()
    l0 = metrics.MESH_COP_LANES.value
    res = select(store, KVRequest(dag, full_table_ranges(TID), start_ts=100, mesh=False))
    assert metrics.MESH_COP_LANES.value == l0
    assert res.batch_stats is None  # pool tier: per-region dispatch


def test_wire_roundtrip_mesh_fields():
    dag = scalar_partial_dag()
    # min-rows rides as i64: the sysvar range (1<<40) exceeds i32 (#review)
    req = CopRequest(dag, full_table_ranges(TID), 100, 3, 1, mesh=True,
                     mesh_min_rows=1 << 33)
    back = decode_cop_request(encode_cop_request(req))
    assert back.mesh is True and back.mesh_min_rows == 1 << 33
    resp = CopResponse(chunk=None, region_error="x", batched=2, mesh_merged=5)
    rback = decode_cop_response(encode_cop_response(resp))
    assert rback.batched == 2 and rback.mesh_merged == 5


def test_run_sharded_partial_agg_rejects_grouped_dag():
    """The exported scalar entry point must fail fast on a grouped DAG
    (#review): its positional psum plan cannot align per-region group
    tables — silence here would return garbage states."""
    import jax

    from tidb_tpu.parallel import region_mesh, run_sharded_partial_agg, stack_region_batches
    from tidb_tpu.chunk import Chunk

    rows = [[Datum.i64(i % 3), Datum.i64(i)] for i in range(8)]
    chunks = [Chunk.from_rows([I, I], rows)] * 2
    gagg = Aggregation(group_by=(col(0, I),),
                       aggs=(AggDesc("sum", (col(1, I),)),), partial=True)
    dag = DAGRequest((scan(), gagg), output_offsets=(0, 1))
    stacked = stack_region_batches(chunks, n_total=8)
    with pytest.raises(AssertionError, match="scalar"):
        run_sharded_partial_agg(dag, stacked, region_mesh())


def test_wire_mode_select_meshes():
    """use_wire routes the batch frames through the serialized seam — the
    mesh marker must survive it."""
    store = fill_store()
    dag = scalar_partial_dag()
    l0 = metrics.MESH_COP_LANES.value
    res = select(store, KVRequest(dag, full_table_ranges(TID), start_ts=100, use_wire=True))
    assert metrics.MESH_COP_LANES.value - l0 == 6
    assert res.batch_stats["mesh_lanes"] == 6


# ----------------------------------------------------------- SQL + chaos

def test_sql_mesh_explain_and_trace():
    from tidb_tpu.sql.session import Session
    from tidb_tpu.util import tracing

    s = Session()
    s.execute("CREATE TABLE mt (id BIGINT PRIMARY KEY, v BIGINT)")
    s.execute("INSERT INTO mt VALUES " + ",".join(f"({i},{i % 13})" for i in range(400)))
    tid = s.catalog.table("mt").table_id
    for i in range(1, 8):
        s.store.cluster.split(tablecodec.encode_row_key(tid, i * 50))
    q = "SELECT count(*), sum(v), min(v), max(v) FROM mt WHERE v < 9"
    s.execute("SET tidb_enable_tpu_mesh = OFF")
    want = s.execute(q).values()  # per-region host-merge reference
    s.execute("SET tidb_enable_tpu_mesh = ON")
    s.store.evict_caches()  # cop-cache-served lanes fall out BEFORE the
    # mesh grouping (by design) — drain so the launch itself is attributed
    got = s.execute(q).values()
    assert got == want
    s.store.evict_caches()
    rows = s.execute("EXPLAIN ANALYZE " + q).values()
    by_exec = {r[0]: r for r in rows}
    mc = by_exec["mesh_cop"]
    assert mc[1] == 8 and mc[2] >= 1  # 8 lanes merged into >=1 launches
    assert mc[5].startswith("merged=8->")
    with tracing.trace("t") as root:
        s.execute(q)
    spans = root.find("distsql.batch_cop")
    assert spans and spans[0].attrs.get("tier") == "mesh"
    assert root.sum_attr("distsql.batch_cop", "mesh_lanes_merged") == 8
    mesh_exec = root.find("cop.mesh_execute")
    assert mesh_exec and mesh_exec[0].attrs.get("kind") == "scalar"


@pytest.mark.slow
def test_chaos_storm_with_mesh_tier():
    """The chaos acceptance bar with the mesh tier enabled (it is ON by
    default — this pins that the storm actually exercised it): seeded
    splits/outages/transfers, zero wrong results, and on-device merges
    really happened."""
    from chaos import run_chaos

    l0 = metrics.MESH_COP_LANES.value
    report = run_chaos(seed=17, statements=80)
    assert report["wrong_results"] == []
    assert report["untyped_errors"] == []
    assert metrics.MESH_COP_LANES.value > l0  # the storm rode the mesh


def test_chaos_small_storm_mesh_quick():
    """Tier-1-sized storm (the slow one above is the full bar): the mesh
    tier stays zero-wrong-results under topology churn."""
    from chaos import run_chaos

    l0 = metrics.MESH_COP_LANES.value
    report = run_chaos(seed=23, statements=30)
    assert report["wrong_results"] == []
    assert report["untyped_errors"] == []
    assert metrics.MESH_COP_LANES.value > l0
