"""Optimizer hints + SQL plan bindings (VERDICT r4 next #7; ref:
pkg/util/hint hintparser consumed in the planner, pkg/bindinfo binding.go
matched at planner/optimize.go:135): a hint observably overrides the
optimizer's choice in EXPLAIN, and a binding applies it to un-hinted
statements by structural digest."""

import pytest

from tidb_tpu.sql import Session, SQLError


def _sess():
    s = Session()
    s.execute("create table t (id bigint primary key, v bigint, w bigint)")
    s.execute("create index iv on t (v)")
    s.execute("insert into t values " + ",".join(f"({i},{i % 5},{i})" for i in range(60)))
    return s


def _access(s, sql):
    return s.execute("explain " + sql).values()[0][0]


def test_use_index_hint_overrides():
    s = _sess()
    base = _access(s, "select w from t where v = 3")
    assert "index" in base  # selective predicate picks the index already
    assert _access(s, "select /*+ IGNORE_INDEX(t, iv) */ w from t where v = 3") == "access: table"
    assert "iv" in _access(s, "select /*+ USE_INDEX(t, iv) */ w from t where v = 3")
    # hinted result content identical
    a = s.execute("select w from t where v = 3 order by w").values()
    b = s.execute("select /*+ IGNORE_INDEX(t, iv) */ w from t where v = 3 order by w").values()
    assert a == b


def test_join_probe_hint():
    s = _sess()
    s.execute("create table small (id bigint primary key, v bigint)")
    s.execute("insert into small values (1, 1), (2, 2)")
    sql = "select count(*) from t join small on t.v = small.v"
    hinted = "select /*+ HASH_JOIN_PROBE(small) */ count(*) from t join small on t.v = small.v"
    assert s.execute(sql).values() == s.execute(hinted).values()


def test_global_binding_with_backslash_literal_mirrors(monkeypatch):
    """The bind_info mirror SQL shares the user-mirror escape contract
    (#review): a bound statement whose text contains backslash-escaped
    string literals must still land one row in mysql.bind_info — only
    doubling quotes would let the backslash swallow the closing quote and
    silently drop the mirror row."""
    s = _sess()
    s.execute("create table bs (w bigint, n varchar(10))")
    tgt = "select w from bs where n = 'x\\\\'"
    hint = "select /*+ HASH_AGG() */ w from bs where n = 'x\\\\'"
    s.execute(f"create global binding for {tgt} using {hint}")
    rows = s.execute("select original_sql from mysql.bind_info").values()
    assert any("x\\\\" in r[0] for r in rows), rows


def test_session_binding_applies_and_drops():
    s = _sess()
    s.execute("create binding for select w from t where v = 3 "
              "using select /*+ IGNORE_INDEX(t, iv) */ w from t where v = 3")
    # un-hinted statement now takes the bound plan (observable in EXPLAIN)
    assert _access(s, "select w from t where v = 3") == "access: table"
    # different CONSTANT, same digest -> still bound
    assert _access(s, "select w from t where v = 1") == "access: table"
    rows = s.execute("show bindings").values()
    assert len(rows) == 1 and "IGNORE_INDEX" in rows[0][1]
    s.execute("drop binding for select w from t where v = 3")
    assert "index" in _access(s, "select w from t where v = 3")


def test_global_binding_lands_in_bind_info():
    s = _sess()
    s.execute("create global binding for select w from t where v = 3 "
              "using select /*+ IGNORE_INDEX(t, iv) */ w from t where v = 3")
    assert _access(s, "select w from t where v = 3") == "access: table"
    assert s.execute("select count(*) from mysql.bind_info").values() == [[1]]
    rows = s.execute("show global bindings").values()
    assert len(rows) == 1
    s.execute("drop global binding for select w from t where v = 3")
    assert s.execute("select count(*) from mysql.bind_info").values() == [[0]]


def test_binding_rejects_structural_mismatch():
    s = _sess()
    with pytest.raises(SQLError, match="structurally"):
        s.execute("create binding for select w from t where v = 3 "
                  "using select /*+ USE_INDEX(t, iv) */ w from t where v = 3 and w > 0")


def test_binding_keeps_query_constants():
    """The binding transfers HINTS only — the incoming query's own
    literals stay (code-review r5: wholesale AST substitution returned the
    binding's constants)."""
    s = _sess()
    s.execute("create binding for select w from t where v = 3 "
              "using select /*+ IGNORE_INDEX(t, iv) */ w from t where v = 3")
    got = s.execute("select w from t where v = 1 order by w").values()
    assert got == [[i] for i in range(60) if i % 5 == 1]


def test_distinct_digest_differs():
    from tidb_tpu.parser import parse_one
    from tidb_tpu.sql.session import ast_digest

    a = ast_digest(parse_one("select w from t where v = 3"))
    b = ast_digest(parse_one("select distinct w from t where v = 3"))
    assert a != b


def test_hint_elsewhere_is_comment():
    s = _sess()
    s.execute("update /*+ NO_INDEX_MERGE() */ t set w = w + 0 where id = 1")
    s.execute("insert /*+ SET_VAR(x=1) */ into t values (1000, 0, 0)")
    assert s.execute("select count(*) from t").values() == [[61]]
