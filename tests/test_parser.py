"""Parser tests (ref: pkg/parser/parser_test.go patterns — statement zoo +
TPC-H shapes)."""

import pytest

from tidb_tpu.parser import ParseError, ast as A, parse, parse_one, parse_expr


def test_simple_select():
    s = parse_one("SELECT 1")
    assert isinstance(s, A.SelectStmt)
    assert s.fields[0].expr.value == 1


def test_select_star_where():
    s = parse_one("select * from t where a > 10 and b = 'x'")
    assert isinstance(s.fields[0].expr, A.Star)
    assert s.from_clause.name == "t"
    assert s.where.op == "and"


def test_qualified_names():
    s = parse_one("select db.t.a, t.b c, `weird col` from db.t `al`")
    f0 = s.fields[0].expr
    assert (f0.db, f0.table, f0.name) == ("db", "t", "a")
    assert s.fields[1].alias == "c"
    assert s.fields[2].expr.name == "weird col"
    assert s.from_clause.alias == "al"


def test_operator_precedence():
    e = parse_expr("1 + 2 * 3")
    assert e.op == "plus" and e.right.op == "mul"
    e = parse_expr("a or b and c")
    assert e.op == "or" and e.right.op == "and"
    e = parse_expr("not a = b")  # NOT binds looser than =
    assert e.op == "not" and e.operand.op == "eq"
    e = parse_expr("1 | 2 & 3")
    assert e.op == "bitor" and e.right.op == "bitand"
    e = parse_expr("- a * b")
    assert e.op == "mul" and isinstance(e.left, A.UnaryOp)


def test_between_in_like_is():
    e = parse_expr("a between 1 and 2")
    assert isinstance(e, A.Between)
    e = parse_expr("a not in (1, 2, 3)")
    assert isinstance(e, A.InList) and e.negated and len(e.items) == 3
    e = parse_expr("name like 'ab%' escape '#'")
    assert isinstance(e, A.Like) and e.escape == "#"
    e = parse_expr("x is not null")
    assert isinstance(e, A.IsNull) and e.negated


def test_case_cast_interval():
    e = parse_expr("case when a > 0 then 'p' when a < 0 then 'n' else 'z' end")
    assert isinstance(e, A.Case) and len(e.when_clauses) == 2
    e = parse_expr("cast(a as decimal(10,2))")
    assert isinstance(e, A.Cast) and e.to_type.length == 10 and e.to_type.decimal == 2
    e = parse_expr("d + interval 7 day")
    assert isinstance(e, A.FuncCall) and e.name == "date_add"


def test_agg_funcs():
    s = parse_one("select count(*), count(distinct a), sum(b), avg(c) from t group by d having sum(b) > 5")
    assert isinstance(s.fields[0].expr, A.AggFunc)
    assert isinstance(s.fields[0].expr.args[0], A.Star)
    assert s.fields[1].expr.distinct
    assert s.having is not None


def test_joins():
    s = parse_one("select * from a join b on a.x = b.x left join c on b.y = c.y")
    j = s.from_clause
    assert isinstance(j, A.Join) and j.kind == "left"
    assert j.left.kind == "inner"
    s2 = parse_one("select * from a, b where a.x = b.x")
    assert s2.from_clause.kind == "cross"


def test_subqueries():
    s = parse_one("select * from t where a in (select b from u) and exists (select 1 from v)")
    assert isinstance(s.where.left, A.InSubquery)
    assert isinstance(s.where.right, A.Exists)
    s = parse_one("select (select max(x) from u) m from t")
    assert isinstance(s.fields[0].expr, A.SubqueryExpr)
    s = parse_one("select * from (select a, b from t) dt where dt.a > 1")
    assert isinstance(s.from_clause, A.SubqueryTable)


def test_union_order_limit():
    s = parse_one("select a from t union all select b from u order by 1 limit 5 offset 2")
    assert isinstance(s, A.SetOprStmt) and s.all_flags == [True]
    assert s.limit.count.value == 5 and s.limit.offset.value == 2


def test_tpch_q6():
    q = """
    select sum(l_extendedprice * l_discount) as revenue from lineitem
    where l_shipdate >= date '1994-01-01'
      and l_shipdate < date '1994-01-01' + interval '1' year
      and l_discount between 0.05 and 0.07 and l_quantity < 24
    """
    s = parse_one(q)
    assert isinstance(s.fields[0].expr, A.AggFunc)
    assert s.fields[0].alias == "revenue"


def test_tpch_q1():
    q = """
    select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty,
      sum(l_extendedprice) as sum_base_price,
      sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
      sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
      avg(l_quantity) as avg_qty, avg(l_extendedprice) as avg_price,
      avg(l_discount) as avg_disc, count(*) as count_order
    from lineitem where l_shipdate <= date '1998-12-01' - interval '90' day
    group by l_returnflag, l_linestatus order by l_returnflag, l_linestatus
    """
    s = parse_one(q)
    assert len(s.fields) == 10 and len(s.group_by) == 2 and len(s.order_by) == 2


def test_tpch_q3():
    q = """
    select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
      o_orderdate, o_shippriority
    from customer, orders, lineitem
    where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
      and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15'
      and l_shipdate > date '1995-03-15'
    group by l_orderkey, o_orderdate, o_shippriority
    order by revenue desc, o_orderdate limit 10
    """
    s = parse_one(q)
    assert s.order_by[0].desc and s.limit.count.value == 10
    assert s.from_clause.kind == "cross"


def test_create_table():
    s = parse_one(
        """CREATE TABLE IF NOT EXISTS t (
            id bigint unsigned not null auto_increment primary key,
            name varchar(64) default 'x' comment 'the name',
            amount decimal(15, 2) not null,
            created datetime default current_timestamp,
            key idx_name (name(10)),
            unique key uq (amount, name)
        ) engine=innodb charset=utf8mb4 auto_increment=100"""
    )
    assert s.if_not_exists
    assert s.columns[0].auto_increment and s.columns[0].primary_key
    assert s.columns[0].type.unsigned
    assert s.columns[2].type.decimal == 2
    assert len(s.indexes) == 2 and s.indexes[1].unique
    assert s.options["auto_increment"] == 100


def test_create_table_pk_constraint():
    s = parse_one("create table t (a int, b int, primary key (a, b))")
    assert s.indexes[0].primary and s.indexes[0].columns == [("a", -1), ("b", -1)]


def test_alter_table():
    s = parse_one("alter table t add column c int not null after b, drop column d, add index i (c), rename to t2")
    assert [sp.action for sp in s.specs] == ["add_column", "drop_column", "add_index", "rename"]
    assert s.specs[0].position == "after:b"


def test_dml():
    s = parse_one("insert into t (a, b) values (1, 'x'), (2, 'y') on duplicate key update b = 'z'")
    assert len(s.values) == 2 and len(s.on_duplicate) == 1
    s = parse_one("insert into t set a = 1, b = 2")
    assert s.columns == ["a", "b"]
    s = parse_one("replace into t values (1)")
    assert s.replace
    s = parse_one("update t set a = a + 1 where b < 5 limit 10")
    assert s.limit.count.value == 10
    s = parse_one("delete from t where a = 1")
    assert isinstance(s, A.DeleteStmt)
    s = parse_one("insert into t select * from u")
    assert s.select is not None


def test_misc_stmts():
    assert isinstance(parse_one("begin"), A.BeginStmt)
    assert isinstance(parse_one("start transaction"), A.BeginStmt)
    assert isinstance(parse_one("commit"), A.CommitStmt)
    assert isinstance(parse_one("rollback"), A.RollbackStmt)
    assert isinstance(parse_one("use test"), A.UseStmt)
    s = parse_one("set @@global.tidb_mem_quota = 1024, autocommit = 1")
    assert s.assignments[0][0] == "global"
    assert s.assignments[1] [1] == "autocommit"
    s = parse_one("show tables from db1 like 't%'")
    assert s.kind == "tables" and s.db == "db1" and s.pattern == "t%"
    s = parse_one("show create table t")
    assert s.kind == "create_table"
    s = parse_one("explain analyze select 1")
    assert s.analyze
    s = parse_one("analyze table t1, t2")
    assert len(s.tables) == 2
    s = parse_one("admin show ddl jobs")
    assert s.kind == "show_ddl_jobs"
    s = parse_one("admin check table t")
    assert s.kind == "check_table"
    s = parse_one("backup database tpch to 'local:///tmp/bk'")
    assert s.kind == "backup" and s.storage == "local:///tmp/bk"
    s = parse_one("drop table if exists a, b")
    assert s.if_exists and len(s.tables) == 2
    s = parse_one("truncate table t")
    assert isinstance(s, A.TruncateTableStmt)
    s = parse_one("rename table a to b")
    assert isinstance(s, A.RenameTableStmt)
    s = parse_one("create index i on t (a, b(5))")
    assert isinstance(s, A.CreateIndexStmt)
    s = parse_one("kill 42")
    assert s.conn_id == 42


def test_prepared():
    s = parse_one("prepare s1 from 'select * from t where a = ?'")
    assert isinstance(s, A.PrepareStmt)
    s = parse_one("execute s1 using @x, @y")
    assert s.using == ["x", "y"]
    s = parse_one("select * from t where a = ? and b = ?")
    markers = []

    def walk(e):
        if isinstance(e, A.ParamMarker):
            markers.append(e.index)
        for f in getattr(e, "__dict__", {}).values():
            if isinstance(f, A.ExprNode):
                walk(f)

    walk(s.where)
    assert markers == [0, 1]


def test_multi_statement():
    stmts = parse("select 1; select 2;")
    assert len(stmts) == 2


def test_comments_and_strings():
    s = parse_one("select /* hi */ 'it''s', \"dq\" -- trailing\n from t")
    assert s.fields[0].expr.value == "it's"
    assert s.fields[1].expr.value == "dq"


def test_errors():
    with pytest.raises(ParseError):
        parse_one("select from where")
    with pytest.raises(ParseError):
        parse_one("bogus statement")
    with pytest.raises(ParseError):
        parse_one("select 'unterminated")


def test_variables():
    e = parse_expr("@@tidb_distsql_scan_concurrency")
    assert isinstance(e, A.Variable) and e.system
    e = parse_expr("@uservar")
    assert not e.system


def test_load_data():
    s = parse_one(
        "load data local infile '/tmp/x.csv' into table t fields terminated by ',' "
        "enclosed by '\"' lines terminated by '\\n' ignore 1 lines (a, b, c)"
    )
    assert s.fields_terminated == "," and s.ignore_lines == 1 and s.columns == ["a", "b", "c"]


def test_union_parenthesized_branch_keeps_local_limit():
    """(#review) A parenthesized union branch's ORDER/LIMIT is branch-local,
    not hoisted to the union."""
    s = parse_one("(select a from t order by a limit 1) union all (select b from u order by b limit 1)")
    assert isinstance(s, A.SetOprStmt)
    assert s.limit is None and s.order_by == []
    assert s.selects[1].limit.count.value == 1 and s.selects[1].order_by[0].expr.name == "b"


def test_bang_binds_tight():
    """'!' binds at unary precedence, unlike NOT (#review)."""
    e = parse_expr("!a in (1,2)")
    assert isinstance(e, A.InList) and isinstance(e.expr, A.UnaryOp)


def test_backquoted_name_never_a_call():
    """`max`(a) is a column ref, not an aggregate (#review)."""
    s = parse_one("select `max` from t")
    assert isinstance(s.fields[0].expr, A.ColumnName)
    with pytest.raises(ParseError):
        parse_one("select `max`(a) from t")


def test_db_table_star():
    s = parse_one("select db.t.* from db.t")
    st = s.fields[0].expr
    assert isinstance(st, A.Star) and st.table == "t" and st.db == "db"


def test_with_cte():
    s = parse_one("with x as (select 1 a), y (b) as (select a from x) select * from y")
    assert len(s.ctes) == 2
    assert s.ctes[1].name == "y" and s.ctes[1].columns == ["b"]
    s = parse_one("with recursive r as (select 1 union all select n + 1 from r) select * from r")
    assert s.ctes[0].recursive
