"""Point-in-time recovery (ISSUE 20): log backup riding the CDC stream
as a raw changefeed with atomic segment+manifest writes, replay-to-ts
RESTORE over the latest full backup with typed gap detection and a
resumable per-segment checkpoint, DDL replication through the feed, the
sliding GC safepoint, the pd.pitr tick phase, and the CHAOS_PITR storm
acceptance (ref: br/pkg/stream + br/pkg/restore's PiTR path)."""

import json
import os
import sys

import pytest

from tidb_tpu.br import (
    LogGapError,
    ReplayInterrupted,
    log_backup_views,
    restore_until,
    start_log_backup,
)
from tidb_tpu.codec import tablecodec
from tidb_tpu.sql.session import Session, SQLError
from tidb_tpu.util import failpoint, metrics

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))


def make_session():
    s = Session()
    s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT, name VARCHAR(16))")
    return s


def rows_of(s, table="t"):
    return s.execute(f"SELECT * FROM {table} ORDER BY 1").values()


def pitr_cluster(tmp_path, n=6):
    """Session + full backup + attached log backup under tmp_path; n
    seed rows land BEFORE the full backup."""
    s = make_session()
    if n:
        s.execute("INSERT INTO t VALUES " + ",".join(
            f"({i},{i * 10},'r{i}')" for i in range(n)))
    root = str(tmp_path / "bk")
    s.execute(f"BACKUP DATABASE * TO '{os.path.join(root, 'full', 'b0')}'")
    s.execute(f"BACKUP LOG TO 'file://{root}'")
    return s, root


# ------------------------------------------------------------- log backup

class TestLogBackup:
    def test_sql_lifecycle_and_show(self, tmp_path):
        s, root = pitr_cluster(tmp_path)
        row = s.execute("SHOW BACKUP LOGS").values()[0]
        assert row[0] == f"file://{root}" and row[2] == "normal"
        s.execute("INSERT INTO t VALUES (50, 1, 'x')")
        s.store.pd.tick()  # the pd.cdc phase drives the raw feed
        row = s.execute("SHOW BACKUP LOGS").values()[0]
        assert row[6] >= 1 and row[7] >= 1  # segments, events
        assert row[4] >= s.store.kv.max_committed()  # checkpoint caught up
        with pytest.raises(SQLError):  # second attach to the same dest
            s.execute(f"BACKUP LOG TO 'file://{root}'")
        s.execute(f"STOP BACKUP LOG TO 'file://{root}'")
        assert s.execute("SHOW BACKUP LOGS").values() == []
        with pytest.raises(SQLError):
            s.execute(f"STOP BACKUP LOG TO 'file://{root}'")

    def test_segments_chain_and_end_in_resolved_marks(self, tmp_path):
        s, root = pitr_cluster(tmp_path)
        for i in range(3):
            s.execute(f"INSERT INTO t VALUES ({60 + i}, {i}, 'w')")
            s.store.pd.tick()
        man = json.loads(open(os.path.join(root, "log", "manifest.json")).read())
        segs = man["segments"]
        assert len(segs) >= 2
        prev_resolved = 0
        for seg in segs:
            # the chain: each link starts where the previous segment ended
            assert seg["base_ts"] == prev_resolved
            assert seg["min_ts"] > seg["base_ts"]
            assert seg["max_ts"] <= seg["resolved_ts"]
            prev_resolved = seg["resolved_ts"]
            lines = open(os.path.join(root, "log", seg["file"])).read().splitlines()
            last = json.loads(lines[-1])
            assert last == {"t": "resolved", "ts": seg["resolved_ts"]}
            assert sum(1 for ln in lines if json.loads(ln).get("t") == "kv") == seg["events"]
        assert man["checkpoint_ts"] >= prev_resolved

    def test_reattach_resumes_chain_without_duplicates(self, tmp_path):
        s, root = pitr_cluster(tmp_path)
        s.execute("INSERT INTO t VALUES (50, 1, 'x')")
        s.store.pd.tick()
        s.execute(f"STOP BACKUP LOG TO 'file://{root}'")
        s.execute("INSERT INTO t VALUES (51, 2, 'y')")  # while detached
        s.execute(f"BACKUP LOG TO 'file://{root}'")  # re-attach resumes
        s.store.pd.tick()
        lb = next(iter(s.store.log_backups.values()))
        seen = set()
        for rec in lb.sink.writer.read_records():
            if rec.get("t") != "kv":
                continue
            assert (rec["k"], rec["ts"]) not in seen
            seen.add((rec["k"], rec["ts"]))
        # the detach-window write was recovered by the incremental scan
        assert lb.sink.checkpoint_ts >= s.store.kv.max_committed()
        until = s.store.next_ts()
        s.store.pd.tick()  # the checkpoint must pass the cut to prove it
        r = Session()
        r.execute(f"RESTORE DATABASE * FROM '{root}' UNTIL TS = {until}")
        assert rows_of(r) == rows_of(s)

    def test_checkpoint_slides_the_gc_safepoint(self, tmp_path):
        s, root = pitr_cluster(tmp_path, n=0)
        s.execute("INSERT INTO t VALUES (1, 10, 'a')")
        s.execute("UPDATE t SET v = 11 WHERE id = 1")  # two versions
        key = tablecodec.encode_row_key(s.catalog.table("t").table_id, 1)
        s.store.run_gc(safepoint=s.store.kv.max_committed() + 1)
        with s.store.kv.lock:
            n_held = len(s.store.kv._data.get(key, ()))
        assert n_held == 2  # the feed's safepoint pinned the old version
        s.store.pd.tick()  # flush: the checkpoint (and safepoint) slide
        s.store.run_gc(safepoint=s.store.kv.max_committed() + 1)
        with s.store.kv.lock:
            n_after = len(s.store.kv._data.get(key, ()))
        assert n_after == 1  # released: GC may fold history the log holds


# -------------------------------------------------------- replay-to-ts

class TestRestoreUntil:
    def test_restore_to_mid_ts_is_byte_exact(self, tmp_path):
        s, root = pitr_cluster(tmp_path)
        s.execute("INSERT INTO t VALUES (50, 1, 'x')")
        s.execute("UPDATE t SET v = 2 WHERE id = 50")
        s.store.pd.tick()
        mid_ts = s.store.next_ts()
        oracle_mid = rows_of(s)
        s.execute("DELETE FROM t WHERE id = 0")
        s.execute("INSERT INTO t VALUES (51, 3, 'y')")
        s.store.pd.tick()
        end_ts = s.store.next_ts()
        oracle_end = rows_of(s)
        s.store.pd.tick()  # the checkpoint must pass end_ts to prove it

        r1 = Session()
        res = r1.execute(f"RESTORE DATABASE * FROM '{root}' UNTIL TS = {mid_ts}")
        assert rows_of(r1) == oracle_mid  # no id=51, no delete, v=2
        assert int(res.values()[0][1]) == mid_ts
        r2 = Session()
        r2.execute(f"RESTORE DATABASE * FROM '{root}' UNTIL TS = {end_ts}")
        assert rows_of(r2) == oracle_end
        # the restored cluster is live: TSO moved past the cut
        r2.execute("INSERT INTO t VALUES (99, 9, 'z')")
        assert len(rows_of(r2)) == len(oracle_end) + 1

    def test_ddl_replays_through_the_feed_to_the_right_cut(self, tmp_path):
        s, root = pitr_cluster(tmp_path, n=2)
        s.store.pd.tick()
        pre_ddl_ts = s.store.next_ts()
        pre_rows = rows_of(s)
        s.execute("ALTER TABLE t ADD COLUMN w BIGINT DEFAULT 7")
        s.execute("INSERT INTO t VALUES (50, 1, 'x', 8)")
        s.store.pd.tick()
        post_ddl_ts = s.store.next_ts()
        post_rows = rows_of(s)
        s.store.pd.tick()  # the checkpoint must pass post_ddl_ts

        r_old = Session()
        r_old.execute(f"RESTORE DATABASE * FROM '{root}' UNTIL TS = {pre_ddl_ts}")
        assert rows_of(r_old) == pre_rows  # 3-column shape: DDL not yet
        assert len(r_old.catalog.table("t").columns) == 3
        r_new = Session()
        r_new.execute(f"RESTORE DATABASE * FROM '{root}' UNTIL TS = {post_ddl_ts}")
        assert rows_of(r_new) == post_rows  # old rows backfill w=7
        assert [c.name for c in r_new.catalog.table("t").columns][-1] == "w"

    def test_log_gap_is_typed_never_silently_short(self, tmp_path):
        s, root = pitr_cluster(tmp_path)
        for i in range(3):
            s.execute(f"INSERT INTO t VALUES ({60 + i}, {i}, 'w')")
            s.store.pd.tick()
        until = s.store.next_ts()
        g0 = metrics.PITR_LOG_GAPS.value
        r = Session()
        failpoint.enable("br/log-gap", 1)
        try:
            with pytest.raises(LogGapError) as ei:
                restore_until(r.store, r.catalog, root, until)
        finally:
            failpoint.disable("br/log-gap")
        assert ei.value.covered_ts < ei.value.target_ts == until
        assert metrics.PITR_LOG_GAPS.value > g0
        # the SQL surface maps it to a typed SQLError, same failpoint
        failpoint.enable("br/log-gap", 1)
        try:
            with pytest.raises(SQLError):
                Session().execute(
                    f"RESTORE DATABASE * FROM '{root}' UNTIL TS = {until}")
        finally:
            failpoint.disable("br/log-gap")

    def test_restore_past_log_end_is_typed(self, tmp_path):
        s, root = pitr_cluster(tmp_path)
        s.execute("INSERT INTO t VALUES (50, 1, 'x')")
        s.store.pd.tick()
        beyond = s.store.next_ts() + 100_000  # no log covers this
        with pytest.raises(LogGapError):
            r = Session()
            restore_until(r.store, r.catalog, root, beyond)

    def test_no_full_backup_under_ts_is_typed(self, tmp_path):
        s = make_session()
        root = str(tmp_path / "bk")
        s.execute(f"BACKUP LOG TO 'file://{root}'")  # log only, no full
        s.execute("INSERT INTO t VALUES (1, 10, 'a')")
        s.store.pd.tick()
        r = Session()
        with pytest.raises(LogGapError):
            restore_until(r.store, r.catalog, root, s.store.next_ts())

    def test_replay_crash_resumes_idempotently(self, tmp_path):
        s, root = pitr_cluster(tmp_path)
        for i in range(3):  # several segments so the crash lands mid-chain
            s.execute(f"INSERT INTO t VALUES ({60 + i}, {i}, 'w')")
            s.store.pd.tick()
        until = s.store.next_ts()
        oracle = rows_of(s)
        s.store.pd.tick()  # the checkpoint must pass the cut to prove it
        r = Session()
        r0 = metrics.PITR_REPLAY_RESUMES.value
        failpoint.enable("restore/replay-crash", 1)
        try:
            with pytest.raises(ReplayInterrupted):
                restore_until(r.store, r.catalog, root, until)
        finally:
            failpoint.disable("restore/replay-crash")
        ckpt = os.path.join(root, f"restore-ckpt-{until}.json")
        assert os.path.exists(ckpt)  # the per-segment checkpoint survived
        rep = restore_until(r.store, r.catalog, root, until)
        assert rep["resumed"] is True
        assert metrics.PITR_REPLAY_RESUMES.value > r0
        assert rows_of(r) == oracle  # re-run is idempotent, not doubled
        assert not os.path.exists(ckpt)  # done: a fresh run starts clean


# ----------------------------------------- atomic segments (satellite 1)

class TestKillMidFlush:
    def test_kill_mid_flush_leaves_no_torn_tail(self, tmp_path):
        """The torn-tail crash this PR fixes: a kill between tmp write
        and rename must leave NOTHING a consumer reads — and the
        re-queued window must land exactly once after RESUME."""
        from tidb_tpu.cdc import FileSink

        s = make_session()
        s.execute(f"CREATE CHANGEFEED cf INTO 'file://{tmp_path}/out' FOR TABLE t WITH start_ts = 0")
        s.execute("INSERT INTO t VALUES (1, 10, 'a')")
        failpoint.enable("cdc/segment-crash", 1)
        s.store.cdc.tick()
        feed = s.store.cdc.get("cf")
        assert feed.view(s.store)["state"] == "error"
        sink_dir = f"{tmp_path}/out/cf"
        assert any(f.endswith(".tmp") for f in os.listdir(sink_dir))
        recs = FileSink(f"{tmp_path}/out", "cf").read_records()
        assert recs == []  # the torn tmp is invisible, not a broken read
        s.store.cdc.resume("cf")
        s.store.cdc.tick()
        assert feed.view(s.store)["state"] == "normal"
        recs = FileSink(f"{tmp_path}/out", "cf").read_records()
        assert sum(1 for r in recs if r.get("type") == "row") == 1  # once


# --------------------------------- snapshot backup safepoint (satellite 2)

class TestSnapshotBackupSafepoint:
    def test_backup_and_restore_pin_then_release(self, tmp_path, monkeypatch):
        from tidb_tpu.tools import backup, restore

        s = make_session()
        s.execute("INSERT INTO t VALUES (1, 10, 'a'), (2, 20, 'b')")
        calls = []
        orig_reg, orig_unreg = s.store.register_snapshot, s.store.unregister_snapshot
        monkeypatch.setattr(s.store, "register_snapshot",
                            lambda ts: (calls.append(("reg", ts)), orig_reg(ts))[1])
        monkeypatch.setattr(s.store, "unregister_snapshot",
                            lambda ts: (calls.append(("unreg", ts)), orig_unreg(ts))[1])
        bdir = str(tmp_path / "full")
        backup(s.store, s.catalog, bdir)
        assert ("reg", calls[0][1]) in calls and ("unreg", calls[0][1]) in calls
        with s.store._tso_lock:
            assert calls[0][1] not in s.store._active_snapshots  # released
        calls.clear()
        r = Session()
        rcalls = []
        r_reg, r_unreg = r.store.register_snapshot, r.store.unregister_snapshot
        monkeypatch.setattr(r.store, "register_snapshot",
                            lambda ts: (rcalls.append(("reg", ts)), r_reg(ts))[1])
        monkeypatch.setattr(r.store, "unregister_snapshot",
                            lambda ts: (rcalls.append(("unreg", ts)), r_unreg(ts))[1])
        restore(r.store, r.catalog, bdir)
        assert [c[0] for c in rcalls] == ["reg", "unreg"]
        assert rows_of(r) == rows_of(s)


# ------------------------------------------------------ surfaces + metrics

class TestSurfaces:
    def test_pd_tick_has_pitr_phase(self, tmp_path):
        s, _root = pitr_cluster(tmp_path, n=1)
        s.store.pd.tick()
        root = s.store.pd.last_tick_root
        assert any(c.name == "pd.pitr" for c in root.children)

    def test_pitr_tick_trims_the_schema_journal(self, tmp_path):
        s, _root = pitr_cluster(tmp_path, n=1)
        s.execute("ALTER TABLE t ADD COLUMN w BIGINT DEFAULT 7")
        assert len(s.store.schema_journal) == 1
        s.store.pd.tick()  # checkpoint passes the DDL; pd.pitr trims below
        assert len(s.store.schema_journal) == 0

    def test_metric_families_pass_scrape_check(self, tmp_path):
        from scrape_check import validate

        s, root = pitr_cluster(tmp_path)
        s.execute("INSERT INTO t VALUES (50, 1, 'x')")
        s.store.pd.tick()
        until = s.store.next_ts()
        s.store.pd.tick()
        r = Session()
        restore_until(r.store, r.catalog, root, until)
        text = metrics.REGISTRY.dump()
        for family in (
            "tidb_tpu_log_backup_segments_total",
            "tidb_tpu_log_backup_events_total",
            "tidb_tpu_log_backup_checkpoint_ts",
            "tidb_tpu_log_backup_resolved_lag",
            "tidb_tpu_pitr_restores_total",
            "tidb_tpu_pitr_segments_replayed_total",
            "tidb_tpu_pitr_replayed_events_total",
            "tidb_tpu_cdc_schema_events_total",
        ):
            assert f"# TYPE {family}" in text, family
        assert 'tidb_tpu_log_backup_checkpoint_ts{changefeed="log-backup:' in text
        assert validate(text) == []

    def test_views_surface(self, tmp_path):
        s, root = pitr_cluster(tmp_path)
        s.execute("INSERT INTO t VALUES (50, 1, 'x')")
        s.store.pd.tick()
        v = log_backup_views(s.store)[0]
        assert v["destination"] == f"file://{root}"
        assert v["state"] == "normal" and v["resolved_lag"] == 0
        assert v["segments"] >= 1 and v["events"] >= 1


# ------------------------------------------------------- storm acceptance

def test_pitr_chaos_storm_acceptance():
    """ISSUE 20 acceptance: a log backup + a mirror replay feed ride a
    seeded DML+DDL storm under splits/transfers/outage and the cdc/*
    failpoints; three mid-storm restore points come back byte-identical
    to the live oracle, the mid-feed ALTERs park nothing, a kill
    mid-flush costs nothing, a mid-replay crash resumes idempotently,
    and a manifest gap fails typed."""
    from chaos import pitr_storm_bad, run_pitr_storm

    report = run_pitr_storm(seed=19, statements=100)
    assert report["untyped_errors"] == [], report["untyped_errors"]
    assert report["ordering_violations"] == [], report["ordering_violations"]
    assert all(r["chaos_t_equal"] and r["chaos_d_equal"]
               for r in report["restores"]), report["restores"]
    assert report["replay_crash_resumed"] and report["log_gap_typed"], report
    assert not pitr_storm_bad(report), report
