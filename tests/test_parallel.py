"""Mesh-parallel tests on the 8-device virtual CPU mesh: region-sharded
partial aggregation with psum, and the all_to_all hash exchange."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tidb_tpu.types import Datum, MyDecimal, new_datetime, new_decimal, new_longlong
from tidb_tpu.chunk import Chunk
from tidb_tpu.expr import AggDesc, col, func, lit
from tidb_tpu.exec import Aggregation, ColumnInfo, DAGRequest, Selection, TableScan, run_dag_reference
from tidb_tpu.parallel import region_mesh, run_sharded_partial_agg, stack_region_batches
from tidb_tpu.parallel.exchange import exchange_group_aggregate, hash_partition_ids, scatter_to_buckets
from tidb_tpu.expr.compile import CompVal, normalize_device_column

BOOL = new_longlong(notnull=True)
FTS = [new_longlong(), new_decimal(10, 2)]


def region_chunks(n_regions=8, rows_per=37, seed=3):
    rng = np.random.default_rng(seed)
    chunks, all_rows = [], []
    for r in range(n_regions):
        rows = []
        for _ in range(rows_per + int(rng.integers(0, 9))):
            row = [
                Datum.NULL if rng.random() < 0.05 else Datum.i64(int(rng.integers(0, 6))),
                Datum.NULL if rng.random() < 0.05 else Datum.dec(MyDecimal(f"{int(rng.integers(-9999, 9999))/100:.2f}")),
            ]
            rows.append(row)
        all_rows.extend(rows)
        chunks.append(Chunk.from_rows(FTS, rows))
    return chunks, all_rows


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8


def test_sharded_scalar_partial_agg_psum():
    chunks, all_rows = region_chunks()
    mesh = region_mesh()
    scan = TableScan(1, (ColumnInfo(1, FTS[0]), ColumnInfo(2, FTS[1])))
    pred = func("gt", BOOL, col(0, FTS[0]), lit(1, new_longlong()))
    agg = Aggregation(
        group_by=(),
        aggs=(AggDesc("sum", (col(1, FTS[1]),)), AggDesc("count", ()), AggDesc("avg", (col(1, FTS[1]),))),
        partial=True,
    )
    dag = DAGRequest((scan, Selection((pred,)), agg), output_offsets=(0, 1, 2, 3))
    stacked = stack_region_batches(chunks, n_total=8)
    states = run_sharded_partial_agg(dag, stacked, mesh)
    # oracle over all rows
    ref = run_dag_reference(
        DAGRequest((scan, Selection((pred,)), Aggregation(group_by=(), aggs=agg.aggs[:2] + (agg.aggs[2],))), output_offsets=(0, 1)),
        Chunk.from_rows(FTS, all_rows),
    )
    want_sum, want_cnt = ref[0][0], ref[0][1]
    got_sum = MyDecimal.from_scaled_int(int(states[0][0][0]), 2)
    got_cnt = int(states[1][0][0])
    assert got_cnt == want_cnt.val
    assert got_sum == want_sum.val
    # avg state: [count, sum]; count counts non-NULL args among selected rows
    want_nn = sum(
        1
        for r in all_rows
        if not r[0].is_null() and r[0].val > 1 and not r[1].is_null()
    )
    assert int(states[2][0][0]) == want_nn
    # sum state null iff no rows
    assert not bool(states[0][1][0])


def test_hash_partition_stable_and_covering():
    chunks, _ = region_chunks(1, 64)
    from tidb_tpu.chunk import to_device_batch

    db = to_device_batch(chunks[0], capacity=80)
    kv = normalize_device_column(db.cols[0])
    part = hash_partition_ids([kv], 8)
    p = np.asarray(part)
    assert ((p >= 0) & (p < 8)).all()
    # equal keys -> equal partitions
    vals = np.asarray(db.cols[0].data)
    nulls = np.asarray(db.cols[0].null)
    seen = {}
    for i in range(64):
        k = None if nulls[i] else int(vals[i])
        if k in seen:
            assert seen[k] == p[i]
        seen[k] = p[i]


def test_scatter_to_buckets_roundtrip():
    n, P, cap = 50, 4, 32
    rng = np.random.default_rng(0)
    vals = jnp.asarray(rng.integers(0, 100, n))
    valid = jnp.asarray(rng.random(n) < 0.9)
    part = jnp.asarray(rng.integers(0, P, n).astype(np.int32))
    (bv,), bvalid, overflow = scatter_to_buckets([vals], valid, part, P, cap)
    assert not bool(overflow)
    got = []
    bv, bvalid = np.asarray(bv), np.asarray(bvalid)
    for p in range(P):
        for s in range(cap):
            if bvalid[p, s]:
                got.append((p, int(bv[p, s])))
    want = sorted((int(part[i]), int(vals[i])) for i in range(n) if bool(valid[i]))
    assert sorted(got) == want


def test_exchange_group_agg_all_to_all():
    """Each device owns one hash partition after all_to_all; per-key counts
    across the mesh match a host group-by."""
    from tidb_tpu.parallel.compat import shard_map
    from jax.sharding import PartitionSpec as P_

    mesh = region_mesh()
    n_dev = 8
    rows_per = 48
    rng = np.random.default_rng(7)
    keys = rng.integers(0, 13, (n_dev, rows_per))
    valid = rng.random((n_dev, rows_per)) < 0.9

    kft = new_longlong()

    def device_fn(k, v):
        k, v = k[0], v[0]  # local leading axis of size 1
        kv = CompVal(k, jnp.zeros(k.shape, bool), kft)

        def agg_fn(cols, fvalid):
            (kc,) = cols
            # count per key 0..12 on owned rows
            onehot = (kc[:, None] == jnp.arange(13)[None, :]) & fvalid[:, None]
            return onehot.sum(axis=0)

        (counts, overflow) = exchange_group_aggregate("region", [kv], agg_fn, [k], v, n_parts=n_dev, bucket_cap=64)
        total = jax.lax.psum(counts, "region")
        return total[None], overflow[None]

    fn = shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(P_("region"), P_("region")),
        out_specs=(P_("region"), P_("region")),
    )
    counts, overflow = jax.jit(fn)(jnp.asarray(keys), jnp.asarray(valid))
    assert not np.asarray(overflow).any()
    got = np.asarray(counts)[0]  # psum makes identical on all devices
    want = np.zeros(13, int)
    for d in range(n_dev):
        for i in range(rows_per):
            if valid[d, i]:
                want[keys[d, i]] += 1
    assert got.tolist() == want.tolist()


def test_sharded_min_max_first_merge():
    """min/max/first_row partials must merge with their own ops, not sum."""
    chunks, all_rows = region_chunks(seed=7)
    mesh = region_mesh()
    scan = TableScan(1, (ColumnInfo(1, FTS[0]), ColumnInfo(2, FTS[1])))
    agg = Aggregation(
        group_by=(),
        aggs=(
            AggDesc("min", (col(0, FTS[0]),)),
            AggDesc("max", (col(1, FTS[1]),)),
            AggDesc("first_row", (col(0, FTS[0]),)),
        ),
        partial=True,
    )
    dag = DAGRequest((scan, agg), output_offsets=(0, 1, 2))
    stacked = stack_region_batches(chunks, n_total=8)
    states = run_sharded_partial_agg(dag, stacked, mesh)
    ints = [r[0].val for r in all_rows if not r[0].is_null()]
    decs = [r[1].val for r in all_rows if not r[1].is_null()]
    assert int(states[0][0][0]) == min(ints)
    assert MyDecimal.from_scaled_int(int(states[1][0][0]), 2) == max(decs)
    # first_row states are [has, value]; value of first region with rows,
    # NULL kept verbatim (row 0's datum here)
    assert int(states[2][0][0]) == 1  # has
    first = all_rows[0][0]
    if first.is_null():
        assert bool(states[3][1][0])
    else:
        assert int(states[3][0][0]) == first.val


def test_hash_partition_float_keys():
    """DOUBLE partition keys must hash (f32 bitcast), not crash (#review)."""
    from tidb_tpu.types import new_double

    v = jnp.asarray(np.array([1.5, -2.25, 0.0, -0.0, 1.5]))
    kv = CompVal(v, jnp.zeros(5, bool), new_double())
    pid = np.asarray(hash_partition_ids([kv], 8))
    assert ((0 <= pid) & (pid < 8)).all()
    assert pid[0] == pid[4]  # equal doubles -> same partition
    assert pid[2] == pid[3]  # -0.0 == 0.0


def test_sharded_unsigned_min_max_merge():
    """Unsigned BIGINT min/max states are raw two's-complement int64; the
    mesh merge must compare in the flipped domain (#review: cross-region
    MIN(unsigned) with values >= 2^63)."""
    from tidb_tpu.types import Flag, new_longlong

    UFT = new_longlong(unsigned=True)
    big, small = (1 << 63) + 5, 10
    chunks = [
        Chunk.from_rows([UFT], [[Datum.u64(big)]]),
        Chunk.from_rows([UFT], [[Datum.u64(small)]]),
    ]
    mesh = region_mesh()
    scan = TableScan(1, (ColumnInfo(1, UFT),))
    agg = Aggregation(
        group_by=(),
        aggs=(AggDesc("min", (col(0, UFT),)), AggDesc("max", (col(0, UFT),))),
        partial=True,
    )
    dag = DAGRequest((scan, agg), output_offsets=(0, 1))
    stacked = stack_region_batches(chunks, n_total=8)
    states = run_sharded_partial_agg(dag, stacked, mesh)
    assert int(states[0][0][0]) & 0xFFFFFFFFFFFFFFFF == small
    assert int(states[1][0][0]) & 0xFFFFFFFFFFFFFFFF == big


def test_sharded_first_row_skips_filtered_region():
    """A region whose rows all fail the filter must not contribute its
    first_row state (#review: garbage value from clipped gather)."""
    FT = new_longlong()
    # region 0 rows fail the predicate col0 > 100; region 1 passes
    chunks = [
        Chunk.from_rows([FT], [[Datum.i64(1)], [Datum.i64(2)]]),
        Chunk.from_rows([FT], [[Datum.i64(500)], [Datum.i64(600)]]),
    ]
    mesh = region_mesh()
    scan = TableScan(1, (ColumnInfo(1, FT),))
    pred = func("gt", BOOL, col(0, FT), lit(100, new_longlong()))
    agg = Aggregation(group_by=(), aggs=(AggDesc("first_row", (col(0, FT),)),), partial=True)
    dag = DAGRequest((scan, Selection((pred,)), agg), output_offsets=(0,))
    stacked = stack_region_batches(chunks, n_total=8)
    states = run_sharded_partial_agg(dag, stacked, mesh)
    assert int(states[0][0][0]) == 1  # has: some region saw rows
    assert int(states[1][0][0]) == 500
    assert not bool(states[1][1][0])


def test_sharded_first_row_keeps_null_value():
    """A legitimately-NULL first value must survive the merge (#review:
    has/is-null conflation) — matches the reference executor's literal
    first row."""
    FT = new_longlong()
    chunks = [
        Chunk.from_rows([FT], [[Datum.NULL], [Datum.i64(2)]]),
        Chunk.from_rows([FT], [[Datum.i64(500)]]),
    ]
    mesh = region_mesh()
    scan = TableScan(1, (ColumnInfo(1, FT),))
    agg = Aggregation(group_by=(), aggs=(AggDesc("first_row", (col(0, FT),)),), partial=True)
    dag = DAGRequest((scan, agg), output_offsets=(0,))
    stacked = stack_region_batches(chunks, n_total=8)
    states = run_sharded_partial_agg(dag, stacked, mesh)
    assert int(states[0][0][0]) == 1
    assert bool(states[1][1][0])  # value is NULL, not 500


# ---------------------------------------------------------------------------
# grouped aggregation over the mesh (VERDICT next #3)
# ---------------------------------------------------------------------------

def _grouped_setup(n_regions=8, seed=0, null_p=0.05):
    import numpy as np

    from tidb_tpu.types import MyDecimal, new_decimal, new_varchar

    fts = [new_longlong(), new_varchar(4), new_decimal(10, 2)]
    chunks, all_rows = [], []
    for i in range(n_regions):
        rng = np.random.default_rng(seed + i)
        rows = []
        for _ in range(30 + 3 * i):
            rows.append([
                Datum.i64(int(rng.integers(0, 7))) if rng.random() > null_p else Datum.NULL,
                Datum.string("AB"[int(rng.integers(2))] + "XY"[int(rng.integers(2))]),
                Datum.dec(MyDecimal(f"{int(rng.integers(-999, 999))/100:.2f}")),
            ])
        chunks.append(Chunk.from_rows(fts, rows))
        all_rows += rows
    return fts, chunks, all_rows


def test_mesh_grouped_agg_matches_oracle():
    """Partial1 -> all_to_all state exchange -> Final merge, bit-for-bit vs
    the single-chip oracle: multi-key (int + string) GROUP BY, 5 agg funcs."""
    from tidb_tpu.exec import run_dag_reference
    from tidb_tpu.exec.executor import datum_group_key
    from tidb_tpu.parallel import run_sharded_grouped_agg
    from tidb_tpu.types import new_decimal

    fts, chunks, all_rows = _grouped_setup()
    C = lambda i: col(i, fts[i])
    scan = TableScan(1, tuple(ColumnInfo(i + 1, ft) for i, ft in enumerate(fts)))
    sel = Selection((func("ge", BOOL, C(2), lit("-5.00", new_decimal(3, 2))),))
    agg = Aggregation(
        group_by=(C(0), C(1)),
        aggs=(
            AggDesc("count", ()),
            AggDesc("sum", (C(2),)),
            AggDesc("avg", (C(2),)),
            AggDesc("min", (C(2),)),
            AggDesc("first_row", (C(0),)),
        ),
    )
    dag = DAGRequest((scan, sel, agg), output_offsets=tuple(range(7)))
    mesh = region_mesh(8)
    stacked = stack_region_batches(chunks, n_total=8)
    chunk, overflow = run_sharded_grouped_agg(dag, stacked, mesh, group_capacity=64)
    assert not overflow
    ref = run_dag_reference(dag, Chunk.concat(chunks))
    got = sorted(tuple(datum_group_key(d) for d in r) for r in chunk.rows())
    want = sorted(tuple(datum_group_key(d) for d in r) for r in ref)
    assert got == want


def test_mesh_grouped_agg_overflow_flag():
    """More groups than capacity must raise the overflow flag, not truncate
    silently."""
    from tidb_tpu.parallel import run_sharded_grouped_agg
    from tidb_tpu.types import new_decimal

    fts, chunks, _ = _grouped_setup()
    C = lambda i: col(i, fts[i])
    scan = TableScan(1, tuple(ColumnInfo(i + 1, ft) for i, ft in enumerate(fts)))
    agg = Aggregation(group_by=(C(2),), aggs=(AggDesc("count", ()),))  # ~unique decimals
    dag = DAGRequest((scan, agg), output_offsets=(0, 1))
    mesh = region_mesh(8)
    stacked = stack_region_batches(chunks, n_total=8)
    _, overflow = run_sharded_grouped_agg(dag, stacked, mesh, group_capacity=8)
    assert overflow


class TestMeshSQL:
    """SQL GROUP BY statements execute through the mesh exchange path
    (ref: fragment.go GenerateRootMPPTasks; VERDICT r2 'mesh execution is
    unreachable from SQL')."""

    def _session_with_regions(self):
        from tidb_tpu.sql import Session

        s = Session()
        s.execute("create table m (g varchar(4), k bigint, v decimal(10,2))")
        rows = []
        for i in range(400):
            rows.append(f"('{'abcd'[i % 4]}', {i % 11}, {i}.25)")
        s.execute("insert into m values " + ",".join(rows))
        # split the table into several regions so the mesh has shards
        from tidb_tpu.codec import tablecodec

        meta = s.catalog.table("m")
        for h in (100, 200, 300):
            s.store.cluster.split(tablecodec.encode_row_key(meta.table_id, h))
        return s

    def test_group_by_runs_on_mesh(self):
        from tidb_tpu.util import metrics

        s = self._session_with_regions()
        before = metrics.MESH_SELECTS.value
        r = s.execute("select g, count(*), sum(v), min(k) from m group by g")
        assert metrics.MESH_SELECTS.value == before + 1, "plan did not take the mesh path"
        got = sorted((str(x[0].val), int(x[1].val), str(x[2].val), int(x[3].val)) for x in r.rows)
        import collections

        want = collections.defaultdict(lambda: [0, 0, None])
        for i in range(400):
            w = want["abcd"[i % 4]]
            w[0] += 1
            w[1] += i * 100 + 25  # cents
            w[2] = i % 11 if w[2] is None else min(w[2], i % 11)
        expect = sorted((g, c, f"{v/100:.2f}", mn) for g, (c, v, mn) in want.items())
        assert got == expect

    def test_mesh_matches_threadpool_path(self):
        from tidb_tpu.util import metrics

        s = self._session_with_regions()
        q = "select k, count(*), avg(v), max(v) from m where k > 2 group by k"
        r_mesh = s.execute(q)
        assert metrics.MESH_SELECTS.value > 0
        s.execute("set tidb_enable_tpu_mesh = OFF")
        before = metrics.MESH_SELECTS.value
        r_tp = s.execute(q)
        assert metrics.MESH_SELECTS.value == before
        key = lambda rows: sorted(tuple(str(d.val) if not d.is_null() else None for d in row) for row in rows)
        assert key(r_mesh.rows) == key(r_tp.rows)

    def test_string_first_row_over_exchange(self):
        """String aggregate values ride the exchange as packed words
        (the r2 NotImplementedError hole)."""
        s = self._session_with_regions()
        r = s.execute("select g, min(g), max(g) from m group by g")
        got = sorted((str(x[0].val), str(x[1].val), str(x[2].val)) for x in r.rows)
        assert got == [("a", "a", "a"), ("b", "b", "b"), ("c", "c", "c"), ("d", "d", "d")]


class TestMeshShuffleJoin:
    """Hash-shuffle (repartition) join over the mesh (VERDICT r3 missing #1:
    'joins never shuffle over the mesh'). Both sides all_to_all by join-key
    hash, local join per device, grouped agg above — ref:
    unistore/cophandler/mpp_exec.go:609-721 Hash mode + joinExec:844."""

    def _sessions(self, n_rows=400, n_orders=37):
        from tidb_tpu.codec import tablecodec
        from tidb_tpu.sql import Session

        s = Session()
        s.execute("create table ords (o_id bigint primary key, flag varchar(2), odate bigint)")
        rows = [f"({i}, '{'xy'[i % 2]}{chr(97 + i % 3)}', {1000 + i % 7})" for i in range(n_orders)]
        s.execute("insert into ords values " + ",".join(rows))
        s.execute("create table items (i_id bigint primary key, oid bigint, v decimal(10,2))")
        rows = [f"({i}, {(i * 7) % (n_orders + 5)}, {i}.50)" for i in range(n_rows)]
        s.execute("insert into items values " + ",".join(rows))
        meta = s.catalog.table("items")
        for h in (100, 200, 300):
            s.store.cluster.split(tablecodec.encode_row_key(meta.table_id, h))
        return s

    def _both_paths(self, s, sql):
        from tidb_tpu.util import metrics

        s.execute("set tidb_enable_tpu_mesh = ON")
        before = metrics.MESH_SELECTS.value
        mesh_rows = s.execute(sql).rows
        took_mesh = metrics.MESH_SELECTS.value == before + 1
        s.execute("set tidb_enable_tpu_mesh = OFF")
        tp_rows = s.execute(sql).rows
        canon = lambda rows: sorted(
            tuple(None if d.is_null() else str(d.val) for d in r) for r in rows
        )
        return took_mesh, canon(mesh_rows), canon(tp_rows)

    def test_inner_join_group_by_over_mesh(self):
        s = self._sessions()
        took, mesh, tp = self._both_paths(
            s, "select flag, count(*), sum(v), min(i_id) from items join ords on oid = o_id group by flag"
        )
        assert took, "plan did not take the mesh join path"
        assert mesh == tp

    def test_join_with_filters_both_sides(self):
        s = self._sessions()
        took, mesh, tp = self._both_paths(
            s,
            "select odate, count(*), sum(v) from items join ords on oid = o_id "
            "where v > 20 and odate < 1005 group by odate",
        )
        assert took
        assert mesh == tp

    def test_join_group_by_build_side_string_key(self):
        s = self._sessions()
        took, mesh, tp = self._both_paths(
            s, "select flag, count(*) from items join ords on oid = o_id group by flag, odate"
        )
        assert took
        assert mesh == tp

    def test_skewed_keys_match(self):
        """Every probe row hits ONE order (all rows land on one device's
        partition) — the skew case the bucket capacity must survive."""
        from tidb_tpu.codec import tablecodec
        from tidb_tpu.sql import Session

        s = Session()
        s.execute("create table ords (o_id bigint primary key, flag varchar(2))")
        s.execute("insert into ords values (1, 'x'), (2, 'y')")
        s.execute("create table items (i_id bigint primary key, oid bigint)")
        s.execute("insert into items values " + ",".join(f"({i}, 1)" for i in range(300)))
        meta = s.catalog.table("items")
        for h in (100, 200):
            s.store.cluster.split(tablecodec.encode_row_key(meta.table_id, h))
        took, mesh, tp = self._both_paths(
            s, "select flag, count(*) from items join ords on oid = o_id group by flag"
        )
        assert took
        assert mesh == tp == [("x", "300")]

    def test_multidevice_mesh_eligibility_kinds(self):
        from tidb_tpu.parallel.sql import mesh_eligible
        from tidb_tpu.parser import parse_one
        from tidb_tpu.sql.planner import plan_select

        s = self._sessions()
        k = mesh_eligible(plan_select(parse_one(
            "select flag, count(*) from items join ords on oid = o_id group by flag"), s.catalog).dag)
        assert k == "join"
        k = mesh_eligible(plan_select(parse_one(
            "select oid, count(*) from items group by oid"), s.catalog).dag)
        assert k == "agg"
        # DISTINCT now rides the raw-row exchange (r5): still mesh-eligible
        k = mesh_eligible(plan_select(parse_one(
            "select flag, count(distinct v) from items join ords on oid = o_id group by flag"), s.catalog).dag)
        assert k == "join"
        # group_concat stays off-mesh (root-only, oracle-evaluated)
        k = mesh_eligible(plan_select(parse_one(
            "select oid, group_concat(v) from items group by oid"), s.catalog).dag)
        assert k is None


def test_mesh_distinct_aggs_match_oracle():
    """DISTINCT aggregates over the mesh: raw rows shuffle by group key
    (every group lands whole on one device), Complete-mode owner agg —
    bit-for-bit vs the single-chip oracle (VERDICT r4 next #5)."""
    from tidb_tpu.exec import run_dag_reference
    from tidb_tpu.exec.executor import datum_group_key
    from tidb_tpu.parallel import run_sharded_grouped_agg
    from tidb_tpu.types import new_decimal

    fts, chunks, all_rows = _grouped_setup()
    C = lambda i: col(i, fts[i])
    scan = TableScan(1, tuple(ColumnInfo(i + 1, ft) for i, ft in enumerate(fts)))
    agg = Aggregation(
        group_by=(C(0),),
        aggs=(
            AggDesc("count", (C(2),), distinct=True),
            AggDesc("sum", (C(2),), distinct=True),
            AggDesc("count", ()),
            AggDesc("avg", (C(2),)),
        ),
    )
    dag = DAGRequest((scan, agg), output_offsets=tuple(range(5)))
    mesh = region_mesh(8)
    stacked = stack_region_batches(chunks, n_total=8)
    chunk, overflow = run_sharded_grouped_agg(dag, stacked, mesh, group_capacity=128, bucket_cap=512)
    assert not overflow
    ref = run_dag_reference(dag, Chunk.concat(chunks))
    got = sorted(tuple(datum_group_key(d) for d in r) for r in chunk.rows())
    want = sorted(tuple(datum_group_key(d) for d in r) for r in ref)
    assert got == want


def test_mesh_distinct_string_group_key():
    """COUNT(DISTINCT) under a STRING group key: the raw-row exchange must
    carry packed string words byte-exactly."""
    from tidb_tpu.exec import run_dag_reference
    from tidb_tpu.exec.executor import datum_group_key
    from tidb_tpu.parallel import run_sharded_grouped_agg

    fts, chunks, all_rows = _grouped_setup()
    C = lambda i: col(i, fts[i])
    scan = TableScan(1, tuple(ColumnInfo(i + 1, ft) for i, ft in enumerate(fts)))
    agg = Aggregation(
        group_by=(C(1),),
        aggs=(AggDesc("count", (C(0),), distinct=True),),
    )
    dag = DAGRequest((scan, agg), output_offsets=(0, 1))
    mesh = region_mesh(8)
    stacked = stack_region_batches(chunks, n_total=8)
    chunk, overflow = run_sharded_grouped_agg(dag, stacked, mesh, group_capacity=64, bucket_cap=512)
    assert not overflow
    ref = run_dag_reference(dag, Chunk.concat(chunks))
    got = sorted(tuple(datum_group_key(d) for d in r) for r in chunk.rows())
    want = sorted(tuple(datum_group_key(d) for d in r) for r in ref)
    assert got == want


class TestMeshJoinChain:
    """Multi-join shuffle chains on the mesh (VERDICT r4 next #5: the Q3
    3-table shape must ride end-to-end): each stage re-exchanges the
    widened schema by its join key."""

    def _sessions(self, nl=600, no=40, nc=12):
        from tidb_tpu.sql import Session

        s = Session()
        s.execute("create table cust (c_id bigint primary key, seg varchar(2))")
        s.execute("insert into cust values " + ",".join(
            f"({i}, '{'AB'[i % 2]}')" for i in range(nc)))
        s.execute("create table ords (o_id bigint primary key, ckey bigint, odate bigint)")
        s.execute("insert into ords values " + ",".join(
            f"({i}, {i % nc}, {1000 + i % 9})" for i in range(no)))
        s.execute("create table items (i_id bigint primary key, oid bigint, v decimal(10,2))")
        s.execute("insert into items values " + ",".join(
            f"({i}, {(i * 3) % (no + 4)}, {i}.25)" for i in range(nl)))
        return s

    def test_three_table_chain_on_mesh(self):
        from tidb_tpu.util import metrics

        s = self._sessions()
        sql = ("select oid, count(*), sum(v) from items "
               "join ords on oid = o_id join cust on ckey = c_id "
               "where seg = 'B' and odate < 1007 group by oid")
        s.execute("set tidb_enable_tpu_mesh = ON")
        before = metrics.MESH_SELECTS.value
        mesh_rows = s.execute(sql).rows
        took_mesh = metrics.MESH_SELECTS.value == before + 1
        s.execute("set tidb_enable_tpu_mesh = OFF")
        tp_rows = s.execute(sql).rows
        canon = lambda rows: sorted(
            tuple(None if d.is_null() else str(d.val) for d in r) for r in rows
        )
        assert canon(mesh_rows) == canon(tp_rows)
        assert took_mesh, "3-table chain did not ride the mesh"

    def test_chain_distinct_on_mesh(self):
        from tidb_tpu.util import metrics

        s = self._sessions()
        sql = ("select ckey, count(distinct oid) from items "
               "join ords on oid = o_id group by ckey")
        s.execute("set tidb_enable_tpu_mesh = ON")
        before = metrics.MESH_SELECTS.value
        mesh_rows = s.execute(sql).rows
        took_mesh = metrics.MESH_SELECTS.value == before + 1
        s.execute("set tidb_enable_tpu_mesh = OFF")
        tp_rows = s.execute(sql).rows
        canon = lambda rows: sorted(
            tuple(None if d.is_null() else str(d.val) for d in r) for r in rows
        )
        assert canon(mesh_rows) == canon(tp_rows)
        assert took_mesh, "distinct join+group did not ride the mesh"
