"""Changefeed subsystem (ISSUE 10): puller over the replication log,
commit-ts sorter, resolved-ts frontier (pd.cdc tick phase), rowcodec
mounter, sinks, lifecycle surfaces, the cdc/* failpoints, and the
mirror-equality chaos acceptance (ref: TiCDC's puller/sorter/mounter/
sink pipeline and the TiDB VLDB'20 log-based replication design)."""

import os
import sys
import threading

import pytest

from tidb_tpu.cdc import (
    ChangefeedError,
    FileSink,
    MemorySink,
    SessionReplaySink,
)
from tidb_tpu.codec import tablecodec
from tidb_tpu.sql.session import Session, SQLError
from tidb_tpu.util import failpoint, metrics

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))


def make_session():
    s = Session()
    s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT, name VARCHAR(16))")
    return s


def feed_on(s, name="f", sink=None, tables=("t",), start_ts=0):
    sink = sink or MemorySink()
    ids = None
    if tables is not None:
        ids = set()
        for t in tables:
            meta = s.catalog.table(t)
            ids.add(meta.table_id)
            ids.update(meta.physical_ids())
    return s.store.cdc.create(name, sink, s.catalog, table_ids=ids, start_ts=start_ts)


def plain(ev):
    return (ev.table, ev.handle, ev.op, ev.commit_ts,
            tuple((n, None if d.is_null() else d.val) for n, d in ev.columns))


# ------------------------------------------------------------ the pipeline

class TestPipeline:
    def test_insert_update_delete_stream_in_commit_order(self):
        s = make_session()
        feed = feed_on(s)
        s.execute("INSERT INTO t VALUES (1, 10, 'a'), (2, 20, 'b')")
        s.execute("UPDATE t SET v = 11 WHERE id = 1")
        s.execute("DELETE FROM t WHERE id = 2")
        emitted = s.store.cdc.tick()
        rows = feed.sink.rows()
        assert emitted == len(rows) == 4
        # commit-ts order, ops decoded, deletes carry no columns
        assert [r.commit_ts for r in rows] == sorted(r.commit_ts for r in rows)
        assert [(r.handle, r.op) for r in rows] == [(1, "put"), (2, "put"), (1, "put"), (2, "delete")]
        assert dict(rows[2].columns)["v"].val == 11
        assert rows[3].columns == ()

    def test_emission_gated_on_resolved_frontier(self):
        """Every emitted row's commit_ts is at or below the resolved ts
        flushed right after it — the transactionally-complete-prefix
        contract."""
        s = make_session()
        feed = feed_on(s)
        for i in range(6):
            s.execute(f"INSERT INTO t VALUES ({i}, {i}, 'x')")
            s.store.cdc.tick()
        marks = feed.sink.resolved_view()
        assert marks == sorted(marks)
        assert all(ev.commit_ts <= marks[-1] for ev in feed.sink.rows())

    def test_initial_incremental_scan_replays_history(self):
        """A feed created AFTER writes still streams them: the birth
        incremental scan covers (start_ts, now]."""
        s = make_session()
        s.execute("INSERT INTO t VALUES (1, 10, 'a'), (2, 20, 'b')")
        s.execute("UPDATE t SET v = 99 WHERE id = 2")
        feed = feed_on(s)
        s.store.cdc.tick()
        got = [(r.handle, r.op) for r in feed.sink.rows()]
        assert got == [(1, "put"), (2, "put"), (2, "put")]  # full MVCC history
        assert metrics.CDC_RECOVERY_SCANS.value > 0

    def test_start_ts_excludes_older_commits(self):
        s = make_session()
        s.execute("INSERT INTO t VALUES (1, 10, 'a')")
        cut = s.store.kv.max_committed()
        s.execute("INSERT INTO t VALUES (2, 20, 'b')")
        feed = feed_on(s, start_ts=cut)
        s.store.cdc.tick()
        assert [r.handle for r in feed.sink.rows()] == [2]

    def test_table_filter_and_index_entries_skipped(self):
        s = make_session()
        s.execute("CREATE TABLE other (id BIGINT PRIMARY KEY, x BIGINT)")
        s.execute("CREATE INDEX iv ON t (v)")
        feed = feed_on(s, tables=("t",))
        sk0 = metrics.CDC_EVENTS_SKIPPED.value
        s.execute("INSERT INTO t VALUES (1, 10, 'a')")  # row + index entry
        s.execute("INSERT INTO other VALUES (5, 50)")  # filtered out
        s.store.cdc.tick()
        assert [(r.table, r.handle) for r in feed.sink.rows()] == [("t", 1)]
        # the index entry was captured (same table) but skipped at mount
        assert metrics.CDC_EVENTS_SKIPPED.value > sk0

    def test_split_and_merge_hand_off_watermarks(self):
        s = make_session()
        s.execute("INSERT INTO t VALUES " + ",".join(f"({i},{i},'x')" for i in range(40)))
        feed = feed_on(s)
        s.store.cdc.tick()
        tid = s.catalog.table("t").table_id
        parent = s.store.cluster.locate(tablecodec.encode_row_key(tid, 0))
        child = s.store.cluster.split(tablecodec.encode_row_key(tid, 20))
        with feed._mu:
            assert feed._watermark[child.region_id] == feed._watermark[parent.region_id]
        before = feed.view(s.store)["checkpoint_ts"]
        s.execute("UPDATE t SET v = 100 WHERE id = 30")  # lands in the child
        s.store.cdc.tick()
        assert feed.view(s.store)["checkpoint_ts"] > before
        merged = s.store.cluster.merge(parent.region_id)
        assert merged is not None
        s.execute("UPDATE t SET v = 101 WHERE id = 5")
        s.store.cdc.tick()
        rows = [r for r in feed.sink.rows() if r.op == "put" and dict(r.columns)["v"].val == 101]
        assert rows, "event across a merge was lost"

    def test_changefeed_pins_gc_safepoint_at_checkpoint(self):
        """The checkpoint is a GC service safepoint (TiCDC's PD service
        safepoint): versions the feed still has to scan survive GC."""
        s = make_session()
        s.execute("INSERT INTO t VALUES (1, 10, 'a')")
        s.execute("UPDATE t SET v = 11 WHERE id = 1")
        s.execute("UPDATE t SET v = 12 WHERE id = 1")
        feed = feed_on(s)  # checkpoint 0: everything pinned
        s.store.run_gc()
        key = tablecodec.encode_row_key(s.catalog.table("t").table_id, 1)
        with s.store.kv.lock:
            versions = list(s.store.kv._data.get(key, ()))
        assert len(versions) == 3, "GC collected history a feed still needs"
        s.store.cdc.tick()
        assert [dict(r.columns)["v"].val for r in feed.sink.rows()] == [10, 11, 12]
        s.store.run_gc()  # checkpoint advanced past the history: GC may fold
        with s.store.kv.lock:
            assert len(s.store.kv._data.get(key, ())) == 1


# ------------------------------------------------------- mounter parity

class TestMounterParity:
    def test_every_column_type_round_trips(self):
        """ISSUE 10 satellite: put_row -> replication log -> mounter
        equals a direct table scan for every supported column type."""
        s = Session()
        s.execute(
            "CREATE TABLE alltypes ("
            " id BIGINT PRIMARY KEY, ti TINYINT, u BIGINT UNSIGNED,"
            " f FLOAT, d DOUBLE, dec DECIMAL(12,3), dt DATETIME, da DATE,"
            " j JSON, e ENUM('red','green','blue'),"
            " sc VARCHAR(32) COLLATE utf8mb4_general_ci, sb VARBINARY(32))"
        )
        s.execute(
            "INSERT INTO alltypes VALUES"
            " (1, -7, 18446744073709551610, 1.5, 2.25, 12345.678,"
            "  '2024-03-01 12:30:45', '2023-12-31', '{\"k\": [1, 2, {\"n\": true}]}',"
            "  'green', 'MixedCase', 'raw'),"
            " (2, NULL, NULL, NULL, NULL, NULL, NULL, NULL, NULL, NULL, NULL, NULL)"
        )
        feed = feed_on(s, tables=("alltypes",))
        s.store.cdc.tick()
        rows = {r.handle: dict(r.columns) for r in feed.sink.rows()}
        assert set(rows) == {1, 2}
        res = s.execute("SELECT * FROM alltypes ORDER BY id")
        names = [c.lower() for c in res.columns]
        for handle, sel in zip((1, 2), res.rows):
            mounted = rows[handle]
            for name, d in zip(names, sel):
                m = mounted[name]
                assert m.is_null() == d.is_null(), (name, m, d)
                if not d.is_null():
                    assert str(m.val) == str(d.val), (name, m, d)


# ----------------------------------------------------------- lifecycle

class TestLifecycle:
    def test_pause_resume_catches_up_from_checkpoint(self):
        s = make_session()
        feed = feed_on(s)
        s.execute("INSERT INTO t VALUES (1, 10, 'a')")
        s.store.cdc.tick()
        s.store.cdc.pause("f")
        s.execute("INSERT INTO t VALUES (2, 20, 'b')")
        s.store.cdc.tick()
        assert [r.handle for r in feed.sink.rows()] == [1]  # paused: nothing
        s.store.cdc.resume("f")
        s.store.cdc.tick()
        assert [r.handle for r in feed.sink.rows()] == [1, 2]  # caught up

    def test_duplicate_and_unknown_names_are_typed_errors(self):
        s = make_session()
        feed_on(s)
        with pytest.raises(ChangefeedError):
            feed_on(s)
        with pytest.raises(ChangefeedError):
            s.store.cdc.drop("nope")

    def test_drop_unpins_gc_and_closes_sink(self):
        s = make_session()
        s.execute("INSERT INTO t VALUES (1, 10, 'a')")
        s.execute("UPDATE t SET v = 11 WHERE id = 1")
        feed = feed_on(s)
        s.store.cdc.drop("f")
        s.store.run_gc()
        key = tablecodec.encode_row_key(s.catalog.table("t").table_id, 1)
        with s.store.kv.lock:
            assert len(s.store.kv._data.get(key, ())) == 1  # pin released
        assert feed.state == "removed"

    def test_sink_failure_parks_feed_in_error_and_resume_retries(self):
        class FlakySink(MemorySink):
            def __init__(self):
                super().__init__()
                self.fail = True

            def write(self, events):
                if self.fail:
                    raise OSError("downstream unavailable")
                super().write(events)

        s = make_session()
        sink = FlakySink()
        feed = feed_on(s, sink=sink)
        s.execute("INSERT INTO t VALUES (1, 10, 'a')")
        s.store.cdc.tick()
        assert feed.view(s.store)["state"] == "error"
        assert "downstream unavailable" in feed.view(s.store)["error"]
        sink.fail = False
        s.store.cdc.resume("f")
        s.store.cdc.tick()
        assert feed.view(s.store)["state"] == "normal"
        assert [r.handle for r in sink.rows()] == [1]  # the batch was not lost


# ----------------------------------------------------- SQL + HTTP surfaces

class TestSurfaces:
    def test_sql_lifecycle_and_show(self, tmp_path):
        s = make_session()
        s.execute(f"CREATE CHANGEFEED cf INTO 'file://{tmp_path}/out' FOR TABLE t WITH start_ts = 0")
        s.execute("INSERT INTO t VALUES (1, 10, 'a')")
        s.store.pd.tick()  # the pd.cdc phase drives the feed
        row = s.execute("SHOW CHANGEFEEDS").values()[0]
        assert row[0] == "cf" and row[1] == "normal" and row[7] >= 1
        s.execute("PAUSE CHANGEFEED cf")
        assert s.execute("SHOW CHANGEFEEDS").values()[0][1] == "paused"
        s.execute("RESUME CHANGEFEED cf")
        # the file sink writes atomic segments per flush (ISSUE 20), each
        # ending in a resolved mark — never a single append-mode file
        recs = FileSink(f"{tmp_path}/out", "cf").read_records()
        assert "row" in {r.get("type") for r in recs}
        assert recs[-1]["type"] == "resolved"
        s.execute("DROP CHANGEFEED cf")
        assert s.execute("SHOW CHANGEFEEDS").values() == []
        with pytest.raises(SQLError):
            s.execute("DROP CHANGEFEED cf")
        with pytest.raises(SQLError):
            s.execute("CREATE CHANGEFEED bad INTO 'kafka://x'")

    def test_bad_start_ts_is_a_typed_error(self):
        s = make_session()
        with pytest.raises(SQLError):
            s.execute("CREATE CHANGEFEED b INTO 'memory://' WITH start_ts = 'abc'")
        with pytest.raises(SQLError):
            s.execute("CREATE CHANGEFEED b INTO 'memory://' WITH start_ts = 1.5")
        with pytest.raises(SQLError):
            s.execute("CREATE CHANGEFEED b INTO 'memory://' WITH start_ts")
        assert s.execute("SHOW CHANGEFEEDS").values() == []  # nothing created

    def test_show_changefeed_name_is_exact_not_like(self):
        s = make_session()
        feed_on(s, name="my_feed")
        feed_on(s, name="myxfeed")
        rows = s.execute("SHOW CHANGEFEED my_feed").values()
        assert [r[0] for r in rows] == ["my_feed"]  # `_` is not a wildcard

    def test_partial_sink_failure_redelivers_without_duplicates(self):
        """At-least-once across a sink failure: the replay sink applies a
        prefix, fails mid-batch, and after RESUME the redelivered prefix
        dedupes by (key, commit_ts) — the mirror ends exact, one version
        per commit."""
        src = make_session()
        mirror = Session()
        mirror.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT, name VARCHAR(16))")
        feed = feed_on(src, sink=SessionReplaySink(mirror), tables=None)
        src.execute("INSERT INTO t VALUES (1, 10, 'a')")
        src.execute("CREATE TABLE t2 (id BIGINT PRIMARY KEY)")  # not on mirror
        src.execute("INSERT INTO t2 VALUES (7)")
        src.store.cdc.tick()  # t row applies, then t2 fails the batch
        assert feed.view(src.store)["state"] == "error"
        mirror.execute("CREATE TABLE t2 (id BIGINT PRIMARY KEY)")
        src.store.cdc.resume("f")
        src.store.cdc.tick()
        assert feed.view(src.store)["state"] == "normal"
        assert mirror.execute("SELECT * FROM t ORDER BY id").values() == [[1, 10, "a"]]
        assert mirror.execute("SELECT * FROM t2").values() == [[7]]
        key = tablecodec.encode_row_key(src.catalog.table("t").table_id, 1)
        with mirror.store.kv.lock:
            versions = list(mirror.store.kv._data.get(key, ()))
        assert len(versions) == 1, versions  # redelivery deduped

    def test_resume_after_stall_redelivers_exactly_once_in_order(self, tmp_path):
        """RESUME after a stall + a kill-mid-flush (ISSUE 20 satellite):
        the re-queued batch redelivers EXACTLY once — per-key commit
        order intact (CheckingSink oracle on the mirror feed), exactly
        one durable copy of every event in the log-backup manifest, and
        the crashed segment's tmp leftover invisible to readers."""
        from chaos import CheckingSink
        from tidb_tpu.br import start_log_backup

        src = make_session()
        mirror = Session()
        mirror.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT, name VARCHAR(16))")
        chk = CheckingSink(SessionReplaySink(mirror))
        feed = feed_on(src, sink=chk, tables=("t",))
        lb = start_log_backup(src.store, src.catalog, str(tmp_path / "bk"))
        src.execute("INSERT INTO t VALUES (1, 10, 'a')")
        src.store.cdc.tick()
        failpoint.enable("cdc/sink-stall", True)
        src.execute("INSERT INTO t VALUES (2, 20, 'b')")
        src.execute("UPDATE t SET v = 21 WHERE id = 2")
        src.store.cdc.tick()  # emission skipped: the sorter holds the backlog
        assert chk.events == 1
        failpoint.disable("cdc/sink-stall")
        # the log feed's next flush dies between write and rename
        failpoint.enable("cdc/segment-crash", 1)
        src.store.cdc.tick()
        logfeed = src.store.cdc.get(lb.feed_name)
        assert logfeed.view(src.store)["state"] == "error"
        assert "segment-crash" in logfeed.view(src.store)["error"]
        assert feed.view(src.store)["state"] == "normal"  # mirror feed unhurt
        leftovers = [f for f in os.listdir(lb.sink.directory) if f.endswith(".tmp")]
        assert leftovers  # the kill left a torn tmp behind...
        src.store.cdc.resume(lb.feed_name)
        src.store.cdc.tick()  # ...and RESUME redelivers the dropped window
        assert logfeed.view(src.store)["state"] == "normal"
        # exactly-once: one durable copy of each version, per-key ts order
        seen: set = set()
        last_by_key: dict = {}
        kv = [r for r in lb.sink.writer.read_records() if r.get("t") == "kv"]
        for rec in kv:
            rk = (rec["k"], rec["ts"])
            assert rk not in seen, f"duplicate event {rk} in the manifest"
            seen.add(rk)
            assert rec["ts"] > last_by_key.get(rec["k"], 0)
            last_by_key[rec["k"]] = rec["ts"]
        assert len(kv) == 3  # insert 1, insert 2, update 2 — nothing lost
        assert chk.violations == [] and chk.events == 3
        assert (mirror.execute("SELECT * FROM t ORDER BY id").values()
                == src.execute("SELECT * FROM t ORDER BY id").values())

    def test_trace_has_pd_cdc_phase(self):
        s = make_session()
        feed_on(s)
        s.store.pd.tick()
        root = s.store.pd.last_tick_root
        assert any(c.name == "pd.cdc" for c in root.children)

    def test_http_api_routes(self):
        from tidb_tpu.server.http_api import StatusServer

        s = make_session()
        feed_on(s, name="web")
        srv = StatusServer(s).start_background()
        try:
            code, body = srv._route("/cdc/api/v1/changefeeds")
            assert code == 200 and body[0]["name"] == "web"
            code, body = srv._route("/cdc/api/v1/changefeeds/web")
            assert code == 200 and body["state"] == "normal"
            code, _ = srv._route("/cdc/api/v1/changefeeds/nope")
            assert code == 404
        finally:
            srv.close()

    def test_cdc_metric_families_pass_scrape_check(self):
        """ISSUE 10 satellite: the tier-1 exposition gate extended to the
        tidb_tpu_cdc_* families."""
        s = make_session()
        feed_on(s)
        s.execute("INSERT INTO t VALUES (1, 10, 'a')")
        s.store.cdc.tick()
        text = metrics.REGISTRY.dump()
        for family in (
            "tidb_tpu_cdc_events_total",
            "tidb_tpu_cdc_events_emitted_total",
            "tidb_tpu_cdc_events_skipped_total",
            "tidb_tpu_cdc_resolved_ts_lag",
            "tidb_tpu_cdc_sink_flush_seconds",
            "tidb_tpu_cdc_recovery_scans_total",
        ):
            assert f"# TYPE {family} " in text, family
        assert 'tidb_tpu_cdc_resolved_ts_lag{changefeed="f"}' in text
        from scrape_check import validate

        assert validate(text) == []


# ----------------------------------------------------------- failpoints

class TestFailpoints:
    def test_puller_drop_recovers_by_incremental_scan(self):
        s = make_session()
        feed = feed_on(s)
        s.execute("INSERT INTO t VALUES (1, 10, 'a')")
        with failpoint.enabled("cdc/puller-drop"):
            s.execute("INSERT INTO t VALUES (2, 20, 'b')")
            s.execute("DELETE FROM t WHERE id = 1")
        s.store.cdc.tick()
        got = [(r.handle, r.op) for r in feed.sink.rows()]
        assert got == [(1, "put"), (2, "put"), (1, "delete")]  # late, not lost

    def test_resolved_stuck_pins_then_resumes(self):
        s = make_session()
        feed = feed_on(s)
        s.execute("INSERT INTO t VALUES (1, 10, 'a')")
        s.store.cdc.tick()
        pinned = feed.view(s.store)["checkpoint_ts"]
        with failpoint.enabled("cdc/resolved-stuck"):
            s.execute("INSERT INTO t VALUES (2, 20, 'b')")
            for _ in range(3):
                s.store.cdc.tick()
            assert feed.view(s.store)["checkpoint_ts"] == pinned
            assert [r.handle for r in feed.sink.rows()] == [1]  # gated
        s.store.cdc.tick()
        assert feed.view(s.store)["checkpoint_ts"] > pinned
        assert [r.handle for r in feed.sink.rows()] == [1, 2]

    def test_sink_stall_holds_checkpoint_then_flushes_backlog(self):
        s = make_session()
        feed = feed_on(s)
        s.execute("INSERT INTO t VALUES (1, 10, 'a')")
        s.store.cdc.tick()
        held = feed.view(s.store)["checkpoint_ts"]
        with failpoint.enabled("cdc/sink-stall"):
            s.execute("INSERT INTO t VALUES (2, 20, 'b')")
            s.store.cdc.tick()
            assert feed.view(s.store)["checkpoint_ts"] == held
            assert len(feed.sink.rows()) == 1
        s.store.cdc.tick()
        assert len(feed.sink.rows()) == 2
        assert feed.view(s.store)["checkpoint_ts"] > held


# ----------------------------------------- lockwatch storm (ISSUE satellite)

def test_cdc_lockwatch_storm():
    """Changefeed ticks vs the PD tick vs a writer vs region splits under
    the runtime lockset detector: zero lock-order cycles, zero unguarded
    annotated accesses, and the sink's ordering oracle stays clean."""
    from chaos import CheckingSink

    from tidb_tpu.analysis import lockwatch

    with lockwatch.watching() as w:
        src = Session()
        src.execute("CREATE TABLE lw (id BIGINT PRIMARY KEY, v BIGINT)")
        src.execute("INSERT INTO lw VALUES " + ",".join(f"({i},{i})" for i in range(64)))
        src.store.cluster.set_stores(4)
        src.store.cluster.scatter()
        tid = src.catalog.table("lw").table_id
        sink = CheckingSink(MemorySink())
        src.store.cdc.create("lw", sink, src.catalog, start_ts=0)
        stop = threading.Event()
        errors: list = []

        def writer():
            w_sess = Session(store=src.store, catalog=src.catalog)
            k = 1000
            while not stop.is_set():
                try:
                    w_sess.execute(f"INSERT INTO lw VALUES ({k}, {k})")
                    w_sess.execute(f"UPDATE lw SET v = v + 1 WHERE id = {k - 1000}")
                    k += 1
                except SQLError:
                    pass
                except Exception as exc:  # noqa: BLE001
                    errors.append(exc)
                    return

        def ticker():
            while not stop.is_set():
                try:
                    src.store.pd.tick()  # includes the pd.cdc phase
                except Exception as exc:  # noqa: BLE001
                    errors.append(exc)
                    return

        def splitter():
            i = 0
            while not stop.is_set():
                try:
                    src.store.cluster.split(
                        tablecodec.encode_row_key(tid, (i * 7) % 64))
                    regions = src.store.cluster.regions()
                    if len(regions) > 6:
                        src.store.cluster.merge(regions[0].region_id)
                    i += 1
                except Exception as exc:  # noqa: BLE001
                    errors.append(exc)
                    return

        threads = [threading.Thread(target=f, daemon=True)
                   for f in (writer, ticker, splitter)]
        for t in threads:
            t.start()
        import time

        time.sleep(1.5)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        for _ in range(4):
            src.store.cdc.tick()  # drain after the storm
    rep = w.report()
    assert rep["cycles"] == [], rep["cycles"]
    assert rep["violations"] == [], "\n".join(rep["violations"])
    assert not errors, errors
    assert sink.violations == [], sink.violations
    assert sink.events > 0
    assert rep["edges"], "lockwatch saw no lock nesting at all"


# --------------------------------------- chaos acceptance (mirror equality)

def test_cdc_chaos_mirror_equality_acceptance():
    """ISSUE 10 acceptance: a seeded storm (split, merge, leader
    transfers, a store outage, replica/apply-lag, and all three cdc/*
    failpoints) runs with a live changefeed replaying into a second
    cluster. At the end the mirror's full scans equal the source, the
    resolved frontier advanced monotonically (and past the stuck
    window), and per-key event order matched commit order with no
    duplicates."""
    from chaos import run_cdc_storm

    report = run_cdc_storm(seed=11, statements=100)
    assert report["untyped_errors"] == [], report["untyped_errors"]
    assert report["ordering_violations"] == [], report["ordering_violations"]
    assert all(report["mirror_equal"].values()), report
    assert report["frontier_monotone"], report["frontier_samples"]
    assert report["frontier_advanced"], report["frontier_samples"]
    assert report["feed_state"] == "normal"
    assert report["events_emitted"] > 0
    assert report["recovery_scans"] > 0  # puller-drop really recovered
