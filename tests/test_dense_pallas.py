"""Parity tests for the one-pass Pallas small-G kernel (ops/dense_pallas.py)
against the sort kernel, run in Pallas interpret mode on CPU (the compiled
path is exercised on real TPU by bench.py's parity gate)."""

import numpy as np
import pytest
import jax.numpy as jnp

from tidb_tpu.expr import AggDesc, col
from tidb_tpu.ops.aggregate import group_aggregate
from tidb_tpu.types import Datum, MyDecimal, new_decimal, new_longlong, new_varchar
from tidb_tpu.chunk import Chunk

from test_ops import eval_vals, make_data


@pytest.fixture(autouse=True)
def _interpret(monkeypatch):
    monkeypatch.setenv("TIDB_TPU_PALLAS", "interpret")


def _assert_same(ref, pal):
    assert bool(pal.overflow) == bool(ref.overflow)
    ng = int(ref.n_groups)
    assert int(pal.n_groups) == ng
    assert jnp.array_equal(ref.group_rep[:ng], pal.group_rep[:ng])
    for rs, ps in zip(ref.states, pal.states):
        for (rv, rn), (pv, pn) in zip(rs, ps):
            assert jnp.array_equal(rv[:ng], pv[:ng]), (rv[:ng], pv[:ng])
            assert jnp.array_equal(rn[:ng], pn[:ng])


def _pallas_engaged(group_bys, aggs):
    from tidb_tpu.ops.dense_pallas import dense_pallas_eligible, pallas_mode

    return pallas_mode() == "interpret" and dense_pallas_eligible(
        group_bys, aggs, merge=False
    )


class TestDensePallas:
    def test_int_key_count_sum_avg(self):
        fts, ch = make_data(n=300, k_card=5)
        db, vals = eval_vals(fts, ch, [col(0, fts[0]), col(1, fts[1])])
        g, d = vals
        aggs = [
            (AggDesc("count", ()), []),
            (AggDesc("count", (col(1, fts[1]),)), [d]),
            (AggDesc("sum", (col(1, fts[1]),)), [d]),
            (AggDesc("avg", (col(1, fts[1]),)), [d]),
        ]
        assert _pallas_engaged([g], aggs)
        rng = np.random.default_rng(3)
        valid = db.row_valid & jnp.asarray(rng.random(300) < 0.8)
        ref = group_aggregate([g], aggs, valid, 64)
        pal = group_aggregate([g], aggs, valid, 64, small_groups=8)
        _assert_same(ref, pal)

    def test_string_key_with_nulls(self):
        fts, ch = make_data(n=257, k_card=4, null_p=0.25)
        db, vals = eval_vals(fts, ch, [col(3, fts[3]), col(1, fts[1])])
        s, d = vals
        aggs = [(AggDesc("count", ()), []), (AggDesc("sum", (col(1, fts[1]),)), [d])]
        assert _pallas_engaged([s], aggs)
        ref = group_aggregate([s], aggs, db.row_valid, 64)
        pal = group_aggregate([s], aggs, db.row_valid, 64, small_groups=8)
        _assert_same(ref, pal)

    def test_two_keys(self):
        fts, ch = make_data(n=300, k_card=3)
        db, vals = eval_vals(fts, ch, [col(0, fts[0]), col(3, fts[3]), col(1, fts[1])])
        g, s, d = vals
        aggs = [(AggDesc("sum", (col(1, fts[1]),)), [d]), (AggDesc("count", ()), [])]
        assert _pallas_engaged([g, s], aggs)
        ref = group_aggregate([g, s], aggs, db.row_valid, 64)
        pal = group_aggregate([g, s], aggs, db.row_valid, 64, small_groups=32)
        _assert_same(ref, pal)

    def test_overflow_when_hint_wrong(self):
        fts, ch = make_data(n=200, k_card=30, null_p=0.0)
        db, vals = eval_vals(fts, ch, [col(0, fts[0])])
        (g,) = vals
        aggs = [(AggDesc("count", ()), [])]
        assert _pallas_engaged([g], aggs)
        pal = group_aggregate([g], aggs, db.row_valid, 64, small_groups=8)
        assert bool(pal.overflow)

    def test_value_range_overflow(self):
        ft = new_longlong()
        big = 1 << 50
        rows = [[Datum.i64(1), Datum.i64(big)], [Datum.i64(1), Datum.i64(3)]]
        ch = Chunk.from_rows([ft, ft], rows)
        db, vals = eval_vals([ft, ft], ch, [col(0, ft), col(1, ft)])
        g, v = vals
        aggs = [(AggDesc("sum", (col(1, ft),)), [v])]
        assert _pallas_engaged([g], aggs)
        pal = group_aggregate([g], aggs, db.row_valid, 64, small_groups=8)
        assert bool(pal.overflow)

    def test_negative_values_exact(self):
        ft = new_longlong()
        rng = np.random.default_rng(0)
        rows = []
        for _ in range(1500):
            rows.append([
                Datum.i64(int(rng.integers(0, 6))),
                Datum.i64(int(rng.integers(-(2**45), 2**45))),
            ])
        ch = Chunk.from_rows([ft, ft], rows)
        db, vals = eval_vals([ft, ft], ch, [col(0, ft), col(1, ft)])
        g, v = vals
        aggs = [(AggDesc("sum", (col(1, ft),)), [v]), (AggDesc("avg", (col(1, ft),)), [v])]
        assert _pallas_engaged([g], aggs)
        ref = group_aggregate([g], aggs, db.row_valid, 64)
        pal = group_aggregate([g], aggs, db.row_valid, 64, small_groups=8)
        _assert_same(ref, pal)

    def test_row_count_bound_gates_eligibility(self):
        """ADVICE r5 medium, pinned at the boundary: the 12-bit limb
        accumulators wrap past int32 around 2^26 rows, so eligibility is
        a strict n < MAX_ROWS — the old docstring's 2^31 claim was wrong.
        The 2^26 case uses zero-copy broadcast views: the gate must reject
        on SHAPE alone, before any value work could materialize 512MB."""
        from tidb_tpu.expr.compile import CompVal
        from tidb_tpu.ops.dense_pallas import MAX_ROWS, dense_pallas_eligible

        assert MAX_ROWS == 1 << 26  # (N/128 rows) * 4095 < 2^31 -> N < ~2^26
        n = MAX_ROWS
        big_v = np.broadcast_to(np.int64(0), (n,))
        big_n = np.broadcast_to(False, (n,))
        g = CompVal(big_v, big_n, new_longlong())
        aggs = [(AggDesc("count", ()), [])]
        assert not dense_pallas_eligible([g], aggs, merge=False)

    def test_row_count_bound_is_strict(self, monkeypatch):
        """Boundary semantics (< not <=) without 512MB allocations: shrink
        the bound and check both sides of it."""
        import tidb_tpu.ops.dense_pallas as dp
        from tidb_tpu.expr.compile import CompVal

        fts, ch = make_data(n=64, k_card=4)
        db, vals = eval_vals(fts, ch, [col(0, fts[0]), col(1, fts[1])])
        g, d = vals
        aggs = [(AggDesc("sum", (col(1, fts[1]),)), [d])]
        n = g.null.shape[0]
        monkeypatch.setattr(dp, "MAX_ROWS", n)
        assert not dp.dense_pallas_eligible([g], aggs, merge=False)
        monkeypatch.setattr(dp, "MAX_ROWS", n + 1)
        assert dp.dense_pallas_eligible([g], aggs, merge=False)

    def test_ineligible_falls_back(self):
        """min/max and DOUBLE args route to the XLA dense kernel unchanged."""
        fts, ch = make_data(n=120, k_card=4)
        db, vals = eval_vals(fts, ch, [col(0, fts[0]), col(1, fts[1]), col(2, fts[2])])
        g, d, r = vals
        aggs = [
            (AggDesc("min", (col(1, fts[1]),)), [d]),
            (AggDesc("avg", (col(2, fts[2]),)), [r]),
        ]
        assert not _pallas_engaged([g], aggs)
        ref = group_aggregate([g], aggs, db.row_valid, 64)
        pal = group_aggregate([g], aggs, db.row_valid, 64, small_groups=8)
        ng = int(ref.n_groups)
        assert int(pal.n_groups) == ng
