"""Kernel parity tests: device group-agg/topn/join vs straightforward
host-side computation over the same rows (the reference-semantics oracle)."""

import numpy as np
import pytest
import jax.numpy as jnp

from tidb_tpu.types import Datum, MyDecimal, new_decimal, new_double, new_longlong, new_varchar
from tidb_tpu.chunk import Chunk, to_device_batch
from tidb_tpu.expr import AggDesc, col, compile_exprs, func, lit
from tidb_tpu.expr.compile import ExprCompiler, CompVal
from tidb_tpu.ops import apply_selection, group_aggregate, hash_join, scalar_aggregate, topn
from tidb_tpu.ops.aggregate import finalize_agg


def eval_vals(fts, chunk, exprs, capacity=None):
    db = to_device_batch(chunk, capacity=capacity or chunk.num_rows())
    comp = ExprCompiler(fts)
    vals = comp.run(exprs, db.cols)
    return db, vals


def make_data(n=200, seed=5, null_p=0.1, k_card=7):
    rng = np.random.default_rng(seed)
    fts = [new_longlong(), new_decimal(10, 2), new_double(), new_varchar(8)]
    words = ["aa", "bb", "cc", "dd", "ee"]
    rows = []
    for _ in range(n):
        def maybe(d):
            return Datum.NULL if rng.random() < null_p else d

        rows.append([
            maybe(Datum.i64(int(rng.integers(0, k_card)))),
            maybe(Datum.dec(MyDecimal(f"{rng.integers(-500, 500)/100:.2f}"))),
            maybe(Datum.f64(float(np.round(rng.normal(), 4)))),
            maybe(Datum.string(words[int(rng.integers(len(words)))])),
        ])
    return fts, Chunk.from_rows(fts, rows)


class TestGroupAgg:
    def test_group_by_int_sum_count_avg_min_max(self):
        fts, ch = make_data()
        db, vals = eval_vals(fts, ch, [col(0, fts[0]), col(1, fts[1]), col(2, fts[2])])
        g, d, r = vals
        aggs = [
            (AggDesc("count", ()), []),
            (AggDesc("sum", (col(1, fts[1]),)), [d]),
            (AggDesc("avg", (col(2, fts[2]),)), [r]),
            (AggDesc("min", (col(1, fts[1]),)), [d]),
            (AggDesc("max", (col(2, fts[2]),)), [r]),
        ]
        res = group_aggregate([g], aggs, db.row_valid, group_capacity=16)
        assert not bool(res.overflow)
        # oracle
        import collections

        groups = collections.defaultdict(list)
        for row in ch.rows():
            key = None if row[0].is_null() else row[0].val
            groups[key].append(row)
        assert int(res.n_groups) == len(groups)
        # map group rep -> key
        reps = np.asarray(res.group_rep)
        gv = np.asarray(res.group_valid)
        got = {}
        for gi in range(int(res.n_groups)):
            rep_row = ch.row(int(reps[gi]))
            key = None if rep_row[0].is_null() else rep_row[0].val
            cnt = int(np.asarray(res.states[0][0][0])[gi])
            s_v = int(np.asarray(res.states[1][0][0])[gi])
            s_null = bool(np.asarray(res.states[1][0][1])[gi])
            avg_v, avg_null = finalize_agg(aggs[2][0], res.states[2], res.group_valid)
            mn = np.asarray(res.states[3][0][0])[gi], bool(np.asarray(res.states[3][0][1])[gi])
            mx = np.asarray(res.states[4][0][0])[gi], bool(np.asarray(res.states[4][0][1])[gi])
            got[key] = (cnt, None if s_null else MyDecimal.from_scaled_int(s_v, 2),
                        (np.asarray(avg_v)[gi], bool(np.asarray(avg_null)[gi])), mn, mx)
        for key, rows in groups.items():
            cnt_w = len(rows)
            decs = [r[1].val for r in rows if not r[1].is_null()]
            sum_w = None
            if decs:
                sum_w = decs[0]
                for x in decs[1:]:
                    sum_w = sum_w + x
            reals = [r[2].val for r in rows if not r[2].is_null()]
            cnt, s, (avg_v, avg_null), (mn_v, mn_null), (mx_v, mx_null) = got[key]
            assert cnt == cnt_w, key
            assert s == sum_w or (s is None and sum_w is None)
            if reals:
                assert not avg_null
                assert avg_v == pytest.approx(sum(reals) / len(reals), rel=1e-12)
                assert not mx_null and mx_v == pytest.approx(max(reals))
            else:
                assert avg_null and mx_null
            if decs:
                assert not mn_null and MyDecimal.from_scaled_int(int(mn_v), 2) == min(decs)
            else:
                assert mn_null

    def test_group_by_string_and_overflow(self):
        fts, ch = make_data(n=100, k_card=5)
        db, vals = eval_vals(fts, ch, [col(3, fts[3]), col(0, fts[0])])
        s, g = vals
        aggs = [(AggDesc("count", ()), [])]
        res = group_aggregate([s], aggs, db.row_valid, group_capacity=16)
        keys = {None if r[3].is_null() else r[3].val for r in ch.rows()}
        assert int(res.n_groups) == len(keys)
        # force overflow
        res2 = group_aggregate([s, g], aggs, db.row_valid, group_capacity=3)
        assert bool(res2.overflow)

    def test_scalar_agg_empty_input(self):
        fts, ch = make_data(n=4)
        db, vals = eval_vals(fts, ch, [col(1, fts[1])])
        (d,) = vals
        none_valid = jnp.zeros_like(db.row_valid)
        states, _ = scalar_aggregate([(AggDesc("count", ()), []), (AggDesc("sum", (col(1, fts[1]),)), [d])], none_valid)
        assert int(states[0][0][0][0]) == 0
        assert bool(states[1][0][1][0])  # sum over empty -> NULL

    def test_merge_phase_equals_single_shot(self):
        """Partial per-half then merge == one-shot over all rows."""
        fts, ch = make_data(n=120, k_card=4)
        half = ch.num_rows() // 2
        ch1, ch2 = ch.slice(0, half), ch.slice(half, ch.num_rows())
        agg = AggDesc("avg", (col(1, fts[1]),))
        cap = 8

        def partial(c):
            db, vals = eval_vals(fts, c, [col(0, fts[0]), col(1, fts[1])])
            g, d = vals
            return db, g, group_aggregate([g], [(agg, [d])], db.row_valid, cap)

        db1, g1, r1 = partial(ch1)
        db2, g2, r2 = partial(ch2)
        # merge: stack partial states as rows keyed by group key value
        from tidb_tpu.types import FieldType, TypeCode

        cnt_ft = new_longlong(notnull=True)
        sum_ft = agg.partial_fts()[1]

        def keyvals(db, g, r):
            reps = r.group_rep
            kv = CompVal(g.value[reps], g.null[reps], g.ft)
            cnt = CompVal(r.states[0][0][0], r.states[0][0][1], cnt_ft)
            sm = CompVal(r.states[0][1][0], r.states[0][1][1], sum_ft)
            return kv, cnt, sm, r.group_valid

        k1, c1, s1, v1 = keyvals(db1, g1, r1)
        k2, c2, s2, v2 = keyvals(db2, g2, r2)
        kk = CompVal(jnp.concatenate([k1.value, k2.value]), jnp.concatenate([k1.null, k2.null]), g1.ft)
        cc = CompVal(jnp.concatenate([c1.value, c2.value]), jnp.concatenate([c1.null, c2.null]), cnt_ft)
        ss = CompVal(jnp.concatenate([s1.value, s2.value]), jnp.concatenate([s1.null, s2.null]), sum_ft)
        vv = jnp.concatenate([v1, v2])
        merged = group_aggregate([kk], [(agg, [cc, ss])], vv, cap, merge=True)

        db, vals = eval_vals(fts, ch, [col(0, fts[0]), col(1, fts[1])])
        g, d = vals
        oneshot = group_aggregate([g], [(agg, [d])], db.row_valid, cap)
        assert int(merged.n_groups) == int(oneshot.n_groups)

        def final_map(r, src_chunk_key):
            av, an = finalize_agg(agg, r.states[0], r.group_valid)
            out = {}
            for gi in range(int(r.n_groups)):
                out[src_chunk_key(int(np.asarray(r.group_rep)[gi]))] = (
                    int(np.asarray(av)[gi]),
                    bool(np.asarray(an)[gi]),
                )
            return out

        m1 = final_map(merged, lambda i: (None if bool(np.asarray(kk.null)[i]) else int(np.asarray(kk.value)[i])))
        m2 = final_map(oneshot, lambda i: (None if ch.row(i)[0].is_null() else ch.row(i)[0].val))
        assert m1 == m2


class TestTopN:
    def test_topn_multi_key_with_nulls(self):
        fts, ch = make_data(n=80)
        db, vals = eval_vals(fts, ch, [col(1, fts[1]), col(2, fts[2])])
        d, r = vals
        idx, valid, _ovf = topn([(d, False), (r, True)], db.row_valid, 10)
        idx, valid = np.asarray(idx), np.asarray(valid)
        assert valid.all()
        # oracle: stable sort by (d asc nulls-first, r desc nulls-last)
        rows = ch.rows()

        def key(i):
            dv = rows[i][1]
            rv = rows[i][2]
            dk = (0, MyDecimal("0")) if dv.is_null() else (1, dv.val)
            rk = (1, 0.0) if rv.is_null() else (0, -rv.val)
            return (dk[0], dk[1].d if hasattr(dk[1], "d") else dk[1], rk[0], rk[1], i)

        want = sorted(range(len(rows)), key=key)[:10]
        assert idx.tolist() == want

    def test_topn_k_exceeds_rows(self):
        fts, ch = make_data(n=5)
        db, vals = eval_vals(fts, ch, [col(0, fts[0])])
        (g,) = vals
        idx, valid, _ovf = topn([(g, False)], db.row_valid, 100)
        assert valid.sum() == 5


class TestHashJoin:
    def _join_oracle(self, lrows, rrows, lkey, rkey, join_type="inner"):
        out = []
        for i, lr in enumerate(lrows):
            lv = lr[lkey]
            matches = []
            if not lv.is_null():
                for j, rr in enumerate(rrows):
                    rv = rr[rkey]
                    if not rv.is_null() and lv.val == rv.val:
                        matches.append(j)
            if matches:
                out.extend((i, j) for j in matches)
            elif join_type == "left_outer":
                out.append((i, None))
        return sorted(out, key=lambda t: (t[0], -1 if t[1] is None else t[1]))

    def test_inner_and_left_outer(self):
        rng = np.random.default_rng(11)
        fts = [new_longlong()]
        lrows = [[Datum.NULL if rng.random() < 0.1 else Datum.i64(int(rng.integers(0, 12)))] for _ in range(60)]
        rrows = [[Datum.NULL if rng.random() < 0.1 else Datum.i64(int(rng.integers(0, 12)))] for _ in range(40)]
        lch, rch = Chunk.from_rows(fts, lrows), Chunk.from_rows(fts, rrows)
        ldb, lvals = eval_vals(fts, lch, [col(0, fts[0])])
        rdb, rvals = eval_vals(fts, rch, [col(0, fts[0])])
        for jt in ("inner", "left_outer"):
            res = hash_join(rvals, lvals, rdb.row_valid, ldb.row_valid, out_capacity=512, join_type=jt)
            assert not bool(res.overflow)
            got = []
            pv, bv, bn, ov = (np.asarray(x) for x in (res.probe_idx, res.build_idx, res.build_null, res.out_valid))
            for s in range(512):
                if ov[s]:
                    got.append((int(pv[s]), None if bn[s] else int(bv[s])))
            got.sort(key=lambda t: (t[0], -1 if t[1] is None else t[1]))
            want = self._join_oracle(lrows, rrows, 0, 0, jt)
            assert got == want, jt

    def test_semi_anti(self):
        fts = [new_longlong()]
        lrows = [[Datum.i64(v)] for v in [1, 2, 3, 4, 5]] + [[Datum.NULL]]
        rrows = [[Datum.i64(v)] for v in [2, 4, 4, 9]]
        lch, rch = Chunk.from_rows(fts, lrows), Chunk.from_rows(fts, rrows)
        ldb, lvals = eval_vals(fts, lch, [col(0, fts[0])])
        rdb, rvals = eval_vals(fts, rch, [col(0, fts[0])])
        semi = hash_join(rvals, lvals, rdb.row_valid, ldb.row_valid, 64, "semi")
        anti = hash_join(rvals, lvals, rdb.row_valid, ldb.row_valid, 64, "anti")
        sv = np.asarray(semi.out_valid)[:6]
        av = np.asarray(anti.out_valid)[:6]
        assert sv.tolist() == [False, True, False, True, False, False]
        # anti: non-matching incl. NULL lhs? MySQL NOT IN with NULL rhs absent here -> NULL key rows dropped...
        assert av.tolist() == [True, False, True, False, True, True]

    def test_build_unique_fast_path(self):
        """Unique-build hint: expansion-free probe-layout output equals the
        general kernel on unique build keys; a duplicate build key flips
        overflow instead of emitting wrong rows."""
        rng = np.random.default_rng(12)
        fts = [new_longlong()]
        lrows = [[Datum.NULL if rng.random() < 0.1 else Datum.i64(int(rng.integers(0, 30)))] for _ in range(50)]
        rrows = [[Datum.i64(v)] for v in rng.permutation(24)[:16]]  # unique
        lch, rch = Chunk.from_rows(fts, lrows), Chunk.from_rows(fts, rrows)
        ldb, lvals = eval_vals(fts, lch, [col(0, fts[0])])
        rdb, rvals = eval_vals(fts, rch, [col(0, fts[0])])
        for jt in ("inner", "left_outer"):
            res = hash_join(rvals, lvals, rdb.row_valid, ldb.row_valid, 256, jt, build_unique=True)
            assert not bool(res.overflow), jt
            got = []
            pv, bv, bn, ov = (np.asarray(x) for x in (res.probe_idx, res.build_idx, res.build_null, res.out_valid))
            for s in range(len(ov)):
                if ov[s]:
                    got.append((int(pv[s]), None if bn[s] else int(bv[s])))
            got.sort(key=lambda t: (t[0], -1 if t[1] is None else t[1]))
            want = self._join_oracle(lrows, rrows, 0, 0, jt)
            assert got == want, jt
        # violated hint: duplicate build keys -> overflow, driver falls back
        rrows_dup = rrows + [rrows[0]]
        rch2 = Chunk.from_rows(fts, rrows_dup)
        rdb2, rvals2 = eval_vals(fts, rch2, [col(0, fts[0])])
        res = hash_join(rvals2, lvals, rdb2.row_valid, ldb.row_valid, 256, "inner", build_unique=True)
        assert bool(res.overflow)

    def test_build_unique_multiword_keys(self):
        """Unique path over composite (hashed) keys, incl. collision checks."""
        fts = [new_longlong(), new_varchar(8)]
        rng = np.random.default_rng(13)
        rrows = [[Datum.i64(i), Datum.string(f"k{i}")] for i in range(12)]
        lrows = [[Datum.i64(int(rng.integers(0, 15))), Datum.string(f"k{int(rng.integers(0, 15))}")] for _ in range(40)]
        lrows = [[r[0], Datum.string("k" + str(r[0].val))] for r in lrows]  # aligned pairs
        lch, rch = Chunk.from_rows(fts, lrows), Chunk.from_rows(fts, rrows)
        ldb, lvals = eval_vals(fts, lch, [col(0, fts[0]), col(1, fts[1])])
        rdb, rvals = eval_vals(fts, rch, [col(0, fts[0]), col(1, fts[1])])
        res = hash_join(rvals, lvals, rdb.row_valid, ldb.row_valid, 128, "inner", build_unique=True)
        assert not bool(res.overflow)
        pv, bv, ov = (np.asarray(x) for x in (res.probe_idx, res.build_idx, res.out_valid))
        got = sorted((int(pv[s]), int(bv[s])) for s in range(len(ov)) if ov[s])
        want = []
        for i, lr in enumerate(lrows):
            for j, rr in enumerate(rrows):
                if lr[0].val == rr[0].val and lr[1].val == rr[1].val:
                    want.append((i, j))
        assert got == sorted(want)

    def test_multiword_string_key_join(self):
        fts = [new_varchar(20)]
        import random

        names = ["alphaalphaalpha1", "betabetabeta2", "gammagammagamma3", "x"]
        lrows = [[Datum.string(random.Random(1).choice(names))] for _ in range(10)]
        lrows = [[Datum.string(names[i % 4])] for i in range(10)]
        rrows = [[Datum.string(names[i % 3])] for i in range(6)]
        lch, rch = Chunk.from_rows(fts, lrows), Chunk.from_rows(fts, rrows)
        ldb, lvals = eval_vals(fts, lch, [col(0, fts[0])])
        rdb, rvals = eval_vals(fts, rch, [col(0, fts[0])])
        res = hash_join(rvals, lvals, rdb.row_valid, ldb.row_valid, 128, "inner")
        got = []
        pv, bv, ov = np.asarray(res.probe_idx), np.asarray(res.build_idx), np.asarray(res.out_valid)
        for s in range(128):
            if ov[s]:
                got.append((int(pv[s]), int(bv[s])))
        want = [(i, j) for i in range(10) for j in range(6) if lrows[i][0].val == rrows[j][0].val]
        assert sorted(got) == sorted(want)


class TestSelection:
    def test_mask_semantics(self):
        fts, ch = make_data(n=50)
        db, vals = eval_vals(fts, ch, [func("gt", new_longlong(notnull=True), col(1, fts[1]), lit("0.00", new_decimal(3, 2)))])
        (c,) = vals
        out = apply_selection(db.row_valid, [c])
        want = np.array([(not r[1].is_null()) and r[1].val > MyDecimal("0") for r in ch.rows()])
        assert np.asarray(out).tolist() == want.tolist()


class TestBitAggs:
    def test_scalar_bit_aggs(self):
        """BIT_AND/OR/XOR on device (segmented-scan reduce), incl. MySQL
        empty-set identities (ref: builtin bit agg semantics)."""
        import jax.numpy as jnp

        from tidb_tpu.expr.agg import AggDesc
        from tidb_tpu.expr.compile import CompVal
        from tidb_tpu.ops.aggregate import scalar_aggregate
        from tidb_tpu.types import new_longlong

        FT = new_longlong(unsigned=True)
        vals = jnp.asarray([0b1100, 0b1010, 0b0110], dtype=jnp.int64)
        nulls = jnp.asarray([False, False, True])  # NULL ignored
        valid = jnp.ones(3, bool)
        a = CompVal(vals, nulls, FT)
        from tidb_tpu.expr import col as _col
        descs = [AggDesc("bit_and", (_col(0, FT),)), AggDesc("bit_or", (_col(0, FT),)), AggDesc("bit_xor", (_col(0, FT),))]
        sts, _ = scalar_aggregate([(d, [a]) for d in descs], valid)
        assert int(sts[0][0][0][0]) == 0b1000
        assert int(sts[1][0][0][0]) == 0b1110
        assert int(sts[2][0][0][0]) == 0b0110
        # empty set: and -> all ones, or/xor -> 0, never NULL
        sts, _ = scalar_aggregate([(d, [a]) for d in descs], jnp.zeros(3, bool))
        assert int(sts[0][0][0][0]) == -1 and not bool(sts[0][0][1][0])
        assert int(sts[1][0][0][0]) == 0
        assert int(sts[2][0][0][0]) == 0

    def test_grouped_bit_aggs(self):
        import jax.numpy as jnp

        from tidb_tpu.expr.agg import AggDesc
        from tidb_tpu.expr.compile import CompVal
        from tidb_tpu.ops.aggregate import group_aggregate
        from tidb_tpu.types import new_longlong

        FT = new_longlong(unsigned=True)
        g = CompVal(jnp.asarray([1, 2, 1, 2], dtype=jnp.int64), jnp.zeros(4, bool), new_longlong())
        a = CompVal(jnp.asarray([0b11, 0b101, 0b10, 0b100], dtype=jnp.int64), jnp.zeros(4, bool), FT)
        from tidb_tpu.expr import col as _col
        res = group_aggregate([g], [(AggDesc("bit_or", (_col(1, FT),)), [a])], jnp.ones(4, bool), 8)
        got = sorted(int(v) for v in res.states[0][0][0][: int(res.n_groups)])
        assert got == sorted([0b11, 0b101])


class TestDenseSmallG:
    def test_dense_matches_sort_kernel(self):
        """The stats-hinted dense small-G kernel must be bit-identical to
        the sort kernel (same states, same first-encounter order)."""
        import jax.numpy as jnp

        from tidb_tpu.expr import col
        from tidb_tpu.expr.agg import AggDesc
        from tidb_tpu.ops.aggregate import group_aggregate

        fts, ch = make_data(n=200, k_card=5)
        db, vals = eval_vals(fts, ch, [col(0, fts[0]), col(1, fts[1]), col(2, fts[2])])
        g, d, r = vals
        aggs = [
            (AggDesc("count", ()), []),
            (AggDesc("sum", (col(1, fts[1]),)), [d]),
            (AggDesc("avg", (col(2, fts[2]),)), [r]),
            (AggDesc("min", (col(1, fts[1]),)), [d]),
            (AggDesc("first_row", (col(0, fts[0]),)), [g]),
        ]
        import numpy as np

        rng = np.random.default_rng(3)
        valid = db.row_valid & jnp.asarray(rng.random(200) < 0.8)  # filtered rows
        ref = group_aggregate([g], aggs, valid, 64)
        dense = group_aggregate([g], aggs, valid, 64, small_groups=8)
        assert not bool(dense.overflow)
        ng = int(ref.n_groups)
        assert int(dense.n_groups) == ng
        assert jnp.array_equal(ref.group_rep[:ng], dense.group_rep[:ng])
        for rs, ds in zip(ref.states, dense.states):
            if hasattr(rs, "idx"):
                assert jnp.array_equal(rs.idx[:ng], ds.idx[:ng])
                assert jnp.array_equal(rs.has[:ng], ds.has[:ng])
            else:
                for (rv, rn), (dv, dn) in zip(rs, ds):
                    if jnp.issubdtype(rv.dtype, jnp.floating):
                        # float sums accumulate in different orders
                        # (cumsum-sorted vs masked-original) — last-ulp only
                        assert jnp.allclose(rv[:ng], dv[:ng], rtol=1e-12)
                    else:
                        assert jnp.array_equal(rv[:ng], dv[:ng])
                    assert jnp.array_equal(rn[:ng], dn[:ng])

    def test_dense_sample_missed_group_overflows(self):
        """A group invisible to the strided extraction sample must raise
        overflow, never silently merge/drop (dense kernel exactness check
        #1 — every valid row's hash must be a table entry)."""
        import numpy as np

        from tidb_tpu.chunk import Chunk, to_device_batch
        from tidb_tpu.expr import col
        from tidb_tpu.expr.agg import AggDesc
        from tidb_tpu.expr.compile import normalize_device_column
        from tidb_tpu.ops.aggregate import group_aggregate
        from tidb_tpu.types import Datum, new_longlong

        ft = new_longlong()
        n = 8192  # stride = n // 4096 = 2: the sample sees even indices only
        vals = np.zeros(n, np.int64)
        vals[1] = 77  # a whole group living ONLY at an odd index
        rows = [[Datum.i64(int(v))] for v in vals]
        ch = Chunk.from_rows([ft], rows)
        db = to_device_batch(ch, capacity=n)
        g = normalize_device_column(db.cols[0])
        res = group_aggregate([g], [(AggDesc("count", ()), [])], db.row_valid, 64, small_groups=8)
        assert bool(res.overflow)

    def test_dense_mxu_sum_exactness_at_scale(self):
        """The MXU limb-matmul sum path (seg.DenseSumBatch) must be EXACT
        for large signed int64 values across many 256-row chunks."""
        import numpy as np

        from tidb_tpu.expr.compile import CompVal
        from tidb_tpu.ops.aggregate import group_aggregate

        N = 1 << 14
        rng = np.random.default_rng(9)
        g = rng.integers(0, 6, N)
        v = rng.integers(-(1 << 45), 1 << 45, N)
        LL = new_longlong()
        gv = CompVal(jnp.asarray(g, jnp.int64), jnp.zeros(N, bool), LL)
        vv = CompVal(jnp.asarray(v, jnp.int64), jnp.zeros(N, bool), LL)
        valid = jnp.ones(N, bool)
        res = group_aggregate(
            [gv], [(AggDesc("count", ()), []), (AggDesc("sum", (col(1, LL),)), [vv])],
            valid, 64, small_groups=8,
        )
        assert not bool(res.overflow)
        ng = int(res.n_groups)
        rep = np.asarray(res.group_rep[:ng])
        for i in range(ng):
            k = int(g[rep[i]])
            m = g == k
            assert int(res.states[0][0][0][i]) == int(m.sum())
            assert int(res.states[1][0][0][i]) == int(v[m].sum())

    def test_dense_overflow_when_hint_wrong(self):
        """More groups than the hint -> overflow flag (driver falls back)."""
        from tidb_tpu.expr import col
        from tidb_tpu.expr.agg import AggDesc
        from tidb_tpu.ops.aggregate import group_aggregate

        fts, ch = make_data(n=200, k_card=50)
        db, vals = eval_vals(fts, ch, [col(0, fts[0])])
        (g,) = vals
        res = group_aggregate([g], [(AggDesc("count", ()), [])], db.row_valid, 64, small_groups=4)
        assert bool(res.overflow)


class TestStreamAgg:
    def test_stream_matches_hash_kernel(self):
        """stream=True boundary-scan == hash kernel on sorted input,
        including interleaved filtered rows and all-filtered runs."""
        import numpy as np

        from tidb_tpu.expr import col
        from tidb_tpu.expr.agg import AggDesc
        from tidb_tpu.ops.aggregate import group_aggregate

        fts, ch = make_data(n=240, k_card=9)
        # sort rows by the group column (nulls first) — the stream contract
        rows = sorted(ch.rows(), key=lambda r: (not r[0].is_null(), r[0].val if not r[0].is_null() else 0))
        from tidb_tpu.chunk import Chunk

        ch2 = Chunk.from_rows(fts, rows)
        db, vals = eval_vals(fts, ch2, [col(0, fts[0]), col(1, fts[1]), col(3, fts[3])])
        g, d, st_ = vals
        rng = np.random.default_rng(5)
        valid = db.row_valid & jnp.asarray(rng.random(240) < 0.7)
        aggs = [
            (AggDesc("count", ()), []),
            (AggDesc("sum", (col(1, fts[1]),)), [d]),
            (AggDesc("min", (col(1, fts[1]),)), [d]),
            (AggDesc("max", (col(3, fts[3]),)), [st_]),  # string max -> GatherState
        ]
        ref = group_aggregate([g], aggs, valid, 64)
        stream = group_aggregate([g], aggs, valid, 64, stream=True)
        assert not bool(stream.overflow)
        ng = int(ref.n_groups)
        assert int(stream.n_groups) == ng
        assert jnp.array_equal(ref.group_rep[:ng], stream.group_rep[:ng])
        for rs, ss in zip(ref.states, stream.states):
            if hasattr(rs, "idx"):
                assert jnp.array_equal(rs.idx[:ng], ss.idx[:ng])
                assert jnp.array_equal(rs.has[:ng], ss.has[:ng])
            else:
                for (rv, rn), (sv, sn) in zip(rs, ss):
                    assert jnp.array_equal(rv[:ng], sv[:ng])
                    assert jnp.array_equal(rn[:ng], sn[:ng])

    def test_stream_kernel_has_no_sort(self):
        """The StreamAgg trace contains NO sort primitive — the measurably
        cheaper path the planner opts into (the hash kernel sorts)."""
        import jax

        from tidb_tpu.expr import col
        from tidb_tpu.expr.agg import AggDesc
        from tidb_tpu.ops.aggregate import group_aggregate

        fts, ch = make_data(n=64, k_card=4, null_p=0.0)
        db, vals = eval_vals(fts, ch, [col(0, fts[0]), col(1, fts[1])])
        g, d = vals
        aggs = [(AggDesc("sum", (col(1, fts[1]),)), [d])]

        def prims(stream):
            jaxpr = jax.make_jaxpr(
                lambda gv, gn, dv, dn, valid: [
                    x
                    for st in group_aggregate(
                        [CompVal(gv, gn, fts[0])],
                        [(aggs[0][0], [CompVal(dv, dn, fts[1])])],
                        valid,
                        16,
                        stream=stream,
                    ).states
                    for (v, nl) in st
                    for x in (v, nl)
                ]
            )(g.value, g.null, d.value, d.null, db.row_valid)
            sizes = []

            def walk(jx):
                for eq in jx.eqns:
                    if eq.primitive.name == "sort":
                        sizes.append(max(int(v.aval.shape[0]) for v in eq.invars))
                    for sub in eq.params.values():
                        if hasattr(sub, "jaxpr"):
                            walk(sub.jaxpr)
            walk(jaxpr.jaxpr)
            return sizes

        # hash kernel sorts the N=64 rows; stream only argsorts the G=16
        # group table for the first-encounter reorder
        assert max(prims(False)) == 64
        assert max(prims(True), default=0) <= 16
