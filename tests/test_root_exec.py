"""Root executor (VERDICT next #4): a logical Complete-mode DAG splits into
per-region Partial1 + root Final merge invisibly; per-region TopN/Limit are
re-applied globally. Every test compares against the single-shot oracle over
all rows — the merge must be caller-invisible."""

import numpy as np
import pytest

from tidb_tpu.chunk import Chunk
from tidb_tpu.codec import tablecodec
from tidb_tpu.distsql import execute_root, full_table_ranges, split_dag
from tidb_tpu.exec import (
    Aggregation,
    ColumnInfo,
    DAGRequest,
    Join,
    Limit,
    Selection,
    TableScan,
    TopN,
    run_dag_reference,
)
from tidb_tpu.exec.executor import datum_group_key
from tidb_tpu.expr import AggDesc, col, func, lit
from tidb_tpu.store import TPUStore
from tidb_tpu.types import Datum, MyDecimal, new_decimal, new_longlong, new_varchar

BOOL = new_longlong(notnull=True)
TID = 77
FTS = [new_longlong(), new_decimal(10, 2), new_varchar(8), new_longlong(unsigned=True)]
C = lambda i: col(i, FTS[i])


def canon(rows):
    return sorted(tuple(datum_group_key(d) for d in r) for r in rows)


def fill_store(n=260, regions=4, seed=11, null_p=0.05):
    store = TPUStore()
    rng = np.random.default_rng(seed)
    rows = []
    words = ["ox", "ant", "bee", "Cat", "dog", ""]
    for h in range(n):
        def maybe(d):
            return Datum.NULL if rng.random() < null_p else d

        row = [
            maybe(Datum.i64(int(rng.integers(0, 7)))),
            maybe(Datum.dec(MyDecimal(f"{int(rng.integers(-9999, 9999))/100:.2f}"))),
            maybe(Datum.string(words[int(rng.integers(len(words)))])),
            maybe(Datum.u64(int(rng.integers(0, 1 << 62)))),
        ]
        rows.append(row)
        store.put_row(TID, h, [1, 2, 3, 4], row, ts=10)
    for i in range(1, regions):
        store.cluster.split(tablecodec.encode_row_key(TID, i * n // regions))
    return store, rows


def scan():
    return TableScan(TID, tuple(ColumnInfo(i + 1, ft) for i, ft in enumerate(FTS)))


def check(store, rows, dag, sort=True):
    got = execute_root(store, dag, full_table_ranges(TID), start_ts=100)
    want = run_dag_reference(dag, Chunk.from_rows(FTS, rows))
    if sort:
        assert canon(got.rows()) == canon(want)
    else:
        g = [tuple(datum_group_key(d) for d in r) for r in got.rows()]
        w = [tuple(datum_group_key(d) for d in r) for r in want]
        assert g == w, f"\ngot ={g[:4]}\nwant={w[:4]}"
    return got


class TestRootExecutor:
    def test_grouped_agg_split(self):
        store, rows = fill_store()
        agg = Aggregation(
            group_by=(C(0), C(2)),
            aggs=(
                AggDesc("count", ()),
                AggDesc("sum", (C(1),)),
                AggDesc("avg", (C(1),)),
                AggDesc("min", (C(2),)),       # string min via gather state
                AggDesc("max", (C(3),)),       # unsigned max
                AggDesc("first_row", (C(1),)),
            ),
        )
        dag = DAGRequest((scan(), agg), output_offsets=tuple(range(8)))
        plan = split_dag(dag)
        assert plan.root_dag is not None and plan.push_dag.executors[-1].partial
        check(store, rows, dag)

    def test_scalar_agg_split(self):
        store, rows = fill_store(n=150, regions=3)
        agg = Aggregation(group_by=(), aggs=(AggDesc("count", ()), AggDesc("sum", (C(1),)), AggDesc("min", (C(1),))))
        dag = DAGRequest((scan(), agg), output_offsets=(0, 1, 2))
        check(store, rows, dag)

    def test_multi_region_topn_reapplied(self):
        """Per-region TopN concatenation is NOT the global TopN — the root
        must re-apply (VERDICT weak #5)."""
        store, rows = fill_store(n=200, regions=4)
        t = TopN(order_by=((C(1), True), (C(0), False)), limit=7)
        dag = DAGRequest((scan(), t), output_offsets=(0, 1, 2))
        got = check(store, rows, dag, sort=False)
        assert got.num_rows() == 7

    def test_multi_region_limit_reapplied(self):
        store, rows = fill_store(n=120, regions=3)
        dag = DAGRequest((scan(), Limit(10)), output_offsets=(0, 1))
        got = execute_root(store, dag, full_table_ranges(TID), start_ts=100)
        assert got.num_rows() == 10
        # rows must come from the table (limit over unordered scan is any-10)
        table = {tuple(datum_group_key(d) for d in (r[0], r[1])) for r in rows}
        for r in got.rows():
            assert tuple(datum_group_key(d) for d in r) in table

    def test_distinct_agg_runs_at_root(self):
        store, rows = fill_store(n=180, regions=3)
        agg = Aggregation(group_by=(C(0),), aggs=(AggDesc("count", (C(1),), distinct=True), AggDesc("sum", (C(1),))))
        dag = DAGRequest((scan(), agg), output_offsets=(0, 1, 2))
        plan = split_dag(dag)
        assert plan.push_dag.executors[-1] is plan.push_dag.executors[0] or not isinstance(plan.push_dag.executors[-1], Aggregation)
        check(store, rows, dag)

    def test_having_after_agg(self):
        """Selection after the aggregation (HAVING) runs at root over the
        merged finals."""
        store, rows = fill_store(n=200, regions=4)
        agg = Aggregation(group_by=(C(0),), aggs=(AggDesc("count", ()), AggDesc("sum", (C(1),))))
        having = Selection((func("gt", BOOL, col(0, agg.aggs[0].ft), lit(20, new_longlong())),))
        t = TopN(order_by=((col(1, agg.aggs[1].ft), True),), limit=3)
        dag = DAGRequest((scan(), agg, having, t), output_offsets=(0, 1, 2))
        check(store, rows, dag, sort=False)

    def test_selection_then_agg(self):
        store, rows = fill_store(n=220, regions=4)
        sel = Selection((func("ge", BOOL, C(1), lit("0.00", new_decimal(3, 2))),))
        agg = Aggregation(group_by=(C(2),), aggs=(AggDesc("avg", (C(1),)), AggDesc("count", ())))
        dag = DAGRequest((scan(), sel, agg), output_offsets=(0, 1, 2))
        check(store, rows, dag)

    def test_plain_scan_no_root(self):
        store, rows = fill_store(n=90, regions=3)
        dag = DAGRequest((scan(), Selection((func("isnull", BOOL, C(2)),))), output_offsets=(0, 2))
        plan = split_dag(dag)
        assert plan.root_dag is None
        check(store, rows, dag)

    def test_empty_table(self):
        store = TPUStore()
        agg = Aggregation(group_by=(), aggs=(AggDesc("count", ()),))
        dag = DAGRequest((scan(), agg), output_offsets=(0,))
        got = execute_root(store, dag, full_table_ranges(TID), start_ts=100)
        assert got.num_rows() == 1 and got.row(0)[0].val == 0


def test_q3_via_root_executor():
    """The hand-rolled Q3 merge from test_join_dag, now through the generic
    root executor: logical DAG in, globally-correct TopN out."""
    import tests.test_join_dag as J

    lrows, orows, crows = J.make_tables(nl=300, no=60, nc=20)
    store = TPUStore()
    for h, r in enumerate(lrows):
        store.put_row(1, h, [1, 2, 3, 4], r, ts=10)
    for h, r in enumerate(orows):
        store.put_row(2, h, [1, 2, 3, 4], r, ts=10)
    for h, r in enumerate(crows):
        store.put_row(3, h, [1, 2], r, ts=10)
    for frac in (1, 2):
        store.cluster.split(tablecodec.encode_row_key(1, frac * 100))

    from tidb_tpu.distsql import KVRequest, select

    ls, os_, cs = J.scans()
    och = select(store, KVRequest(DAGRequest((os_,), output_offsets=tuple(range(4))), full_table_ranges(2), start_ts=100)).merged()
    cch = select(store, KVRequest(DAGRequest((cs,), output_offsets=tuple(range(2))), full_table_ranges(3), start_ts=100)).merged()

    base = J.q3_dag(partial=False)
    topn = TopN(order_by=((col(0, base.executors[-1].aggs[0].ft), True),), limit=10)
    dag = DAGRequest(base.executors + (topn,), output_offsets=base.output_offsets)
    got = execute_root(store, dag, full_table_ranges(1), start_ts=100, aux_chunks=[och, cch])
    want = run_dag_reference(dag, [Chunk.from_rows(J.LFTS, lrows), Chunk.from_rows(J.OFTS, orows), Chunk.from_rows(J.CFTS, crows)])
    got_rev = sorted(str(r[0].val) for r in got.rows())
    want_rev = sorted(str(r[0].val) for r in want)
    assert got_rev == want_rev
