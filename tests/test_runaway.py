"""Runaway-query control (VERDICT r4 next #10): max_execution_time checked
at every coprocessor dispatch boundary (the BeforeCopRequest hook point,
ref: pkg/resourcegroup/runaway/checker.go:27), KILL QUERY via the same
checker."""

import threading
import time

import pytest

from tidb_tpu.distsql.runaway import QueryKilledError, RunawayChecker
from tidb_tpu.sql import Session, SQLError
from tidb_tpu.util import failpoint


def _multi_region_session(rows=400, regions=12):
    from tidb_tpu.codec import tablecodec

    s = Session()
    s.execute("create table big (id bigint primary key, v bigint)")
    s.execute("insert into big values " + ",".join(f"({i}, {i})" for i in range(rows)))
    meta = s.catalog.table("big")
    for r in range(1, regions):
        s.store.cluster.split(tablecodec.encode_row_key(meta.table_id, r * rows // regions))
    return s


def test_checker_deadline_fake_clock():
    now = [0.0]
    c = RunawayChecker(50, now_fn=lambda: now[0])
    c.before_cop_request()  # within budget
    now[0] = 0.051
    with pytest.raises(QueryKilledError, match="maximum statement execution time"):
        c.before_cop_request()


def test_max_execution_time_kills_slow_scan():
    s = _multi_region_session()
    s.execute("set max_execution_time = 30")
    # each region task sleeps past the budget: the second dispatch
    # boundary must abort the statement
    with failpoint.enabled("distsql.before_task", lambda: time.sleep(0.04)):
        with pytest.raises(SQLError, match="maximum statement execution time"):
            s.execute("select sum(v) from big")
    # budget back to unlimited: the same query runs
    s.execute("set max_execution_time = 0")
    assert s.execute("select count(*) from big").values() == [[400]]


def test_kill_query_aborts():
    s = _multi_region_session()
    errs = []

    def stall():
        time.sleep(0.05)

    def run():
        try:
            with failpoint.enabled("distsql.before_task", stall):
                s.execute("select sum(v) from big")
        except SQLError as exc:
            errs.append(str(exc))

    t = threading.Thread(target=run)
    t.start()
    time.sleep(0.02)
    s.kill_query()
    t.join(timeout=10)
    assert errs and "interrupted" in errs[0]
