"""Views: CREATE/DROP/query-through/SHOW CREATE/persistence (VERDICT r3
missing #5; ref: pkg/planner/core/logical_plan_builder.go buildDataSource
view branch, meta/model ViewInfo)."""

import pytest

from tidb_tpu.sql import Session


def _mk():
    s = Session()
    s.execute("create table t (id bigint primary key, g varchar(8), v bigint)")
    s.execute("insert into t values (1,'a',10),(2,'b',20),(3,'a',30),(4,'c',40)")
    return s


class TestViews:
    def test_create_and_query(self):
        s = _mk()
        s.execute("create view va as select g, sum(v) as total from t group by g")
        r = s.execute("select g, total from va order by g")
        assert [(str(x[0].val), int(str(x[1].val))) for x in r.rows] == [
            ("a", 40), ("b", 20), ("c", 40)]
        # views join with tables
        r = s.execute("select t.id from t join va on t.g = va.g where va.total > 30 order by t.id")
        assert [int(x[0].val) for x in r.rows] == [1, 3, 4]

    def test_view_with_column_list(self):
        s = _mk()
        s.execute("create view vc (grp, cnt) as select g, count(*) from t group by g")
        r = s.execute("select grp, cnt from vc order by grp")
        assert [(str(x[0].val), int(x[1].val)) for x in r.rows] == [("a", 2), ("b", 1), ("c", 1)]

    def test_view_over_view(self):
        s = _mk()
        s.execute("create view v1 as select id, v from t where v >= 20")
        s.execute("create view v2 as select id from v1 where v < 40")
        r = s.execute("select * from v2 order by id")
        assert [int(x[0].val) for x in r.rows] == [2, 3]

    def test_show_create_view_and_show_tables(self):
        s = _mk()
        s.execute("create view va as select id from t")
        r = s.execute("show create view va")
        assert r.columns == ["View", "Create View"]
        assert "select id from t" in str(r.rows[0][1].val)
        names = [str(x[0].val) for x in s.execute("show tables").rows]
        assert "va" in names and "t" in names

    def test_or_replace_and_drop(self):
        s = _mk()
        s.execute("create view va as select id from t")
        with pytest.raises(Exception):
            s.execute("create view va as select v from t")
        s.execute("create or replace view va as select v from t")
        r = s.execute("select * from va order by v")
        assert int(r.rows[0][0].val) == 10
        s.execute("drop view va")
        with pytest.raises(Exception):
            s.execute("select * from va")
        s.execute("drop view if exists va")

    def test_view_sees_current_data(self):
        s = _mk()
        s.execute("create view va as select count(*) as n from t")
        assert int(s.execute("select n from va").rows[0][0].val) == 4
        s.execute("insert into t values (5,'d',50)")
        assert int(s.execute("select n from va").rows[0][0].val) == 5

    def test_view_name_clashes(self):
        s = _mk()
        s.execute("create view va as select id from t")
        with pytest.raises(Exception):
            s.execute("create table va (x bigint)")
        with pytest.raises(Exception):
            s.execute("drop table va")  # it's a view
        with pytest.raises(Exception):
            s.execute("create view t as select 1")  # t is a table

    def test_create_view_validates_body(self):
        s = _mk()
        with pytest.raises(Exception):
            s.execute("create view bad as select nosuchcol from t")
        with pytest.raises(Exception):
            s.execute("create view bad (a, b) as select id from t")  # arity

    def test_view_survives_restart(self):
        s = _mk()
        s.execute("create view va as select id from t where v > 15")
        s2 = Session(store=s.store)
        r = s2.execute("select * from va order by id")
        assert [int(x[0].val) for x in r.rows] == [2, 3, 4]

    def test_cte_shadows_view(self):
        s = _mk()
        s.execute("create view va as select id from t")
        r = s.execute("with va as (select 99 as id) select id from va")
        assert [int(x[0].val) for x in r.rows] == [99]
