"""IndexScan + ranger (VERDICT next #9): predicate -> range pruning for the
PK handle, covering index scans that read fewer rows than a full scan, and
index maintenance through every DML path. Ref: mpp_exec.go:284 indexScanExec,
pkg/util/ranger."""

import pytest

from tidb_tpu.sql import Session
from tidb_tpu.sql.ranger import Interval, intervals_for_column
from tidb_tpu.parser.parser import parse_one
from tidb_tpu.parser import ast as A
from tidb_tpu.types import Datum, new_longlong


@pytest.fixture()
def sess():
    s = Session()
    s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, g INT, v DECIMAL(8,2), s VARCHAR(10))")
    vals = ", ".join(f"({i}, {i % 7}, {i}.50, 'w{i % 5}')" for i in range(300))
    s.execute(f"INSERT INTO t (id, g, v, s) VALUES {vals}")
    return s


def _scanned_rows(sess, sql):
    """Rows the probe scan produced (exec summary of the scan executor)."""
    from tidb_tpu.distsql import KVRequest, full_table_ranges, select, split_dag
    from tidb_tpu.sql.planner import plan_select

    plan = plan_select(parse_one(sql), sess.catalog)
    rp = split_dag(plan.dag)
    ranges = plan.ranges if plan.ranges is not None else full_table_ranges(plan.probe_table.table_id)
    res = select(sess.store, KVRequest(rp.push_dag, ranges, start_ts=10_000))
    return sum(sm[0].num_produced_rows for sm in res.exec_summaries), plan.access_path


class TestRanger:
    def test_intervals_basics(self):
        ev = lambda lit: Datum.i64(int(lit.value))
        conj = [parse_one("SELECT 1 FROM t WHERE a > 5 AND a <= 20").where]
        # split by hand: the conjuncts list comes from the planner normally
        c = conj[0]
        ivs = intervals_for_column([c.left, c.right], "a", ev)
        assert len(ivs) == 1
        iv = ivs[0]
        assert iv.low.val == 5 and not iv.low_inc and iv.high.val == 20 and iv.high_inc

    def test_intervals_in_and_empty(self):
        ev = lambda lit: Datum.i64(int(lit.value))
        w = parse_one("SELECT 1 FROM t WHERE a IN (3, 7, 9)").where
        ivs = intervals_for_column([w], "a", ev)
        assert [(iv.low.val, iv.high.val) for iv in ivs] == [(3, 3), (7, 7), (9, 9)]
        w1 = parse_one("SELECT 1 FROM t WHERE a = 5").where
        w2 = parse_one("SELECT 1 FROM t WHERE a = 6").where
        assert intervals_for_column([w1, w2], "a", ev) == []

    def test_unrelated_conjuncts_ignored(self):
        ev = lambda lit: Datum.i64(int(lit.value))
        w = parse_one("SELECT 1 FROM t WHERE b < 9").where
        assert intervals_for_column([w], "a", ev) is None


class TestPKPruning:
    def test_range_scan_reads_fewer_rows(self, sess):
        n, path = _scanned_rows(sess, "SELECT v FROM t WHERE id BETWEEN 10 AND 20")
        assert path == "table-range" and n == 11

    def test_point_get(self, sess):
        n, path = _scanned_rows(sess, "SELECT v FROM t WHERE id = 42")
        assert path == "table-range" and n == 1
        assert str(sess.execute("SELECT v FROM t WHERE id = 42").scalar()) == "42.50"

    def test_correct_results_with_pruning(self, sess):
        r = sess.execute("SELECT sum(v), count(*) FROM t WHERE id >= 290")
        assert r.rows[0][1].val == 10
        assert float(str(r.rows[0][0].val)) == sum(i + 0.5 for i in range(290, 300))

    def test_empty_range(self, sess):
        assert sess.execute("SELECT count(*) FROM t WHERE id = 5 AND id = 6").scalar() == 0
        assert sess.execute("SELECT v FROM t WHERE id = -1").rows == []


class TestCoveringIndex:
    @pytest.fixture()
    def isess(self, sess):
        sess.execute("CREATE INDEX ig ON t (g, id)")
        return sess

    def test_index_selected_and_fewer_rows(self, isess):
        n, path = _scanned_rows(isess, "SELECT count(*) FROM t WHERE g = 3")
        assert path == "index(ig)" and n == 43

    def test_index_results_match_table_scan(self, isess):
        got = isess.execute("SELECT g, count(*), min(id), max(id) FROM t WHERE g IN (2, 5) GROUP BY g ORDER BY g")
        want = [[g, len(ids), min(ids), max(ids)] for g, ids in
                ((2, [i for i in range(300) if i % 7 == 2]), (5, [i for i in range(300) if i % 7 == 5]))]
        assert got.values() == want

    def test_non_covering_uses_index_lookup(self, isess):
        # v is not in the index -> no covering scan, but the selective
        # point predicate on g routes through the double-read now
        # (r2 behavior was a full table scan; VERDICT r2 missing #3)
        _, path = _scanned_rows(isess, "SELECT v FROM t WHERE g = 3")
        assert path == "index_lookup(ig)"

    def test_index_range(self, isess):
        n, path = _scanned_rows(isess, "SELECT g FROM t WHERE g > 4")
        assert path == "index(ig)"
        assert n == sum(1 for i in range(300) if i % 7 > 4)

    def test_index_maintained_by_dml(self, isess):
        isess.execute("DELETE FROM t WHERE g = 3 AND id < 100")
        assert isess.execute("SELECT count(*) FROM t WHERE g = 3").scalar() == sum(
            1 for i in range(100, 300) if i % 7 == 3
        )
        isess.execute("UPDATE t SET g = 3 WHERE id = 0")
        assert isess.execute("SELECT count(*) FROM t WHERE g = 3").scalar() == 1 + sum(
            1 for i in range(100, 300) if i % 7 == 3
        )
        isess.execute("INSERT INTO t (id, g, v, s) VALUES (1000, 3, 1.00, 'x')")
        assert isess.execute("SELECT max(id) FROM t WHERE g = 3").scalar() == 1000

    def test_create_index_backfills(self, sess):
        # index created AFTER the inserts must see existing rows (backfill)
        sess.execute("CREATE INDEX iv ON t (g)")
        n, path = _scanned_rows(sess, "SELECT count(*) FROM t WHERE g = 0")
        assert path == "index(iv)"
        assert sess.execute("SELECT count(*) FROM t WHERE g = 0").scalar() == sum(1 for i in range(300) if i % 7 == 0)

    def test_drop_index(self, isess):
        isess.execute("DROP INDEX ig ON t")
        n, path = _scanned_rows(isess, "SELECT count(*) FROM t WHERE g = 3")
        assert path == "table"
        from tidb_tpu.sql import CatalogError

        with pytest.raises(CatalogError, match="unknown index"):
            isess.execute("DROP INDEX nope ON t")


class TestReviewRegressions:
    def test_lossy_literal_does_not_prune(self, sess):
        # 1.5 rounds to 2 for a BIGINT column; pruning with the rounded
        # bound would drop id=2 (2 > 1.5) — the conjunct must stay a filter
        r = sess.execute("SELECT id FROM t WHERE id > 1.5 AND id < 3.5 ORDER BY id")
        assert [x for x, in r.values()] == [2, 3]

    def test_unique_index_enforced(self, sess):
        from tidb_tpu.sql import SQLError

        sess.execute("CREATE TABLE u (id BIGINT PRIMARY KEY, a INT)")
        sess.execute("INSERT INTO u VALUES (1, 5), (2, 6)")
        sess.execute("CREATE UNIQUE INDEX ua ON u (a)")
        with pytest.raises(SQLError, match="duplicate entry"):
            sess.execute("INSERT INTO u VALUES (3, 5)")
        with pytest.raises(SQLError, match="duplicate entry"):
            sess.execute("UPDATE u SET a = 6 WHERE id = 1")
        sess.execute("INSERT INTO u VALUES (4, NULL), (5, NULL)")  # NULLs ok
        sess.execute("INSERT INTO u VALUES (6, 7)")

    def test_unique_backfill_detects_dup(self, sess):
        from tidb_tpu.sql import SQLError

        sess.execute("CREATE TABLE ub (id BIGINT PRIMARY KEY, a INT)")
        sess.execute("INSERT INTO ub VALUES (1, 5), (2, 5)")
        with pytest.raises(SQLError, match="backfill"):
            sess.execute("CREATE UNIQUE INDEX ua ON ub (a)")
        # rolled back: the index is gone
        assert not sess.catalog.table("ub").indices


class TestIndexLookup:
    """Non-covering selective index predicates use the index-lookup
    double-read (ref: pkg/executor/distsql.go IndexLookUpExecutor) instead
    of degrading to a full table scan (VERDICT r2 missing #3)."""

    def _mk(self):
        from tidb_tpu.sql import Session

        s = Session()
        s.execute("create table lk (id bigint primary key, k bigint, payload varchar(20), key ik (k))")
        rows = ",".join(f"({i}, {i % 50}, 'p{i}')" for i in range(1000))
        s.execute("insert into lk values " + rows)
        s.execute("analyze table lk")
        return s

    def test_plan_chooses_index_lookup(self):
        s = self._mk()
        r = s.execute("explain select payload from lk where k = 7")
        plan_text = "\n".join(str(x[0].val) for x in r.rows)
        assert "index_lookup(ik)" in plan_text, plan_text

    def test_results_match_full_scan(self):
        s = self._mk()
        got = sorted(str(x[0].val) for x in s.execute("select payload from lk where k = 7").rows)
        want = sorted(f"p{i}" for i in range(1000) if i % 50 == 7)
        assert got == want and len(got) == 20

    def test_reads_o_of_table_rows(self):
        """Exec summaries prove the second-phase scan touches only the
        looked-up handles, not the whole table."""
        s = self._mk()
        r = s.execute("explain analyze select payload from lk where k = 3")
        # rows: [label, actRows, tasks, time]; the TableScan push row
        scan_rows = None
        for row in r.rows:
            if "TableScan" in str(row[0].val):
                scan_rows = int(row[1].val)
        assert scan_rows is not None and scan_rows <= 20, scan_rows

    def test_unselective_predicate_stays_full_scan(self):
        s = self._mk()
        r = s.execute("explain select payload from lk where k >= 0")
        plan_text = "\n".join(str(x[0].val) for x in r.rows)
        assert "index_lookup" not in plan_text, plan_text
