"""MySQL wire protocol server + client (ref: pkg/server/conn.go handshake,
dispatch, writeResultSet; validated over a real TCP socket with the
framework's own text-protocol client)."""

import pytest

from tidb_tpu.server import MiniClient, MySQLServer, split_statements
from tidb_tpu.server.client import ClientError


@pytest.fixture(scope="module")
def server():
    srv = MySQLServer(port=0)
    srv.start_background()
    yield srv
    srv.close()


@pytest.fixture()
def client(server):
    c = MiniClient(server.host, server.port)
    yield c
    c.close()


def test_handshake_and_ping(client):
    assert client.ping()


def test_ddl_dml_select(client):
    assert client.query("CREATE TABLE st (id INT PRIMARY KEY, name VARCHAR(20), v INT)") == 0
    assert client.query("INSERT INTO st VALUES (1,'ann',10),(2,'bob',20)") == 2
    cols, rows = client.query("SELECT id, name, v FROM st ORDER BY id")
    assert cols == ["id", "name", "v"]
    assert rows == [["1", "ann", "10"], ["2", "bob", "20"]]


def test_null_and_expressions(client):
    client.query("CREATE TABLE sn (id INT PRIMARY KEY, x INT)")
    client.query("INSERT INTO sn VALUES (1, NULL), (2, 5)")
    cols, rows = client.query("SELECT x, x + 1 FROM sn ORDER BY id")
    assert rows == [[None, None], ["5", "6"]]


def test_aggregate_over_wire(client):
    client.query("CREATE TABLE sa (id INT PRIMARY KEY, v INT)")
    client.query("INSERT INTO sa VALUES (1,1),(2,2),(3,3)")
    cols, rows = client.query("SELECT count(*), sum(v), avg(v) FROM sa")
    assert rows[0][0] == "3"
    assert rows[0][1] == "6"


def test_error_packet(client):
    with pytest.raises(ClientError) as ei:
        client.query("SELECT * FROM no_such_table")
    assert "no_such_table" in str(ei.value)


def test_multi_statement(client):
    client.query("CREATE TABLE sm (id INT PRIMARY KEY)")
    got = client.query("INSERT INTO sm VALUES (1); INSERT INTO sm VALUES (2); SELECT count(*) FROM sm")
    assert got == (["count(*)"], [["2"]]) or got[1] == [["2"]]


def test_transactions_over_wire(server):
    c1 = MiniClient(server.host, server.port)
    c2 = MiniClient(server.host, server.port)
    try:
        c1.query("CREATE TABLE stx (id INT PRIMARY KEY, v INT)")
        c1.query("INSERT INTO stx VALUES (1, 10)")
        c1.query("BEGIN")
        c1.query("UPDATE stx SET v = 99 WHERE id = 1")
        _, rows = c2.query("SELECT v FROM stx")
        assert rows == [["10"]], "other connection must not see uncommitted write"
        c1.query("COMMIT")
        _, rows = c2.query("SELECT v FROM stx")
        assert rows == [["99"]]
    finally:
        c1.close()
        c2.close()


def test_auth_rejected():
    srv = MySQLServer(port=0, users={"alice": b"secret"})
    srv.start_background()
    try:
        with pytest.raises(ClientError):
            MiniClient(srv.host, srv.port, user="mallory", password="nope")
        c = MiniClient(srv.host, srv.port, user="alice", password="secret")
        assert c.ping()
        c.close()
        with pytest.raises(ClientError):
            MiniClient(srv.host, srv.port, user="alice", password="wrong")
    finally:
        srv.close()


def test_split_statements():
    assert split_statements("a; b;c") == ["a", "b", "c"]
    assert split_statements("insert into t values (';');") == ["insert into t values (';')"]
    assert split_statements('select ";;" ; x') == ['select ";;"', "x"]
    assert split_statements("select 1") == ["select 1"]
