"""Parser corpus ratchet (VERDICT r2 weak #8): every statement in the
reference's integration-test corpus replays through the parser; the pass
rate may only go UP. Skips cleanly when the reference tree is absent."""

import os
import sys

import pytest

CORPUS = "/root/reference/tests/integrationtest/t"
# measured 2026-07-30: 46515/47460 = 98.0%. Raise when it improves; never
# lower — a grammar regression must fail here.
RATCHET = 0.975


@pytest.mark.skipif(not os.path.isdir(CORPUS), reason="reference corpus not present")
def test_corpus_pass_rate_ratchet():
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    from parser_corpus import run_corpus

    r = run_corpus(CORPUS)
    assert r["total"] > 40_000, "corpus extraction collapsed"
    assert r["rate"] >= RATCHET, (
        f"parser corpus pass rate regressed: {r['ok']}/{r['total']} = "
        f"{r['rate']:.4f} < ratchet {RATCHET}; top failures: "
        f"{list(r['failures'].items())[:8]}"
    )
