"""Join executor in the DAG (VERDICT next #1): device hash join vs oracle
for every join type, nested build pipelines, TPC-H Q3 end-to-end through
distsql with broadcast build sides, and the overflow->oracle fallback."""

import numpy as np
import pytest

from tidb_tpu.chunk import Chunk
from tidb_tpu.codec import tablecodec
from tidb_tpu.distsql import KVRequest, full_table_ranges, select
from tidb_tpu.exec import (
    Aggregation,
    ColumnInfo,
    DAGRequest,
    Join,
    Selection,
    TableScan,
    TopN,
    run_dag_on_chunk,
    run_dag_on_chunks,
    run_dag_reference,
)
from tidb_tpu.exec.executor import datum_group_key
from tidb_tpu.expr import AggDesc, AggMode, col, func, lit
from tidb_tpu.store import TPUStore
from tidb_tpu.types import Datum, MyDecimal, MyTime, new_datetime, new_decimal, new_longlong, new_varchar

BOOL = new_longlong(notnull=True)

# lineitem-lite / orders-lite / customer-lite schemas
LFTS = [new_longlong(), new_decimal(10, 2), new_decimal(4, 2), new_datetime()]  # orderkey, price, disc, shipdate
OFTS = [new_longlong(), new_longlong(), new_datetime(), new_longlong()]  # orderkey, custkey, orderdate, shippriority
CFTS = [new_longlong(), new_varchar(10)]  # custkey, mktsegment

L = lambda i: col(i, LFTS[i])


def canon(rows):
    return sorted(tuple(datum_group_key(d) for d in r) for r in rows)


def rand_date(rng):
    return Datum.time(MyTime.from_ymd(1994 + int(rng.integers(3)), 1 + int(rng.integers(12)), 1 + int(rng.integers(28))))


def make_tables(nl=300, no=60, nc=20, seed=5, null_p=0.04):
    rng = np.random.default_rng(seed)

    def maybe(d):
        return Datum.NULL if rng.random() < null_p else d

    lrows = [
        [
            maybe(Datum.i64(int(rng.integers(0, no + 10)))),
            maybe(Datum.dec(MyDecimal(f"{int(rng.integers(100, 99999))/100:.2f}"))),
            maybe(Datum.dec(MyDecimal(f"0.0{int(rng.integers(10))}"))),
            maybe(rand_date(rng)),
        ]
        for _ in range(nl)
    ]
    orows = [
        [
            Datum.i64(k),
            maybe(Datum.i64(int(rng.integers(0, nc + 3)))),
            maybe(rand_date(rng)),
            Datum.i64(int(rng.integers(0, 3))),
        ]
        for k in range(no)
    ]
    crows = [
        [Datum.i64(k), maybe(Datum.string(["BUILDING", "AUTOMOBILE", "MACHINERY"][int(rng.integers(3))]))]
        for k in range(nc)
    ]
    return lrows, orows, crows


def scans():
    ls = TableScan(1, tuple(ColumnInfo(i + 1, ft) for i, ft in enumerate(LFTS)))
    os_ = TableScan(2, tuple(ColumnInfo(i + 1, ft) for i, ft in enumerate(OFTS)))
    cs = TableScan(3, tuple(ColumnInfo(i + 1, ft) for i, ft in enumerate(CFTS)))
    return ls, os_, cs


class TestJoinTypes:
    @pytest.mark.parametrize("jt", ["inner", "left_outer", "semi", "anti"])
    def test_parity(self, jt):
        lrows, orows, _ = make_tables()
        lch, och = Chunk.from_rows(LFTS, lrows), Chunk.from_rows(OFTS, orows)
        ls, os_, _ = scans()
        join = Join(build=(os_,), probe_keys=(L(0),), build_keys=(col(0, OFTS[0]),), join_type=jt)
        offs = tuple(range(8)) if jt in ("inner", "left_outer") else tuple(range(4))
        dag = DAGRequest((ls, join), output_offsets=offs)
        dev = run_dag_on_chunks(dag, [lch, och])
        ref = run_dag_reference(dag, [lch, och])
        assert canon(dev.rows()) == canon(ref)

    def test_string_key_join(self):
        _, _, crows = make_tables()
        c2 = [[r[1], Datum.i64(i)] for i, r in enumerate(crows)]  # (segment, id)
        fts2 = [CFTS[1], new_longlong()]
        pch = Chunk.from_rows(fts2, c2)
        bch = Chunk.from_rows([CFTS[1]], [[Datum.string("BUILDING")], [Datum.string("MACHINERY")]])
        ps = TableScan(5, (ColumnInfo(1, fts2[0]), ColumnInfo(2, fts2[1])))
        bs = TableScan(6, (ColumnInfo(1, CFTS[1]),))
        join = Join(build=(bs,), probe_keys=(col(0, fts2[0]),), build_keys=(col(0, CFTS[1]),), join_type="semi")
        dag = DAGRequest((ps, join), output_offsets=(0, 1))
        dev = run_dag_on_chunks(dag, [pch, bch])
        ref = run_dag_reference(dag, [pch, bch])
        assert canon(dev.rows()) == canon(ref)

    def test_key_type_mismatch_raises(self):
        lrows, orows, _ = make_tables(nl=10, no=5)
        lch, och = Chunk.from_rows(LFTS, lrows), Chunk.from_rows(OFTS, orows)
        ls, os_, _ = scans()
        # decimal(10,2) key vs int key: planner must cast; builder refuses
        join = Join(build=(os_,), probe_keys=(L(1),), build_keys=(col(0, OFTS[0]),), join_type="inner")
        dag = DAGRequest((ls, join), output_offsets=(0,))
        with pytest.raises(TypeError, match="join key class mismatch"):
            run_dag_on_chunks(dag, [lch, och])


def test_join_max_key_vs_null_collision():
    """A legitimate BIGINT-max join key must not collide with the +max mask
    used for NULL-key build rows (regression: unusable rows must sort
    strictly after usable rows of the max-key run)."""
    fts = [new_longlong()]
    mx = (1 << 63) - 1
    brows = [[Datum.NULL], [Datum.i64(mx)], [Datum.NULL], [Datum.i64(5)]]
    prows = [[Datum.i64(mx)], [Datum.i64(5)], [Datum.NULL]]
    pch, bch = Chunk.from_rows(fts, prows), Chunk.from_rows(fts, brows)
    ps = TableScan(1, (ColumnInfo(1, fts[0]),))
    bs = TableScan(2, (ColumnInfo(1, fts[0]),))
    for jt in ("inner", "left_outer", "semi", "anti"):
        join = Join(build=(bs,), probe_keys=(col(0, fts[0]),), build_keys=(col(0, fts[0]),), join_type=jt)
        offs = (0, 1) if jt in ("inner", "left_outer") else (0,)
        dag = DAGRequest((ps, join), output_offsets=offs)
        dev = run_dag_on_chunks(dag, [pch, bch])
        ref = run_dag_reference(dag, [pch, bch])
        assert canon(dev.rows()) == canon(ref), jt


def test_overflow_oracle_fallback():
    """Degenerate fan-out (all keys equal) exhausts the capacity retries;
    the spill analog (probe halving, exec/executor.py _spill_partitioned)
    then resolves it with device kernels only — no oracle needed."""
    from tidb_tpu.util import metrics

    n = 64
    fts = [new_longlong()]
    pch = Chunk.from_rows(fts, [[Datum.i64(1)] for _ in range(n)])
    bch = Chunk.from_rows(fts, [[Datum.i64(1)] for _ in range(n)])
    ps = TableScan(1, (ColumnInfo(1, fts[0]),))
    bs = TableScan(2, (ColumnInfo(1, fts[0]),))
    join = Join(build=(bs,), probe_keys=(col(0, fts[0]),), build_keys=(col(0, fts[0]),), join_type="inner")
    dag = DAGRequest((ps, join), output_offsets=(0, 1))
    out = run_dag_on_chunks(dag, [pch, bch], max_retries=0)  # 64*64 out rows >> 64 capacity
    assert out.num_rows() == n * n
    before = metrics.SPILL_PARTITIONS.value
    out2 = run_dag_on_chunks(dag, [pch, bch], max_retries=0, oracle_fallback=False)
    assert out2.num_rows() == n * n
    assert metrics.SPILL_PARTITIONS.value > before


def test_store_overflow_fallback_partial_agg():
    """Region cop task with degenerate join fan-out + Partial1 agg: the
    store's oracle fallback must handle partial mode (not just Complete)."""
    from tidb_tpu.store import CopRequest

    store = TPUStore()
    fts = [new_longlong()]
    n = 128
    for h in range(n):
        store.put_row(1, h, [1], [Datum.i64(1)], ts=5)  # all join keys equal
    bch = Chunk.from_rows(fts, [[Datum.i64(1)] for _ in range(n)])
    ps = TableScan(1, (ColumnInfo(1, fts[0]),))
    bs = TableScan(2, (ColumnInfo(1, fts[0]),))
    join = Join(build=(bs,), probe_keys=(col(0, fts[0]),), build_keys=(col(0, fts[0]),), join_type="inner")
    agg = Aggregation(group_by=(col(0, fts[0]),), aggs=(AggDesc("count", ()),), partial=True)
    dag = DAGRequest((ps, join, agg), output_offsets=(0, 1))
    region = store.cluster.regions_in_range(b"", b"\xff" * 20)[0]
    resp = store.coprocessor(CopRequest(dag, full_table_ranges(1), start_ts=100, region_id=region.region_id, region_epoch=region.epoch, aux_chunks=[bch]))
    assert resp.other_error is None, resp.other_error
    # 128*128 join rows >> capacity growth; fallback produced the state
    r = resp.chunk.rows()
    assert len(r) == 1 and r[0][0].val == n * n
    # summaries aligned with the device walk: [probe scan, build scan, join, agg]
    assert len(resp.exec_summaries) == 4


def q3_dag(partial: bool):
    """TPC-H Q3 shape: lineitem ⋈ (orders ⋈ customer) + filters + group agg.

    revenue = sum(l_extendedprice * (1 - l_discount)) grouped by
    (l_orderkey, o_orderdate, o_shippriority)."""
    ls, os_, cs = scans()
    cust_sel = Selection((func("eq", BOOL, col(1, CFTS[1]), lit("BUILDING", new_varchar(10))),))
    inner_join = Join(
        build=(cs, cust_sel),
        probe_keys=(col(1, OFTS[1]),),
        build_keys=(col(0, CFTS[0]),),
        join_type="inner",
    )
    build_pipeline = (os_, Selection((func("lt", BOOL, col(2, OFTS[2]), lit("1995-03-15", new_datetime())),)), inner_join)
    outer_join = Join(
        build=build_pipeline,
        probe_keys=(L(0),),
        build_keys=(col(0, OFTS[0]),),
        join_type="inner",
    )
    lineitem_sel = Selection((func("gt", BOOL, L(3), lit("1995-03-15", new_datetime())),))
    # post-join schema: l(4 cols) + o(4 cols) + c(2 cols)
    post = LFTS + OFTS + CFTS
    revenue = func(
        "mul",
        new_decimal(31, 4),
        col(1, post[1]),
        func("minus", new_decimal(12, 2), lit(1, new_longlong()), col(2, post[2])),
    )
    agg = Aggregation(
        group_by=(col(0, post[0]), col(6, post[6]), col(7, post[7])),
        aggs=(AggDesc("sum", (revenue,)),),
        partial=partial,
    )
    dag = DAGRequest((ls, lineitem_sel, outer_join, agg), output_offsets=(0, 1, 2, 3))
    return dag


def test_q3_single_chunk_parity():
    lrows, orows, crows = make_tables()
    chunks = [Chunk.from_rows(LFTS, lrows), Chunk.from_rows(OFTS, orows), Chunk.from_rows(CFTS, crows)]
    dag = q3_dag(partial=False)
    dev = run_dag_on_chunks(dag, chunks)
    ref = run_dag_reference(dag, chunks)
    assert len(ref) > 0, "Q3 test data must produce rows"
    assert canon(dev.rows()) == canon(ref)


def test_q3_through_distsql_broadcast():
    """Q3 over a region-split store: per-region broadcast join + Partial1
    agg, root Final merge + TopN — BASELINE config #5's execution shape."""
    lrows, orows, crows = make_tables(nl=400, no=80, nc=25)
    store = TPUStore()
    for h, r in enumerate(lrows):
        store.put_row(1, h, [1, 2, 3, 4], r, ts=10)
    for h, r in enumerate(orows):
        store.put_row(2, h, [1, 2, 3, 4], r, ts=10)
    for h, r in enumerate(crows):
        store.put_row(3, h, [1, 2], r, ts=10)
    for frac in (1, 2, 3):
        store.cluster.split(tablecodec.encode_row_key(1, frac * 100))

    # root: fetch broadcast operands (scan-only DAGs through distsql)
    ls, os_, cs = scans()
    odag = DAGRequest((os_,), output_offsets=tuple(range(4)))
    cdag = DAGRequest((cs,), output_offsets=tuple(range(2)))
    och = select(store, KVRequest(odag, full_table_ranges(2), start_ts=100)).merged()
    cch = select(store, KVRequest(cdag, full_table_ranges(3), start_ts=100)).merged()

    # per-region: join + Partial1 agg with broadcast aux chunks
    dag = q3_dag(partial=True)
    res = select(store, KVRequest(dag, full_table_ranges(1), start_ts=100, aux_chunks=[och, cch]))
    assert len(res.chunks) == 4  # one per region
    stacked = Chunk.concat(res.chunks)

    # root Final merge + TopN(revenue desc, orderdate) LIMIT 10
    pfts = stacked.field_types()  # [sum_state, l_orderkey, o_orderdate, o_shippriority]
    merge_agg = Aggregation(
        group_by=(col(1, pfts[1]), col(2, pfts[2]), col(3, pfts[3])),
        aggs=(AggDesc("sum", (col(0, pfts[0]),), mode=AggMode.Final),),
        merge=True,
    )
    topn = TopN(order_by=((col(0, pfts[0]), True), (col(2, pfts[2]), False)), limit=10)
    root = DAGRequest(
        (TableScan(0, tuple(ColumnInfo(i, ft) for i, ft in enumerate(pfts))), merge_agg, topn),
        output_offsets=(0, 1, 2, 3),
    )
    final = run_dag_on_chunk(root, stacked)

    # oracle: single-shot Complete Q3 + same TopN over all rows
    oracle_rows = run_dag_reference(
        q3_dag(partial=False), [Chunk.from_rows(LFTS, lrows), Chunk.from_rows(OFTS, orows), Chunk.from_rows(CFTS, crows)]
    )
    # oracle schema: [revenue, l_orderkey, o_orderdate, o_shippriority]
    ordered = sorted(
        oracle_rows,
        key=lambda r: (
            -(float(str(r[0].val)) if not r[0].is_null() else float("-inf")),
            r[2].val.packed if not r[2].is_null() else -1,
        ),
    )[:10]
    # compare revenue multisets of the top-10 (order ties can permute)
    got = sorted(str(r[0].val) for r in final.rows())
    want = sorted(str(r[0].val) for r in ordered)
    assert final.num_rows() == len(ordered)
    assert got == want, f"\ngot ={got}\nwant={want}"


def test_planner_marks_pk_build_unique():
    """Joins whose build keys are the build table's PK handle (or a unique
    index) carry build_unique=True; non-unique keys do not."""
    from tidb_tpu.exec.dag import Join
    from tidb_tpu.sql import Session

    s = Session()
    s.execute("create table orders (o_id bigint primary key, o_cust bigint)")
    s.execute("create table lineitem (l_id bigint primary key, l_oid bigint, qty bigint)")
    s.execute("create table tags (t bigint, name varchar(10))")
    s.execute("create unique index uq_t on tags (t)")
    s.execute("insert into orders values (1, 10), (2, 20)")
    s.execute("insert into lineitem values (1, 1, 5), (2, 1, 7), (3, 2, 9)")
    s.execute("insert into tags values (10, 'a'), (20, 'b')")

    from tidb_tpu.parser import parse_one
    from tidb_tpu.sql.planner import plan_select

    def joins_of(sql):
        plan = plan_select(parse_one(sql), s.catalog)
        return [e for e in plan.dag.executors if isinstance(e, Join)]

    js = joins_of("select count(*) from lineitem, orders where l_oid = o_id")
    assert len(js) == 1 and js[0].build_unique  # PK handle build key
    js = joins_of("select count(*) from orders, tags where o_cust = t")
    assert len(js) == 1 and js[0].build_unique  # unique index build key
    # self-join on a NON-unique column: neither side's key is unique
    js = joins_of("select count(*) from lineitem a, lineitem b where a.l_oid = b.l_oid")
    assert len(js) == 1 and not js[0].build_unique

    # end-to-end result through the unique fast path
    r = s.execute(
        "select o_id, sum(qty) from lineitem join orders on l_oid = o_id group by o_id order by o_id"
    )
    assert [(int(x[0].val), int(str(x[1].val))) for x in r.rows] == [(1, 12), (2, 9)]
