"""HTTP status API (VERDICT r4 missing #5; ref: pkg/server/http_status.go,
docs/tidb_http_api.md): /status, /schema, /ddl/history, /settings,
/metrics, /mvcc, /regions — served next to the MySQL listener."""

import json
import urllib.request

import pytest

from tidb_tpu.server.http_api import StatusServer
from tidb_tpu.sql import Session


@pytest.fixture()
def api():
    s = Session()
    s.execute("create table t (id bigint primary key, v bigint)")
    s.execute("insert into t values (1, 10), (2, 20)")
    s.execute("update t set v = 11 where id = 1")
    s.execute("create index iv on t (v)")
    srv = StatusServer(s).start_background()
    yield srv
    srv.close()


def _get(srv, path):
    with urllib.request.urlopen(f"http://{srv.host}:{srv.port}{path}") as r:
        return r.status, json.loads(r.read())


def test_status_and_schema(api):
    code, body = _get(api, "/status")
    assert code == 200 and "tidb_tpu" in body["version"]
    code, dbs = _get(api, "/schema")
    assert "test" in dbs and "mysql" in dbs
    code, tables = _get(api, "/schema/test")
    names = [t["name"]["O"] for t in tables]
    assert "t" in names
    code, ti = _get(api, "/schema/test/t")
    assert code == 200 and ti["pk_is_handle"] and len(ti["cols"]) == 2
    assert any(i["name"] == "iv" for i in ti["index_info"])


def test_ddl_history(api):
    code, jobs = _get(api, "/ddl/history")
    assert code == 200 and jobs
    assert any(j["type"] == "add index" or "index" in j["type"] for j in jobs) or len(jobs) >= 1


def test_settings_metrics(api):
    code, st = _get(api, "/settings")
    assert code == 200 and "max_execution_time" in st
    code, m = _get(api, "/metrics/json")
    assert code == 200 and "prometheus" in m and "samples" in m


def test_metrics_text_exposition(api):
    """GET /metrics is raw Prometheus text v0.0.4 — what a scraper parses."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    from scrape_check import validate

    with urllib.request.urlopen(f"http://{api.host}:{api.port}/metrics") as r:
        assert r.status == 200
        assert r.headers["Content-Type"].startswith("text/plain")
        assert "version=0.0.4" in r.headers["Content-Type"]
        text = r.read().decode()
    assert "# TYPE tidb_tpu_cop_requests_total counter" in text
    assert 'tidb_tpu_cop_duration_seconds_bucket{le="+Inf"}' in text
    assert validate(text) == []


def test_mvcc_versions(api):
    code, body = _get(api, "/mvcc/key/test/t/1")
    assert code == 200 and len(body["versions"]) >= 2  # insert + update
    try:
        _get(api, "/mvcc/key/test/t/999")
        raise AssertionError("expected 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404


def test_regions_meta(api):
    code, regions = _get(api, "/regions/meta")
    assert code == 200 and regions and "region_id" in regions[0]


def test_unknown_route_404(api):
    try:
        _get(api, "/nope")
        raise AssertionError("expected 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404
