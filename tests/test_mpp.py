"""MPP exchange data plane (ISSUE 18): fragment planner eligibility,
fragment-topology wire round-trip, dispatch tier fall-out (failpoints,
epoch retries), the non-unique radix build parity pin, and the
tidb_tpu_mpp_* metric families."""

import os
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from tidb_tpu.exec import Aggregation, ColumnInfo, DAGRequest, Join, Selection, TableScan
from tidb_tpu.expr import AggDesc, col, func, lit
from tidb_tpu.mpp.fragment import (
    EXCHANGE_HASH,
    EXCHANGE_PASSTHROUGH,
    ROOT_COLLECTOR,
    chunks_exchange_safe,
    fragment_plan,
)
from tidb_tpu.types import Datum, new_longlong, new_varchar
from tidb_tpu.util import failpoint
from tidb_tpu.util import metrics as M

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

I = new_longlong()


def _scan(tid):
    return TableScan(tid, (ColumnInfo(1, I), ColumnInfo(2, I)))


def _chain_dag(n_joins=2):
    """scan [Join(scan)]*n Aggregation(GROUP BY) — the Q3 chain shape."""
    exs = [_scan(10)]
    for j in range(n_joins):
        exs.append(Join(build=(_scan(11 + j),), probe_keys=(col(0, I),),
                        build_keys=(col(0, I),), join_type="inner"))
    exs.append(Aggregation(group_by=(col(1, I),),
                           aggs=(AggDesc("count", ()),)))
    return DAGRequest(tuple(exs), output_offsets=(0, 1))


class TestFragmentPlanner:
    def test_q3_chain_cuts_into_exchange_linked_fragments(self):
        fp = fragment_plan(_chain_dag(2), n_tasks=8)
        assert fp is not None and fp.n_tasks == 8
        # probe, 2 builds, 2 joins, final = 6 fragments
        assert len(fp.fragments) == 6
        probe = fp.fragments[0]
        assert probe.sender.exchange_type == EXCHANGE_HASH
        assert probe.sender.target_fragment == 2
        # every join fragment receives probe side first, build second
        join0 = fp.fragments[2]
        assert [r.source_fragment for r in join0.receivers] == [0, 1]
        join1 = fp.fragments[4]
        assert [r.source_fragment for r in join1.receivers] == [2, 3]
        final = fp.fragments[fp.root]
        assert final.sender.exchange_type == EXCHANGE_PASSTHROUGH
        assert final.sender.target_fragment == ROOT_COLLECTOR
        # the last join fragment re-exchanges by the GROUP key to final
        assert join1.sender.target_fragment == fp.root
        assert join1.sender.exchange_type == EXCHANGE_HASH

    def test_agg_shape_is_two_fragments(self):
        dag = DAGRequest(
            (_scan(10), Selection((func("gt", I, col(1, I), lit(2, I)),)),
             Aggregation(group_by=(col(0, I),), aggs=(AggDesc("count", ()),))),
            output_offsets=(0, 1))
        fp = fragment_plan(dag, n_tasks=4)
        assert fp is not None and len(fp.fragments) == 2
        assert fp.fragments[0].sender.exchange_type == EXCHANGE_HASH
        assert fp.fragments[1].sender.target_fragment == ROOT_COLLECTOR

    def test_join_inside_build_side_stays_off_mesh(self):
        inner = Join(build=(_scan(12),), probe_keys=(col(0, I),),
                     build_keys=(col(0, I),), join_type="inner")
        dag = DAGRequest(
            (_scan(10),
             Join(build=(_scan(11), inner), probe_keys=(col(0, I),),
                  build_keys=(col(0, I),), join_type="inner"),
             Aggregation(group_by=(col(1, I),), aggs=(AggDesc("count", ()),))),
            output_offsets=(0, 1))
        assert fragment_plan(dag, n_tasks=4) is None

    def test_scalar_agg_has_no_group_key_to_exchange(self):
        dag = DAGRequest(
            (_scan(10), Aggregation(group_by=(), aggs=(AggDesc("count", ()),))),
            output_offsets=(0,))
        assert fragment_plan(dag, n_tasks=4) is None

    def test_string_width_gate_measures_actual_bytes(self):
        from tidb_tpu.chunk import Chunk

        V = new_varchar(64)
        ok = Chunk.from_rows([V], [[Datum.string("x" * 32)]])
        wide = Chunk.from_rows([V], [[Datum.string("y" * 33)]])
        assert chunks_exchange_safe([ok])
        assert not chunks_exchange_safe([wide])


class TestFragmentWire:
    def test_topology_round_trips_byte_exactly(self):
        from tidb_tpu.codec.wire import decode_fragment_plan, encode_fragment_plan

        for dag in (_chain_dag(1), _chain_dag(3)):
            fp = fragment_plan(dag, n_tasks=8)
            raw = encode_fragment_plan(fp)
            fp2 = decode_fragment_plan(raw)
            # the decoded topology re-encodes to the SAME bytes (stable
            # numbering) and matches structurally
            assert encode_fragment_plan(fp2) == raw
            assert fp2.n_tasks == fp.n_tasks and fp2.root == fp.root
            assert len(fp2.fragments) == len(fp.fragments)
            for a, b in zip(fp.fragments, fp2.fragments):
                assert a.idx == b.idx
                assert a.sender.exchange_type == b.sender.exchange_type
                assert a.sender.target_fragment == b.sender.target_fragment
                assert len(a.sender.partition_keys) == len(b.sender.partition_keys)
                assert [r.source_fragment for r in a.receivers] == \
                       [r.source_fragment for r in b.receivers]
                assert len(a.executors) == len(b.executors)


def _q3_session(nl=600, no=40, nc=12):
    from tidb_tpu.sql import Session

    s = Session()
    s.execute("create table cust (c_id bigint primary key, seg varchar(2))")
    s.execute("insert into cust values " + ",".join(
        f"({i}, '{'AB'[i % 2]}')" for i in range(nc)))
    s.execute("create table ords (o_id bigint primary key, ckey bigint, odate bigint)")
    s.execute("insert into ords values " + ",".join(
        f"({i}, {i % nc}, {1000 + i % 9})" for i in range(no)))
    s.execute("create table items (i_id bigint primary key, oid bigint, v decimal(10,2))")
    s.execute("insert into items values " + ",".join(
        f"({i}, {(i * 3) % (no + 4)}, {i}.25)" for i in range(nl)))
    return s


Q3_SQL = ("select oid, count(*), sum(v) from items "
          "join ords on oid = o_id join cust on ckey = c_id "
          "where seg = 'B' and odate < 1007 group by oid")


def _canon(rows):
    return sorted(
        tuple(None if d.is_null() else str(d.val) for d in r) for r in rows)


class TestMppDispatch:
    def test_q3_chain_rides_mpp_byte_identical(self):
        s = _q3_session()
        m0, f0 = M.MPP_SELECTS.value, M.MPP_FRAGMENTS.value
        b0 = M.MPP_EXCHANGED_BYTES.value
        mpp_rows = s.execute(Q3_SQL).rows
        assert M.MPP_SELECTS.value == m0 + 1, "Q3 chain did not ride mpp"
        assert M.MPP_FRAGMENTS.value - f0 >= 2, "chain must plan >= 2 fragments"
        assert M.MPP_EXCHANGED_BYTES.value > b0
        s.execute("set tidb_enable_tpu_mesh = OFF")
        assert _canon(mpp_rows) == _canon(s.execute(Q3_SQL).rows)

    def test_allow_mpp_off_takes_the_mesh_shortcut(self):
        s = _q3_session()
        s.execute("set tidb_allow_mpp = OFF")
        m0, e0 = M.MPP_SELECTS.value, M.MESH_SELECTS.value
        rows = s.execute(Q3_SQL).rows
        assert M.MPP_SELECTS.value == m0
        assert M.MESH_SELECTS.value == e0 + 1
        s.execute("set tidb_enable_tpu_mesh = OFF")
        assert _canon(rows) == _canon(s.execute(Q3_SQL).rows)

    def test_dispatch_lost_is_a_counted_fallback(self):
        s = _q3_session()
        m0, fb0 = M.MPP_SELECTS.value, M.MPP_FALLBACKS.value
        with failpoint.enabled("mpp/dispatch-lost"):
            rows = s.execute(Q3_SQL).rows
        assert M.MPP_SELECTS.value == m0
        assert M.MPP_FALLBACKS.value == fb0 + 1
        s.execute("set tidb_enable_tpu_mesh = OFF")
        assert _canon(rows) == _canon(s.execute(Q3_SQL).rows)

    def test_exchange_stall_is_a_counted_fallback(self):
        s = _q3_session()
        m0, fb0 = M.MPP_SELECTS.value, M.MPP_FALLBACKS.value
        with failpoint.enabled("mpp/exchange-stall"):
            rows = s.execute(Q3_SQL).rows
        assert M.MPP_SELECTS.value == m0
        assert M.MPP_FALLBACKS.value == fb0 + 1
        s.execute("set tidb_enable_tpu_mesh = OFF")
        assert _canon(rows) == _canon(s.execute(Q3_SQL).rows)

    def test_mid_query_epoch_error_retries_typed(self):
        """A region-epoch error inside the mpp probe scan rides the same
        transparent re-split retry as the per-region path — typed region
        fall-out, never a torn result."""
        s = _q3_session()
        r0, m0 = M.DISTSQL_RETRIES.value, M.MPP_SELECTS.value
        with failpoint.enabled("cop-region-error", 1):
            rows = s.execute(Q3_SQL).rows
        assert M.DISTSQL_RETRIES.value == r0 + 1
        assert M.MPP_SELECTS.value == m0 + 1
        s.execute("set tidb_enable_tpu_mesh = OFF")
        assert _canon(rows) == _canon(s.execute(Q3_SQL).rows)

    def test_partitioned_probe_table_rides_mpp(self):
        from tidb_tpu.sql import Session

        s = Session()
        s.execute("create table pd (d_id bigint primary key, g bigint)")
        s.execute("insert into pd values " + ",".join(
            f"({i}, {i % 5})" for i in range(20)))
        s.execute("CREATE TABLE pt (a BIGINT PRIMARY KEY, g BIGINT, v BIGINT) "
                  "PARTITION BY HASH(a) PARTITIONS 3")
        s.execute("insert into pt values " + ",".join(
            f"({i}, {i % 5}, {i * 7 % 23})" for i in range(300)))
        sql = ("select pt.g, count(*), sum(v) from pt "
               "join pd on pt.g = d_id group by pt.g")
        m0 = M.MPP_SELECTS.value
        rows = s.execute(sql).rows
        assert M.MPP_SELECTS.value == m0 + 1, "partitioned probe did not ride mpp"
        s.execute("set tidb_enable_tpu_mesh = OFF")
        assert _canon(rows) == _canon(s.execute(sql).rows)

    def test_replica_served_probe_matches_row_store(self):
        s = _q3_session()
        s.execute("ALTER TABLE items SET COLUMNAR REPLICA 1")
        s.store.pd.tick()
        m0 = M.MPP_SELECTS.value
        rows = s.execute(Q3_SQL).rows
        assert M.MPP_SELECTS.value == m0 + 1
        r = s.execute("TRACE " + Q3_SQL).values()
        assert any("mpp.dispatch" in str(row[0]) for row in r)
        s.execute("set tidb_enable_tpu_mesh = OFF")
        assert _canon(rows) == _canon(s.execute(Q3_SQL).rows)

    def test_mpp_metric_families_pass_scrape_check(self):
        s = _q3_session()
        s.execute(Q3_SQL)
        text = M.REGISTRY.dump()
        for family in (
            "tidb_tpu_mpp_selects_total",
            "tidb_tpu_mpp_fragments_total",
            "tidb_tpu_mpp_tasks_total",
            "tidb_tpu_mpp_fallbacks_total",
            "tidb_tpu_mpp_exchanged_bytes_total",
        ):
            assert f"# TYPE {family}" in text, family
        from scrape_check import validate

        assert validate(text) == []


class TestNonUniqueRadixBuild:
    """The satellite pin: the radix kernel's expansion lift must agree
    with the monolithic join on duplicate build keys, escapes included."""

    @pytest.mark.parametrize("join_type", ["inner", "left_outer"])
    @pytest.mark.parametrize("strategy", ["search", "dense"])
    def test_duplicate_build_keys_match_monolithic(self, join_type, strategy):
        from tidb_tpu.expr.compile import CompVal
        from tidb_tpu.ops.join import hash_join
        from tidb_tpu.ops.radix_join import radix_hash_join

        rng = np.random.default_rng(11)
        nb, np_ = 512, 1024
        bk = rng.integers(0, 60, nb)          # heavy duplication
        pk = rng.integers(0, 80, np_)
        bvalid = rng.random(nb) < 0.9
        pvalid = rng.random(np_) < 0.9
        bnull = rng.random(nb) < 0.05
        pnull = rng.random(np_) < 0.05
        bcv = [CompVal(jnp.asarray(bk), jnp.asarray(bnull), I)]
        pcv = [CompVal(jnp.asarray(pk), jnp.asarray(pnull), I)]
        cap = 16384
        plan = (4, 256, 512, 2048)  # (n_parts, part_cap, probe_cap, esc_cap)
        res, _esc = radix_hash_join(
            bcv, pcv, jnp.asarray(bvalid), jnp.asarray(pvalid),
            join_type, cap, plan, strategy=strategy,
            build_unique=False, out_capacity=cap)
        ref = hash_join(bcv, pcv, jnp.asarray(bvalid), jnp.asarray(pvalid),
                        out_capacity=cap, join_type=join_type,
                        build_unique=False)
        assert not bool(res.overflow) and not bool(ref.overflow)

        def pairs(r):
            ov = np.asarray(r.out_valid)
            pi = np.asarray(r.probe_idx)[ov]
            bi = np.asarray(r.build_idx)[ov]
            nl = np.asarray(r.build_null)[ov]
            return sorted(
                (int(p), -1 if n else int(b)) for p, b, n in zip(pi, bi, nl))

        assert pairs(res) == pairs(ref)

    def test_non_unique_build_join_on_session_path(self):
        """End-to-end: a join keyed on a NON-unique build column rides the
        mpp tier and matches the root path."""
        s = _q3_session()
        sql = ("select ckey, count(*), sum(v) from items "
               "join ords on oid = ckey group by ckey")
        m0 = M.MPP_SELECTS.value
        rows = s.execute(sql).rows
        assert M.MPP_SELECTS.value == m0 + 1
        s.execute("set tidb_enable_tpu_mesh = OFF")
        assert _canon(rows) == _canon(s.execute(sql).rows)
