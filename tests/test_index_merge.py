"""Index merge (VERDICT r4 next #8; ref: pkg/executor/index_merge_reader.go
+ the planner's index-merge path generation): an OR of range predicates on
two different indexed columns unions the per-index handle sets before one
table read, gated by tidb_enable_index_merge / USE_INDEX_MERGE."""

from tidb_tpu.sql import Session


def _sess():
    s = Session()
    s.execute("create table t (id bigint primary key, a bigint, b bigint, w bigint)")
    s.execute("create index ia on t (a)")
    s.execute("create index ib on t (b)")
    s.execute("insert into t values " + ",".join(
        f"({i}, {i % 97}, {(i * 7) % 89}, {i})" for i in range(500)))
    return s


SQL = "select w from t where a = 5 or b = 11"


def _access(s, sql):
    return s.execute("explain " + sql).values()[0][0]


def test_sysvar_gates_index_merge():
    s = _sess()
    # ON by default (the reference's default since v5.4)
    assert "index_merge(union:ia,ib)" in _access(s, SQL)
    s.execute("set tidb_enable_index_merge = OFF")
    assert "index_merge" not in _access(s, SQL)


def test_hint_forces_and_disables():
    s = _sess()
    assert "index_merge" in _access(s, "select /*+ USE_INDEX_MERGE(t) */ w from t where a = 5 or b = 11")
    s.execute("set tidb_enable_index_merge = ON")
    assert "index_merge" not in _access(s, "select /*+ NO_INDEX_MERGE() */ w from t where a = 5 or b = 11")


def test_results_match_full_scan():
    s = _sess()
    want = s.execute(SQL + " order by w").values()
    s.execute("set tidb_enable_index_merge = ON")
    assert "index_merge" in _access(s, SQL)
    got = s.execute(SQL + " order by w").values()
    assert got == want and len(got) > 5


def test_non_or_predicates_unaffected():
    s = _sess()
    s.execute("set tidb_enable_index_merge = ON")
    # AND predicates keep the ordinary single-index paths
    a = _access(s, "select w from t where a = 5 and b = 11")
    assert "index_merge" not in a
