"""Transactions: Percolator 2PC engine (store/txn.py) + session txn layer
(ref: unistore/tikv/mvcc.go prewrite/commit, lockstore; client-go 2PC;
pkg/session LazyTxn; pkg/executor/union_scan.go read-your-writes)."""

import pytest

from tidb_tpu.sql.catalog import Catalog
from tidb_tpu.sql.session import Session, SQLError
from tidb_tpu.store import TPUStore
from tidb_tpu.store.txn import KeyIsLocked, TxnEngine, WriteConflict


@pytest.fixture()
def pair():
    store, cat = TPUStore(), Catalog()
    s1, s2 = Session(store, cat), Session(store, cat)
    s1.execute("CREATE TABLE t (id INT PRIMARY KEY, v INT)")
    s1.execute("INSERT INTO t VALUES (1,10),(2,20)")
    return s1, s2


# ---------------------------------------------------------------- engine


def test_engine_prewrite_commit():
    from tidb_tpu.store.kv import MemKV

    kv = MemKV()
    eng = TxnEngine(kv)
    eng.commit_txn({b"a": b"1", b"b": b"2"}, start_ts=10, commit_ts=11)
    assert kv.get(b"a", 11) == b"1" and kv.get(b"b", 11) == b"2"
    assert kv.get(b"a", 10) is None  # snapshot before commit_ts


def test_engine_write_conflict():
    from tidb_tpu.store.kv import MemKV

    kv = MemKV()
    eng = TxnEngine(kv)
    eng.commit_txn({b"a": b"1"}, 10, 15)
    with pytest.raises(WriteConflict):
        eng.commit_txn({b"a": b"2"}, 12, 16)  # started before the commit landed
    assert kv.get(b"a", 100) == b"1"
    assert not eng.locks  # failed prewrite leaves no locks behind


def test_engine_key_is_locked():
    from tidb_tpu.store.kv import MemKV

    eng = TxnEngine(MemKV())
    eng.prewrite({b"a": b"1"}, b"a", 10)
    with pytest.raises(KeyIsLocked):
        eng.prewrite({b"a": b"2"}, b"a", 12)
    eng.rollback([b"a"], 10)
    eng.commit_txn({b"a": b"2"}, 12, 13)


def test_engine_pessimistic_converts():
    from tidb_tpu.store.kv import MemKV

    kv = MemKV()
    eng = TxnEngine(kv)
    eng.acquire_pessimistic([b"a"], b"a", 10, 10)
    with pytest.raises(KeyIsLocked):
        eng.acquire_pessimistic([b"a"], b"a", 20, 20)
    eng.commit_txn({b"a": b"x"}, 10, 12)
    assert kv.get(b"a", 12) == b"x"
    assert not eng.locks


# ---------------------------------------------------------------- session


def test_read_your_writes_and_isolation(pair):
    s1, s2 = pair
    s1.execute("BEGIN")
    s1.execute("UPDATE t SET v = 99 WHERE id = 1")
    s1.execute("INSERT INTO t VALUES (3,30)")
    s1.execute("DELETE FROM t WHERE id = 2")
    assert s1.execute("SELECT * FROM t ORDER BY id").values() == [[1, 99], [3, 30]]
    # other session sees the pre-txn snapshot
    assert s2.execute("SELECT * FROM t ORDER BY id").values() == [[1, 10], [2, 20]]
    s1.execute("COMMIT")
    assert s2.execute("SELECT * FROM t ORDER BY id").values() == [[1, 99], [3, 30]]


def test_rollback_discards(pair):
    s1, s2 = pair
    s1.execute("BEGIN")
    s1.execute("UPDATE t SET v = 0")
    s1.execute("ROLLBACK")
    assert s1.execute("SELECT * FROM t ORDER BY id").values() == [[1, 10], [2, 20]]


def test_repeatable_read_snapshot(pair):
    s1, s2 = pair
    s1.execute("BEGIN")
    assert s1.execute("SELECT v FROM t WHERE id = 1").values() == [[10]]
    s2.execute("UPDATE t SET v = 77 WHERE id = 1")
    # repeatable read: s1 still sees its snapshot
    assert s1.execute("SELECT v FROM t WHERE id = 1").values() == [[10]]
    s1.execute("COMMIT")
    assert s1.execute("SELECT v FROM t WHERE id = 1").values() == [[77]]


def test_pessimistic_lock_conflict(pair):
    s1, s2 = pair
    s1.execute("BEGIN")
    s1.execute("UPDATE t SET v = 1 WHERE id = 2")
    with pytest.raises(SQLError, match="locked"):
        s2.execute("UPDATE t SET v = 2 WHERE id = 2")
    s1.execute("COMMIT")
    s2.execute("UPDATE t SET v = 2 WHERE id = 2")
    assert s2.execute("SELECT v FROM t WHERE id = 2").values() == [[2]]


def test_optimistic_write_conflict(pair):
    s1, s2 = pair
    s1.execute("SET tidb_txn_mode = 'optimistic'")
    s1.execute("BEGIN")
    s1.execute("UPDATE t SET v = 5 WHERE id = 1")
    s2.execute("UPDATE t SET v = 7 WHERE id = 1")
    with pytest.raises(SQLError, match="conflict"):
        s1.execute("COMMIT")
    assert s2.execute("SELECT v FROM t WHERE id = 1").values() == [[7]]


def test_select_for_update_locks(pair):
    s1, s2 = pair
    s1.execute("BEGIN")
    s1.execute("SELECT * FROM t WHERE id = 2 FOR UPDATE")
    with pytest.raises(SQLError):
        s2.execute("DELETE FROM t WHERE id = 2")
    s1.execute("ROLLBACK")
    s2.execute("DELETE FROM t WHERE id = 2")
    assert s2.execute("SELECT count(*) FROM t").values() == [[1]]


def test_txn_aggregate_sees_own_writes(pair):
    s1, _ = pair
    s1.execute("BEGIN")
    s1.execute("INSERT INTO t VALUES (10, 100), (11, 200)")
    got = s1.execute("SELECT count(*), sum(v) FROM t").values()
    assert [[got[0][0], int(str(got[0][1]))]] == [[4, 330]]
    s1.execute("COMMIT")
    assert s1.execute("SELECT count(*) FROM t").values() == [[4]]


def test_txn_join_with_dirty_table(pair):
    s1, _ = pair
    s1.execute("CREATE TABLE u (id INT PRIMARY KEY, tv INT)")
    s1.execute("INSERT INTO u VALUES (1, 10)")
    s1.execute("BEGIN")
    s1.execute("INSERT INTO u VALUES (2, 20)")
    got = s1.execute("SELECT t.id, u.id FROM t JOIN u ON t.v = u.tv ORDER BY t.id").values()
    assert got == [[1, 1], [2, 2]]
    s1.execute("ROLLBACK")
    got = s1.execute("SELECT t.id, u.id FROM t JOIN u ON t.v = u.tv ORDER BY t.id").values()
    assert got == [[1, 1]]


def test_ddl_implicitly_commits(pair):
    s1, s2 = pair
    s1.execute("BEGIN")
    s1.execute("UPDATE t SET v = 1 WHERE id = 1")
    s1.execute("CREATE TABLE z (a INT PRIMARY KEY)")  # implicit commit
    assert s2.execute("SELECT v FROM t WHERE id = 1").values() == [[1]]
    assert s1.txn is None


def test_begin_commits_previous(pair):
    s1, s2 = pair
    s1.execute("BEGIN")
    s1.execute("UPDATE t SET v = 42 WHERE id = 1")
    s1.execute("BEGIN")  # implicitly commits the first txn
    assert s2.execute("SELECT v FROM t WHERE id = 1").values() == [[42]]
    s1.execute("ROLLBACK")


def test_unique_check_sees_buffer(pair):
    s1, _ = pair
    s1.execute("CREATE UNIQUE INDEX uv ON t (v)")
    s1.execute("BEGIN")
    s1.execute("INSERT INTO t VALUES (5, 50)")
    with pytest.raises(SQLError, match="duplicate"):
        s1.execute("INSERT INTO t VALUES (6, 50)")  # dup within the buffer
    s1.execute("ROLLBACK")


def test_failed_statement_in_autocommit_leaves_no_trace(pair):
    s1, _ = pair
    with pytest.raises(SQLError):
        s1.execute("INSERT INTO t VALUES (1, 999)")  # dup pk
    assert s1.execute("SELECT count(*) FROM t").values() == [[2]]
    assert not s1.store.txn.locks


class TestReplaceIgnoreUnique:
    """ADVICE r2: REPLACE INTO / INSERT IGNORE on a SECONDARY unique-index
    conflict must follow MySQL semantics (ref: executor/replace.go
    removeRow; insert IGNORE duplicate-as-warning), not raise."""

    def _mk(self):
        from tidb_tpu.sql import Session

        s = Session()
        s.execute("create table t (id bigint primary key, u bigint, v varchar(10), unique key uk (u))")
        s.execute("insert into t values (1, 10, 'a'), (2, 20, 'b')")
        return s

    def test_replace_deletes_conflicting_row(self):
        s = self._mk()
        r = s.execute("replace into t values (3, 10, 'c')")  # conflicts with id=1 on uk
        assert r.affected == 2  # one delete + one insert
        rows = sorted((int(x[0].val), int(x[1].val), str(x[2].val)) for x in s.execute("select * from t").rows)
        assert rows == [(2, 20, "b"), (3, 10, "c")]

    def test_replace_conflicting_pk_and_unique(self):
        s = self._mk()
        # conflicts with id=2 on PK AND id=1 on uk: both rows die
        r = s.execute("replace into t values (2, 10, 'z')")
        assert r.affected == 3  # MySQL: uk-row delete + in-place delete+insert
        rows = sorted((int(x[0].val), int(x[1].val)) for x in s.execute("select * from t").rows)
        assert rows == [(2, 10)]

    def test_insert_ignore_skips_unique_conflict(self):
        s = self._mk()
        r = s.execute("insert ignore into t values (3, 10, 'c'), (4, 40, 'd')")
        assert r.affected == 1  # only (4,40,'d') lands
        rows = sorted(int(x[0].val) for x in s.execute("select * from t").rows)
        assert rows == [1, 2, 4]


class TestNamedSavepoints:
    def test_rollback_to_savepoint(self):
        from tidb_tpu.sql import Session

        s = Session()
        s.execute("create table sv (a bigint primary key)")
        s.execute("begin")
        s.execute("insert into sv values (1)")
        s.execute("savepoint sp1")
        s.execute("insert into sv values (2)")
        s.execute("rollback to savepoint sp1")
        s.execute("commit")
        rows = sorted(int(r[0].val) for r in s.execute("select * from sv").rows)
        assert rows == [1]

    def test_rollback_to_missing_savepoint_errors(self):
        from tidb_tpu.sql import Session

        s = Session()
        s.execute("create table sv2 (a bigint)")
        s.execute("begin")
        try:
            s.execute("rollback to savepoint nope")
            raise AssertionError("expected error")
        except Exception as exc:
            assert "does not exist" in str(exc)
        s.execute("rollback")
