"""ALTER TABLE + DDL job framework (ref: pkg/ddl online schema change,
ddl_api.go actions, ADMIN SHOW DDL JOBS)."""

import pytest

from tidb_tpu.sql.session import Session, SQLError


@pytest.fixture()
def sess():
    s = Session()
    s.execute("CREATE TABLE t (id INT PRIMARY KEY, v INT)")
    s.execute("INSERT INTO t VALUES (1,10),(2,20)")
    return s


def test_add_column_origin_default(sess):
    sess.execute("ALTER TABLE t ADD COLUMN w INT DEFAULT 7")
    assert sess.execute("SELECT * FROM t ORDER BY id").values() == [[1, 10, 7], [2, 20, 7]]
    sess.execute("INSERT INTO t VALUES (3, 30, 99)")
    # origin default only fills pre-ADD rows; filters see it too
    assert sess.execute("SELECT id FROM t WHERE w = 7 ORDER BY id").values() == [[1], [2]]
    # point-get path fills the default as well
    assert sess.execute("SELECT w FROM t WHERE id = 1").values() == [[7]]


def test_add_column_nullable(sess):
    sess.execute("ALTER TABLE t ADD COLUMN z VARCHAR(5)")
    assert sess.execute("SELECT z FROM t WHERE id = 1").values() == [[None]]


def test_add_column_not_null_implicit_default(sess):
    sess.execute("ALTER TABLE t ADD COLUMN n INT NOT NULL")
    assert sess.execute("SELECT n FROM t WHERE id = 1").values() == [[0]]


def test_add_column_positions(sess):
    sess.execute("ALTER TABLE t ADD COLUMN a INT FIRST")
    sess.execute("ALTER TABLE t ADD COLUMN b INT AFTER id")
    assert [c.name for c in sess.catalog.table("t").columns] == ["a", "id", "b", "v"]


def test_drop_column(sess):
    sess.execute("ALTER TABLE t ADD COLUMN w INT DEFAULT 1")
    sess.execute("ALTER TABLE t DROP COLUMN w")
    assert [c.name for c in sess.catalog.table("t").columns] == ["id", "v"]
    with pytest.raises(SQLError):
        sess.execute("ALTER TABLE t DROP COLUMN id")  # handle column


def test_drop_indexed_column_rejected(sess):
    sess.execute("CREATE INDEX iv ON t (v)")
    with pytest.raises(SQLError, match="indexed"):
        sess.execute("ALTER TABLE t DROP COLUMN v")


def test_change_column_rename_keeps_values(sess):
    sess.execute("ALTER TABLE t CHANGE COLUMN v volume BIGINT")
    assert sess.execute("SELECT volume FROM t WHERE id = 2").values() == [[20]]


def test_modify_incompatible_rejected(sess):
    with pytest.raises(SQLError, match="reinterpret"):
        sess.execute("ALTER TABLE t MODIFY COLUMN v VARCHAR(10)")


def test_alter_add_drop_index(sess):
    sess.execute("ALTER TABLE t ADD UNIQUE INDEX uv (v)")
    with pytest.raises(SQLError, match="duplicate"):
        sess.execute("INSERT INTO t VALUES (9, 10)")
    sess.execute("ALTER TABLE t DROP INDEX uv")
    sess.execute("INSERT INTO t VALUES (9, 10)")


def test_rename_table(sess):
    sess.execute("RENAME TABLE t TO t2")
    assert sess.execute("SELECT count(*) FROM t2").values() == [[2]]
    with pytest.raises(Exception):
        sess.execute("SELECT * FROM t")


def test_ddl_jobs_recorded(sess):
    sess.execute("ALTER TABLE t ADD COLUMN w INT")
    sess.execute("CREATE INDEX iv ON t (v)")
    rows = sess.execute("ADMIN SHOW DDL JOBS").values()
    assert rows[0][1] == "add index" and rows[0][4] == "synced"
    assert rows[1][1] == "add column"
    # index job stepped through the online states
    job = sess.catalog.ddl_jobs.jobs[-1]
    assert job.states_seen == ["delete_only", "write_only", "write_reorg", "public"]


def test_failed_job_recorded_cancelled(sess):
    with pytest.raises(SQLError):
        sess.execute("ALTER TABLE t MODIFY COLUMN v VARCHAR(5)")
    job = sess.catalog.ddl_jobs.jobs[-1]
    assert job.state == "cancelled" and "reinterpret" in job.error


def test_admin_check_table(sess):
    sess.execute("CREATE INDEX iv ON t (v)")
    sess.execute("ADMIN CHECK TABLE t")  # consistent: no raise
    # corrupt the index: drop one entry behind the session's back
    from tidb_tpu.codec import tablecodec
    from tidb_tpu.types import Datum

    meta = sess.catalog.table("t")
    idx = meta.indices[0]
    key = tablecodec.encode_index_key(meta.table_id, idx.index_id, [Datum.i64(10), Datum.i64(1)])
    sess.store.put_index(key, None, sess.store.next_ts())
    with pytest.raises(SQLError, match="missing"):
        sess.execute("ADMIN CHECK TABLE t")


def test_alter_in_txn_implicitly_commits(sess):
    sess.execute("BEGIN")
    sess.execute("UPDATE t SET v = 1 WHERE id = 1")
    sess.execute("ALTER TABLE t ADD COLUMN w INT")
    assert sess.txn is None
    assert sess.execute("SELECT v FROM t WHERE id = 1").values() == [[1]]
