"""tidb-vet static-analysis suite + lockwatch runtime detector (ISSUE 7):
every pass flags its true-positive fixture in tests/vet_fixtures/, the
live tree is clean, suppression markers work, the CLI contract holds
(exit 0 on the tree, nonzero on the corpus, --json parses), and the PR-6
chaos storm + PD concurrent dispatch run under lockwatch with zero
lock-order cycles and zero unguarded annotated accesses."""

import json
import os
import subprocess
import sys
import threading

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(__file__), "vet_fixtures")
sys.path.insert(0, os.path.join(REPO, "tools"))

from tidb_tpu import analysis
from tidb_tpu.analysis import guards, lockwatch
from tidb_tpu.analysis.common import SourceFile


def _fixture(name: str) -> SourceFile:
    return SourceFile.load(os.path.join(FIXTURES, name), repo=REPO)


def _messages(findings):
    return [f.render() for f in findings]


# ------------------------------------------------- fixtures: true positives

class TestFixtureCorpus:
    def test_jit_purity_flags_fixture(self):
        found = analysis.run_pass("jit-purity", [_fixture("jit_purity_bad.py")])
        names = " ".join(_messages(found))
        assert len(found) == 3, names
        assert "BAD_CONST" in names and "BAD_DERIVED" in names
        assert "mutates global jax config" in names

    def test_lock_discipline_flags_fixture(self):
        found = analysis.run_pass("lock-discipline", [_fixture("lock_bad.py")])
        msgs = _messages(found)
        assert len(found) == 2, msgs
        assert any("written outside" in m for m in msgs)
        assert any("read outside" in m for m in msgs)
        # the `# requires: _mu` helper and the locked bump stay clean
        assert not any(":15:" in m or ":24:" in m for m in msgs)

    def test_error_taxonomy_flags_fixture(self):
        found = analysis.run_pass("error-taxonomy", [_fixture("error_bad.py")])
        assert len(found) == 2
        assert all("bare `raise" in m for m in _messages(found))

    def test_metrics_flags_fixture(self):
        found = analysis.run_pass("metrics", [_fixture("metrics_bad.py")])
        msgs = " | ".join(_messages(found))
        for expect in (
            "registered more than once",
            "must end `_total`",
            "invalid metric name",
            "must not claim the counter suffix",
            "takes 1 label value(s)",
            "is a labeled family",
            "has no .labels()",
            "not a registered instrument",
        ):
            assert expect in msgs, f"missing {expect!r} in {msgs}"

    def test_wire_parity_flags_fixture(self):
        found = analysis.run_pass("wire-parity", [_fixture("bad_wire.py")])
        msgs = " | ".join(_messages(found))
        assert "encode_orphan has no matching decode_orphan" in msgs
        assert "field-kind mismatch" in msgs and "'f64'" in msgs
        assert "sub-structure mismatch" in msgs

    def test_failpoints_flags_fixture(self):
        from tidb_tpu.analysis import failpoints

        uses = failpoints._scan(
            failpoints._USE, [os.path.join(FIXTURES, "failpoint_bad.py")])
        assert "vetfix/undefined-name" in uses
        _findings, sites = failpoints.analyze()
        # the armed name resolves to no site — exactly what the pass flags
        assert "vetfix/undefined-name" not in sites
        # ... and the live-tree run must NOT scan the fixture corpus
        assert not any("vet_fixtures" in w for ws in sites.values() for w in ws)


# ------------------------------------------------- live tree + suppression

class TestLiveTree:
    def test_every_pass_clean_on_the_tree(self):
        findings = analysis.run_all()
        assert findings == [], "\n".join(_messages(findings))

    def test_suppression_marker_drops_finding(self, tmp_path):
        p = tmp_path / "sup.py"
        p.write_text(
            "import threading\n\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._mu = threading.Lock()\n"
            "        self.v = 0  # guarded_by: _mu\n\n"
            "    def racy(self):\n"
            "        return self.v  # vet: ignore[lock-discipline]\n\n"
            "    def racy2(self):\n"
            "        return self.v\n"
        )
        sf = SourceFile.load(str(p), repo=str(tmp_path))
        found = analysis.run_pass("lock-discipline", [sf])
        assert len(found) == 1 and found[0].line == 12  # only the unmarked one

    def test_guard_collection_reads_the_conventions(self):
        sf = SourceFile.load(os.path.join(REPO, "tidb_tpu", "store", "store.py"))
        g = guards.collect(sf.tree, sf.lines)
        assert g.classes["TPUStore"]["_cop_cache"] == "_cop_lock"
        assert g.classes["TPUStore"]["_write_ver"] == "_cop_lock"
        sf = SourceFile.load(os.path.join(REPO, "tidb_tpu", "store", "kv.py"))
        g = guards.collect(sf.tree, sf.lines)
        assert g.classes["MemKV"]["_data"] == "lock"
        assert ("MemKV", "_ensure_sorted") in g.requires


# ------------------------------------------------- CLI contract

class TestVetCLI:
    def _run(self, *args):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "vet.py"), *args],
            capture_output=True, text=True, timeout=300,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )

    def test_clean_tree_exits_zero_and_json_parses(self):
        r = self._run("--json")
        assert r.returncode == 0, r.stdout + r.stderr
        assert json.loads(r.stdout) == []

    def test_fixture_corpus_exits_nonzero_with_diffable_json(self):
        fixtures = sorted(
            os.path.join(FIXTURES, f) for f in os.listdir(FIXTURES) if f.endswith(".py"))
        r = self._run("--json", "--files", *fixtures)
        assert r.returncode == 1, r.stdout + r.stderr
        findings = json.loads(r.stdout)
        assert findings, "fixture corpus produced no findings"
        assert {f["pass"] for f in findings} >= {
            "jit-purity", "lock-discipline", "error-taxonomy", "metrics", "wire-parity"}
        assert all({"path", "line", "pass", "message"} <= set(f) for f in findings)


# ------------------------------------------------- lockwatch: unit seeds

class _Shared:
    def __init__(self):
        self._mu = threading.Lock()
        self.val = 0


class TestLockwatch:
    def test_seeded_lock_order_cycle_is_reported(self):
        with lockwatch.watching(guard_tree=False) as w:
            a = threading.Lock()
            b = threading.Lock()
            assert isinstance(a, lockwatch.WatchedLock)  # repo frame: wrapped
            with a:
                with b:
                    pass
            with b:
                with a:  # the ABBA inversion
                    pass
        rep = w.report()
        assert rep["cycles"], rep["edges"]
        cyc = rep["cycles"][0]
        assert any("test_vet.py" in site for site in cyc)

    def test_consistent_order_reports_no_cycle(self):
        with lockwatch.watching(guard_tree=False) as w:
            a = threading.Lock()
            b = threading.Lock()
            for _ in range(3):
                with a:
                    with b:
                        pass
        assert w.report()["cycles"] == []

    def test_seeded_unguarded_write_is_reported(self):
        with lockwatch.watching(guard_tree=False) as w:
            obj = _Shared()
            w.guard_class(_Shared, {"val": "_mu"})
            obj.val = 1  # first (exclusive) thread: exempt

            def racy():
                obj.val = 2  # second thread, guard not held

            t = threading.Thread(target=racy)
            t.start()
            t.join()
            assert w.violations, "unguarded cross-thread write not reported"
            v = w.violations[0]
            assert v.attr == "val" and v.guard == "_mu" and v.mode == "write"

            n = len(w.violations)

            def disciplined():
                with obj._mu:
                    obj.val = 3

            t = threading.Thread(target=disciplined)
            t.start()
            t.join()
            assert len(w.violations) == n  # guarded access stays quiet

    def test_rlock_reentry_adds_no_edge(self):
        with lockwatch.watching(guard_tree=False) as w:
            r = threading.RLock()
            with r:
                with r:
                    pass
        assert w.report()["edges"] == []

    def test_stdlib_locks_stay_real(self):
        with lockwatch.watching(guard_tree=False):
            import queue

            q = queue.Queue()  # stdlib frames create its internal locks
            q.put(1)
            assert q.get() == 1
            assert not isinstance(q.mutex, lockwatch.WatchedLock)


# ------------------------------------ lockwatch over the tier-1 workloads

def test_chaos_storm_under_lockwatch():
    """ISSUE 7 acceptance: the PR-6 seeded chaos storm — store outage,
    busy storm, heartbeat blackout, not-leader flaps, operator timeouts —
    runs under the runtime detector with ZERO lock-order cycles and ZERO
    unguarded annotated accesses, while keeping its own invariants."""
    from chaos import run_chaos

    with lockwatch.watching() as w:
        report = run_chaos(seed=11, statements=40)
    rep = w.report()
    assert rep["cycles"] == [], rep["cycles"]
    assert rep["violations"] == [], "\n".join(rep["violations"])
    assert report["wrong_results"] == [] and report["untyped_errors"] == []
    # the detector actually observed the engine's locking (not a no-op run)
    assert rep["edges"], "lockwatch saw no lock nesting at all"


def test_pd_concurrent_dispatch_under_lockwatch():
    """PD tick thread vs dispatch pool under the detector: splits, moves
    and failpoint storms while scans run — no cycles, no violations."""
    from tidb_tpu.codec import tablecodec
    from tidb_tpu.distsql.dispatch import KVRequest, full_table_ranges, select
    from tidb_tpu.exec.dag import ColumnInfo, DAGRequest, TableScan
    from tidb_tpu.types import Datum, new_longlong
    from tidb_tpu.util import failpoint

    TID, rows = 31, 160
    with lockwatch.watching() as w:
        from tidb_tpu.store import TPUStore

        store = TPUStore()
        for h in range(rows):
            store.put_row(TID, h, [1], [Datum.i64(h)], ts=10)
        for i in range(1, 8):
            store.cluster.split(tablecodec.encode_row_key(TID, i * rows // 8))
        store.cluster.set_stores(4)
        store.cluster.scatter()
        dag = DAGRequest((TableScan(TID, (ColumnInfo(1, new_longlong()),)),),
                         output_offsets=(0,))
        stop = threading.Event()
        errors: list = []
        counts: list = []

        def scanner():
            while not stop.is_set():
                try:
                    res = select(store, KVRequest(dag, full_table_ranges(TID), 100))
                    counts.append(sum(c.num_rows() for c in res.chunks))
                except Exception as exc:  # noqa: BLE001
                    errors.append(exc)
                    return

        threads = [threading.Thread(target=scanner, daemon=True) for _ in range(3)]
        for t in threads:
            t.start()
        try:
            with failpoint.enabled("pd/heartbeat-lost"), \
                 failpoint.enabled("pd/operator-timeout"):
                for _ in range(4):
                    store.pd.tick()
            for _ in range(6):
                store.pd.tick()
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=30)
    assert errors == []
    assert counts and all(c == rows for c in counts)
    rep = w.report()
    assert rep["cycles"] == [], rep["cycles"]
    assert rep["violations"] == [], "\n".join(rep["violations"])
    assert rep["edges"]
