"""tidb-vet static-analysis suite + lockwatch runtime detector (ISSUE 7
seeded it; ISSUE 9 added the interprocedural dataflow passes, the jaxpr
auditor, the stale-suppression audit and result caching): every pass
flags its true-positive fixture in tests/vet_fixtures/, the live tree is
clean, suppression markers work (and rot is flagged), the CLI contract
holds (exit 0 on the tree, nonzero on the corpus, --json parses,
baseline/diff round-trips), and the chaos / PD / replication-catch-up
storms run under lockwatch with zero lock-order cycles and zero
unguarded annotated accesses."""

import json
import os
import subprocess
import sys
import threading

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(__file__), "vet_fixtures")
sys.path.insert(0, os.path.join(REPO, "tools"))

from tidb_tpu import analysis
from tidb_tpu.analysis import dataflow, guards, jaxaudit, lockwatch, suppress_audit
from tidb_tpu.analysis.common import SourceFile


def _fixture(name: str) -> SourceFile:
    return SourceFile.load(os.path.join(FIXTURES, name), repo=REPO)


def _messages(findings):
    return [f.render() for f in findings]


# ------------------------------------------------- fixtures: true positives

class TestFixtureCorpus:
    def test_jit_purity_flags_fixture(self):
        found = analysis.run_pass("jit-purity", [_fixture("jit_purity_bad.py")])
        names = " ".join(_messages(found))
        assert len(found) == 3, names
        assert "BAD_CONST" in names and "BAD_DERIVED" in names
        assert "mutates global jax config" in names

    def test_lock_discipline_flags_fixture(self):
        found = analysis.run_pass("lock-discipline", [_fixture("lock_bad.py")])
        msgs = _messages(found)
        assert len(found) == 2, msgs
        assert any("written outside" in m for m in msgs)
        assert any("read outside" in m for m in msgs)
        # the `# requires: _mu` helper and the locked bump stay clean
        assert not any(":15:" in m or ":24:" in m for m in msgs)

    def test_dataflow_snapshot_flags_fixture(self):
        found = analysis.run_pass("dataflow-snapshot", [_fixture("dataflow_snapshot_bad.py")])
        msgs = _messages(found)
        assert len(found) == 4, msgs
        assert any("max_ts" in m and "NEWEST version" in m for m in msgs)
        assert any("latest-version ts (12345)" in m for m in msgs)
        assert any("does not flow" in m for m in msgs)
        # the disciplined reads stay clean: req.start_ts direct (line 30)
        # and start_ts flowing through helper_scan (lines 35/38)
        assert not any(f.line in (30, 35, 38) for f in found)

    def test_dataflow_backoff_flags_fixture(self):
        found = analysis.run_pass("dataflow-backoff", [_fixture("dataflow_backoff_bad.py")])
        msgs = _messages(found)
        assert len(found) == 2, msgs
        assert any("never consults a Backoffer budget" in m for m in msgs)
        assert any("raw time.sleep" in m for m in msgs)

    def test_dataflow_closure_findings_not_duplicated(self, tmp_path):
        """A violation inside a nested closure reports ONCE: the closure
        is its own FuncInfo, so the parent's walk must not re-cover it
        (review fix: both used to report the same line)."""
        p = tmp_path / "m.py"
        p.write_text(
            "import time\n\n"
            "def select(store, req):  # vet: request-path-root\n"
            "    def worker():\n"
            "        time.sleep(0.05)\n"
            "    run(worker)\n")
        sf = SourceFile.load(str(p), repo=str(tmp_path))
        found = analysis.run_pass("dataflow-backoff", [sf])
        assert len(found) == 1 and found[0].line == 5, _messages(found)

    def test_escape_lexical_floor_covers_control_plane(self, tmp_path):
        """The old error-taxonomy guarantee survives the promotion: a
        bare raise in a dispatch/store/PD-layer file is a finding even
        OUTSIDE the request cone (PD ticks/schedulers)."""
        (tmp_path / "tidb_tpu" / "pd").mkdir(parents=True)
        root = tmp_path / "root.py"
        root.write_text("def select(store, req):  # vet: request-path-root\n"
                        "    return None\n")
        sched = tmp_path / "tidb_tpu" / "pd" / "sched.py"
        sched.write_text("def tick():\n    raise RuntimeError('boom')\n")
        files = [SourceFile.load(str(root), repo=str(tmp_path)),
                 SourceFile.load(str(sched), repo=str(tmp_path))]
        found = analysis.run_pass("dataflow-error-escape", files)
        assert len(found) == 1, _messages(found)
        assert "dispatch/store/PD layer" in found[0].message

    def test_dataflow_escape_flags_fixture(self):
        found = analysis.run_pass("dataflow-error-escape", [_fixture("dataflow_escape_bad.py")])
        msgs = _messages(found)
        assert len(found) == 2, msgs
        assert any("bare `raise RuntimeError` escapes" in m for m in msgs)
        assert any("RegionTimeoutError" in m and "session boundary" in m for m in msgs)

    def test_jax_audit_flags_fixture(self):
        found = analysis.run_pass("jax-audit", [_fixture("jaxaudit_bad.py")])
        msgs = _messages(found)
        assert len(found) == 2, msgs
        assert any("float64 leaked into an integer-only program" in m for m in msgs)
        assert any("DIFFERENT jaxprs" in m and "closure-captured" in m for m in msgs)

    def test_metrics_flags_fixture(self):
        found = analysis.run_pass("metrics", [_fixture("metrics_bad.py")])
        msgs = " | ".join(_messages(found))
        for expect in (
            "registered more than once",
            "must end `_total`",
            "invalid metric name",
            "must not claim the counter suffix",
            "takes 1 label value(s)",
            "is a labeled family",
            "has no .labels()",
            "not a registered instrument",
        ):
            assert expect in msgs, f"missing {expect!r} in {msgs}"

    def test_wire_parity_flags_fixture(self):
        found = analysis.run_pass("wire-parity", [_fixture("bad_wire.py")])
        msgs = " | ".join(_messages(found))
        assert "encode_orphan has no matching decode_orphan" in msgs
        assert "field-kind mismatch" in msgs and "'f64'" in msgs
        assert "sub-structure mismatch" in msgs

    def test_failpoints_flags_fixture(self):
        from tidb_tpu.analysis import failpoints

        uses = failpoints._scan(
            failpoints._USE, [os.path.join(FIXTURES, "failpoint_bad.py")])
        assert "vetfix/undefined-name" in uses
        _findings, sites = failpoints.analyze()
        # the armed name resolves to no site — exactly what the pass flags
        assert "vetfix/undefined-name" not in sites
        # ... and the live-tree run must NOT scan the fixture corpus
        assert not any("vet_fixtures" in w for ws in sites.values() for w in ws)


# ------------------------------------------------- live tree + suppression

class TestLiveTree:
    def test_every_pass_clean_on_the_tree(self):
        findings = analysis.run_all()
        assert findings == [], "\n".join(_messages(findings))

    def test_suppression_marker_drops_finding(self, tmp_path):
        p = tmp_path / "sup.py"
        p.write_text(
            "import threading\n\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._mu = threading.Lock()\n"
            "        self.v = 0  # guarded_by: _mu\n\n"
            "    def racy(self):\n"
            "        return self.v  # vet: ignore[lock-discipline]\n\n"
            "    def racy2(self):\n"
            "        return self.v\n"
        )
        sf = SourceFile.load(str(p), repo=str(tmp_path))
        found = analysis.run_pass("lock-discipline", [sf])
        assert len(found) == 1 and found[0].line == 12  # only the unmarked one

    def test_stale_suppression_flagged(self, tmp_path):
        p = tmp_path / "s.py"
        p.write_text("x = 1  # vet: ignore[jit-purity]\n"
                     "y = 2  # vet: ignore[no-such-pass]\n")
        sf = SourceFile.load(str(p), repo=str(tmp_path))
        out = suppress_audit.audit(
            [sf], used_markers=set(), ran_passes={"jit-purity"},
            known_passes={"jit-purity"})
        msgs = [f.message for f in out]
        assert len(out) == 2, msgs
        assert any("stale suppression" in m for m in msgs)
        assert any("unknown pass 'no-such-pass'" in m for m in msgs)

    def test_live_suppression_not_flagged(self, tmp_path):
        """A marker that actually suppressed a finding is live — the
        audit subtracts the used-marker set the filter recorded."""
        from tidb_tpu.analysis.common import filter_suppressed

        p = tmp_path / "s.py"
        p.write_text(
            "import threading\n\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._mu = threading.Lock()\n"
            "        self.v = 0  # guarded_by: _mu\n\n"
            "    def racy(self):\n"
            "        return self.v  # vet: ignore[lock-discipline]\n")
        sf = SourceFile.load(str(p), repo=str(tmp_path))
        from tidb_tpu.analysis import lock_discipline

        used: set = set()
        kept = filter_suppressed(lock_discipline.run([sf]), {sf.rel: sf}, used)
        assert kept == [] and used  # the marker earned its keep
        out = suppress_audit.audit(
            [sf], used_markers=used, ran_passes={"lock-discipline"},
            known_passes={"lock-discipline"})
        assert out == [], [f.message for f in out]

    def test_pass_not_run_gives_no_verdict(self, tmp_path):
        p = tmp_path / "s.py"
        p.write_text("x = 1  # vet: ignore[jit-purity]\n")
        sf = SourceFile.load(str(p), repo=str(tmp_path))
        out = suppress_audit.audit(
            [sf], used_markers=set(), ran_passes=set(),
            known_passes={"jit-purity"})
        assert out == []

    def test_guard_collection_reads_the_conventions(self):
        sf = SourceFile.load(os.path.join(REPO, "tidb_tpu", "store", "store.py"))
        g = guards.collect(sf.tree, sf.lines)
        assert g.classes["TPUStore"]["_cop_cache"] == "_cop_lock"
        assert g.classes["TPUStore"]["_write_ver"] == "_cop_lock"
        sf = SourceFile.load(os.path.join(REPO, "tidb_tpu", "store", "kv.py"))
        g = guards.collect(sf.tree, sf.lines)
        assert g.classes["MemKV"]["_data"] == "lock"
        assert ("MemKV", "_ensure_sorted") in g.requires


# ------------------------------------------------- CLI contract

class TestVetCLI:
    def _run(self, *args):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "vet.py"), *args],
            capture_output=True, text=True, timeout=300,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )

    def test_clean_tree_exits_zero_and_json_parses(self):
        r = self._run("--json")
        assert r.returncode == 0, r.stdout + r.stderr
        assert json.loads(r.stdout) == []

    def test_fixture_corpus_exits_nonzero_with_diffable_json(self):
        fixtures = sorted(
            os.path.join(FIXTURES, f) for f in os.listdir(FIXTURES) if f.endswith(".py"))
        r = self._run("--json", "--files", *fixtures)
        assert r.returncode == 1, r.stdout + r.stderr
        findings = json.loads(r.stdout)
        assert findings, "fixture corpus produced no findings"
        assert {f["pass"] for f in findings} >= {
            "jit-purity", "lock-discipline", "metrics", "wire-parity",
            "dataflow-snapshot", "dataflow-backoff", "dataflow-error-escape",
            "jax-audit"}
        assert all({"path", "line", "pass", "message"} <= set(f) for f in findings)

    def test_only_accepts_globs(self):
        r = self._run("--only", "dataflow-*")
        assert r.returncode == 0, r.stdout + r.stderr
        assert "dataflow-snapshot" in r.stdout and "dataflow-error-escape" in r.stdout

    def test_only_suppressions_runs_the_full_suite(self):
        """The stale-marker audit needs every pass's verdict: --only
        suppressions triggers a full run and reports just that pass
        (review fix: it used to be rejected as an unknown pass that
        --list itself advertised)."""
        r = self._run("--only", "suppressions")
        assert r.returncode == 0, r.stdout + r.stderr
        with pytest.raises(ValueError, match="run_all"):
            analysis.run_pass("suppressions")

    def test_diff_is_a_multiset(self):
        """A SECOND instance of an identical-message defect in the same
        file is a NEW finding (review fix: a set-diff waved it through
        the gate)."""
        import vet

        a = {"path": "p.py", "line": 3, "pass": "x", "message": "m"}
        a2 = {"path": "p.py", "line": 9, "pass": "x", "message": "m"}
        new, fixed = vet._diff_sets([a], [a, a2])
        assert new == [a2] and fixed == []
        new, fixed = vet._diff_sets([a, a2], [a])
        assert new == [] and len(fixed) == 1

    def test_diff_missing_baseline_is_exit_2(self, tmp_path):
        r = self._run("--files", os.path.join(FIXTURES, "jaxaudit_bad.py"),
                      "--diff", str(tmp_path / "nope.json"))
        assert r.returncode == 2, r.stdout + r.stderr
        assert "unusable baseline" in r.stderr

    def test_baseline_diff_roundtrip(self, tmp_path):
        """--baseline emits stable sorted JSON; --diff against that
        baseline reports {"new": [], "fixed": []} and exits 0; a finding
        absent from the baseline exits 1 as `new` (the cross-commit
        regression contract)."""
        fixtures = sorted(
            os.path.join(FIXTURES, f) for f in os.listdir(FIXTURES) if f.endswith(".py"))
        base = tmp_path / "base.json"
        r = self._run("--files", *fixtures, "--baseline", str(base))
        assert r.returncode == 0, r.stdout + r.stderr
        recorded = json.loads(base.read_text())
        assert recorded and recorded == sorted(
            recorded, key=lambda d: (d["path"], d["line"], d["pass"]))
        r = self._run("--files", *fixtures, "--diff", str(base))
        assert r.returncode == 0, r.stdout + r.stderr
        d = json.loads(r.stdout)
        assert d == {"new": [], "fixed": []}
        # an EMPTY baseline makes every corpus finding "new" -> exit 1
        empty = tmp_path / "empty.json"
        empty.write_text("[]")
        r = self._run("--files", *fixtures, "--diff", str(empty))
        assert r.returncode == 1
        d = json.loads(r.stdout)
        assert d["fixed"] == [] and len(d["new"]) == len(recorded)


# ------------------------------------------- dataflow engine: unit seeds

class TestDataflowEngine:
    @pytest.fixture(scope="class")
    def graph(self):
        from tidb_tpu.analysis.common import load_files, py_files

        return dataflow.graph_for(load_files(py_files("tidb_tpu")))

    def test_call_graph_resolves_dispatch_into_the_store(self, graph):
        fi = graph.funcs["tidb_tpu/distsql/dispatch.py::_run_one_task"]
        callees = {c.qname for c, _ in fi.callees}
        assert "tidb_tpu/store/store.py::TPUStore.coprocessor" in callees

    def test_request_path_cone_is_nontrivial(self, graph):
        reach = graph.reachable(graph.request_roots())
        assert "tidb_tpu/store/store.py::TPUStore.region_chunk" in reach
        assert "tidb_tpu/store/kv.py::MemKV.scan" in reach
        # the PD's control-plane scan is NOT on the request path: its
        # latest-version split-key read is legitimate there
        assert "tidb_tpu/pd/core.py::PlacementDriver._split_key" not in reach

    def test_start_ts_fact_reaches_the_kv_seam(self, graph):
        dataflow.TaintAnalysis(graph)
        fi = graph.funcs["tidb_tpu/store/store.py::TPUStore._scan_region_kvs"]
        assert dataflow.TS in fi.facts.get("start_ts", set())

    def test_escape_tracks_typed_errors_to_the_boundary(self, graph):
        dataflow.EscapeAnalysis(graph)
        b = graph.boundaries()[0]
        names = {t[1] for t in b.escapes if isinstance(t, tuple)}
        # the mapped dispatch errors DO reach the boundary (the mapping
        # is what keeps them out of the findings, not their absence)
        assert "RegionUnavailableError" in names or "CopInternalError" in names


# --------------------------------------------------- jax-audit: live view

class TestJaxAudit:
    def test_catalog_covers_every_builder_path(self):
        names = {n for n, _dag, _nb, _caps in jaxaudit.live_catalog()}
        assert names == {"selection", "hashagg", "streamagg", "topn", "hashjoin",
                         "radix_join", "partial_scalar_agg", "partial_hashagg",
                         "columnar_scan"}

    def test_mesh_variants_audited(self):
        """The mesh-tier shard_map programs are walked too: every catalog
        shape the dispatch planner would route to the mesh gets a
        mesh-{kind} trace through the jaxpr checks."""
        from tidb_tpu.distsql.planner import mesh_merge_kind

        kinds = {n: mesh_merge_kind(dag) for n, dag, _nb, _caps in jaxaudit.live_catalog()}
        assert kinds["partial_scalar_agg"] == "scalar"
        assert kinds["partial_hashagg"] == "group"
        assert kinds["topn"] == "topn"
        assert kinds["radix_join"] == "group"  # the radix join meshes too
        assert kinds["hashagg"] is None  # Complete mode stays off-mesh

    def test_live_catalog_is_clean(self):
        assert jaxaudit.run() == []

    def test_vmap_axis_checker_fires_on_drift(self):
        class _A:
            def __init__(self, shape, dtype):
                self.shape, self.dtype = shape, dtype

        single = [_A((8,), "int64")]
        good = [_A((jaxaudit._VMAP_BATCH, 8), "int64")]
        assert jaxaudit._check_vmap_axis("x", single, good, ("f", 1)) == []
        dropped = [_A((8,), "int64")]  # region axis lost
        retyped = [_A((jaxaudit._VMAP_BATCH, 8), "int32")]
        assert jaxaudit._check_vmap_axis("x", single, dropped, ("f", 1))
        assert jaxaudit._check_vmap_axis("x", single, retyped, ("f", 1))


# ----------------------------------------------------- result cache

class TestVetCache:
    def test_roundtrip_and_invalidation(self, tmp_path, monkeypatch):
        from tidb_tpu.analysis.common import Finding
        from tidb_tpu.analysis.vetcache import VetCache

        monkeypatch.setenv("TIDB_TPU_VET_CACHE", str(tmp_path / "c.json"))
        src = tmp_path / "m.py"
        src.write_text("x = 1\n")
        sf = SourceFile.load(str(src), repo=str(tmp_path))
        c = VetCache()
        key = VetCache.file_key("p", "sha1", sf)
        c.put(key, [Finding("m.py", 1, "p", "msg")])
        c.save()
        c2 = VetCache()
        hit = c2.get(key)
        assert hit and hit[0].render() == "m.py:1: [p] msg"
        # editing the file changes (mtime, sha) -> a different key: miss
        src.write_text("x = 2\n")
        sf2 = SourceFile.load(str(src), repo=str(tmp_path))
        assert VetCache.file_key("p", "sha1", sf2) != key
        assert c2.get(VetCache.file_key("p", "sha1", sf2)) is None

    def test_run_all_cold_equals_warm(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TIDB_TPU_VET_CACHE", str(tmp_path / "c.json"))
        cold = analysis.run_all()
        warm = analysis.run_all()
        assert [f.render() for f in cold] == [f.render() for f in warm] == []


# ------------------------------------------------- lockwatch: unit seeds

class _Shared:
    def __init__(self):
        self._mu = threading.Lock()
        self.val = 0


class TestLockwatch:
    def test_seeded_lock_order_cycle_is_reported(self):
        with lockwatch.watching(guard_tree=False) as w:
            a = threading.Lock()
            b = threading.Lock()
            assert isinstance(a, lockwatch.WatchedLock)  # repo frame: wrapped
            with a:
                with b:
                    pass
            with b:
                with a:  # the ABBA inversion
                    pass
        rep = w.report()
        assert rep["cycles"], rep["edges"]
        cyc = rep["cycles"][0]
        assert any("test_vet.py" in site for site in cyc)

    def test_consistent_order_reports_no_cycle(self):
        with lockwatch.watching(guard_tree=False) as w:
            a = threading.Lock()
            b = threading.Lock()
            for _ in range(3):
                with a:
                    with b:
                        pass
        assert w.report()["cycles"] == []

    def test_seeded_unguarded_write_is_reported(self):
        with lockwatch.watching(guard_tree=False) as w:
            obj = _Shared()
            w.guard_class(_Shared, {"val": "_mu"})
            obj.val = 1  # first (exclusive) thread: exempt

            def racy():
                obj.val = 2  # second thread, guard not held

            t = threading.Thread(target=racy)
            t.start()
            t.join()
            assert w.violations, "unguarded cross-thread write not reported"
            v = w.violations[0]
            assert v.attr == "val" and v.guard == "_mu" and v.mode == "write"

            n = len(w.violations)

            def disciplined():
                with obj._mu:
                    obj.val = 3

            t = threading.Thread(target=disciplined)
            t.start()
            t.join()
            assert len(w.violations) == n  # guarded access stays quiet

    def test_rlock_reentry_adds_no_edge(self):
        with lockwatch.watching(guard_tree=False) as w:
            r = threading.RLock()
            with r:
                with r:
                    pass
        assert w.report()["edges"] == []

    def test_stdlib_locks_stay_real(self):
        with lockwatch.watching(guard_tree=False):
            import queue

            q = queue.Queue()  # stdlib frames create its internal locks
            q.put(1)
            assert q.get() == 1
            assert not isinstance(q.mutex, lockwatch.WatchedLock)


# ------------------------------------ lockwatch over the tier-1 workloads

def test_chaos_storm_under_lockwatch():
    """ISSUE 7 acceptance: the PR-6 seeded chaos storm — store outage,
    busy storm, heartbeat blackout, not-leader flaps, operator timeouts —
    runs under the runtime detector with ZERO lock-order cycles and ZERO
    unguarded annotated accesses, while keeping its own invariants."""
    from chaos import run_chaos

    with lockwatch.watching() as w:
        report = run_chaos(seed=11, statements=40)
    rep = w.report()
    assert rep["cycles"] == [], rep["cycles"]
    assert rep["violations"] == [], "\n".join(rep["violations"])
    assert report["wrong_results"] == [] and report["untyped_errors"] == []
    # the detector actually observed the engine's locking (not a no-op run)
    assert rep["edges"], "lockwatch saw no lock nesting at all"


def test_replication_catchup_under_lockwatch():
    """ISSUE 9 satellite: the replication CATCH-UP path under the runtime
    detector — leader transfers, the resolved-ts catch-up driver and a
    follower-read dispatch pool racing while writes land and the
    apply-lag failpoint wedges/unwedges followers. Zero lock-order
    cycles, zero unguarded annotated accesses, scans never lose rows,
    and once writers stop and the wedge lifts, catch-up drains every
    follower's safe_ts lag to zero."""
    from tidb_tpu.codec import tablecodec
    from tidb_tpu.distsql.dispatch import KVRequest, full_table_ranges, select
    from tidb_tpu.exec.dag import ColumnInfo, DAGRequest, TableScan
    from tidb_tpu.types import Datum, new_longlong
    from tidb_tpu.util import failpoint

    TID, rows, regions = 37, 120, 6
    with lockwatch.watching() as w:
        from tidb_tpu.store import TPUStore

        store = TPUStore()
        for h in range(rows):
            store.put_row(TID, h, [1], [Datum.i64(h)], ts=10)
        for i in range(1, regions):
            store.cluster.split(tablecodec.encode_row_key(TID, i * rows // regions))
        store.cluster.set_stores(4)
        store.cluster.scatter()
        dag = DAGRequest((TableScan(TID, (ColumnInfo(1, new_longlong()),)),),
                         output_offsets=(0,))
        stop = threading.Event()
        errors: list = []
        counts: list = []

        def scanner():
            # snapshot at 50: the seed rows (ts=10) are visible, the
            # writer's versions (TSO >= 100) are not — every scan must
            # return exactly the seed rows, through transfers, wedged
            # followers and DataIsNotReady fallbacks
            while not stop.is_set():
                try:
                    res = select(store, KVRequest(
                        dag, full_table_ranges(TID), 50, replica_read="follower"))
                    counts.append(sum(c.num_rows() for c in res.chunks))
                except Exception as exc:  # noqa: BLE001
                    errors.append(exc)
                    return

        def writer():
            h = rows
            while not stop.is_set():
                store.put_row(TID, h, [1], [Datum.i64(h)], ts=store.next_ts())
                h += 1

        def transferrer():
            k = 0
            while not stop.is_set():
                for r in store.cluster.regions():
                    folls = store.cluster.followers_of(r.region_id)
                    if folls:
                        store.cluster.transfer_leader(
                            r.region_id, folls[k % len(folls)])
                k += 1

        def catcher_up():
            while not stop.is_set():
                store.replication.catch_up()

        threads = [threading.Thread(target=t, daemon=True)
                   for t in (scanner, scanner, writer, transferrer, catcher_up)]
        for t in threads:
            t.start()
        import time

        # phase 1: wedge one follower's apply loop (lag accumulates)
        with failpoint.enabled("replica/apply-lag", {1}):
            time.sleep(0.6)
        # phase 2: wedge lifted — the catch-up thread drains the lag
        time.sleep(0.6)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        # quiesced: a few explicit catch-up rounds must zero every lag
        for _ in range(5):
            store.replication.catch_up()
        lags = store.replication.lag_view()
    rep = w.report()
    assert rep["cycles"] == [], rep["cycles"]
    assert rep["violations"] == [], "\n".join(rep["violations"])
    assert not errors, errors
    assert counts and all(c == rows for c in counts)
    assert all(v == 0 for v in lags.values()), lags
    assert rep["edges"], "lockwatch saw no lock nesting at all"


def test_pd_concurrent_dispatch_under_lockwatch():
    """PD tick thread vs dispatch pool under the detector: splits, moves
    and failpoint storms while scans run — no cycles, no violations."""
    from tidb_tpu.codec import tablecodec
    from tidb_tpu.distsql.dispatch import KVRequest, full_table_ranges, select
    from tidb_tpu.exec.dag import ColumnInfo, DAGRequest, TableScan
    from tidb_tpu.types import Datum, new_longlong
    from tidb_tpu.util import failpoint

    TID, rows = 31, 160
    with lockwatch.watching() as w:
        from tidb_tpu.store import TPUStore

        store = TPUStore()
        for h in range(rows):
            store.put_row(TID, h, [1], [Datum.i64(h)], ts=10)
        for i in range(1, 8):
            store.cluster.split(tablecodec.encode_row_key(TID, i * rows // 8))
        store.cluster.set_stores(4)
        store.cluster.scatter()
        dag = DAGRequest((TableScan(TID, (ColumnInfo(1, new_longlong()),)),),
                         output_offsets=(0,))
        stop = threading.Event()
        errors: list = []
        counts: list = []

        def scanner():
            while not stop.is_set():
                try:
                    res = select(store, KVRequest(dag, full_table_ranges(TID), 100))
                    counts.append(sum(c.num_rows() for c in res.chunks))
                except Exception as exc:  # noqa: BLE001
                    errors.append(exc)
                    return

        threads = [threading.Thread(target=scanner, daemon=True) for _ in range(3)]
        for t in threads:
            t.start()
        try:
            with failpoint.enabled("pd/heartbeat-lost"), \
                 failpoint.enabled("pd/operator-timeout"):
                for _ in range(4):
                    store.pd.tick()
            for _ in range(6):
                store.pd.tick()
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=30)
    assert errors == []
    assert counts and all(c == rows for c in counts)
    rep = w.report()
    assert rep["cycles"] == [], rep["cycles"]
    assert rep["violations"] == [], "\n".join(rep["violations"])
    assert rep["edges"]
