"""Prepared statements, privileges, and the extension registry
(ref: pkg/planner/core/plan_cache.go prepared statements,
pkg/privilege/privileges, pkg/extension)."""

import pytest

from tidb_tpu.sql.catalog import Catalog
from tidb_tpu.sql.session import Session, SQLError
from tidb_tpu.store import TPUStore


@pytest.fixture()
def env():
    store, cat = TPUStore(), Catalog()
    root = Session(store, cat)
    root.execute("CREATE TABLE t (id INT PRIMARY KEY, v INT)")
    root.execute("INSERT INTO t VALUES (1,10),(2,20)")
    return store, cat, root


# ------------------------------------------------------------- prepared


def test_prepare_execute_deallocate(env):
    _, _, s = env
    s.execute("PREPARE q FROM 'SELECT v FROM t WHERE id = ?'")
    s.execute("SET @a = 2")
    assert s.execute("EXECUTE q USING @a").values() == [[20]]
    s.execute("SET @a = 1")
    assert s.execute("EXECUTE q USING @a").values() == [[10]]
    s.execute("DEALLOCATE PREPARE q")
    with pytest.raises(SQLError):
        s.execute("EXECUTE q USING @a")


def test_prepare_param_count_mismatch(env):
    _, _, s = env
    s.execute("PREPARE q FROM 'SELECT v FROM t WHERE id = ? AND v > ?'")
    s.execute("SET @a = 1")
    with pytest.raises(SQLError, match="parameters"):
        s.execute("EXECUTE q USING @a")


def test_prepare_dml(env):
    _, _, s = env
    s.execute("PREPARE ins FROM 'INSERT INTO t VALUES (?, ?)'")
    s.execute("SET @i = 5")
    s.execute("SET @v = 50")
    s.execute("EXECUTE ins USING @i, @v")
    assert s.execute("SELECT v FROM t WHERE id = 5").values() == [[50]]


def test_prepare_template_reusable(env):
    _, _, s = env
    s.execute("PREPARE q FROM 'SELECT count(*) FROM t WHERE v >= ?'")
    for val, want in ((10, 2), (15, 1), (99, 0)):
        s.execute(f"SET @x = {val}")
        assert s.execute("EXECUTE q USING @x").values() == [[want]]


# ------------------------------------------------------------- privileges


def test_user_lifecycle_and_grants(env):
    store, cat, root = env
    root.execute("CREATE USER 'alice' IDENTIFIED BY 'pw'")
    root.execute("GRANT SELECT ON t TO 'alice'")
    alice = Session(store, cat)
    alice.user = "alice"
    assert alice.execute("SELECT count(*) FROM t").values() == [[2]]
    with pytest.raises(SQLError, match="INSERT"):
        alice.execute("INSERT INTO t VALUES (9,90)")
    root.execute("GRANT INSERT ON t TO 'alice'")
    alice.execute("INSERT INTO t VALUES (9,90)")
    root.execute("REVOKE SELECT ON t FROM 'alice'")
    with pytest.raises(SQLError, match="SELECT"):
        alice.execute("SELECT 1 FROM t")
    with pytest.raises(SQLError, match="SUPER"):
        alice.execute("CREATE USER 'bob'")
    root.execute("DROP USER 'alice'")
    with pytest.raises(SQLError):
        root.execute("DROP USER 'alice'")
    root.execute("DROP USER IF EXISTS 'alice'")


def test_user_name_with_backslash_mirrors_cleanly(env):
    """ADVICE r5 low, pinned: the CREATE/DROP USER mirror SQL is built by
    string concatenation and the lexer honors backslash escapes — a name
    ending in a lone backslash used to swallow the closing quote, break
    the mirrored statement, and leave mysql.user missing the row (the
    failure was silently swallowed). Backslashes must escape too."""
    store, cat, root = env
    name = "back\\slash\\"  # embedded AND trailing backslash
    root.execute("CREATE USER 'back\\\\slash\\\\' IDENTIFIED BY 'pw'")
    rows = root.execute("SELECT User, Host FROM `mysql.user`").values()
    assert [name, "%"] in rows, rows
    # re-run under IF NOT EXISTS: delete-then-insert must keep ONE row
    root.execute("CREATE USER IF NOT EXISTS 'back\\\\slash\\\\'")
    rows = root.execute("SELECT User FROM `mysql.user`").values()
    assert rows.count([name]) == 1
    root.execute("DROP USER 'back\\\\slash\\\\'")
    rows = root.execute("SELECT User FROM `mysql.user`").values()
    assert [name] not in rows


def test_global_and_db_grants(env):
    store, cat, root = env
    root.execute("CREATE USER 'carol'")
    root.execute("GRANT SELECT ON *.* TO 'carol'")
    carol = Session(store, cat)
    carol.user = "carol"
    assert carol.execute("SELECT count(*) FROM t").values() == [[2]]
    with pytest.raises(SQLError):
        carol.execute("DROP TABLE t")


def test_select_without_from_needs_no_priv(env):
    store, cat, root = env
    root.execute("CREATE USER 'dave'")
    dave = Session(store, cat)
    dave.user = "dave"
    assert dave.execute("SELECT 1 + 1").values() == [[2]]


# ------------------------------------------------------------- extension


def test_extension_function(env):
    from tidb_tpu.sql.extension import EXTENSIONS
    from tidb_tpu.types import new_longlong

    _, _, s = env
    EXTENSIONS.register_function("tri_ple", lambda x: None if x is None else x * 3, new_longlong())
    try:
        got = s.execute("SELECT tri_ple(v) FROM t ORDER BY id").values()
        assert got == [[30], [60]]
        # inside WHERE too (host-only, root-side evaluation)
        assert s.execute("SELECT id FROM t WHERE tri_ple(v) = 60").values() == [[2]]
    finally:
        EXTENSIONS.unregister_function("tri_ple")


def test_extension_function_cannot_shadow_builtin():
    from tidb_tpu.sql.extension import EXTENSIONS

    with pytest.raises(ValueError):
        EXTENSIONS.register_function("concat", lambda *a: "")


def test_extension_sysvar(env):
    from tidb_tpu.sql.extension import EXTENSIONS
    from tidb_tpu.sql.sysvar import DEFINITIONS

    _, _, s = env
    if "x_custom_flag" not in DEFINITIONS:
        EXTENSIONS.register_sysvar("x_custom_flag", "default_val")
    assert s.sysvars.get("x_custom_flag") == "default_val"
    s.execute("SET x_custom_flag = 'on2'")
    assert s.sysvars.get("x_custom_flag") == "on2"
