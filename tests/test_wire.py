"""Serialized coprocessor seam (VERDICT next #7): DAGRequest and chunks
round-trip through bytes with zero result diff; dispatch can route every
cop request through the bytes boundary (the sidecar shape)."""

import numpy as np
import pytest

from tidb_tpu.chunk import Chunk
from tidb_tpu.codec import tablecodec
from tidb_tpu.codec.wire import (
    decode_chunk,
    decode_cop_response,
    decode_dag,
    encode_chunk,
    encode_cop_request,
    encode_dag,
)
from tidb_tpu.distsql import KVRequest, full_table_ranges, select
from tidb_tpu.exec import Aggregation, ColumnInfo, DAGRequest, Join, Limit, Selection, TableScan, TopN
from tidb_tpu.expr import AggDesc, AggMode, col, func, lit
from tidb_tpu.store import TPUStore
from tidb_tpu.types import Datum, MyDecimal, MyTime, new_datetime, new_decimal, new_longlong, new_varchar

BOOL = new_longlong(notnull=True)


def sample_dag():
    fts = [new_longlong(), new_decimal(10, 2), new_varchar(8), new_datetime()]
    C = lambda i: col(i, fts[i])
    scan = TableScan(9, tuple(ColumnInfo(i + 1, ft) for i, ft in enumerate(fts)))
    build = TableScan(10, (ColumnInfo(1, fts[0]), ColumnInfo(2, fts[2])))
    join = Join(
        build=(build, Selection((func("like", BOOL, col(1, fts[2]), lit("a%", new_varchar(2))),))),
        probe_keys=(C(0),),
        build_keys=(col(0, fts[0]),),
        join_type="left_outer",
    )
    sel = Selection((
        func("and", BOOL,
             func("ge", BOOL, C(3), lit("2020-01-01", new_datetime())),
             func("between", BOOL, C(1), lit("1.00", new_decimal(3, 2)), lit("9.99", new_decimal(3, 2)))),
    ))
    agg = Aggregation(
        group_by=(C(2),),
        aggs=(AggDesc("sum", (C(1),)), AggDesc("count", (), mode=AggMode.Partial1), AggDesc("first_row", (C(3),))),
        partial=True,
    )
    t = TopN(order_by=((C(1), True),), limit=12)
    return DAGRequest((scan, sel, join, agg, t), output_offsets=(0, 1, 2), time_zone="UTC", flags=3)


def test_dag_roundtrip_bitexact():
    dag = sample_dag()
    b = encode_dag(dag)
    dag2 = decode_dag(b)
    assert dag2 == dag  # frozen dataclasses: full structural equality
    assert dag2.fingerprint() == dag.fingerprint()
    assert encode_dag(dag2) == b  # stable re-encode


def test_chunk_roundtrip():
    fts = [new_longlong(), new_longlong(unsigned=True), new_decimal(8, 3), new_varchar(12), new_datetime()]
    rng = np.random.default_rng(0)
    rows = []
    for i in range(57):
        rows.append([
            Datum.i64(int(rng.integers(-1000, 1000))) if i % 7 else Datum.NULL,
            Datum.u64(int(rng.integers(0, 2**63))),
            Datum.dec(MyDecimal(f"{int(rng.integers(-99999, 99999))/1000:.3f}")),
            Datum.string("αβ" if i % 5 == 0 else f"s{i}") if i % 6 else Datum.NULL,
            Datum.time(MyTime.from_ymd(2020 + i % 5, 1 + i % 12, 1 + i % 28)),
        ])
    ch = Chunk.from_rows(fts, rows)
    ch2 = decode_chunk(encode_chunk(ch))
    from tidb_tpu.exec.executor import datum_group_key

    assert [[datum_group_key(d) for d in r] for r in ch2.rows()] == [
        [datum_group_key(d) for d in r] for r in ch.rows()
    ]


def test_dispatch_through_wire_zero_diff():
    """select() with use_wire=True: every cop request/response crosses the
    bytes boundary; results identical to the in-process path."""
    store = TPUStore()
    tid = 4
    fts = [new_longlong(), new_decimal(10, 2)]
    rng = np.random.default_rng(1)
    for h in range(150):
        store.put_row(tid, h, [1, 2], [Datum.i64(int(rng.integers(0, 9))), Datum.dec(MyDecimal(f"{h}.25"))], ts=5)
    store.cluster.split(tablecodec.encode_row_key(tid, 75))
    scan = TableScan(tid, (ColumnInfo(1, fts[0]), ColumnInfo(2, fts[1])))
    agg = Aggregation(group_by=(col(0, fts[0]),), aggs=(AggDesc("count", ()), AggDesc("sum", (col(1, fts[1]),))), partial=True)
    dag = DAGRequest((scan, agg), output_offsets=tuple(range(3)))

    plain = select(store, KVRequest(dag, full_table_ranges(tid), start_ts=100))
    wired = select(store, KVRequest(dag, full_table_ranges(tid), start_ts=100, use_wire=True))
    from tidb_tpu.exec.executor import datum_group_key

    def canon(res):
        return sorted(tuple(datum_group_key(d) for d in r) for c in res.chunks for r in c.rows())

    assert canon(wired) == canon(plain)
    # summaries and paging survive the wire too
    assert all(len(sm) == 2 for sm in wired.exec_summaries)


def test_wire_paging():
    store = TPUStore()
    tid = 6
    for h in range(40):
        store.put_row(tid, h, [1], [Datum.i64(h)], ts=5)
    scan = TableScan(tid, (ColumnInfo(1, new_longlong()),))
    dag = DAGRequest((scan,), output_offsets=(0,))
    res = select(store, KVRequest(dag, full_table_ranges(tid), start_ts=100, paging_size=15, use_wire=True))
    assert len(res.chunks) == 3
    assert sorted(r[0].val for c in res.chunks for r in c.rows()) == list(range(40))


def test_wire_malformed_request():
    store = TPUStore()
    resp = decode_cop_response(store.coprocessor_bytes(b"\x01\x02garbage"))
    assert resp.other_error and "bad request" in resp.other_error
