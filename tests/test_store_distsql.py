"""Store + distsql: multi-region cop dispatch, partial agg across regions,
region-split retry — the reference's testkit-style in-process cluster
(ref: pkg/testkit/mockstore.go CreateMockStore + unistore cluster)."""

import numpy as np
import pytest

from tidb_tpu.types import Datum, MyDecimal, new_decimal, new_longlong, new_varchar
from tidb_tpu.chunk import Chunk
from tidb_tpu.codec import tablecodec
from tidb_tpu.distsql import KVRequest, full_table_ranges, select
from tidb_tpu.exec import Aggregation, ColumnInfo, DAGRequest, Selection, TableScan, run_dag_reference
from tidb_tpu.expr import AggDesc, AggMode, col, func, lit
from tidb_tpu.store import TPUStore

BOOL = new_longlong(notnull=True)
TID = 44
FTS = [new_longlong(), new_decimal(10, 2), new_varchar(6)]
COL_IDS = [1, 2, 3]


def fill_store(n=300, regions=4, seed=2):
    store = TPUStore()
    rng = np.random.default_rng(seed)
    rows = []
    for h in range(n):
        row = [
            Datum.i64(int(rng.integers(0, 9))),
            Datum.dec(MyDecimal(f"{int(rng.integers(-10000, 10000))/100:.2f}")),
            Datum.string(["red", "green", "blue"][int(rng.integers(3))]),
        ]
        rows.append(row)
        store.put_row(TID, h, COL_IDS, row, ts=10)
    # split into regions on handle boundaries (ref: cluster.SplitKeys)
    for i in range(1, regions):
        store.cluster.split(tablecodec.encode_row_key(TID, i * n // regions))
    return store, rows


def scan():
    return TableScan(TID, tuple(ColumnInfo(cid, ft) for cid, ft in zip(COL_IDS, FTS)))


def test_multi_region_scan_concat():
    store, rows = fill_store()
    dag = DAGRequest((scan(),), output_offsets=(0, 1, 2))
    res = select(store, KVRequest(dag, full_table_ranges(TID), start_ts=100))
    assert len(res.chunks) == 4  # one per region
    merged = res.merged()
    assert merged.num_rows() == len(rows)
    got = sorted((r[0].val, str(r[1].val), r[2].val) for r in merged.rows())
    want = sorted((r[0].val, str(r[1].val), r[2].val) for r in rows)
    assert got == want


def test_partial_agg_per_region_then_merge():
    """Partial1 on each region; Final merge at root — the north-star shape."""
    store, rows = fill_store(n=200, regions=4)
    g = col(0, FTS[0])
    d = col(1, FTS[1])
    partial = Aggregation(group_by=(g,), aggs=(AggDesc("avg", (d,)), AggDesc("count", ())), partial=True)
    dag = DAGRequest((scan(), partial), output_offsets=tuple(range(4)))
    res = select(store, KVRequest(dag, full_table_ranges(TID), start_ts=100))
    # root merge: stack partial chunks, Final aggregate keyed on group col
    stacked = res.merged()
    # partial schema: [avg.count, avg.sum, count.count, g]
    pfts = stacked.field_types()
    from tidb_tpu.exec import run_dag_on_chunk

    avg_desc = AggDesc("avg", (col(1, FTS[1]),))
    cnt_desc = AggDesc("count", ())
    merge_agg = Aggregation(
        group_by=(col(3, pfts[3]),),
        aggs=(
            AggDesc("avg", (col(0, pfts[0]), col(1, pfts[1])), mode=AggMode.Final),
            AggDesc("count", (col(2, pfts[2]),), mode=AggMode.Final),
        ),
        merge=True,
    )
    root = DAGRequest((TableScan(0, tuple(ColumnInfo(i, ft) for i, ft in enumerate(pfts))), merge_agg), output_offsets=(0, 1, 2))
    final = run_dag_on_chunk(root, stacked)
    # oracle: single-shot over all rows
    oracle_agg = Aggregation(group_by=(g,), aggs=(avg_desc, cnt_desc))
    oracle = run_dag_reference(DAGRequest((scan(), oracle_agg), output_offsets=(0, 1, 2)), Chunk.from_rows(FTS, rows))
    got = sorted((str(r[0].val) if not r[0].is_null() else None, r[1].val, r[2].val if not r[2].is_null() else None) for r in final.rows())
    want = sorted((str(r[0].val) if not r[0].is_null() else None, r[1].val, r[2].val if not r[2].is_null() else None) for r in oracle)
    assert got == want


def test_selection_pushdown_multi_region():
    store, rows = fill_store(n=150, regions=3)
    pred = func("gt", BOOL, col(1, FTS[1]), lit("0.00", new_decimal(3, 2)))
    dag = DAGRequest((scan(), Selection((pred,))), output_offsets=(0, 1))
    res = select(store, KVRequest(dag, full_table_ranges(TID), start_ts=100))
    merged = res.merged()
    want = [r for r in rows if not r[1].is_null() and r[1].val > MyDecimal("0")]
    assert merged.num_rows() == len(want)


def test_region_split_retry():
    """Split after tasks are built -> epoch mismatch -> transparent retry."""
    store, rows = fill_store(n=100, regions=2)
    dag = DAGRequest((scan(),), output_offsets=(0,))

    # build tasks against the current view, then split to invalidate epochs
    from tidb_tpu.distsql.dispatch import _build_tasks

    ranges = full_table_ranges(TID)
    tasks = _build_tasks(store, ranges)
    store.cluster.split(tablecodec.encode_row_key(TID, 25))

    # run through select: it rebuilds from fresh view internally, so emulate
    # staleness by issuing the stale task directly first
    from tidb_tpu.store import CopRequest

    stale = tasks[0]
    resp = store.coprocessor(CopRequest(dag, stale.ranges, 100, stale.region_id, stale.epoch))
    assert resp.region_error is not None and "epoch_not_match" in resp.region_error

    res = select(store, KVRequest(dag, ranges, start_ts=100))
    assert res.merged().num_rows() == 100


def test_mvcc_snapshot_read():
    store, _ = fill_store(n=20, regions=1)
    # overwrite handle 0 at ts=50
    store.put_row(TID, 0, COL_IDS, [Datum.i64(777), Datum.dec("1.00"), Datum.string("red")], ts=50)
    dag = DAGRequest((scan(),), output_offsets=(0,))
    old = select(store, KVRequest(dag, full_table_ranges(TID), start_ts=20)).merged()
    new = select(store, KVRequest(dag, full_table_ranges(TID), start_ts=60)).merged()
    olds = sorted(r[0].val for r in old.rows())
    news = sorted(r[0].val for r in new.rows())
    assert 777 not in olds
    assert 777 in news
    # delete visible only after its ts
    store.delete_row(TID, 1, ts=70)
    assert select(store, KVRequest(dag, full_table_ranges(TID), start_ts=60)).merged().num_rows() == 20
    assert select(store, KVRequest(dag, full_table_ranges(TID), start_ts=80)).merged().num_rows() == 19
