"""Ecosystem tools: dump (Dumpling analog), LOAD DATA (Lightning analog
with resumable checkpoints), BACKUP/RESTORE (BR analog with checksums)
(ref: dumpling/export, pkg/lightning, br/pkg)."""

import json
import os

import pytest

from tidb_tpu.sql.catalog import Catalog
from tidb_tpu.sql.session import Session, SQLError
from tidb_tpu.store import TPUStore


@pytest.fixture()
def sess():
    s = Session()
    s.execute("CREATE TABLE t (id INT PRIMARY KEY, v INT, name VARCHAR(16))")
    s.execute("CREATE UNIQUE INDEX uv ON t (v)")
    s.execute("INSERT INTO t VALUES (1,10,'a'),(2,20,'b,c'),(3,NULL,NULL)")
    return s


# ---------------------------------------------------------------- dump


def test_dump_csv(sess, tmp_path):
    from tidb_tpu.tools import dump_table

    out = dump_table(sess, "t", str(tmp_path), fmt="csv")
    assert out["rows"] == 3
    lines = open(out["data_path"]).read().splitlines()
    assert lines[0] == "id,v,name"
    assert lines[2] == '2,20,"b,c"'  # quoting
    assert lines[3] == "3,\\N,\\N"  # nulls
    schema = open(out["schema_path"]).read()
    assert "PRIMARY KEY" in schema and "UNIQUE KEY `uv`" in schema


def test_dump_sql_reimportable(sess, tmp_path):
    from tidb_tpu.tools import dump_table

    out = dump_table(sess, "t", str(tmp_path), fmt="sql")
    s2 = Session()
    s2.execute(open(out["schema_path"]).read().rstrip().rstrip(";"))
    for stmt in open(out["data_path"]).read().split(";\n"):
        if stmt.strip():
            s2.execute(stmt)
    assert s2.execute("SELECT count(*) FROM t").values() == [[3]]
    assert s2.execute("SELECT name FROM t WHERE id = 2").values() == [["b,c"]]


def test_dump_all_consistent_snapshot(sess, tmp_path):
    from tidb_tpu.tools import dump_all

    sess.execute("CREATE TABLE u (id INT PRIMARY KEY)")
    sess.execute("INSERT INTO u VALUES (1)")
    out = dump_all(sess, str(tmp_path))
    assert set(out) == {"t", "u"}


# ---------------------------------------------------------------- load data


def test_load_data_basic(sess, tmp_path):
    p = tmp_path / "rows.tsv"
    p.write_text("4\t40\td\n5\t50\te\n6\t\\N\t\\N\n")
    r = sess.execute(f"LOAD DATA INFILE '{p}' INTO TABLE t")
    assert r.affected == 3
    assert sess.execute("SELECT count(*) FROM t").values() == [[6]]
    assert sess.execute("SELECT v, name FROM t WHERE id = 6").values() == [[None, None]]
    assert not os.path.exists(str(p) + ".ckpt")


def test_load_data_checkpoint_resume(sess, tmp_path):
    p = tmp_path / "rows.tsv"
    p.write_text("\n".join(f"{i}\t{i * 10}\tr{i}" for i in range(10, 20)) + "\n")
    # simulate a prior partial run: checkpoint says 4 rows are durable
    (tmp_path / "rows.tsv.ckpt").write_text("4")
    # make those 4 rows actually exist (as the crashed run would have left)
    sess.execute("INSERT INTO t VALUES (10,100,'r10'),(11,110,'r11'),(12,120,'r12'),(13,130,'r13')")
    r = sess.execute(f"LOAD DATA INFILE '{p}' INTO TABLE t")
    assert r.affected == 6  # only the tail imports
    assert sess.execute("SELECT count(*) FROM t WHERE id >= 10").values() == [[10]]


def test_load_data_duplicate_pk_fails(sess, tmp_path):
    p = tmp_path / "dup.tsv"
    p.write_text("1\t999\tx\n")
    with pytest.raises(SQLError, match="duplicate"):
        sess.execute(f"LOAD DATA INFILE '{p}' INTO TABLE t")


def test_load_data_indexes_maintained(sess, tmp_path):
    p = tmp_path / "rows.tsv"
    p.write_text("7\t70\tg\n")
    sess.execute(f"LOAD DATA INFILE '{p}' INTO TABLE t")
    # unique index uv must now see 70
    with pytest.raises(SQLError, match="duplicate"):
        sess.execute("INSERT INTO t VALUES (99, 70, 'clash')")


# ---------------------------------------------------------------- backup/restore


def test_backup_restore_roundtrip(sess, tmp_path):
    bdir = str(tmp_path / "bk")
    r = sess.execute(f"BACKUP DATABASE * TO '{bdir}'")
    assert r.columns == ["Destination", "Keys", "SnapshotTS"]
    store2, cat2 = TPUStore(), Catalog()
    s2 = Session(store2, cat2)
    r2 = s2.execute(f"RESTORE DATABASE * FROM '{bdir}'")
    assert r2.values()[0][2] == 1  # one table
    assert s2.execute("SELECT id, v, name FROM t ORDER BY id").values() == \
        sess.execute("SELECT id, v, name FROM t ORDER BY id").values()
    # index + autoid survive
    assert s2.execute("SELECT id FROM t WHERE v = 20").values() == [[2]]
    s2.execute("INSERT INTO t (v, name) VALUES (77, 'new')")
    assert s2.execute("SELECT max(id) FROM t").values() == [[4]]


def test_restore_rejects_existing_table(sess, tmp_path):
    bdir = str(tmp_path / "bk")
    sess.execute(f"BACKUP DATABASE * TO '{bdir}'")
    with pytest.raises(Exception, match="already exists"):
        sess.execute(f"RESTORE DATABASE * FROM '{bdir}'")


def test_restore_detects_corruption(sess, tmp_path):
    bdir = tmp_path / "bk"
    sess.execute(f"BACKUP DATABASE * TO '{bdir}'")
    seg = json.load(open(bdir / "manifest.json"))["segments"][0]["file"]
    data = bytearray((bdir / seg).read_bytes())
    data[-1] ^= 0xFF
    (bdir / seg).write_bytes(bytes(data))
    s2 = Session(TPUStore(), Catalog())
    with pytest.raises(Exception, match="checksum"):
        s2.execute(f"RESTORE DATABASE * FROM '{bdir}'")


def test_backup_resume_skips_valid_segments(sess, tmp_path):
    from tidb_tpu.tools import backup

    bdir = str(tmp_path / "bk")
    m1 = backup(sess.store, sess.catalog, bdir)
    m2 = backup(sess.store, sess.catalog, bdir)  # second run: resume path
    assert [s["sha256"] for s in m1["segments"]] == [s["sha256"] for s in m2["segments"]]


def test_brie_requires_super(sess, tmp_path):
    sess.execute("CREATE USER 'u'")
    store, cat = sess.store, sess.catalog
    u = Session(store, cat)
    u.user = "u"
    with pytest.raises(SQLError, match="SUPER"):
        u.execute(f"BACKUP DATABASE * TO '{tmp_path}/x'")


def test_backup_restore_views(sess, tmp_path):
    sess.execute("CREATE VIEW v_hi AS SELECT id, v FROM t WHERE v >= 20")
    bdir = str(tmp_path / "bk")
    sess.execute(f"BACKUP DATABASE * TO '{bdir}'")
    s2 = Session(TPUStore(), Catalog())
    s2.execute(f"RESTORE DATABASE * FROM '{bdir}'")
    assert s2.execute("SELECT id FROM v_hi ORDER BY id").values() == [[2]]


# ------------------------------------------------- tidb-vet (ISSUE 7 + 9)

def test_vet_repo_is_clean():
    """Tier-1 gate: every tidb-vet pass — the lexical families, the
    interprocedural dataflow passes, the jaxpr auditor and the
    stale-suppression audit — reports zero findings on the live tree
    (the fixture corpus in tests/vet_fixtures/ proves each pass CAN
    fire; see tests/test_vet.py)."""
    from tidb_tpu import analysis

    findings = analysis.run_all()
    assert findings == [], "\n".join(f.render() for f in findings)
    # the suite really covers all the families (error-taxonomy was
    # promoted into dataflow-error-escape in ISSUE 9)
    assert set(analysis.PASSES) == {
        "jit-purity", "lock-discipline", "metrics", "wire-parity",
        "failpoints", "dataflow-snapshot", "dataflow-backoff",
        "dataflow-error-escape", "jax-audit",
    }
    assert analysis.SUPPRESSIONS == "suppressions"


def test_vet_baseline_json_roundtrips():
    """ISSUE 9 satellite: --baseline emits stable sorted JSON that
    --diff reads back byte-for-byte (the cross-commit diffing seam) —
    asserted here at the library level; tests/test_vet.py drives the
    CLI end to end."""
    import json

    from tidb_tpu import analysis

    findings = analysis.run_all()
    dicts = [f.to_dict() for f in findings]
    assert dicts == sorted(dicts, key=lambda d: (d["path"], d["line"], d["pass"]))
    assert json.loads(json.dumps(dicts)) == dicts


def test_load_data_lock_conflict_is_a_sql_error(sess, tmp_path):
    """Pin for the live finding dataflow-error-escape surfaced (ISSUE 9):
    LOAD DATA hitting a key held by a live transaction must surface a
    typed SQLError, not a raw KeyIsLocked engine exception escaping the
    session boundary."""
    from tidb_tpu.codec import tablecodec
    from tidb_tpu.store.txn import KeyIsLocked

    p = tmp_path / "rows.tsv"
    p.write_text("9\t90\tz\n")
    meta = sess.catalog.table("t")
    key = tablecodec.encode_row_key(meta.table_id, 9)
    lock_ts = sess.store.next_ts()
    sess.store.txn.prewrite({key: b"\x00"}, key, lock_ts)
    try:
        with pytest.raises(SQLError, match="locked"):
            sess.execute(f"LOAD DATA INFILE '{p}' INTO TABLE t")
    except KeyIsLocked as exc:  # the pre-fix failure mode, kept loud
        pytest.fail(f"KeyIsLocked escaped the session boundary: {exc}")
    finally:
        sess.store.txn.release_all(lock_ts)
    # with the lock gone the import succeeds
    assert sess.execute(f"LOAD DATA INFILE '{p}' INTO TABLE t").affected == 1


# ------------------------------------------------------- failpoint_check

def test_failpoint_check_repo_is_clean():
    """Tier-1 gate (ISSUE 6 satellite): every failpoint name armed in
    tests/tools/bench resolves to a real eval/is_armed/peek site in
    tidb_tpu/, and every site carries a catalog description — a typo'd
    name silently never fires, so this is the only guard."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    import failpoint_check

    errors, sites = failpoint_check.check()
    assert errors == []
    # the fault-injection surface this PR added is part of the catalog
    for name in ("store/unreachable", "store/not-leader", "store/server-busy",
                 "pd/heartbeat-lost", "pd/operator-timeout"):
        assert name in sites, name


def test_failpoint_check_catches_a_typo(tmp_path):
    """A use of an undefined name must be reported (the failure mode the
    tool exists for)."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    import failpoint_check

    # the bogus name is spliced in at runtime so the checker's own scan of
    # THIS file (it caught the literal form — proof it works) stays clean
    typo = "store/" + "unreachble"
    bogus = 'from tidb_tpu.util import failpoint\nfailpoint.enable(%r)\n' % typo
    uses = failpoint_check._scan(failpoint_check._USE, [str(tmp_path / "t.py")])
    assert uses == {}  # unreadable/missing file: no crash
    p = tmp_path / "t.py"
    p.write_text(bogus)
    uses = failpoint_check._scan(failpoint_check._USE, [str(p)])
    assert typo in uses


def test_failpoint_catalog_generation(tmp_path):
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    import failpoint_check

    _errors, sites = failpoint_check.check()
    out = tmp_path / "FAILPOINTS.md"
    failpoint_check.write_catalog(sites, str(out))
    text = out.read_text()
    assert "| `store/server-busy` |" in text
    assert "| `pd/operator-timeout` |" in text
    for name in sites:
        assert f"| `{name}` |" in text
