"""JSON type + functions, regexp, ENUM/SET (VERDICT r2 missing #4) —
oracle-diffed through the full SQL path."""

from tidb_tpu.sql import Session


def _mk():
    s = Session()
    s.execute("create table j (id bigint primary key, doc json, tag enum('red','green','blue'), opts set('a','b','c'))")
    s.execute("""insert into j values
        (1, '{"name": "alpha", "nums": [1, 2, 3], "deep": {"k": true}}', 'red', 'a,c'),
        (2, '{"name": "beta", "nums": [4], "deep": {"k": false}}', 'blue', ''),
        (3, '[10, 20, 30]', 'green', 'b')""")
    return s


class TestJSON:
    def test_json_extract_arrow_ops(self):
        s = _mk()
        from tidb_tpu.types import json_binary as jb

        r = s.execute("select id, doc->'$.name', doc->>'$.name' from j where id < 3 order by id")
        rows = [(int(x[0].val), jb.decode(x[1].val), str(x[2].val)) for x in r.rows]
        assert rows[0] == (1, "alpha", "alpha")
        assert rows[1][2] == "beta"

    def test_json_functions(self):
        s = _mk()
        r = s.execute(
            "select json_type(doc), json_valid(doc), json_length(doc), "
            "json_extract(doc, '$.nums[1]') from j where id = 1"
        )
        row = r.rows[0]
        assert str(row[0].val) == "OBJECT"
        assert int(row[1].val) == 1
        assert int(row[2].val) == 3
        from tidb_tpu.types import json_binary as jb

        assert jb.decode(row[3].val) == 2

    def test_json_where_and_member_of(self):
        s = _mk()
        r = s.execute("select id from j where json_contains(doc, '2', '$.nums')" if False else
                      "select id from j where json_extract(doc, '$.deep.k') = true")
        # boolean true compare via json — fall back to contains below
        r2 = s.execute("select id from j where 20 member of (doc)")
        assert [int(x[0].val) for x in r2.rows] == [3]

    def test_json_group_by_extract(self):
        s = _mk()
        r = s.execute("select json_type(doc), count(*) from j group by json_type(doc)")
        got = sorted((str(x[0].val), int(x[1].val)) for x in r.rows)
        assert got == [("ARRAY", 1), ("OBJECT", 2)]

    def test_json_roundtrip_output(self):
        from tidb_tpu.server import MiniClient, MySQLServer

        s = _mk()
        srv = MySQLServer(port=0, store=s.store, catalog=s.catalog)
        srv.start_background()
        try:
            c = MiniClient(srv.host, srv.port)
            cols, rows = c.query("select doc from j where id = 3")
            assert rows[0][0] == "[10, 20, 30]"
        finally:
            srv.close()


class TestRegexp:
    def test_regexp_operator_and_like(self):
        s = _mk()
        r = s.execute("select id from j where doc->>'$.name' regexp '^al'")
        assert [int(x[0].val) for x in r.rows] == [1]
        r = s.execute("select regexp_like('Hello', '^he', 'i'), regexp_like('Hello', '^he', 'c')")
        assert int(r.rows[0][0].val) == 1 and int(r.rows[0][1].val) == 0
        r = s.execute("select id from j where tag not regexp 'e{2}'")
        assert sorted(int(x[0].val) for x in r.rows) == [1, 2]  # only green contains ee


class TestEnumSet:
    def test_enum_storage_and_compare(self):
        s = _mk()
        r = s.execute("select id, tag from j order by tag, id")
        rows = [(int(x[0].val), str(x[1].val)) for x in r.rows]
        # enum orders by member NUMBER: red(1) < green(2) < blue(3)
        assert rows == [(1, "red"), (3, "green"), (2, "blue")]
        r = s.execute("select id from j where tag = 'green'")
        assert [int(x[0].val) for x in r.rows] == [3]
        r = s.execute("select id from j where tag > 'red' order by id")
        assert [int(x[0].val) for x in r.rows] == [2, 3]

    def test_set_storage(self):
        s = _mk()
        r = s.execute("select id, opts from j order by id")
        rows = [(int(x[0].val), str(x[1].val)) for x in r.rows]
        assert rows == [(1, "a,c"), (2, ""), (3, "b")]

    def test_invalid_enum_rejected(self):
        s = _mk()
        try:
            s.execute("insert into j values (9, '1', 'purple', '')")
            raise AssertionError("expected invalid enum error")
        except Exception as exc:
            assert "enum" in str(exc).lower()

    def test_enum_survives_restart(self):
        s = _mk()
        s2 = Session(store=s.store)
        r = s2.execute("select tag from j where id = 1")
        assert str(r.rows[0][0].val) == "red"


class TestReviewRegressions:
    def test_json_scalar_string_args(self):
        s = _mk()
        from tidb_tpu.types import json_binary as jb

        r = s.execute("select json_object('k', 'v'), json_array('abc', '[1,2]'), json_unquote('abc')")
        assert jb.decode(r.rows[0][0].val) == {"k": "v"}
        assert jb.decode(r.rows[0][1].val) == ["abc", "[1,2]"]
        assert str(r.rows[0][2].val) == "abc"

    def test_member_of_string_scalar(self):
        s = _mk()
        r = s.execute("select 'alpha' member of (json_array('alpha', 'beta'))")
        assert int(r.rows[0][0].val) == 1

    def test_json_equals_string(self):
        s = _mk()
        r = s.execute("select id from j where doc->>'$.name' = 'alpha'")
        assert [int(x[0].val) for x in r.rows] == [1]
        r = s.execute("select id from j where doc->'$.name' = 'alpha'")
        assert [int(x[0].val) for x in r.rows] == [1]

    def test_enum_nonmember_literal_matches_nothing(self):
        s = _mk()
        r = s.execute("select id from j where tag = 'purple'")
        assert r.rows == []

    def test_undefined_named_window_errors(self):
        s = _mk()
        try:
            s.execute("select rank() over w from j")
            raise AssertionError("expected undefined-window error")
        except Exception as exc:
            assert "not defined" in str(exc)

    def test_enum_nonmember_ne_matches_all(self):
        # ADVICE r3: != against a non-member must match every non-NULL row
        s = _mk()
        r = s.execute("select id from j where tag != 'purple'")
        assert sorted(int(x[0].val) for x in r.rows) == [1, 2, 3]

    def test_enum_nonmember_in_list(self):
        s = _mk()
        r = s.execute("select id from j where tag in ('purple', 'red')")
        assert [int(x[0].val) for x in r.rows] == [1]

    def test_enum_nonmember_ordering_raises(self):
        # ADVICE r3: `tag > 'purple'` must NOT lower to `tag > -1`
        # (match-everything); ordering against a non-member raises
        s = _mk()
        for q in ("select id from j where tag > 'purple'",
                  "select id from j where tag between 'purple' and 'red'"):
            try:
                s.execute(q)
                raise AssertionError(f"expected non-member ordering error: {q}")
            except Exception as exc:
                assert "non-member" in str(exc), exc

    def test_json_object_odd_arity_is_sql_error(self):
        # ADVICE r3: odd argument count raises a SQL-level error, not
        # IndexError out of the evaluator
        s = _mk()
        try:
            s.execute("select json_object('k')")
            raise AssertionError("expected arity error")
        except IndexError:
            raise AssertionError("IndexError leaked out of the evaluator")
        except Exception as exc:
            assert "json_object" in str(exc)

    def test_named_window_referenced_from_order_by(self):
        # ADVICE r3: WINDOW clause windows are visible to window functions
        # in ORDER BY (parsed after the WINDOW clause)
        s = _mk()
        r = s.execute(
            "select id from j window w as (order by id desc) order by rank() over w"
        )
        assert [int(x[0].val) for x in r.rows] == [3, 2, 1]

    def test_json_group_by_on_multidevice_mesh_falls_back(self):
        # ADVICE r3 (medium): host-only exprs in group-by must not reach the
        # shard_map trace — the mesh gate rejects them and the per-region
        # path answers (8-device CPU mesh active in tests)
        s = _mk()
        assert s.sysvars.get_bool("tidb_enable_tpu_mesh")
        r = s.execute("select json_type(doc), count(*) from j group by json_type(doc)")
        got = sorted((str(x[0].val), int(x[1].val)) for x in r.rows)
        assert got == [("ARRAY", 1), ("OBJECT", 2)]

    def test_named_window_block_scoped_in_order_by_subquery(self):
        # code-review r4: a same-named WINDOW in an ORDER BY subquery must
        # not capture the outer block's OVER w reference
        from tidb_tpu.parser import parse_one

        st = parse_one(
            "select rank() over w as r from t window w as (order by id desc) "
            "order by (select count(*) over w from t2 window w as (order by x asc))"
        )
        wf = st.fields[0].expr
        bi = wf.order_by[0]
        assert (bi.expr.name if hasattr(bi, "expr") else bi.name) == "id"
        try:
            parse_one(
                "select rank() over w from t order by "
                "(select count(*) over wi from t2 window wi as (order by x), w as (order by y))"
            )
            raise AssertionError("outer w resolved against inner block")
        except Exception as exc:
            assert "not defined" in str(exc)
