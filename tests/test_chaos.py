"""Fault-tolerant dispatch (ISSUE 6): typed region errors across the wire
seam, store fault switches, circuit breakers + PD failover, session-level
MySQL error mapping, and the seeded chaos harness (ref: client-go's
backoff/regionCache error handling + pingcap/failpoint-driven chaos
suites)."""

import os
import sys
import threading

import pytest

from tidb_tpu.codec import tablecodec
from tidb_tpu.distsql.dispatch import (
    BreakerBoard,
    CircuitBreaker,
    CopInternalError,
    KVRequest,
    RegionUnavailableError,
    select,
    select_stream,
    full_table_ranges,
)
from tidb_tpu.exec.dag import ColumnInfo, DAGRequest, TableScan
from tidb_tpu.sql.session import Session, SQLError
from tidb_tpu.store import (
    CopRequest,
    EpochNotMatch,
    KeyRange,
    NotLeader,
    RegionNotFound,
    ServerIsBusy,
    StoreUnavailable,
    TPUStore,
    parse_region_error,
)
from tidb_tpu.types import Datum, new_longlong
from tidb_tpu.util import failpoint, metrics

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

TID = 11


def fill_store(rows=120, regions=4, stores=4):
    store = TPUStore()
    for h in range(rows):
        store.put_row(TID, h, [1], [Datum.i64(h)], ts=10)
    for i in range(1, regions):
        store.cluster.split(tablecodec.encode_row_key(TID, i * rows // regions))
    store.cluster.set_stores(stores)
    store.cluster.scatter()
    return store


def scan_req(**kw):
    dag = DAGRequest((TableScan(TID, (ColumnInfo(1, new_longlong()),)),), output_offsets=(0,))
    return KVRequest(dag, full_table_ranges(TID), start_ts=100, **kw)


def make_session(rows=160, regions=8, stores=4):
    s = Session()
    s.execute("CREATE TABLE ft (id BIGINT PRIMARY KEY, v BIGINT)")
    s.execute("INSERT INTO ft VALUES " + ",".join(f"({i},{i % 9})" for i in range(rows)))
    tid = s.catalog.table("ft").table_id
    for i in range(1, regions):
        s.store.cluster.split(tablecodec.encode_row_key(tid, i * rows // regions))
    s.store.cluster.set_stores(stores)
    s.store.cluster.scatter()
    return s


# ------------------------------------------------------- typed region errors

class TestTypedRegionErrors:
    def test_parse_round_trips_every_kind(self):
        cases = [
            (NotLeader.make(5, 2), NotLeader, {"store_id": 2}),
            (ServerIsBusy.make(1, 250), ServerIsBusy, {"backoff_ms": 250}),
            (StoreUnavailable.make(3), StoreUnavailable, {"store_id": 3}),
        ]
        for err, cls, attrs in cases:
            back = parse_region_error(str(err))
            assert isinstance(back, cls), str(err)
            assert back.kind == err.kind
            for k, v in attrs.items():
                assert getattr(back, k) == v
        # the strings the store already emits classify too
        assert isinstance(parse_region_error("epoch_not_match: have 3, got 2"), EpochNotMatch)
        assert isinstance(parse_region_error("region 9 not found"), RegionNotFound)
        assert parse_region_error("mystery failure").kind == "region_miss"
        assert parse_region_error(None) is None

    def test_region_errors_survive_the_wire_seam(self):
        """A typed error injected store-side must classify identically
        after the bytes round trip (single frame AND batch frame)."""
        from tidb_tpu.codec.wire import (
            decode_batch_cop_response,
            decode_cop_response,
            encode_batch_cop_request,
            encode_cop_request,
        )

        store = fill_store()
        store.set_down(0)
        region = next(r for r in store.cluster.regions()
                      if store.cluster.store_of(r.region_id) == 0)
        dag = DAGRequest((TableScan(TID, (ColumnInfo(1, new_longlong()),)),), output_offsets=(0,))
        creq = CopRequest(dag, [KeyRange(region.start_key, region.end_key)], 100,
                          region.region_id, region.epoch)
        resp = decode_cop_response(store.coprocessor_bytes(encode_cop_request(creq)))
        err = parse_region_error(resp.region_error)
        assert isinstance(err, StoreUnavailable) and err.store_id == 0
        resps = decode_batch_cop_response(
            store.batch_coprocessor_bytes(encode_batch_cop_request([creq, creq])))
        for r in resps:
            assert isinstance(parse_region_error(r.region_error), StoreUnavailable)

    def test_per_store_failpoint_arming(self):
        """store/* failpoints arm per store: only regions placed on the
        armed store see the fault."""
        store = fill_store()
        by_store = {}
        for r in store.cluster.regions():
            by_store.setdefault(store.cluster.store_of(r.region_id), r)
        dag = DAGRequest((TableScan(TID, (ColumnInfo(1, new_longlong()),)),), output_offsets=(0,))

        def cop(region):
            return store.coprocessor(CopRequest(
                dag, [KeyRange(region.start_key, region.end_key)], 100,
                region.region_id, region.epoch))

        with failpoint.enabled("store/not-leader", {1}):
            ok = cop(by_store[0])
            assert ok.region_error is None
            bad = cop(by_store[1])
            assert isinstance(parse_region_error(bad.region_error), NotLeader)
        with failpoint.enabled("store/server-busy", {"stores": {2}, "backoff_ms": 40}):
            busy = cop(by_store[2])
            err = parse_region_error(busy.region_error)
            assert isinstance(err, ServerIsBusy) and err.backoff_ms == 40
        with failpoint.enabled("store/unreachable", {3}):
            assert not store.ping_store(3)
            assert store.ping_store(0)
            down = cop(by_store[3])
            assert isinstance(parse_region_error(down.region_error), StoreUnavailable)
        assert cop(by_store[3]).region_error is None  # disarmed: healthy again


# --------------------------------------------------------- circuit breakers

class TestCircuitBreaker:
    def test_opens_after_threshold_probes_and_recloses(self):
        t = [0.0]
        br = CircuitBreaker(0, threshold=3, probe_after=1.0, now_fn=lambda: t[0])
        assert br.allow_request()
        assert not br.record_failure() and not br.record_failure()
        assert br.record_failure()  # third consecutive -> opens
        assert br.state == "open" and not br.allow_request()
        t[0] += 1.5
        assert br.allow_request()  # half-open probe admitted
        assert not br.allow_request()  # ...but only ONE probe
        assert br.record_failure()  # probe failed -> re-opens
        assert br.state == "open"
        t[0] += 1.5
        assert br.allow_request()
        br.record_success()
        assert br.state == "closed" and br.allow_request()

    def test_success_resets_consecutive_failures(self):
        br = CircuitBreaker(0, threshold=3)
        br.record_failure(), br.record_failure()
        br.record_success()
        assert not br.record_failure() and not br.record_failure()
        assert br.state == "closed"  # never saw 3 CONSECUTIVE

    def test_board_views(self):
        board = BreakerBoard(threshold=1, probe_after=99.0)
        board.record_failure(2)
        assert board.open_stores() == {2}
        assert board.states()[2] == "open"
        assert not board.all_closed()
        board.record_success(2)
        assert board.all_closed()


# ----------------------------------------------- dispatch failover via PD

class TestDispatchFailover:
    def test_down_store_fails_over_and_query_answers(self):
        store = fill_store()
        store.set_down(1)
        f0 = metrics.PD_FAILOVERS.value
        res = select(store, scan_req())
        assert sum(c.num_rows() for c in res.chunks) == 120
        assert metrics.PD_FAILOVERS.value > f0
        assert 1 not in store.cluster.counts_per_store() or \
            store.cluster.counts_per_store()[1] == 0
        assert store.breakers.states()[1] == "open"
        assert store.pd.store_state(1) == "down"

    def test_down_store_mid_batch_fails_over(self):
        """ISSUE 6 acceptance: a store taken down with batch_cop on —
        its lanes fall out of the batch, fail over via PD, and the query
        still answers in full."""
        store = fill_store(rows=120, regions=6, stores=3)
        store.set_down(2)
        res = select(store, scan_req(batch_cop=True))
        assert sum(c.num_rows() for c in res.chunks) == 120
        assert store.cluster.counts_per_store().get(2, 0) == 0

    def test_open_breaker_skips_batch_dispatch(self):
        store = fill_store(rows=120, regions=6, stores=3)
        # pin the probe window far away: the breaker must STAY open for
        # the whole select (no timing-dependent half-open probe)
        store.breakers = BreakerBoard(threshold=3, probe_after=99.0)
        for _ in range(3):
            store.breakers.record_failure(0)  # trip it by hand
        c0 = metrics.COP_ERRORS.value
        res = select(store, scan_req(batch_cop=True))
        assert sum(c.num_rows() for c in res.chunks) == 120
        # open breaker meant NO request ever hit the (healthy) store's
        # fault path — lanes failed over before sending
        assert metrics.COP_ERRORS.value == c0
        assert store.cluster.counts_per_store().get(0, 0) == 0

    def test_all_stores_down_raises_region_unavailable(self):
        store = fill_store(rows=60, regions=2, stores=2)
        store.set_down(0), store.set_down(1)
        with pytest.raises(RegionUnavailableError, match="backoff budget exhausted"):
            select(store, scan_req(backoff_weight=0))

    def test_select_stream_surfaces_identical_typed_errors(self):
        store = fill_store(rows=60, regions=2, stores=2)
        store.set_down(0), store.set_down(1)
        with pytest.raises(RegionUnavailableError):
            list(select_stream(store, scan_req(backoff_weight=0)))
        for sid in (0, 1):
            store.set_up(sid)
        with failpoint.enabled("cop-other-error"):
            with pytest.raises(CopInternalError):
                list(select_stream(store, scan_req()))

    def test_server_busy_honors_suggested_backoff_then_succeeds(self):
        store = fill_store(rows=60, regions=2, stores=2)
        b0 = metrics.BACKOFF_SECONDS.labels("server_busy").value
        # transient storm: the callable value yields a per-store arming
        # dict for its first hits, then the store 'recovers' — sequential
        # dispatch so the hit order is deterministic
        hits = [0]

        def flaky():
            hits[0] += 1
            return {"stores": {1}, "backoff_ms": 4} if hits[0] <= 3 else None

        with failpoint.enabled("store/server-busy", flaky):
            res = select(store, scan_req(concurrency=1))
        assert sum(c.num_rows() for c in res.chunks) == 60
        assert metrics.BACKOFF_SECONDS.labels("server_busy").value > b0

    def test_pd_tick_health_probe_recloses_breakers(self):
        store = fill_store()
        store.set_down(3)
        select(store, scan_req())  # opens breaker 3, fails regions over
        assert store.breakers.states()[3] == "open"
        store.set_up(3)
        store.pd.tick()  # the PD's liveness probe IS the half-open probe
        assert store.breakers.all_closed()
        assert store.pd.store_state(3) == "up"
        view = {d["store_id"]: d for d in store.pd.stores_view()}
        assert view[3]["state"] == "up" and view[3]["breaker"] == "closed"


# ------------------------------------------------------- session error codes

class TestSessionErrorMapping:
    def test_exhausted_backoff_maps_to_9005(self):
        s = make_session(rows=60, regions=2, stores=2)
        s.execute("SET tidb_backoff_weight = 0")
        s.store.set_down(0), s.store.set_down(1)
        with pytest.raises(SQLError, match="Region is unavailable") as ei:
            s.execute("SELECT count(*) FROM ft")
        assert ei.value.code == 9005
        s.store.set_up(0), s.store.set_up(1)

    def test_backoff_weight_sysvar_scales_the_budget(self):
        """tidb_backoff_weight now changes behavior: weight 0 gives up on
        the first unresolved region error, a larger weight rides out the
        same transient fault."""
        s = make_session(rows=60, regions=2, stores=2)
        s.store.set_down(0)

        # weight 0: the very first store_unavailable cannot back off ->
        # 9005 (the breaker hasn't opened yet, so no failover either)
        s.execute("SET tidb_backoff_weight = 0")
        with pytest.raises(SQLError) as ei:
            s.execute("SELECT count(*) FROM ft")
        assert ei.value.code == 9005
        # default weight: backoff + breaker + failover ride it out
        s.execute("SET tidb_backoff_weight = 2")
        assert s.execute("SELECT count(*) FROM ft").scalar() == 60
        s.store.set_up(0)

    def test_other_error_maps_to_1105(self):
        s = make_session(rows=40, regions=2, stores=1)
        with failpoint.enabled("cop-other-error"):
            with pytest.raises(SQLError) as ei:
                s.execute("SELECT count(*) FROM ft")
        assert ei.value.code == 1105


# ------------------------------------------------------------ chaos harness

def test_chaos_200_statements_zero_wrong_results():
    """ISSUE 6 + ISSUE 8 acceptance: the seeded storm schedule — leader
    kills, apply-lag, transfer timeouts — over a 200-statement mixed
    workload running with `tidb_replica_read='follower'`: zero wrong
    answers, every error typed, breakers all re-closed, the storm
    provably fired (failovers + trips > 0), every failover was a LEADER
    TRANSFER (placement moves only on quorum loss, and this storm never
    loses quorum), and follower peers served a measurable share of cop
    tasks without ever violating the safe_ts gate (a violation would
    show up as a wrong result — the oracle comparison IS the gate test).
    ~2min of tier-1 budget, spent deliberately: this is the PR's green
    bar."""
    from chaos import run_chaos

    report = run_chaos(seed=7, statements=200)
    assert report["wrong_results"] == []
    assert report["untyped_errors"] == []
    assert report["breakers_all_closed"], report["breakers"]
    assert report["failovers"] >= 1  # the outage really dispatched
    assert report["breaker_trips"] >= 1
    assert report["transfer_leaders"] >= 1  # failover = leader transfer
    assert report["failover_moves"] == 0  # quorum never lost -> no moves
    assert report["replica_reads"]["follower"] > 0
    assert report["ok"] + report["typed_errors"] == 200


def test_chaos_short_run_smoke():
    """A second-seed storm pass at 1/5 scale: same invariants, different
    fault/workload interleaving — cheap diversity on top of the seed-7
    acceptance run above."""
    from chaos import run_chaos

    report = run_chaos(seed=11, statements=40)
    assert report["wrong_results"] == []
    assert report["untyped_errors"] == []
    assert report["breakers_all_closed"], report["breakers"]
    assert report["failovers"] >= 1
    assert report["failover_moves"] == 0  # transfers, never moves
    assert report["replica_reads"]["follower"] > 0
    assert report["ok"] + report["typed_errors"] == 40
