"""MVCC GC (ref: pkg/store/gcworker/gc_worker.go) and catalog persistence
through the m-prefix keyspace (ref: pkg/meta/meta.go, domain.go:1131)."""

import numpy as np

from tidb_tpu.sql import Session


class TestMVCCGC:
    def test_version_count_bounded_under_update_loop(self):
        s = Session()
        s.execute("create table g (id bigint primary key, v bigint)")
        s.execute("insert into g values (1, 0)")
        from tidb_tpu.codec import tablecodec

        meta = s.catalog.table("g")
        key = tablecodec.encode_row_key(meta.table_id, 1)
        for i in range(50):
            s.execute(f"update g set v = {i} where id = 1")
        assert len(s.store.kv._data[key]) == 51
        removed = s.store.run_gc()
        assert removed >= 50
        assert len(s.store.kv._data[key]) == 1
        # reads after GC still see the latest value
        assert int(s.execute("select v from g").rows[0][0].val) == 49

    def test_tombstones_fully_collected(self):
        s = Session()
        s.execute("create table g2 (id bigint primary key)")
        s.execute("insert into g2 values (1), (2), (3)")
        s.execute("delete from g2 where id >= 2")
        before = len(s.store.kv)
        s.store.run_gc()
        # deleted keys vanish entirely (version lists dropped)
        assert len(s.store.kv) < before
        assert len(s.execute("select * from g2").rows) == 1

    def test_safepoint_clamped_below_active_txn(self):
        s = Session()
        s.execute("create table g3 (id bigint primary key, v bigint)")
        s.execute("insert into g3 values (1, 10)")
        s.execute("begin")
        s.execute("update g3 set v = 11 where id = 1")  # lock held
        locked_start = s.txn.start_ts
        removed = s.store.run_gc()  # must not collect under the open txn
        from tidb_tpu.codec import tablecodec

        meta = s.catalog.table("g3")
        key = tablecodec.encode_row_key(meta.table_id, 1)
        # the pre-txn version survives: the open txn may still read it
        assert any(ts <= locked_start for ts, _ in s.store.kv._data[key])
        s.execute("commit")

    def test_gc_worker_ticks(self):
        import time

        from tidb_tpu.background import GCWorker

        s = Session()
        s.execute("create table g4 (id bigint primary key, v bigint)")
        s.execute("insert into g4 values (1, 0)")
        for i in range(10):
            s.execute(f"update g4 set v = {i} where id = 1")
        w = GCWorker(s.store, interval=0.05).start()
        try:
            deadline = time.time() + 3
            while w.runs == 0 and time.time() < deadline:
                time.sleep(0.05)
        finally:
            w.stop()
        assert w.runs >= 1 and w.removed_total >= 10


class TestCatalogPersistence:
    def test_restart_recovers_schema_and_data(self):
        s1 = Session()
        s1.execute("create table p (id bigint primary key, name varchar(20), key ik (name))")
        s1.execute("insert into p values (1, 'alpha'), (2, 'beta')")
        store = s1.store
        # "restart": a brand-new session over the same store, NO catalog
        s2 = Session(store=store)
        rows = sorted((int(r[0].val), str(r[1].val)) for r in s2.execute("select id, name from p").rows)
        assert rows == [(1, "alpha"), (2, "beta")]
        # schema details survive: indices, handles, DML keeps working
        s2.execute("insert into p values (3, 'gamma')")
        assert len(s2.execute("select * from p where name = 'beta'").rows) == 1

    def test_drop_and_alter_survive_restart(self):
        s1 = Session()
        s1.execute("create table p1 (id bigint primary key)")
        s1.execute("create table p2 (id bigint primary key)")
        s1.execute("drop table p1")
        s1.execute("alter table p2 add column extra bigint")
        s2 = Session(store=s1.store)
        assert "p1" not in s2.catalog.tables()
        s2.execute("insert into p2 values (1, 42)")
        assert int(s2.execute("select extra from p2").rows[0][0].val) == 42

    def test_fresh_store_still_boots(self):
        from tidb_tpu.store.store import TPUStore

        s = Session(store=TPUStore())
        s.execute("create table q (a bigint)")
        s.execute("insert into q values (5)")
        assert int(s.execute("select a from q").rows[0][0].val) == 5


class TestReviewRegressions:
    def test_read_only_txn_snapshot_survives_gc(self):
        """A lock-free open txn pins its snapshot against GC (review r3)."""
        s1 = Session()
        s1.execute("create table rr (id bigint primary key, v bigint)")
        s1.execute("insert into rr values (1, 10)")
        s2 = Session(store=s1.store, catalog=s1.catalog)
        s2.execute("begin")
        assert int(s2.execute("select v from rr where id = 1").rows[0][0].val) == 10
        s1.execute("update rr set v = 99 where id = 1")
        s1.store.run_gc()
        # repeatable read: the old version must still be there
        assert int(s2.execute("select v from rr where id = 1").rows[0][0].val) == 10
        s2.execute("commit")
        s1.store.run_gc()
        assert int(s2.execute("select v from rr where id = 1").rows[0][0].val) == 99

    def test_create_index_survives_restart(self):
        s1 = Session()
        s1.execute("create table ci (id bigint primary key, k bigint)")
        s1.execute("create unique index uk on ci (k)")
        s1.execute("insert into ci values (1, 7)")
        s2 = Session(store=s1.store)
        assert any(i.name == "uk" for i in s2.catalog.table("ci").indices)
        try:
            s2.execute("insert into ci values (2, 7)")
            raise AssertionError("unique index not enforced after restart")
        except Exception as exc:
            assert "duplicate" in str(exc)

    def test_handle_allocator_rebased_after_restart(self):
        s1 = Session()
        s1.execute("create table ha (a bigint)")  # hidden rowid handles
        s1.execute("insert into ha values (10), (20), (30)")  # DML advances allocator
        s2 = Session(store=s1.store)
        s2.execute("insert into ha values (40)")  # must not collide
        assert len(s2.execute("select * from ha").rows) == 4


class TestDefaultsPersist:
    def test_column_default_survives_restart(self):
        s1 = Session()
        s1.execute("create table dd (id bigint primary key, v bigint default 5, ts datetime default current_timestamp)")
        s2 = Session(store=s1.store)
        s2.execute("insert into dd (id) values (1)")
        r = s2.execute("select v from dd where id = 1")
        assert int(r.rows[0][0].val) == 5


class TestAutocommitReadPin:
    def test_autocommit_read_ts_pins_snapshot_against_gc(self):
        """ADVICE r3: a background GC tick between an autocommit read's TSO
        draw and its kv reads must not collect the version visible at the
        read ts (ref: gc_worker.go calcSafePointByMinStartTS)."""
        s = Session()
        s.execute("create table gp (id bigint primary key, v bigint)")
        s.execute("insert into gp values (1, 10)")
        ts = s._pin_read_ts()  # autocommit statement's ts draw
        s.execute("update gp set v = 11 where id = 1")  # newer version lands
        s.store.run_gc()  # background GCWorker tick mid-statement
        from tidb_tpu.codec import tablecodec

        meta = s.catalog.table("gp")
        key = tablecodec.encode_row_key(meta.table_id, 1)
        # the version visible at `ts` survived the GC pass
        assert any(vts <= ts for vts, _ in s.store.kv._data[key])
        row = s._read_row(meta, 1, ts)
        assert row is not None and int(row[1].val) == 10
        s._unpin_read_ts(ts)
        s.store.run_gc()  # unpinned: the old version may now go
        assert len(s.store.kv._data[key]) == 1
