"""Fragment planner — cut an eligible pushdown DAG at exchange boundaries
into ExchangeSender/ExchangeReceiver-linked fragments (ref:
pkg/planner/core/fragment.go:116 GenerateRootMPPTasks; the sender modes are
unistore/cophandler/mpp_exec.go:669-719).

The reference walks the physical plan top-down, starts a new fragment under
every ExchangeReceiver, and assigns each fragment one MPP task per
participating store. Here the cut points are structural — each JOIN
boundary (both sides hash-partition by the join key) and the final-agg
boundary (Partial1 states hash-partition by group key; the Final fragment
streams to root PassThrough) — and the task topology is the mesh itself:
every fragment runs `n_tasks` SPMD tasks, one per device, so the fragment
graph is a launch plan for ONE shard_map program (`mpp/exchange_op.py`)
rather than a process tree. The topology is STABLE: fragment indices are
assigned bottom-up per stage, so equal DAG shapes produce equal plans and
the wire frame (codec/wire.py encode_fragment_plan) round-trips them
byte-exactly.

The string width gate lives here because it is a property of the EXCHANGE,
not of any one tier: packed compare words carry the first
STRING_WORDS*8 bytes across the all_to_all; longer values would silently
truncate, so every exchange consumer (mesh tier, mpp tier) shares this
check. flen counts CHARACTERS (utf8mb4: up to 4 bytes each) and inserts do
not enforce it, so the static gate is advisory only — the authoritative
check measures actual bytes in the scanned chunks (chunks_exchange_safe).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..exec.dag import Aggregation, DAGRequest, Join, Selection, TableScan

# exchange partition modes (ref: mpp_exec.go:669 partition types)
EXCHANGE_HASH = "hash"
EXCHANGE_BROADCAST = "broadcast"
EXCHANGE_PASSTHROUGH = "passthrough"

# widest string (bytes) the packed compare words carry byte-exactly
MAX_EXCHANGE_STR = 32

# the root collector pseudo-fragment: the Final fragment's PassThrough
# sender streams to it (ref: the TiDB-side MPPGather above the plan)
ROOT_COLLECTOR = -1


def chunks_exchange_safe(chunks) -> bool:
    """No string value in any scanned column exceeds the packed-word width
    the exchange can carry byte-exactly."""
    for c in chunks:
        for col in c.columns:
            if col.is_varlen() and len(col):
                if int((col.offsets[1:] - col.offsets[:-1]).max()) > MAX_EXCHANGE_STR:
                    return False
    return True


@dataclass(frozen=True)
class ExchangeSender:
    """The fragment's output boundary (ref: PhysicalExchangeSender)."""

    exchange_type: str        # EXCHANGE_HASH | _BROADCAST | _PASSTHROUGH
    partition_keys: tuple     # Expr tuple (hash mode; empty otherwise)
    target_fragment: int      # receiving fragment idx (ROOT_COLLECTOR = root)


@dataclass(frozen=True)
class ExchangeReceiver:
    """The fragment's input boundary (ref: PhysicalExchangeReceiver)."""

    source_fragment: int      # fragment whose sender feeds this input


@dataclass(frozen=True)
class Fragment:
    """One exchange-delimited plan slice; runs n_tasks SPMD tasks."""

    idx: int
    executors: tuple          # DAG executor nodes local to this fragment
    receivers: tuple          # ExchangeReceiver inputs, probe side first
    sender: ExchangeSender


@dataclass(frozen=True)
class FragmentPlan:
    fragments: tuple
    n_tasks: int              # tasks per fragment = mesh width
    root: int                 # idx of the Final fragment (streams to root)


def split_join_dag(dag: DAGRequest):
    """-> (probe_scan, pre_sels, [(join, post_sels), ...], agg) or None.

    A CHAIN of shuffle joins is eligible (TPC-H Q3's 3-table shape:
    lineitem ⋈ orders ⋈ customer — each stage re-exchanges the widened
    schema by the next join key, ref: fragment.go stacking ExchangeSender
    under each HashJoin). Build sides must be scan [selection]* — a join
    nested INSIDE a build side still stays off-mesh; the planner
    right-deepens chains so that shape is the common one."""
    exs = dag.executors
    if not exs or not isinstance(exs[0], TableScan):
        return None
    i = 1
    pre = []
    while i < len(exs) and isinstance(exs[i], Selection):
        pre.append(exs[i])
        i += 1
    stages = []
    while i < len(exs) and isinstance(exs[i], Join):
        join = exs[i]
        i += 1
        post = []
        while i < len(exs) and isinstance(exs[i], Selection):
            post.append(exs[i])
            i += 1
        if not join.build or not isinstance(join.build[0], TableScan):
            return None
        if not all(isinstance(e, Selection) for e in join.build[1:]):
            return None
        stages.append((join, post))
    if not stages or i != len(exs) - 1 or not isinstance(exs[i], Aggregation):
        return None
    return exs[0], pre, stages, exs[i]


def fragment_kind(dag: DAGRequest) -> str | None:
    """Exchange-shape eligibility — "agg" | "join" | None. Delegates to the
    shared gate (parallel/sql.py mesh_eligible: DAG shape + host-only-expr
    refusal), which both the mesh shortcut and the mpp tier consult."""
    from ..parallel.sql import mesh_eligible

    return mesh_eligible(dag)


def fragment_plan(dag: DAGRequest, n_tasks: int) -> FragmentPlan | None:
    """Cut the DAG at its exchange boundaries (fragment.go:116 analog).

    Join shape — per stage i, bottom-up:

        [probe scan frag] --hash(probe key 0)--\\
        [build frag 0]    --hash(build key 0)---> [join frag 0] --hash(...)-> ...
                                ...                [join frag k] --hash(group key)-> [final frag] --passthrough-> root

    Agg shape: [scan+sel+Partial1] --hash(group key)--> [Final] -> root.
    The SAME Aggregation node appears in both agg-boundary fragments: its
    mode (Partial1 vs Final merge) is positional, exactly as the device
    program splits it (grouped.agg_exchange_phases phases 1 and 3)."""
    parts = split_join_dag(dag)
    if parts is not None:
        probe_scan, pre_sels, stages, agg = parts
        frags = []
        n_stages = len(stages)

        def join_frag_idx(i):
            return 2 + 2 * i

        frags.append(Fragment(
            idx=0,
            executors=(probe_scan, *pre_sels),
            receivers=(),
            sender=ExchangeSender(EXCHANGE_HASH, tuple(stages[0][0].probe_keys), join_frag_idx(0)),
        ))
        for i, (join, post_sels) in enumerate(stages):
            frags.append(Fragment(
                idx=2 * i + 1,
                executors=tuple(join.build),
                receivers=(),
                sender=ExchangeSender(EXCHANGE_HASH, tuple(join.build_keys), join_frag_idx(i)),
            ))
            last = i == n_stages - 1
            if last:
                out = ExchangeSender(EXCHANGE_HASH, tuple(agg.group_by), 2 * n_stages + 1)
            else:
                out = ExchangeSender(EXCHANGE_HASH, tuple(stages[i + 1][0].probe_keys), join_frag_idx(i + 1))
            upstream = 0 if i == 0 else join_frag_idx(i - 1)
            frags.append(Fragment(
                idx=join_frag_idx(i),
                executors=(join, *post_sels, *((agg,) if last else ())),
                receivers=(ExchangeReceiver(upstream), ExchangeReceiver(2 * i + 1)),
                sender=out,
            ))
        root_idx = 2 * n_stages + 1
        frags.append(Fragment(
            idx=root_idx,
            executors=(agg,),
            receivers=(ExchangeReceiver(join_frag_idx(n_stages - 1)),),
            sender=ExchangeSender(EXCHANGE_PASSTHROUGH, (), ROOT_COLLECTOR),
        ))
        return FragmentPlan(tuple(frags), n_tasks, root_idx)

    # agg shape: scan [Selection]* Aggregation(GROUP BY)
    exs = dag.executors
    if (len(exs) < 2 or not isinstance(exs[0], TableScan)
            or not isinstance(exs[-1], Aggregation)
            or not all(isinstance(e, Selection) for e in exs[1:-1])):
        return None
    agg = exs[-1]
    if not agg.group_by:
        return None
    frags = (
        Fragment(
            idx=0,
            executors=tuple(exs),
            receivers=(),
            sender=ExchangeSender(EXCHANGE_HASH, tuple(agg.group_by), 1),
        ),
        Fragment(
            idx=1,
            executors=(agg,),
            receivers=(ExchangeReceiver(0),),
            sender=ExchangeSender(EXCHANGE_PASSTHROUGH, (), ROOT_COLLECTOR),
        ),
    )
    return FragmentPlan(frags, n_tasks, 1)
