"""MPP dispatch — the coordination layer above the fragment planner and
the exchange operator (ref: pkg/executor/mpp_gather.go MPPGather +
store/copr/mpp.go DispatchMPPTask; unistore/cophandler/mpp.go handles the
task side).

The reference's coordinator cuts the plan into fragments, serializes each
fragment into a DispatchMPPTaskRequest per store, and gathers the root
fragment's PassThrough stream. Here the task topology IS the device mesh:
every fragment runs n_tasks SPMD tasks inside ONE shard_map program
(`exchange_op.run_exchange_join_agg` / `grouped.run_sharded_grouped_agg`),
so "dispatch" means (1) prove the fragment topology (`fragment_plan`) and
round-trip it through the wire codec — the executed plan is the DECODED
one, the same seam a real coordinator ships across the network — then
(2) source the probe-side scan, preferring the columnar replica's
device-resident stable chunks when the replica covers the snapshot
(`columnar_would_serve` + the data_not_ready readiness gate), falling back
to the row-store scan pushdown otherwise, and (3) launch the exchange
program with the overflow capacity ladder.

Failure discipline mirrors `columnar/route.py`: every decline is a COUNTED
fallback (`MPP_FALLBACKS`) and the caller dispatches to the next tier as
if routing never happened — degrade, never fail; the row store still owns
the authoritative answer. Typed region errors and epoch fall-out surface
from the row-store scan path itself (`distsql.dispatch.select`), so a
mid-query region split aborts the MPP attempt with the same typed shape
the per-region path raises.

Failpoints:
  mpp/dispatch-lost   a task dispatch is lost before launch — counted
                      fallback to the non-MPP tiers.
  mpp/exchange-stall  an exchange never delivers mid-run — the
                      coordinator abandons the run (counted fallback).
"""

from __future__ import annotations

from ..chunk import Chunk
from ..exec.dag import DAGRequest
from .fragment import chunks_exchange_safe, fragment_kind, fragment_plan

MPP_SYSVAR = "tidb_allow_mpp"

# (encoded dag, n devices, base group capacity) -> last successful
# (gc, scale) ladder rung; bounded FIFO, see execute_exchange_plan
_LADDER_HINTS: dict[tuple, tuple[int, int]] = {}


def _chunks_nbytes(chunks) -> int:
    total = 0
    for c in chunks:
        if c is not None:
            total += int(c.nbytes())
    return total


def execute_exchange_plan(dag, chunks, aux_chunks, kind, devs,
                          group_capacity: int = 1024) -> Chunk | None:
    """Launch the exchange program over already-scanned chunks — the
    shared execution core of the mesh tier and the mpp tier. Region
    chunks play the task lanes; build tables are sliced across devices so
    each slice plays a region shard. Overflow (too many groups / join
    fan-out / hash collision) retries with 4x capacity — the capacity
    also salts the hash, mirroring drive_program's contract — reusing the
    scanned chunks, not rescanning. Returns the projected result Chunk,
    or None for a fallback to the per-region path."""
    from ..parallel.grouped import run_sharded_grouped_agg
    from ..parallel.mesh import region_mesh, stack_region_batches
    from ..util import metrics

    agg = dag.executors[-1]
    out_fts = agg.output_fts()
    if not chunks:
        # zero rows scanned: grouped aggregation of nothing is no groups
        return Chunk.empty([out_fts[i] for i in dag.output_offsets])
    if not chunks_exchange_safe(chunks):
        return None  # wide strings cannot ride the exchange byte-exactly

    n = len(devs)
    n_total = ((len(chunks) + n - 1) // n) * n
    try:
        stacked = stack_region_batches(chunks, n_total=n_total)
    except NotImplementedError:
        return None  # e.g. non-ASCII CI data: the per-region path's
        # oracle fallback owns it (chunk/device.py guard)
    mesh = region_mesh(n)

    stacked_builds = None
    if kind == "join":
        from .fragment import split_join_dag

        n_stages = len(split_join_dag(dag)[2])
        if aux_chunks is None or len(aux_chunks) < n_stages:
            return None
        stacked_builds = []
        for build in aux_chunks[:n_stages]:
            if not chunks_exchange_safe([build]):
                return None
            if build.num_rows() == 0:
                bslices = [build]
            else:
                step = (build.num_rows() + n - 1) // n
                bslices = [
                    build.slice(i * step, min((i + 1) * step, build.num_rows()))
                    for i in range(n)
                    if i * step < build.num_rows()
                ]
            try:
                stacked_builds.append(stack_region_batches(bslices, n_total=n))
            except NotImplementedError:
                return None  # non-ASCII CI build data -> per-region path

    # the ladder's start rung is remembered per plan identity: a skewed key
    # distribution that overflowed rung 1 last time will overflow it again —
    # a repeated digest starts at the rung that last succeeded, so the
    # steady state is ONE cached program, not a re-walk of the failed rungs
    from ..codec.wire import encode_dag

    hint_key = (encode_dag(dag), n, group_capacity)
    gc, scale = _LADDER_HINTS.get(hint_key, (group_capacity, 1))
    for _ in range(3):
        try:
            if kind == "join":
                from .exchange_op import run_exchange_join_agg

                chunk, overflow = run_exchange_join_agg(
                    dag, stacked, stacked_builds, mesh, group_capacity=gc, scale=scale
                )
            else:
                chunk, overflow = run_sharded_grouped_agg(dag, stacked, mesh, group_capacity=gc)
        except NotImplementedError:
            # an op the device compiler refuses slipped past the static
            # gate: fall back to the per-region thread-pool path, which
            # keeps host-only work at root (mirrors store.coprocessor's
            # oracle fallback)
            return None
        if not overflow:
            if len(_LADDER_HINTS) >= 256:
                _LADDER_HINTS.pop(next(iter(_LADDER_HINTS)))
            _LADDER_HINTS[hint_key] = (gc, scale)
            metrics.MESH_SELECTS.inc()
            cols = [chunk.columns[i] for i in dag.output_offsets]
            return Chunk(cols)
        # one overflow flag covers groups, exchange buckets, and join
        # fan-out. Exchange/fan-out skew (scale) is far more common than
        # group-count overflow in chain shapes, and gc inflates the group
        # tables of EVERY device — so the middle rung grows scale alone,
        # and only the last rung grows both
        if scale >= 4:
            gc *= 4
        scale *= 4
    return None  # caller falls back to the per-region path


def _replica_probe_chunks(store, dag, ranges, start_ts, n_lanes,
                          engines, backoff_weight, checker):
    """Source the probe scan from the columnar replica's stable chunks,
    sliced into n_lanes task shards. Returns a chunk list, or None when
    the replica does not cover the snapshot (the row-store scan pushdown
    is the fallback source — not a query failure)."""
    from ..columnar.replica import ColumnarNotReady, _schema_sig
    from ..columnar.route import _plan_intervals, _wait_ready, columnar_would_serve
    from ..util import metrics

    # the probe fragment's scan is the bare TableScan — the mpp eligibility
    # gate already proved the analytical shape, so would-serve is asked on
    # the FULL dag (Aggregation present) with the probe's ranges
    if not columnar_would_serve(store, dag, ranges, engines):
        return None
    rep = store.columnar
    plan = _plan_intervals(dag, ranges)
    if not plan:
        return None
    sig = _schema_sig(dag.scan().columns)
    tables = []
    for pid in plan:
        t = rep.table_for(pid)
        if t is None or t.schema_sig != sig:
            return None
    for pid in plan:
        tables.append(rep.table_for(pid))
    ts_eff = _wait_ready(store, tables, start_ts, backoff_weight, checker)
    if ts_eff is None:
        metrics.COLUMNAR_FALLBACKS.inc()
        return None
    try:
        scans = [t.scan(ts_eff, plan[pid]) for pid, t in zip(plan, tables)]
    except ColumnarNotReady:
        # a compaction advanced the floor between the gate and the scan
        metrics.COLUMNAR_FALLBACKS.inc()
        return None
    except Exception:  # noqa: BLE001 — degrade, never fail: the row
        # store still owns the authoritative answer
        metrics.COLUMNAR_FALLBACKS.inc()
        return None
    merged = scans[0][0] if len(scans) == 1 else Chunk.concat([c for c, _b in scans])
    rows = merged.num_rows()
    if rows == 0:
        return []
    step = (rows + n_lanes - 1) // n_lanes
    return [
        merged.slice(i * step, min((i + 1) * step, rows))
        for i in range(n_lanes)
        if i * step < rows
    ]


def try_mpp_select(
    store,
    dag: DAGRequest,
    ranges: list,
    start_ts: int,
    *,
    group_capacity: int = 1024,
    min_devices: int = 2,
    aux_chunks: list | None = None,
    engines: tuple = (),
    backoff_weight: int = 2,
    checker=None,
) -> Chunk | None:
    """Plan and run an eligible DAG as an MPP fragment graph; None = not
    taken (counted fallback — the caller dispatches to the mesh shortcut /
    per-region tiers as if MPP routing never happened)."""
    kind = fragment_kind(dag)
    if kind is None:
        return None
    if kind == "join" and not aux_chunks:
        return None
    import jax

    devs = jax.devices()
    if len(devs) < min_devices:
        return None
    fplan = fragment_plan(dag, n_tasks=len(devs))
    if fplan is None:
        return None
    from ..util import failpoint, metrics, tracing

    # the wire seam: a real coordinator ships each fragment inside a
    # DispatchMPPTaskRequest — round-trip the topology through the codec
    # so the EXECUTED plan is the decoded one, byte-exact
    from ..codec.wire import decode_fragment_plan, encode_fragment_plan

    fplan = decode_fragment_plan(encode_fragment_plan(fplan))
    if failpoint.eval("mpp/dispatch-lost"):
        # a task dispatch was lost before launch: abandon the MPP run
        metrics.MPP_FALLBACKS.inc()
        return None
    with tracing.span("mpp.dispatch", kind=kind, n_fragments=len(fplan.fragments),
                      n_tasks=fplan.n_tasks, n_ranges=len(ranges)) as sp:
        chunks = _replica_probe_chunks(
            store, dag, ranges, start_ts, len(devs), engines,
            backoff_weight, checker)
        replica_served = chunks is not None
        if chunks is None:
            # row-store scan pushdown (paging/retry, typed region errors
            # and epoch fall-out preserved — a mid-query split raises the
            # same typed shape the per-region path does)
            from ..distsql.dispatch import KVRequest, select

            scan = dag.executors[0]
            scan_dag = DAGRequest((scan,), output_offsets=tuple(range(len(scan.columns))))
            res = select(store, KVRequest(scan_dag, ranges, start_ts))
            chunks = [c for c in res.chunks if c is not None and c.num_rows() > 0]
        if failpoint.eval("mpp/exchange-stall"):
            # an exchange never delivered mid-run: abandon the MPP run
            metrics.MPP_FALLBACKS.inc()
            return None
        out = execute_exchange_plan(dag, chunks, aux_chunks, kind, devs,
                                    group_capacity=group_capacity)
        if out is None:
            metrics.MPP_FALLBACKS.inc()
            return None
        metrics.MPP_SELECTS.inc()
        metrics.MPP_FRAGMENTS.inc(len(fplan.fragments))
        metrics.MPP_TASKS.inc(len(fplan.fragments) * fplan.n_tasks)
        metrics.MPP_EXCHANGED_BYTES.inc(
            _chunks_nbytes(chunks) + _chunks_nbytes(aux_chunks or []))
        if sp is not None:
            sp.set("rows", out.num_rows())
            sp.set("replica_served", replica_served)
        return out
