"""On-device exchange operator — ExchangeSender / ExchangeReceiver as mesh
collectives (ref: unistore/cophandler/mpp_exec.go:609-841 exchSenderExec /
exchRecvExec; partition modes :669-719).

The reference's ExchangeSender hash-partitions rows by fnv64 over the
encoded partition keys into per-task tunnels, and ExchangeReceiver merges
the streams. On TPU the tunnels are a single `jax.lax.all_to_all` over the
mesh axis: each device scatters its rows into P send buckets by key hash,
the collective transposes buckets across devices, and every device ends up
owning one hash partition — then local group aggregation (or join
build/probe) runs on owned rows only.

This module is the ONE home of that machinery (ISSUE 18): the scatter ->
all_to_all -> flatten sequence that used to be hand-rolled four times over
(`parallel/exchange.py`, joinmesh's `_exchange_side`, grouped's state and
distinct phases) is `exchange_arrays`; the shuffle-join device program
(`run_exchange_join_agg`) lives here and `parallel/joinmesh.py` wraps it.
The all_to_all is explicit — not sharding-propagated — because the
partition function is data-dependent (hash of key values).

`local_partition_join` is the per-partition join the receivers feed: the
planner-unified key shape routes through the radix-partitioned kernel when
its plan gate passes (including the NON-unique build via the expansion
lift, the ISSUE 13 follow-on), and through the monolithic sort-merge
kernel otherwise — one semantics, strategy-routed at trace time.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..expr.compile import CompVal, ExprCompiler, normalize_device_column
from ..ops import apply_selection
from ..ops.keys import sort_key_arrays

# the 1-D mesh axis every exchange collective runs over; canonical HERE so
# the operator has no import-time dependency on parallel/ (parallel/mesh.py
# re-exports it — the wrapper depends on the subsystem, never the reverse)
REGION_AXIS = "region"

FNV_OFFSET = np.int64(-3750763034362895579)  # 0xcbf29ce484222325 as i64; numpy: import-time pure
FNV_PRIME = np.int64(1099511628211)
# murmur3 fmix64 constants (as i64 two's complement). The FNV fold alone is
# NOT enough to partition with: one multiply by an odd prime leaves
# `h mod 2^b` a function of `k mod 2^b` alone, so with a power-of-two
# n_parts the partition id ignores every high bit — derived keys that share
# low bits with the previous stage's key (ckey = oid % 64 after an exchange
# on oid) land 100% of a device's rows in ONE bucket, and all-even keys use
# half the partitions. The xor-shift finalizer avalanches high bits down.
FMIX_C1 = np.int64(np.uint64(0xFF51AFD7ED558CCD).astype(np.int64))
FMIX_C2 = np.int64(np.uint64(0xC4CEB9FE1A85EC53).astype(np.int64))


def hash_partition_ids(key_vals: list[CompVal], n_parts: int) -> jax.Array:
    """Row -> partition id in [0, n_parts) from an FNV-style fold over the
    normalized key words, finished with the murmur3 fmix64 avalanche (NULL
    hashes to partition of its zeroed words — all NULLs land together, as
    the reference's encoded-datum hash does)."""
    h = jnp.broadcast_to(FNV_OFFSET, key_vals[0].null.shape)
    for kv in key_vals:
        for w in sort_key_arrays(kv):
            if jnp.issubdtype(w.dtype, jnp.floating):
                # real keys stay float in sort_key_arrays (TPU x64 emulation
                # can't bitcast f64<->s64); a f32 bitcast is supported and
                # equal doubles hash equal, which is all partitioning needs
                w = jax.lax.bitcast_convert_type(w.astype(jnp.float32), jnp.int32).astype(jnp.int64)
            h = (h ^ w) * FNV_PRIME
    h = (h ^ jax.lax.shift_right_logical(h, 33)) * FMIX_C1
    h = (h ^ jax.lax.shift_right_logical(h, 33)) * FMIX_C2
    h = h ^ jax.lax.shift_right_logical(h, 33)
    # avoid negative mod
    return jnp.abs(h % n_parts).astype(jnp.int32)


def scatter_to_buckets(cols: list[jax.Array], valid: jax.Array, part: jax.Array, n_parts: int, bucket_cap: int):
    """Pack rows into [n_parts, bucket_cap] send buffers by partition id.

    Position within a bucket = rank of the row among same-partition rows
    (prefix count). Returns (bucketed cols, bucket valid, overflow flag).
    """
    n = valid.shape[0]
    part = jnp.where(valid, part, n_parts)  # invalid rows -> ghost bucket
    onehot = part[:, None] == jnp.arange(n_parts + 1)[None, :]  # [n, P+1]
    rank = jnp.cumsum(onehot, axis=0) - 1  # rank within partition
    pos_in_bucket = jnp.take_along_axis(rank, part[:, None], axis=1)[:, 0]
    counts = onehot.sum(axis=0)[:n_parts]
    overflow = jnp.any(counts > bucket_cap)
    flat_pos = part * bucket_cap + jnp.minimum(pos_in_bucket, bucket_cap - 1)
    total = (n_parts + 1) * bucket_cap

    out_valid = jnp.zeros(total, bool).at[flat_pos].set(valid & (pos_in_bucket < bucket_cap))
    out_cols = []
    for c in cols:
        buf = jnp.zeros((total,) + c.shape[1:], c.dtype)
        buf = buf.at[flat_pos].set(c)
        out_cols.append(buf.reshape((n_parts + 1, bucket_cap) + c.shape[1:])[:n_parts])
    return out_cols, out_valid.reshape(n_parts + 1, bucket_cap)[:n_parts], overflow


def exchange_arrays(arrays: list[jax.Array], valid, part, n_parts: int, bucket_cap: int, axis: str = REGION_AXIS):
    """ExchangeSender Hash mode + ExchangeReceiver merge for raw arrays:
    scatter rows into per-destination buckets, `all_to_all` the buckets
    over the mesh axis (dim0 indexes destination partition going in, source
    device coming out — ref: ExchangerTunnel per-task streams), and flatten
    the received [P, cap] tables back to rows. Returns (arrays, valid,
    overflow): every row of this device's hash partition, from all peers."""
    bufs, bvalid, overflow = scatter_to_buckets(arrays, valid, part, n_parts, bucket_cap)
    recv = [jax.lax.all_to_all(b, axis, 0, 0, tiled=False) for b in bufs]
    rvalid = jax.lax.all_to_all(bvalid, axis, 0, 0, tiled=False)
    flat = [r.reshape((-1,) + r.shape[2:]) for r in recv]
    return flat, rvalid.reshape(-1), overflow


def broadcast_exchange(mesh_axis: str, cols: list, valid):
    """Broadcast mode (ref: mpp_exec.go:669 Broadcast partition type, the
    TiFlash broadcast-join operand path): every device receives EVERY row.
    Returns ([P*n]-shaped cols, valid) identical on all devices — one
    all_gather over ICI per column."""
    out_cols = []
    for c in cols:
        g = jax.lax.all_gather(c, mesh_axis, axis=0, tiled=False)  # [P, n, ...]
        out_cols.append(g.reshape((-1,) + c.shape[1:]))
    gv = jax.lax.all_gather(valid, mesh_axis, axis=0, tiled=False).reshape(-1)
    return out_cols, gv


def passthrough_exchange(mesh_axis: str, cols: list, valid, target: int = 0):
    """PassThrough mode (ref: mpp_exec.go:669-719 PassThrough partition
    type — the root-gather: every task streams all rows to the single
    collector). All devices' rows land on `target`; other devices keep the
    buffers (SPMD static shapes) with all-False validity."""
    out_cols, gv = broadcast_exchange(mesh_axis, cols, valid)
    me = jax.lax.axis_index(mesh_axis)
    gv = gv & (me == target)
    return out_cols, gv


def exchange_group_aggregate(mesh_axis: str, key_vals, agg_fn, cols, valid, n_parts: int, bucket_cap: int):
    """Inside shard_map: hash-exchange rows so each device owns one hash
    partition, then run `agg_fn(owned_cols, owned_valid)` locally.

    agg_fn receives rows of shape [n_parts * bucket_cap] (all rows of this
    device's partition gathered from every peer).
    """
    part = hash_partition_ids(key_vals, n_parts)
    flat_cols, flat_valid, overflow = exchange_arrays(cols, valid, part, n_parts, bucket_cap, axis=mesh_axis)
    overflow = jax.lax.pmax(overflow.astype(jnp.int32), mesh_axis) > 0
    return agg_fn(flat_cols, flat_valid), overflow


def exchange_compvals(cvals: list[CompVal], valid, part, n_parts: int, bucket_cap: int, axis: str = REGION_AXIS):
    """`exchange_arrays` over typed columns: each CompVal rides the wire as
    its (value, null) array pair and is reassembled on the receiver with
    its FieldType intact."""
    flat = [a for c in cvals for a in (c.value, c.null)]
    flat_r, rvalid, ovf = exchange_arrays(flat, valid, part, n_parts, bucket_cap, axis=axis)
    out = [
        CompVal(flat_r[2 * i], flat_r[2 * i + 1].astype(bool), c.ft)
        for i, c in enumerate(cvals)
    ]
    return out, rvalid, ovf


def gather_compvals(cols: list[CompVal], idx) -> list[CompVal]:
    out = []
    for c in cols:
        if c.value.ndim == 2:
            out.append(CompVal(c.value[idx, :], c.null[idx], c.ft))
        else:
            out.append(CompVal(c.value[idx], c.null[idx], c.ft))
    return out


def local_partition_join(build_keys, probe_keys, build_valid, probe_valid,
                         out_capacity: int, join_type: str, build_unique: bool):
    """The per-partition join above the receivers (ref: mpp_exec.go:844
    joinExec). Strategy-routed at TRACE time on static shapes: the
    radix-partitioned kernel when its plan gate passes on a single-word
    int-class key — including the NON-unique build, which rides the
    expansion lift the exchange unlocked (ops/radix_join.py) — and the
    monolithic sort-merge kernel everywhere else. Identical JoinResult
    contract either way, so the caller never knows which ran."""
    from ..ops.join import _key_matrix, hash_join
    from ..ops.radix_join import radix_hash_join, radix_plan

    nb = int(build_valid.shape[0])
    np_ = int(probe_valid.shape[0])
    plan = radix_plan(nb, np_, out_capacity)
    if plan is not None and len(build_keys) == 1 and len(probe_keys) == 1:
        bw, _bu = _key_matrix(build_keys, build_valid)
        pw, _pu = _key_matrix(probe_keys, probe_valid)
        if (len(bw) == 1 and len(pw) == 1
                and not jnp.issubdtype(bw[0].dtype, jnp.floating)
                and not jnp.issubdtype(pw[0].dtype, jnp.floating)):
            res, _escapes = radix_hash_join(
                build_keys, probe_keys, build_valid, probe_valid,
                join_type, out_capacity, plan,
                build_unique=build_unique, out_capacity=out_capacity,
            )
            return res
    return hash_join(
        build_keys, probe_keys, build_valid, probe_valid,
        out_capacity=out_capacity, join_type=join_type,
        build_unique=build_unique,
    )


def exchange_join_program(dag, mesh, group_capacity: int = 1024, scale: int = 1):
    """Build (don't run) the shuffle-join shard_map program for an eligible
    chain DAG: `fn(stacked_probe, *stacked_builds) -> flat group outputs`.
    Split from `run_exchange_join_agg` so the jax-audit catalog can trace
    the exchange-join shape through the jaxpr checks without launching."""
    from ..parallel.grouped import _flatten_local, agg_exchange_phases
    from .fragment import split_join_dag

    parts = split_join_dag(dag)
    assert parts is not None, "not a shuffle-join DAG shape"
    probe_scan, pre_sels, stages, agg = parts
    pfts = [c.ft for c in probe_scan.columns]
    n_parts = mesh.devices.size

    def device_fn(lp, *lbs):
        pcols, pvalid = _flatten_local(lp)
        pc = [normalize_device_column(c) for c in pcols]
        for ex in pre_sels:
            conds = ExprCompiler(pfts).run(list(ex.conditions), pc)
            pvalid = apply_selection(pvalid, conds)
        # drop raw string bytes: only packed words cross the exchange
        pc = [CompVal(c.value, c.null, c.ft) for c in pc]
        schema = list(pfts)
        valid = pvalid
        cols = pc
        extra = jnp.bool_(False)
        # expected VALID rows per device (static): post-exchange each device
        # owns one hash partition ~ total/n, and total stacked rows are
        # n * lane_rows — so the fair share IS the lane size. Capacities
        # derive from this estimate, NOT from the previous stage's padded
        # slot count: slot-derived caps compound `2*scale` per stage
        # (scale^2 across a chain — the 8-device bench paid 500K-slot
        # exchanges for a 16K-row table). Skew past the 2x headroom is the
        # ladder's job, and `scale` grows est linearly, never quadratically.
        est = valid.shape[0]

        for (join, post_sels), lb in zip(stages, lbs):
            bfts = [c.ft for c in join.build[0].columns]
            bcols, bvalid = _flatten_local(lb)
            bc = [normalize_device_column(c) for c in bcols]
            for ex in join.build[1:]:
                conds = ExprCompiler(bfts).run(list(ex.conditions), bc)
                bvalid = apply_selection(bvalid, conds)
            bc = [CompVal(c.value, c.null, c.ft) for c in bc]

            # hash-partition both sides by THIS stage's join key
            pkeys = ExprCompiler(schema).run(list(join.probe_keys), cols)
            bkeys = ExprCompiler(bfts).run(list(join.build_keys), bc)
            # 2.5x the fair share: hash partitioning is balanced per KEY,
            # not per row — a few dozen fat keys per device routinely put
            # one partition ~2.5x over the row mean, and a whole ladder
            # rung costs more than the 25% slack
            pcap = max(64, 5 * scale * est // (2 * n_parts))
            bcap_ = max(64, 5 * scale * bvalid.shape[0] // (2 * n_parts))
            pp = hash_partition_ids(pkeys, n_parts)
            bp = hash_partition_ids(bkeys, n_parts)
            pc2, pvalid2, povf = exchange_compvals(cols, valid, pp, n_parts, pcap)
            bc2, bvalid2, bovf = exchange_compvals(bc, bvalid, bp, n_parts, bcap_)

            # local join on the owned partition (ref: joinExec above receivers)
            pkeys2 = ExprCompiler(schema).run(list(join.probe_keys), pc2)
            bkeys2 = ExprCompiler(bfts).run(list(join.build_keys), bc2)
            if join.join_type in ("semi", "anti"):
                out_cap = pvalid2.shape[0]  # probe-shaped output
            else:
                if not join.build_unique:
                    est = 4 * est  # duplicate-build fan-out headroom
                out_cap = max(128, 2 * scale * est)
            res = local_partition_join(
                bkeys2, pkeys2, bvalid2, pvalid2,
                out_capacity=out_cap,
                join_type=join.join_type,
                build_unique=join.build_unique,
            )
            extra = extra | povf | bovf | res.overflow
            if join.join_type in ("semi", "anti"):
                cols = pc2
                valid = res.out_valid
            else:
                nb = bvalid2.shape[0]
                p_g = pc2 if res.probe_identity else gather_compvals(pc2, res.probe_idx)
                b_g = gather_compvals(bc2, jnp.clip(res.build_idx, 0, nb - 1))
                b_g = [CompVal(c.value, c.null | res.build_null, c.ft) for c in b_g]
                cols = p_g + b_g
                valid = res.out_valid
                schema = schema + (
                    [f.clone_nullable() for f in bfts]
                    if join.join_type == "left_outer" else bfts
                )
            for ex in post_sels:
                conds = ExprCompiler(schema).run(list(ex.conditions), cols)
                valid = apply_selection(valid, conds)

        # the state-exchange bucket cap is data-sized like the join
        # exchanges (distinct groups <= rows; gc-sized buckets made the agg
        # phase 8x the whole join's work at the upper ladder rungs)
        return agg_exchange_phases(
            agg, schema, cols, valid, n_parts, group_capacity,
            max(64, 2 * scale * est // n_parts), extra_overflow=extra,
        )

    from jax.sharding import PartitionSpec as P

    from ..parallel.compat import shard_map
    from ..parallel.mesh import group_mesh_out_spec

    def wrap(stacked_probe, *stacked_builds):
        spec_p = jax.tree.map(lambda _: P(REGION_AXIS), stacked_probe)
        spec_bs = tuple(jax.tree.map(lambda _: P(REGION_AXIS), sb) for sb in stacked_builds)
        fn = shard_map(device_fn, mesh=mesh, in_specs=(spec_p, *spec_bs),
                       out_specs=group_mesh_out_spec(agg), check_vma=False)
        return fn(stacked_probe, *stacked_builds)

    return wrap


# compiled exchange programs, keyed by (wire-encoded DAG, mesh devices,
# capacities). A fresh `jax.jit(closure)` per query re-traces the whole
# shard_map program every time — at bench scale the re-trace dominates the
# query by ~20x. The wire encoding is the plan identity (same bytes = same
# device program), so repeated statements hit XLA's executable cache; the
# jitted callable itself still keys on input shapes, so shape changes only
# re-trace, never collide. Bounded FIFO — a digest-churning workload evicts,
# it doesn't grow without bound.
_PROGRAM_CACHE: dict[tuple, object] = {}
_PROGRAM_CACHE_CAP = 64


def cached_exchange_program(dag, mesh, build, *cap_key):
    """`build() -> fn`, jitted + cached under the DAG's wire identity."""
    from ..codec.wire import encode_dag

    key = (encode_dag(dag),
           tuple(int(d.id) for d in mesh.devices.flat), *cap_key)
    fn = _PROGRAM_CACHE.get(key)
    if fn is None:
        if len(_PROGRAM_CACHE) >= _PROGRAM_CACHE_CAP:
            _PROGRAM_CACHE.pop(next(iter(_PROGRAM_CACHE)))
        fn = jax.jit(build())
        _PROGRAM_CACHE[key] = fn
    return fn


def run_exchange_join_agg(
    dag,
    stacked_probe,
    stacked_builds: list,
    mesh,
    group_capacity: int = 1024,
    scale: int = 1,
):
    """Execute scan [sel] (JOIN(scan [sel]) [sel])+ GROUP BY over the mesh
    as ONE shard_map program; returns (chunk, overflow flag). Output layout
    matches the single-chip executor: [agg results..., group keys...].
    Multi-join chains (TPC-H Q3) re-exchange the widened probe schema at
    every stage by that stage's join key — the per-fragment dataflow
    `mpp/fragment.py` plans is exactly these phases.

    Exchange buckets are sized ~2x the per-device fair share (total/n) so
    per-device post-exchange work stays ~1/n of the table — the point of
    the repartition; `scale` (grown by the caller's overflow retry)
    multiplies every data-dependent capacity: exchange buckets for skewed
    keys and the join out-capacity for fan-out > 1."""
    from ..parallel.mesh import decode_group_mesh_outputs
    from .fragment import split_join_dag

    if not isinstance(stacked_builds, (list, tuple)):
        stacked_builds = [stacked_builds]
    n_stages = len(split_join_dag(dag)[2])
    assert len(stacked_builds) == n_stages, "one build batch per join stage"
    agg = dag.executors[-1]
    fn = cached_exchange_program(
        dag, mesh,
        lambda: exchange_join_program(dag, mesh, group_capacity=group_capacity, scale=scale),
        group_capacity, scale)
    outs = fn(stacked_probe, *stacked_builds)
    # decode via the shared seam (parallel/mesh.py) — same layout as grouped
    return decode_group_mesh_outputs(outs, agg)
