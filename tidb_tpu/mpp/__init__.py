"""MPP exchange data plane (ref: pkg/planner/core/fragment.go +
unistore/cophandler/mpp_exec.go, SURVEY §3.2/§5).

The reference's MPP path cuts an eligible physical plan at every exchange
boundary into fragments (`fragment.go:116 GenerateRootMPPTasks`), hash-
partitions rows by join/group key in ExchangeSender, streams partitions
between peer tasks over per-task tunnels, and re-assembles above
ExchangeReceiver. The TPU-native mapping (SURVEY §5): the tunnels are ONE
`jax.lax.all_to_all` over the ICI mesh axis inside a single `shard_map`
program, the final-merge gather is a passthrough exchange, and fragments
are launch phases of that one program rather than separate processes.

Three layers, mirroring the reference's split:

  fragment.py     the fragment planner — cuts a shuffle-eligible DAG at
                  each join/final-agg boundary into ExchangeSender/
                  ExchangeReceiver-linked fragments with a stable task
                  topology (DAG analog of GenerateRootMPPTasks).
  exchange_op.py  the on-device exchange operator — hash partition ids,
                  scatter-to-bucket packing, the all_to_all collective,
                  and the shuffle-join device program. `parallel/`'s
                  grouped/join mesh paths are thin wrappers over this.
  dispatch.py     the dispatch/coordination layer (DispatchMPPTask
                  analog) — sources fragment inputs from the columnar
                  replica's stable chunks when the snapshot is covered
                  (row-store decode fallback otherwise), round-trips the
                  fragment frames through the wire codec, and runs the
                  overflow capacity ladder.

Import submodules directly (`from tidb_tpu.mpp import fragment`); this
package initializer stays import-light so the `parallel/` compatibility
shims can load it mid-initialization without a cycle.
"""
