"""Native (C++) runtime components with ctypes bindings.

The reference's scan-decode hot loop runs in native code (TiKV in Rust;
in-repo Go: rowcodec ChunkDecoder at cophandler/cop_handler.go:424-467).
This package builds the framework's C++ equivalent on first use with the
toolchain's g++ (no pip/pybind dependency — plain C ABI via ctypes) and
falls back to the pure-Python decoders when compilation or decoding fails,
so the native layer is a transparent accelerator, never a requirement.

Components:
  rowcodec.cpp  tt_decode_rows — rowcodec-v2 rows -> columnar buffers
                (compact ints, comparable floats, binary decimals to
                scaled int64, packed times, string pools, null masks)
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(__file__)
_SRC = os.path.join(_DIR, "src", "rowcodec.cpp")
_BUILD = os.path.join(_DIR, "_build")
_SO = os.path.join(_BUILD, "librowcodec.so")

_lock = threading.Lock()
_lib = None  # guarded_by: _lock
_lib_failed = False  # guarded_by: _lock

# column classes — must match rowcodec.cpp
CLS_INT, CLS_UINT, CLS_FLOAT, CLS_DECIMAL, CLS_STRING, CLS_HANDLE = 0, 1, 2, 3, 5, 7


def _build() -> bool:
    os.makedirs(_BUILD, exist_ok=True)
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", _SO, _SRC],
            check=True, capture_output=True, timeout=120,
        )
        return True
    except Exception:  # noqa: BLE001 — any toolchain problem = fallback
        return False


def get_lib():
    """The loaded shared library, building it if needed; None = unavailable."""
    global _lib, _lib_failed
    # double-checked fast path: once built, the unlocked read is stable
    if _lib is not None or _lib_failed:  # vet: ignore[lock-discipline]
        return _lib  # vet: ignore[lock-discipline]
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            stale = (not os.path.exists(_SO)
                     or os.path.getmtime(_SO) < os.path.getmtime(_SRC))
            if stale and not _build():
                _lib_failed = True
                return None
            lib = ctypes.CDLL(_SO)
            lib.tt_decode_rows.restype = ctypes.c_int
            lib.tt_decode_rows.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_int64,
            ]
            if lib.tt_version() != 2:
                _lib_failed = True
                return None
            _lib = lib
        except Exception:  # noqa: BLE001
            _lib_failed = True
    return _lib  # vet: ignore[lock-discipline] — set under the lock above


def available() -> bool:
    return get_lib() is not None


def _col_class(ft) -> tuple[int, int] | None:
    """FieldType -> (class, decimal scale) or None when unsupported."""
    from ..types import TypeCode

    if ft.is_int():
        return (CLS_UINT if ft.is_unsigned() else CLS_INT), 0
    if ft.tp == TypeCode.Double:
        return CLS_FLOAT, 0
    if ft.is_decimal():
        return CLS_DECIMAL, max(ft.decimal, 0)
    if ft.is_time():
        return CLS_UINT, 0
    if ft.is_duration():
        return CLS_INT, 0
    if ft.tp in (TypeCode.Enum, TypeCode.Set, TypeCode.Bit):
        return CLS_UINT, 0
    if ft.is_string() and ft.tp != TypeCode.JSON:
        return CLS_STRING, 0
    return None  # Float32, JSON: python fallback


def decode_rows_columnar(values: list, handles: list, columns) -> "list | None":
    """Decode rowcodec-v2 value blobs into host Columns (one per requested
    scan column). Returns None when the native path is unavailable or the
    schema/bytes are outside its coverage — caller falls back."""
    from ..chunk.column import Column, numpy_dtype_for

    lib = get_lib()
    if lib is None:
        return None
    classes = []
    for c in columns:
        if c.col_id == -1:
            classes.append((CLS_HANDLE, 0))
            continue
        cc = _col_class(c.ft)
        if cc is None:
            return None
        classes.append(cc)
    n_rows, n_cols = len(values), len(columns)
    if n_cols > 256:
        return None
    blob = b"".join(values)
    row_offs = np.zeros(n_rows + 1, np.int64)
    np.cumsum([len(v) for v in values], out=row_offs[1:])
    blob_arr = np.frombuffer(blob, np.uint8) if blob else np.zeros(0, np.uint8)
    handles_arr = np.asarray(handles, np.int64) if handles else np.zeros(n_rows, np.int64)
    ids = np.array([c.col_id for c in columns], np.int64)
    cls_arr = np.array([c for c, _ in classes], np.uint8)
    scale_arr = np.array([s for _, s in classes], np.int32)
    out_fixed = np.zeros((n_cols, max(n_rows, 1)), np.int64)
    out_null = np.zeros((n_cols, max(n_rows, 1)), np.uint8)
    out_len = np.zeros((n_cols, max(n_rows, 1)), np.int64)
    # pool rows exist only for string columns (upper bound per column:
    # every value byte in the batch)
    pool_idx = np.full(n_cols, -1, np.int32)
    n_str = 0
    for i, (c, _) in enumerate(classes):
        if c == CLS_STRING:
            pool_idx[i] = n_str
            n_str += 1
    pool_stride = len(blob) if n_str else 0
    pool = np.zeros((max(n_str, 1), max(pool_stride, 1)), np.uint8)

    def p(a):
        return a.ctypes.data_as(ctypes.c_void_p)

    rc = lib.tt_decode_rows(
        p(blob_arr), p(row_offs), n_rows, p(handles_arr), p(ids), p(cls_arr),
        p(scale_arr), p(pool_idx), n_cols, p(out_fixed), p(out_null), p(out_len),
        p(pool), pool_stride if n_str else 1,
    )
    if rc != 0:
        from ..util import metrics

        metrics.NATIVE_DECODE_FALLBACKS.inc()
        return None
    cols = []
    for ci, c in enumerate(columns):
        null = out_null[ci, :n_rows].astype(bool)
        dt = numpy_dtype_for(c.ft)
        if dt is None:  # varlen
            lens = out_len[ci, :n_rows]
            offs = np.zeros(n_rows + 1, np.int64)
            np.cumsum(lens, out=offs[1:])
            pr = int(pool_idx[ci])
            blob_out = pool[pr, : int(offs[-1])].copy() if offs[-1] else np.zeros(0, np.uint8)
            cols.append(Column(c.ft, None, null, offs, blob_out))
            continue
        raw = out_fixed[ci, :n_rows]
        if dt == np.uint64:
            data = raw.view(np.uint64).copy()
        elif dt == np.float64:
            data = raw.view(np.float64).copy()
        else:
            data = raw.copy()
        cols.append(Column(c.ft, data, null))
    return cols
