// Native scan-decode kernel: rowcodec v2 rows -> columnar buffers.
//
// This is the framework's C++ runtime component for the host-side hot loop
// the reference executes in native code on the store side (TiKV, Rust:
// row decode feeding the coprocessor; in-repo semantics:
// pkg/util/rowcodec/decoder.go ChunkDecoder used at
// unistore/cophandler/cop_handler.go:424-467, value encodings
// rowcodec/encoder.go, decimal binary pkg/types/mydecimal.go FromBin,
// comparable float pkg/util/codec/float.go).
//
// One call decodes a whole region batch: for each row, parse the v2 header
// ([128][flags][notnull u16][null u16][ids][end-offsets][values]) once,
// binary-search each requested column id, and write fixed-width values
// (int64 bit-space), null flags, and string bytes into caller-allocated
// column-major buffers. Any malformed byte aborts the batch with an error
// code; the Python caller falls back to the row-at-a-time decoder.
//
// ABI kept C-plain (ctypes): no exceptions, no allocation, int return.

#include <cstdint>
#include <cstring>

namespace {

constexpr int kDig2Bytes[10] = {0, 1, 1, 2, 2, 3, 3, 4, 4, 4};
constexpr int kDigitsPerWord = 9;
constexpr int kWordSize = 4;

// column classes (must match tidb_tpu/native/__init__.py)
enum Cls : uint8_t {
  CLS_INT = 0,      // signed compact LE
  CLS_UINT = 1,     // unsigned compact LE (also packed time, enum/set/bit)
  CLS_FLOAT = 2,    // comparable float64 (bitcast into the int64 slot)
  CLS_DECIMAL = 3,  // [prec][frac][bin] -> scaled int64 at col_scale
  CLS_STRING = 5,   // raw bytes -> per-column pool
  CLS_HANDLE = 7,   // from the handles array, not the row
};

inline int64_t read_int_le(const uint8_t* p, int64_t n) {
  switch (n) {
    case 1: return static_cast<int8_t>(p[0]);
    case 2: { int16_t v; std::memcpy(&v, p, 2); return v; }
    case 4: { int32_t v; std::memcpy(&v, p, 4); return v; }
    case 8: { int64_t v; std::memcpy(&v, p, 8); return v; }
    default: return INT64_MIN;  // signalled by caller via size check
  }
}

inline uint64_t read_uint_le(const uint8_t* p, int64_t n) {
  switch (n) {
    case 1: return p[0];
    case 2: { uint16_t v; std::memcpy(&v, p, 2); return v; }
    case 4: { uint32_t v; std::memcpy(&v, p, 4); return v; }
    case 8: { uint64_t v; std::memcpy(&v, p, 8); return v; }
    default: return 0;
  }
}

inline uint64_t read_be(const uint8_t* p, int n) {
  uint64_t v = 0;
  for (int i = 0; i < n; i++) v = (v << 8) | p[i];
  return v;
}

inline double decode_float_cmp(const uint8_t* p) {
  uint64_t u = read_be(p, 8);
  if (u & 0x8000000000000000ULL) {
    u &= 0x7FFFFFFFFFFFFFFFULL;
  } else {
    u = ~u;
  }
  double d;
  std::memcpy(&d, &u, 8);
  return d;
}

const int64_t kPow10[19] = {
    1LL, 10LL, 100LL, 1000LL, 10000LL, 100000LL, 1000000LL, 10000000LL,
    100000000LL, 1000000000LL, 10000000000LL, 100000000000LL,
    1000000000000LL, 10000000000000LL, 100000000000000LL,
    1000000000000000LL, 10000000000000000LL, 100000000000000000LL,
    1000000000000000000LL};

// Decode MySQL binary decimal at `p` (after the [prec][frac] header) into a
// scaled int64 at target_scale. Returns false on malformed input.
bool decode_decimal_bin(const uint8_t* p, int64_t avail, int prec, int frac,
                        int target_scale, int64_t* out) {
  if (prec <= 0 || frac < 0 || frac > prec) return false;
  const int int_digits = prec - frac;
  const int leading = int_digits % kDigitsPerWord;
  const int trailing = frac % kDigitsPerWord;
  const int size = kDig2Bytes[leading] + (int_digits / kDigitsPerWord) * kWordSize +
                   (frac / kDigitsPerWord) * kWordSize + kDig2Bytes[trailing];
  if (size <= 0 || size > avail || size > 64) return false;
  uint8_t buf[64];
  std::memcpy(buf, p, size);
  const bool neg = !(buf[0] & 0x80);
  buf[0] ^= 0x80;
  if (neg)
    for (int i = 0; i < size; i++) buf[i] ^= 0xFF;

  __int128 intpart = 0, fracpart = 0;
  int cur = 0;
  if (leading) {
    intpart = read_be(buf + cur, kDig2Bytes[leading]);
    cur += kDig2Bytes[leading];
  }
  for (int w = 0; w < int_digits / kDigitsPerWord; w++) {
    intpart = intpart * 1000000000 + read_be(buf + cur, kWordSize);
    cur += kWordSize;
  }
  int frac_digits = 0;
  for (int w = 0; w < frac / kDigitsPerWord; w++) {
    fracpart = fracpart * 1000000000 + read_be(buf + cur, kWordSize);
    cur += kWordSize;
    frac_digits += kDigitsPerWord;
  }
  if (trailing) {
    uint64_t t = read_be(buf + cur, kDig2Bytes[trailing]);
    fracpart = fracpart * kPow10[trailing] + t;
    frac_digits += trailing;
  }
  // kPow10 covers exponents 0..18 (int64-scaled values cannot exceed that
  // anyway); wider MySQL scales fall back to the Python decoder
  if (frac_digits > 18 || target_scale > 18 ||
      (target_scale > frac_digits && target_scale - frac_digits > 18) ||
      (frac_digits > target_scale && frac_digits - target_scale > 18))
    return false;
  // value = intpart.fracpart ; scale to target_scale with round-half-away
  __int128 scaled;
  if (target_scale >= frac_digits) {
    scaled = (intpart * kPow10[frac_digits] + fracpart);
    scaled *= kPow10[target_scale - frac_digits];
  } else {
    __int128 full = intpart * kPow10[frac_digits] + fracpart;
    __int128 div = kPow10[frac_digits - target_scale];
    __int128 q = full / div, r = full % div;
    if (2 * r >= div) q += 1;
    scaled = q;
  }
  if (neg) scaled = -scaled;
  *out = static_cast<int64_t>(scaled);
  return true;
}

struct RowHeader {
  bool large;
  int n_notnull, n_null;
  const uint8_t* ids;
  const uint8_t* offs;
  const uint8_t* data;
  int64_t data_len;
};

inline bool parse_header(const uint8_t* b, int64_t len, RowHeader* h) {
  if (len < 6 || b[0] != 128) return false;
  h->large = (b[1] & 1) != 0;
  h->n_notnull = b[2] | (b[3] << 8);
  h->n_null = b[4] | (b[5] << 8);
  const int id_sz = h->large ? 4 : 1;
  const int off_sz = h->large ? 4 : 2;
  const int64_t ids_off = 6;
  const int64_t offs_off = ids_off + (int64_t)(h->n_notnull + h->n_null) * id_sz;
  const int64_t data_off = offs_off + (int64_t)h->n_notnull * off_sz;
  if (data_off > len) return false;
  h->ids = b + ids_off;
  h->offs = b + offs_off;
  h->data = b + data_off;
  h->data_len = len - data_off;
  return true;
}

inline int64_t id_at(const RowHeader& h, int i) {
  if (h.large) {
    uint32_t v;
    std::memcpy(&v, h.ids + 4 * i, 4);
    return v;
  }
  return h.ids[i];
}

inline int64_t end_off(const RowHeader& h, int i) {
  if (h.large) {
    uint32_t v;
    std::memcpy(&v, h.offs + 4 * i, 4);
    return v;
  }
  uint16_t v;
  std::memcpy(&v, h.offs + 2 * i, 2);
  return v;
}

// -1: null/absent; -2: malformed; >=0: value found, sets *start/*vlen
inline int find_value(const RowHeader& h, int64_t col_id, int64_t* start, int64_t* vlen) {
  int lo = 0, hi = h.n_notnull;
  while (lo < hi) {
    int mid = (lo + hi) / 2;
    int64_t cid = id_at(h, mid);
    if (cid < col_id) lo = mid + 1;
    else if (cid > col_id) hi = mid;
    else {
      int64_t s = mid ? end_off(h, mid - 1) : 0;
      int64_t e = end_off(h, mid);
      if (s < 0 || e < s || e > h.data_len) return -2;
      *start = s;
      *vlen = e - s;
      return 0;
    }
  }
  return -1;  // null or absent (both decode as NULL)
}

}  // namespace

extern "C" {

// Returns 0 on success; <0 on the first malformed row (caller falls back).
// Layouts: out_fixed/out_null/out_len are column-major [n_cols][n_rows];
// str_pool is [n_cols][pool_stride] — column c's string bytes append from
// str_pool + c*pool_stride, lengths recorded in out_len.
// col_pool[c] is the pool-row index for string columns (-1 otherwise), so
// the pool only needs one stride per STRING column, not per column.
int tt_decode_rows(const uint8_t* blob, const int64_t* row_offs, int64_t n_rows,
                   const int64_t* handles, const int64_t* col_ids,
                   const uint8_t* col_cls, const int32_t* col_scale,
                   const int32_t* col_pool, int64_t n_cols, int64_t* out_fixed,
                   uint8_t* out_null, int64_t* out_len, uint8_t* str_pool,
                   int64_t pool_stride) {
  // per-column string write cursors (stack cap: plenty for any schema)
  int64_t str_cur[256];
  if (n_cols > 256) return -100;
  for (int64_t c = 0; c < n_cols; c++) str_cur[c] = 0;

  for (int64_t r = 0; r < n_rows; r++) {
    const uint8_t* row = blob + row_offs[r];
    const int64_t row_len = row_offs[r + 1] - row_offs[r];
    RowHeader h;
    if (!parse_header(row, row_len, &h)) return -1;
    for (int64_t c = 0; c < n_cols; c++) {
      int64_t* slot = out_fixed + c * n_rows + r;
      uint8_t* nul = out_null + c * n_rows + r;
      int64_t* slen = out_len + c * n_rows + r;
      *slen = 0;
      const uint8_t cls = col_cls[c];
      if (cls == CLS_HANDLE) {
        *slot = handles[r];
        *nul = 0;
        continue;
      }
      int64_t start = 0, vlen = 0;
      int rc = find_value(h, col_ids[c], &start, &vlen);
      if (rc == -2) return -2;
      if (rc < 0) {
        *slot = 0;
        *nul = 1;
        continue;
      }
      const uint8_t* v = h.data + start;
      *nul = 0;
      switch (cls) {
        case CLS_INT: {
          if (vlen != 1 && vlen != 2 && vlen != 4 && vlen != 8) return -3;
          *slot = read_int_le(v, vlen);
          break;
        }
        case CLS_UINT: {
          if (vlen != 1 && vlen != 2 && vlen != 4 && vlen != 8) return -3;
          uint64_t u = read_uint_le(v, vlen);
          std::memcpy(slot, &u, 8);
          break;
        }
        case CLS_FLOAT: {
          if (vlen != 8) return -4;
          double d = decode_float_cmp(v);
          std::memcpy(slot, &d, 8);
          break;
        }
        case CLS_DECIMAL: {
          if (vlen < 3) return -5;
          int prec = v[0], frac = v[1];
          int64_t out;
          if (!decode_decimal_bin(v + 2, vlen - 2, prec, frac, col_scale[c], &out))
            return -5;
          *slot = out;
          break;
        }
        case CLS_STRING: {
          const int32_t pr = col_pool[c];
          if (pr < 0 || str_cur[c] + vlen > pool_stride) return -6;
          std::memcpy(str_pool + (int64_t)pr * pool_stride + str_cur[c], v, vlen);
          str_cur[c] += vlen;
          *slen = vlen;
          *slot = 0;
          break;
        }
        default:
          return -7;
      }
    }
  }
  return 0;
}

int tt_version() { return 2; }

}  // extern "C"
