from .field_type import (
    new_json,
    new_enum,
    new_set,
    FieldType,
    TypeCode,
    Flag,
    Collation,
    UNSPECIFIED_LENGTH,
    new_longlong,
    new_double,
    new_float,
    new_decimal,
    new_varchar,
    new_date,
    new_datetime,
)
from .datum import EnumVal, SetVal, Datum, DatumKind
from .mydecimal import MyDecimal, DIV_FRAC_INCR
from .mytime import MyTime, pack_datetime, unpack_datetime

__all__ = [
    "FieldType",
    "TypeCode",
    "Flag",
    "Collation",
    "UNSPECIFIED_LENGTH",
    "Datum",
    "DatumKind",
    "MyDecimal",
    "DIV_FRAC_INCR",
    "MyTime",
    "pack_datetime",
    "unpack_datetime",
    "new_longlong",
    "new_double",
    "new_float",
    "new_decimal",
    "new_varchar",
    "new_date",
    "new_datetime",
]
