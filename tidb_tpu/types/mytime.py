"""DATETIME/DATE/DURATION representations.

The reference stores datetimes as a bit-packed uint64 (ref: pkg/types/time.go
`Time.ToPackedUint` / `FromPackedUint`, the MySQL packed layout):

    ymd    = (year*13 + month) << 5 | day
    hms    = hour << 12 | minute << 6 | second
    packed = ((ymd << 17) | hms) << 24 | microsecond

The packing is order-preserving, so the packed uint64 *is* the device
representation: comparisons, group-by keys and min/max work directly on it;
EXTRACT-style functions unpack with shifts/masks inside kernels.

DURATION is int64 nanoseconds (ref: pkg/types/time.go Duration).
"""

from __future__ import annotations

from dataclasses import dataclass


def pack_datetime(year: int, month: int, day: int, hour: int = 0, minute: int = 0,
                  second: int = 0, microsecond: int = 0) -> int:
    ymd = (year * 13 + month) << 5 | day
    hms = hour << 12 | minute << 6 | second
    return ((ymd << 17) | hms) << 24 | microsecond


def unpack_datetime(packed: int) -> tuple[int, int, int, int, int, int, int]:
    microsecond = packed & ((1 << 24) - 1)
    rest = packed >> 24
    hms = rest & ((1 << 17) - 1)
    ymd = rest >> 17
    day = ymd & 31
    ym = ymd >> 5
    year, month = divmod(ym, 13)
    second = hms & 63
    minute = (hms >> 6) & 63
    hour = hms >> 12
    return year, month, day, hour, minute, second, microsecond


@dataclass(frozen=True)
class MyTime:
    """A host-side datetime value; `tp` distinguishes DATE/DATETIME/TIMESTAMP."""

    packed: int
    fsp: int = 0

    @classmethod
    def from_ymd(cls, year: int, month: int, day: int, hour: int = 0, minute: int = 0,
                 second: int = 0, microsecond: int = 0, fsp: int = 0) -> "MyTime":
        return cls(pack_datetime(year, month, day, hour, minute, second, microsecond), fsp)

    @classmethod
    def parse(cls, s: str, fsp: int = 0) -> "MyTime":
        s = s.strip()
        date_part, _, time_part = s.partition(" ")
        y, m, d = (int(x) for x in date_part.split("-"))
        hh = mm = ss = us = 0
        if time_part:
            hms, _, frac = time_part.partition(".")
            hh, mm, ss = (int(x) for x in hms.split(":"))
            if frac:
                us = int(frac[:6].ljust(6, "0"))
        return cls.from_ymd(y, m, d, hh, mm, ss, us, fsp)

    def parts(self):
        return unpack_datetime(self.packed)

    def is_date_only(self) -> bool:
        _, _, _, h, mi, s, us = self.parts()
        return h == 0 and mi == 0 and s == 0 and us == 0

    def __str__(self) -> str:
        y, m, d, h, mi, s, us = self.parts()
        base = f"{y:04d}-{m:02d}-{d:02d}"
        if self.fsp > 0:
            frac = f"{us:06d}"[: self.fsp]
            return f"{base} {h:02d}:{mi:02d}:{s:02d}.{frac}"
        if h or mi or s or us:
            return f"{base} {h:02d}:{mi:02d}:{s:02d}"
        return base

    def str_full(self) -> str:
        y, m, d, h, mi, s, us = self.parts()
        base = f"{y:04d}-{m:02d}-{d:02d} {h:02d}:{mi:02d}:{s:02d}"
        if self.fsp > 0:
            return base + "." + f"{us:06d}"[: self.fsp]
        return base

    def __lt__(self, other: "MyTime") -> bool:
        return self.packed < other.packed
