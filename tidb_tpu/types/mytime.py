"""DATETIME/DATE/DURATION representations.

The reference stores datetimes as a bit-packed uint64 (ref: pkg/types/time.go
`Time.ToPackedUint` / `FromPackedUint`, the MySQL packed layout):

    ymd    = (year*13 + month) << 5 | day
    hms    = hour << 12 | minute << 6 | second
    packed = ((ymd << 17) | hms) << 24 | microsecond

The packing is order-preserving, so the packed uint64 *is* the device
representation: comparisons, group-by keys and min/max work directly on it;
EXTRACT-style functions unpack with shifts/masks inside kernels.

DURATION is int64 nanoseconds (ref: pkg/types/time.go Duration).
"""

from __future__ import annotations

from dataclasses import dataclass


def pack_datetime(year: int, month: int, day: int, hour: int = 0, minute: int = 0,
                  second: int = 0, microsecond: int = 0) -> int:
    ymd = (year * 13 + month) << 5 | day
    hms = hour << 12 | minute << 6 | second
    return ((ymd << 17) | hms) << 24 | microsecond


def days_from_civil(y, m, d):
    """Days since 1970-01-01 (proleptic Gregorian; Hinnant's algorithm with
    floor division — ref: types/time.go calcDaynr semantics).

    Branchless on purpose: works identically for Python ints AND numpy/jnp
    arrays (the device date kernels call this with int64 lanes), so the
    calendar math exists exactly once."""
    y = y - (m <= 2)
    era = y // 400
    yoe = y - era * 400
    mp = (m + 9) % 12
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def civil_from_days(z):
    z = z + 719468
    era = z // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + 3 - 12 * (mp >= 10)
    return y + (m <= 2), m, d


def days_in_month(y, m):
    """Branchless (scalar or array): 31 minus the 30-day months minus the
    February adjustment (28/29)."""
    leap = ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)
    is30 = (m == 4) | (m == 6) | (m == 9) | (m == 11)
    return 31 - is30 * 1 - (m == 2) * (3 - leap * 1)


def add_months(y, m, d, months):
    """Month arithmetic with month-end clamping, branchless (scalar or
    array) — the one copy both the oracle and the device kernel use."""
    t = y * 12 + (m - 1) + months
    y2, m2 = t // 12, t % 12 + 1
    dim = days_in_month(y2, m2)
    d2 = d - (d - dim) * (d > dim)  # min(d, dim)
    return y2, m2, d2


_UNIT_SECONDS = {"second": 1, "minute": 60, "hour": 3600, "day": 86400, "week": 7 * 86400}


def datetime_add(packed: int, n: int, unit: str) -> int:
    """packed datetime + INTERVAL n unit (ref: types/time.go AddDate /
    builtin_time date_add). Month/quarter/year clamp the day to the target
    month's length (MySQL: '2020-01-31' + 1 month = '2020-02-29')."""
    y, m, d, hh, mm, ss, micro = unpack_datetime(packed)
    if unit in _UNIT_SECONDS:
        total = days_from_civil(y, m, d) * 86400 + hh * 3600 + mm * 60 + ss + n * _UNIT_SECONDS[unit]
        days, secs = total // 86400, total % 86400
        y, m, d = civil_from_days(days)
        hh, mm, ss = secs // 3600, (secs // 60) % 60, secs % 60
    else:
        months = {"month": 1, "quarter": 3, "year": 12}[unit] * n
        y, m, d = add_months(y, m, d, months)
    return pack_datetime(y, m, d, hh, mm, ss, micro)


def unpack_datetime(packed: int) -> tuple[int, int, int, int, int, int, int]:
    microsecond = packed & ((1 << 24) - 1)
    rest = packed >> 24
    hms = rest & ((1 << 17) - 1)
    ymd = rest >> 17
    day = ymd & 31
    ym = ymd >> 5
    year, month = divmod(ym, 13)
    second = hms & 63
    minute = (hms >> 6) & 63
    hour = hms >> 12
    return year, month, day, hour, minute, second, microsecond


@dataclass(frozen=True)
class MyTime:
    """A host-side datetime value; `tp` distinguishes DATE/DATETIME/TIMESTAMP."""

    packed: int
    fsp: int = 0

    @classmethod
    def from_ymd(cls, year: int, month: int, day: int, hour: int = 0, minute: int = 0,
                 second: int = 0, microsecond: int = 0, fsp: int = 0) -> "MyTime":
        return cls(pack_datetime(year, month, day, hour, minute, second, microsecond), fsp)

    @classmethod
    def parse(cls, s: str, fsp: int = 0) -> "MyTime":
        s = s.strip()
        date_part, _, time_part = s.partition(" ")
        y, m, d = (int(x) for x in date_part.split("-"))
        hh = mm = ss = us = 0
        if time_part:
            hms, _, frac = time_part.partition(".")
            hh, mm, ss = (int(x) for x in hms.split(":"))
            if frac:
                us = int(frac[:6].ljust(6, "0"))
        return cls.from_ymd(y, m, d, hh, mm, ss, us, fsp)

    def parts(self):
        return unpack_datetime(self.packed)

    def is_date_only(self) -> bool:
        _, _, _, h, mi, s, us = self.parts()
        return h == 0 and mi == 0 and s == 0 and us == 0

    def __str__(self) -> str:
        y, m, d, h, mi, s, us = self.parts()
        base = f"{y:04d}-{m:02d}-{d:02d}"
        if self.fsp > 0:
            frac = f"{us:06d}"[: self.fsp]
            return f"{base} {h:02d}:{mi:02d}:{s:02d}.{frac}"
        if h or mi or s or us:
            return f"{base} {h:02d}:{mi:02d}:{s:02d}"
        return base

    def str_full(self) -> str:
        y, m, d, h, mi, s, us = self.parts()
        base = f"{y:04d}-{m:02d}-{d:02d} {h:02d}:{mi:02d}:{s:02d}"
        if self.fsp > 0:
            return base + "." + f"{us:06d}"[: self.fsp]
        return base

    def __lt__(self, other: "MyTime") -> bool:
        return self.packed < other.packed
