"""MySQL field types, flags and collations.

Reimplements the type metadata the reference carries on every column and
expression (ref: pkg/parser/mysql/type.go, pkg/parser/types/field_type.go).
Only metadata lives here; evaluation semantics live in expr/ and ops/.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field


class TypeCode(enum.IntEnum):
    """MySQL column type codes (ref: pkg/parser/mysql/type.go:17-51)."""

    Decimal = 0
    Tiny = 1
    Short = 2
    Long = 3
    Float = 4
    Double = 5
    Null = 6
    Timestamp = 7
    LongLong = 8
    Int24 = 9
    Date = 10
    Duration = 11
    Datetime = 12
    Year = 13
    NewDate = 14
    Varchar = 15
    Bit = 16
    JSON = 0xF5
    NewDecimal = 0xF6
    Enum = 0xF7
    Set = 0xF8
    TinyBlob = 0xF9
    MediumBlob = 0xFA
    LongBlob = 0xFB
    Blob = 0xFC
    VarString = 0xFD
    String = 0xFE
    Geometry = 0xFF


class Flag(enum.IntFlag):
    """Column flags (ref: pkg/parser/mysql/type.go:56-78)."""

    NotNull = 1
    PriKey = 2
    UniqueKey = 4
    MultipleKey = 8
    Blob = 16
    Unsigned = 32
    Zerofill = 64
    Binary = 128
    Enum = 256
    AutoIncrement = 512
    Timestamp = 1024
    Set = 2048


class Collation(enum.IntEnum):
    """The collation subset the engine understands (ref: pkg/util/collate).

    Negative IDs are what TiDB sends over the wire when new collation is
    enabled (RewriteNewCollationIDIfNeeded); we store positive IDs and handle
    the sign at the protocol edge.
    """

    Binary = 63
    Utf8GeneralCI = 33
    Utf8MB4Bin = 46
    Utf8MB4GeneralCI = 45
    Utf8MB4UnicodeCI = 224
    Utf8MB4_0900AICI = 255
    Latin1Bin = 47
    ASCIIBin = 65


INT_TYPES = frozenset(
    {TypeCode.Tiny, TypeCode.Short, TypeCode.Int24, TypeCode.Long, TypeCode.LongLong, TypeCode.Year}
)
FLOAT_TYPES = frozenset({TypeCode.Float, TypeCode.Double})
STRING_TYPES = frozenset(
    {
        TypeCode.Varchar,
        TypeCode.VarString,
        TypeCode.String,
        TypeCode.TinyBlob,
        TypeCode.MediumBlob,
        TypeCode.LongBlob,
        TypeCode.Blob,
    }
)
TIME_TYPES = frozenset({TypeCode.Date, TypeCode.Datetime, TypeCode.Timestamp, TypeCode.NewDate})

UNSPECIFIED_LENGTH = -1


@dataclass
class FieldType:
    """Column/expression result type (ref: pkg/parser/types/field_type.go:40).

    flen/decimal carry display width & fractional digits; for NewDecimal they
    are the precision/scale that drive MyDecimal arithmetic parity.
    """

    tp: TypeCode = TypeCode.LongLong
    flag: Flag = Flag(0)
    flen: int = UNSPECIFIED_LENGTH
    decimal: int = UNSPECIFIED_LENGTH
    charset: str = "binary"
    collate: Collation = Collation.Binary
    elems: tuple = field(default_factory=tuple)  # Enum/Set members

    # ---- predicates -------------------------------------------------------
    def is_unsigned(self) -> bool:
        return bool(self.flag & Flag.Unsigned)

    def is_int(self) -> bool:
        return self.tp in INT_TYPES

    def is_float(self) -> bool:
        return self.tp in FLOAT_TYPES

    def is_decimal(self) -> bool:
        return self.tp in (TypeCode.NewDecimal, TypeCode.Decimal)

    def is_string(self) -> bool:
        return self.tp in STRING_TYPES

    def is_time(self) -> bool:
        return self.tp in TIME_TYPES

    def is_duration(self) -> bool:
        return self.tp == TypeCode.Duration

    def not_null(self) -> bool:
        return bool(self.flag & Flag.NotNull)

    def is_ci(self) -> bool:
        """Case/accent-insensitive collation (ref: pkg/util/collate):
        weight-based on the oracle path (types/collate.py); the device
        ASCII-folds and refuses non-ASCII CI data (oracle fallback)."""
        return self.collate in (
            Collation.Utf8GeneralCI,
            Collation.Utf8MB4GeneralCI,
            Collation.Utf8MB4UnicodeCI,
            Collation.Utf8MB4_0900AICI,
        )

    # ---- evaluation class (ref: pkg/types/field_type.go EvalType) ---------
    def eval_type(self) -> str:
        if self.is_int():
            return "int"
        if self.is_float():
            return "real"
        if self.is_decimal():
            return "decimal"
        if self.is_time():
            return "time"
        if self.is_duration():
            return "duration"
        if self.tp == TypeCode.JSON:
            return "json"
        if self.tp in (TypeCode.Enum, TypeCode.Set, TypeCode.Bit):
            return "int"  # device compare/order by member number
        return "string"

    def clone(self) -> "FieldType":
        return FieldType(self.tp, self.flag, self.flen, self.decimal, self.charset, self.collate, self.elems)

    def clone_nullable(self) -> "FieldType":
        """Copy with NotNull dropped (outer-join null extension)."""
        return FieldType(self.tp, self.flag & ~Flag.NotNull, self.flen, self.decimal, self.charset, self.collate, self.elems)

    def __hash__(self):
        return hash((self.tp, int(self.flag), self.flen, self.decimal, self.collate))


# ---- constructors mirroring types.NewFieldType defaults -------------------

def new_longlong(unsigned: bool = False, notnull: bool = False) -> FieldType:
    fl = Flag.Binary
    if unsigned:
        fl |= Flag.Unsigned
    if notnull:
        fl |= Flag.NotNull
    return FieldType(TypeCode.LongLong, fl, flen=20 if unsigned else 21, decimal=0)


def new_double() -> FieldType:
    return FieldType(TypeCode.Double, Flag.Binary, flen=22, decimal=UNSPECIFIED_LENGTH)


def new_float() -> FieldType:
    return FieldType(TypeCode.Float, Flag.Binary, flen=12, decimal=UNSPECIFIED_LENGTH)


def new_decimal(precision: int = 11, scale: int = 0) -> FieldType:
    return FieldType(TypeCode.NewDecimal, Flag.Binary, flen=precision, decimal=scale)


def new_varchar(flen: int = UNSPECIFIED_LENGTH, collate: Collation = Collation.Utf8MB4Bin) -> FieldType:
    return FieldType(TypeCode.Varchar, Flag(0), flen=flen, decimal=0, charset="utf8mb4", collate=collate)


def new_date() -> FieldType:
    return FieldType(TypeCode.Date, Flag.Binary, flen=10, decimal=0)


def new_json() -> FieldType:
    return FieldType(TypeCode.JSON, Flag(0), UNSPECIFIED_LENGTH, 0)


def new_enum(elems: tuple, notnull: bool = False) -> FieldType:
    return FieldType(TypeCode.Enum, Flag.NotNull if notnull else Flag(0), UNSPECIFIED_LENGTH, 0, elems=tuple(elems))


def new_set(elems: tuple, notnull: bool = False) -> FieldType:
    return FieldType(TypeCode.Set, Flag.NotNull if notnull else Flag(0), UNSPECIFIED_LENGTH, 0, elems=tuple(elems))


def new_datetime(fsp: int = 0) -> FieldType:
    return FieldType(TypeCode.Datetime, Flag.Binary, flen=19 + (fsp + 1 if fsp else 0), decimal=fsp)
