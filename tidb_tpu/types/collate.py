"""Collation weight transforms — full-Unicode general_ci / unicode_ci
(ref: pkg/util/collate/collate.go:335-348 collator registration,
general_ci.go, unicode_ci_data.go).

The engine compares strings through WEIGHT BYTES: two strings are
equal/ordered under a collation iff their weight strings are. The oracle
evaluator calls `weight_bytes` directly; the device path packs raw bytes
and ASCII-folds, so any CI column containing a non-ASCII byte is routed to
the oracle (chunk/device.py raises, the executor's NotImplementedError
fallback catches) — never silently wrong (VERDICT r4 weak #6).

  general_ci   per-codepoint simple uppercase, BMP only; supplementary
               planes collapse to 0xFFFD — MySQL's documented
               utf8mb4_general_ci behavior (no expansions/contractions)
  unicode_ci   primary-strength UCA approximation: NFD-decompose, drop
               combining marks, casefold — é == e == É, ß == ss (the
               casefold expansion), matching the corpus' accent/case
               equality classes; full DUCET cross-script ORDER is not
               reproduced (documented approximation)
"""

from __future__ import annotations

import unicodedata

from .field_type import Collation

_GENERAL_CI = frozenset({Collation.Utf8GeneralCI, Collation.Utf8MB4GeneralCI})
# 0900_ai_ci is accent-insensitive: unicode_ci semantics
_UNICODE_CI = frozenset({Collation.Utf8MB4UnicodeCI, Collation.Utf8MB4_0900AICI})


def _simple_upper(ch: str) -> str:
    up = ch.upper()
    return up if len(up) == 1 else ch  # general_ci has no expansions


def general_ci_weights(s: str) -> bytes:
    out = bytearray()
    for ch in s:
        cp = ord(ch)
        if cp > 0xFFFF:
            w = 0xFFFD  # supplementary planes share one weight (MySQL doc)
        else:
            w = ord(_simple_upper(ch)) & 0xFFFF
        out += w.to_bytes(2, "big")
    return bytes(out)


def unicode_ci_weights(s: str) -> bytes:
    nfd = unicodedata.normalize("NFD", s)
    base = "".join(c for c in nfd if unicodedata.category(c) != "Mn")
    folded = base.casefold()
    out = bytearray()
    for ch in folded:
        cp = ord(ch)
        out += (0xFFFD if cp > 0xFFFF else cp).to_bytes(2, "big")
    return bytes(out)


def weight_bytes(v, collation: Collation) -> bytes:
    """Value (str/bytes) -> collation weight string for compare/group/sort."""
    if isinstance(v, (bytes, bytearray)):
        try:
            v = bytes(v).decode("utf-8")
        except UnicodeDecodeError:
            return bytes(v)  # undecodable -> binary semantics
    if collation in _UNICODE_CI:
        return unicode_ci_weights(v)
    if collation in _GENERAL_CI:
        return general_ci_weights(v)
    return v.encode("utf-8")


def fold_text(s: str, collation: Collation) -> str:
    """Text fold consistent with weight_bytes (LIKE and friends must agree
    with '=' under the same collation)."""
    if collation in _UNICODE_CI:
        nfd = unicodedata.normalize("NFD", s)
        return "".join(c for c in nfd if unicodedata.category(c) != "Mn").casefold()
    if collation in _GENERAL_CI:
        return "".join(_simple_upper(c) for c in s)
    return s
