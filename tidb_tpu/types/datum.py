"""Host-side dynamic value (ref: pkg/types/datum.go `Datum`).

Used at the edges only — codec round-trips, constant folding, final result
rendering, the row-at-a-time parity evaluator. The hot path never touches
Datums; it runs on columnar device arrays.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Any, ClassVar

from .mydecimal import MyDecimal
from .mytime import MyTime


class DatumKind(enum.IntEnum):
    """(ref: pkg/types/datum.go:48-70 Kind* constants)."""

    Null = 0
    Int64 = 1
    Uint64 = 2
    Float32 = 3
    Float64 = 4
    String = 5
    Bytes = 6
    BinaryLiteral = 7
    MysqlDecimal = 8
    MysqlDuration = 9
    MysqlEnum = 10
    MysqlBit = 11
    MysqlSet = 12
    MysqlTime = 13
    Interface = 14
    MinNotNull = 15
    MaxValue = 16
    Raw = 17
    MysqlJSON = 18


class EnumVal:
    """ENUM value: 1-based member number + resolved name (ref:
    pkg/types/enum.go). Compares and stores by number; renders as name."""

    __slots__ = ("number", "name")

    def __init__(self, number: int, name: str):
        self.number = int(number)
        self.name = name

    def __int__(self):
        return self.number

    __index__ = __int__

    def __str__(self):
        return self.name

    def __eq__(self, other):
        return isinstance(other, EnumVal) and other.number == self.number

    def __hash__(self):
        return hash(("enum", self.number))

    def __repr__(self):
        return f"EnumVal({self.number}, {self.name!r})"


class SetVal:
    """SET value: member bitmask + resolved names (ref: pkg/types/set.go)."""

    __slots__ = ("number", "names")

    def __init__(self, number: int, names: tuple):
        self.number = int(number)
        self.names = tuple(names)

    def __int__(self):
        return self.number

    __index__ = __int__

    def __str__(self):
        return ",".join(self.names)

    def __eq__(self, other):
        return isinstance(other, SetVal) and other.number == self.number

    def __hash__(self):
        return hash(("set", self.number))

    def __repr__(self):
        return f"SetVal({self.number}, {self.names!r})"


@dataclass(frozen=True)
class Datum:
    kind: DatumKind
    val: Any = None

    NULL: ClassVar["Datum"]  # set below

    @classmethod
    def i64(cls, v: int) -> "Datum":
        # int subclasses pass through intact (bools still normalize): the
        # plan cache's slot-tagged literals ride Datums through lowering
        return cls(DatumKind.Int64,
                   v if (isinstance(v, int) and not isinstance(v, bool)) else int(v))

    @classmethod
    def u64(cls, v: int) -> "Datum":
        return cls(DatumKind.Uint64, int(v))

    @classmethod
    def f64(cls, v: float) -> "Datum":
        return cls(DatumKind.Float64, float(v))

    @classmethod
    def string(cls, v: str) -> "Datum":
        return cls(DatumKind.String, v)

    @classmethod
    def bytes_(cls, v: bytes) -> "Datum":
        return cls(DatumKind.Bytes, v)

    @classmethod
    def dec(cls, v, scale: int | None = None) -> "Datum":
        return cls(DatumKind.MysqlDecimal, v if isinstance(v, MyDecimal) else MyDecimal(v, scale))

    @classmethod
    def time(cls, v: MyTime) -> "Datum":
        return cls(DatumKind.MysqlTime, v)

    @classmethod
    def json(cls, binary: bytes) -> "Datum":
        """JSON datum over the BINARY encoding (types/json_binary.py) —
        the canonical in-engine representation, decoded lazily."""
        return cls(DatumKind.MysqlJSON, bytes(binary))

    @classmethod
    def enum(cls, number: int, name: str) -> "Datum":
        return cls(DatumKind.MysqlEnum, EnumVal(number, name))

    @classmethod
    def set_val(cls, number: int, names: tuple) -> "Datum":
        return cls(DatumKind.MysqlSet, SetVal(number, names))

    @classmethod
    def enum_from(cls, elems: tuple, number: int) -> "Datum":
        """Member number -> ENUM datum (name resolved; THE one place the
        out-of-range rule lives)."""
        name = elems[number - 1] if 0 < number <= len(elems) else ""
        return cls(DatumKind.MysqlEnum, EnumVal(number, name))

    @classmethod
    def set_from(cls, elems: tuple, mask: int) -> "Datum":
        names = tuple(e for i, e in enumerate(elems) if mask >> i & 1)
        return cls(DatumKind.MysqlSet, SetVal(mask, names))

    @classmethod
    def duration(cls, nanos: int) -> "Datum":
        # fsp (fractional rendering width) lives on the FieldType, not the value
        return cls(DatumKind.MysqlDuration, int(nanos))

    def is_null(self) -> bool:
        return self.kind == DatumKind.Null

    def __repr__(self):
        if self.kind == DatumKind.Null:
            return "NULL"
        return f"{self.kind.name}({self.val!r})"


Datum.NULL = Datum(DatumKind.Null)
