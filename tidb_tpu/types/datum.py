"""Host-side dynamic value (ref: pkg/types/datum.go `Datum`).

Used at the edges only — codec round-trips, constant folding, final result
rendering, the row-at-a-time parity evaluator. The hot path never touches
Datums; it runs on columnar device arrays.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Any, ClassVar

from .mydecimal import MyDecimal
from .mytime import MyTime


class DatumKind(enum.IntEnum):
    """(ref: pkg/types/datum.go:48-70 Kind* constants)."""

    Null = 0
    Int64 = 1
    Uint64 = 2
    Float32 = 3
    Float64 = 4
    String = 5
    Bytes = 6
    BinaryLiteral = 7
    MysqlDecimal = 8
    MysqlDuration = 9
    MysqlEnum = 10
    MysqlBit = 11
    MysqlSet = 12
    MysqlTime = 13
    Interface = 14
    MinNotNull = 15
    MaxValue = 16
    Raw = 17
    MysqlJSON = 18


@dataclass(frozen=True)
class Datum:
    kind: DatumKind
    val: Any = None

    NULL: ClassVar["Datum"]  # set below

    @classmethod
    def i64(cls, v: int) -> "Datum":
        return cls(DatumKind.Int64, int(v))

    @classmethod
    def u64(cls, v: int) -> "Datum":
        return cls(DatumKind.Uint64, int(v))

    @classmethod
    def f64(cls, v: float) -> "Datum":
        return cls(DatumKind.Float64, float(v))

    @classmethod
    def string(cls, v: str) -> "Datum":
        return cls(DatumKind.String, v)

    @classmethod
    def bytes_(cls, v: bytes) -> "Datum":
        return cls(DatumKind.Bytes, v)

    @classmethod
    def dec(cls, v, scale: int | None = None) -> "Datum":
        return cls(DatumKind.MysqlDecimal, v if isinstance(v, MyDecimal) else MyDecimal(v, scale))

    @classmethod
    def time(cls, v: MyTime) -> "Datum":
        return cls(DatumKind.MysqlTime, v)

    @classmethod
    def duration(cls, nanos: int) -> "Datum":
        # fsp (fractional rendering width) lives on the FieldType, not the value
        return cls(DatumKind.MysqlDuration, int(nanos))

    def is_null(self) -> bool:
        return self.kind == DatumKind.Null

    def __repr__(self):
        if self.kind == DatumKind.Null:
            return "NULL"
        return f"{self.kind.name}({self.val!r})"


Datum.NULL = Datum(DatumKind.Null)
