"""MySQL DECIMAL semantics on host.

The reference implements a word-based fixed-point decimal
(ref: pkg/types/mydecimal.go — int32 words of 9 digits). We need bit-exact
*semantics* (precision/scale propagation, rounding, division precision
increment), not the word layout, so this wraps python `decimal` with MySQL's
rules:

  - max precision 65, max scale 30 (ref: pkg/types/mydecimal.go:32-38)
  - add/sub result scale  = max(s1, s2)
  - mul result scale      = min(s1 + s2, 30)
  - div result scale      = min(s1 + DivFracIncr, 30), DivFracIncr = 4
    (ref: pkg/expression/builtin_arithmetic.go, `types.DivFracIncr`;
     cophandler applies the same at cop_handler.go:350-354)
  - rounding: half away from zero ("round half up" in MySQL docs)

On device, decimals travel as scaled int64 (value * 10^scale) when the scale
is known and small enough — see chunk/device.py; this class is the host-side
edge (parsing, final merge, result encoding).
"""

from __future__ import annotations

import decimal
from decimal import Decimal

MAX_PRECISION = 65
MAX_SCALE = 30
DIV_FRAC_INCR = 4

_CTX = decimal.Context(prec=MAX_PRECISION + 10, rounding=decimal.ROUND_HALF_UP)


class MyDecimal:
    """Immutable fixed-point decimal with an explicit scale ("frac")."""

    __slots__ = ("d", "scale")

    def __init__(self, value, scale: int | None = None):
        if isinstance(value, MyDecimal):
            d = value.d
            scale = value.scale if scale is None else scale
        elif isinstance(value, Decimal):
            d = value
        elif isinstance(value, float):
            # MySQL converts float via its shortest decimal repr.
            d = Decimal(repr(value))
        else:
            d = Decimal(str(value))
        if scale is None:
            scale = max(0, -d.as_tuple().exponent)
        scale = min(scale, MAX_SCALE)
        self.scale = scale
        self.d = d.quantize(Decimal(1).scaleb(-scale), context=_CTX)

    # ---- arithmetic -------------------------------------------------------
    def __add__(self, other: "MyDecimal") -> "MyDecimal":
        s = max(self.scale, other.scale)
        return MyDecimal(_CTX.add(self.d, other.d), s)

    def __sub__(self, other: "MyDecimal") -> "MyDecimal":
        s = max(self.scale, other.scale)
        return MyDecimal(_CTX.subtract(self.d, other.d), s)

    def __mul__(self, other: "MyDecimal") -> "MyDecimal":
        s = min(self.scale + other.scale, MAX_SCALE)
        return MyDecimal(_CTX.multiply(self.d, other.d), s)

    def div(self, other: "MyDecimal", frac_incr: int = DIV_FRAC_INCR) -> "MyDecimal | None":
        """MySQL division; returns None for division by zero (-> SQL NULL)."""
        if other.d == 0:
            return None
        s = min(self.scale + frac_incr, MAX_SCALE)
        q = _CTX.divide(self.d, other.d)
        return MyDecimal(q, s)

    def __neg__(self) -> "MyDecimal":
        return MyDecimal(-self.d, self.scale)

    # ---- comparison (scale-insensitive, like the reference Compare) -------
    def __eq__(self, other) -> bool:
        return isinstance(other, MyDecimal) and self.d == other.d

    def __lt__(self, other: "MyDecimal") -> bool:
        return self.d < other.d

    def __le__(self, other: "MyDecimal") -> bool:
        return self.d <= other.d

    def __hash__(self):
        return hash(self.d)

    # ---- conversions ------------------------------------------------------
    def round(self, scale: int) -> "MyDecimal":
        return MyDecimal(self.d, scale)

    def to_float(self) -> float:
        return float(self.d)

    def to_int(self) -> int:
        """Round to integer, half away from zero (ref mydecimal ToInt)."""
        return int(self.d.quantize(Decimal(1), context=_CTX))

    def to_scaled_int(self, scale: int | None = None) -> int:
        """value * 10^scale as a python int — the device representation."""
        s = self.scale if scale is None else scale
        return int(self.d.scaleb(s).quantize(Decimal(1), context=_CTX))

    @classmethod
    def from_scaled_int(cls, v: int, scale: int) -> "MyDecimal":
        return cls(Decimal(v).scaleb(-scale), scale)

    def __str__(self) -> str:
        # MySQL prints with exactly `scale` fractional digits.
        return str(self.d)

    def __repr__(self) -> str:
        return f"MyDecimal({self.d}, scale={self.scale})"
