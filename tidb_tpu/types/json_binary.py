"""MySQL/TiDB binary JSON codec + path engine
(ref: pkg/types/json_binary.go — the storage format rowcodec embeds —
and pkg/types/json_path_expr.go for path grammar).

Value model on the Python side: None/True/False/int/float/str/list/dict
(dict keys are str, insertion order preserved; MySQL sorts object keys by
length-then-bytes in the binary format, reproduced here for byte parity).

Binary layout (little-endian; ref: json_binary.go:20-60 doc comment):
  value      ::= type(1) payload
  object     ::= elemCount(4) size(4) keyEntry* valueEntry* key* value*
  array      ::= elemCount(4) size(4) valueEntry* value*
  keyEntry   ::= keyOff(4) keyLen(2)
  valueEntry ::= type(1) offset-or-inlined(4)
  literal    ::= 0x00 NULL | 0x01 TRUE | 0x02 FALSE
  string     ::= varint-len data
"""

from __future__ import annotations

import json as _pyjson
import struct

TYPE_OBJECT = 0x01
TYPE_ARRAY = 0x03
TYPE_LITERAL = 0x04
TYPE_I64 = 0x09
TYPE_U64 = 0x0A
TYPE_F64 = 0x0B
TYPE_STRING = 0x0C

LIT_NULL = 0x00
LIT_TRUE = 0x01
LIT_FALSE = 0x02

_INLINE_TYPES = (TYPE_LITERAL,)


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(b: bytes, pos: int) -> tuple[int, int]:
    shift = n = 0
    while True:
        c = b[pos]
        pos += 1
        n |= (c & 0x7F) << shift
        if not c & 0x80:
            return n, pos
        shift += 7


def _type_of(v) -> int:
    if v is None or isinstance(v, bool):
        return TYPE_LITERAL
    if isinstance(v, int):
        return TYPE_I64 if -(1 << 63) <= v < (1 << 63) else TYPE_U64
    if isinstance(v, float):
        return TYPE_F64
    if isinstance(v, str):
        return TYPE_STRING
    if isinstance(v, list):
        return TYPE_ARRAY
    if isinstance(v, dict):
        return TYPE_OBJECT
    raise TypeError(f"unsupported JSON value {type(v).__name__}")


def _encode_payload(v) -> bytes:
    t = _type_of(v)
    if t == TYPE_LITERAL:
        return bytes([LIT_NULL if v is None else (LIT_TRUE if v else LIT_FALSE)])
    if t == TYPE_I64:
        return struct.pack("<q", v)
    if t == TYPE_U64:
        return struct.pack("<Q", v & ((1 << 64) - 1))
    if t == TYPE_F64:
        return struct.pack("<d", v)
    if t == TYPE_STRING:
        raw = v.encode()
        return _varint(len(raw)) + raw
    # containers
    if t == TYPE_ARRAY:
        entries = [(_type_of(x), x) for x in v]
        keys: list[bytes] = []
    else:
        # MySQL sorts object keys by (length, bytes) in storage
        items = sorted(v.items(), key=lambda kv: (len(kv[0].encode()), kv[0].encode()))
        keys = [k.encode() for k, _ in items]
        entries = [(_type_of(x), x) for _, x in items]
    n = len(entries)
    key_entry_sz = 6 * len(keys)
    val_entry_sz = 5 * n
    header = 8 + key_entry_sz + val_entry_sz
    key_blob = bytearray()
    key_offs = []
    for k in keys:
        key_offs.append(header + len(key_blob))
        key_blob += k
    val_blob = bytearray()
    val_entries = []
    base = header + len(key_blob)
    for t2, x in entries:
        if t2 == TYPE_LITERAL:
            val_entries.append((t2, LIT_NULL if x is None else (LIT_TRUE if x else LIT_FALSE)))
        else:
            val_entries.append((t2, base + len(val_blob)))
            val_blob += _encode_payload(x)
    total = base + len(val_blob)
    out = bytearray(struct.pack("<II", n, total))
    for off, k in zip(key_offs, keys):
        out += struct.pack("<IH", off, len(k))
    for t2, off in val_entries:
        out += struct.pack("<BI", t2, off)
    out += key_blob
    out += val_blob
    return bytes(out)


def encode(v) -> bytes:
    """Python value -> binary JSON (type byte + payload)."""
    return bytes([_type_of(v)]) + _encode_payload(v)


def _decode_payload(t: int, b: bytes, pos: int):
    if t == TYPE_LITERAL:
        lit = b[pos]
        return None if lit == LIT_NULL else lit == LIT_TRUE
    if t == TYPE_I64:
        return struct.unpack_from("<q", b, pos)[0]
    if t == TYPE_U64:
        return struct.unpack_from("<Q", b, pos)[0]
    if t == TYPE_F64:
        return struct.unpack_from("<d", b, pos)[0]
    if t == TYPE_STRING:
        n, p = _read_varint(b, pos)
        return b[p : p + n].decode("utf-8", "surrogateescape")
    # containers: offsets in entries are relative to the container start
    n, _total = struct.unpack_from("<II", b, pos)
    if t == TYPE_ARRAY:
        out = []
        ve = pos + 8
        for i in range(n):
            t2, off = struct.unpack_from("<BI", b, ve + 5 * i)
            if t2 == TYPE_LITERAL:
                out.append(None if off == LIT_NULL else off == LIT_TRUE)
            else:
                out.append(_decode_payload(t2, b, pos + off))
        return out
    obj = {}
    ke = pos + 8
    ve = ke + 6 * n
    for i in range(n):
        koff, klen = struct.unpack_from("<IH", b, ke + 6 * i)
        key = b[pos + koff : pos + koff + klen].decode("utf-8", "surrogateescape")
        t2, off = struct.unpack_from("<BI", b, ve + 5 * i)
        if t2 == TYPE_LITERAL:
            obj[key] = None if off == LIT_NULL else off == LIT_TRUE
        else:
            obj[key] = _decode_payload(t2, b, pos + off)
    return obj


def decode(b: bytes):
    """Binary JSON -> Python value."""
    return _decode_payload(b[0], bytes(b), 1)


def parse_text(s: str):
    """JSON text -> Python value (MySQL-compatible errors collapse to
    ValueError)."""
    return _pyjson.loads(s)


def to_text(v) -> str:
    """Python value -> MySQL-style JSON text (", " separators like MySQL)."""
    return _pyjson.dumps(v, separators=(", ", ": "), ensure_ascii=False)


def json_type_name(v) -> str:
    """(ref: json_binary.go TypeCode -> type name for JSON_TYPE())."""
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "BOOLEAN"
    if isinstance(v, int):
        return "INTEGER" if -(1 << 63) <= v < (1 << 63) else "UNSIGNED INTEGER"
    if isinstance(v, float):
        return "DOUBLE"
    if isinstance(v, str):
        return "STRING"
    if isinstance(v, list):
        return "ARRAY"
    return "OBJECT"


# ------------------------------------------------------------------ paths
class PathError(ValueError):
    pass


def parse_path(path: str) -> list:
    """JSONPath subset (ref: json_path_expr.go): $, .key, ."quoted",
    [N], [*], .*, ** (prefix wildcard). Returns a list of legs:
    ("key", name) | ("idx", n) | ("key*",) | ("idx*",) | ("**",)."""
    s = path.strip()
    if not s.startswith("$"):
        raise PathError(f"invalid JSON path {path!r}")
    i = 1
    legs: list = []
    while i < len(s):
        c = s[i]
        if c == ".":
            i += 1
            if i < len(s) and s[i] == "*":
                legs.append(("key*",))
                i += 1
            elif i < len(s) and s[i] == '"':
                j = s.index('"', i + 1)
                legs.append(("key", s[i + 1 : j]))
                i = j + 1
            else:
                j = i
                while j < len(s) and (s[j].isalnum() or s[j] in "_$"):
                    j += 1
                if j == i:
                    raise PathError(f"invalid JSON path {path!r}")
                legs.append(("key", s[i:j]))
                i = j
        elif c == "[":
            j = s.index("]", i)
            inner = s[i + 1 : j].strip()
            if inner == "*":
                legs.append(("idx*",))
            else:
                legs.append(("idx", int(inner)))
            i = j + 1
        elif c == "*" and i + 1 < len(s) and s[i + 1] == "*":
            legs.append(("**",))
            i += 2
        elif c.isspace():
            i += 1
        else:
            raise PathError(f"invalid JSON path {path!r}")
    return legs


def _walk(v, legs: list, out: list):
    if not legs:
        out.append(v)
        return
    leg, rest = legs[0], legs[1:]
    if leg[0] == "key":
        if isinstance(v, dict) and leg[1] in v:
            _walk(v[leg[1]], rest, out)
    elif leg[0] == "idx":
        if isinstance(v, list):
            if 0 <= leg[1] < len(v):
                _walk(v[leg[1]], rest, out)
        elif leg[1] == 0:
            _walk(v, rest, out)  # scalar acts as a one-element array
    elif leg[0] == "key*":
        if isinstance(v, dict):
            for x in v.values():
                _walk(x, rest, out)
    elif leg[0] == "idx*":
        if isinstance(v, list):
            for x in v:
                _walk(x, rest, out)
    elif leg[0] == "**":
        _walk(v, rest, out)
        if isinstance(v, dict):
            for x in v.values():
                _walk(x, legs, out)
        elif isinstance(v, list):
            for x in v:
                _walk(x, legs, out)


def extract(v, paths: list[str]):
    """JSON_EXTRACT semantics (ref: builtin_json_vec.go vecEvalJSONExtract):
    one non-wildcard path -> the value itself (or missing -> None marker);
    multiple paths or wildcards -> array of matches. Returns (found, value)."""
    matches: list = []
    single_scalar = len(paths) == 1
    for p in paths:
        legs = parse_path(p)
        if any(l[0] in ("key*", "idx*", "**") for l in legs):
            single_scalar = False
        _walk(v, legs, matches)
    if not matches:
        return False, None
    if single_scalar and len(matches) == 1:
        return True, matches[0]
    return True, matches


def contains(doc, target) -> bool:
    """JSON_CONTAINS semantics (ref: types/json_binary_functions.go)."""
    if isinstance(doc, list):
        if isinstance(target, list):
            return all(contains(doc, t) for t in target)
        return any(contains(x, target) if isinstance(x, (list, dict)) else _eq(x, target) for x in doc)
    if isinstance(doc, dict):
        if isinstance(target, dict):
            return all(k in doc and contains(doc[k], v) if isinstance(doc[k], (dict, list)) else (k in doc and _eq(doc[k], v)) for k, v in target.items())
        return False
    return _eq(doc, target)


def _eq(a, b) -> bool:
    if isinstance(a, bool) or isinstance(b, bool):
        return a is b or (isinstance(a, bool) and isinstance(b, bool) and a == b)
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        return float(a) == float(b)
    return type(a) is type(b) and a == b
