"""Hierarchical statement tracing — the span tree behind `TRACE <stmt>`
(ref: pkg/util/tracing over opentracing spans + executor/trace.go's
TraceExec collecting them into the result set).

Design:

  * A trace is a tree of `Span`s. `trace(name)` opens a root; `span(name)`
    opens a child of the ambient current span. When NO trace is active,
    `span()` yields None at near-zero cost — instrumentation stays in the
    hot paths permanently, like the reference's always-on tracing hooks.
  * The ambient span is a `contextvars.ContextVar`, so nested sync code
    parents correctly. Worker threads (the distsql dispatch pool) do NOT
    inherit context: the dispatcher captures `current_span()` on the
    session thread and passes it as `span(..., parent=...)` — the
    explicit-handoff analog of opentracing's SpanContext propagation.
  * Child attach is lock-protected (concurrent cop tasks append to one
    parent); finished spans are immutable in practice and render without
    the lock.

Durations are perf_counter_ns; a span still inside `with` reports the
elapsed time so a partial tree (failing statement) renders consistently.
"""

from __future__ import annotations

import contextvars
import json
import threading
import time
from contextlib import contextmanager

_current: contextvars.ContextVar = contextvars.ContextVar("tidb_tpu_span", default=None)


class Span:
    """One timed operation with attributes and children."""

    __slots__ = ("name", "attrs", "start_ns", "end_ns", "children", "_lock")

    def __init__(self, name: str, **attrs):
        self.name = name
        self.attrs: dict = dict(attrs)
        self.start_ns = time.perf_counter_ns()
        self.end_ns: int | None = None
        self.children: list[Span] = []  # guarded_by: _lock
        self._lock = threading.Lock()

    # -- building ----------------------------------------------------------
    def child(self, name: str, **attrs) -> "Span":
        sp = Span(name, **attrs)
        with self._lock:
            self.children.append(sp)
        return sp

    def set(self, key: str, value) -> None:
        """Record an attribute (rows, bytes, cache_hit, region_id...)."""
        self.attrs[key] = value

    def finish(self) -> None:
        if self.end_ns is None:
            self.end_ns = time.perf_counter_ns()

    # -- reading -----------------------------------------------------------
    @property
    def duration_ns(self) -> int:
        end = self.end_ns if self.end_ns is not None else time.perf_counter_ns()
        return end - self.start_ns

    def find(self, name: str) -> list["Span"]:
        """All spans named `name` anywhere under (and including) this one."""
        out = [self] if self.name == name else []
        with self._lock:
            kids = list(self.children)
        for c in kids:
            out.extend(c.find(name))
        return out

    def sum_attr(self, name: str, attr: str) -> int:
        """Sum a numeric attribute over every span named `name` under (and
        including) this one — how a statement-level reader aggregates
        per-dispatch attribution (e.g. `batch_size` / `launches_saved` on
        the distsql.batch_cop spans) without walking the tree by hand."""
        total = 0
        for sp in self.find(name):
            v = sp.attrs.get(attr)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                total += v
        return int(total)

    def to_dict(self) -> dict:
        with self._lock:
            kids = list(self.children)
        d: dict = {"name": self.name, "duration_ns": self.duration_ns}
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        if kids:
            d["children"] = [c.to_dict() for c in kids]
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), default=str)

    def rows(self, _depth: int = 0, _t0: int | None = None) -> list[tuple]:
        """Flatten to (operation, start_offset_us, duration_us, attrs-json)
        rows, children indented two spaces per level — the `TRACE
        FORMAT='row'` rendering (ref: executor/trace.go dfsTree)."""
        t0 = self.start_ns if _t0 is None else _t0
        with self._lock:
            kids = list(self.children)
        row = (
            "  " * _depth + self.name,
            (self.start_ns - t0) // 1000,
            self.duration_ns // 1000,
            json.dumps(self.attrs, sort_keys=True, default=str) if self.attrs else "",
        )
        out = [row]
        for c in kids:
            out.extend(c.rows(_depth + 1, t0))
        return out


def current_span() -> Span | None:
    """The ambient span of THIS thread's context, or None (tracing off)."""
    return _current.get()


@contextmanager
def trace(name: str, **attrs):
    """Open a root span and make it ambient. The statement entry point."""
    root = Span(name, **attrs)
    token = _current.set(root)
    try:
        yield root
    finally:
        root.finish()
        _current.reset(token)


@contextmanager
def span(name: str, parent: Span | None = None, **attrs):
    """Child span of `parent` (explicit cross-thread handoff) or of the
    ambient span; yields None — and skips all bookkeeping — when neither
    exists. Exceptions are recorded on the span and re-raised, so a failing
    statement leaves a partial tree with `error` attributes."""
    cur = parent if parent is not None else _current.get()
    if cur is None:
        yield None
        return
    sp = cur.child(name, **attrs)
    token = _current.set(sp)
    try:
        yield sp
    except BaseException as exc:
        sp.attrs["error"] = f"{type(exc).__name__}: {exc}"
        raise
    finally:
        sp.finish()
        _current.reset(token)
