"""JAX version shims, in one dependency-free module (importable from ops,
parallel, and exec without package cycles).

`jax.shard_map` (with its `check_vma` kwarg) only exists on newer JAX; older
releases ship it as `jax.experimental.shard_map.shard_map` with the kwarg
spelled `check_rep`. Likewise `jax.enable_x64` is the new-jax spelling of
the context manager older releases keep in `jax.experimental`. One wrapper
each keeps every call site on the new spelling.
"""

from __future__ import annotations

import jax

try:  # jax >= 0.6: top-level function, check_vma kwarg
    from jax import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                          check_vma=check_vma)
except ImportError:  # jax 0.4.x: experimental module, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                          check_rep=check_vma)


enable_x64 = getattr(jax, "enable_x64", None)
if enable_x64 is None:
    from jax.experimental import enable_x64  # noqa: F401
