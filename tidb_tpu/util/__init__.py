from . import failpoint
from .memory import MemTracker, QuotaExceeded
from .metrics import REGISTRY

__all__ = ["failpoint", "MemTracker", "QuotaExceeded", "REGISTRY"]
