"""Slow-query log + statement summary (ref: pkg/executor/adapter.go:1580
ExecStmt.LogSlowQuery and pkg/util/stmtsummary — the reference writes slow
entries to the slow log file and aggregates per SQL digest into
`information_schema.statements_summary`; here both live in one in-process
registry shared by every session of a catalog (the domain analog) and are
served as information_schema memtables).

Digests normalize the SQL through the real lexer: literals become '?', so
`select * from t where a = 5` and `... a = 7` share one summary row, the
same way the reference's parser.NormalizeDigest works."""

from __future__ import annotations

import hashlib
import threading
import time
from dataclasses import dataclass, field


def normalize_sql(sql: str) -> tuple[str, str]:
    """(normalized text, hex digest). Literals -> '?', idents lowered —
    the parser.Normalize/Digest analog.

    FALLBACK ONLY (ISSUE 17): every statement that went through the
    session already carries the plan-cache probe's identical pair from
    its one lexer pass, and `record()` takes it via `norm_digest` — this
    re-lex serves only direct `record()` callers (tests, tools) and the
    unlexable-statement path. Slow log, statement summary, Top SQL and
    the plan cache therefore share ONE digest per statement by
    construction."""
    from ..parser.lexer import T, tokenize

    try:
        toks = tokenize(sql)
    except Exception:  # noqa: BLE001 — unlexable SQL still gets a digest
        norm = " ".join(sql.split()).lower()
        return norm, hashlib.sha256(norm.encode()).hexdigest()[:32]
    parts = []
    for t in toks:
        if t.kind is T.EOF:
            break
        if t.kind in (T.NUMBER, T.STRING):
            parts.append("?")
        elif t.kind in (T.IDENT, T.QIDENT):
            # quoted and bare identifiers normalize identically (lookups
            # are case-insensitive, so `T` and t are one statement)
            parts.append(t.text.lower())
        else:
            parts.append(t.text)
    norm = " ".join(parts)
    return norm, hashlib.sha256(norm.encode()).hexdigest()[:32]


@dataclass
class SlowLogEntry:
    """(ref: the slow-log fields adapter.go writes: Time, Query_time, SQL,
    digest, result rows, success). plan_digest joins slow-log rows against
    statement summaries (ref: the Plan_digest slow-log field)."""

    ts: float
    duration_ms: float
    sql: str
    digest: str
    rows: int
    success: bool
    error: str = ""
    plan_digest: str = ""


@dataclass
class StmtSummary:
    """(ref: stmtsummary.stmtSummaryByDigest)."""

    digest: str
    normalized: str
    sample_sql: str
    exec_count: int = 0
    sum_latency_ms: float = 0.0
    max_latency_ms: float = 0.0
    min_latency_ms: float = float("inf")
    sum_rows: int = 0
    errors: int = 0
    last_seen: float = 0.0
    sum_cpu_ms: float = 0.0  # thread CPU time (the Top SQL attribution,
    # ref: pkg/util/topsql/collector — per-digest CPU sampling; in-process
    # the exact thread_time delta replaces statistical sampling)
    # resource-tag attribution (ISSUE 17): the Top SQL sinks' per-statement
    # totals, folded here so statements_summary answers avg/max device and
    # wait costs per digest without a join against the windowed reporter
    sum_device_ns: int = 0
    max_device_ns: int = 0
    sum_compile_ns: int = 0
    sum_backoff_ms: float = 0.0
    sum_queue_ms: float = 0.0

    @property
    def avg_latency_ms(self) -> float:
        return self.sum_latency_ms / self.exec_count if self.exec_count else 0.0

    @property
    def avg_device_ns(self) -> float:
        return self.sum_device_ns / self.exec_count if self.exec_count else 0.0


class StmtLog:
    """Shared per-catalog registry: bounded slow-query ring + per-digest
    summaries (LRU-bounded like tidb_stmt_summary_max_stmt_count)."""

    def __init__(self, slow_capacity: int = 512, max_digests: int = 3000):
        self._lock = threading.Lock()
        self.slow: list[SlowLogEntry] = []  # guarded_by: _lock
        self.slow_capacity = slow_capacity
        self.summaries: dict[str, StmtSummary] = {}  # guarded_by: _lock
        self.max_digests = max_digests

    def record(
        self,
        sql: str,
        duration_ms: float,
        rows: int,
        success: bool,
        error: str = "",
        slow_threshold_ms: float | None = 300.0,
        summary_enabled: bool = True,
        cpu_ms: float = 0.0,
        plan_digest: str = "",
        norm_digest: tuple[str, str] | None = None,
        attr: dict | None = None,
    ):
        # a FAILED statement leaves a slow-log artifact regardless of the
        # threshold (slow log still enabled) — a fast-failing dispatch
        # error is exactly the query one needs to find afterwards (ref:
        # adapter.go LogSlowQuery records failed statements with their error)
        is_slow = slow_threshold_ms is not None and (duration_ms > slow_threshold_ms or not success)
        if not summary_enabled and not is_slow:
            return  # neither sink wants it: skip the lexer+digest pass
        # the session hands its already-computed (normalized, digest) pair
        # when it lexed the statement anyway (the plan-cache probe), and
        # EXECUTE hands the UNDERLYING prepared statement's pair so the
        # run joins that summary row instead of the "execute s" shape
        norm, digest = norm_digest if norm_digest is not None else normalize_sql(sql)
        now = time.time()
        with self._lock:
            if summary_enabled:
                s = self.summaries.get(digest)
                if s is None:
                    if len(self.summaries) >= self.max_digests:
                        # evict the least-recently-seen digest
                        victim = min(self.summaries.values(), key=lambda x: x.last_seen)
                        del self.summaries[victim.digest]
                    s = StmtSummary(digest, norm, sql[:256])
                    self.summaries[digest] = s
                s.exec_count += 1
                s.sum_latency_ms += duration_ms
                s.max_latency_ms = max(s.max_latency_ms, duration_ms)
                s.min_latency_ms = min(s.min_latency_ms, duration_ms)
                s.sum_rows += rows
                s.errors += 0 if success else 1
                s.sum_cpu_ms += cpu_ms
                if attr is not None:  # the statement's resource-tag totals
                    s.sum_device_ns += attr.get("device_ns", 0)
                    s.max_device_ns = max(s.max_device_ns, attr.get("device_ns", 0))
                    s.sum_compile_ns += attr.get("compile_ns", 0)
                    s.sum_backoff_ms += attr.get("backoff_ms", 0.0)
                    s.sum_queue_ms += attr.get("queue_ms", 0.0)
                s.last_seen = now
            if is_slow:
                self.slow.append(
                    SlowLogEntry(now, duration_ms, sql[:4096], digest, rows, success,
                                 error, plan_digest)
                )
                if len(self.slow) > self.slow_capacity:
                    del self.slow[: len(self.slow) - self.slow_capacity]

    def top_sql(self, n: int = 30) -> list[StmtSummary]:
        """Top digests by cumulative CPU time (ref: pkg/util/topsql's
        top-N reporter over the per-digest CPU attribution)."""
        with self._lock:
            return sorted(self.summaries.values(), key=lambda s: -s.sum_cpu_ms)[:n]

    def slow_entries(self) -> list[SlowLogEntry]:
        with self._lock:
            return list(self.slow)

    def summary_rows(self) -> list[StmtSummary]:
        with self._lock:
            return sorted(self.summaries.values(), key=lambda s: -s.sum_latency_ms)

    def clear(self):
        with self._lock:
            self.slow.clear()
            self.summaries.clear()
