"""Metrics registry — counters, gauges and histograms per subsystem, plain
and labeled (ref: pkg/metrics Prometheus wrappers; CounterVec/HistogramVec
are the prometheus client_golang vec types). `Registry.dump()` emits the
Prometheus text exposition format v0.0.4 — `# HELP`/`# TYPE` headers,
label sets, and cumulative `_bucket{le="..."}` lines — which the HTTP
status server serves raw at `GET /metrics`."""

from __future__ import annotations

import threading
from bisect import bisect_right

_DEFAULT_BUCKETS = (0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0)


def _esc(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"')


def _fmt_labels(names: tuple, values: tuple) -> str:
    if not names:
        return ""
    return "{" + ",".join(f'{k}="{_esc(v)}"' for k, v in zip(names, values)) + "}"


def _fmt_value(v) -> str:
    if isinstance(v, float):
        return f"{v:.6f}"
    return str(v)


class Counter:
    __slots__ = ("name", "help", "_v", "_lock", "_labels")

    def __init__(self, name: str, help: str = "", labels: str = ""):
        self.name = name
        self.help = help
        self._v = 0  # guarded_by: _lock
        self._lock = threading.Lock()
        self._labels = labels  # pre-rendered {k="v",...} or ""

    def inc(self, n: int = 1):
        with self._lock:
            self._v += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._v

    def _expose(self) -> list[str]:
        with self._lock:
            v = self._v
        return [f"{self.name}{self._labels} {v}"]


class Gauge:
    """A value that goes up AND down (open txns, cache entries, pool size)."""

    __slots__ = ("name", "help", "_v", "_lock", "_labels")

    def __init__(self, name: str, help: str = "", labels: str = ""):
        self.name = name
        self.help = help
        self._v = 0.0  # guarded_by: _lock
        self._lock = threading.Lock()
        self._labels = labels

    def set(self, v: float):
        with self._lock:
            self._v = v

    def inc(self, n: float = 1):
        with self._lock:
            self._v += n

    def dec(self, n: float = 1):
        with self._lock:
            self._v -= n

    @property
    def value(self) -> float:
        with self._lock:
            return self._v

    def _expose(self) -> list[str]:
        with self._lock:
            v = self._v
        return [f"{self.name}{self._labels} {_fmt_value(int(v) if float(v).is_integer() else v)}"]


class Histogram:
    __slots__ = ("name", "help", "buckets", "_counts", "_sum", "_n", "_lock", "_labels")

    def __init__(self, name: str, help: str = "", buckets=_DEFAULT_BUCKETS, labels: str = ""):
        self.name = name
        self.help = help
        self.buckets = tuple(buckets)
        self._counts = [0] * (len(self.buckets) + 1)  # guarded_by: _lock
        self._sum = 0.0  # guarded_by: _lock
        self._n = 0  # guarded_by: _lock
        self._lock = threading.Lock()
        self._labels = labels

    def observe(self, v: float):
        with self._lock:
            self._counts[bisect_right(self.buckets, v)] += 1
            self._sum += v
            self._n += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._n

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def _expose(self) -> list[str]:
        """Cumulative bucket lines + sum + count, the histogram exposition
        contract (`le` is inclusive upper bound; +Inf == count)."""
        base = self._labels[1:-1] if self._labels else ""
        lines = []
        with self._lock:
            cum = 0
            for ub, c in zip(self.buckets, self._counts):
                cum += c
                ls = ",".join(x for x in (base, f'le="{ub}"') if x)
                lines.append(f"{self.name}_bucket{{{ls}}} {cum}")
            ls = ",".join(x for x in (base, 'le="+Inf"') if x)
            lines.append(f"{self.name}_bucket{{{ls}}} {self._n}")
            lines.append(f"{self.name}_sum{self._labels} {self._sum:.6f}")
            lines.append(f"{self.name}_count{self._labels} {self._n}")
        return lines


class _Vec:
    """Label-set family sharing one metric name (ref: prometheus *Vec).
    `labels(**kv)` returns (creating once) the child for that label set."""

    _child_cls: type = Counter
    typ = "counter"

    def __init__(self, name: str, help: str = "", labelnames: tuple = (), **kw):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._kw = kw
        self._children: dict[tuple, object] = {}  # guarded_by: _lock
        self._lock = threading.Lock()

    def labels(self, *values, **kv):
        if kv:
            if values:
                raise ValueError("pass label values positionally or by name, not both")
            values = tuple(kv[n] for n in self.labelnames)
        values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(f"{self.name} expects labels {self.labelnames}, got {values}")
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._child_cls(
                    self.name, self.help,
                    labels=_fmt_labels(self.labelnames, values), **self._kw,
                )
                self._children[values] = child
            return child

    def _expose(self) -> list[str]:
        with self._lock:
            kids = [self._children[k] for k in sorted(self._children)]
        out: list[str] = []
        for c in kids:
            out.extend(c._expose())
        return out


class CounterVec(_Vec):
    _child_cls = Counter
    typ = "counter"


class GaugeVec(_Vec):
    _child_cls = Gauge
    typ = "gauge"


class HistogramVec(_Vec):
    _child_cls = Histogram
    typ = "histogram"


_TYPE_OF = {Counter: "counter", Gauge: "gauge", Histogram: "histogram"}


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}  # guarded_by: _lock

    def _get_or_make(self, name: str, factory):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_make(name, lambda: Counter(name, help))

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_make(name, lambda: Gauge(name, help))

    def histogram(self, name: str, help: str = "", buckets=_DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_make(name, lambda: Histogram(name, help, buckets))

    def counter_vec(self, name: str, help: str = "", labelnames: tuple = ()) -> CounterVec:
        return self._get_or_make(name, lambda: CounterVec(name, help, labelnames))

    def gauge_vec(self, name: str, help: str = "", labelnames: tuple = ()) -> GaugeVec:
        return self._get_or_make(name, lambda: GaugeVec(name, help, labelnames))

    def histogram_vec(self, name: str, help: str = "", labelnames: tuple = (), buckets=_DEFAULT_BUCKETS) -> HistogramVec:
        return self._get_or_make(
            name, lambda: HistogramVec(name, help, labelnames, buckets=buckets)
        )

    def dump(self) -> str:
        """Prometheus text exposition format v0.0.4 (the scrapeable form;
        tools/scrape_check.py validates this output in the test suite)."""
        with self._lock:
            items = sorted(self._metrics.items())
        lines: list[str] = []
        for name, m in items:
            typ = getattr(m, "typ", None) or _TYPE_OF[type(m)]
            if m.help:
                lines.append(f"# HELP {name} {m.help}")
            lines.append(f"# TYPE {name} {typ}")
            lines.extend(m._expose())
        return "\n".join(lines) + ("\n" if lines else "")

    def sample_lines(self) -> list[tuple[str, str]]:
        """(series-with-labels, value) pairs of every sample — the SHOW
        STATUS / JSON view, comment lines excluded."""
        out = []
        for line in self.dump().splitlines():
            if not line or line.startswith("#"):
                continue
            series, _, value = line.rpartition(" ")
            out.append((series, value))
        return out

    def labeled_samples(self, family: str) -> dict:
        """First-label-value -> numeric sample for one labeled family
        (e.g. "tidb_tpu_replica_read_total" -> {"leader": 3.0, ...}) —
        THE shared parser for bench/chaos-style per-label readouts (three
        call sites used to hand-roll the same sample_lines() split)."""
        out: dict[str, float] = {}
        for series, value in self.sample_lines():
            if series.startswith(family + "{"):
                out[series.split('"')[1]] = float(value)
        return out

    def reset(self):
        with self._lock:
            self._metrics.clear()


REGISTRY = Registry()

# the subsystems' shared instruments (ref: pkg/metrics per-subsystem files)
COP_REQUESTS = REGISTRY.counter("tidb_tpu_cop_requests_total", "coprocessor requests served")
COP_ERRORS = REGISTRY.counter("tidb_tpu_cop_errors_total", "coprocessor requests failed")
COP_FALLBACKS = REGISTRY.counter("tidb_tpu_cop_oracle_fallbacks_total", "cop requests served by the oracle fallback")
COP_CACHE_HITS = REGISTRY.counter("tidb_tpu_cop_cache_hits_total", "cop requests served from the coprocessor result cache")
BATCH_COP_BATCHES = REGISTRY.counter("tidb_tpu_batch_cop_batches_total", "vmapped multi-region coprocessor launches")
BATCH_COP_REGIONS = REGISTRY.counter("tidb_tpu_batch_cop_regions_total", "regions served by batched coprocessor launches")
BATCH_COP_LAUNCHES_SAVED = REGISTRY.counter("tidb_tpu_batch_cop_launches_saved_total", "per-region XLA launches avoided by batching (regions - launches)")
COP_DURATION = REGISTRY.histogram("tidb_tpu_cop_duration_seconds", "coprocessor request latency")
COP_EXECUTOR_ROWS = REGISTRY.counter_vec(
    "tidb_tpu_cop_executor_rows_total", "rows produced per pushed executor",
    labelnames=("executor",),
)
DISTSQL_TASKS = REGISTRY.counter("tidb_tpu_distsql_tasks_total", "per-region cop tasks dispatched")
DISTSQL_STORE_TASKS = REGISTRY.counter_vec(
    "tidb_tpu_distsql_store_tasks_total", "cop tasks dispatched per placement store",
    labelnames=("store",),
)
DISTSQL_TASK_DURATION = REGISTRY.histogram_vec(
    "tidb_tpu_distsql_task_duration_seconds", "per-region cop task latency incl. paging+retries",
    labelnames=("scan",),
)
MESH_SELECTS = REGISTRY.counter("tidb_tpu_mesh_selects_total", "SQL plans executed over the device mesh")
MESH_COP_BATCHES = REGISTRY.counter("tidb_tpu_mesh_cop_batches_total", "shard_map mesh-tier launches (one merged state per launch)")
MESH_COP_LANES = REGISTRY.counter("tidb_tpu_mesh_cop_lanes_total", "region lanes whose partial states were psum-merged on device")
MESH_COP_FALLBACKS = REGISTRY.counter("tidb_tpu_mesh_cop_fallbacks_total", "mesh-tier groups degraded to the vmapped batch tier (overflow/trace failure)")
SPILL_PARTITIONS = REGISTRY.counter("tidb_tpu_spill_partitions_total", "out-of-capacity host-partitioned multi-pass executions (the spill analog)")
MEM_EVICTIONS = REGISTRY.counter("tidb_tpu_mem_evictions_total", "store cache evictions by the OOM action")
MEM_DEGRADED_QUERIES = REGISTRY.counter("tidb_tpu_mem_degraded_total", "queries degraded to the low-memory fold path")
DISTSQL_RETRIES = REGISTRY.counter("tidb_tpu_distsql_region_retries_total", "region-error retries")
BACKOFF_SECONDS = REGISTRY.counter_vec(
    "tidb_tpu_backoff_seconds_total", "dispatch backoff sleep time by error kind",
    labelnames=("kind",),
)
REGION_ERRORS = REGISTRY.counter_vec(
    "tidb_tpu_region_errors_total", "typed region errors seen by dispatch",
    labelnames=("kind",),
)
BREAKER_STATE = REGISTRY.gauge_vec(
    "tidb_tpu_store_breaker_state", "per-store circuit breaker state (0=closed 1=half-open 2=open)",
    labelnames=("store",),
)
BREAKER_TRIPS = REGISTRY.counter_vec(
    "tidb_tpu_store_breaker_trips_total", "circuit-breaker open transitions per store",
    labelnames=("store",),
)
# region replication (tidb_tpu/replication) — replica reads + safe_ts
REPLICA_READS = REGISTRY.counter_vec(
    "tidb_tpu_replica_read_total", "cop tasks served by peer role under tidb_replica_read routing",
    labelnames=("target",),
)
REPLICA_SAFE_TS_LAG = REGISTRY.gauge_vec(
    "tidb_tpu_replica_safe_ts_lag", "worst follower safe_ts lag behind its leader's committed watermark, per store (ts units)",
    labelnames=("store",),
)
REPLICA_QUORUM_FAILS = REGISTRY.counter(
    "tidb_tpu_replica_quorum_fail_total", "write proposals that failed to reach quorum ack")
PROGRAM_COMPILES = REGISTRY.counter("tidb_tpu_program_compiles_total", "fused XLA programs built")
PROGRAM_LAUNCHES = REGISTRY.counter("tidb_tpu_program_launches_total", "fused XLA program executions dispatched (batched counts once)")
PROGRAM_CACHE_HITS = REGISTRY.counter("tidb_tpu_program_cache_hits_total", "program-cache hits (compile skipped)")
PROGRAM_CACHE_ENTRIES = REGISTRY.gauge("tidb_tpu_program_cache_entries", "compiled programs resident in the cache")
PROGRAM_COMPILE_DURATION = REGISTRY.histogram(
    "tidb_tpu_program_compile_seconds", "XLA trace+compile time per program",
    buckets=(0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0),
)
STATEMENTS = REGISTRY.counter_vec(
    "tidb_tpu_statements_total", "statements executed by type and outcome",
    labelnames=("type", "status"),
)
# production front door (ISSUE 15) — digest-keyed plan cache + admission
PLAN_CACHE_HITS = REGISTRY.counter(
    "tidb_tpu_plan_cache_hits_total", "statements served from the digest-keyed plan cache (parse+plan skipped)")
PLAN_CACHE_MISSES = REGISTRY.counter(
    "tidb_tpu_plan_cache_misses_total", "cacheable statements that planned cold and installed an entry")
PLAN_CACHE_EVICTIONS = REGISTRY.counter(
    "tidb_tpu_plan_cache_evictions_total", "plan-cache entries evicted by the LRU capacity bound")
PLAN_CACHE_DECLINES = REGISTRY.counter_vec(
    "tidb_tpu_plan_cache_declines_total", "statements declined by the plan cache, by typed reason",
    labelnames=("reason",),
)
PLAN_CACHE_ENTRIES = REGISTRY.gauge(
    "tidb_tpu_plan_cache_entries", "plan templates resident in the cache")
PLAN_CACHE_SHARED_HITS = REGISTRY.counter(
    "tidb_tpu_plan_cache_shared_hits_total",
    "local-miss lookups served by the shared cross-catalog tier (fingerprint-revalidated)")
ADMISSION_ADMITTED = REGISTRY.counter(
    "tidb_tpu_admission_admitted_total", "statements admitted through the bounded statement gate")
ADMISSION_SHED = REGISTRY.counter_vec(
    "tidb_tpu_admission_shed_total", "statements shed with typed ServerIsBusy backpressure, by gate",
    labelnames=("where",),
)
ADMISSION_QUEUE_WAITS = REGISTRY.counter(
    "tidb_tpu_admission_queue_waits_total", "statements that waited in a per-session admission queue")
ADMISSION_INFLIGHT = REGISTRY.gauge(
    "tidb_tpu_admission_inflight", "statements currently executing inside the admission gate")
# cross-session fused execution (ISSUE 19) — the per-store session
# coalescer: point-get micro-batch windows + group-commit write batching
COALESCE_BATCHES = REGISTRY.counter(
    "tidb_tpu_coalesce_batches_total", "coalescer micro-batch windows flushed (read launches + write group commits)")
COALESCE_LANES = REGISTRY.counter_vec(
    "tidb_tpu_coalesce_lanes_total", "session lanes served through a coalesced window, by kind",
    labelnames=("kind",),
)
COALESCE_LAUNCHES_SAVED = REGISTRY.counter(
    "tidb_tpu_coalesce_launches_saved_total", "device launches avoided by cross-session point-get coalescing (lanes - launches)")
COALESCE_FALLBACKS = REGISTRY.counter_vec(
    "tidb_tpu_coalesce_fallbacks_total", "lanes that fell out of a window to the single path, by typed reason",
    labelnames=("reason",),
)
COALESCE_GROUP_COMMITS = REGISTRY.counter(
    "tidb_tpu_coalesce_group_commits_total", "write lanes committed through a group-commit window")
COALESCE_GROUP_PROPOSALS_SAVED = REGISTRY.counter(
    "tidb_tpu_coalesce_group_proposals_saved_total", "quorum proposals avoided by folding lanes into per-region group proposals")
COALESCE_WINDOW_WAIT = REGISTRY.histogram(
    "tidb_tpu_coalesce_window_wait_seconds", "time a lane parked in the coalescer window before flush",
    buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.05),
)
OPEN_TXNS = REGISTRY.gauge("tidb_tpu_open_txns", "transactions currently open")
NATIVE_DECODES = REGISTRY.counter("tidb_tpu_native_decode_batches_total", "region batches decoded by the C++ rowcodec")
NATIVE_DECODE_FALLBACKS = REGISTRY.counter("tidb_tpu_native_decode_fallbacks_total", "native decode errors served by the python decoder")

# change data capture (tidb_tpu/cdc) — the TiCDC-analog changefeed
# families (ref: ticdc_* metrics: puller/sorter event counts, the
# checkpoint/resolved lag gauges, sink flush histograms)
CDC_EVENTS = REGISTRY.counter(
    "tidb_tpu_cdc_events_total", "raw change entries captured from the replication log (live + recovery scans)")
CDC_EVENTS_EMITTED = REGISTRY.counter(
    "tidb_tpu_cdc_events_emitted_total", "mounted row events emitted to changefeed sinks")
CDC_EVENTS_SKIPPED = REGISTRY.counter(
    "tidb_tpu_cdc_events_skipped_total", "captured entries skipped at mount (index entries, meta keys, unknown tables)")
CDC_RESOLVED_LAG = REGISTRY.gauge_vec(
    "tidb_tpu_cdc_resolved_ts_lag", "latest commit watermark minus the changefeed's emitted resolved frontier (ts units)",
    labelnames=("changefeed",),
)
CDC_SINK_FLUSH = REGISTRY.histogram(
    "tidb_tpu_cdc_sink_flush_seconds", "sink write+flush latency per changefeed tick")
CDC_RECOVERY_SCANS = REGISTRY.counter(
    "tidb_tpu_cdc_recovery_scans_total", "incremental re-scans after a lost subscription, pause resume, or changefeed birth")
CDC_SCHEMA_EVENTS = REGISTRY.counter(
    "tidb_tpu_cdc_schema_events_total", "schema-change entries replicated through changefeeds as ordered DDL events (ISSUE 20)")
CDC_SCHEMA_DRIFT_LEGACY = REGISTRY.counter(
    "tidb_tpu_cdc_schema_drift_legacy_total", "rows the tracked snapshot could not decode, re-decoded against the live catalog (the counted legacy drift fallback)")

# HTAP columnar replica (tidb_tpu/columnar) — the TiFlash-analog tier
# (ref: tiflash_* metrics: apply throughput, delta compaction counts, the
# replica freshness gauges)
COLUMNAR_APPLIED = REGISTRY.counter(
    "tidb_tpu_columnar_applied_events_total", "mounted row events applied into columnar delta layers")
COLUMNAR_COMPACTIONS = REGISTRY.counter(
    "tidb_tpu_columnar_compactions_total", "delta-to-stable compaction passes that folded rows")
COLUMNAR_SCANS = REGISTRY.counter(
    "tidb_tpu_columnar_scans_total", "analytical queries served by the columnar replica")
COLUMNAR_FALLBACKS = REGISTRY.counter(
    "tidb_tpu_columnar_fallbacks_total", "engine-routed queries that fell back to the row store (frontier lag, floored snapshot, schema drift)")
COLUMNAR_RESOLVED_LAG = REGISTRY.gauge_vec(
    "tidb_tpu_columnar_resolved_ts_lag", "latest commit watermark minus the replica's applied resolved frontier, per table (ts units)",
    labelnames=("table",),
)
COLUMNAR_RESHAPES = REGISTRY.counter(
    "tidb_tpu_columnar_reshapes_total", "mid-feed ALTERs applied to columnar replicas by col_id remap (zero parks; ISSUE 20)")

# point-in-time recovery (tidb_tpu/br; ISSUE 20) — the log-backup stream
# and replay-to-ts restore families (ref: BR's br_log_backup_* /
# tikv_log_backup_* checkpoint and flush metrics)
LOG_BACKUP_SEGMENTS = REGISTRY.counter(
    "tidb_tpu_log_backup_segments_total", "atomic log-backup segments committed (write-temp + fsync + rename)")
LOG_BACKUP_EVENTS = REGISTRY.counter(
    "tidb_tpu_log_backup_events_total", "raw KV change records persisted into log-backup segments")
LOG_BACKUP_CHECKPOINT_TS = REGISTRY.gauge_vec(
    "tidb_tpu_log_backup_checkpoint_ts", "the log backup's durable manifest checkpoint (every commit at or below it is restorable)",
    labelnames=("changefeed",),
)
LOG_BACKUP_LAG = REGISTRY.gauge_vec(
    "tidb_tpu_log_backup_resolved_lag", "latest commit watermark minus the log backup's durable checkpoint (ts units)",
    labelnames=("changefeed",),
)
PITR_RESTORES = REGISTRY.counter(
    "tidb_tpu_pitr_restores_total", "RESTORE ... UNTIL TS runs that completed (full backup + log replay)")
PITR_SEGMENTS_REPLAYED = REGISTRY.counter(
    "tidb_tpu_pitr_segments_replayed_total", "log segments replayed into a restore target")
PITR_REPLAYED_EVENTS = REGISTRY.counter(
    "tidb_tpu_pitr_replayed_events_total", "KV and schema records applied during log replay")
PITR_LOG_GAPS = REGISTRY.counter(
    "tidb_tpu_pitr_log_gaps_total", "restores refused with a typed LogGapError (missing/corrupt segment, broken chain, short log)")
PITR_REPLAY_RESUMES = REGISTRY.counter(
    "tidb_tpu_pitr_replay_resumes_total", "restores that resumed from a per-segment checkpoint after a mid-replay crash")

# mpp exchange data plane (ISSUE 18; ref: tiflash_coprocessor_* mpp task
# metrics and the mpp_gather dispatch counters)
MPP_SELECTS = REGISTRY.counter(
    "tidb_tpu_mpp_selects_total", "SQL plans executed through the mpp exchange tier")
MPP_FRAGMENTS = REGISTRY.counter(
    "tidb_tpu_mpp_fragments_total", "plan fragments cut at exchange boundaries by the fragment planner")
MPP_TASKS = REGISTRY.counter(
    "tidb_tpu_mpp_tasks_total", "SPMD fragment tasks dispatched (fragments x mesh width)")
MPP_FALLBACKS = REGISTRY.counter(
    "tidb_tpu_mpp_fallbacks_total", "mpp-eligible plans that fell back (dispatch lost, exchange stall, overflow ladder exhausted, stack refusal)")
MPP_EXCHANGED_BYTES = REGISTRY.counter(
    "tidb_tpu_mpp_exchanged_bytes_total", "bytes entering the all_to_all exchange (probe + build sides, pre-partition)")

# placement driver (tidb_tpu/pd) — its own pd_ namespace, like the
# reference PD process exposing pd_scheduler_*/pd_hotspot_* families
PD_REGION_HEARTBEATS = REGISTRY.counter("pd_region_heartbeat_total", "region heartbeat snapshots absorbed by the PD")
PD_OPERATORS = REGISTRY.counter_vec(
    "pd_operator_total", "operators admitted to the PD queue by type",
    labelnames=("type",),
)
PD_OPERATOR_TIMEOUTS = REGISTRY.counter("pd_operator_timeout_total", "pending operators expired before dispatch")
PD_OPERATOR_PENDING = REGISTRY.gauge("pd_operator_pending", "operators waiting in the PD queue")
PD_HOT_REGION = REGISTRY.gauge_vec(
    "pd_hot_region", "hot regions (read or write) placed on each store",
    labelnames=("store",),
)
PD_STORE_REGIONS = REGISTRY.gauge_vec(
    "pd_store_regions", "regions placed on each store",
    labelnames=("store",),
)
PD_REGIONS = REGISTRY.gauge("pd_regions", "regions in the cluster")
PD_PLACEMENT_DECISIONS = REGISTRY.counter("pd_placement_decision_total", "placement-map misses resolved by a PD least-loaded decision")
PD_FAILOVERS = REGISTRY.counter("pd_failover_total", "regions failed over off a sick store (leader transfer or placement move)")
PD_TRANSFER_LEADER = REGISTRY.counter("pd_transfer_leader_total", "region leaderships transferred between peers")
PD_TICK_DURATION = REGISTRY.histogram("pd_tick_seconds", "PD scheduling tick latency")

# Top SQL resource attribution (tidb_tpu/topsql) — ref: the
# tidb_topsql_* families of pkg/util/topsql/reporter. Time counters stay
# in the ledger's native integer units (ns / ms) so the exposition
# reconciles EXACTLY against the window sums the API serves — converting
# to seconds would make the cross-surface consistency check float-fuzzy.
TOPSQL_RECORDS = REGISTRY.counter(
    "tidb_tpu_topsql_records_total", "finished statements folded into the Top SQL ledger")
TOPSQL_CPU_NS = REGISTRY.counter(
    "tidb_tpu_topsql_cpu_ns_total", "host thread-CPU ns attributed to tagged statements")
TOPSQL_DEVICE_NS = REGISTRY.counter(
    "tidb_tpu_topsql_device_ns_total", "fused-program device ns attributed to tagged statements")
TOPSQL_COMPILE_NS = REGISTRY.counter(
    "tidb_tpu_topsql_compile_ns_total", "program compile ns attributed to tagged statements")
TOPSQL_BACKOFF_MS = REGISTRY.counter(
    "tidb_tpu_topsql_backoff_ms_total", "Backoffer sleep ms attributed to tagged statements")
TOPSQL_QUEUE_MS = REGISTRY.counter(
    "tidb_tpu_topsql_queue_ms_total", "admission queue wait ms attributed to tagged statements")
TOPSQL_LAUNCH_DEVICE_NS = REGISTRY.counter(
    "tidb_tpu_topsql_launch_device_ns_total", "total device ns of launches that ran under a statement tag (the conservation ledger)")
TOPSQL_WINDOWS_SEALED = REGISTRY.counter(
    "tidb_tpu_topsql_windows_sealed_total", "Top SQL reporter windows sealed into the ring")
TOPSQL_OTHERS_FOLDED = REGISTRY.counter(
    "tidb_tpu_topsql_others_folded_total", "digests folded into a window's (others) row at seal time")
TOPSQL_LIVE_DIGESTS = REGISTRY.gauge(
    "tidb_tpu_topsql_live_digests", "distinct digests in the live (unsealed) window")
TOPSQL_CLASS_DECISIONS = REGISTRY.counter_vec(
    "tidb_tpu_topsql_class_admissions_total", "cost-classed admission decisions by class",
    labelnames=("cost_class", "decision"),
)
