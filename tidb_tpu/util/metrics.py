"""Metrics registry — counters and histograms per subsystem (ref:
pkg/metrics Prometheus wrappers; this is the in-process equivalent with a
text exposition dump instead of an HTTP endpoint)."""

from __future__ import annotations

import threading
from bisect import bisect_right

_DEFAULT_BUCKETS = (0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0)


class Counter:
    __slots__ = ("name", "help", "_v", "_lock")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1):
        with self._lock:
            self._v += n

    @property
    def value(self) -> int:
        return self._v


class Histogram:
    __slots__ = ("name", "help", "buckets", "_counts", "_sum", "_n", "_lock")

    def __init__(self, name: str, help: str = "", buckets=_DEFAULT_BUCKETS):
        self.name = name
        self.help = help
        self.buckets = tuple(buckets)
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._n = 0
        self._lock = threading.Lock()

    def observe(self, v: float):
        with self._lock:
            self._counts[bisect_right(self.buckets, v)] += 1
            self._sum += v
            self._n += 1

    @property
    def count(self) -> int:
        return self._n

    @property
    def sum(self) -> float:
        return self._sum


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}

    def counter(self, name: str, help: str = "") -> Counter:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Counter(name, help)
                self._metrics[name] = m
            return m

    def histogram(self, name: str, help: str = "", buckets=_DEFAULT_BUCKETS) -> Histogram:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Histogram(name, help, buckets)
                self._metrics[name] = m
            return m

    def dump(self) -> str:
        """Prometheus-style text exposition."""
        lines = []
        with self._lock:
            for name in sorted(self._metrics):
                m = self._metrics[name]
                if isinstance(m, Counter):
                    lines.append(f"{name} {m.value}")
                else:
                    lines.append(f"{name}_count {m.count}")
                    lines.append(f"{name}_sum {m.sum:.6f}")
        return "\n".join(lines)

    def reset(self):
        with self._lock:
            self._metrics.clear()


REGISTRY = Registry()

# the subsystems' shared instruments (ref: pkg/metrics per-subsystem files)
COP_REQUESTS = REGISTRY.counter("tidb_tpu_cop_requests_total", "coprocessor requests served")
COP_ERRORS = REGISTRY.counter("tidb_tpu_cop_errors_total", "coprocessor requests failed")
COP_FALLBACKS = REGISTRY.counter("tidb_tpu_cop_oracle_fallbacks_total", "cop requests served by the oracle fallback")
COP_DURATION = REGISTRY.histogram("tidb_tpu_cop_duration_seconds", "coprocessor request latency")
DISTSQL_TASKS = REGISTRY.counter("tidb_tpu_distsql_tasks_total", "per-region cop tasks dispatched")
MESH_SELECTS = REGISTRY.counter("tidb_tpu_mesh_selects_total", "SQL plans executed over the device mesh")
SPILL_PARTITIONS = REGISTRY.counter("tidb_tpu_spill_partitions_total", "out-of-capacity host-partitioned multi-pass executions (the spill analog)")
MEM_EVICTIONS = REGISTRY.counter("tidb_tpu_mem_evictions_total", "store cache evictions by the OOM action")
MEM_DEGRADED_QUERIES = REGISTRY.counter("tidb_tpu_mem_degraded_total", "queries degraded to the low-memory fold path")
DISTSQL_RETRIES = REGISTRY.counter("tidb_tpu_distsql_region_retries_total", "region-error retries")
PROGRAM_COMPILES = REGISTRY.counter("tidb_tpu_program_compiles_total", "fused XLA programs built")
NATIVE_DECODES = REGISTRY.counter("tidb_tpu_native_decode_batches_total", "region batches decoded by the C++ rowcodec")
NATIVE_DECODE_FALLBACKS = REGISTRY.counter("tidb_tpu_native_decode_fallbacks_total", "native decode errors served by the python decoder")
