"""Failpoints — compile-time-free fault injection (ref:
github.com/pingcap/failpoint; 673 sites in the reference, activated
per-test via testkit/testfailpoint).

A failpoint is a named hook; tests enable it with a value (bool, count, or
callable). Production code calls `eval("name")` at the site; disabled sites
cost one dict lookup."""

from __future__ import annotations

import threading

_lock = threading.Lock()
_active: dict[str, object] = {}  # guarded_by: _lock


def enable(name: str, value: object = True):
    with _lock:
        _active[name] = value


def disable(name: str):
    with _lock:
        _active.pop(name, None)


def is_armed(name: str) -> bool:
    """True when the failpoint is enabled, WITHOUT consuming a count —
    batch paths use this to route through the single-request code where
    the injection site actually lives."""
    # benign unlocked probe: one GIL-atomic dict lookup on the hot path
    return name in _active  # vet: ignore[lock-discipline]


def peek(name: str):
    """The failpoint's raw value WITHOUT consuming a count or invoking a
    callable — health probes use this to ask 'would this site fire for
    store N?' without firing it."""
    return _active.get(name)  # vet: ignore[lock-discipline] — GIL-atomic probe


def eval(name: str):  # noqa: A001 (mirrors the reference API)
    """Returns the failpoint's value if enabled, else None. A callable
    value is invoked (and may raise, the usual injection shape); an int
    value decrements per hit and auto-disables at 0 (fire-N-times)."""
    # disabled sites cost ONE unlocked dict lookup (the contract above);
    # arming/decrement take the lock
    v = _active.get(name)  # vet: ignore[lock-discipline]
    if v is None:
        return None
    if callable(v):
        return v()
    if isinstance(v, int) and not isinstance(v, bool):
        with _lock:
            left = _active.get(name)
            if isinstance(left, int) and left <= 1:
                _active.pop(name, None)
            elif isinstance(left, int):
                _active[name] = left - 1
        return True
    return v


class enabled:  # noqa: N801 — context manager, test-side sugar
    def __init__(self, name: str, value: object = True):
        self.name = name
        self.value = value

    def __enter__(self):
        enable(self.name, self.value)
        return self

    def __exit__(self, *exc):
        disable(self.name)
        return False
