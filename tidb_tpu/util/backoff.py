"""Backoffer — per-error-kind exponential backoff with a per-task budget
(ref: tikv/client-go/v2 retry/backoff.go Backoffer + config.go's
BoRegionMiss/BoUpdateLeader/BoServerBusy/BoTiKVRPC configs; TiDB scales
every budget by the `tidb_backoff_weight` sysvar, sessionctx/variable
BackOffWeight -> store/copr's backoffer construction).

Each region-error KIND owns an exponential (base, cap) schedule with equal
jitter — attempt n sleeps uniform[raw/2, raw] where raw = min(base·2ⁿ, cap)
— while ONE shared budget bounds the task's total sleep: when the next
sleep would exceed `budget_ms × weight`, the Backoffer raises
`BackoffExhausted` and the dispatch layer surfaces a typed
RegionUnavailableError (MySQL 9005) instead of spinning forever.

Sleeps are engineered, not naive:

  * deadline-aware — never sleeps past the RunawayChecker's
    MAX_EXECUTION_TIME deadline (sleeping longer would only wake up to die);
  * interruptible — sleeps in small slices, consulting the checker between
    slices, so KILL QUERY aborts a statement MID-backoff rather than after;
  * attributed — every slept interval lands on the ambient trace span
    (`backoff_ms`) and the `tidb_tpu_backoff_seconds_total{kind=}` counter.

The schedule values are the reference's, scaled to this engine's
in-process latencies (a TiKV RPC is ~ms; a cop call here is ~µs).

This module is the ONLY sanctioned sleep on a request path: the
`dataflow-backoff` vet pass (tidb_tpu/analysis/dataflow.py) flags any
raw `time.sleep` reachable from dispatch, and any unbounded retry loop
that never consults a Backoffer budget."""

from __future__ import annotations

import random
import time
from dataclasses import dataclass


class BackoffExhausted(RuntimeError):
    """The task's total-sleep budget is spent; the error is no longer
    retryable at this layer (ref: Backoffer.Backoff returning
    ErrTimeout once totalSleep exceeds maxSleep)."""

    def __init__(self, message: str, kind: str = ""):
        super().__init__(message)
        self.kind = kind


@dataclass(frozen=True)
class BackoffConfig:
    """One error kind's schedule (ref: retry/config.go NewConfig)."""

    kind: str
    base_ms: float
    cap_ms: float


# client-go's budgets, scaled ~1/25 to in-process latencies
# (BoRegionMiss 2/500, BoUpdateLeader 1/10, BoServerBusy 2000/10000,
# BoTiKVRPC 100/2000)
CONFIGS = {
    "region_miss": BackoffConfig("region_miss", 2, 100),
    "epoch_not_match": BackoffConfig("epoch_not_match", 2, 100),
    "region_not_found": BackoffConfig("region_not_found", 2, 100),
    "not_leader": BackoffConfig("not_leader", 2, 100),
    "server_busy": BackoffConfig("server_busy", 10, 400),
    "store_unavailable": BackoffConfig("store_unavailable", 10, 400),
    # follower safe_ts behind start_ts (ref: BoMaxDataNotReady 2/2000);
    # one short wait, then the client falls back to the leader
    "data_not_ready": BackoffConfig("data_not_ready", 2, 80),
}

DEFAULT_BUDGET_MS = 200.0  # per-task; scaled by tidb_backoff_weight
_SLICE_MS = 10.0  # checker-consultation granularity inside one sleep


class Backoffer:
    """One per cop task (the reference allocates one per request chain).

    `weight` is the `tidb_backoff_weight` sysvar; `checker` the
    statement's RunawayChecker (deadline + KILL flag); `rng`, `sleep_fn`
    and `now_fn` are injectable for deterministic tests."""

    def __init__(self, budget_ms: float = DEFAULT_BUDGET_MS, weight: int = 2,
                 checker=None, rng: random.Random | None = None,
                 sleep_fn=time.sleep, now_fn=time.monotonic):
        self.limit_ms = float(budget_ms) * max(int(weight), 0)
        self.checker = checker
        self.total_ms = 0.0
        self.attempts: dict[str, int] = {}
        self._rng = rng or random.Random()
        self._sleep = sleep_fn
        self._now = now_fn

    def backoff(self, kind: str, err: str = "", suggested_ms: float = 0.0) -> float:
        """Sleep one step of `kind`'s schedule (the server's suggested
        wait — ServerIsBusy.backoff_ms — acts as a floor, like client-go
        honoring the errorpb suggestion). Returns ms actually slept;
        raises BackoffExhausted when the budget cannot cover the step."""
        cfg = CONFIGS.get(kind) or BackoffConfig(kind, 2, 100)
        n = self.attempts.get(kind, 0)
        self.attempts[kind] = n + 1
        raw = min(cfg.base_ms * (2.0 ** n), cfg.cap_ms)
        ms = raw / 2.0 + self._rng.uniform(0.0, raw / 2.0)  # equal jitter
        ms = max(ms, float(suggested_ms))
        if self.total_ms + ms > self.limit_ms:
            raise BackoffExhausted(
                f"backoff budget exhausted after {self.total_ms:.0f}ms "
                f"(limit {self.limit_ms:.0f}ms, kind {kind}): {err}",
                kind=kind,
            )
        return self.sleep(ms, kind)

    def sleep(self, ms: float, kind: str = "manual") -> float:
        """Deadline-clamped, checker-interruptible sleep. The checker is
        consulted BETWEEN slices so KILL QUERY lands mid-backoff (a
        statement must not finish a 400ms server-busy nap before noticing
        it was killed); the deadline clamp means a sleep never outlives
        MAX_EXECUTION_TIME."""
        from . import metrics, tracing

        if self.checker is not None:
            self.checker.before_cop_request()  # raises if killed/overdue
            dl = getattr(self.checker, "deadline", None)
            if dl is not None:
                ms = min(ms, max((dl - self._now()) * 1000.0, 0.0))
        slept = 0.0
        while slept < ms:
            step = min(_SLICE_MS, ms - slept)
            self._sleep(step / 1000.0)
            slept += step
            if self.checker is not None and slept < ms:
                self.checker.before_cop_request()
        self.total_ms += slept
        metrics.BACKOFF_SECONDS.labels(kind).inc(slept / 1000.0)
        sp = tracing.current_span()
        if sp is not None:
            sp.set("backoff_ms", round(sp.attrs.get("backoff_ms", 0.0) + slept, 2))
        from ..topsql import record_backoff

        record_backoff(slept)  # Top SQL: the statement owns its naps
        return slept
