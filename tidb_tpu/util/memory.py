"""Memory tracking (ref: pkg/util/memory — Tracker tree with quotas and
OOM action chain: spill / cancel / log).

Trackers form a parent tree; consumption propagates to the root. Exceeding
a tracker's quota runs its action (default: raise QuotaExceeded — the
'cancel' action; callers can install softer actions such as cache
eviction, the spill analog)."""

from __future__ import annotations

import threading


class QuotaExceeded(MemoryError):
    def __init__(self, tracker: "MemTracker", requested: int):
        super().__init__(
            f"memory quota exceeded: tracker {tracker.label!r} at "
            f"{tracker.consumed} + {requested} > {tracker.quota}"
        )
        self.tracker = tracker


class MemTracker:
    def __init__(self, label: str, quota: int | None = None, parent: "MemTracker | None" = None, action=None):
        self.label = label
        self.quota = quota
        self.parent = parent
        self.action = action  # callable(tracker, requested) -> None; may free
        self._consumed = 0  # guarded_by: _lock
        self._peak = 0  # guarded_by: _lock
        self._lock = threading.Lock()

    @property
    def consumed(self) -> int:
        with self._lock:
            return self._consumed

    @property
    def peak(self) -> int:
        with self._lock:
            return self._peak

    def consume(self, n: int):
        """Account n bytes (negative releases). Over-quota runs the action
        once, then re-checks; still over -> QuotaExceeded."""
        with self._lock:
            self._consumed += n
            self._peak = max(self._peak, self._consumed)
            over = self.quota is not None and n > 0 and self._consumed > self.quota
        if over:
            if self.action is not None:
                self.action(self, n)
                with self._lock:
                    over = self.quota is not None and self._consumed > self.quota
            if over:
                raise QuotaExceeded(self, n)
        if self.parent is not None:
            self.parent.consume(n)

    def release_all(self):
        with self._lock:
            n = self._consumed
            self._consumed = 0
        if self.parent is not None and n:
            self.parent.consume(-n)
