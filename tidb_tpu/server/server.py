"""MySQL protocol server over the embedded engine (ref: pkg/server/server.go
accept loop, conn.go clientConn.Run/dispatch/writeResultSet;
cmd/tidb-server/main.go wiring).

One OS thread per connection (the reference runs one goroutine per conn);
every connection gets its own Session over the shared store + catalog, so
transactions, sysvars and temporary state are per-connection exactly like
the reference's session management."""

from __future__ import annotations

import socket
import socketserver
import threading

from ..sql import Session, SQLError
from ..sql.catalog import Catalog, CatalogError
from ..sql.planner import PlanError
from ..store import TPUStore
from ..types import Datum, DatumKind, Flag
from . import protocol as P


def datum_text(d: Datum) -> str | None:
    """Datum -> text-protocol cell (ref: dumpTextRow value formatting)."""
    if d.is_null():
        return None
    if d.kind == DatumKind.Bytes:
        v = d.val
        return v.decode("utf-8", "surrogateescape") if isinstance(v, bytes) else str(v)
    if d.kind in (DatumKind.Float32, DatumKind.Float64):
        v = float(d.val)
        if v == int(v) and abs(v) < 1e15:
            return str(int(v))
        return repr(v)
    if d.kind == DatumKind.MysqlJSON:
        from ..types import json_binary as jb

        return jb.to_text(jb.decode(d.val))
    return str(d.val)


def column_flags(ft) -> int:
    flags = 0
    if ft.flag & Flag.NotNull:
        flags |= 1  # NOT_NULL_FLAG
    if ft.is_unsigned():
        flags |= 32  # UNSIGNED_FLAG
    return flags


class Connection:
    def __init__(self, sock, server, conn_id: int):
        self.io = P.PacketIO(sock)
        self.server = server
        self.conn_id = conn_id
        self.session = Session(server.store, server.catalog, config=server.config)

    # ------------------------------------------------------------------
    def handshake(self) -> bool:
        salt = P.new_salt()
        self.io.write(P.handshake_v10(self.conn_id, salt))
        resp = P.parse_handshake_response(self.io.read())
        user = resp["user"]
        if self.server.users:
            stored = self.server.users.get(user)  # explicit override map
        else:
            # CREATE USER records (ref: privilege cache feeding auth)
            stored = self.server.catalog.privileges.password_of(user)
        if stored is None:
            self.io.write(P.err_packet(1045, f"Access denied for user '{user}'", "28000"))
            return False
        if not P.check_auth(stored, salt, resp["auth"]):
            self.io.write(P.err_packet(1045, f"Access denied for user '{user}'", "28000"))
            return False
        if not self.server.users:
            # privilege-store users run as themselves; the explicit override
            # map is a test shortcut whose users bypass privilege checks
            self.session.user = user.lower()
        self.io.write(P.ok_packet(status=self._status()))
        return True

    def _status(self) -> int:
        st = P.SERVER_STATUS_AUTOCOMMIT
        if self.session.txn is not None:
            st |= P.SERVER_STATUS_IN_TRANS
        return st

    # ------------------------------------------------------------------
    def run(self):
        while True:
            self.io.reset()
            try:
                pkt = self.io.read()
            except (ConnectionError, OSError):
                return
            if not pkt:
                continue
            cmd, payload = pkt[0], pkt[1:]
            if cmd == P.COM_QUIT:
                return
            if cmd == P.COM_PING:
                self.io.write(P.ok_packet(status=self._status()))
                continue
            if cmd == P.COM_INIT_DB:
                self.io.write(P.ok_packet(status=self._status()))
                continue
            if cmd == P.COM_FIELD_LIST:
                self.io.write(P.eof_packet(self._status()))
                continue
            if cmd == P.COM_QUERY:
                self.handle_query(payload.decode("utf-8", "replace"))
                continue
            if cmd in (P.COM_STMT_PREPARE, P.COM_STMT_EXECUTE, P.COM_STMT_CLOSE):
                self.io.write(P.err_packet(1295, "binary protocol not supported; use text PREPARE/EXECUTE"))
                continue
            self.io.write(P.err_packet(1047, f"unknown command {cmd}"))

    def handle_query(self, sql: str):
        """(ref: conn.go handleQuery -> handleStmt -> writeResultSet)."""
        from ..parser.parser import ParseError

        stmts = split_statements(sql)
        for i, stmt_sql in enumerate(stmts):
            try:
                res = self.session.execute(stmt_sql)
            except (SQLError, PlanError, CatalogError, ParseError) as exc:
                # typed statement errors carry their MySQL errno (9005
                # region-unavailable, 3024/1317 killed); the rest are 1105
                self.io.write(P.err_packet(getattr(exc, "code", 1105), str(exc)))
                return
            except Exception as exc:  # noqa: BLE001 — wire must answer
                self.io.write(P.err_packet(1105, f"internal error: {exc}"))
                return
            self.write_result(res, more=i + 1 < len(stmts))

    SERVER_MORE_RESULTS = 0x0008

    def write_result(self, res, more: bool = False):
        status = self._status() | (self.SERVER_MORE_RESULTS if more else 0)
        if not res.columns:
            self.io.write(P.ok_packet(affected=res.affected, status=status))
            return
        fts = getattr(res, "fts", None)
        self.io.write(P.lenenc_int(len(res.columns)))
        for i, name in enumerate(res.columns):
            ft = fts[i] if fts else None
            if ft is not None:
                self.io.write(P.column_def(str(name), int(ft.tp), ft.flen, max(ft.decimal, 0), column_flags(ft)))
            else:
                self.io.write(P.column_def(str(name), 0xFD))  # VAR_STRING
        self.io.write(P.eof_packet(status))
        for row in res.rows:
            self.io.write(P.text_row([datum_text(d) for d in row]))
        self.io.write(P.eof_packet(status))


def split_statements(sql: str) -> list[str]:
    """Split a COM_QUERY payload on top-level semicolons (multi-statement
    support; quote-aware, no comment handling beyond trailing whitespace)."""
    out, buf, quote = [], [], None
    i = 0
    while i < len(sql):
        ch = sql[i]
        if quote:
            buf.append(ch)
            if ch == quote and not (i + 1 < len(sql) and sql[i + 1] == quote):
                quote = None
            elif ch == quote:
                buf.append(sql[i + 1])
                i += 1
            elif ch == "\\" and i + 1 < len(sql):
                buf.append(sql[i + 1])
                i += 1
        elif ch in ("'", '"', "`"):
            quote = ch
            buf.append(ch)
        elif ch == ";":
            s = "".join(buf).strip()
            if s:
                out.append(s)
            buf = []
        else:
            buf.append(ch)
        i += 1
    s = "".join(buf).strip()
    if s:
        out.append(s)
    return out


class MySQLServer:
    """(ref: server.NewServer + Run). Listens on a TCP port; serves each
    connection on a thread. `users` maps user -> password bytes; empty map
    = accept anyone (the mock default)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 store: TPUStore | None = None, catalog: Catalog | None = None,
                 users: dict | None = None, config=None):
        self.store = store or TPUStore()
        self.catalog = catalog or Catalog()
        self.users = users or {}
        self.config = config
        self._conn_ids = iter(range(1, 1 << 31))
        self._sock = socket.create_server((host, port))
        self.host, self.port = self._sock.getsockname()
        self._threads: list = []
        self._closing = False
        # a config'd server boots the placement driver's scheduling loop
        # (ref: PD runs beside the cluster; embedded here, so the server
        # owns its lifecycle). Config-less servers (tests) tick manually.
        if config is not None and getattr(self.store, "pd", None) is not None:
            self.store.pd.start_background(config.pd_tick_interval)

    def serve_forever(self):
        while not self._closing:
            try:
                sock, _ = self._sock.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve_conn, args=(sock,), daemon=True)
            t.start()
            self._threads.append(t)

    def start_background(self) -> threading.Thread:
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t

    def _serve_conn(self, sock):
        conn = Connection(sock, self, next(self._conn_ids))
        try:
            if conn.handshake():
                conn.run()
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def close(self):
        self._closing = True
        if getattr(self.store, "pd", None) is not None:
            self.store.pd.stop()
        try:
            self._sock.close()
        except OSError:
            pass
