"""MySQL wire protocol server (ref: pkg/server).

Lazily re-exported (PEP 562): the store tier imports
`server.admission` for its AdmissionGate, and eagerly importing the wire
server here would cycle back through sql -> store."""

__all__ = ["MySQLServer", "MiniClient", "split_statements",
           "AdmissionGate", "AdmissionShed", "SessionCoalescer"]


def __getattr__(name):
    if name == "MiniClient":
        from .client import MiniClient
        return MiniClient
    if name in ("MySQLServer", "split_statements"):
        from . import server as _server
        return getattr(_server, name)
    if name in ("AdmissionGate", "AdmissionShed"):
        from . import admission as _admission
        return getattr(_admission, name)
    if name == "SessionCoalescer":
        from .coalesce import SessionCoalescer
        return SessionCoalescer
    raise AttributeError(name)
