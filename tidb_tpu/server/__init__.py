"""MySQL wire protocol server (ref: pkg/server)."""

from .client import MiniClient
from .server import MySQLServer, split_statements

__all__ = ["MySQLServer", "MiniClient", "split_statements"]
