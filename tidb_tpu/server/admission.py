"""Admission control — the server tier's load-shedding front door (ref:
TiDB's server-side connection/token limits + TiKV's ServerIsBusy
backpressure: when the store saturates, new work is REFUSED with a typed
wait hint instead of queueing until something wedges).

One `AdmissionGate` per store (every session and the dispatch layer of a
server consult the same gate):

  * `admit()` bounds concurrently EXECUTING statements (`max_inflight`).
    A statement arriving at a full gate waits in a bounded PER-SESSION
    queue (`session_queue` deep, `queue_wait_ms` long); past either bound
    it is SHED: a typed `AdmissionShed{backoff_ms}` whose message is the
    wire `server_is_busy` string, so `parse_region_error` classifies it
    and clients retry on the existing Backoffer `server_busy` budget
    (the PR-6 taxonomy ride).
  * `before_dispatch()` answers the same shed BEFORE any cop task is
    built when the dispatch tier itself saturates (`max_dispatch`
    concurrent distsql dispatches) — the store never sees work it would
    have to drop mid-flight.

The measured-cost mode (`admission.cost_classed`, ISSUE 17): a flat
in-flight count treats a 2µs point-get and a full-mesh aggregate as the
same unit of load, so saturation sheds them with equal probability. In
cost mode the gate weighs in-flight statements by their Top SQL cost
class — the per-digest EWMA of measured (cpu_ns + device_ns), never a
guess from the statement text. `max_inflight` becomes a weight budget
denominated in point-gets: a class of weight w gets `max_inflight // w`
concurrent slots of its own, so heavy digests saturate (and shed, same
typed 9003) at a quarter of the budget while point-gets keep their full
count flowing. Queue wait in either mode is attributed to the waiting
statement's resource tag.

The `server/admission-full` failpoint forces the saturated answer, so
tests and the chaos harness can exercise shedding without real load.
Defaults are fully open (0 = unlimited): embedded/test sessions pay one
lock-free-ish check per statement and nothing else.
"""

from __future__ import annotations

import threading
import time

from ..store.errors import ServerIsBusy
from ..topsql import CLASS_WEIGHTS, COLLECTOR, record_queue_wait
from ..util import failpoint, metrics


class AdmissionShed(RuntimeError):
    """Statement refused at the admission gate. `backoff_ms` is the
    suggested client wait (rides the message in the wire server_is_busy
    format, so parse_region_error -> ServerIsBusy{backoff_ms} and the
    Backoffer honors it as a floor on the server_busy budget)."""

    def __init__(self, backoff_ms: int, where: str = "admission"):
        super().__init__(str(ServerIsBusy.make(-1, backoff_ms)) + f" ({where})")
        self.backoff_ms = backoff_ms
        self.where = where


class AdmissionGate:
    """Bounded statement admission + dispatch saturation check."""

    def __init__(self, max_inflight: int = 0, session_queue: int = 4,
                 queue_wait_ms: float = 50.0, shed_backoff_ms: int = 5,
                 max_dispatch: int = 0, now_fn=time.monotonic,
                 cost_classed: bool = False, classifier=None):
        self.max_inflight = max_inflight  # 0 = unlimited
        self.session_queue = session_queue
        self.queue_wait_ms = queue_wait_ms
        self.shed_backoff_ms = shed_backoff_ms
        self.max_dispatch = max_dispatch  # 0 = unlimited
        self.cost_classed = cost_classed
        # digest -> cost class; defaults to the Top SQL collector's
        # measured EWMA classes (injectable for tests)
        self._classifier = classifier
        self._now = now_fn
        self._cv = threading.Condition()  # ONE lock: gate counters + waiters
        self._inflight = 0  # guarded_by: _cv
        self._dispatching = 0  # guarded_by: _cv
        self._queued: dict = {}  # session id -> queued count; guarded_by: _cv
        self._by_class: dict = {}  # cost class -> inflight count; guarded_by: _cv

    def configure(self, max_inflight: int | None = None,
                  session_queue: int | None = None,
                  queue_wait_ms: float | None = None,
                  shed_backoff_ms: int | None = None,
                  max_dispatch: int | None = None,
                  cost_classed: bool | None = None):
        with self._cv:
            if max_inflight is not None:
                self.max_inflight = max_inflight
            if session_queue is not None:
                self.session_queue = session_queue
            if queue_wait_ms is not None:
                self.queue_wait_ms = queue_wait_ms
            if shed_backoff_ms is not None:
                self.shed_backoff_ms = shed_backoff_ms
            if max_dispatch is not None:
                self.max_dispatch = max_dispatch
            if cost_classed is not None:
                self.cost_classed = cost_classed
            self._cv.notify_all()

    def _classify(self, digest) -> str:
        if self._classifier is not None:
            return self._classifier(digest)
        return COLLECTOR.cost_class(digest)

    def _shed(self, where: str) -> AdmissionShed:
        metrics.ADMISSION_SHED.labels(where).inc()
        return AdmissionShed(self.shed_backoff_ms, where)

    # ---------------------------------------------------- statement gate
    def admit(self, session_id, digest: str | None = None) -> "_AdmitToken":
        """Enter the statement gate (context manager). Raises
        AdmissionShed when saturated past this session's queue bound or
        queue wait — BEFORE any parse/plan/dispatch work happens.
        `digest` is the statement's literal-masked SQL digest (the plan
        cache probe's): in cost-classed mode it selects the weight lane;
        the flat gate ignores it."""
        if failpoint.eval("server/admission-full"):
            raise self._shed("gate")
        if self.max_inflight <= 0:
            return _AdmitToken(self, counted=False)
        if self.cost_classed:
            return self._admit_classed(session_id, digest)
        with self._cv:
            if self._inflight < self.max_inflight:
                self._inflight += 1
                metrics.ADMISSION_ADMITTED.inc()
                metrics.ADMISSION_INFLIGHT.set(self._inflight)
                return _AdmitToken(self, counted=True)
            self._enqueue_locked(session_id)
            t_q = self._now()
            try:
                deadline = t_q + self.queue_wait_ms / 1000.0
                while self._inflight >= self.max_inflight > 0:
                    left = deadline - self._now()
                    if left <= 0:
                        raise self._shed("queue_timeout")
                    self._cv.wait(left)
            finally:
                self._dequeue_locked(session_id)
                self._note_queue_wait(t_q)
            self._inflight += 1
            metrics.ADMISSION_ADMITTED.inc()
            metrics.ADMISSION_INFLIGHT.set(self._inflight)
            return _AdmitToken(self, counted=True)

    def _admit_classed(self, session_id, digest: str | None) -> "_AdmitToken":
        """The measured-cost gate: the statement's class (Top SQL EWMA)
        picks its weight lane — a class of weight w owns
        `max_inflight // w` slots, so heavy digests saturate first and
        shed the same typed 9003 while point-gets keep their full count."""
        cls = self._classify(digest)
        cap = max(1, self.max_inflight // CLASS_WEIGHTS.get(cls, 1))
        with self._cv:
            if self._by_class.get(cls, 0) < cap:
                return self._admit_classed_locked(cls)
            self._enqueue_locked(session_id)
            t_q = self._now()
            try:
                deadline = t_q + self.queue_wait_ms / 1000.0
                while self._by_class.get(cls, 0) >= cap:
                    left = deadline - self._now()
                    if left <= 0:
                        metrics.TOPSQL_CLASS_DECISIONS.labels(cls, "shed").inc()
                        raise self._shed("queue_timeout")
                    self._cv.wait(left)
            finally:
                self._dequeue_locked(session_id)
                self._note_queue_wait(t_q)
            return self._admit_classed_locked(cls)

    def _admit_classed_locked(self, cls: str) -> "_AdmitToken":  # requires: _cv
        self._by_class[cls] = self._by_class.get(cls, 0) + 1
        self._inflight += 1
        metrics.ADMISSION_ADMITTED.inc()
        metrics.ADMISSION_INFLIGHT.set(self._inflight)
        metrics.TOPSQL_CLASS_DECISIONS.labels(cls, "admit").inc()
        return _AdmitToken(self, counted=True, cls=cls)

    def _enqueue_locked(self, session_id) -> None:  # requires: _cv
        q = self._queued.get(session_id, 0)
        if q >= self.session_queue:
            raise self._shed("queue_full")
        self._queued[session_id] = q + 1
        metrics.ADMISSION_QUEUE_WAITS.inc()

    def _dequeue_locked(self, session_id) -> None:  # requires: _cv
        n = self._queued.get(session_id, 1) - 1
        if n <= 0:
            self._queued.pop(session_id, None)
        else:
            self._queued[session_id] = n

    def _note_queue_wait(self, t_q: float) -> None:
        # queue wait onto the waiting statement's resource tag (Top SQL:
        # a digest that spends its life waiting at the gate should show
        # it). The tag lock is a leaf — safe under _cv.
        record_queue_wait((self._now() - t_q) * 1000.0)

    def _release(self, cls: str | None = None):
        with self._cv:
            self._inflight -= 1
            if cls is not None:
                n = self._by_class.get(cls, 1) - 1
                if n <= 0:
                    self._by_class.pop(cls, None)
                else:
                    self._by_class[cls] = n
            metrics.ADMISSION_INFLIGHT.set(self._inflight)
            # classed waiters wait on per-class capacity: wake them all,
            # each re-checks its own lane
            self._cv.notify_all()

    # ----------------------------------------------------- dispatch gate
    def before_dispatch(self) -> "_DispatchToken":
        """Saturation check at the distsql dispatch seam — answers the
        typed shed BEFORE building cop tasks (the store never starts work
        it would drop). Unlimited by default."""
        if failpoint.eval("server/admission-full"):
            raise self._shed("dispatch")
        if self.max_dispatch <= 0:
            return _DispatchToken(self, counted=False)
        with self._cv:
            if self._dispatching >= self.max_dispatch:
                raise self._shed("dispatch")
            self._dispatching += 1
        return _DispatchToken(self, counted=True)

    def _release_dispatch(self):
        with self._cv:
            self._dispatching -= 1

    def view(self) -> dict:
        with self._cv:
            return {
                "max_inflight": self.max_inflight,
                "inflight": self._inflight,
                "dispatching": self._dispatching,
                "queued": sum(self._queued.values()),
                "cost_classed": self.cost_classed,
                "by_class": dict(self._by_class),
                "weighted_inflight": sum(
                    n * CLASS_WEIGHTS.get(c, 1) for c, n in self._by_class.items()
                ),
            }


class _AdmitToken:
    def __init__(self, gate: AdmissionGate, counted: bool, cls: str | None = None):
        self._gate, self._counted, self._cls = gate, counted, cls

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if self._counted:
            self._gate._release(self._cls)
        return False


class _DispatchToken:
    def __init__(self, gate: AdmissionGate, counted: bool):
        self._gate, self._counted = gate, counted

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if self._counted:
            self._gate._release_dispatch()
        return False
