"""Admission control — the server tier's load-shedding front door (ref:
TiDB's server-side connection/token limits + TiKV's ServerIsBusy
backpressure: when the store saturates, new work is REFUSED with a typed
wait hint instead of queueing until something wedges).

One `AdmissionGate` per store (every session and the dispatch layer of a
server consult the same gate):

  * `admit()` bounds concurrently EXECUTING statements (`max_inflight`).
    A statement arriving at a full gate waits in a bounded PER-SESSION
    queue (`session_queue` deep, `queue_wait_ms` long); past either bound
    it is SHED: a typed `AdmissionShed{backoff_ms}` whose message is the
    wire `server_is_busy` string, so `parse_region_error` classifies it
    and clients retry on the existing Backoffer `server_busy` budget
    (the PR-6 taxonomy ride).
  * `before_dispatch()` answers the same shed BEFORE any cop task is
    built when the dispatch tier itself saturates (`max_dispatch`
    concurrent distsql dispatches) — the store never sees work it would
    have to drop mid-flight.

The `server/admission-full` failpoint forces the saturated answer, so
tests and the chaos harness can exercise shedding without real load.
Defaults are fully open (0 = unlimited): embedded/test sessions pay one
lock-free-ish check per statement and nothing else.
"""

from __future__ import annotations

import threading
import time

from ..store.errors import ServerIsBusy
from ..util import failpoint, metrics


class AdmissionShed(RuntimeError):
    """Statement refused at the admission gate. `backoff_ms` is the
    suggested client wait (rides the message in the wire server_is_busy
    format, so parse_region_error -> ServerIsBusy{backoff_ms} and the
    Backoffer honors it as a floor on the server_busy budget)."""

    def __init__(self, backoff_ms: int, where: str = "admission"):
        super().__init__(str(ServerIsBusy.make(-1, backoff_ms)) + f" ({where})")
        self.backoff_ms = backoff_ms
        self.where = where


class AdmissionGate:
    """Bounded statement admission + dispatch saturation check."""

    def __init__(self, max_inflight: int = 0, session_queue: int = 4,
                 queue_wait_ms: float = 50.0, shed_backoff_ms: int = 5,
                 max_dispatch: int = 0, now_fn=time.monotonic):
        self.max_inflight = max_inflight  # 0 = unlimited
        self.session_queue = session_queue
        self.queue_wait_ms = queue_wait_ms
        self.shed_backoff_ms = shed_backoff_ms
        self.max_dispatch = max_dispatch  # 0 = unlimited
        self._now = now_fn
        self._cv = threading.Condition()  # ONE lock: gate counters + waiters
        self._inflight = 0  # guarded_by: _cv
        self._dispatching = 0  # guarded_by: _cv
        self._queued: dict = {}  # session id -> queued count; guarded_by: _cv

    def configure(self, max_inflight: int | None = None,
                  session_queue: int | None = None,
                  queue_wait_ms: float | None = None,
                  shed_backoff_ms: int | None = None,
                  max_dispatch: int | None = None):
        with self._cv:
            if max_inflight is not None:
                self.max_inflight = max_inflight
            if session_queue is not None:
                self.session_queue = session_queue
            if queue_wait_ms is not None:
                self.queue_wait_ms = queue_wait_ms
            if shed_backoff_ms is not None:
                self.shed_backoff_ms = shed_backoff_ms
            if max_dispatch is not None:
                self.max_dispatch = max_dispatch
            self._cv.notify_all()

    def _shed(self, where: str) -> AdmissionShed:
        metrics.ADMISSION_SHED.labels(where).inc()
        return AdmissionShed(self.shed_backoff_ms, where)

    # ---------------------------------------------------- statement gate
    def admit(self, session_id) -> "_AdmitToken":
        """Enter the statement gate (context manager). Raises
        AdmissionShed when saturated past this session's queue bound or
        queue wait — BEFORE any parse/plan/dispatch work happens."""
        if failpoint.eval("server/admission-full"):
            raise self._shed("gate")
        if self.max_inflight <= 0:
            return _AdmitToken(self, counted=False)
        with self._cv:
            if self._inflight < self.max_inflight:
                self._inflight += 1
                metrics.ADMISSION_ADMITTED.inc()
                metrics.ADMISSION_INFLIGHT.set(self._inflight)
                return _AdmitToken(self, counted=True)
            q = self._queued.get(session_id, 0)
            if q >= self.session_queue:
                raise self._shed("queue_full")
            self._queued[session_id] = q + 1
            metrics.ADMISSION_QUEUE_WAITS.inc()
            deadline = self._now() + self.queue_wait_ms / 1000.0
            try:
                while self._inflight >= self.max_inflight > 0:
                    left = deadline - self._now()
                    if left <= 0:
                        raise self._shed("queue_timeout")
                    self._cv.wait(left)
            finally:
                n = self._queued.get(session_id, 1) - 1
                if n <= 0:
                    self._queued.pop(session_id, None)
                else:
                    self._queued[session_id] = n
            self._inflight += 1
            metrics.ADMISSION_ADMITTED.inc()
            metrics.ADMISSION_INFLIGHT.set(self._inflight)
            return _AdmitToken(self, counted=True)

    def _release(self):
        with self._cv:
            self._inflight -= 1
            metrics.ADMISSION_INFLIGHT.set(self._inflight)
            self._cv.notify()

    # ----------------------------------------------------- dispatch gate
    def before_dispatch(self) -> "_DispatchToken":
        """Saturation check at the distsql dispatch seam — answers the
        typed shed BEFORE building cop tasks (the store never starts work
        it would drop). Unlimited by default."""
        if failpoint.eval("server/admission-full"):
            raise self._shed("dispatch")
        if self.max_dispatch <= 0:
            return _DispatchToken(self, counted=False)
        with self._cv:
            if self._dispatching >= self.max_dispatch:
                raise self._shed("dispatch")
            self._dispatching += 1
        return _DispatchToken(self, counted=True)

    def _release_dispatch(self):
        with self._cv:
            self._dispatching -= 1

    def view(self) -> dict:
        with self._cv:
            return {
                "max_inflight": self.max_inflight,
                "inflight": self._inflight,
                "dispatching": self._dispatching,
                "queued": sum(self._queued.values()),
            }


class _AdmitToken:
    def __init__(self, gate: AdmissionGate, counted: bool):
        self._gate, self._counted = gate, counted

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if self._counted:
            self._gate._release()
        return False


class _DispatchToken:
    def __init__(self, gate: AdmissionGate, counted: bool):
        self._gate, self._counted = gate, counted

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if self._counted:
            self._gate._release_dispatch()
        return False
