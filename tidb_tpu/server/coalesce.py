"""Cross-session fused execution (ISSUE 19) — the per-store session
coalescer.

BENCH_CONCURRENT showed the engine stops being the bottleneck at 256
sessions: p99 is scheduling-bound because every session still pays its
own device launch and its own quorum proposal. The paper's north star is
sessions AS vmap lanes — this module makes that literal:

  reads   concurrent plan-cache-hit point-gets park in a short
          micro-batch window (bounded by `tidb_tpu_coalesce_wait_us`
          and a max lane count) and ship as ONE vmapped device launch
          through the existing `batch_coprocessor` stacking path; every
          lane's rows slice back out, with honest per-lane device-time
          attribution through the Top SQL `split_by_rows` seam
  writes  concurrent autocommit single-row writes fold into GROUP
          COMMIT — `TxnEngine.commit_group` 2PCs every lane at its own
          commit ts in one critical section, and the store folds the
          applied lanes into ONE quorum proposal per (region, window)
          (`ReplicaManager.propose_group`)

Protocol — leader/follower, no daemon thread: the FIRST session to open
a window becomes its leader and waits out the window (condition wait
with a deadline, never a sleep); followers park on their lane's event.
The leader CLAIMS the window's lanes atomically, flushes them, and
answers every lane. A follower whose leader stalls past its patience
(the `coalesce/window-stall` chaos shape) withdraws its lane — if still
unclaimed — and falls back to the single path; a claimed lane always
waits for its answer. Any lane the flush could not answer (a region
fault, a lost flush, a refused quorum) FALLS OUT to the caller's single
path exactly like a stale-epoch lane falls out of batch cop — the
coalescer never invents an error path the single path doesn't have.

Lock order: the coalescer mutex is a LEAF — no store/txn/dispatch lock
is ever taken while holding it (lanes are snapshotted under the mutex,
flushed outside it).
"""

from __future__ import annotations

import threading
import time

from ..util import failpoint, metrics

# fall-out reasons (typed, each a `tidb_tpu_coalesce_fallbacks_total` label):
#   window_stall  follower patience expired with the window unclaimed
#   flush_lost    the flush died (or `coalesce/flush-lost` fired) before
#                 this lane was answered
#   fault_lane    a region/store fault answered one of the lane's cop
#                 requests — the single path owns retry/backoff
#   txn_conflict  group-commit prewrite/conflict check refused the lane —
#                 the single path re-runs the same checks canonically
FALLBACK_REASONS = ("window_stall", "flush_lost", "fault_lane", "txn_conflict")


class _Window:
    __slots__ = ("kind", "lanes", "closed", "claimed")

    def __init__(self, kind: str):
        self.kind = kind
        self.lanes: list = []  # guarded_by: SessionCoalescer._mu
        self.closed = False  # guarded_by: SessionCoalescer._mu — full, no new lanes
        self.claimed = False  # guarded_by: SessionCoalescer._mu — leader took the lanes


class _Lane:
    __slots__ = ("kind", "tag", "done", "meta", "handles", "mutations",
                 "start_ts", "result", "error", "fallback", "reason",
                 "enq", "window")

    def __init__(self, kind: str, tag):
        self.kind = kind
        self.tag = tag  # Top SQL ResourceTag for cross-thread attribution
        self.done = threading.Event()
        self.meta = None
        self.handles: list = []
        self.mutations: dict = {}
        self.start_ts = 0
        self.result = None
        self.error: BaseException | None = None
        self.fallback = False
        self.reason = ""
        self.enq = 0.0
        self.window: _Window | None = None


class SessionCoalescer:
    """One per store (TPUStore.__init__), shared by every session."""

    def __init__(self, store):
        self.store = store
        self._mu = threading.RLock()  # RLock: Condition._is_owned works
        # under the lockwatch proxy (a plain Lock has no ownership probe)
        self._cv = threading.Condition(self._mu)
        self._open: dict[str, _Window | None] = {"read": None, "write": None}  # guarded_by: _mu

    # ------------------------------------------------------------- API
    def point_get(self, meta, handles, tag=None,
                  wait_us: int = 300, max_lanes: int = 64):
        """Park a point-get lane (table meta + integer handles) in the
        read window. Returns {handle: row datums} covering every handle
        that exists at the window's shared snapshot, or None — the lane
        fell out and the caller must run its single path."""
        if max_lanes <= 1 or wait_us <= 0:
            return None
        lane = _Lane("read", tag)
        lane.meta = meta
        lane.handles = list(handles)
        if not self._park(lane, wait_us, max_lanes):
            return None
        if lane.error is not None:
            raise lane.error
        return lane.result

    def group_commit(self, mutations: dict, start_ts: int, tag=None,
                     wait_us: int = 300, max_lanes: int = 64):
        """Park an autocommit write lane (key -> value|None at start_ts)
        in the write window. Returns the lane's commit_ts on success, or
        None — the lane fell out (stall / lost flush / conflict) and the
        caller must commit through the single path. A typed refusal the
        single path would also raise (quorum lost) raises here."""
        if max_lanes <= 1 or wait_us <= 0 or not mutations:
            return None
        lane = _Lane("write", tag)
        lane.mutations = dict(mutations)
        lane.start_ts = start_ts
        if not self._park(lane, wait_us, max_lanes):
            return None
        if lane.error is not None:
            raise lane.error
        return lane.result

    # -------------------------------------------------------- protocol
    @staticmethod
    def _patience(wait_s: float) -> float:
        # a follower outwaits the leader's window plus scheduling slack;
        # anything longer means the leader is wedged (window-stall chaos)
        return wait_s * 4 + 0.05

    def _park(self, lane: _Lane, wait_us: int, max_lanes: int) -> bool:
        """Enqueue the lane; lead or follow; True = lane was answered
        (result/error set), False = lane fell out to the single path."""
        wait_s = wait_us / 1e6
        lane.enq = time.perf_counter()
        with self._mu:
            win = self._open.get(lane.kind)
            if win is None or win.closed or win.claimed:
                win = _Window(lane.kind)
                self._open[lane.kind] = win
                leader = True
            else:
                leader = False
            win.lanes.append(lane)
            lane.window = win
            if len(win.lanes) >= max_lanes:
                win.closed = True
                self._cv.notify_all()
        if leader:
            self._lead(win, wait_s)
        elif not lane.done.wait(self._patience(wait_s)):
            with self._mu:
                if not win.claimed and not lane.done.is_set():
                    win.lanes.remove(lane)
                    self._fall_out(lane, "window_stall")
            # claimed in the race window: the leader's flush owns the
            # answer now and its finally-clause guarantees the event
            lane.done.wait()
        return not lane.fallback

    def _lead(self, win: _Window, wait_s: float) -> None:
        deadline = time.monotonic() + wait_s
        with self._mu:
            while not win.closed:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._cv.wait(left)
            stall = failpoint.eval("coalesce/window-stall")
            if stall:
                # chaos: a descheduled leader holds the window open past
                # its deadline — followers withdraw and fall back
                hold = time.monotonic() + (stall if isinstance(stall, float) else 0.25)
                while True:
                    left = hold - time.monotonic()
                    if left <= 0:
                        break
                    self._cv.wait(left)
            win.closed = True
            win.claimed = True
            if self._open.get(win.kind) is win:
                self._open[win.kind] = None
            lanes = list(win.lanes)
        try:
            if win.kind == "read":
                self._flush_reads(lanes)
            else:
                self._flush_writes(lanes)
        finally:
            for lane in lanes:  # a flush that died mid-way answers
                if not lane.done.is_set():  # every claimed lane anyway
                    self._fall_out(lane, "flush_lost")

    def _fall_out(self, lane: _Lane, reason: str) -> None:
        lane.fallback = True
        lane.reason = reason
        metrics.COALESCE_FALLBACKS.labels(reason).inc()
        lane.done.set()

    # ---------------------------------------------------------- flush
    def _flush_reads(self, lanes: list) -> None:
        """ONE batch_coprocessor call for the whole window: every lane's
        point ranges become per-region cop requests at ONE shared
        snapshot ts (same-table lanes share a DAG, so they land in the
        same vmapped launch group). Faulted lanes fall out; the rest get
        {handle: row} plus their proportional share of the launch."""
        from .. import topsql
        from ..codec import tablecodec
        from ..distsql.dispatch import _build_tasks
        from ..exec.dag import ColumnInfo, DAGRequest, TableScan
        from ..sql.session import HANDLE_FT
        from ..store.store import CopRequest, KeyRange

        store = self.store
        if failpoint.eval("coalesce/flush-lost"):
            for lane in lanes:
                self._fall_out(lane, "flush_lost")
            return
        t_flush = time.perf_counter()
        # ONE snapshot for the window: batch_coprocessor groups lanes by
        # (fingerprint, start_ts, ...) — per-session timestamps would
        # never stack. Serializing the window's autocommit reads at one
        # TSO tick is a legal serial order for them.
        shared_ts = store.next_ts()
        store.register_snapshot(shared_ts)
        try:
            reqs: list = []
            spans: list = []  # (lane, first req index, past-last index)
            dags: dict = {}
            for lane in lanes:
                meta = lane.meta
                dag = dags.get(meta.table_id)
                if dag is None:
                    cols = [ColumnInfo(-1, HANDLE_FT)] + list(meta.scan_columns())
                    dags[meta.table_id] = dag = DAGRequest(
                        (TableScan(meta.table_id, tuple(cols)),),
                        output_offsets=tuple(range(len(cols))),
                    )
                ranges = [
                    KeyRange(tablecodec.encode_row_key(meta.table_id, h),
                             tablecodec.encode_row_key(meta.table_id, h) + b"\x00")
                    for h in lane.handles
                ]
                lo = len(reqs)
                for t in _build_tasks(store, ranges):
                    reqs.append(CopRequest(
                        dag=dag, ranges=t.ranges, start_ts=shared_ts,
                        region_id=t.region_id, region_epoch=t.epoch,
                        peer_store=store.cluster.leader_of(t.region_id),
                    ))
                spans.append((lane, lo, len(reqs)))
            t0 = time.perf_counter_ns()
            with topsql.adopt(None):
                # untagged launch: the store's internal record_device
                # no-ops, so device time lands ONLY through the per-lane
                # shares below — each lane attributed once, exactly
                resps = store.batch_coprocessor(reqs)
            elapsed = time.perf_counter_ns() - t0
        finally:
            store.unregister_snapshot(shared_ts)
        launch_ids = {r.batched for r in resps if r.batched}
        batched_n = sum(1 for r in resps if r.batched)
        metrics.COALESCE_BATCHES.inc()
        metrics.COALESCE_LANES.labels("read").inc(len(lanes))
        if batched_n > len(launch_ids):
            metrics.COALESCE_LAUNCHES_SAVED.inc(batched_n - len(launch_ids))
        rows_per_lane = []
        for lane, lo, hi in spans:
            sub = resps[lo:hi]
            if any(r.region_error or r.other_error for r in sub):
                self._fall_out(lane, "fault_lane")
                rows_per_lane.append(0)
                continue
            by_handle: dict = {}
            for r in sub:
                if r.chunk is not None:
                    for row in r.chunk.rows():
                        by_handle[int(row[0].val)] = list(row[1:])
            lane.result = by_handle
            rows_per_lane.append(len(by_handle))
        shares = topsql.split_by_rows(elapsed, rows_per_lane)
        for (lane, _lo, _hi), share in zip(spans, shares):
            if lane.fallback:
                continue
            park_s = max(t_flush - lane.enq, 0.0)
            metrics.COALESCE_WINDOW_WAIT.observe(park_s)
            with topsql.adopt(lane.tag):
                topsql.record_device(share)
                topsql.record_queue_wait(park_s * 1000.0)
            lane.done.set()

    def _flush_writes(self, lanes: list) -> None:
        """ONE group commit for the window: every lane 2PCs at its own
        commit ts inside one engine critical section; the store folds
        the applied lanes into one proposal per region. Conflict-refused
        lanes fall out to the single path; a quorum refusal raises the
        same typed error the single path would."""
        from .. import topsql
        from ..store.txn import TxnError

        store = self.store
        if failpoint.eval("coalesce/flush-lost"):
            for lane in lanes:
                self._fall_out(lane, "flush_lost")
            return
        t_flush = time.perf_counter()
        results = store.txn.commit_group(
            [(lane.mutations, lane.start_ts) for lane in lanes],
            store.next_ts,
        )
        metrics.COALESCE_BATCHES.inc()
        metrics.COALESCE_LANES.labels("write").inc(len(lanes))
        for lane, res in zip(lanes, results):
            park_s = max(t_flush - lane.enq, 0.0)
            metrics.COALESCE_WINDOW_WAIT.observe(park_s)
            with topsql.adopt(lane.tag):
                topsql.record_queue_wait(park_s * 1000.0)
            if isinstance(res, TxnError):
                self._fall_out(lane, "txn_conflict")
            elif isinstance(res, BaseException):
                lane.error = res  # typed (quorum lost): raise in the lane
                lane.done.set()
            elif res is None:
                self._fall_out(lane, "txn_conflict")  # empty lane: single path
            else:
                lane.result = res
                metrics.COALESCE_GROUP_COMMITS.inc()
                lane.done.set()
