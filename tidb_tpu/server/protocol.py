"""MySQL client/server wire protocol codec (ref: pkg/server/conn.go packet
IO + handshake, pkg/server/column.go column definitions, and the protocol
constants in pkg/parser/mysql/const.go).

Covers what a standard client needs to connect and run queries:
  - packet framing: 3-byte little-endian length + 1-byte sequence id
  - HandshakeV10 greeting, HandshakeResponse41 parsing
  - mysql_native_password auth (SHA1 scramble check; empty password OK)
  - OK / ERR / EOF packets (CLIENT_PROTOCOL_41 shapes)
  - column definition 41 + text-protocol result rows (length-encoded)
"""

from __future__ import annotations

import hashlib
import os
import struct

# capability flags (ref: mysql/const.go Client*)
CLIENT_LONG_PASSWORD = 1 << 0
CLIENT_FOUND_ROWS = 1 << 1
CLIENT_LONG_FLAG = 1 << 2
CLIENT_CONNECT_WITH_DB = 1 << 3
CLIENT_PROTOCOL_41 = 1 << 9
CLIENT_TRANSACTIONS = 1 << 13
CLIENT_SECURE_CONNECTION = 1 << 15
CLIENT_MULTI_STATEMENTS = 1 << 16
CLIENT_MULTI_RESULTS = 1 << 17
CLIENT_PLUGIN_AUTH = 1 << 19
CLIENT_DEPRECATE_EOF = 1 << 24

SERVER_CAPS = (
    CLIENT_LONG_PASSWORD | CLIENT_FOUND_ROWS | CLIENT_LONG_FLAG
    | CLIENT_CONNECT_WITH_DB | CLIENT_PROTOCOL_41 | CLIENT_TRANSACTIONS
    | CLIENT_SECURE_CONNECTION | CLIENT_MULTI_STATEMENTS
    | CLIENT_MULTI_RESULTS | CLIENT_PLUGIN_AUTH
)

SERVER_STATUS_AUTOCOMMIT = 0x0002
SERVER_STATUS_IN_TRANS = 0x0001

# commands (ref: mysql/const.go Com*)
COM_QUIT = 0x01
COM_INIT_DB = 0x02
COM_QUERY = 0x03
COM_FIELD_LIST = 0x04
COM_PING = 0x0E
COM_STMT_PREPARE = 0x16
COM_STMT_EXECUTE = 0x17
COM_STMT_CLOSE = 0x19

CHARSET_UTF8MB4 = 255  # utf8mb4_0900_ai_ci


class PacketIO:
    """Framed packet reader/writer over a socket (ref: conn.go readPacket /
    writePacket; sequence ids reset per command)."""

    def __init__(self, sock):
        self.sock = sock
        self.seq = 0

    def reset(self):
        self.seq = 0

    def read(self) -> bytes:
        header = self._read_exact(4)
        length = header[0] | header[1] << 8 | header[2] << 16
        self.seq = (header[3] + 1) & 0xFF
        return self._read_exact(length)

    def write(self, payload: bytes):
        # 16MB+ splitting is not needed for this server's result sizes, but
        # keep the loop for protocol correctness
        while True:
            chunk, payload = payload[: 0xFFFFFF], payload[0xFFFFFF:]
            self.sock.sendall(struct.pack("<I", len(chunk))[:3] + bytes([self.seq]) + chunk)
            self.seq = (self.seq + 1) & 0xFF
            if len(chunk) < 0xFFFFFF:
                break

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            part = self.sock.recv(n - len(buf))
            if not part:
                raise ConnectionError("peer closed")
            buf += part
        return buf


# ---------------------------------------------------------------- lenenc

def lenenc_int(v: int) -> bytes:
    if v < 251:
        return bytes([v])
    if v < 1 << 16:
        return b"\xfc" + struct.pack("<H", v)
    if v < 1 << 24:
        return b"\xfd" + struct.pack("<I", v)[:3]
    return b"\xfe" + struct.pack("<Q", v)


def lenenc_str(s: bytes) -> bytes:
    return lenenc_int(len(s)) + s


def read_lenenc_int(buf: bytes, pos: int) -> tuple[int, int]:
    first = buf[pos]
    if first < 251:
        return first, pos + 1
    if first == 0xFC:
        return struct.unpack_from("<H", buf, pos + 1)[0], pos + 3
    if first == 0xFD:
        return buf[pos + 1] | buf[pos + 2] << 8 | buf[pos + 3] << 16, pos + 4
    return struct.unpack_from("<Q", buf, pos + 1)[0], pos + 9


def read_lenenc_str(buf: bytes, pos: int) -> tuple[bytes, int]:
    n, pos = read_lenenc_int(buf, pos)
    return buf[pos : pos + n], pos + n


# ---------------------------------------------------------------- packets

def handshake_v10(conn_id: int, salt: bytes, version: str = "8.0.11-tidb-tpu") -> bytes:
    """Initial greeting (ref: conn.go writeInitialHandshake)."""
    out = bytes([10]) + version.encode() + b"\x00"
    out += struct.pack("<I", conn_id)
    out += salt[:8] + b"\x00"
    out += struct.pack("<H", SERVER_CAPS & 0xFFFF)
    out += bytes([CHARSET_UTF8MB4])
    out += struct.pack("<H", SERVER_STATUS_AUTOCOMMIT)
    out += struct.pack("<H", (SERVER_CAPS >> 16) & 0xFFFF)
    out += bytes([21])  # auth plugin data length
    out += b"\x00" * 10
    out += salt[8:20] + b"\x00"
    out += b"mysql_native_password\x00"
    return out


def parse_handshake_response(payload: bytes) -> dict:
    """HandshakeResponse41 (ref: conn.go readOptionalSSLRequestAndHandshakeResponse)."""
    caps, _max_packet, _charset = struct.unpack_from("<IIB", payload, 0)
    pos = 4 + 4 + 1 + 23
    end = payload.index(b"\x00", pos)
    user = payload[pos:end].decode()
    pos = end + 1
    if caps & CLIENT_PLUGIN_AUTH or caps & CLIENT_SECURE_CONNECTION:
        alen = payload[pos]
        auth = payload[pos + 1 : pos + 1 + alen]
        pos += 1 + alen
    else:
        end = payload.index(b"\x00", pos)
        auth = payload[pos:end]
        pos = end + 1
    db = ""
    if caps & CLIENT_CONNECT_WITH_DB and pos < len(payload):
        end = payload.index(b"\x00", pos)
        db = payload[pos:end].decode()
        pos = end + 1
    return {"caps": caps, "user": user, "auth": auth, "db": db}


def native_password_scramble(password: bytes, salt: bytes) -> bytes:
    """mysql_native_password: SHA1(pw) XOR SHA1(salt + SHA1(SHA1(pw)))."""
    if not password:
        return b""
    h1 = hashlib.sha1(password).digest()
    h2 = hashlib.sha1(h1).digest()
    mix = hashlib.sha1(salt + h2).digest()
    return bytes(a ^ b for a, b in zip(h1, mix))


def check_auth(stored_password: bytes, salt: bytes, client_auth: bytes) -> bool:
    if not stored_password:
        return client_auth in (b"", None) or client_auth == native_password_scramble(b"", salt)
    return client_auth == native_password_scramble(stored_password, salt)


def ok_packet(affected: int = 0, last_insert_id: int = 0, status: int = SERVER_STATUS_AUTOCOMMIT,
              warnings: int = 0) -> bytes:
    return (b"\x00" + lenenc_int(affected) + lenenc_int(last_insert_id)
            + struct.pack("<HH", status, warnings))


def err_packet(code: int, message: str, state: str = "HY000") -> bytes:
    return (b"\xff" + struct.pack("<H", code) + b"#" + state.encode()[:5].ljust(5, b"0")
            + message.encode())


def eof_packet(status: int = SERVER_STATUS_AUTOCOMMIT, warnings: int = 0) -> bytes:
    return b"\xfe" + struct.pack("<HH", warnings, status)


def column_def(name: str, tp: int, flen: int = 0, decimals: int = 0, flags: int = 0,
               charset: int = CHARSET_UTF8MB4) -> bytes:
    """ColumnDefinition41 (ref: pkg/server/column.go Dump)."""
    out = lenenc_str(b"def")  # catalog
    out += lenenc_str(b"")  # schema
    out += lenenc_str(b"")  # table
    out += lenenc_str(b"")  # org_table
    out += lenenc_str(name.encode())
    out += lenenc_str(name.encode())  # org_name
    out += bytes([0x0C])  # fixed-length fields size
    out += struct.pack("<H", charset)
    out += struct.pack("<I", max(flen, 0) or 255)
    out += bytes([tp & 0xFF])
    out += struct.pack("<H", flags)
    out += bytes([decimals])
    out += b"\x00\x00"
    return out


def text_row(values: list) -> bytes:
    """values: list of str|None (ref: pkg/server/util.go dumpTextRow)."""
    out = b""
    for v in values:
        if v is None:
            out += b"\xfb"
        else:
            out += lenenc_str(str(v).encode())
    return out


def new_salt() -> bytes:
    # 20 bytes, no zero bytes (clients c-string them)
    raw = bytearray(os.urandom(20))
    for i, b in enumerate(raw):
        if b == 0 or b == ord("$"):
            raw[i] = b + 1
    return bytes(raw)
