"""HTTP status API (ref: pkg/server/http_status.go + the handler set in
pkg/server/handler/tikvhandler — docs/tidb_http_api.md):

  GET /status                          server status (version, git hash)
  GET /schema                          all databases
  GET /schema/{db}                     tables of a database
  GET /schema/{db}/{table}             one table's TableInfo
  GET /ddl/history                     DDL job log (newest first)
  GET /settings                        config + global sysvars
  GET /metrics                         Prometheus text exposition v0.0.4
                                       (text/plain — scrapers point here)
  GET /metrics/json                    the same samples as a JSON object
  GET /mvcc/key/{db}/{table}/{handle}  MVCC versions of one row
  GET /regions/meta                    region/cluster layout
  GET /pd/api/v1/regions               PD view: regions + placement + size
  GET /pd/api/v1/stores                PD view: per-store region/hot counts
  GET /pd/api/v1/hotspot               PD view: hot read/write peers
  GET /pd/api/v1/operators             PD view: pending + recent operators
  GET /cdc/api/v1/changefeeds          changefeed list (state, frontier)
  GET /cdc/api/v1/changefeeds/{name}   one changefeed's detail
  GET /columnar/api/v1/tables          columnar replica tables (delta rows,
                                       stable chunks, applied resolved-ts)
  GET /columnar/api/v1/tables/{name}   one columnar table's detail
  GET /topsql/api/v1/windows           Top SQL reporter windows (top-K
                                       digests + "(others)" fold per window)
  GET /topsql/api/v1/digests/{digest}  one digest across windows + its
                                       measured cost class / EWMA

The /pd/api/v1 prefix mirrors the reference PD's HTTP API (pd
server/api/router.go) and /cdc/api/v1 mirrors TiCDC's open API — both
served from this status port since PD and CDC are embedded in the store
process.

Runs on its own port next to the MySQL protocol listener, like the
reference's status server. JSON bodies except /metrics; 404 with a
message otherwise."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


def _table_info(meta) -> dict:
    return {
        "id": meta.table_id,
        "name": {"O": meta.name.rsplit(".", 1)[-1], "L": meta.name.rsplit(".", 1)[-1]},
        "cols": [
            {
                "id": c.col_id,
                "name": {"O": c.name, "L": c.name},
                "type": c.decl or c.ft.eval_type(),
                "nullable": not c.ft.not_null(),
                "generated": c.generated is not None,
            }
            for c in meta.columns
        ],
        "index_info": [
            {"id": i.index_id, "name": i.name, "cols": i.col_names,
             "unique": i.unique, "state": i.state}
            for i in meta.indices
        ],
        "fk_info": [
            {"name": fk.name, "cols": fk.cols, "ref_table": fk.ref_table,
             "ref_cols": fk.ref_cols, "on_delete": fk.on_delete}
            for fk in getattr(meta, "foreign_keys", [])
        ],
        "pk_is_handle": meta.handle_col is not None,
        "row_count": meta.row_count,
        "partition": None if meta.partition is None else {
            "type": meta.partition.method,
            "expr": meta.partition.col,
            "definitions": [{"id": p.pid, "name": p.name} for p in meta.partition.parts],
        },
    }


class StatusServer:
    """The status endpoint server; `start_background()` + `.port`."""

    def __init__(self, session, host: str = "127.0.0.1", port: int = 0):
        self.session = session
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):  # noqa: N802 (stdlib contract)
                ctype = "application/json"
                try:
                    routed = outer._route(self.path)
                    if len(routed) == 3:  # raw body + explicit content type
                        code, data, ctype = routed
                        data = data if isinstance(data, bytes) else data.encode()
                    else:
                        code, body = routed
                        data = json.dumps(body, indent=1, default=str).encode()
                except Exception as exc:  # noqa: BLE001 — surface, don't kill the thread
                    code, data = 500, json.dumps({"error": str(exc)}).encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address

    def start_background(self):
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        t.start()
        return self

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()

    # ---------------------------------------------------------- routing
    def _route(self, path: str):
        s = self.session
        parts = [p for p in path.split("?")[0].split("/") if p]
        if parts == ["status"]:
            return 200, {
                "connections": 0,
                "version": "8.0.11-tidb_tpu",
                "git_hash": "tpu-native",
                "status_port": self.port,
            }
        if parts == ["schema"]:
            return 200, sorted({"information_schema"} | s.catalog.databases)
        if len(parts) == 2 and parts[0] == "schema":
            db = parts[1].lower()
            pre = "" if db == "test" else db + "."
            out = []
            for name in s.catalog.tables():
                if db == "test" and "." not in name:
                    out.append(_table_info(s.catalog.table(name)))
                elif pre and name.startswith(pre):
                    out.append(_table_info(s.catalog.table(name)))
            return 200, out
        if len(parts) == 3 and parts[0] == "schema":
            key = parts[2].lower() if parts[1].lower() == "test" else f"{parts[1].lower()}.{parts[2].lower()}"
            try:
                return 200, _table_info(s.catalog.table(key))
            except Exception:  # noqa: BLE001
                return 404, {"error": f"table {parts[1]}.{parts[2]} not found"}
        if parts == ["ddl", "history"]:
            return 200, [
                {"id": j.job_id, "type": j.job_type, "state": j.state,
                 "schema_state": j.schema_state, "table": j.table,
                 "query": j.query}
                for j in reversed(s.catalog.ddl_jobs.view())
            ]
        if parts == ["settings"]:
            return 200, dict(s.sysvars.items())
        if parts == ["metrics"]:
            from ..util import metrics

            # raw exposition a Prometheus scraper actually parses
            return 200, metrics.REGISTRY.dump(), "text/plain; version=0.0.4; charset=utf-8"
        if parts == ["metrics", "json"]:
            from ..util import metrics

            return 200, {
                "prometheus": metrics.REGISTRY.dump(),
                "samples": dict(metrics.REGISTRY.sample_lines()),
            }
        if len(parts) >= 4 and parts[:3] == ["cdc", "api", "v1"]:
            return self._cdc_route(parts[3:])
        if len(parts) >= 4 and parts[:3] == ["columnar", "api", "v1"]:
            return self._columnar_route(parts[3:])
        if len(parts) >= 4 and parts[:3] == ["topsql", "api", "v1"]:
            return self._topsql_route(parts[3:])
        if len(parts) == 4 and parts[:3] == ["pd", "api", "v1"]:
            pd = getattr(s.store, "pd", None)
            if pd is None:
                return 404, {"error": "no placement driver attached to this store"}
            view = {
                "regions": pd.regions_view,
                "stores": pd.stores_view,
                "hotspot": pd.hotspot_view,
                "operators": pd.operators_view,
            }.get(parts[3])
            if view is None:
                return 404, {"error": f"unknown pd route {parts[3]!r} (regions|stores|hotspot|operators)"}
            return 200, view()
        if parts == ["regions", "meta"]:
            return 200, [
                {"region_id": r.region_id, "epoch": r.epoch,
                 "start_key": r.start_key.hex(), "end_key": r.end_key.hex()}
                for r in s.store.cluster.regions()
            ]
        if len(parts) == 5 and parts[:2] == ["mvcc", "key"]:
            db, tbl, h = parts[2].lower(), parts[3].lower(), int(parts[4])
            key = tbl if db == "test" else f"{db}.{tbl}"
            meta = s.catalog.table(key)
            from ..codec import tablecodec

            out = []
            for pid in meta.physical_ids():
                k = tablecodec.encode_row_key(pid, h)
                with s.store.kv.lock:
                    vers = list(s.store.kv._data.get(k, []))
                for ts, val in vers:
                    out.append({
                        "key": k.hex(), "commit_ts": ts,
                        "deleted": val is None,
                        "value_len": 0 if val is None else len(val),
                    })
            if not out:
                return 404, {"error": "no MVCC versions for that handle"}
            return 200, {"handle": h, "versions": out}
        return 404, {"error": f"unknown path {path!r} (see docs/tidb_http_api.md routes)"}

    def _columnar_route(self, parts: list):
        """/columnar/api/v1/tables[/{name}] (ISSUE 12; the TiFlash-analog
        of information_schema.tiflash_replica as an HTTP view): per-table
        delta rows, stable chunks, and the applied resolved-ts frontier.
        A vet request-path root: state reads stay typed and total."""
        rep = getattr(self.session.store, "columnar", None)
        if rep is None or parts[0] != "tables":
            return 404, {"error": "unknown columnar route (tables)"}
        views = rep.views()
        if len(parts) == 1:
            return 200, views
        for v in views:
            if v["table"] == parts[1]:
                return 200, v
        return 404, {"error": f"columnar table {parts[1]!r} not found"}

    def _topsql_route(self, parts: list):
        """/topsql/api/v1/windows and /topsql/api/v1/digests/{digest}
        (ISSUE 17; ref: TiDB's Top SQL pushed to ng-monitoring — here
        pulled from the embedded reporter). Serves the SAME
        `windows_view()` rows information_schema.tidb_top_sql renders,
        so the two surfaces are byte-consistent by construction. A
        registered vet request-path root: reporter reads stay typed and
        total."""
        from ..topsql import COLLECTOR

        if parts[0] == "windows" and len(parts) == 1:
            return 200, COLLECTOR.windows_view()
        if parts[0] == "digests" and len(parts) == 2:
            view = COLLECTOR.digest_view(parts[1])
            if not view["windows"] and not view["measured_executions"]:
                return 404, {"error": f"digest {parts[1]!r} not in any window"}
            return 200, view
        return 404, {"error": "unknown topsql route (windows | digests/{digest})"}

    def _cdc_route(self, parts: list):
        """/cdc/api/v1/changefeeds[/{name}] (ref: TiCDC's open API
        api/v1/changefeeds — list + detail). A registered vet
        request-path root: CDC state reads must stay typed and total."""
        hub = getattr(self.session.store, "cdc", None)
        if hub is None or parts[0] != "changefeeds":
            return 404, {"error": "unknown cdc route (changefeeds)"}
        views = hub.views()
        if len(parts) == 1:
            return 200, views
        for v in views:
            if v["name"] == parts[1]:
                return 200, v
        return 404, {"error": f"changefeed {parts[1]!r} not found"}
