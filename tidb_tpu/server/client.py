"""Minimal MySQL text-protocol client — the test/CLI counterpart of the
server (the reference relies on go-sql-driver in tests; there is no MySQL
client library in this environment, so the framework ships its own).

Implements HandshakeResponse41 + mysql_native_password and the text result
set decode; enough to validate the server against the real wire format.
"""

from __future__ import annotations

import socket
import struct

from . import protocol as P


class ClientError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(f"({code}) {message}")
        self.code = code
        self.message = message


class MiniClient:
    def __init__(self, host: str, port: int, user: str = "root", password: str = "",
                 database: str = "", timeout: float = 10.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.io = P.PacketIO(self.sock)
        self._handshake(user, password.encode(), database)

    def _handshake(self, user: str, password: bytes, database: str):
        greeting = self.io.read()
        assert greeting[0] == 10, "expected HandshakeV10"
        ver_end = greeting.index(b"\x00", 1)
        pos = ver_end + 1
        (self.conn_id,) = struct.unpack_from("<I", greeting, pos)
        pos += 4
        salt = greeting[pos : pos + 8]
        pos += 9  # salt1 + filler
        pos += 2 + 1 + 2 + 2 + 1 + 10  # caps_lo, charset, status, caps_hi, salt_len, reserved
        salt += greeting[pos : pos + 12]
        caps = (
            P.CLIENT_PROTOCOL_41 | P.CLIENT_SECURE_CONNECTION | P.CLIENT_PLUGIN_AUTH
            | P.CLIENT_MULTI_STATEMENTS | P.CLIENT_MULTI_RESULTS
            | (P.CLIENT_CONNECT_WITH_DB if database else 0)
        )
        auth = P.native_password_scramble(password, salt)
        payload = struct.pack("<IIB", caps, 1 << 24, P.CHARSET_UTF8MB4) + b"\x00" * 23
        payload += user.encode() + b"\x00"
        payload += bytes([len(auth)]) + auth
        if database:
            payload += database.encode() + b"\x00"
        payload += b"mysql_native_password\x00"
        self.io.write(payload)
        resp = self.io.read()
        if resp[0] == 0xFF:
            code, msg = self._parse_err(resp)
            raise ClientError(code, msg)

    @staticmethod
    def _parse_err(payload: bytes) -> tuple[int, str]:
        (code,) = struct.unpack_from("<H", payload, 1)
        msg = payload[3:]
        if msg[:1] == b"#":
            msg = msg[6:]
        return code, msg.decode("utf-8", "replace")

    # ------------------------------------------------------------------
    def query(self, sql: str):
        """Run one statement; returns (columns, rows) for result sets or
        affected-row count for OK responses. Multi-statement payloads
        return the LAST result."""
        self.io.reset()
        self.io.write(bytes([P.COM_QUERY]) + sql.encode())
        result = None
        while True:
            result = self._read_result()
            if not self._more_results:
                return result

    _more_results = False

    def _read_result(self):
        first = self.io.read()
        self._more_results = False
        if first[0] == 0xFF:
            code, msg = self._parse_err(first)
            raise ClientError(code, msg)
        if first[0] == 0x00:
            affected, pos = P.read_lenenc_int(first, 1)
            _, pos = P.read_lenenc_int(first, pos)
            (status,) = struct.unpack_from("<H", first, pos)
            self._more_results = bool(status & 0x0008)  # SERVER_MORE_RESULTS_EXISTS
            return affected
        ncols, _ = P.read_lenenc_int(first, 0)
        columns = []
        for _ in range(ncols):
            cdef = self.io.read()
            pos = 0
            for _ in range(4):  # catalog, schema, table, org_table
                _, pos = P.read_lenenc_str(cdef, pos)
            name, pos = P.read_lenenc_str(cdef, pos)
            columns.append(name.decode())
        eof = self.io.read()
        assert eof[0] == 0xFE
        rows = []
        while True:
            pkt = self.io.read()
            if pkt[0] == 0xFE and len(pkt) < 9:
                (status,) = struct.unpack_from("<H", pkt, 3)
                self._more_results = bool(status & 0x0008)
                break
            if pkt[0] == 0xFF:
                code, msg = self._parse_err(pkt)
                raise ClientError(code, msg)
            row, pos = [], 0
            for _ in range(ncols):
                if pkt[pos] == 0xFB:
                    row.append(None)
                    pos += 1
                else:
                    v, pos = P.read_lenenc_str(pkt, pos)
                    row.append(v.decode())
            rows.append(row)
        return columns, rows

    def ping(self) -> bool:
        self.io.reset()
        self.io.write(bytes([P.COM_PING]))
        return self.io.read()[0] == 0x00

    def close(self):
        try:
            self.io.reset()
            self.io.write(bytes([P.COM_QUIT]))
        except OSError:
            pass
        self.sock.close()
