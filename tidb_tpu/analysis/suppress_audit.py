"""`suppressions` — stale-suppression audit (ISSUE 9 satellite):
a `# vet: ignore[<pass>]` marker that no longer suppresses a live
finding is rot. The code it excused was fixed or rewritten, but the
marker keeps silencing the pass for whatever lands on that line next —
exactly how a real regression ships under a years-old waiver. Nothing
noticed until now; this pass does.

Runs only from the full-suite driver (`run_all` / the vet CLI without
`--only`): a marker is judged stale only when the pass it names actually
RAN over its file and produced nothing for it to suppress. A marker
naming an unknown pass is always a finding — it can never suppress
anything.
"""

from __future__ import annotations

from .common import Finding

PASS = "suppressions"


def audit(files, used_markers: set, ran_passes: set, known_passes: set) -> list:
    """`used_markers` = {(rel, marker_line, passname)} recorded by the
    suppression filter; any ignore marker in `files` not in that set —
    for a pass that ran — is stale."""
    findings: list = []
    for sf in files:
        for line, names in sf.ignore_markers():
            for name in names:
                if name == PASS:
                    continue  # suppressing the auditor itself is meta-rot,
                    # but flagging it would make the marker unfixable
                if name not in known_passes:
                    findings.append(Finding(
                        sf.rel, line, PASS,
                        f"suppression names unknown pass {name!r} — it can never "
                        f"suppress anything (see tools/vet.py --list)"))
                    continue
                if name not in ran_passes:
                    continue  # pass didn't run this invocation: no verdict
                if (sf.rel, line, name) not in used_markers:
                    findings.append(Finding(
                        sf.rel, line, PASS,
                        f"stale suppression: `vet: ignore[{name}]` no longer "
                        f"suppresses any finding here — the excused code is gone; "
                        f"remove the marker before it silences the next regression"))
    return findings
