"""Shared plumbing for the `tidb-vet` analysis suite (ref: the shape of a
golang.org/x/tools/go/analysis.Pass — each pass gets parsed sources and
reports findings; the driver in tools/vet.py aggregates and sets the exit
code).

Suppression: a finding anchored on a line carrying (or immediately
preceded by a line carrying) `# vet: ignore[<pass>]` is dropped. The
marker names the pass explicitly so a suppression can never silence a
different analyzer by accident.
"""

from __future__ import annotations

import ast
import hashlib
import os
import re
from dataclasses import dataclass, field

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_IGNORE = re.compile(r"#\s*vet:\s*ignore\[([a-z0-9_,\- ]+)\]")


@dataclass(frozen=True)
class Finding:
    """One analyzer hit: `path` is repo-relative, `line` 1-based."""

    path: str
    line: int
    passname: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.passname}] {self.message}"

    def to_dict(self) -> dict:
        return {"path": self.path, "line": self.line,
                "pass": self.passname, "message": self.message}


@dataclass
class SourceFile:
    """One parsed module: raw text, split lines and the ast tree (None on a
    syntax error — passes skip unparseable files; vet itself reports them)."""

    path: str  # absolute
    rel: str  # repo-relative
    text: str
    lines: list[str] = field(default_factory=list)
    tree: ast.AST | None = None
    parse_error: str | None = None
    mtime: float = 0.0  # cache-key ingredients: (rel, mtime, sha) identify
    sha: str = ""  # one analyzed file revision (tidb_tpu/analysis/vetcache.py)

    @staticmethod
    def load(path: str, repo: str = REPO) -> "SourceFile":
        rel = os.path.relpath(path, repo)
        try:
            text = open(path, encoding="utf-8").read()
            mtime = os.stat(path).st_mtime
        except OSError as exc:
            return SourceFile(path, rel, "", [], None, f"unreadable: {exc}")
        sf = SourceFile(path, rel, text, text.splitlines(), mtime=mtime,
                        sha=hashlib.sha256(text.encode("utf-8")).hexdigest())
        try:
            sf.tree = ast.parse(text, filename=rel)
        except SyntaxError as exc:
            sf.parse_error = f"syntax error: {exc}"
        return sf

    def suppression_line(self, line: int, passname: str) -> int | None:
        """Line number of the inline `# vet: ignore[<pass>]` marker
        covering `line` (the line itself or the one above), or None."""
        for ln in (line, line - 1):
            if 1 <= ln <= len(self.lines):
                m = _IGNORE.search(self.lines[ln - 1])
                if m and passname in [p.strip() for p in m.group(1).split(",")]:
                    return ln
        return None

    def suppressed(self, line: int, passname: str) -> bool:
        """True when `line` (or the line above it) carries an inline
        `# vet: ignore[<pass>]` marker naming this pass."""
        return self.suppression_line(line, passname) is not None

    def ignore_markers(self) -> list[tuple[int, list[str]]]:
        """Every `# vet: ignore[...]` marker in the file as
        (line, [passnames]) — the stale-suppression audit's input."""
        out = []
        for ln, text in enumerate(self.lines, 1):
            m = _IGNORE.search(text)
            if m:
                out.append((ln, [p.strip() for p in m.group(1).split(",")]))
        return out


def py_files(*rel_paths: str, repo: str = REPO) -> list[str]:
    """Every .py file under the given repo-relative dirs (files pass
    through), sorted for deterministic output."""
    out: list[str] = []
    for rel in rel_paths:
        root = os.path.join(repo, rel)
        if os.path.isfile(root):
            out.append(root)
            continue
        for dirpath, _dirs, files in os.walk(root):
            for f in sorted(files):
                if f.endswith(".py"):
                    out.append(os.path.join(dirpath, f))
    return sorted(out)


def load_files(paths) -> list[SourceFile]:
    return [SourceFile.load(p) for p in paths]


def filter_suppressed(findings, files_by_rel: dict, used: set | None = None) -> list:
    """Drop findings covered by an inline ignore marker. When `used` is
    given, every marker that actually suppressed something is recorded as
    (rel, marker_line, passname) — the stale-suppression audit subtracts
    this set from the universe of markers."""
    out = []
    for f in findings:
        sf = files_by_rel.get(f.path)
        if sf is not None:
            ln = sf.suppression_line(f.line, f.passname)
            if ln is not None:
                if used is not None:
                    used.add((f.path, ln, f.passname))
                continue
        out.append(f)
    return out
