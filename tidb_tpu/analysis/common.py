"""Shared plumbing for the `tidb-vet` analysis suite (ref: the shape of a
golang.org/x/tools/go/analysis.Pass — each pass gets parsed sources and
reports findings; the driver in tools/vet.py aggregates and sets the exit
code).

Suppression: a finding anchored on a line carrying (or immediately
preceded by a line carrying) `# vet: ignore[<pass>]` is dropped. The
marker names the pass explicitly so a suppression can never silence a
different analyzer by accident.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_IGNORE = re.compile(r"#\s*vet:\s*ignore\[([a-z0-9_,\- ]+)\]")


@dataclass(frozen=True)
class Finding:
    """One analyzer hit: `path` is repo-relative, `line` 1-based."""

    path: str
    line: int
    passname: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.passname}] {self.message}"

    def to_dict(self) -> dict:
        return {"path": self.path, "line": self.line,
                "pass": self.passname, "message": self.message}


@dataclass
class SourceFile:
    """One parsed module: raw text, split lines and the ast tree (None on a
    syntax error — passes skip unparseable files; vet itself reports them)."""

    path: str  # absolute
    rel: str  # repo-relative
    text: str
    lines: list[str] = field(default_factory=list)
    tree: ast.AST | None = None
    parse_error: str | None = None

    @staticmethod
    def load(path: str, repo: str = REPO) -> "SourceFile":
        rel = os.path.relpath(path, repo)
        try:
            text = open(path, encoding="utf-8").read()
        except OSError as exc:
            return SourceFile(path, rel, "", [], None, f"unreadable: {exc}")
        sf = SourceFile(path, rel, text, text.splitlines())
        try:
            sf.tree = ast.parse(text, filename=rel)
        except SyntaxError as exc:
            sf.parse_error = f"syntax error: {exc}"
        return sf

    def suppressed(self, line: int, passname: str) -> bool:
        """True when `line` (or the line above it) carries an inline
        `# vet: ignore[<pass>]` marker naming this pass."""
        for ln in (line, line - 1):
            if 1 <= ln <= len(self.lines):
                m = _IGNORE.search(self.lines[ln - 1])
                if m and passname in [p.strip() for p in m.group(1).split(",")]:
                    return True
        return False


def py_files(*rel_paths: str, repo: str = REPO) -> list[str]:
    """Every .py file under the given repo-relative dirs (files pass
    through), sorted for deterministic output."""
    out: list[str] = []
    for rel in rel_paths:
        root = os.path.join(repo, rel)
        if os.path.isfile(root):
            out.append(root)
            continue
        for dirpath, _dirs, files in os.walk(root):
            for f in sorted(files):
                if f.endswith(".py"):
                    out.append(os.path.join(dirpath, f))
    return sorted(out)


def load_files(paths) -> list[SourceFile]:
    return [SourceFile.load(p) for p in paths]


def filter_suppressed(findings, files_by_rel: dict) -> list:
    out = []
    for f in findings:
        sf = files_by_rel.get(f.path)
        if sf is not None and sf.suppressed(f.line, f.passname):
            continue
        out.append(f)
    return out
