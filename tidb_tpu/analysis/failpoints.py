"""`failpoints` — failpoint cross-reference checking + catalog generation
(moved from tools/failpoint_check.py, which remains as a thin CLI shim;
one pass among peers in the tidb-vet suite since ISSUE 7).

A failpoint armed under a typo'd name silently never fires — the test
that "exercises" a fault path then passes by exercising nothing (the
reference avoids this with compile-time failpoint rewriting; a runtime
registry has no such guard). Statically:

  * every `failpoint.enable/enabled/disable("name")` in tests/, tools/
    and bench.py must reference a SITE — a `failpoint.eval/is_armed/
    peek("name")` call — defined in `tidb_tpu/` (or in the same file, for
    the failpoint module's own unit tests);
  * every site defined in `tidb_tpu/` must carry a one-line description
    in DESCRIPTIONS below — that's what makes the generated catalog
    (FAILPOINTS.md) complete by construction.
"""

from __future__ import annotations

import os
import re

try:
    from .common import REPO, Finding
except ImportError:  # loaded by file path (tools/failpoint_check.py shim
    # keeps itself importable without the engine's jax-importing package
    # __init__) — pull common.py in the same way
    import importlib.util as _ilu
    import sys as _sys

    _spec = _ilu.spec_from_file_location(
        "_ttvet_common",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), "common.py"))
    _common = _ilu.module_from_spec(_spec)
    _sys.modules["_ttvet_common"] = _common  # dataclasses resolve __module__
    _spec.loader.exec_module(_common)
    REPO, Finding = _common.REPO, _common.Finding

PASS = "failpoints"

# one line per failpoint: what arming it injects (the catalog body)
DESCRIPTIONS = {
    "cop-region-error": "injects `epoch_not_match` at the coprocessor RPC seam — exercises the re-split retry path",
    "cop-other-error": "injects a non-retryable `other_error` cop response — surfaces as CopInternalError / MySQL 1105",
    "cop-debug-raise": "re-raises store-side execution errors with a stack instead of folding them into `other_error`",
    "distsql.before_task": "hook before every cop-task send — tests raise or count here to probe the dispatch loop",
    "ddl_index_delete_only": "pauses online index DDL in the delete-only state so tests can write concurrently",
    "ddl_index_write_only": "pauses online index DDL in the write-only state",
    "ddl_index_write_reorg": "pauses online index DDL in the write-reorg (backfill) state",
    "cdc/puller-drop": "drops a changefeed's live log deliveries — the span is marked lost and recovered by an incremental scan from the checkpoint at the next tick (the TiCDC re-subscribe path); nothing is lost, only late",
    "cdc/resolved-stuck": "pins every changefeed's resolved-ts watermarks — the frontier stops advancing (and the checkpoint with it) until disarmed; emission stays gated so downstream still only sees complete prefixes",
    "cdc/sink-stall": "skips a tick's sink emission — the sorter keeps the backlog and the emitted checkpoint holds until the stall clears",
    "columnar/apply-stall": "wedges the columnar replica's apply sink — the feeding changefeed parks in `error` with the backlog re-queued below its held checkpoint; RESUME (ColumnarReplica.resume_all) replays it, absorbed by the idempotent delta fold",
    "columnar/compact-stall": "skips the pd.columnar tick's delta-to-stable compaction — delta layers grow and the stable floor stops advancing; scans keep serving through the delta overlay",
    "mpp/dispatch-lost": "loses an MPP task dispatch before launch — the coordinator abandons the fragment run as a counted fallback (MPP_FALLBACKS) and the statement re-dispatches on the non-MPP tiers, byte-identically",
    "mpp/exchange-stall": "stalls the fragment exchange mid-run — the coordinator abandons the MPP attempt after sourcing the probe scan; a counted fallback, never a torn result",
    "server/admission-full": "forces the admission gate's saturated answer — every statement/dispatch arriving at an armed gate sheds as typed ServerIsBusy{backoff_ms} without consuming a slot, so tests exercise backpressure without real load",
    "pd/heartbeat-lost": "drops one tick's region-heartbeat interval on the floor (a lost heartbeat stream)",
    "pd/operator-timeout": "force-expires every pending PD operator at the next tick's dispatch phase",
    "replica/apply-lag": "wedges armed follower stores' apply loop — their safe_ts stops advancing, so replica reads at newer snapshots answer DataIsNotReady until disarmed (per-store arming)",
    "replica/drop-ack": "drops armed follower stores' replication acks — proposals count quorum without them, and losing quorum flips the group to quorum_lost (placement-move failover)",
    "store/not-leader": "injects a typed NotLeader region error for requests to armed stores (True/set/dict arming)",
    "store/transfer-leader-timeout": "times out leader-transfer attempts (breaker failover and the PD transfer-leader operator) — the operator retires as timeout and the caller backs off",
    "store/server-busy": "injects ServerIsBusy with an optional `backoff_ms` suggestion for armed stores",
    "store/unreachable": "injects StoreUnavailable for armed stores and fails their liveness probe (ping_store)",
    "coalesce/window-stall": "wedges the coalescer window's leader past its deadline (arm with a float to choose the hold seconds) — followers outwait their patience, withdraw their unclaimed lanes, and fall back to the single path as counted `window_stall` fallbacks",
    "coalesce/flush-lost": "loses a coalescer window's flush before any lane is answered — every lane falls out as a counted `flush_lost` fallback and re-runs its single path; no statement is lost, none launches twice",
    "cdc/segment-crash": "kills a segment flush between the tmp write and the rename (typed SinkError, tmp left behind) — the kill-mid-flush drill: consumers must see only whole renamed-in segments, and the feed re-queues the window for exactly-once redelivery",
    "restore/replay-crash": "raises typed ReplayInterrupted right after a replayed segment's checkpoint write — a re-run of the same RESTORE ... UNTIL TS resumes past every already-applied segment (counted PITR_REPLAY_RESUMES)",
    "br/log-gap": "drops the middle entry from the log-backup manifest as restore reads it — the coverage chain breaks and the restore MUST fail as typed LogGapError, never a silently-short cluster",
}

_SITE = re.compile(r"""(?:failpoint|_fp|fp)\s*\.\s*(?:eval|is_armed|peek)\(\s*["']([^"']+)["']""")
_USE = re.compile(r"""(?:failpoint|_fp|fp)\s*\.\s*(?:enable|enabled|disable)\(\s*["']([^"']+)["']""")


def _py_files(*rel_dirs: str):
    for rel in rel_dirs:
        root = os.path.join(REPO, rel)
        if os.path.isfile(root):
            yield root
            continue
        for dirpath, _dirs, files in os.walk(root):
            if "vet_fixtures" in dirpath:
                continue  # true-positive corpora are scanned EXPLICITLY by
                # tests/test_vet.py, never by the live-tree run
            for f in sorted(files):
                if f.endswith(".py"):
                    yield os.path.join(dirpath, f)


def _scan(pattern: re.Pattern, paths) -> dict[str, list[str]]:
    """name -> ["relpath:line", ...] for every match of `pattern`."""
    out: dict[str, list[str]] = {}
    for path in paths:
        rel = os.path.relpath(path, REPO)
        try:
            text = open(path, encoding="utf-8").read()
        except OSError:
            continue
        for ln, line in enumerate(text.splitlines(), 1):
            for m in pattern.finditer(line):
                out.setdefault(m.group(1), []).append(f"{rel}:{ln}")
    return out


def check() -> tuple[list[str], dict[str, list[str]]]:
    """Returns (errors, defined-sites) — the tools/failpoint_check.py
    contract. Sites defined under tidb_tpu/ are the catalog; uses
    elsewhere must name one of them OR a site defined in the SAME file
    (self-contained failpoint unit tests)."""
    findings, sites = analyze()
    return [f.message for f in findings], sites


def _loc(where: str) -> tuple[str, int]:
    rel, _, ln = where.rpartition(":")
    return rel, int(ln)


def _unresolved_uses(sites: dict, uses: dict, local_sites: dict) -> list:
    """Findings for armed names no tidb_tpu/ (or same-file) site defines."""
    findings: list = []
    for name, where in sorted(uses.items()):
        if name in sites:
            continue
        local = {w.split(":")[0] for w in local_sites.get(name, ())}
        missing = [w for w in where if w.split(":")[0] not in local]
        if missing:
            rel, ln = _loc(missing[0])
            findings.append(Finding(
                rel, ln, PASS,
                f"failpoint {name!r} armed at {', '.join(missing)} but no "
                f"eval/is_armed/peek site defines it under tidb_tpu/ — it can never fire"))
    return findings


def analyze() -> tuple[list, dict[str, list[str]]]:
    """Finding-shaped variant of check() for the vet driver."""
    sites = _scan(_SITE, _py_files("tidb_tpu"))
    uses = _scan(_USE, _py_files("tests", "tools", "bench.py"))
    local_sites = _scan(_SITE, _py_files("tests", "tools", "bench.py"))
    findings = _unresolved_uses(sites, uses, local_sites)
    for name in sorted(sites):
        if name not in DESCRIPTIONS:
            rel, ln = _loc(sites[name][0])
            findings.append(Finding(
                rel, ln, PASS,
                f"failpoint {name!r} (defined at {sites[name][0]}) has no entry in "
                f"tidb_tpu/analysis/failpoints.py DESCRIPTIONS — add one line so the "
                f"catalog stays complete"))
    return findings, sites


def run(files=None) -> list:
    """Vet-pass entry point. With no `files` the pass owns its scoping
    (sites in tidb_tpu/, uses in tests//tools//bench.py); with an explicit
    list (the driver's --files mode) the GIVEN files' arms are checked
    against the live tree's sites — a fixture corpus must report, not
    silently fall back to a clean full-tree scan."""
    if not files:
        return analyze()[0]
    sites = _scan(_SITE, _py_files("tidb_tpu"))
    paths = [sf.path for sf in files]
    return _unresolved_uses(sites, _scan(_USE, paths), _scan(_SITE, paths))


def write_catalog(sites: dict[str, list[str]], path: str) -> None:
    lines = [
        "# Failpoint catalog",
        "",
        "Generated by `python tools/failpoint_check.py --catalog` — every",
        "`failpoint.eval/is_armed/peek` site in `tidb_tpu/` and what arming it",
        "injects. Arm with `failpoint.enable(name, value)` (bool = always, int =",
        "fire-N-times, set/dict = per-store arming for `store/*` points, a",
        "ZERO-arg callable returning any of those shapes = custom per-hit",
        "logic); disarm with `failpoint.disable(name)`.",
        "",
        "| failpoint | injection sites | injects |",
        "|---|---|---|",
    ]
    for name in sorted(sites):
        where = ", ".join(f"`{w}`" for w in sites[name])
        lines.append(f"| `{name}` | {where} | {DESCRIPTIONS.get(name, '')} |")
    with open(path, "w", encoding="utf-8") as f:
        f.write("\n".join(lines) + "\n")
